"""Ring attention: sequence-parallel exact attention via ppermute over ICI.

Long-context support (SURVEY.md §5): the sequence is sharded over a mesh axis
(``sp``); each device holds a local Q/K/V block.  K/V blocks rotate around
the ring (``lax.ppermute``) while each device accumulates its Q block's
attention with the numerically-stable online-softmax update (the flash/
blockwise recurrence, all in f32):

    m' = max(m, rowmax(S))          # running max
    l' = l * exp(m - m') + rowsum(exp(S - m'))
    o' = o * exp(m - m') + exp(S - m') V

After ``n`` rotations every Q block has seen every K/V block; outputs are
exact (not approximate) attention.  Communication is nearest-neighbor
ppermute riding the ICI ring — the TPU-native replacement for the
all-to-all/NCCL schemes GPU sequence parallelism uses.

Causality across blocks uses global position offsets derived from the ring
step: the K/V block at rotation ``r`` on device ``i`` originated on device
``(i - r) mod n``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_attn(q, k, v, q_off, k_off, scale, causal):
    """Partial (unnormalized) attention of one Q block vs one K/V block.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D].  Returns (scores_max [B,H,Sq],
    exp-sum [B,H,Sq], weighted values [B,Sq,H,D]) in f32.
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        q_ids = q_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_ids = k_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((k_ids <= q_ids)[None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # [B,H,Sq]
    # guard fully-masked rows (no valid keys yet in this block)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1)  # noqa: E741
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v, preferred_element_type=jnp.float32)
    return m_safe, l, o


def _ring_forward(q32, k32, v32, axis_name: str, causal: bool):
    """Online-softmax ring pass.  Returns (out_f32, logsumexp [B,H,Sq])."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q32.shape[1]
    scale = 1.0 / np.sqrt(q32.shape[-1])
    q_off = idx * s_local

    # derive the carries from q32 so they inherit its device-varying spec:
    # under a composed mesh (e.g. data x sp) the loop values vary over
    # EVERY axis the inputs shard on, not just the ring axis — a pcast to
    # ("sp",) alone would type-mismatch the scan carry there
    m0 = jnp.transpose(q32[..., 0], (0, 2, 1)) * 0.0 - jnp.inf  # [B,H,Sq]
    l0 = jnp.zeros_like(m0)
    o0 = jnp.zeros_like(q32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, r):
        m, l, o, kr, vr = carry  # noqa: E741
        src = (idx - r) % n  # ring step r holds the block from device src
        k_off = src * s_local
        bm, bl, bo = _block_attn(q32, kr, vr, q_off, k_off, scale, causal)
        new_m = jnp.maximum(m, bm)
        # rescale both accumulators to the new max
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - new_m), 0.0)
        beta = jnp.where(jnp.isfinite(bm) & (bl > 0), jnp.exp(bm - new_m), 0.0)
        new_l = l * alpha + bl * beta
        new_o = (
            o * alpha.transpose(0, 2, 1)[..., None]
            + bo * beta.transpose(0, 2, 1)[..., None]
        )
        kr = jax.lax.ppermute(kr, axis_name, perm)
        vr = jax.lax.ppermute(vr, axis_name, perm)
        return (new_m, new_l, new_o, kr, vr), None

    (m, l, o, _, _), _ = jax.lax.scan(  # noqa: E741
        body, (m0, l0, o0, k32, v32), jnp.arange(n)
    )
    l_safe = jnp.maximum(l, 1e-30)
    out = o / l_safe.transpose(0, 2, 1)[..., None]
    lse = m + jnp.log(l_safe)  # [B, H, Sq]
    return out, lse


@functools.lru_cache(maxsize=None)
def _ring_with_flash_bwd(axis_name: str, causal: bool):
    """custom_vjp ring attention with the blockwise (flash-style) backward.

    Plain reverse-AD through the ring either saves every step's
    [B, H, S/n, S/n] score blocks (O(S^2/n) per device) or — under
    jax.checkpoint — every step's visiting K/V blocks (O(S) per device,
    not shrinking with ring size).  The flash recurrence needs neither:
    forward saves only the LOCAL q/k/v/out plus the per-query logsumexp,
    and backward re-rotates K/V around the ring with the dK/dV
    accumulators riding along — after n steps each accumulator is home at
    its owner.  Per-device residuals are O(S/n); per-step temps are the
    (S/n)^2 block working set, recomputed.
    """

    @jax.custom_vjp
    def fn(q32, k32, v32):
        return _ring_forward(q32, k32, v32, axis_name, causal)[0]

    def fwd(q32, k32, v32):
        out, lse = _ring_forward(q32, k32, v32, axis_name, causal)
        return out, (q32, k32, v32, out, lse)

    def bwd(res, g):
        q32, k32, v32, out, lse = res
        do = g.astype(jnp.float32)
        n = jax.lax.axis_size(axis_name)
        idx = jax.lax.axis_index(axis_name)
        s_local = q32.shape[1]
        scale = 1.0 / np.sqrt(q32.shape[-1])
        q_off = idx * s_local
        perm = [(i, (i + 1) % n) for i in range(n)]
        # D_i = rowsum(dO * O) per query [B, H, Sq]
        d_term = jnp.einsum("bqhd,bqhd->bhq", do, out)

        def body(carry, r):
            dq, dk_r, dv_r, kr, vr = carry
            src = (idx - r) % n
            k_off = src * s_local
            s = jnp.einsum(
                "bqhd,bkhd->bhqk", q32, kr,
                preferred_element_type=jnp.float32,
            ) * scale
            if causal:
                sq, sk = s_local, s_local
                q_ids = q_off + jax.lax.broadcasted_iota(
                    jnp.int32, (sq, sk), 0
                )
                k_ids = k_off + jax.lax.broadcasted_iota(
                    jnp.int32, (sq, sk), 1
                )
                s = jnp.where((k_ids <= q_ids)[None, None], s, -jnp.inf)
            p = jnp.exp(s - lse[..., None])  # exact probs (masked -> 0)
            dp = jnp.einsum(
                "bqhd,bkhd->bhqk", do, vr,
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - d_term[..., None])
            dq = dq + jnp.einsum(
                "bhqk,bkhd->bqhd", ds, kr,
                preferred_element_type=jnp.float32,
            ) * scale
            # gradient for the VISITING block, accumulated in ring order:
            # after n rotations it is back at the block's owner
            dk_r = dk_r + jnp.einsum(
                "bhqk,bqhd->bkhd", ds, q32,
                preferred_element_type=jnp.float32,
            ) * scale
            dv_r = dv_r + jnp.einsum(
                "bhqk,bqhd->bkhd", p, do,
                preferred_element_type=jnp.float32,
            )
            kr = jax.lax.ppermute(kr, axis_name, perm)
            vr = jax.lax.ppermute(vr, axis_name, perm)
            dk_r = jax.lax.ppermute(dk_r, axis_name, perm)
            dv_r = jax.lax.ppermute(dv_r, axis_name, perm)
            return (dq, dk_r, dv_r, kr, vr), None

        zeros = jnp.zeros_like(k32)
        dq0 = jnp.zeros_like(q32)
        (dq, dk, dv, _, _), _ = jax.lax.scan(
            body, (dq0, zeros, jnp.zeros_like(v32), k32, v32), jnp.arange(n)
        )
        return dq, dk, dv

    fn.defvjp(fwd, bwd)
    return fn


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Exact attention over a sequence sharded on ``axis_name``.

    Call inside shard_map.  q/k/v: [B, S_local, H, D] (same H on every
    device — combine with Ulysses/TP for head sharding).  Returns
    [B, S_local, H, D] in q.dtype.  Differentiable with the flash-style
    ring backward (O(S/n) residuals per device).
    """
    q32, k32, v32 = (x.astype(jnp.float32) for x in (q, k, v))
    out = _ring_with_flash_bwd(axis_name, causal)(q32, k32, v32)
    return out.astype(q.dtype)


def make_ring_attention(
    mesh: Mesh, *, sp_axis: str, causal: bool = False
) -> "jax.stages.Wrapped":
    """jit-able wrapper: full [B, S, H, D] arrays sharded on S over sp_axis."""
    from jax import shard_map

    spec = P(None, sp_axis, None, None)

    fn = shard_map(
        functools.partial(ring_attention, axis_name=sp_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return jax.jit(fn)


def ring_attention_spmd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    sp_axis: str,
    causal: bool = False,
) -> jax.Array:
    """Ring attention as an op INSIDE a GSPMD program (partial shard_map).

    The composition VERDICT r4 #5 asked for: only ``sp_axis`` goes manual
    (the ppermute ring needs an explicit axis); every other mesh axis stays
    Auto, so a TP ``model`` sharding on the head dim — or an FSDP ``data``
    sharding anywhere else — keeps flowing through GSPMD untouched.  Call
    from ordinary jit-traced code on GLOBAL [B, S, H, D] views (the flax
    trunk); contrast ``ring_attention``, which must live inside a whole-
    program shard_map and sees [B, S/n, H, D] locals.
    """
    spec = P(None, sp_axis, None, None)
    manual = frozenset({sp_axis})
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=sp_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names=manual,
    )
    return fn(q, k, v)


def reference_attention(q, k, v, *, causal=False) -> jax.Array:
    """Plain full-softmax attention (test oracle)."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)
