"""Device-side sparse table primitives: row gather / scatter-add.

These are the TPU equivalents of the reference server's hot loops
(``src/parameter/kv_vector.h`` :: ``ParallelOrderedMatch`` merge + scatter-ADD
into the value array, and the Pull-side row gather [U — reference mount empty,
public layout]).  The host has already localized global keys to dense row ids
(:mod:`parameter_server_tpu.utils.keys`), so the device only sees fixed-shape
``int32`` row-id vectors.

Two implementations:

- **XLA** (default): ``jnp.take`` / ``.at[].add``.  Differentiable, handles
  duplicate ids, runs everywhere.  XLA lowers these to native gather/scatter
  which is adequate for small-dim tables (e.g. LR weights).
- **Pallas** (``impl="pallas"``): a double-buffer-free DMA kernel that copies
  ``block_rows`` table rows HBM→VMEM per grid step via scalar-prefetched ids,
  adds, and writes back.  The table never materializes in VMEM, so capacity is
  bounded by HBM only.  Requires: unique row ids (pre-combined duplicates —
  exactly what :func:`localize_batch` + :func:`segment_combine` produce),
  ``dim % 128 == 0``, float32.  Padding rows must carry zero values and may
  all point at the shared trash row (writes become idempotent ``+0``).

The duplicate-key pre-combine that the reference does inside
``ParallelOrderedMatch`` happens here as a device-side ``segment_sum``
(:func:`segment_combine`) keyed by the localizer's inverse indices.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Literal, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Impl = Literal["auto", "xla", "pallas"]


def _side_effect_params():
    """``compiler_params`` marking the kernel side-effecting, across the
    pallas API rename: new toolchains expose ``pltpu.CompilerParams``, jax
    0.4.x ships ``TPUCompilerParams`` without a ``has_side_effects`` field
    (aliased outputs are kept live there by ``input_output_aliases``, so
    omitting the flag is safe — results are always consumed)."""
    cp = getattr(pltpu, "CompilerParams", None)
    if cp is not None:
        return cp(has_side_effects=True)
    return None

#: row-wise update rule: (value_rows, state_rows, grad_rows) ->
#: (new_value_rows, new_state_rows).  ServerOptimizer.apply satisfies this
#: contract directly — pure, elementwise over [n, dim] blocks — which is what
#: lets :func:`apply_rows` inline it into a single gather→apply→scatter pass.
RowFn = Callable[
    [jax.Array, Dict[str, jax.Array], jax.Array],
    Tuple[jax.Array, Dict[str, jax.Array]],
]


def segment_combine(values: jax.Array, inverse: jax.Array, num_rows: int) -> jax.Array:
    """Sum per-position values into their unique-key rows.

    ``inverse`` is the position->unique-row map from ``localize_batch``;
    ``num_rows`` the (bucket-padded) unique count.  Rows past the true unique
    count receive zero — exactly the padding contract the pallas scatter path
    requires.
    """
    return jax.ops.segment_sum(values, inverse, num_segments=num_rows)


# ---------------------------------------------------------------------------
# XLA implementations
# ---------------------------------------------------------------------------


def gather_rows_xla(table: jax.Array, ids: jax.Array) -> jax.Array:
    return jnp.take(table, ids, axis=0)


def scatter_add_rows_xla(table: jax.Array, ids: jax.Array, rows: jax.Array) -> jax.Array:
    return table.at[ids].add(rows)


def scatter_update_rows_xla(table: jax.Array, ids: jax.Array, rows: jax.Array) -> jax.Array:
    return table.at[ids].set(rows)


# ---------------------------------------------------------------------------
# Pallas implementations
# ---------------------------------------------------------------------------


def _pick_block_rows(n: int, block_rows: int | None) -> int:
    """Largest supported block dividing ``n`` (or validate an explicit one)."""
    if block_rows is not None:
        if n % block_rows != 0:
            raise ValueError(
                f"pallas path requires len(ids) % block_rows == 0, got "
                f"{n} % {block_rows}"
            )
        return block_rows
    for b in (32, 16, 8):
        if n % b == 0:
            return b
    raise ValueError(
        f"pallas path requires len(ids) divisible by 8, got {n}; "
        "bucket-pad ids (utils.keys.localize_batch) or use impl='xla'"
    )


def _chunks(dim: int) -> int:
    """Row chunking factor: logical rows are DMAed as ``c`` physical
    ``(., 128)`` rows of the ``(rows*c, 128)`` view.

    Mosaic (this toolchain) only slices HBM memrefs along dim 0 in
    tile-aligned units: a squeezed single-row slice works when the row is
    exactly one 128-lane tile (dim == 128 -> c == 1), and a ``(c, 128)``
    slice works when c is a multiple of the 8-sublane tiling (dim % 1024
    == 0).  Anything between falls back to XLA (measured on-chip: dim
    256/384/512 all reject single-row slices).
    """
    if dim == 128:
        return 1
    c = dim // 128
    if dim % 128 == 0 and c % 8 == 0:
        return c
    raise ValueError(
        f"pallas path requires dim == 128 or dim % 1024 == 0, got {dim}; "
        "use impl='xla'"
    )


def _check_pallas_args(table: jax.Array, ids: jax.Array) -> None:
    if table.ndim != 2 or table.dtype != jnp.float32:
        raise ValueError(
            f"pallas path requires a 2-D float32 table, got "
            f"{table.shape} {table.dtype}; use impl='xla'"
        )
    _chunks(table.shape[1])


def _copy_rows(src_ref, src_row, dst_ref, dst_row, sem, c):
    """Async copy of one logical row (c physical 128-lane rows)."""
    if c == 1:
        return pltpu.make_async_copy(
            src_ref.at[src_row], dst_ref.at[dst_row], sem
        )
    return pltpu.make_async_copy(
        src_ref.at[pl.ds(src_row * c, c)],
        dst_ref.at[pl.ds(dst_row * c, c)],
        sem,
    )


def _gather_kernel(ids_ref, table_ref, out_ref, sems, *, block, c):
    i = pl.program_id(0)
    for k in range(block):
        row = ids_ref[i * block + k]
        _copy_rows(table_ref, row, out_ref, k, sems.at[k], c).start()
    for k in range(block):
        row = ids_ref[i * block + k]
        _copy_rows(table_ref, row, out_ref, k, sems.at[k], c).wait()


def _pallas_gather(
    table: jax.Array,
    ids: jax.Array,
    *,
    interpret: bool,
    block_rows: int | None = None,
) -> jax.Array:
    _check_pallas_args(table, ids)
    n = ids.shape[0]
    block = _pick_block_rows(n, block_rows)
    dim = table.shape[1]
    c = _chunks(dim)
    tview = table.reshape(-1, 128) if c > 1 else table
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // block,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (block * c, 128 if c > 1 else dim),
            lambda i, ids: (i, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[pltpu.SemaphoreType.DMA((block,))],
    )
    out = pl.pallas_call(
        functools.partial(_gather_kernel, block=block, c=c),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (n * c, 128) if c > 1 else (n, dim), table.dtype
        ),
        interpret=interpret,
    )(ids, tview)
    return out.reshape(n, dim) if c > 1 else out


def _scatter_add_kernel(ids_ref, vals_ref, table_ref, out_ref, scratch,
                        rsems, wsems, *, block, c):
    """Double-buffered read-modify-write scatter-add.

    out_ref aliases table_ref (donated input).  Two scratch slots pipeline
    the row round-trips: while block *i* adds and writes back from slot
    ``i%2``, block *i+1*'s rows are already streaming HBM->VMEM into the
    other slot, hiding the gather latency behind the add+write of the
    previous block (the "double-buffering" VERDICT r2 #4 asked for).

    Safety: row ids are unique (callers guarantee; duplicates are
    pre-combined), so block *i*'s write-backs and block *i+1*'s prefetches
    never touch the same row — except the shared trash row, which holds
    zeros and receives +0 writes (bytes unchanged), making the overlap
    benign there too.
    """
    i = pl.program_id(0)
    nb = pl.num_programs(0)
    slot = i % 2
    nxt = (i + 1) % 2

    @pl.when(i == 0)
    def _first_reads():
        for k in range(block):
            row = ids_ref[k]
            _copy_rows(out_ref, row, scratch.at[0], k, rsems.at[0, k], c).start()

    # Slot reuse: the write-backs issued at step i-1 came FROM scratch[nxt];
    # they must land before new rows stream INTO that slot.
    @pl.when(i > 0)
    def _drain_prev_writes():
        for k in range(block):
            row = ids_ref[(i - 1) * block + k]
            _copy_rows(
                scratch.at[nxt], k, out_ref, row, wsems.at[nxt, k], c
            ).wait()

    @pl.when(i + 1 < nb)
    def _prefetch_next():
        for k in range(block):
            row = ids_ref[(i + 1) * block + k]
            _copy_rows(
                out_ref, row, scratch.at[nxt], k, rsems.at[nxt, k], c
            ).start()

    for k in range(block):
        row = ids_ref[i * block + k]
        _copy_rows(out_ref, row, scratch.at[slot], k, rsems.at[slot, k], c).wait()
    scratch[slot] = scratch[slot] + vals_ref[...]
    for k in range(block):
        row = ids_ref[i * block + k]
        _copy_rows(scratch.at[slot], k, out_ref, row, wsems.at[slot, k], c).start()

    @pl.when(i + 1 == nb)
    def _drain_last_writes():
        for k in range(block):
            row = ids_ref[i * block + k]
            _copy_rows(
                scratch.at[slot], k, out_ref, row, wsems.at[slot, k], c
            ).wait()


def _pallas_scatter_add(
    table: jax.Array,
    ids: jax.Array,
    rows: jax.Array,
    *,
    interpret: bool,
    block_rows: int | None = None,
) -> jax.Array:
    _check_pallas_args(table, ids)
    n = ids.shape[0]
    block = _pick_block_rows(n, block_rows)
    dim = table.shape[1]
    c = _chunks(dim)
    tview = table.reshape(-1, 128) if c > 1 else table
    rview = rows.reshape(-1, 128) if c > 1 else rows
    vdim = 128 if c > 1 else dim
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec(
                (block * c, vdim), lambda i, ids: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, block * c, vdim), table.dtype),
            pltpu.SemaphoreType.DMA((2, block)),
            pltpu.SemaphoreType.DMA((2, block)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_scatter_add_kernel, block=block, c=c),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(tview.shape, table.dtype),
        input_output_aliases={2: 0},  # table (arg idx incl. scalar prefetch) -> out
        interpret=interpret,
        compiler_params=_side_effect_params(),
    )(ids, rview, tview)
    return out.reshape(table.shape) if c > 1 else out


def _scatter_set_kernel(ids_ref, vals_ref, table_ref, out_ref, sems, *, block, c):
    """Write-only row update (Push apply writes new rows; no RMW needed).

    Duplicate ids are tolerated ONLY when they carry identical rows (the
    padded-trash-row case): concurrent same-bytes writes are idempotent.
    """
    i = pl.program_id(0)
    for k in range(block):
        row = ids_ref[i * block + k]
        _copy_rows(vals_ref, k, out_ref, row, sems.at[k], c).start()
    for k in range(block):
        row = ids_ref[i * block + k]
        _copy_rows(vals_ref, k, out_ref, row, sems.at[k], c).wait()


def _pallas_scatter_set(
    table: jax.Array,
    ids: jax.Array,
    rows: jax.Array,
    *,
    interpret: bool,
    block_rows: int | None = None,
) -> jax.Array:
    _check_pallas_args(table, ids)
    n = ids.shape[0]
    block = _pick_block_rows(n, block_rows)
    dim = table.shape[1]
    c = _chunks(dim)
    tview = table.reshape(-1, 128) if c > 1 else table
    rview = rows.reshape(-1, 128) if c > 1 else rows
    vdim = 128 if c > 1 else dim
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec(
                (block * c, vdim), lambda i, ids: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((block,))],
    )
    out = pl.pallas_call(
        functools.partial(_scatter_set_kernel, block=block, c=c),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(tview.shape, table.dtype),
        input_output_aliases={2: 0},
        interpret=interpret,
        compiler_params=_side_effect_params(),
    )(ids, rview, tview)
    return out.reshape(table.shape) if c > 1 else out


def _apply_rows_xla(
    value: jax.Array,
    state: Dict[str, jax.Array],
    ids: jax.Array,
    grads: jax.Array,
    row_fn: RowFn,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Gather → row_fn → scatter-update, expressed as one XLA graph.

    Op-for-op identical to the legacy three-pass body of
    ``KVTable._push_impl`` (same gathers, same elementwise update, same
    ``.at[].set`` write-backs), so switching a table between fused and
    three-pass mode is bitwise-neutral on the XLA backends.
    """
    v_rows = gather_rows_xla(value, ids)
    s_rows = {k: gather_rows_xla(v, ids) for k, v in state.items()}
    new_v, new_s = row_fn(v_rows, s_rows, grads)
    value = scatter_update_rows_xla(value, ids, new_v)
    state = {
        k: scatter_update_rows_xla(state[k], ids, new_s[k]) for k in state
    }
    return value, state


def _apply_kernel(ids_ref, grads_ref, *refs, block, c, names, row_fn, dim):
    """Single-pass gather → optimizer step → scatter over value + S states.

    ``refs`` layout (S = len(names)): ``1 + S`` table inputs (HBM, aliased
    to the outputs, so all DMA goes through the output refs), ``1 + S``
    output refs, ``1 + S`` VMEM scratch buffers (2 slots each), then the
    read/write DMA semaphore arrays (shape ``(2, 1 + S, block)``).

    Double-buffered exactly like ``_scatter_add_kernel``: block *i*'s
    compute + write-back overlaps block *i+1*'s row prefetch.  Unique row
    ids keep the overlap race-free for real rows.  The shared trash row is
    the one exception — unlike scatter-add's ``+0`` (bytes unchanged), a
    state rule may rewrite trash bytes (e.g. Adam's per-row ``t``), so
    concurrent trash prefetch/write-back can race.  That nondeterminism is
    confined to the trash row, which the table layer re-zeros immediately
    after every apply — the visible table state stays deterministic.
    """
    ns = 1 + len(names)
    tabs = refs[ns : 2 * ns]  # output refs (alias the input tables)
    scratch = refs[2 * ns : 3 * ns]
    rsems, wsems = refs[3 * ns], refs[3 * ns + 1]
    i = pl.program_id(0)
    nb = pl.num_programs(0)
    slot = i % 2
    nxt = (i + 1) % 2

    def rows(tab_j, row, scr_j, k, sems, slot_k):
        return _copy_rows(tabs[tab_j], row, scratch[scr_j].at[slot_k], k,
                          sems.at[slot_k, tab_j, k], c)

    def back(tab_j, row, scr_j, k, sems, slot_k):
        return _copy_rows(scratch[scr_j].at[slot_k], k, tabs[tab_j], row,
                          sems.at[slot_k, tab_j, k], c)

    @pl.when(i == 0)
    def _first_reads():
        for k in range(block):
            row = ids_ref[k]
            for j in range(ns):
                rows(j, row, j, k, rsems, 0).start()

    @pl.when(i > 0)
    def _drain_prev_writes():
        for k in range(block):
            row = ids_ref[(i - 1) * block + k]
            for j in range(ns):
                back(j, row, j, k, wsems, nxt).wait()

    @pl.when(i + 1 < nb)
    def _prefetch_next():
        for k in range(block):
            row = ids_ref[(i + 1) * block + k]
            for j in range(ns):
                rows(j, row, j, k, rsems, nxt).start()

    for k in range(block):
        row = ids_ref[i * block + k]
        for j in range(ns):
            rows(j, row, j, k, rsems, slot).wait()
    v = scratch[0][slot].reshape(block, dim)
    s = {
        name: scratch[1 + j][slot].reshape(block, dim)
        for j, name in enumerate(names)
    }
    g = grads_ref[...].reshape(block, dim)
    new_v, new_s = row_fn(v, s, g)
    scratch[0][slot] = new_v.reshape(scratch[0].shape[1:])
    for j, name in enumerate(names):
        scratch[1 + j][slot] = new_s[name].reshape(scratch[1 + j].shape[1:])
    for k in range(block):
        row = ids_ref[i * block + k]
        for j in range(ns):
            back(j, row, j, k, wsems, slot).start()

    @pl.when(i + 1 == nb)
    def _drain_last_writes():
        for k in range(block):
            row = ids_ref[i * block + k]
            for j in range(ns):
                back(j, row, j, k, wsems, slot).wait()


def _pallas_apply(
    value: jax.Array,
    state: Dict[str, jax.Array],
    ids: jax.Array,
    grads: jax.Array,
    row_fn: RowFn,
    *,
    interpret: bool,
    block_rows: int | None = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    _check_pallas_args(value, ids)
    n = ids.shape[0]
    block = _pick_block_rows(n, block_rows)
    dim = value.shape[1]
    c = _chunks(dim)
    names = tuple(sorted(state))
    ns = 1 + len(names)
    vdim = 128 if c > 1 else dim
    views = [value] + [state[k] for k in names]
    if c > 1:
        views = [t.reshape(-1, 128) for t in views]
        grads = grads.reshape(-1, 128)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec(
                (block * c, vdim), lambda i, ids: (i, 0),
                memory_space=pltpu.VMEM,
            ),
        ]
        + [pl.BlockSpec(memory_space=pl.ANY)] * ns,
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * ns,
        scratch_shapes=[pltpu.VMEM((2, block * c, vdim), value.dtype)] * ns
        + [
            pltpu.SemaphoreType.DMA((2, ns, block)),
            pltpu.SemaphoreType.DMA((2, ns, block)),
        ],
    )
    outs = pl.pallas_call(
        functools.partial(
            _apply_kernel, block=block, c=c, names=names, row_fn=row_fn,
            dim=dim,
        ),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(t.shape, t.dtype) for t in views],
        # table j rides at arg 2 + j (after scalar-prefetch ids and grads)
        input_output_aliases={2 + j: j for j in range(ns)},
        interpret=interpret,
        compiler_params=_side_effect_params(),
    )(ids, grads, *views)
    if c > 1:
        outs = [o.reshape(value.shape) for o in outs]
    return outs[0], {k: outs[1 + j] for j, k in enumerate(names)}


# ---------------------------------------------------------------------------
# Public dispatchers
# ---------------------------------------------------------------------------


# MEASURED VERDICT (bench.py --micro on v5e via axon, 2026-07-29; grid in
# BASELINE.md): XLA's native gather/scatter already runs at the HBM roofline
# for the PS row shapes (dim 128 / batch 1k-32k, ~700 GB/s effective), and
# the hand-rolled DMA kernels match it within run-to-run jitter but never
# consistently beat it.  "auto" therefore resolves to XLA — the pallas path
# stays flag-selectable (and interpreter-testable) for shapes/toolchains
# where the balance shifts.  This is the "prove or drop, by measurement"
# resolution of SURVEY §7 hard part #2.


def gather_rows(
    table: jax.Array,
    ids: jax.Array,
    *,
    impl: Impl = "auto",
    interpret: bool = False,
    block_rows: int | None = None,
) -> jax.Array:
    """Gather ``table[ids]`` (Pull hot loop #2 of the reference server)."""
    if impl != "pallas":
        return gather_rows_xla(table, ids)
    return _pallas_gather(table, ids, interpret=interpret, block_rows=block_rows)


def scatter_add_rows(
    table: jax.Array,
    ids: jax.Array,
    rows: jax.Array,
    *,
    impl: Impl = "auto",
    interpret: bool = False,
    block_rows: int | None = None,
) -> jax.Array:
    """Scatter-add rows into the table (Push hot loop #1 of the reference).

    The pallas path requires unique ``ids`` (pre-combined duplicates); the XLA
    path accepts duplicates.
    """
    if impl != "pallas":
        return scatter_add_rows_xla(table, ids, rows)
    return _pallas_scatter_add(
        table, ids, rows, interpret=interpret, block_rows=block_rows
    )


def scatter_update_rows(
    table: jax.Array,
    ids: jax.Array,
    rows: jax.Array,
    *,
    impl: Impl = "auto",
    interpret: bool = False,
    block_rows: int | None = None,
) -> jax.Array:
    """Overwrite table rows at unique ``ids`` (the Push apply write-back).

    The pallas path is write-only DMA (no read-modify-write); duplicate ids
    are only safe when they carry identical rows (padded trash-row rows do).
    """
    if impl != "pallas":
        return scatter_update_rows_xla(table, ids, rows)
    return _pallas_scatter_set(
        table, ids, rows, interpret=interpret, block_rows=block_rows
    )


def apply_rows(
    value: jax.Array,
    state: Dict[str, jax.Array],
    ids: jax.Array,
    grads: jax.Array,
    row_fn: RowFn,
    *,
    impl: Impl = "auto",
    interpret: bool = False,
    block_rows: int | None = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Fused push apply: gather → ``row_fn`` → scatter-update in one pass.

    Replaces the three kernel groups of the legacy push body (``1 + S``
    gathers, the update, ``1 + S`` scatter-sets) with a single traversal of
    the touched rows.  ``ids`` must be unique real rows (duplicates
    pre-combined; pads all point at the shared trash row, which the caller
    re-zeros).  The pallas path DMAs value + state rows through VMEM once,
    runs ``row_fn`` on the resident block, and writes straight back —
    double-buffered, tables never materialize in VMEM.
    """
    if impl != "pallas":
        return _apply_rows_xla(value, state, ids, grads, row_fn)
    return _pallas_apply(
        value, state, ids, grads, row_fn,
        interpret=interpret, block_rows=block_rows,
    )


@functools.partial(jax.jit, static_argnames=("num_rows", "unique_ids", "impl"))
def combine_and_scatter_add(
    table: jax.Array,
    ids: jax.Array,
    inverse: jax.Array,
    values: jax.Array,
    num_rows: int,
    unique_ids: bool = False,
    impl: Impl = "auto",
) -> jax.Array:
    """Fused duplicate pre-combine + scatter-add (the full Push apply).

    ``inverse`` pre-combines duplicates *per unique key*, but distinct keys
    may still share a row slot once the Localizer overflows (feature
    hashing), so the pallas kernel is only legal with ``unique_ids=True``
    (e.g. ``not localizer.overflowed``) AND an explicit ``impl="pallas"`` —
    by measurement "auto" is XLA (see the dispatcher note above).
    """
    if impl == "pallas" and not unique_ids:
        raise ValueError("impl='pallas' requires unique_ids=True (pre-combined)")
    combined = segment_combine(values, inverse, num_rows)
    return scatter_add_rows(table, ids, combined, impl=impl)
