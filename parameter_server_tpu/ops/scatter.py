"""Device-side sparse table primitives: row gather / scatter-add.

These are the TPU equivalents of the reference server's hot loops
(``src/parameter/kv_vector.h`` :: ``ParallelOrderedMatch`` merge + scatter-ADD
into the value array, and the Pull-side row gather [U — reference mount empty,
public layout]).  The host has already localized global keys to dense row ids
(:mod:`parameter_server_tpu.utils.keys`), so the device only sees fixed-shape
``int32`` row-id vectors.

Two implementations:

- **XLA** (default): ``jnp.take`` / ``.at[].add``.  Differentiable, handles
  duplicate ids, runs everywhere.  XLA lowers these to native gather/scatter
  which is adequate for small-dim tables (e.g. LR weights).
- **Pallas** (``impl="pallas"``): a double-buffer-free DMA kernel that copies
  ``block_rows`` table rows HBM→VMEM per grid step via scalar-prefetched ids,
  adds, and writes back.  The table never materializes in VMEM, so capacity is
  bounded by HBM only.  Requires: unique row ids (pre-combined duplicates —
  exactly what :func:`localize_batch` + :func:`segment_combine` produce),
  ``dim % 128 == 0``, float32.  Padding rows must carry zero values and may
  all point at the shared trash row (writes become idempotent ``+0``).

The duplicate-key pre-combine that the reference does inside
``ParallelOrderedMatch`` happens here as a device-side ``segment_sum``
(:func:`segment_combine`) keyed by the localizer's inverse indices.
"""

from __future__ import annotations

import functools
from typing import Literal

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Impl = Literal["auto", "xla", "pallas"]


def segment_combine(values: jax.Array, inverse: jax.Array, num_rows: int) -> jax.Array:
    """Sum per-position values into their unique-key rows.

    ``inverse`` is the position->unique-row map from ``localize_batch``;
    ``num_rows`` the (bucket-padded) unique count.  Rows past the true unique
    count receive zero — exactly the padding contract the pallas scatter path
    requires.
    """
    return jax.ops.segment_sum(values, inverse, num_segments=num_rows)


# ---------------------------------------------------------------------------
# XLA implementations
# ---------------------------------------------------------------------------


def gather_rows_xla(table: jax.Array, ids: jax.Array) -> jax.Array:
    return jnp.take(table, ids, axis=0)


def scatter_add_rows_xla(table: jax.Array, ids: jax.Array, rows: jax.Array) -> jax.Array:
    return table.at[ids].add(rows)


def scatter_update_rows_xla(table: jax.Array, ids: jax.Array, rows: jax.Array) -> jax.Array:
    return table.at[ids].set(rows)


# ---------------------------------------------------------------------------
# Pallas implementations
# ---------------------------------------------------------------------------


def _pick_block_rows(n: int, block_rows: int | None) -> int:
    """Largest supported block dividing ``n`` (or validate an explicit one)."""
    if block_rows is not None:
        if n % block_rows != 0:
            raise ValueError(
                f"pallas path requires len(ids) % block_rows == 0, got "
                f"{n} % {block_rows}"
            )
        return block_rows
    for b in (32, 16, 8):
        if n % b == 0:
            return b
    raise ValueError(
        f"pallas path requires len(ids) divisible by 8, got {n}; "
        "bucket-pad ids (utils.keys.localize_batch) or use impl='xla'"
    )


def _chunks(dim: int) -> int:
    """Row chunking factor: logical rows are DMAed as ``c`` physical
    ``(., 128)`` rows of the ``(rows*c, 128)`` view.

    Mosaic (this toolchain) only slices HBM memrefs along dim 0 in
    tile-aligned units: a squeezed single-row slice works when the row is
    exactly one 128-lane tile (dim == 128 -> c == 1), and a ``(c, 128)``
    slice works when c is a multiple of the 8-sublane tiling (dim % 1024
    == 0).  Anything between falls back to XLA (measured on-chip: dim
    256/384/512 all reject single-row slices).
    """
    if dim == 128:
        return 1
    c = dim // 128
    if dim % 128 == 0 and c % 8 == 0:
        return c
    raise ValueError(
        f"pallas path requires dim == 128 or dim % 1024 == 0, got {dim}; "
        "use impl='xla'"
    )


def _check_pallas_args(table: jax.Array, ids: jax.Array) -> None:
    if table.ndim != 2 or table.dtype != jnp.float32:
        raise ValueError(
            f"pallas path requires a 2-D float32 table, got "
            f"{table.shape} {table.dtype}; use impl='xla'"
        )
    _chunks(table.shape[1])


def _copy_rows(src_ref, src_row, dst_ref, dst_row, sem, c):
    """Async copy of one logical row (c physical 128-lane rows)."""
    if c == 1:
        return pltpu.make_async_copy(
            src_ref.at[src_row], dst_ref.at[dst_row], sem
        )
    return pltpu.make_async_copy(
        src_ref.at[pl.ds(src_row * c, c)],
        dst_ref.at[pl.ds(dst_row * c, c)],
        sem,
    )


def _gather_kernel(ids_ref, table_ref, out_ref, sems, *, block, c):
    i = pl.program_id(0)
    for k in range(block):
        row = ids_ref[i * block + k]
        _copy_rows(table_ref, row, out_ref, k, sems.at[k], c).start()
    for k in range(block):
        row = ids_ref[i * block + k]
        _copy_rows(table_ref, row, out_ref, k, sems.at[k], c).wait()


def _pallas_gather(
    table: jax.Array,
    ids: jax.Array,
    *,
    interpret: bool,
    block_rows: int | None = None,
) -> jax.Array:
    _check_pallas_args(table, ids)
    n = ids.shape[0]
    block = _pick_block_rows(n, block_rows)
    dim = table.shape[1]
    c = _chunks(dim)
    tview = table.reshape(-1, 128) if c > 1 else table
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // block,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (block * c, 128 if c > 1 else dim),
            lambda i, ids: (i, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[pltpu.SemaphoreType.DMA((block,))],
    )
    out = pl.pallas_call(
        functools.partial(_gather_kernel, block=block, c=c),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (n * c, 128) if c > 1 else (n, dim), table.dtype
        ),
        interpret=interpret,
    )(ids, tview)
    return out.reshape(n, dim) if c > 1 else out


def _scatter_add_kernel(ids_ref, vals_ref, table_ref, out_ref, scratch,
                        rsems, wsems, *, block, c):
    """Double-buffered read-modify-write scatter-add.

    out_ref aliases table_ref (donated input).  Two scratch slots pipeline
    the row round-trips: while block *i* adds and writes back from slot
    ``i%2``, block *i+1*'s rows are already streaming HBM->VMEM into the
    other slot, hiding the gather latency behind the add+write of the
    previous block (the "double-buffering" VERDICT r2 #4 asked for).

    Safety: row ids are unique (callers guarantee; duplicates are
    pre-combined), so block *i*'s write-backs and block *i+1*'s prefetches
    never touch the same row — except the shared trash row, which holds
    zeros and receives +0 writes (bytes unchanged), making the overlap
    benign there too.
    """
    i = pl.program_id(0)
    nb = pl.num_programs(0)
    slot = i % 2
    nxt = (i + 1) % 2

    @pl.when(i == 0)
    def _first_reads():
        for k in range(block):
            row = ids_ref[k]
            _copy_rows(out_ref, row, scratch.at[0], k, rsems.at[0, k], c).start()

    # Slot reuse: the write-backs issued at step i-1 came FROM scratch[nxt];
    # they must land before new rows stream INTO that slot.
    @pl.when(i > 0)
    def _drain_prev_writes():
        for k in range(block):
            row = ids_ref[(i - 1) * block + k]
            _copy_rows(
                scratch.at[nxt], k, out_ref, row, wsems.at[nxt, k], c
            ).wait()

    @pl.when(i + 1 < nb)
    def _prefetch_next():
        for k in range(block):
            row = ids_ref[(i + 1) * block + k]
            _copy_rows(
                out_ref, row, scratch.at[nxt], k, rsems.at[nxt, k], c
            ).start()

    for k in range(block):
        row = ids_ref[i * block + k]
        _copy_rows(out_ref, row, scratch.at[slot], k, rsems.at[slot, k], c).wait()
    scratch[slot] = scratch[slot] + vals_ref[...]
    for k in range(block):
        row = ids_ref[i * block + k]
        _copy_rows(scratch.at[slot], k, out_ref, row, wsems.at[slot, k], c).start()

    @pl.when(i + 1 == nb)
    def _drain_last_writes():
        for k in range(block):
            row = ids_ref[i * block + k]
            _copy_rows(
                scratch.at[slot], k, out_ref, row, wsems.at[slot, k], c
            ).wait()


def _pallas_scatter_add(
    table: jax.Array,
    ids: jax.Array,
    rows: jax.Array,
    *,
    interpret: bool,
    block_rows: int | None = None,
) -> jax.Array:
    _check_pallas_args(table, ids)
    n = ids.shape[0]
    block = _pick_block_rows(n, block_rows)
    dim = table.shape[1]
    c = _chunks(dim)
    tview = table.reshape(-1, 128) if c > 1 else table
    rview = rows.reshape(-1, 128) if c > 1 else rows
    vdim = 128 if c > 1 else dim
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec(
                (block * c, vdim), lambda i, ids: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, block * c, vdim), table.dtype),
            pltpu.SemaphoreType.DMA((2, block)),
            pltpu.SemaphoreType.DMA((2, block)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_scatter_add_kernel, block=block, c=c),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(tview.shape, table.dtype),
        input_output_aliases={2: 0},  # table (arg idx incl. scalar prefetch) -> out
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
    )(ids, rview, tview)
    return out.reshape(table.shape) if c > 1 else out


def _scatter_set_kernel(ids_ref, vals_ref, table_ref, out_ref, sems, *, block, c):
    """Write-only row update (Push apply writes new rows; no RMW needed).

    Duplicate ids are tolerated ONLY when they carry identical rows (the
    padded-trash-row case): concurrent same-bytes writes are idempotent.
    """
    i = pl.program_id(0)
    for k in range(block):
        row = ids_ref[i * block + k]
        _copy_rows(vals_ref, k, out_ref, row, sems.at[k], c).start()
    for k in range(block):
        row = ids_ref[i * block + k]
        _copy_rows(vals_ref, k, out_ref, row, sems.at[k], c).wait()


def _pallas_scatter_set(
    table: jax.Array,
    ids: jax.Array,
    rows: jax.Array,
    *,
    interpret: bool,
    block_rows: int | None = None,
) -> jax.Array:
    _check_pallas_args(table, ids)
    n = ids.shape[0]
    block = _pick_block_rows(n, block_rows)
    dim = table.shape[1]
    c = _chunks(dim)
    tview = table.reshape(-1, 128) if c > 1 else table
    rview = rows.reshape(-1, 128) if c > 1 else rows
    vdim = 128 if c > 1 else dim
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec(
                (block * c, vdim), lambda i, ids: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((block,))],
    )
    out = pl.pallas_call(
        functools.partial(_scatter_set_kernel, block=block, c=c),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(tview.shape, table.dtype),
        input_output_aliases={2: 0},
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
    )(ids, rview, tview)
    return out.reshape(table.shape) if c > 1 else out


# ---------------------------------------------------------------------------
# Public dispatchers
# ---------------------------------------------------------------------------


# MEASURED VERDICT (bench.py --micro on v5e via axon, 2026-07-29; grid in
# BASELINE.md): XLA's native gather/scatter already runs at the HBM roofline
# for the PS row shapes (dim 128 / batch 1k-32k, ~700 GB/s effective), and
# the hand-rolled DMA kernels match it within run-to-run jitter but never
# consistently beat it.  "auto" therefore resolves to XLA — the pallas path
# stays flag-selectable (and interpreter-testable) for shapes/toolchains
# where the balance shifts.  This is the "prove or drop, by measurement"
# resolution of SURVEY §7 hard part #2.


def gather_rows(
    table: jax.Array,
    ids: jax.Array,
    *,
    impl: Impl = "auto",
    interpret: bool = False,
    block_rows: int | None = None,
) -> jax.Array:
    """Gather ``table[ids]`` (Pull hot loop #2 of the reference server)."""
    if impl != "pallas":
        return gather_rows_xla(table, ids)
    return _pallas_gather(table, ids, interpret=interpret, block_rows=block_rows)


def scatter_add_rows(
    table: jax.Array,
    ids: jax.Array,
    rows: jax.Array,
    *,
    impl: Impl = "auto",
    interpret: bool = False,
    block_rows: int | None = None,
) -> jax.Array:
    """Scatter-add rows into the table (Push hot loop #1 of the reference).

    The pallas path requires unique ``ids`` (pre-combined duplicates); the XLA
    path accepts duplicates.
    """
    if impl != "pallas":
        return scatter_add_rows_xla(table, ids, rows)
    return _pallas_scatter_add(
        table, ids, rows, interpret=interpret, block_rows=block_rows
    )


def scatter_update_rows(
    table: jax.Array,
    ids: jax.Array,
    rows: jax.Array,
    *,
    impl: Impl = "auto",
    interpret: bool = False,
    block_rows: int | None = None,
) -> jax.Array:
    """Overwrite table rows at unique ``ids`` (the Push apply write-back).

    The pallas path is write-only DMA (no read-modify-write); duplicate ids
    are only safe when they carry identical rows (padded trash-row rows do).
    """
    if impl != "pallas":
        return scatter_update_rows_xla(table, ids, rows)
    return _pallas_scatter_set(
        table, ids, rows, interpret=interpret, block_rows=block_rows
    )


@functools.partial(jax.jit, static_argnames=("num_rows", "unique_ids", "impl"))
def combine_and_scatter_add(
    table: jax.Array,
    ids: jax.Array,
    inverse: jax.Array,
    values: jax.Array,
    num_rows: int,
    unique_ids: bool = False,
    impl: Impl = "auto",
) -> jax.Array:
    """Fused duplicate pre-combine + scatter-add (the full Push apply).

    ``inverse`` pre-combines duplicates *per unique key*, but distinct keys
    may still share a row slot once the Localizer overflows (feature
    hashing), so the pallas kernel is only legal with ``unique_ids=True``
    (e.g. ``not localizer.overflowed``) AND an explicit ``impl="pallas"`` —
    by measurement "auto" is XLA (see the dispatcher note above).
    """
    if impl == "pallas" and not unique_ids:
        raise ValueError("impl='pallas' requires unique_ids=True (pre-combined)")
    combined = segment_combine(values, inverse, num_rows)
    return scatter_add_rows(table, ids, combined, impl=impl)
