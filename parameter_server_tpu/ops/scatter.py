"""Device-side sparse table primitives: row gather / scatter-add.

These are the TPU equivalents of the reference server's hot loops
(``src/parameter/kv_vector.h`` :: ``ParallelOrderedMatch`` merge + scatter-ADD
into the value array, and the Pull-side row gather [U — reference mount empty,
public layout]).  The host has already localized global keys to dense row ids
(:mod:`parameter_server_tpu.utils.keys`), so the device only sees fixed-shape
``int32`` row-id vectors.

Two implementations:

- **XLA** (default): ``jnp.take`` / ``.at[].add``.  Differentiable, handles
  duplicate ids, runs everywhere.  XLA lowers these to native gather/scatter
  which is adequate for small-dim tables (e.g. LR weights).
- **Pallas** (``impl="pallas"``): a double-buffer-free DMA kernel that copies
  ``block_rows`` table rows HBM→VMEM per grid step via scalar-prefetched ids,
  adds, and writes back.  The table never materializes in VMEM, so capacity is
  bounded by HBM only.  Requires: unique row ids (pre-combined duplicates —
  exactly what :func:`localize_batch` + :func:`segment_combine` produce),
  ``dim % 128 == 0``, float32.  Padding rows must carry zero values and may
  all point at the shared trash row (writes become idempotent ``+0``).

The duplicate-key pre-combine that the reference does inside
``ParallelOrderedMatch`` happens here as a device-side ``segment_sum``
(:func:`segment_combine`) keyed by the localizer's inverse indices.
"""

from __future__ import annotations

import functools
from typing import Literal

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Impl = Literal["auto", "xla", "pallas"]

#: rows copied per pallas grid step; 8 == f32 sublane count.
_BLOCK_ROWS = 8


def segment_combine(values: jax.Array, inverse: jax.Array, num_rows: int) -> jax.Array:
    """Sum per-position values into their unique-key rows.

    ``inverse`` is the position->unique-row map from ``localize_batch``;
    ``num_rows`` the (bucket-padded) unique count.  Rows past the true unique
    count receive zero — exactly the padding contract the pallas scatter path
    requires.
    """
    return jax.ops.segment_sum(values, inverse, num_segments=num_rows)


# ---------------------------------------------------------------------------
# XLA implementations
# ---------------------------------------------------------------------------


def gather_rows_xla(table: jax.Array, ids: jax.Array) -> jax.Array:
    return jnp.take(table, ids, axis=0)


def scatter_add_rows_xla(table: jax.Array, ids: jax.Array, rows: jax.Array) -> jax.Array:
    return table.at[ids].add(rows)


def scatter_update_rows_xla(table: jax.Array, ids: jax.Array, rows: jax.Array) -> jax.Array:
    return table.at[ids].set(rows)


# ---------------------------------------------------------------------------
# Pallas implementations
# ---------------------------------------------------------------------------


def _gather_kernel(ids_ref, table_ref, out_ref, sems):
    i = pl.program_id(0)
    for k in range(_BLOCK_ROWS):
        row = ids_ref[i * _BLOCK_ROWS + k]
        pltpu.make_async_copy(table_ref.at[row], out_ref.at[k], sems.at[k]).start()
    for k in range(_BLOCK_ROWS):
        row = ids_ref[i * _BLOCK_ROWS + k]
        pltpu.make_async_copy(table_ref.at[row], out_ref.at[k], sems.at[k]).wait()


def _check_pallas_args(table: jax.Array, ids: jax.Array) -> None:
    if ids.shape[0] % _BLOCK_ROWS != 0:
        raise ValueError(
            f"pallas path requires len(ids) % {_BLOCK_ROWS} == 0, got {ids.shape[0]}; "
            "bucket-pad ids (utils.keys.localize_batch) or use impl='xla'"
        )
    if table.ndim != 2 or table.shape[1] % 128 != 0 or table.dtype != jnp.float32:
        raise ValueError(
            f"pallas path requires a 2-D float32 table with dim % 128 == 0, got "
            f"{table.shape} {table.dtype}; use impl='xla'"
        )


def _pallas_gather(table: jax.Array, ids: jax.Array, *, interpret: bool) -> jax.Array:
    _check_pallas_args(table, ids)
    n = ids.shape[0]
    dim = table.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // _BLOCK_ROWS,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (_BLOCK_ROWS, dim), lambda i, ids: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[pltpu.SemaphoreType.DMA((_BLOCK_ROWS,))],
    )
    return pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, dim), table.dtype),
        interpret=interpret,
    )(ids, table)


def _scatter_add_kernel(ids_ref, vals_ref, table_ref, out_ref, scratch, sems):
    # out_ref aliases table_ref (donated input): read rows, add, write back.
    i = pl.program_id(0)
    for k in range(_BLOCK_ROWS):
        row = ids_ref[i * _BLOCK_ROWS + k]
        pltpu.make_async_copy(out_ref.at[row], scratch.at[k], sems.at[k]).start()
    for k in range(_BLOCK_ROWS):
        row = ids_ref[i * _BLOCK_ROWS + k]
        pltpu.make_async_copy(out_ref.at[row], scratch.at[k], sems.at[k]).wait()
    scratch[...] = scratch[...] + vals_ref[...]
    for k in range(_BLOCK_ROWS):
        row = ids_ref[i * _BLOCK_ROWS + k]
        pltpu.make_async_copy(scratch.at[k], out_ref.at[row], sems.at[k]).start()
    for k in range(_BLOCK_ROWS):
        row = ids_ref[i * _BLOCK_ROWS + k]
        pltpu.make_async_copy(scratch.at[k], out_ref.at[row], sems.at[k]).wait()


def _pallas_scatter_add(
    table: jax.Array, ids: jax.Array, rows: jax.Array, *, interpret: bool
) -> jax.Array:
    _check_pallas_args(table, ids)
    n = ids.shape[0]
    dim = table.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // _BLOCK_ROWS,),
        in_specs=[
            pl.BlockSpec(
                (_BLOCK_ROWS, dim), lambda i, ids: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((_BLOCK_ROWS, dim), table.dtype),
            pltpu.SemaphoreType.DMA((_BLOCK_ROWS,)),
        ],
    )
    return pl.pallas_call(
        _scatter_add_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        input_output_aliases={2: 0},  # table (arg idx incl. scalar prefetch) -> out
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
    )(ids, rows, table)


def _pallas_ok(table: jax.Array, ids: jax.Array) -> bool:
    return (
        table.ndim == 2
        and table.dtype == jnp.float32
        and table.shape[1] % 128 == 0
        and ids.shape[0] % _BLOCK_ROWS == 0
    )


# ---------------------------------------------------------------------------
# Public dispatchers
# ---------------------------------------------------------------------------


def _on_tpu() -> bool:
    # The axon PJRT plugin used in the dev environment also reports "tpu".
    return jax.default_backend() == "tpu"


def gather_rows(
    table: jax.Array, ids: jax.Array, *, impl: Impl = "auto", interpret: bool = False
) -> jax.Array:
    """Gather ``table[ids]`` (Pull hot loop #2 of the reference server)."""
    if impl == "xla" or (impl == "auto" and not (_on_tpu() and _pallas_ok(table, ids))):
        return gather_rows_xla(table, ids)
    return _pallas_gather(table, ids, interpret=interpret)


def scatter_add_rows(
    table: jax.Array,
    ids: jax.Array,
    rows: jax.Array,
    *,
    impl: Impl = "auto",
    interpret: bool = False,
) -> jax.Array:
    """Scatter-add rows into the table (Push hot loop #1 of the reference).

    The pallas path requires unique ``ids`` (pre-combined duplicates); the XLA
    path accepts duplicates.
    """
    if impl == "xla" or (impl == "auto" and not (_on_tpu() and _pallas_ok(table, ids))):
        return scatter_add_rows_xla(table, ids, rows)
    return _pallas_scatter_add(table, ids, rows, interpret=interpret)


@functools.partial(jax.jit, static_argnames=("num_rows", "unique_ids"))
def combine_and_scatter_add(
    table: jax.Array,
    ids: jax.Array,
    inverse: jax.Array,
    values: jax.Array,
    num_rows: int,
    unique_ids: bool = False,
) -> jax.Array:
    """Fused duplicate pre-combine + scatter-add (the full Push apply).

    ``inverse`` pre-combines duplicates *per unique key*, but distinct keys may
    still share a row slot once the Localizer overflows (feature hashing), so
    by default the duplicate-tolerant XLA scatter is used.  Pass
    ``unique_ids=True`` only when the caller guarantees slot uniqueness (e.g.
    ``not localizer.overflowed``) to enable the pallas fast path.
    """
    combined = segment_combine(values, inverse, num_rows)
    impl: Impl = "auto" if unique_ids else "xla"
    return scatter_add_rows(table, ids, combined, impl=impl)
