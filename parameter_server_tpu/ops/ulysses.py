"""Ulysses-style sequence parallelism: all-to-all head redistribution.

The alternative to ring attention (SURVEY.md §5): instead of rotating K/V
around the ring, redistribute — each device starts with the full head set on
a sequence shard, all-to-alls to hold *all* sequence positions for a subset
of heads, runs ordinary (full-sequence) attention locally, and all-to-alls
back.  Two collectives per attention call; preferable when heads >> devices
and the per-device full-sequence block fits memory.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from parameter_server_tpu.ops.ring_attention import reference_attention


def ulysses_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Call inside shard_map. q/k/v: [B, S_local, H, D]; H % axis_size == 0.

    Returns [B, S_local, H, D].
    """
    # Tiled all_to_alls: split one axis into n source-ordered chunks,
    # concatenate received chunks on another — seq_to_heads and
    # heads_to_seq are exact mirrors, head order stays group-major, and
    # (unlike the earlier reshape-and-transpose formulation) the transpose
    # rule is clean, so reverse-AD through the attention works — required
    # since SpLMTrainer(attn="ulysses") TRAINS through this op.
    def seq_to_heads(x):  # [B, S_loc, H, D] -> [B, S_glob, H/n, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):  # [B, S_glob, H/n, D] -> [B, S_loc, H, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = reference_attention(qg, kg, vg, causal=causal)
    return heads_to_seq(out)


def make_ulysses_attention(mesh: Mesh, *, sp_axis: str, causal: bool = False):
    from jax import shard_map

    spec = P(None, sp_axis, None, None)
    fn = shard_map(
        functools.partial(ulysses_attention, axis_name=sp_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return jax.jit(fn)
