"""ops subpackage."""
