"""Multi-host SPMD job launch: one process per pod host, global GSPMD mesh.

The counterpart of ``launch.py`` (which spawns the *PS role* topology over
TcpVan) for the pure-GSPMD data plane: a v5e-16 pod runs 4 host processes,
each owning 4 chips, joined by ``jax.distributed`` into one global mesh
(SURVEY.md §7 step 4; VERDICT r1 missing #2).  On dev machines the same job
runs as N processes x K virtual CPU devices — identical program, Gloo
collectives instead of ICI.

Per-process flow (:func:`main`): ``distributed.initialize`` -> global
``(data, model)`` mesh -> :class:`~parameter_server_tpu.parallel.lr_spmd.SpmdLRTrainer`
row-sharded across all hosts -> each step, every process generates the SAME
deterministic global batch (seeded stream, the reference's WorkloadPool
determinism) and feeds only its :func:`~parameter_server_tpu.parallel.distributed.local_batch_slice`
of it.  Process 0 writes the loss trajectory for the launcher to aggregate.

``launch_spmd`` spawns the whole job locally (the CPU-sim pod) and returns
the losses — used by tests and ``__graft_entry__.dryrun_multichip`` to prove
multi-process GSPMD training matches single-process loss-for-loss.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Optional

from parameter_server_tpu.launch import _free_port


def _assign_shards(num_procs: int, n_shards: int) -> dict:
    """Deterministic WorkloadPool shard assignment, same on every process.

    Every process replays the identical request order against a local
    :class:`~parameter_server_tpu.learner.workload.WorkloadPool`, so the
    assignment is coordination-free (no scheduler RPC needed for the static
    SPMD schedule) yet uses the same pool machinery the PS topology uses
    dynamically.  Shards are CONTIGUOUS blocks per process — shard i is
    global-batch rows [i*B/n, (i+1)*B/n), and a process's devices address a
    contiguous 1/num_procs slice — and the shard streams themselves are
    process-count-independent, so a 1-process job and an N-process job see
    byte-identical global batches (the mesh-shape-defined-program invariant).
    """
    from parameter_server_tpu.learner.workload import WorkloadPool

    if n_shards % num_procs:
        raise ValueError(f"data shards {n_shards} % procs {num_procs} != 0")
    per = n_shards // num_procs
    pool = WorkloadPool(list(range(n_shards)))
    assignment: dict = {}
    for p in range(num_procs):  # block order: proc p owns [p*per, (p+1)*per)
        assignment[p] = [pool.get(f"proc{p}").payload for _ in range(per)]
    return assignment


def _ckpt_path(root: str, step: int) -> str:
    return os.path.join(root, f"spmd_step{step:06d}.npz")


def _latest_ckpt_step(root: str) -> Optional[int]:
    if not root or not os.path.isdir(root):
        return None
    steps = []
    for name in os.listdir(root):
        if name.startswith("spmd_step") and name.endswith(".npz"):
            steps.append(int(name[len("spmd_step") : -4]))
    return max(steps) if steps else None


def run_job(
    *,
    coordinator: Optional[str],
    num_procs: int,
    proc_id: int,
    cpu_devices: int,
    steps: int,
    rows: int,
    global_batch: int,
    nnz: int,
    mesh_data: int,
    seed: int = 0,
    data_shards: Optional[int] = None,
    ckpt_root: Optional[str] = None,
    ckpt_every: int = 0,
    resume: bool = False,
    die_after_step: Optional[int] = None,
    die_proc: int = 1,
) -> dict:
    """One process's share of the SPMD LR job.

    Returns ``{"losses": [...], "data_digest": ..., "start_step": ...}``.
    Losses are global (replicated out of the jit step), so every process
    returns the same trajectory — asserting them equal across processes is
    part of the test contract.

    Data is genuinely PER-PROCESS sharded (VERDICT r2 #6): each process owns
    WorkloadPool-assigned shard streams and generates ONLY its local share
    of every global batch — no generate-everything-and-slice.  With
    ``ckpt_root``/``ckpt_every`` the full sharded state checkpoints every K
    steps (barriered, then process 0 writes atomically); ``resume`` restarts
    from the newest checkpoint with data streams fast-forwarded, which is
    how a killed process (or whole job) rejoins.  ``die_after_step`` is the
    fault-injection hook: ``die_proc`` exits hard after that step.
    """
    from parameter_server_tpu.parallel import distributed

    distributed.initialize(
        coordinator, num_procs, proc_id, cpu_devices=cpu_devices
    )
    import jax
    import jax.numpy as jnp
    import numpy as np_  # shadow-proof alias under the function scope
    from jax.experimental import multihost_utils

    from parameter_server_tpu.config import OptimizerConfig, TableConfig
    from parameter_server_tpu.data.synthetic import SyntheticCTR
    from parameter_server_tpu.parallel import lr_spmd

    n_dev = len(jax.devices())
    if n_dev % mesh_data:
        raise ValueError(f"{n_dev} devices not divisible by data={mesh_data}")
    mesh = distributed.global_mesh((mesh_data, n_dev // mesh_data))
    cfg = TableConfig(
        name="w",
        rows=rows,
        dim=1,
        optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.1),
    )
    trainer = lr_spmd.SpmdLRTrainer(cfg, mesh, seed=seed)

    # -- per-process data shards (each proc generates ONLY its share) -------
    # A process feeds the batch rows its own devices address.  When the data
    # axis spans the processes (mesh_data >= num_procs) each process
    # generates exactly its own shards; otherwise (batch replicated along
    # the model axis) every process must feed the full batch, i.e. it owns
    # ALL shards — the streams are identical either way, so the global batch
    # is process-count-invariant.
    n_shards = data_shards or max(2 * num_procs, 4)
    if global_batch % n_shards:
        raise ValueError(f"global_batch {global_batch} % shards {n_shards}")
    shard_batch = global_batch // n_shards
    sharded_feed = mesh_data >= num_procs and mesh_data % num_procs == 0
    if sharded_feed:
        my_shards = _assign_shards(num_procs, n_shards)[proc_id]
    else:
        my_shards = list(range(n_shards))

    def _stream(shard: int) -> SyntheticCTR:
        return SyntheticCTR(
            key_space=4 * rows, nnz=nnz, batch_size=shard_batch,
            seed=seed + 7919 * (shard + 1),
        )

    streams = {shard: _stream(shard) for shard in my_shards}
    digest = None  # first local batch fingerprint (test observability)

    # -- resume --------------------------------------------------------------
    start_step = 0
    if resume and ckpt_root:
        last = _latest_ckpt_step(ckpt_root)
        if last is not None:
            with np_.load(_ckpt_path(ckpt_root, last)) as z:
                host_state = {k: z[k] for k in z.files}
            st = trainer.state
            shardings = jax.tree.map(lambda a: a.sharding, st)

            def put(np_arr, sharding):
                return jax.make_array_from_callback(
                    np_arr.shape, sharding, lambda idx: np_arr[idx]
                )

            trainer.state = lr_spmd.ShardedLRState(
                value=put(host_state["value"], shardings.value),
                state={
                    k: put(host_state[f"state.{k}"], shardings.state[k])
                    for k in st.state
                },
                bias=put(host_state["bias"], shardings.bias),
                bias_state={
                    k: put(host_state[f"bias_state.{k}"], shardings.bias_state[k])
                    for k in st.bias_state
                },
            )
            start_step = last
    # absolute-step indexed feeding: regenerate and skip consumed batches so
    # a resumed run sees exactly the batches the lost steps would have seen
    for _ in range(start_step):
        for stream in streams.values():
            stream.next_batch()

    losses = []
    for s in range(start_step, steps):
        parts = [streams[sh].next_batch() for sh in my_shards]
        keys = np_.concatenate([p[0] for p in parts])
        labels = np_.concatenate([p[1] for p in parts])
        if digest is None:
            digest = int(np_.asarray(keys, dtype=np_.uint64).sum())
        losses.append(trainer.step(keys, labels, global_batch=global_batch))
        done = s + 1
        if ckpt_root and ckpt_every and done % ckpt_every == 0 and done < steps:
            # gather the full state on every process; proc 0 writes atomically
            full = jax.tree.map(
                lambda a: np_.asarray(multihost_utils.process_allgather(a, tiled=True)),
                trainer.state,
            )
            if proc_id == 0:
                os.makedirs(ckpt_root, exist_ok=True)
                arrays = {"value": full.value, "bias": full.bias}
                arrays.update({f"state.{k}": v for k, v in full.state.items()})
                arrays.update(
                    {f"bias_state.{k}": v for k, v in full.bias_state.items()}
                )
                tmp = _ckpt_path(ckpt_root, done) + ".tmp"
                with open(tmp, "wb") as f:
                    np_.savez(f, **arrays)
                os.replace(tmp, _ckpt_path(ckpt_root, done))
            multihost_utils.sync_global_devices(f"ckpt{done}")
        if (
            die_after_step is not None
            and (die_proc < 0 or proc_id == die_proc)
            and done == die_after_step
        ):
            # fault injection: hard kill mid-job.  die_proc=-1 kills EVERY
            # process at that step (a whole-job death): a single-proc kill
            # leaves the survivors blocked in the next Gloo collective until
            # the launch timeout, which is realistic but burns minutes of
            # suite wall clock (ADVICE r3) — resume semantics are identical.
            os._exit(17)
    return {"losses": losses, "data_digest": digest, "start_step": start_step}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--coordinator", default=None)
    p.add_argument("--num-procs", type=int, default=1)
    p.add_argument("--proc-id", type=int, default=0)
    p.add_argument("--cpu-devices", type=int, default=0)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--rows", type=int, default=1 << 12)
    p.add_argument("--global-batch", type=int, default=256)
    p.add_argument("--nnz", type=int, default=8)
    p.add_argument("--mesh-data", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--outdir", default=None)
    p.add_argument("--data-shards", type=int, default=None)
    p.add_argument("--ckpt-root", default=None)
    p.add_argument("--ckpt-every", type=int, default=0)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--die-after-step", type=int, default=None)
    p.add_argument("--die-proc", type=int, default=1)
    args = p.parse_args(argv)
    result = run_job(
        coordinator=args.coordinator,
        num_procs=args.num_procs,
        proc_id=args.proc_id,
        cpu_devices=args.cpu_devices,
        steps=args.steps,
        rows=args.rows,
        global_batch=args.global_batch,
        nnz=args.nnz,
        mesh_data=args.mesh_data,
        seed=args.seed,
        data_shards=args.data_shards,
        ckpt_root=args.ckpt_root,
        ckpt_every=args.ckpt_every,
        resume=args.resume,
        die_after_step=args.die_after_step,
        die_proc=args.die_proc,
    )
    if args.outdir:
        path = os.path.join(args.outdir, f"proc{args.proc_id}.json")
        with open(path, "w") as f:
            json.dump({"proc": args.proc_id, **result}, f)
    return 0


def launch_spmd(
    *,
    num_procs: int = 2,
    cpu_devices: int = 4,
    steps: int = 8,
    rows: int = 1 << 12,
    global_batch: int = 256,
    nnz: int = 8,
    mesh_data: int = 2,
    seed: int = 0,
    timeout: float = 300.0,
    python: str = sys.executable,
    data_shards: Optional[int] = None,
    ckpt_root: Optional[str] = None,
    ckpt_every: int = 0,
    resume: bool = False,
    die_after_step: Optional[int] = None,
    die_proc: int = 1,
) -> dict:
    """Spawn the CPU-sim pod: ``num_procs`` processes x ``cpu_devices``.

    Returns ``{"returncodes": [...], "losses": {proc_id: [...]},
    "digests": {...}, "start_steps": {...}}``.
    """
    port = _free_port()
    outdir = tempfile.mkdtemp(prefix="psx_spmd_")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pypath = os.environ.get("PYTHONPATH", "")
    env = dict(
        os.environ,
        PYTHONPATH=f"{repo_root}:{pypath}" if pypath else repo_root,
    )

    extra = []
    if data_shards is not None:
        extra += ["--data-shards", str(data_shards)]
    if ckpt_root:
        extra += ["--ckpt-root", ckpt_root, "--ckpt-every", str(ckpt_every)]
    if resume:
        extra += ["--resume"]
    if die_after_step is not None:
        extra += [
            "--die-after-step", str(die_after_step), "--die-proc", str(die_proc)
        ]
    procs = [
        subprocess.Popen(
            [
                python, "-m", "parameter_server_tpu.launch_spmd",
                "--coordinator", f"127.0.0.1:{port}",
                "--num-procs", str(num_procs),
                "--proc-id", str(i),
                "--cpu-devices", str(cpu_devices),
                "--steps", str(steps), "--rows", str(rows),
                "--global-batch", str(global_batch), "--nnz", str(nnz),
                "--mesh-data", str(mesh_data), "--seed", str(seed),
                "--outdir", outdir,
                *extra,
            ],
            env=env,
        )
        for i in range(num_procs)
    ]
    deadline = time.monotonic() + timeout
    rcs = []
    try:
        for p_ in procs:
            try:
                rcs.append(
                    p_.wait(timeout=max(deadline - time.monotonic(), 1.0))
                )
            except subprocess.TimeoutExpired:
                # e.g. the coordinator died and a peer hangs in initialize:
                # report which processes hung instead of raising, so callers
                # see the real failing rc alongside the -9s
                rcs.append(None)
    finally:
        for p_ in procs:
            if p_.poll() is None:
                p_.kill()
        for p_ in procs:
            # reap: SIGKILL delivery is asynchronous, so an immediate poll()
            # can still read None — wait bounds it and makes the reported
            # returncode deterministically -9 (ADVICE r2)
            if p_.poll() is None:
                try:
                    p_.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass  # unkillable (D-state): leave rc as None
    rcs = [p_.poll() if rc is None else rc for rc, p_ in zip(rcs, procs)]
    losses = {}
    digests = {}
    start_steps = {}
    for i in range(num_procs):
        path = os.path.join(outdir, f"proc{i}.json")
        if os.path.exists(path):
            with open(path) as f:
                rec = json.load(f)
            losses[i] = rec["losses"]
            digests[i] = rec.get("data_digest")
            start_steps[i] = rec.get("start_step", 0)
    shutil.rmtree(outdir, ignore_errors=True)
    return {
        "returncodes": rcs,
        "losses": losses,
        "digests": digests,
        "start_steps": start_steps,
    }


if __name__ == "__main__":
    sys.exit(main())
