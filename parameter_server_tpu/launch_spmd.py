"""Multi-host SPMD job launch: one process per pod host, global GSPMD mesh.

The counterpart of ``launch.py`` (which spawns the *PS role* topology over
TcpVan) for the pure-GSPMD data plane: a v5e-16 pod runs 4 host processes,
each owning 4 chips, joined by ``jax.distributed`` into one global mesh
(SURVEY.md §7 step 4; VERDICT r1 missing #2).  On dev machines the same job
runs as N processes x K virtual CPU devices — identical program, Gloo
collectives instead of ICI.

Per-process flow (:func:`main`): ``distributed.initialize`` -> global
``(data, model)`` mesh -> :class:`~parameter_server_tpu.parallel.lr_spmd.SpmdLRTrainer`
row-sharded across all hosts -> each step, every process generates the SAME
deterministic global batch (seeded stream, the reference's WorkloadPool
determinism) and feeds only its :func:`~parameter_server_tpu.parallel.distributed.local_batch_slice`
of it.  Process 0 writes the loss trajectory for the launcher to aggregate.

``launch_spmd`` spawns the whole job locally (the CPU-sim pod) and returns
the losses — used by tests and ``__graft_entry__.dryrun_multichip`` to prove
multi-process GSPMD training matches single-process loss-for-loss.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Optional

from parameter_server_tpu.launch import _free_port


def run_job(
    *,
    coordinator: Optional[str],
    num_procs: int,
    proc_id: int,
    cpu_devices: int,
    steps: int,
    rows: int,
    global_batch: int,
    nnz: int,
    mesh_data: int,
    seed: int = 0,
) -> list[float]:
    """One process's share of the SPMD LR job; returns per-step losses.

    Losses are global (replicated out of the jit step), so every process
    returns the same trajectory — asserting them equal across processes is
    part of the test contract.
    """
    from parameter_server_tpu.parallel import distributed

    distributed.initialize(
        coordinator, num_procs, proc_id, cpu_devices=cpu_devices
    )
    import jax

    from parameter_server_tpu.config import OptimizerConfig, TableConfig
    from parameter_server_tpu.data.synthetic import SyntheticCTR
    from parameter_server_tpu.parallel import lr_spmd

    n_dev = len(jax.devices())
    if n_dev % mesh_data:
        raise ValueError(f"{n_dev} devices not divisible by data={mesh_data}")
    mesh = distributed.global_mesh((mesh_data, n_dev // mesh_data))
    cfg = TableConfig(
        name="w",
        rows=rows,
        dim=1,
        optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.1),
    )
    trainer = lr_spmd.SpmdLRTrainer(cfg, mesh, seed=seed)
    # every process generates the identical global stream; determinism of the
    # data assignment is what lets a restarted/elastic process rejoin
    data = SyntheticCTR(
        key_space=4 * rows, nnz=nnz, batch_size=global_batch, seed=seed
    )
    # A process feeds the batch rows its own devices address.  When the data
    # axis spans the processes (mesh_data >= num_procs) that is a contiguous
    # 1/num_procs slice; when it doesn't (e.g. mesh_data=1: batch replicated
    # along the model axis), every process addresses the full batch.
    if mesh_data >= num_procs and mesh_data % num_procs == 0:
        sl = distributed.local_batch_slice(proc_id, num_procs, global_batch)
    else:
        sl = slice(None)
    losses = []
    for _ in range(steps):
        keys, labels = data.next_batch()
        losses.append(
            trainer.step(keys[sl], labels[sl], global_batch=global_batch)
        )
    return losses


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--coordinator", default=None)
    p.add_argument("--num-procs", type=int, default=1)
    p.add_argument("--proc-id", type=int, default=0)
    p.add_argument("--cpu-devices", type=int, default=0)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--rows", type=int, default=1 << 12)
    p.add_argument("--global-batch", type=int, default=256)
    p.add_argument("--nnz", type=int, default=8)
    p.add_argument("--mesh-data", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--outdir", default=None)
    args = p.parse_args(argv)
    losses = run_job(
        coordinator=args.coordinator,
        num_procs=args.num_procs,
        proc_id=args.proc_id,
        cpu_devices=args.cpu_devices,
        steps=args.steps,
        rows=args.rows,
        global_batch=args.global_batch,
        nnz=args.nnz,
        mesh_data=args.mesh_data,
        seed=args.seed,
    )
    if args.outdir:
        path = os.path.join(args.outdir, f"proc{args.proc_id}.json")
        with open(path, "w") as f:
            json.dump({"proc": args.proc_id, "losses": losses}, f)
    return 0


def launch_spmd(
    *,
    num_procs: int = 2,
    cpu_devices: int = 4,
    steps: int = 8,
    rows: int = 1 << 12,
    global_batch: int = 256,
    nnz: int = 8,
    mesh_data: int = 2,
    seed: int = 0,
    timeout: float = 300.0,
    python: str = sys.executable,
) -> dict:
    """Spawn the CPU-sim pod: ``num_procs`` processes x ``cpu_devices``.

    Returns ``{"returncodes": [...], "losses": {proc_id: [...]}}``.
    """
    port = _free_port()
    outdir = tempfile.mkdtemp(prefix="psx_spmd_")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pypath = os.environ.get("PYTHONPATH", "")
    env = dict(
        os.environ,
        PYTHONPATH=f"{repo_root}:{pypath}" if pypath else repo_root,
    )

    procs = [
        subprocess.Popen(
            [
                python, "-m", "parameter_server_tpu.launch_spmd",
                "--coordinator", f"127.0.0.1:{port}",
                "--num-procs", str(num_procs),
                "--proc-id", str(i),
                "--cpu-devices", str(cpu_devices),
                "--steps", str(steps), "--rows", str(rows),
                "--global-batch", str(global_batch), "--nnz", str(nnz),
                "--mesh-data", str(mesh_data), "--seed", str(seed),
                "--outdir", outdir,
            ],
            env=env,
        )
        for i in range(num_procs)
    ]
    deadline = time.monotonic() + timeout
    rcs = []
    try:
        for p_ in procs:
            try:
                rcs.append(
                    p_.wait(timeout=max(deadline - time.monotonic(), 1.0))
                )
            except subprocess.TimeoutExpired:
                # e.g. the coordinator died and a peer hangs in initialize:
                # report which processes hung instead of raising, so callers
                # see the real failing rc alongside the -9s
                rcs.append(None)
    finally:
        for p_ in procs:
            if p_.poll() is None:
                p_.kill()
        for p_ in procs:
            # reap: SIGKILL delivery is asynchronous, so an immediate poll()
            # can still read None — wait bounds it and makes the reported
            # returncode deterministically -9 (ADVICE r2)
            if p_.poll() is None:
                try:
                    p_.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass  # unkillable (D-state): leave rc as None
    rcs = [p_.poll() if rc is None else rc for rc, p_ in zip(rcs, procs)]
    losses = {}
    for i in range(num_procs):
        path = os.path.join(outdir, f"proc{i}.json")
        if os.path.exists(path):
            with open(path) as f:
                losses[i] = json.load(f)["losses"]
    shutil.rmtree(outdir, ignore_errors=True)
    return {"returncodes": rcs, "losses": losses}


if __name__ == "__main__":
    sys.exit(main())
