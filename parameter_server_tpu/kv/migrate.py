"""ShardMigrator: the driver side of live shard migration.

The reference treats key-range handoff as a scheduler-coordinated copy
(Li et al. §4.3: a recovering or retiring server's range is reassigned and
its data fetched from peers); PR-6's online version runs against a LIVE
donor that keeps serving pushes while the bulk of the range streams out:

1. ``migrate_begin`` arms dirty-row tracking on the donor for ``[lo, hi)``.
2. ``migrate_send`` x N streams fixed-size chunks donor -> recipient over
   the replica-chain transport path (the donor's dedicated ``.mig``
   endpoint); pushes landing between chunks are recorded as dirty.
3. ``migrate_commit`` is the freeze fence: on the donor's recv thread
   (atomic wrt pushes) the dirty DELTA is exported, the recipient installs
   chunks+delta and adopts the new routing, then the donor shrinks — the
   freeze is bounded by the delta, not the range (the array-redistribution
   schedule shape from PAPERS.md: bulk copies overlap, only the last hop
   synchronizes).
4. Remaining servers adopt the new table via ``adopt_routing``; workers
   converge off fences (or the scheduler's ROUTING broadcast if wired).

Safety ordering: the recipient's install is ACKED before the donor drops
its copy, so a dead recipient can never strand the range — the donor still
owns it and the migration re-runs idempotently (a fresh migration id
supersedes stale staged chunks).  A donor crash mid-stream falls back to
the PR-4 same-id restart path; re-running the migration afterwards yields
the identical final state.
"""

from __future__ import annotations

import itertools
from typing import Optional

import numpy as np

from parameter_server_tpu.core import flightrec
from parameter_server_tpu.core.messages import Message, Task, TaskKind, server_id
from parameter_server_tpu.core.postoffice import Customer, Postoffice
from parameter_server_tpu.kv.routing import RoutingTable


class MigrationError(RuntimeError):
    """A migration attempt failed; ownership is unchanged (safe to retry)."""


class ShardMigrator(Customer):
    """Drives migrations against the servers' ``migrate_*`` control ops.

    One instance per driver/trainer process; it is a plain Customer on its
    own Postoffice (e.g. ``Postoffice("M0", van)``) speaking to the servers'
    ``kv`` customer.
    """

    def __init__(
        self,
        post: Postoffice,
        *,
        name: str = "kv",
        chunk_rows: int = 4096,
        timeout: float = 60.0,
    ) -> None:
        super().__init__(name, post)
        self.chunk_rows = chunk_rows
        self.timeout = timeout
        #: dashboard counters
        self.migrations = 0
        self.aborts = 0
        self.rows_moved = 0
        self.freeze_s_last = 0.0
        self._mid_seq = itertools.count()

    def counters(self) -> dict:
        return {
            "migrations": self.migrations,
            "migration_aborts": self.aborts,
            "rows_moved": self.rows_moved,
            # the dirty-delta-bounded commit freeze; the durability plane's
            # snapshot commit (kv/server.py snap_commit) reuses exactly this
            # dirty-tracking/bounded-freeze pattern, reported as
            # ckpt_freeze_s in the server's own counters
            "freeze_s_last": round(self.freeze_s_last, 6),
        }

    # -- low-level control RPC ------------------------------------------------
    def _rpc(self, recver: str, payload: dict) -> Message:
        ts = self.submit(
            [
                Message(
                    task=Task(TaskKind.CONTROL, self.name, payload=payload),
                    recver=recver,
                )
            ],
            keep_responses=True,
        )
        if not self.wait(ts, timeout=self.timeout):
            self.cancel(ts, f"{payload.get('op')!r} deadline", remote=True)
            self.take_responses(ts)
            raise MigrationError(f"{payload.get('op')!r} to {recver} timed out")
        errs = self.errors(ts)
        responses = self.take_responses(ts)
        if errs:
            raise MigrationError(
                f"{payload.get('op')!r} to {recver} failed: " + "; ".join(errs)
            )
        return responses[0]

    # -- the migration --------------------------------------------------------
    def migrate(
        self,
        routing: RoutingTable,
        table: str,
        lo: int,
        hi: int,
        to: int,
        *,
        sched=None,
    ) -> RoutingTable:
        """Move global rows ``[lo, hi)`` of ``table`` to server ``to``.

        The whole range must currently belong to ONE donor (split a
        multi-owner range into per-donor calls).  Returns the new routing
        table (epoch + 1); pass ``sched`` (the scheduler-side NodeManager)
        to also broadcast it cluster-wide via the ROUTING verb.  On failure
        both sides are aborted and :class:`MigrationError` raised —
        ownership is unchanged and the call is safe to re-run.
        """
        tr = routing.tables[table]
        if not (0 <= lo < hi <= tr.rows):
            raise ValueError(f"bad range [{lo}, {hi}) for rows={tr.rows}")
        donors = {tr.owner_of(r) for r in (lo, hi - 1)}
        donors.update(
            o
            for i, o in enumerate(tr.owners)
            if tr.offsets[i] < hi and tr.offsets[i + 1] > lo
        )
        if len(donors) != 1:
            raise ValueError(
                f"[{lo}, {hi}) of {table!r} spans donors {sorted(donors)}; "
                "migrate per-donor sub-ranges"
            )
        donor = donors.pop()
        if donor == to:
            return routing
        new_routing = routing.move(table, lo, hi, to)
        mid = (
            f"{self.post.node_id}:{table}:{lo}:{hi}:{to}:"
            f"{routing.epoch}:{next(self._mid_seq)}"
        )
        d_id, r_id = server_id(donor), server_id(to)
        try:
            self._rpc(
                d_id,
                {"op": "migrate_begin", "mid": mid, "table": table,
                 "lo": lo, "hi": hi},
            )
            for a in range(lo, hi, self.chunk_rows):
                b = min(a + self.chunk_rows, hi)
                self._rpc(
                    d_id,
                    {"op": "migrate_send", "mid": mid, "to": r_id,
                     "lo": a, "hi": b},
                )
            reply = self._rpc(
                d_id,
                {
                    "op": "migrate_commit",
                    "mid": mid,
                    "to": r_id,
                    "routing": new_routing.to_payload(),
                },
            )
            self.freeze_s_last = float(np.asarray(reply.values[0])[0])
        except MigrationError as e:
            self.aborts += 1
            flightrec.record(
                "migrate.abort", node=self.post.node_id, mid=mid,
                donor=d_id, recipient=r_id, error=str(e)[:120],
            )
            for node in (d_id, r_id):
                try:
                    self._rpc(node, {"op": "migrate_abort", "mid": mid})
                except MigrationError:
                    pass  # a dead side restarts without the stale mid anyway
            raise
        # lazily converge the rest of the fleet: non-participant servers
        # adopt eagerly here; workers adopt off their first fence (or the
        # scheduler broadcast below)
        for s in new_routing.servers():
            if s in (donor, to):
                continue
            try:
                self._rpc(
                    server_id(s),
                    {"op": "adopt_routing",
                     "routing": new_routing.to_payload()},
                )
            except MigrationError:
                pass  # fences self-heal; a dead server re-registers fresh
        if sched is not None:
            sched.set_routing(new_routing)
        self.migrations += 1
        self.rows_moved += hi - lo
        return new_routing

    def drain(
        self,
        routing: RoutingTable,
        server: int,
        *,
        sched=None,
        plan: Optional[dict] = None,
    ) -> RoutingTable:
        """Migrate EVERY range off ``server`` (the drain_down data plane).

        ``plan``: optional ``{table: target_server}``; defaults to the
        least-loaded-by-rows remaining owner per table.
        """
        for t, tr in routing.tables.items():
            for lo, hi in tr.owned_segments(server):
                if plan and t in plan:
                    target = plan[t]
                else:
                    others = [s for s in routing.servers() if s != server]
                    if not others:
                        raise MigrationError(
                            f"cannot drain {server}: no other owner"
                        )
                    target = min(
                        others, key=lambda s: routing.tables[t].server_rows(s)
                    )
                routing = self.migrate(routing, t, lo, hi, target, sched=sched)
        return routing
