"""Row-range partitioning of a table across servers.

The reference NodeAssigner splits the key space into contiguous ranges, one
per server, and ``Parameter::Slice`` routes each request's (keys, values) by
binary search (``src/system/assigner.h``, ``src/parameter/parameter.h`` [U]).
Here the partitioned space is the *localized row-id* space ``[0, rows)``
(plus the global trash row id ``rows``, owned by the last server as its own
local trash row).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Iterator

import numpy as np


@dataclasses.dataclass(frozen=True)
class RangePartition:
    rows: int
    num_servers: int

    @functools.cached_property
    def offsets(self) -> np.ndarray:
        """``num_servers + 1`` row offsets; server s owns [off[s], off[s+1]).

        Cached: ``slice_ids`` sits on the per-request routing hot path and
        was rebuilding the cumsum every call.  ``cached_property`` stores
        into the instance ``__dict__`` directly, so the dataclass stays
        frozen (no ``__setattr__`` involved).
        """
        base = self.rows // self.num_servers
        rem = self.rows % self.num_servers
        sizes = [base + (1 if s < rem else 0) for s in range(self.num_servers)]
        return np.cumsum([0] + sizes)

    def server_rows(self, s: int) -> int:
        off = self.offsets
        return int(off[s + 1] - off[s])

    def slice_ids(
        self, sorted_ids: np.ndarray
    ) -> Iterator[tuple[int, slice, np.ndarray]]:
        """Split sorted unique row ids into per-server segments.

        Yields ``(server, segment_slice, local_ids)`` for every server (empty
        segments included — BSP tasks expect a response from each server).
        Padded ids (== rows) fall to the last server's trash row.
        """
        off = self.offsets
        idx = np.searchsorted(sorted_ids, off[1:-1], side="left")
        bounds = np.concatenate([[0], idx, [sorted_ids.shape[0]]])
        for s in range(self.num_servers):
            seg = slice(int(bounds[s]), int(bounds[s + 1]))
            local = (sorted_ids[seg] - off[s]).astype(np.int32)
            yield s, seg, local
