"""Wire-enforced consistency plane (ISSUE 20).

Two halves, deliberately decoupled:

``FleetClock`` — the SERVER-side per-table vector clock of per-worker
committed steps.  Every gated request stamps the sender's committed step
(``CONSIST_STEP_KEY``); the clock folds it in and the server gates the
request against the fleet minimum: a sender more than ``bound`` steps
ahead of the slowest registered worker is deferred with a typed
``__wait__`` reply (fence-shaped, so old workers retry it blindly — see
``kv/routing.py``).  The invariant the gate enforces is the SSP contract
from the paper: no worker's step ``s`` may exceed ``fleet_min + bound``
— which bounds how stale the weights any worker computes on can be,
because a pull at step ``s`` observes at least every push committed by
workers at step ``>= s - bound``.

Liveness analysis (why this cannot deadlock): the slowest registered
worker always has ``s == fleet_min`` and therefore always passes the
gate, so the minimum can always advance; a single registered worker is
always its own minimum and never gates; and entries that stop
participating are pruned two ways — eagerly on incarnation advance (the
van detected a same-id restart: the OLD incarnation's entry is dead and
must not wedge the minimum) and lazily on idle timeout (a vanished
worker that never came back).  Deferred senders keep retrying, and every
retry re-observes their step, so a deferred sender is never mistaken for
an idle one.

``BoundTuner`` — the DRIVER-side closed loop over the SSP bound.  Pure
decision logic (caller supplies the clock time and the SLO verdict):
widen the bound when the wire is the bottleneck (gate-wait SLO breach —
workers are spending their time parked on ``__wait__`` replies, so
staleness is cheaper than stalls), tighten it when loss variance spikes
(the statistical cost of staleness is showing up in the optimization).
The caller applies the verdict fleet-wide via the ``consist_set``
control op and records a ``consist.retune`` flight-recorder event.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..config import ConsistencyConfig, ConsistencyMode

#: telemetry gauge encoding of the active mode (0 = ungated table).
MODE_CODES = {
    ConsistencyMode.BSP: 1,
    ConsistencyMode.SSP: 2,
    ConsistencyMode.ASP: 3,
}
MODE_NAMES = {0: "-", 1: "bsp", 2: "ssp", 3: "asp"}


class FleetClock:
    """Per-table vector clock of per-worker committed steps.

    Single-writer friendly: all mutation happens on the server's recv
    thread, but reads (counters/telemetry) come from other threads, so a
    lock guards the tiny dict ops — never any device or wire work.
    """

    def __init__(self, *, idle_timeout_s: float = 30.0) -> None:
        self._lock = threading.Lock()
        #: worker id -> [incarnation, committed step, last-seen monotonic]
        self._clock: Dict[str, List[float]] = {}
        self.idle_timeout_s = float(idle_timeout_s)
        self.pruned = 0  # cumulative entries dropped (telemetry)

    # -- membership -----------------------------------------------------
    def hello(self, worker: str, incarnation: int, step: int = 0) -> None:
        """Register (or re-register) a worker at ``step``.

        A newer incarnation replaces the old entry outright — the old
        incarnation is dead by definition and its step must not wedge
        the fleet minimum.  An equal/older incarnation only raises the
        step (hellos may race data traffic that already advanced it).
        """
        with self._lock:
            ent = self._clock.get(worker)
            now = time.monotonic()
            if ent is None or incarnation > ent[0]:
                self._clock[worker] = [incarnation, int(step), now]
            else:
                ent[1] = max(ent[1], int(step))
                ent[2] = now

    def on_incarnation_advance(self, worker: str, incarnation: int) -> None:
        """Van-observed same-id restart: drop the DEAD incarnation's entry.

        The new incarnation re-registers via ``consist_hello`` (or its
        first stamped request) at its restored step; until then it simply
        does not participate in the minimum — pruning, not resetting,
        is what keeps a crashed worker from deadlocking the fleet.
        """
        with self._lock:
            ent = self._clock.get(worker)
            if ent is not None and incarnation > ent[0]:
                del self._clock[worker]
                self.pruned += 1

    def forget(self, worker: str) -> None:
        """Planned removal (scale-down drain): drop the entry."""
        with self._lock:
            if self._clock.pop(worker, None) is not None:
                self.pruned += 1

    # -- clock advance --------------------------------------------------
    def observe(self, worker: str, step: int) -> None:
        """Fold a stamped request's step in (request seen, not applied)."""
        with self._lock:
            ent = self._clock.get(worker)
            now = time.monotonic()
            if ent is None:
                # unannounced sender (old-style bring-up): register at its
                # stamped step with incarnation 0 so any later real
                # incarnation advance still prunes it
                self._clock[worker] = [0, int(step), now]
            else:
                ent[1] = max(ent[1], int(step))
                ent[2] = now

    def commit(self, worker: str, step: int) -> None:
        """A push stamped ``step`` was APPLIED: the worker committed it,
        so its clock advances past it (``max(clock, step + 1)``)."""
        with self._lock:
            ent = self._clock.get(worker)
            now = time.monotonic()
            if ent is None:
                self._clock[worker] = [0, int(step) + 1, now]
            else:
                ent[1] = max(ent[1], int(step) + 1)
                ent[2] = now

    # -- gate -----------------------------------------------------------
    def fleet_min(self) -> int:
        with self._lock:
            if not self._clock:
                return 0
            return min(int(e[1]) for e in self._clock.values())

    def gate(
        self, worker: str, step: int, bound: Optional[int]
    ) -> Tuple[bool, int]:
        """Admission decision for a request stamped ``step``.

        Returns ``(allowed, fleet_min)``.  ``bound is None`` (ASP) always
        admits — the clock still tracked the observation.  Before
        deferring, idle entries (no traffic for ``idle_timeout_s``) are
        pruned so a vanished worker cannot wedge the fleet; deferred
        senders re-observe on every retry and thus never look idle.
        """
        self.observe(worker, step)
        if bound is None:
            return True, self.fleet_min()
        with self._lock:
            fm = min(int(e[1]) for e in self._clock.values())
            if int(step) - fm <= int(bound):
                return True, fm
            # would defer: make sure the minimum isn't held by a corpse
            now = time.monotonic()
            stale = [
                w
                for w, e in self._clock.items()
                if w != worker and now - e[2] > self.idle_timeout_s
            ]
            for w in stale:
                del self._clock[w]
                self.pruned += 1
            fm = min(int(e[1]) for e in self._clock.values())
            return int(step) - fm <= int(bound), fm

    # -- introspection --------------------------------------------------
    def snapshot(self) -> Dict[str, int]:
        """Worker -> committed step (the ``__wait__`` reply's fleet view)."""
        with self._lock:
            return {w: int(e[1]) for w, e in self._clock.items()}

    def size(self) -> int:
        with self._lock:
            return len(self._clock)


class BoundTuner:
    """Closed-loop SSP bound controller (driver-side, pure decisions).

    Policy: WIDEN (double, capped) when the gate-wait SLO says workers
    are parked on the wire; TIGHTEN (halve, floored) when the loss-
    variance ratio of the recent window over the prior window spikes —
    staleness is hurting the statistics more than the stalls hurt the
    wall clock.  A cooldown keeps the two rules from fighting.
    """

    def __init__(
        self,
        cfg: ConsistencyConfig,
        *,
        min_bound: int = 1,
        max_bound: int = 64,
        window: int = 16,
        var_spike: float = 4.0,
        cooldown_s: float = 5.0,
    ) -> None:
        if cfg.mode != ConsistencyMode.SSP:
            raise ValueError("BoundTuner only tunes SSP bounds")
        self.bound = max(min_bound, int(cfg.max_delay))
        self.min_bound = int(min_bound)
        self.max_bound = int(max_bound)
        self.window = int(window)
        self.var_spike = float(var_spike)
        self.cooldown_s = float(cooldown_s)
        self._losses: List[float] = []
        self._last_retune: Optional[float] = None
        self.retunes = 0

    def observe_loss(self, loss: float) -> None:
        if math.isfinite(loss):
            self._losses.append(float(loss))
            if len(self._losses) > 2 * self.window:
                del self._losses[: -2 * self.window]

    def _variance_ratio(self) -> Optional[float]:
        if len(self._losses) < 2 * self.window:
            return None
        recent = self._losses[-self.window:]
        prior = self._losses[-2 * self.window: -self.window]

        def var(xs: List[float]) -> float:
            m = sum(xs) / len(xs)
            return sum((x - m) ** 2 for x in xs) / len(xs)

        vp = var(prior)
        return var(recent) / vp if vp > 0 else None

    def maybe_retune(
        self, now: float, *, wire_bottleneck: bool
    ) -> Optional[Tuple[int, str]]:
        """Returns ``(new_bound, why)`` when the bound should change.

        ``wire_bottleneck`` is the caller's SLO verdict (gate-wait p99
        breached).  Tightening wins over widening when both fire: a
        statistics regression is the costlier failure.
        """
        if (
            self._last_retune is not None
            and now - self._last_retune < self.cooldown_s
        ):
            return None
        ratio = self._variance_ratio()
        if ratio is not None and ratio > self.var_spike:
            nb = max(self.min_bound, self.bound // 2)
            if nb != self.bound:
                self.bound = nb
                self._last_retune = now
                self.retunes += 1
                return nb, f"loss variance spiked (x{ratio:.1f}): tighten"
        if wire_bottleneck:
            nb = min(self.max_bound, self.bound * 2)
            if nb != self.bound:
                self.bound = nb
                self._last_retune = now
                self.retunes += 1
                return nb, "gate-wait SLO breach: widen"
        return None
