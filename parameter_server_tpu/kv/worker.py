"""KVWorker: the classic Push/Pull facade with timestamps.

API parity with the reference worker (north-star requirement): ``push`` /
``pull`` return an integer timestamp; ``wait(ts)`` blocks; pulls deliver
values aligned with the request's key positions.  (Reference:
``src/parameter/parameter.h`` :: ``Parameter::Push/Pull/Wait`` [U].)

Pipeline per call (SURVEY.md §3.2 hot path, TPU mapping):

1. host: ``localize_to_slots`` — dedup keys, map to unique row slots
   (deterministic ``HashLocalizer`` for multi-worker consistency).
2. device: ``segment_combine`` duplicate positions (push only) — the
   worker-side pre-reduction.  With a :class:`~parameter_server_tpu.kv.
   routing.WorkerGroup` (ISSUE 15) this is also where the GROUP
   pre-reduction hangs: members hand their combined planes to the elected
   leader, which reduces them (``core/coalesce.py::GroupReducer`` — an XLA
   ``psum`` over a shared mesh where one exists, a deterministic
   sorted-union merge over the loopback topology) so only ONE reduced
   tensor crosses the wire per group per step.
3. host: ``RoutingTable.slice_ids`` — split the sorted slot segment per
   OWNING server (the reference's ``Parameter::Slice``, but against the
   epoch-versioned routing table of PR 6, so ranges can move at runtime).
4. Van: one request per server; responses complete the timestamp.

Routing fences (PR 6): every wire leg is stamped with the worker's routing
epoch (``__repoch__``).  A server holding a different table generation
answers with a typed ``__fenced__`` error carrying its own table; the
``*_sync`` paths adopt the highest-epoch table seen and retry exactly the
rejected positions — **rejected, not lost**.  Fire-and-forget ``push()``
cannot observe replies, so during live migration use :meth:`push_sync`
(which is what ``learner/elastic.py`` trains through).
"""

from __future__ import annotations

import contextlib
import functools
import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from parameter_server_tpu.config import GroupConfig, TableConfig, TraceConfig
from parameter_server_tpu.core import flightrec
from parameter_server_tpu.core.coalesce import GroupReducer
from parameter_server_tpu.core.tracectx import TRACE_KEY, sampled
from parameter_server_tpu.core.messages import Message, Task, TaskKind, server_id
from parameter_server_tpu.core.postoffice import Customer, Postoffice
from parameter_server_tpu.kv.cache import HotRowCache
from parameter_server_tpu.kv.partition import RangePartition
from parameter_server_tpu.kv.routing import (
    BUSY_KEY,
    CONSIST_STEP_KEY,
    FENCED_KEY,
    GROUP_KEY,
    READ_ONLY_KEY,
    ROUTING_EPOCH_KEY,
    ROUTING_KEY,
    VERSION_KEY,
    WAIT_KEY,
    RoutingTable,
    WorkerGroup,
)
from parameter_server_tpu.ops import scatter
from parameter_server_tpu.utils.keys import HashLocalizer, localize_to_slots
from parameter_server_tpu.utils.trace import NULL_TRACER, LatencyHistogram, Tracer


@functools.partial(jax.jit, static_argnames=("num_rows",))
def _segment_combine(inverse, values, num_rows: int):
    return scatter.segment_combine(values, inverse, num_rows)


class KVWorker(Customer):
    def __init__(
        self,
        post: Postoffice,
        table_cfgs: Dict[str, TableConfig],
        num_servers: int,
        *,
        name: str = "kv",
        localizers: Optional[Dict[str, HashLocalizer]] = None,
        min_bucket: int = 256,
        tracer: Tracer = NULL_TRACER,
        retry_on_timeout: bool = True,
        routing: Optional[RoutingTable] = None,
        max_fence_retries: int = 8,
        fence_backoff: float = 0.02,
        cache: Optional[HotRowCache] = None,
        group: Optional[WorkerGroup] = None,
        group_cfg: Optional[GroupConfig] = None,
        trace: Optional[TraceConfig] = None,
    ) -> None:
        """``retry_on_timeout``: when a pull's deadline expires (dead or
        mid-promotion server), cancel the stuck task and re-issue it ONCE
        against the same server identity — by then
        :class:`~parameter_server_tpu.kv.replica.ReplicaSet` has typically
        rebound ``S{i}`` to the promoted standby, so the retry lands on live
        state and training continues without surfacing the death.

        ``routing``: initial routing table (defaults to the uniform epoch-0
        split).  The worker converges to newer tables lazily off fence
        rejects and eagerly off scheduler ROUTING broadcasts (wire either
        into :meth:`adopt_routing`).

        ``cache``: a :class:`~parameter_server_tpu.kv.cache.HotRowCache`
        turns this worker into a serving node (ISSUE 13): :meth:`pull_serve`
        answers hot keys locally, every stamped reply refreshes the cache's
        invalidation watermark, and routing adoption drops all entries.

        ``group``: a :class:`~parameter_server_tpu.kv.routing.WorkerGroup`
        this worker belongs to (ISSUE 15).  Pushes then pre-reduce across
        the group and only the elected leader's reduced tensor crosses the
        wire — see :meth:`push` / :meth:`push_sync`.  ``group_cfg`` tunes
        fallback/reduce behaviour (defaults to ``GroupConfig`` matched to
        the group's size and election mode)."""
        super().__init__(name, post)
        #: host-side span recorder (Push/Pull latency histograms, SURVEY §5)
        self.tracer = tracer
        self.table_cfgs = table_cfgs
        self.num_servers = num_servers
        self.min_bucket = min_bucket
        self.retry_on_timeout = retry_on_timeout
        self.max_fence_retries = max_fence_retries
        self.fence_backoff = fence_backoff
        self.routing = routing or RoutingTable.uniform(table_cfgs, num_servers)
        self._routing_lock = threading.Lock()
        #: legacy uniform split, kept for introspection/compat — routing
        #: decisions now go through ``self.routing``
        self.partitions = {
            t: RangePartition(cfg.rows, num_servers) for t, cfg in table_cfgs.items()
        }
        self.localizers = localizers or {
            t: HashLocalizer(cfg.rows) for t, cfg in table_cfgs.items()
        }
        #: per-timestamp reassembly info for pulls
        self._pull_plans: Dict[int, dict] = {}
        #: deadline-retry counters (surfaced next to transport counters)
        self.pull_retries = 0
        self.push_retries = 0
        #: fence-driven routing refresh retries (the "rejected, not lost"
        #: loop re-submitting fenced positions under the adopted table)
        self.refresh_retries = 0
        #: cross-node trace ids (see :meth:`_trace_ctx`)
        self._trace_seq = itertools.count()
        # -- sampled request tracing (ISSUE 18) ------------------------------
        #: sampling policy; requests whose hashed id misses the 1-in-N
        #: sample carry NO trace context (zero wire bytes)
        self.trace = trace or TraceConfig()
        self._trace_lock = threading.Lock()
        #: tid -> [t0_mono, legs outstanding]; the span tree closes (and the
        #: e2e latency records) when the last leg's ack returns.  Bounded:
        #: oldest entries are evicted so a lost ack can never leak memory.
        self._trace_pending: Dict[str, list] = {}
        #: end-to-end request latency across sampled requests (submit ->
        #: last ack), exported as ``trace.e2e`` via :meth:`latency_digests`
        self._trace_e2e = LatencyHistogram()
        #: sampled requests stamped / span trees closed (Dashboard-mergeable)
        self.trace_samples = 0
        self.trace_closed = 0
        # -- staleness observability (ISSUE 10) ------------------------------
        #: highest server version this worker's own pushes have been acked
        #: at, per (table, server) — the baseline update lag is measured from
        self._last_push_version: Dict[Tuple[str, str], int] = {}
        #: update-lag distributions per (table, server), in VERSIONS (the
        #: histogram's seconds axis reused as a unitless count axis)
        self._staleness: Dict[Tuple[str, str], LatencyHistogram] = {}
        self._staleness_lock = threading.Lock()
        #: total lag samples recorded (Dashboard-mergeable gauge)
        self.staleness_samples = 0
        # -- device-plane backpressure (ISSUE 12) ----------------------------
        #: total ``__busy__``-hinted acks seen (Dashboard-mergeable)
        self.busy_hints = 0
        #: monotonic stamp of the last busy hint per server — the admission
        #: signal a throttling training loop polls via :meth:`server_busy`
        self._busy_last: Dict[str, float] = {}
        # -- read-heavy serving plane (ISSUE 13) -----------------------------
        #: hot-row cache; None = this worker does not serve reads
        self.cache = cache
        #: table -> (TableRouting identity, per-segment owner-code vector);
        #: memoizes the serve path's owner interning per adopted routing
        self._serve_codes: Dict[str, tuple] = {}
        # -- hierarchical push (ISSUE 15) ------------------------------------
        #: group membership; None (or size 1) = direct pushes
        self._group = group if (group is not None and group.size > 1) else None
        if self._group is not None:
            if self.post.node_id not in self._group.members:
                raise ValueError(
                    f"{self.post.node_id} is not a member of group "
                    f"{self._group.gid}"
                )
            if group_cfg is not None and group_cfg.size != self._group.size:
                raise ValueError(
                    f"group_cfg.size={group_cfg.size} != group size "
                    f"{self._group.size}"
                )
            self._group_cfg = group_cfg or GroupConfig(
                size=self._group.size, election=self._group.election
            )
            #: EF interaction with the quantized wire plane (ISSUE 14):
            #: rotation would move the residual owner every step, so group
            #: frames bypass the codec; fixed election pins one leader,
            #: whose (sender, table) store then owns the group's residual
            self._group_ef = (
                "leader" if self._group.election == "fixed" else "bypass"
            )
            #: every member carries a reducer — any of them can be elected
            self._group_reducer: Optional[GroupReducer] = GroupReducer(
                self._group.size,
                node=self.post.node_id,
                mode=self._group_cfg.reduce,
            )
        else:
            self._group_cfg = None
            self._group_ef = None
            self._group_reducer = None
        self._group_lock = threading.Lock()
        #: per-table local step counter keying leader election — members
        #: advance in lockstep (the data-parallel training contract); skew
        #: degrades to the timeout fallback, never to loss
        self._group_steps: Dict[str, int] = {}
        #: (table, step) -> Event set by the done notify (sync waiters)
        self._group_events: Dict[Tuple[str, int], threading.Event] = {}
        #: group counters (Dashboard-mergeable via :meth:`counters`)
        self.group_pushes = 0  # reduced wire pushes sent (as leader)
        self.group_reduced_fanin = 0  # member contributions those carried
        self.group_contribs = 0  # contributions sent (as member)
        self.group_fallbacks = 0  # degradations to direct push
        self.group_done_recv = 0  # done notifies applied
        self.group_handoffs = 0  # fence re-elections handed to a new leader
        # -- consistency plane (ISSUE 20) ------------------------------------
        #: per-table committed step — how many :meth:`push_sync` calls for
        #: the table fully completed.  This is the ``__cstep__`` value
        #: stamped onto gated PUSH/PULL traffic (tables whose
        #: ``TableConfig.consistency`` is set); servers fold it into their
        #: fleet vector clock and gate against the configured bound.
        self._consist_steps: Dict[str, int] = {}
        self._consist_lock = threading.Lock()
        #: ``__wait__`` defers received / pulls shed to the stale cache /
        #: requests forced through ungated past the gate deadline
        #: (Dashboard-mergeable via :meth:`counters`)
        self.consist_waits = 0
        self.consist_sheds = 0
        self.consist_forced = 0
        #: time parked on consistency gates (first defer -> admitted),
        #: exported as ``consist.gate_wait`` via :meth:`latency_digests` —
        #: the gate-wait-p99 SLO's series (utils/slo.py
        #: consistency_plane_specs)
        self._gate_hist = LatencyHistogram()

    def _serve_owner_codes(self, table: str, tr, cache) -> np.ndarray:
        """Owner :meth:`HotRowCache.server_code` per segment of ``tr``.

        Identity-keyed memo: :meth:`adopt_routing` replaces routing objects
        wholesale, so ``ent[0] is tr`` is exact — no epoch bookkeeping.
        """
        ent = self._serve_codes.get(table)
        if ent is not None and ent[0] is tr:
            return ent[1]
        codes = np.asarray(
            [cache.server_code(server_id(int(o))) for o in tr.owners],
            dtype=np.int32,
        )
        self._serve_codes[table] = (tr, codes)
        return codes

    # -- routing --------------------------------------------------------------
    def adopt_routing(self, routing) -> bool:
        """Adopt a routing table iff it is NEWER than what this worker holds.

        Accepts a :class:`RoutingTable` or its wire payload (the form riding
        fence replies and scheduler broadcasts).  Highest epoch wins without
        coordination: a fence carrying an older table — possible for a
        bounded moment mid-broadcast — is simply ignored, and the backoff in
        the retry loops outlasts the broadcast window.
        """
        if routing is None:
            return False
        if isinstance(routing, dict):
            routing = RoutingTable.from_payload(routing)
        with self._routing_lock:
            if routing.epoch <= self.routing.epoch:
                adopted = False
            else:
                self.routing = routing
                adopted = True
        if adopted and self.cache is not None:
            # serving plane: entries are keyed by owner, so most would miss
            # anyway (owner changed) — but a range that moved AND moved back
            # across epochs could alias, so adoption drops everything.
            self.cache.invalidate_all(reason="routing-epoch")
        if adopted:
            # quantized wire plane: error-feedback residuals describe error
            # owed to the OLD owners of each key range — after a migration
            # they would replay stale error into the new owner's rows.
            from parameter_server_tpu.core.filters import find_quantizers

            van = getattr(self.post, "van", None)
            if van is not None:
                for codec in find_quantizers(van):
                    codec.reset_residuals(
                        sender=self.post.node_id, reason="adopt_routing"
                    )
        return adopted

    def counters(self) -> dict:
        """Retry counters, Dashboard-mergeable (utils.metrics)."""
        out = {
            "pull_retries": self.pull_retries,
            "push_retries": self.push_retries,
            "refresh_retries": self.refresh_retries,
            "staleness_samples": self.staleness_samples,
            "busy_hints": self.busy_hints,
            "trace_samples": self.trace_samples,
            "trace_closed": self.trace_closed,
        }
        if self._group is not None:
            out.update(
                {
                    "group_pushes": self.group_pushes,
                    "group_reduced_fanin": self.group_reduced_fanin,
                    "group_contribs": self.group_contribs,
                    "group_fallbacks": self.group_fallbacks,
                    "group_done_recv": self.group_done_recv,
                    "group_handoffs": self.group_handoffs,
                }
            )
        if self.cache is not None:
            out.update(self.cache.counters())
        with self._consist_lock:
            if self.consist_waits or self._consist_steps:
                # consistency plane (ISSUE 20): defer/shed/force totals plus
                # the committed-step gauge (sum over gated tables)
                out["consist_waits"] = self.consist_waits
                out["consist_sheds"] = self.consist_sheds
                out["consist_forced"] = self.consist_forced
                # combined degradation counter: the shed-rate SLO watches
                # one cumulative series for "the gate deadline fired"
                out["consist_degraded"] = (
                    self.consist_sheds + self.consist_forced
                )
                out["consist_step"] = sum(self._consist_steps.values())
        return out

    def server_busy(self, server: str, within_s: float = 1.0) -> bool:
        """True if ``server`` stamped ``__busy__`` onto an ack within the
        last ``within_s`` seconds — the soft-backpressure poll a throttling
        training loop consumes (the hint is advisory: pushes were applied)."""
        with self._staleness_lock:
            t = self._busy_last.get(server)
        return t is not None and (time.monotonic() - t) <= within_s

    # -- staleness observability (ISSUE 10) -----------------------------------
    def _on_response(self, msg) -> None:
        """Tap every data reply for the server's ``__sver__`` version stamp.

        Runs on the recv thread for push AND pull replies — including
        fire-and-forget pushes whose bodies ``submit`` drops — so the
        version bookkeeping is uniform across sync and async training.
        PUSH acks advance this worker's last-pushed version for that
        (table, server); PULL replies record ``server_version -
        last_pushed_version`` — how many fleet updates the pulled ranges
        have seen since this worker last contributed — into a per-range
        histogram.  Cheap (two dict ops) and fail-safe: the super() call
        that completes the task always runs.
        """
        try:
            payload = msg.task.payload
            tctx = payload.get(TRACE_KEY)
            if tctx is not None and isinstance(tctx, dict):
                # sampled request tracing (ISSUE 18): the server echoed the
                # context back on this ack/reply — this leg's return closes
                # part of the span tree; the LAST leg records end-to-end
                # latency and emits the closure event postmortem anchors on
                tid = tctx.get("tid")
                done = e2e = None
                if tid is not None:
                    with self._trace_lock:
                        ent = self._trace_pending.get(tid)
                        if ent is not None:
                            ent[1] -= 1
                            if ent[1] <= 0:
                                self._trace_pending.pop(tid, None)
                                e2e = time.monotonic() - ent[0]
                                self._trace_e2e.record(max(e2e, 0.0))
                                self.trace_closed += 1
                                done = True
                    if done:
                        flightrec.record(
                            "trace.ack",
                            tid=tid,
                            node=self.post.node_id,
                            sender=msg.sender,
                            fenced=bool(payload.get(FENCED_KEY)),
                            e2e_ms=round(e2e * 1e3, 3),
                        )
            if payload.get(BUSY_KEY):
                # device-plane soft backpressure (ISSUE 12): the server's
                # ApplyLedger backlog exceeded its bound when this ack was
                # stamped.  Count + timestamp; :meth:`server_busy` reads it.
                with self._staleness_lock:
                    self.busy_hints += 1
                    self._busy_last[msg.sender] = time.monotonic()
            sver = payload.get(VERSION_KEY)
            table = payload.get("table")
            if sver is not None and table is not None:
                if self.cache is not None:
                    # serving plane: EVERY stamped reply — push ack, pull
                    # reply, and (ISSUE 13) fence reject — raises the
                    # cache-invalidation watermark for (table, server)
                    self.cache.observe(table, msg.sender, int(sver))
                key = (table, msg.sender)
                if payload.get(FENCED_KEY):
                    # fence: the request was REJECTED, so the stamp must not
                    # advance last-push bookkeeping (the push never applied)
                    # nor count as a served-pull staleness sample — it only
                    # feeds the watermark above
                    pass
                else:
                    with self._staleness_lock:
                        if msg.task.kind == TaskKind.PUSH:
                            prev = self._last_push_version.get(key, 0)
                            if sver > prev:
                                self._last_push_version[key] = int(sver)
                        elif msg.task.kind == TaskKind.PULL:
                            last = self._last_push_version.get(key)
                            if last is not None:
                                hist = self._staleness.get(key)
                                if hist is None:
                                    hist = self._staleness[key] = (
                                        LatencyHistogram()
                                    )
                                hist.record(float(max(int(sver) - last, 0)))
                                self.staleness_samples += 1
        except Exception:  # noqa: BLE001 — observability must never lose
            pass  # the reply itself
        super()._on_response(msg)

    def staleness_digests(self) -> Dict[str, dict]:
        """Cumulative update-lag digests, named for the telemetry plane.

        ``staleness.<table>`` merges every server's distribution (the
        SLO-able fleet series, e.g. ``SloSpec("staleness.w", 8,
        source="p99", p99_scale=1)``); ``staleness.<table>@<server>`` keeps
        the per-key-range split for diagnosis.  Digests are cumulative and
        monotone — ``TelemetryPublisher`` delta-encodes them.
        """
        with self._staleness_lock:
            per_range = {
                f"staleness.{t}@{s}": h.to_dict()
                for (t, s), h in self._staleness.items()
            }
            merged: Dict[str, LatencyHistogram] = {}
            for (t, _s), h in self._staleness.items():
                agg = merged.get(t)
                if agg is None:
                    agg = merged[t] = LatencyHistogram()
                agg.merge(h)
        out = {f"staleness.{t}": h.to_dict() for t, h in merged.items()}
        out.update(per_range)
        return out

    def latency_digests(self) -> Dict[str, dict]:
        """Tracing-plane digests for the telemetry publisher (ISSUE 18).

        ``trace.e2e`` is submit → last-ack latency across sampled requests
        — the denominator ``tools/critpath.py`` attributes into plane
        segments.  Cumulative and monotone, same contract as the server's
        :meth:`~parameter_server_tpu.kv.server.KVServer.latency_digests`.
        """
        out = {}
        with self._trace_lock:
            if self._trace_e2e.count:
                out["trace.e2e"] = self._trace_e2e.to_dict()
        with self._consist_lock:
            if self._gate_hist.count:
                # consistency plane (ISSUE 20): seconds parked on gates
                out["consist.gate_wait"] = self._gate_hist.to_dict()
        return out

    # -- consistency plane (ISSUE 20) -----------------------------------------
    def consist_step(self, table: str) -> int:
        """This worker's committed step for ``table`` (completed pushes)."""
        with self._consist_lock:
            return self._consist_steps.get(table, 0)

    def _consist_commit(self, table: str) -> int:
        with self._consist_lock:
            s = self._consist_steps.get(table, 0) + 1
            self._consist_steps[table] = s
            return s

    def _gated(self, table: str) -> bool:
        return self.table_cfgs[table].consistency is not None

    @staticmethod
    def _scan_waits(responses, order) -> Tuple[list, list, list, float]:
        """Split out typed ``__wait__`` consistency defers (ISSUE 20).

        Wait replies are fence-SHAPED (they carry ``__fenced__`` too, for
        old workers) but are not fences: routing is fine, the sender just
        ran too far ahead of the fleet minimum.  Returns ``(rest, waits,
        waited position arrays, max retry_after hint)`` so the retry loops
        can park on the gate budget instead of burning fence retries.
        """
        rest, waits, pos, retry = [], [], [], 0.0
        for resp in responses:
            p = resp.task.payload
            if p.get(WAIT_KEY):
                waits.append(resp)
                pos.append(order[resp.sender])
                retry = max(retry, float(p.get("retry_after") or 0.0))
            else:
                rest.append(resp)
        return rest, waits, pos, retry

    @staticmethod
    def _scan_fences(responses, order) -> Tuple[list, set, List[np.ndarray]]:
        """Split a completed task's responses into (data, fenced senders,
        fenced position arrays)."""
        data, senders, fenced = [], set(), []
        for resp in responses:
            if resp.task.payload.get(FENCED_KEY):
                senders.add(resp.sender)
                fenced.append(order[resp.sender])
            else:
                data.append(resp)
        return data, senders, fenced

    @staticmethod
    def _real_errors(errs, fenced_senders) -> list:
        """Errors minus the typed fence rejects (recorded as 'S0: <err>')."""
        return [
            e
            for e in errs
            if not any(e.startswith(f"{s}: ") for s in fenced_senders)
        ]

    def _adopt_from(self, responses) -> None:
        for resp in responses:
            if resp.task.payload.get(FENCED_KEY):
                self.adopt_routing(resp.task.payload.get(ROUTING_KEY))

    def _trace_ctx(self) -> Optional[dict]:
        """Fresh trace context for one logical request — or ``None``.

        ``None`` means the request missed the deterministic hash sample
        (``core/tracectx.py``): no context is stamped, no ``__trace__``
        payload key exists, zero trace bytes ride the wire, and the int-only
        fast meta codec stays eligible.  A sampled request gets a dict
        stamped into ``Task.payload["__trace__"]`` of every wire leg and
        recorded as a ``trace`` attr on this worker's span; the receiving
        van stamps ``rx``, the server adds dispatch/reply stamps and echoes
        the context back on acks, so ``tools/merge_traces.py`` +
        ``tools/critpath.py`` can stitch one cross-node timeline.  The id is
        unique per (node, customer, request) — no coordination needed
        across nodes, and the sampling decision is a pure function of
        ``(tid, seed)`` so replays sample the same requests.
        """
        tid = f"{self.post.node_id}/{self.name}/{next(self._trace_seq)}"
        if not self.trace.enabled or not sampled(
            tid, self.trace.seed, self.trace.sample_every
        ):
            return None
        return {
            "tid": tid,
            "origin": self.post.node_id,
            "customer": self.name,
            "t": time.monotonic(),
        }

    def _trace_submitted(self, tctx: dict, op: str, legs: int) -> None:
        """Bookkeep one sampled submit: ``legs`` acks close the span tree.

        A ``None`` tctx (unsampled request) is a no-op — the whole body
        sits behind the sampling gate, a contract ``tools/check_wrappers.py``
        enforces statically (``TRACE_GATED_FUNCS``).
        The pending map is bounded — the oldest entry is evicted when full,
        so a reply that never returns (dead server past the resend budget)
        degrades to a missing e2e sample, never to leaked memory.  The
        orphan still shows in flightrec: ``trace.submit`` with no matching
        ``trace.ack`` is exactly what ``tools/postmortem.py`` anchors on.
        """
        if tctx is not None:
            with self._trace_lock:
                self.trace_samples += 1
                while len(self._trace_pending) >= 4096:
                    self._trace_pending.pop(next(iter(self._trace_pending)))
                self._trace_pending[tctx["tid"]] = [tctx["t"], int(legs)]
            flightrec.record(
                "trace.submit",
                tid=tctx["tid"],
                op=op,
                node=self.post.node_id,
                legs=int(legs),
                t0_s=tctx["t"],
            )

    # -- hierarchical push (ISSUE 15) ----------------------------------------
    def _group_push(
        self,
        table: str,
        slots: np.ndarray,
        combined: np.ndarray,
        *,
        sync: bool,
        timeout: Optional[float],
    ) -> int:
        """Route one prepared push through the group: elect, then either
        lead the rendezvous or contribute to the elected leader.

        Returns the submit timestamp of whatever leg THIS member sent this
        step (the reduced wire push when leading and the set completed
        locally, the contribution otherwise); ``-1`` when the leader is
        still waiting on members (the completing deposit issues the wire
        push from its own thread).
        """
        step = self._group_step_next(table)
        leader = self._group.leader(table, step)
        flightrec.record(
            "group.elect",
            node=self.post.node_id,
            table=table,
            step=step,
            leader=leader,
            size=self._group.size,
        )
        # flush rendezvous sets a dead/skewed member stranded (partial
        # reduction — the contributions that DID arrive are never lost)
        self._group_gc_stale()
        if leader == self.post.node_id:
            return self._group_lead(
                table, step, slots, combined, sync=sync, timeout=timeout
            )
        return self._group_contribute(
            table, step, leader, slots, combined, sync=sync, timeout=timeout
        )

    def _group_step_next(self, table: str) -> int:
        with self._group_lock:
            step = self._group_steps.get(table, 0)
            self._group_steps[table] = step + 1
        return step

    def _group_event(self, table: str, step: int) -> threading.Event:
        with self._group_lock:
            ev = self._group_events.get((table, step))
            if ev is None:
                ev = self._group_events[(table, step)] = threading.Event()
        return ev

    def _group_pop_event(self, table: str, step: int) -> None:
        with self._group_lock:
            self._group_events.pop((table, step), None)

    def _group_lead(
        self, table, step, slots, combined, *, sync, timeout
    ) -> int:
        """Leader leg: deposit own contribution; push when the set
        completes; on member timeout flush a PARTIAL reduction (no loss)."""
        cfg = self._group_cfg
        ev = self._group_event(table, step) if sync else None
        done = self._group_reducer.deposit(
            table, step, self.post.node_id, slots, combined
        )
        ts = -1
        if done is not None:
            ts = self._group_wire_push(table, step, *done)
        if not sync:
            return ts
        try:
            # the degradation decision runs on the group's own clock
            # (fallback_timeout), not the caller's push deadline — chaos
            # runs stay deterministic whatever timeout the test passes
            if not ev.wait(cfg.fallback_timeout):
                part = self._group_reducer.take(table, step)
                if part is not None:
                    if cfg.fallback == "none":
                        raise TimeoutError(
                            f"group push of {table!r} step {step}: members "
                            f"missing and fallback='none'"
                        )
                    with self._group_lock:
                        self.group_fallbacks += 1
                    flightrec.record(
                        "group.fallback",
                        node=self.post.node_id,
                        table=table,
                        step=step,
                        reason="member_timeout",
                        fanin=part[2],
                    )
                    ts = self._group_wire_push(table, step, *part)
                # either way the wire push is now in flight (here or from
                # the completing deposit's thread); wait for its acks
                if not ev.wait(timeout if timeout is not None else cfg.fallback_timeout):
                    raise TimeoutError(
                        f"group push of {table!r} step {step} timed out"
                    )
            return ts
        finally:
            self._group_pop_event(table, step)

    def _group_contribute(
        self, table, step, leader, slots, combined, *, sync, timeout
    ) -> int:
        """Member leg: ship the combined plane to the leader as a CONTROL
        contribution (CoalescingVan passthrough — never bundled), degrade
        to a direct push if the leader is dead or partitioned."""
        cfg = self._group_cfg
        ev = self._group_event(table, step) if sync else None
        msg = Message(
            task=Task(
                TaskKind.CONTROL,
                self.name,
                payload={
                    GROUP_KEY: {
                        "op": "contrib",
                        "table": table,
                        "step": int(step),
                        "member": self.post.node_id,
                        "fanin": 1,
                    }
                },
            ),
            recver=leader,
            keys=np.asarray(slots).astype(np.int64, copy=False),
            values=[combined],
        )
        with self._group_lock:
            self.group_contribs += 1
        if not sync:
            cb = functools.partial(
                self._group_contrib_done, table, step, slots, combined
            )
            return self.submit([msg], callback=cb)
        ts = self.submit([msg], keep_responses=True)
        try:
            if not self.wait(ts, cfg.fallback_timeout):
                # partitioned leader (blackhole): fence the contribution so
                # a late delivery cannot double-apply, then push direct
                self.cancel(ts, "group leader deadline", remote=True)
                self.take_responses(ts)
                return self._group_fallback(
                    table, step, slots, combined,
                    reason="leader_timeout", sync=True, timeout=timeout,
                )
            errs = self.errors(ts)
            self.take_responses(ts)
            if errs:
                # dead leader: the send failed outright (undeliverable) or
                # its handler errored — the contribution was NOT absorbed
                return self._group_fallback(
                    table, step, slots, combined,
                    reason="dead_leader", sync=True, timeout=timeout,
                )
            # acked: the leader owns this gradient now.  Wait for the done
            # notify (which advances _last_push_version so staleness
            # accounting sees the group push as our own).  No fallback
            # after this point — re-pushing an absorbed gradient would
            # double-apply; a lost done notify only costs bookkeeping.
            ev.wait(timeout if timeout is not None else cfg.fallback_timeout)
            return ts
        finally:
            self._group_pop_event(table, step)

    def _group_contrib_done(self, table, step, slots, combined, responses):
        """Async-contribution callback: degrade on a dead leader."""
        ok = any(
            r.task.payload.get("__error__") is None for r in responses
        )
        if not ok:
            self._group_fallback(
                table, step, slots, combined,
                reason="dead_leader", sync=False, timeout=None,
            )

    def _group_fallback(
        self, table, step, slots, combined, *, reason, sync, timeout
    ) -> int:
        """Direct per-worker push of this member's own gradient — the
        same-step, no-loss degradation the group contract promises."""
        if self._group_cfg.fallback == "none":
            raise RuntimeError(
                f"group push of {table!r} step {step}: leader unreachable "
                f"({reason}) and fallback='none'"
            )
        with self._group_lock:
            self.group_fallbacks += 1
        flightrec.record(
            "group.fallback",
            node=self.post.node_id,
            table=table,
            step=step,
            reason=reason,
        )
        if sync:
            return self._push_sync_prepared(table, slots, combined, timeout)
        ts, _ = self._submit_push(table, slots, combined)
        return ts

    def _group_gc_stale(self) -> None:
        """Flush rendezvous sets whose stragglers exceeded the timeout."""
        red = self._group_reducer
        if red is None or not red.pending():
            return
        for table, step, (keys, vals, fanin) in red.take_stale(
            self._group_cfg.fallback_timeout
        ):
            with self._group_lock:
                self.group_fallbacks += 1
            flightrec.record(
                "group.fallback",
                node=self.post.node_id,
                table=table,
                step=step,
                reason="stale_set",
                fanin=fanin,
            )
            self._group_wire_push(table, step, keys, vals, fanin)

    def _group_wire_push(
        self, table, step, keys, vals, fanin, attempt: int = 0,
        positions: Optional[np.ndarray] = None,
    ) -> int:
        """Push the reduced tensor, stamped as ONE logical group apply.

        Non-blocking by contract: this runs on driver threads, the
        endpoint recv thread (a completing deposit), and the callback pool
        (fence retries) — blocking here on a same-endpoint reply would
        deadlock the LoopbackVan's single recv thread, so acks are handled
        by :meth:`_group_wire_done` via the submit callback.
        """
        stamp = {
            "id": self._group.gid,
            "n": int(fanin),
            "step": int(step),
            "ef": self._group_ef,
        }
        # hierarchical hop: the LEADER stamps a fresh context for the
        # reduced wire push — member contributions that fed it were local
        # to the group, so the cross-node chain starts here
        tctx = self._trace_ctx()
        routing = self.routing
        keys = np.asarray(keys)
        if positions is None:
            positions = np.arange(keys.shape[0], dtype=np.int64)
        sub = keys[positions]
        msgs, order = [], {}
        for s, rel, ids in routing.slice_ids(table, sub):
            abs_pos = positions[rel]
            order[server_id(s)] = abs_pos
            payload = {
                "table": table,
                ROUTING_EPOCH_KEY: routing.epoch,
                GROUP_KEY: dict(stamp),
            }
            if tctx is not None:
                payload[TRACE_KEY] = tctx
            msgs.append(
                Message(
                    task=Task(TaskKind.PUSH, self.name, payload=payload),
                    recver=server_id(s),
                    keys=ids.astype(np.int32),
                    values=[vals[abs_pos]],
                )
            )
        cb = functools.partial(
            self._group_wire_done, table, step, keys, vals, fanin, attempt,
            order,
        )
        # registered before the submit: the acks race the submit call
        self._trace_submitted(tctx, "group_push", len(msgs))
        with self.coalesce_window():
            ts = self.submit(msgs, callback=cb)
        with self._group_lock:
            self.group_pushes += 1
            self.group_reduced_fanin += int(fanin)
        return ts

    def _group_wire_done(
        self, table, step, keys, vals, fanin, attempt, order, responses
    ) -> None:
        """Ack callback of a group wire push: adopt/re-elect on fences,
        then broadcast the done notify carrying the acked versions.

        Fence re-election (the ``push_many``/``push_sync`` contract): a
        fenced reduced push re-elects with ``salt=attempt+1`` — if the new
        leader is another member, the reduced subset is HANDED OFF so the
        retry load rotates; the handoff degrades to a local retry if that
        member is unreachable.
        """
        try:
            self._adopt_from(responses)
            data, _senders, fenced = self._scan_fences(responses, order)
            vers = {}
            for r in data:
                p = r.task.payload
                if p.get("__error__") is None:
                    sver = p.get(VERSION_KEY)
                    if sver is not None:
                        vers[r.sender] = int(sver)
            if fenced and attempt < self.max_fence_retries:
                pos = np.sort(np.concatenate(fenced))
                with self._group_lock:
                    self.refresh_retries += 1
                new_leader = self._group.leader(
                    table, step, salt=attempt + 1
                )
                flightrec.record(
                    "group.elect",
                    node=self.post.node_id,
                    table=table,
                    step=step,
                    leader=new_leader,
                    size=self._group.size,
                    salt=attempt + 1,
                    cause="fence",
                )
                if new_leader != self.post.node_id:
                    self._group_handoff(
                        new_leader, table, step, keys[pos], vals[pos],
                        fanin, attempt + 1,
                    )
                else:
                    self._group_wire_push(
                        table, step, keys, vals, fanin, attempt + 1,
                        positions=pos,
                    )
            if fenced:
                if vers:  # acked legs advance versions; retry notifies later
                    self._group_notify_done(table, step, vers, final=False)
            else:
                self._group_notify_done(table, step, vers, final=True)
        except Exception:  # noqa: BLE001 — a callback-thread error must not
            # strand the group's sync waiters silently un-notified forever
            flightrec.record(
                "group.fallback",
                node=self.post.node_id,
                table=table,
                step=step,
                reason="wire_done_error",
            )

    def _group_handoff(
        self, new_leader, table, step, keys, vals, fanin, attempt
    ) -> None:
        with self._group_lock:
            self.group_handoffs += 1
        msg = Message(
            task=Task(
                TaskKind.CONTROL,
                self.name,
                payload={
                    GROUP_KEY: {
                        "op": "handoff",
                        "table": table,
                        "step": int(step),
                        "fanin": int(fanin),
                        "attempt": int(attempt),
                    }
                },
            ),
            recver=new_leader,
            keys=np.asarray(keys).astype(np.int64, copy=False),
            values=[vals],
        )
        cb = functools.partial(
            self._group_handoff_done, table, step, keys, vals, fanin, attempt
        )
        self.submit([msg], callback=cb)

    def _group_handoff_done(
        self, table, step, keys, vals, fanin, attempt, responses
    ) -> None:
        ok = any(
            r.task.payload.get("__error__") is None for r in responses
        )
        if not ok:  # new leader unreachable too: retry the push locally
            self._group_wire_push(table, step, keys, vals, fanin, attempt)

    def _group_notify_done(self, table, step, vers, *, final) -> None:
        """Tell every member the group push landed (fire-and-forget).

        Carries the per-server acked versions so each member advances its
        OWN ``_last_push_version`` — the group push is one logical apply
        owned by the whole group, and the staleness plane (ISSUE 10) must
        measure every member's update lag from it, not just the leader's.
        """
        self._group_apply_done(table, step, vers, final)
        for m in self._group.members:
            if m == self.post.node_id:
                continue
            self.post.send(
                Message(
                    task=Task(
                        TaskKind.CONTROL,
                        self.name,
                        # fresh payload per leg (Loopback may alias them)
                        payload={
                            GROUP_KEY: {
                                "op": "done",
                                "table": table,
                                "step": int(step),
                                "vers": dict(vers),
                                "final": bool(final),
                            }
                        },
                    ),
                    recver=m,
                )
            )

    def _group_apply_done(self, table, step, vers, final) -> None:
        with self._staleness_lock:
            for server, sver in vers.items():
                key = (table, server)
                if int(sver) > self._last_push_version.get(key, 0):
                    self._last_push_version[key] = int(sver)
        with self._group_lock:
            self.group_done_recv += 1
            ev = self._group_events.get((table, int(step))) if final else None
        if ev is not None:
            ev.set()

    def handle_request(self, msg: Message) -> Optional[Message]:
        """Worker-to-worker group ops (ISSUE 15): contribution deposit,
        fence-retry handoff, done notify.  Anything else keeps the base
        behaviour (NotImplementedError -> typed ``__error__`` reply)."""
        payload = msg.task.payload
        grp = payload.get(GROUP_KEY) if isinstance(payload, dict) else None
        if grp is None or self._group is None:
            return super().handle_request(msg)
        op = grp.get("op")
        if op == "contrib":
            table, step = grp["table"], int(grp["step"])
            done = self._group_reducer.deposit(
                table,
                step,
                grp.get("member", msg.sender),
                msg.keys,
                msg.values[0],
                fanin=int(grp.get("fanin", 1)),
            )
            if done is not None:
                self._group_wire_push(table, step, *done)
            self._group_gc_stale()
            return msg.reply()
        if op == "handoff":
            self._group_wire_push(
                grp["table"],
                int(grp["step"]),
                msg.keys,
                msg.values[0],
                int(grp.get("fanin", 1)),
                attempt=int(grp.get("attempt", 0)),
            )
            return msg.reply()
        if op == "done":
            self._group_apply_done(
                grp["table"],
                int(grp["step"]),
                {k: int(v) for k, v in (grp.get("vers") or {}).items()},
                bool(grp.get("final", True)),
            )
            return None  # fire-and-forget: the sender tracks no task
        return super().handle_request(msg)

    # -- push ---------------------------------------------------------------
    def _submit_push(
        self,
        table: str,
        slots: np.ndarray,
        combined,
        positions: Optional[np.ndarray] = None,
        *,
        keep: bool = False,
        tctx: Optional[dict] = None,
        ungated: bool = False,
    ) -> Tuple[int, Dict[str, np.ndarray]]:
        """Wire one push of ``combined[positions]`` rows at global ids
        ``slots[positions]``; returns ``(ts, {server: positions})``.

        ``positions`` (absolute indices into ``slots``, ascending) defaults
        to all of them; fence retries pass only the rejected subset.
        ``ungated=True`` skips the consistency stamp (ISSUE 20) — the
        gate-deadline force-through path: the push bypasses the fleet gate
        rather than being dropped.
        """
        tctx = tctx if tctx is not None else self._trace_ctx()
        routing = self.routing  # one consistent table per submit
        if positions is None:
            positions = np.arange(slots.shape[0], dtype=np.int64)
        sub = slots[positions]
        # consistency plane (ISSUE 20): gated tables stamp the sender's
        # committed step (a plain int — the fast meta codec stays eligible)
        cstep = (
            self.consist_step(table)
            if not ungated and self._gated(table)
            else None
        )
        msgs, order = [], {}
        for s, rel, ids in routing.slice_ids(table, sub):
            abs_pos = positions[rel]
            order[server_id(s)] = abs_pos
            payload = {
                "table": table,
                ROUTING_EPOCH_KEY: routing.epoch,
            }
            if cstep is not None:
                payload[CONSIST_STEP_KEY] = cstep
            if tctx is not None:
                payload[TRACE_KEY] = tctx
            msgs.append(
                Message(
                    task=Task(TaskKind.PUSH, self.name, payload=payload),
                    recver=server_id(s),
                    keys=ids.astype(np.int32),
                    values=[combined[abs_pos]],
                )
            )
        # register the span tree BEFORE the wire submit: replies race the
        # submit call (a fast peer can ack before submit() returns), and a
        # decrement that finds no pending entry would leak an open tree
        self._trace_submitted(tctx, "push", len(msgs))
        # window: under a CoalescingVan the burst flushes at submit
        # exit (no flush-timer latency); nested inside push_many's
        # window it coalesces across tables instead
        with self.coalesce_window():
            ts = self.submit(msgs, keep_responses=keep)
        return ts, order

    def _prepare_push(self, table: str, keys, values):
        """Host half of a push: localize + device duplicate pre-combine."""
        cfg = self.table_cfgs[table]
        vals = np.asarray(values, dtype=cfg.dtype).reshape(keys.size, cfg.dim)
        slots, inverse, _n = localize_to_slots(
            keys, self.localizers[table], min_bucket=self.min_bucket
        )
        combined = np.asarray(
            _segment_combine(jnp.asarray(inverse), jnp.asarray(vals), slots.shape[0])
        )
        return slots, combined

    def push(self, table: str, keys: np.ndarray, values: np.ndarray) -> int:
        """Push per-position gradient rows for ``keys``.  Returns timestamp.

        ``values`` has shape ``[len(keys), dim]`` (or ``[len(keys)]`` for
        dim=1 tables).  Fire-and-forget: cannot observe routing fences —
        under live migration use :meth:`push_sync`.

        With a :class:`~parameter_server_tpu.kv.routing.WorkerGroup` the
        push routes through the group pre-reduction instead (ISSUE 15):
        non-leaders ship their combined plane to the elected leader, whose
        reduced tensor is the only PUSH on the wire; a dead leader
        degrades to a direct push via the submit callback (no loss).
        """
        tctx = self._trace_ctx()
        with self.tracer.span(
            "kv.push", table=table, n=int(keys.size),
            **({"trace": tctx["tid"]} if tctx is not None else {}),
        ):
            slots, combined = self._prepare_push(table, keys, values)
            if self._group is not None:
                return self._group_push(
                    table, slots, combined, sync=False, timeout=None
                )
            ts, _ = self._submit_push(table, slots, combined, tctx=tctx)
            return ts

    def push_device(self, table: str, keys: np.ndarray, values) -> int:
        """Device-resident push: gradient rows never leave the device.

        Only the (small, int) keys are handled on the host; the value rows
        are a ``jax.Array`` that is duplicate-combined on device and sliced
        per server as device views.  Over the LoopbackVan those views flow
        to the server tables with no host round-trip — the SArray-zero-copy
        role of SURVEY §2 #19 in its TPU form.  (A cross-host Van serializes
        at its own boundary, which is where the reference copies too.)
        """
        tctx = self._trace_ctx()
        with self.tracer.span(
            "kv.push", table=table, n=int(keys.size),
            **({"trace": tctx["tid"]} if tctx is not None else {}),
        ):
            cfg = self.table_cfgs[table]
            vals = values.reshape(keys.size, cfg.dim)
            slots, inverse, _n = localize_to_slots(
                keys, self.localizers[table], min_bucket=self.min_bucket
            )
            combined = _segment_combine(jnp.asarray(inverse), vals, slots.shape[0])
            ts, _ = self._submit_push(table, slots, combined, tctx=tctx)
            return ts

    def coalesce_window(self):
        """Context manager batching this worker's sends per destination.

        When the Postoffice's Van stack includes a
        :class:`~parameter_server_tpu.core.coalesce.CoalescingVan`, every
        message sent inside the window is bundled per server — a multi-table
        push pays the per-server frame overhead (pickle header, seq/ACK,
        filter pass) once.  A no-op (null context) on plain stacks, so
        callers never need to know what the Van is.
        """
        win = getattr(self.post.van, "window", None)
        return win() if callable(win) else contextlib.nullcontext()

    def push_many(
        self, updates: Dict[str, Tuple[np.ndarray, np.ndarray]]
    ) -> Dict[str, int]:
        """Push several tables' gradients in one coalescing window.

        ``updates``: ``{table: (keys, values)}``.  Returns ``{table: ts}``
        — one timestamp per table (responses from the same server must not
        share a ts), all of whose wire messages coalesce into one frame per
        server.  ``wait()`` each ts as usual.

        Group mode (ISSUE 15): each table elects its own leader (the crc32
        table offset in :meth:`~parameter_server_tpu.kv.routing.
        WorkerGroup.leader` de-phases them), and fenced rejects of any
        reduced push re-elect per table inside the ack callback.
        """
        with self.coalesce_window():
            return {
                t: self.push(t, keys, values)
                for t, (keys, values) in updates.items()
            }

    # -- pull ---------------------------------------------------------------
    def pull(self, table: str, keys: np.ndarray, *, read_only: bool = False) -> int:
        """Request weights for ``keys``; fetch with :meth:`pull_result`.

        ``read_only=True`` stamps the serving plane's ``__ro__`` flag: the
        server answers on the read-only fast path (ISSUE 13) — relaxed
        reads that may NOT observe writes coalesced into the same wire
        bundle.  Training pulls must keep the default.
        """
        slots, inverse, _n = localize_to_slots(
            keys, self.localizers[table], min_bucket=self.min_bucket
        )
        return self._submit_pull(
            table, slots, inverse, keys.shape, read_only=read_only
        )

    def _submit_pull(
        self,
        table,
        slots,
        inverse,
        shape,
        positions: Optional[np.ndarray] = None,
        *,
        read_only: bool = False,
        ungated: bool = False,
    ) -> int:
        tctx = self._trace_ctx()
        routing = self.routing
        if positions is None:
            positions = np.arange(slots.shape[0], dtype=np.int64)
        sub = slots[positions]
        msgs = []
        order = {}
        payload = {
            "table": table,
            ROUTING_EPOCH_KEY: routing.epoch,
        }
        # consistency plane (ISSUE 20): training pulls on gated tables
        # stamp the committed step so a lagging/ahead worker is gated at
        # the server.  Read-only serving pulls are NEVER gated — they are
        # the shed target — and ``ungated=True`` is the deadline
        # force-through (fresh data can never violate a staleness bound).
        if not read_only and not ungated and self._gated(table):
            payload[CONSIST_STEP_KEY] = self.consist_step(table)
        if tctx is not None:
            payload[TRACE_KEY] = tctx
        if read_only:
            payload[READ_ONLY_KEY] = True
        for s, rel, ids in routing.slice_ids(table, sub):
            abs_pos = positions[rel]
            order[server_id(s)] = abs_pos
            msgs.append(
                Message(
                    # fresh dict per leg: payloads must never be shared
                    # across messages (a Loopback reply path may alias them)
                    task=Task(TaskKind.PULL, self.name, payload=dict(payload)),
                    recver=server_id(s),
                    keys=ids.astype(np.int32),
                )
            )
        # registered before the submit: the replies race the submit call
        self._trace_submitted(tctx, "pull", len(msgs))
        with self.coalesce_window():
            ts = self.submit(msgs, keep_responses=True)
        self._pull_plans[ts] = {
            "order": order,
            "inverse": inverse,
            "n_slots": slots.shape[0],
            "shape": shape,
            "table": table,
            # retained so deadline/fence retries can re-issue subsets
            "slots": slots,
            "trace": tctx["tid"] if tctx is not None else None,
            "ro": read_only,
            "ungated": ungated,
        }
        return ts

    def _await_pull(self, ts: int, timeout: Optional[float]) -> tuple:
        """Wait for pull ``ts``; on deadline, cancel the stuck task and
        retry ONCE against the (possibly promoted) server identity.

        Returns ``(plan, responses, errs)`` with all kept state drained.
        """
        tid = self._pull_plans[ts].get("trace")
        with self.tracer.span("kv.pull.wait", ts=ts, trace=tid):
            completed = self.wait(ts, timeout)
        if not completed and self.retry_on_timeout:
            plan = self._pull_plans.pop(ts)
            # remote=True fences the dead pull at servers whose request leg
            # is still in flight — they drop it instead of computing a reply
            # nobody will read
            self.cancel(ts, "pull deadline", remote=True)
            self.take_responses(ts)  # responses of the dead task: drained
            self.pull_retries += 1
            pos = np.sort(np.concatenate(list(plan["order"].values())))
            ts = self._submit_pull(
                plan["table"],
                plan["slots"],
                plan["inverse"],
                plan["shape"],
                positions=pos,
                read_only=plan.get("ro", False),
                ungated=plan.get("ungated", False),
            )
            tid = self._pull_plans[ts].get("trace")
            with self.tracer.span("kv.pull.wait", ts=ts, retry=1, trace=tid):
                completed = self.wait(ts, timeout)
        plan = self._pull_plans.pop(ts)  # always reclaim, even on error paths
        errs = self.errors(ts)
        responses = self.take_responses(ts)  # always drain kept state
        if not completed:
            raise TimeoutError(f"pull ts={ts} timed out")
        return plan, responses, errs

    def _shed_pull_stale(self, plan: dict, pos: np.ndarray):
        """Answer the WAITED positions from the stale cache (ISSUE 20).

        The gate-deadline shed target: the PR 13 stale serving path,
        bounded by whatever ``__sver__`` each cached row's reply carried.
        Returns a synthetic ``(positions, rows, sver, "cache")`` pair, or
        None when any waited slot is uncached (the caller then forces an
        ungated pull — fresh data, never a dropped read).
        """
        cache = self.cache
        if cache is None:
            return None
        table = plan["table"]
        cfg = self.table_cfgs[table]
        grows = self.routing.tables[table].rows
        rows = np.zeros((int(pos.shape[0]), cfg.dim), dtype=cfg.dtype)
        sver = None
        for j, sl in enumerate(plan["slots"][pos].tolist()):
            if int(sl) >= grows:
                continue  # bucket pad: stays zero, matching the wire reply
            hit = cache.lookup_stale(table, int(sl))
            if hit is None:
                return None
            rows[j] = hit[0]
            sver = hit[1] if sver is None else min(sver, hit[1])
        return pos, rows, sver, "cache"

    def _gate_deadline_s(self, table: str) -> float:
        cfg = self.table_cfgs[table].consistency
        return cfg.gate_deadline_s if cfg is not None else 0.0

    def _gate_pause(self, table: str, retry_after: float) -> None:
        cfg = self.table_cfgs[table].consistency
        base = cfg.gate_retry_s if cfg is not None else 0.005
        time.sleep(max(retry_after, base))

    def _pull_pairs(self, ts: int, timeout: Optional[float]) -> tuple:
        """Resolve pull ``ts`` into ``(plan, [(positions, rows, sver,
        sender)])``, looping over routing fences: fenced legs adopt the
        attached table and only their positions are re-pulled (under the
        NEW epoch).  ``sver``/``sender`` let :meth:`pull_serve` stamp cache
        inserts with the version EACH REPLY actually carried — never the
        watermark at insert time, which may have advanced concurrently.

        Consistency gates (ISSUE 20): ``__wait__`` defers are NOT fences —
        waited positions retry on the gate budget (``gate_deadline_s``,
        honoring the server's ``retry_after`` hint) without consuming
        fence retries.  Past the deadline the read degrades gracefully:
        shed to the stale cache when it covers the waited rows
        (``consist.shed``), else forced through ungated — counted, never
        dropped."""
        pairs: list = []
        first_plan = None
        attempt = 0  # fence budget only; gate waits ride their own clock
        gate_t0 = None
        forced = False
        ungated = False
        while attempt <= self.max_fence_retries:
            plan, responses, errs = self._await_pull(ts, timeout)
            if first_plan is None:
                first_plan = plan
                ungated = plan.get("ungated", False)
            self._adopt_from(responses)
            responses, waits, wait_pos, retry_after = self._scan_waits(
                responses, plan["order"]
            )
            data, fenced_senders, fenced = self._scan_fences(
                responses, plan["order"]
            )
            skip = fenced_senders | {r.sender for r in waits}
            real = self._real_errors(errs, skip)
            if real:  # a dropped leg must not read as zero weights
                raise RuntimeError(f"pull ts={ts} failed on: " + "; ".join(real))
            if len(responses) + len(waits) < len(plan["order"]):
                raise RuntimeError(
                    f"pull ts={ts} incomplete: {len(responses)}/"
                    f"{len(plan['order'])} servers answered (dead server?)"
                )
            pairs.extend(
                (
                    plan["order"][r.sender],
                    r.values[0],
                    r.task.payload.get(VERSION_KEY),
                    r.sender,
                )
                for r in data
            )
            if not fenced and not waits:
                if gate_t0 is not None:
                    with self._consist_lock:
                        self._gate_hist.record(
                            max(time.monotonic() - gate_t0, 0.0)
                        )
                return first_plan, pairs
            pending = list(fenced)
            if waits:
                with self._consist_lock:
                    self.consist_waits += len(waits)
                if gate_t0 is None:
                    gate_t0 = time.monotonic()
                table = first_plan["table"]
                deadline = self._gate_deadline_s(table)
                waited = np.sort(np.concatenate(wait_pos))
                if (
                    deadline > 0
                    and time.monotonic() - gate_t0 > deadline
                    and not forced
                ):
                    # graceful degradation: past the deadline the read
                    # sheds to the stale cache, else forces through
                    shed = self._shed_pull_stale(first_plan, waited)
                    with self._consist_lock:
                        self._gate_hist.record(
                            max(time.monotonic() - gate_t0, 0.0)
                        )
                    if shed is not None:
                        pairs.append(shed)
                        with self._consist_lock:
                            self.consist_sheds += 1
                        flightrec.record(
                            "consist.shed", node=self.post.node_id,
                            table=table, op="pull", how="stale-cache",
                            n=int(waited.shape[0]),
                        )
                        if not fenced:
                            return first_plan, pairs
                    else:
                        forced = ungated = True
                        pending.append(waited)
                        with self._consist_lock:
                            self.consist_forced += 1
                        flightrec.record(
                            "consist.shed", node=self.post.node_id,
                            table=table, op="pull", how="forced",
                            n=int(waited.shape[0]),
                        )
                else:
                    pending.append(waited)
                    self._gate_pause(table, retry_after)
            if fenced:
                self.refresh_retries += 1
                attempt += 1
                if attempt > 1:  # mid-broadcast epoch bounce: outlast it
                    time.sleep(self.fence_backoff * (attempt - 1))
            pos = np.sort(np.concatenate(pending))
            ts = self._submit_pull(
                first_plan["table"],
                first_plan["slots"],
                first_plan["inverse"],
                first_plan["shape"],
                positions=pos,
                read_only=first_plan.get("ro", False),
                ungated=ungated,
            )
        raise RuntimeError(
            f"pull of {first_plan['table']!r}: routing fence retries "
            f"exhausted after {self.max_fence_retries} refreshes"
        )

    @staticmethod
    def _sole_full_pair(pairs: list, n_slots: int):
        """The single reply covering every slot in identity order, or None.

        The common single-server (or single-owner-after-localize) pull has
        exactly one ``(positions, rows)`` pair whose positions are
        ``0..n_slots-1``; its rows array — a zero-copy view of the received
        wire frame — can feed the inverse gather directly, skipping the
        zeros allocation + scatter pass entirely.
        """
        if len(pairs) != 1:
            return None
        pos, rows = pairs[0][0], pairs[0][1]
        pos = np.asarray(pos)
        if pos.size == n_slots and np.array_equal(pos, np.arange(n_slots)):
            return rows
        return None

    def pull_result(self, ts: int, timeout: Optional[float] = None) -> np.ndarray:
        """Block for pull ``ts`` and reassemble per-position weight rows.

        Output shape: ``keys.shape + (dim,)`` for dim>1 tables, ``keys.shape``
        for dim=1.
        """
        plan, pairs = self._pull_pairs(ts, timeout)
        cfg = self.table_cfgs[plan["table"]]
        sole = self._sole_full_pair(pairs, plan["n_slots"])
        if sole is not None:
            # dtype= is a no-op passthrough when the reply already matches
            # (the normal case); only an off-dtype reply pays a cast copy
            uniq_rows = np.asarray(sole, dtype=cfg.dtype).reshape(-1, cfg.dim)
        else:
            uniq_rows = np.zeros((plan["n_slots"], cfg.dim), dtype=cfg.dtype)
            for pos, rows, *_meta in pairs:
                uniq_rows[pos] = np.asarray(rows).reshape(-1, cfg.dim)
        out = uniq_rows[plan["inverse"]]
        if cfg.dim == 1:
            return out.reshape(plan["shape"])
        return out.reshape(plan["shape"] + (cfg.dim,))

    def pull_result_device(self, ts: int, timeout: Optional[float] = None):
        """Like :meth:`pull_result` but assembles rows ON DEVICE.

        Servers replying with device arrays (``KVServer(device_replies=
        True)``) never touch host memory; numpy replies are uploaded once.
        Returns a ``jax.Array`` of shape ``keys.shape + (dim,)`` (or
        ``keys.shape`` for dim=1).
        """
        plan, pairs = self._pull_pairs(ts, timeout)
        cfg = self.table_cfgs[plan["table"]]
        sole = self._sole_full_pair(pairs, plan["n_slots"])
        if sole is not None:
            uniq = jnp.asarray(sole, jnp.dtype(cfg.dtype)).reshape(-1, cfg.dim)
        else:
            uniq = jnp.zeros((plan["n_slots"], cfg.dim), jnp.dtype(cfg.dtype))
            for pos, rows, *_meta in pairs:
                rows = jnp.asarray(rows).reshape(-1, cfg.dim)
                uniq = uniq.at[jnp.asarray(pos)].set(rows)
        out = jnp.take(uniq, jnp.asarray(plan["inverse"]), axis=0)
        if cfg.dim == 1:
            return out.reshape(plan["shape"])
        return out.reshape(plan["shape"] + (cfg.dim,))

    def pull_sync(
        self, table: str, keys: np.ndarray, timeout: Optional[float] = None
    ) -> np.ndarray:
        return self.pull_result(self.pull(table, keys), timeout)

    # -- read-heavy serving plane (ISSUE 13) ---------------------------------
    def pull_serve(
        self, table: str, keys: np.ndarray, timeout: Optional[float] = None
    ) -> np.ndarray:
        """Serve a read: hot-row cache first, read-only RPC for the misses.

        Same output contract as :meth:`pull_sync`, but every key the cache
        holds at a fresh version (entry ``__sver__`` >= the owner's observed
        watermark) is answered locally; only the misses go on the wire —
        stamped ``__ro__``, so the server answers them on the fast path.
        Fetched rows are inserted at the version THEIR reply carried, which
        is what keeps the bounded-staleness contract exact under races.
        Without a cache this degrades to a plain read-only pull.
        """
        keys = np.asarray(keys)
        cache = self.cache
        if cache is None:
            return self.pull_result(
                self.pull(table, keys, read_only=True), timeout
            )
        cfg = self.table_cfgs[table]
        with self.tracer.span("kv.pull_serve", table=table, n=int(keys.size)):
            # No dedup/sort on the hit path: ``Localizer.assign`` is
            # elementwise, so probe one slot PER POSITION (duplicates probe
            # twice — vectorized, cheaper than a ``np.unique``) and the
            # inverse is the identity.  Only the miss subset pays the sort
            # that ``Routing.slice_ids`` requires.
            loc = self.localizers[table]
            slots = loc.assign(
                np.ascontiguousarray(keys, dtype=np.uint64).ravel()
            )
            inverse = np.arange(slots.shape[0], dtype=np.int32)
            tr = self.routing.tables[table]
            grows = tr.rows
            n_slots = int(slots.shape[0])
            rows_out = np.zeros((n_slots, cfg.dim), dtype=cfg.dtype)
            real = np.flatnonzero(slots < grows)
            rslots = slots[real].astype(np.int64, copy=False)
            seg = np.searchsorted(
                np.asarray(tr.offsets, dtype=np.int64), rslots, side="right"
            ) - 1
            seg = np.clip(seg, 0, len(tr.owners) - 1)
            # per-segment owner codes interned once per adopted routing
            # table (identity-keyed: adoption replaces the object), so the
            # batch compare inside the cache is pure vector ops
            owner_codes = self._serve_owner_codes(table, tr, cache)[seg]
            hit, hit_rows = cache.lookup_many(table, rslots, owner_codes)
            n_hit = int(hit.sum())
            if n_hit:
                rows_out[real[hit]] = hit_rows
                flightrec.record(
                    "cache.hit", node=self.post.node_id, table=table,
                    n=n_hit,
                )
            if n_hit < int(real.shape[0]):
                miss = ~hit
                # slice_ids routes by searchsorted: subset must be sorted
                pos = real[miss][np.argsort(rslots[miss], kind="stable")]
                flightrec.record(
                    "cache.miss", node=self.post.node_id, table=table,
                    n=int(pos.shape[0]),
                )
                ts = self._submit_pull(
                    table, slots, inverse, keys.shape,
                    positions=pos, read_only=True,
                )
                _plan, pairs = self._pull_pairs(ts, timeout)
                for p, rows, sver, sender in pairs:
                    rows = np.asarray(rows, dtype=cfg.dtype).reshape(
                        -1, cfg.dim
                    )
                    rows_out[p] = rows
                    ids = slots[p]
                    realm = ids < grows
                    if sver is not None and realm.any():
                        cache.insert(
                            table, ids[realm], rows[realm], int(sver), sender
                        )
            out = rows_out[inverse]
        if cfg.dim == 1:
            return out.reshape(keys.shape)
        return out.reshape(keys.shape + (cfg.dim,))

    def pull_stale(
        self, table: str, keys: np.ndarray
    ) -> Optional[np.ndarray]:
        """Serve entirely from cache IGNORING freshness — the "stale" shed
        policy's degraded answer during overload.  Returns None unless
        every real key is cached (a partially-stale answer would mix
        freshness classes invisibly); never touches the wire."""
        cache = self.cache
        if cache is None:
            return None
        keys = np.asarray(keys)
        cfg = self.table_cfgs[table]
        slots, inverse, _n = localize_to_slots(
            keys, self.localizers[table], min_bucket=self.min_bucket
        )
        grows = self.routing.tables[table].rows
        rows_out = np.zeros((int(slots.shape[0]), cfg.dim), dtype=cfg.dtype)
        for j, sl in enumerate(np.asarray(slots).tolist()):
            if int(sl) >= grows:
                continue
            hit = cache.lookup_stale(table, int(sl))
            if hit is None:
                return None
            rows_out[j] = hit[0]
        out = rows_out[inverse]
        if cfg.dim == 1:
            return out.reshape(keys.shape)
        return out.reshape(keys.shape + (cfg.dim,))

    def push_sync(
        self,
        table: str,
        keys: np.ndarray,
        values: np.ndarray,
        timeout: Optional[float] = None,
    ) -> int:
        """Push and block for all server acks, retrying once on deadline and
        looping on routing fences.

        The deadline path mirrors :meth:`pull_result`: the stuck task is
        cancelled (no leaked ``_pending`` state) and the push re-issued
        against the same ``S{i}`` identities — live again after a
        :class:`~parameter_server_tpu.kv.replica.ReplicaSet` promotion.
        Retried pushes are deduplicated by the transport only when the SAME
        message is retransmitted (``ReliableVan``); an app-layer retry is a
        new message, so — like the reference's retry — it can double-apply
        iff the original was applied but its ack was lost AND the transport
        below is unreliable.  Run over ``ReliableVan`` (acks retransmitted)
        that window closes: a surviving server acks, only a dead one
        triggers the retry.

        Fence loop (PR 6): legs rejected for a stale routing epoch or moved
        range adopt the server's table and re-push ONLY the fenced positions
        — the fence fired BEFORE any apply, so the retry cannot double-count
        and the accepted legs are never re-sent.  Returns the completing
        timestamp.

        Group mode (ISSUE 15): the push routes through the group
        pre-reduction and this call blocks until the group's done notify
        (all members of a step must run :meth:`push_sync` concurrently —
        the leader's rendezvous completes only when every contribution
        lands).  Fenced rejects of the reduced push RE-ELECT
        (``salt=attempt``) inside the leader's ack callback, handing the
        retry to the next member; leader death degrades to this member's
        own direct push within the same step.
        """
        slots, combined = self._prepare_push(table, keys, values)
        if self._group is not None:
            return self._group_push(
                table, slots, combined, sync=True, timeout=timeout
            )
        return self._push_sync_prepared(table, slots, combined, timeout)

    def _push_sync_prepared(
        self,
        table: str,
        slots: np.ndarray,
        combined: np.ndarray,
        timeout: Optional[float] = None,
    ) -> int:
        """The direct (ungrouped) sync push loop over prepared planes —
        also the group mode's no-loss degradation target.

        Consistency gates (ISSUE 20): ``__wait__`` defers park the waited
        positions on the gate budget (no fence retries consumed).  Pushes
        are NEVER dropped: past ``gate_deadline_s`` the remainder is
        forced through ungated (``consist.shed``, how="forced").  A fully
        acked push commits this worker's step for the table — the
        ``__cstep__`` every later request stamps."""
        positions: Optional[np.ndarray] = None
        ts = -1
        attempt = 0  # fence budget only; gate waits ride their own clock
        gate_t0 = None
        ungated = False
        while attempt <= self.max_fence_retries:
            ts, order = self._submit_push(
                table, slots, combined, positions, keep=True, ungated=ungated
            )
            if not self.wait(ts, timeout):
                if not self.retry_on_timeout:
                    raise TimeoutError(f"push ts={ts} timed out")
                # remote=True: servers that have not applied the original yet
                # DROP it, closing the original+retry double-apply window
                # that the transport argument alone cannot (a delayed request
                # leg is not a retransmit, so ReliableVan dedup never sees it)
                self.cancel(ts, "push deadline", remote=True)
                self.take_responses(ts)
                self.push_retries += 1
                ts, order = self._submit_push(
                    table, slots, combined, positions, keep=True,
                    ungated=ungated,
                )
                if not self.wait(ts, timeout):
                    self.cancel(ts, "push deadline (retry)", remote=True)
                    self.take_responses(ts)
                    raise TimeoutError(f"push ts={ts} timed out after retry")
            errs = self.errors(ts)
            responses = self.take_responses(ts)
            self._adopt_from(responses)
            responses, waits, wait_pos, retry_after = self._scan_waits(
                responses, order
            )
            _, fenced_senders, fenced = self._scan_fences(responses, order)
            skip = fenced_senders | {r.sender for r in waits}
            real = self._real_errors(errs, skip)
            if real:
                raise RuntimeError(
                    f"push ts={ts} failed on: " + "; ".join(real)
                )
            if not fenced and not waits:
                if self._gated(table):
                    self._consist_commit(table)
                    if gate_t0 is not None:
                        with self._consist_lock:
                            self._gate_hist.record(
                                max(time.monotonic() - gate_t0, 0.0)
                            )
                return ts
            pending = list(fenced)
            if waits:
                with self._consist_lock:
                    self.consist_waits += len(waits)
                if gate_t0 is None:
                    gate_t0 = time.monotonic()
                pending.append(np.sort(np.concatenate(wait_pos)))
                deadline = self._gate_deadline_s(table)
                if (
                    deadline > 0
                    and time.monotonic() - gate_t0 > deadline
                    and not ungated
                ):
                    # never dropped: force the remainder through ungated
                    ungated = True
                    with self._consist_lock:
                        self.consist_forced += 1
                        self._gate_hist.record(
                            max(time.monotonic() - gate_t0, 0.0)
                        )
                    flightrec.record(
                        "consist.shed", node=self.post.node_id,
                        table=table, op="push", how="forced",
                        n=int(sum(p.shape[0] for p in wait_pos)),
                    )
                else:
                    self._gate_pause(table, retry_after)
            if fenced:
                self.refresh_retries += 1
                attempt += 1
                if attempt > 1:  # mid-broadcast epoch bounce: outlast it
                    time.sleep(self.fence_backoff * (attempt - 1))
            positions = np.sort(np.concatenate(pending))
        raise RuntimeError(
            f"push of {table!r}: routing fence retries exhausted after "
            f"{self.max_fence_retries} refreshes"
        )

    # -- checkpoint (reference SaveModel/LoadModel broadcast tasks) ----------
    def save_model(
        self,
        root: str,
        step: int,
        *,
        clocks: Optional[list] = None,
        extras: Optional[dict] = None,
        timeout: Optional[float] = 600.0,
    ) -> None:
        """Broadcast SaveModel to all servers, then commit the manifest.

        Blocking: returns once every shard is on disk and MANIFEST.json is
        written (the commit marker — see ``checkpoint.finalize``).  Raises if
        any server's save failed (disk full etc.) instead of committing a
        partial checkpoint.
        """
        from parameter_server_tpu import checkpoint
        from parameter_server_tpu.utils.keys import localizer_meta

        ts = self._broadcast_control("save_model", {"root": root, "step": step})
        if not self.wait(ts, timeout):
            raise TimeoutError("save_model timed out")
        self.check(ts)
        self.take_responses(ts)
        # Record each table's key->row mapping so offline eval reconstructs
        # the exact localizer (hash_bits/seed) instead of guessing a default.
        extras = dict(extras or {})
        extras.setdefault(
            "localizers",
            {t: localizer_meta(loc) for t, loc in self.localizers.items()},
        )
        checkpoint.finalize(
            root,
            step,
            self.num_servers,
            {t: cfg.rows for t, cfg in self.table_cfgs.items()},
            clocks=clocks,
            extras=extras,
        )

    def load_model(
        self, root: str, step: int, *, timeout: Optional[float] = 600.0
    ) -> None:
        """Broadcast LoadModel: every server restores its row-range."""
        ts = self._broadcast_control("load_model", {"root": root, "step": step})
        if not self.wait(ts, timeout):
            raise TimeoutError("load_model timed out")
        self.check(ts)
        self.take_responses(ts)

    # -- consistency plane control (ISSUE 20) --------------------------------
    def consist_hello(
        self,
        *,
        table: Optional[str] = None,
        step: Optional[int] = None,
        incarnation: Optional[int] = None,
        timeout: Optional[float] = 30.0,
    ) -> None:
        """Register this worker in every server's fleet clock up front.

        Call BEFORE training on a gated table (ElasticTrainer and the
        bench harness do): until every peer is registered, the clock
        cannot know the fleet is larger than the senders it has seen, so
        a fast worker could free-run ahead during bring-up.  After a
        same-id restart, re-hello at the restored ``step`` with the new
        incarnation — the dead incarnation's entry is replaced, not
        wedged into the fleet minimum.
        """
        if incarnation is None:
            reg = getattr(self.post.van, "incarnations", None)
            incarnation = reg.get(self.post.node_id) if reg is not None else 0
        if step is None:
            step = (
                self.consist_step(table)
                if table is not None
                else max(self._consist_steps.values(), default=0)
            )
        payload = {
            "worker": self.post.node_id,
            "incarnation": int(incarnation or 0),
            "step": int(step),
        }
        if table is not None:
            payload["table"] = table
        ts = self._broadcast_control("consist_hello", payload)
        if not self.wait(ts, timeout):
            raise TimeoutError("consist_hello timed out")
        self.check(ts)
        self.take_responses(ts)

    def set_consistency(
        self,
        *,
        table: Optional[str] = None,
        bound: Optional[int] = None,
        mode: Optional[str] = None,
        why: str = "manual",
        timeout: Optional[float] = 30.0,
    ) -> None:
        """Live-retune the fleet's gate: new ``bound`` and/or ``mode``.

        The BoundTuner's lever (bound only) and the scenario DSL's
        ``consistency_mode`` phase knob (mode flips mid-run).  Broadcast
        to every server, then flight-recorded as ``consist.retune`` so a
        postmortem can line tuning decisions up against SLO breaches.
        """
        payload: dict = {}
        if table is not None:
            payload["table"] = table
        if bound is not None:
            payload["bound"] = int(bound)
        if mode is not None:
            payload["mode"] = str(mode)
        ts = self._broadcast_control("consist_set", payload)
        if not self.wait(ts, timeout):
            raise TimeoutError("consist_set timed out")
        self.check(ts)
        self.take_responses(ts)
        flightrec.record(
            "consist.retune", node=self.post.node_id,
            table=table or "*", bound=-1 if bound is None else int(bound),
            mode=mode or "-", why=why[:120],
        )

    def _broadcast_control(self, op: str, payload: dict) -> int:
        # broadcast to the CURRENT owner set (post-migration it need not be
        # the contiguous 0..num_servers-1 of the launch split)
        msgs = [
            Message(
                task=Task(
                    TaskKind.CONTROL, self.name, payload={"op": op, **payload}
                ),
                recver=server_id(s),
            )
            for s in self.routing.servers()
        ]
        return self.submit(msgs, keep_responses=True)

    def _control_round(
        self, msgs: List[Message], what: str, timeout: Optional[float]
    ) -> List[Message]:
        """Submit control messages, wait, raise on any error, return replies."""
        ts = self.submit(msgs, keep_responses=True)
        if not self.wait(ts, timeout):
            raise TimeoutError(f"{what} timed out")
        self.check(ts)
        return self.take_responses(ts)

    # -- durability plane (ISSUE 16): partitioned incremental snapshots ------
    def save_snapshot(
        self,
        root: str,
        step: int,
        *,
        base_step: Optional[int] = None,
        clocks: Optional[list] = None,
        extras: Optional[dict] = None,
        timeout: Optional[float] = 600.0,
    ) -> dict:
        """Partitioned, incremental, non-blocking snapshot of every table.

        Unlike :meth:`save_model` this works for ANY routing layout: each
        owning server writes one file per owned segment, and the driver
        (here) assembles + CRC-verifies the manifest.  With ``base_step``
        set, segments whose version clock has not advanced are NOT
        rewritten — the base snapshot's file is carried forward by
        reference and only the dirty-row delta logs ship (the PR-10
        ``__sver__`` clock as LSN).  Pushes keep applying throughout; the
        only freeze is each server's delta export at ``snap_commit``.

        Returns a summary: carried/written segment counts, total delta
        rows, and per-server commit-freeze seconds.
        """
        from parameter_server_tpu import checkpoint
        from parameter_server_tpu.utils.keys import localizer_meta

        base = None
        if base_step is not None:
            base = checkpoint.read_snapshot(root, base_step)
        base_entries = {
            (e["table"], int(e["lo"]), int(e["hi"])): e
            for e in (base["segments"] if base else [])
        }
        sid = f"ckpt-{int(step)}-e{self.routing.epoch}"
        begun = False
        try:
            self._control_round(
                [
                    Message(
                        task=Task(TaskKind.CONTROL, self.name,
                                  payload={"op": "snap_begin", "sid": sid}),
                        recver=server_id(s),
                    )
                    for s in self.routing.servers()
                ],
                "snap_begin", timeout,
            )
            begun = True
            # one snap_write per segment, addressed to its owner; servers
            # process them serially on the recv thread, so pushes
            # interleave between segments — no bulk-copy freeze
            writes = []
            for t in sorted(self.routing.tables):
                for lo, hi, owner in self.routing.tables[t].segments():
                    payload = {
                        "op": "snap_write", "sid": sid, "root": root,
                        "step": int(step), "table": t, "lo": lo, "hi": hi,
                    }
                    be = base_entries.get((t, lo, hi))
                    if be is not None:
                        payload["base_sver"] = int(be.get("sver", 0))
                    writes.append(
                        Message(
                            task=Task(TaskKind.CONTROL, self.name,
                                      payload=payload),
                            recver=server_id(owner),
                        )
                    )
            # a migrated owner holds several segments; the Customer dedups
            # responses per (ts, sender), so each round may address any
            # server at most once — round-robin the writes into such rounds
            rounds: List[List[Message]] = []
            for m in writes:
                for batch in rounds:
                    if all(b.recver != m.recver for b in batch):
                        batch.append(m)
                        break
                else:
                    rounds.append([m])
            entries: List[dict] = []
            carried_tables: set = set()
            n_carried = 0
            for batch in rounds:
                for r in self._control_round(batch, "snap_write", timeout):
                    pl = r.task.payload
                    key = (str(pl["table"]), int(pl["lo"]), int(pl["hi"]))
                    if pl.get("carried"):
                        entries.append(dict(base_entries[key]))
                        carried_tables.add(key[0])
                        n_carried += 1
                    else:
                        entries.append(dict(pl["entry"]))
            # commit: the measured, delta-bounded freeze on every server
            deltas: List[dict] = []
            svers: Dict[tuple, int] = {}
            freezes: List[float] = []
            delta_rows = 0
            for r in self._control_round(
                [
                    Message(
                        task=Task(
                            TaskKind.CONTROL, self.name,
                            payload={"op": "snap_commit", "sid": sid,
                                     "root": root, "step": int(step)},
                        ),
                        recver=server_id(s),
                    )
                    for s in self.routing.servers()
                ],
                "snap_commit", timeout,
            ):
                pl = r.task.payload
                for d in pl["deltas"]:
                    deltas.append(dict(d))
                    delta_rows += int(d["rows"])
                for t, lo, hi, v in pl["svers"]:
                    svers[(str(t), int(lo), int(hi))] = int(v)
                freezes.append(float(pl["freeze_s"]))
        except Exception:
            if begun:
                # best-effort: release server-side dirty tracking; orphan
                # segment files are swept by retention, and with no
                # manifest the step simply never exists
                try:
                    msgs = [
                        Message(
                            task=Task(
                                TaskKind.CONTROL, self.name,
                                payload={"op": "snap_abort", "sid": sid,
                                         "why": "driver error"},
                            ),
                            recver=server_id(s),
                        )
                        for s in self.routing.servers()
                    ]
                    self._control_round(msgs, "snap_abort", timeout)
                except Exception:
                    pass
            raise
        # stamp commit-time segment versions: a row pushed between a
        # segment's write and the commit is in this snapshot's delta log,
        # so the NEXT snapshot may carry the file at the commit-time clock
        for e in entries:
            key = (e["table"], int(e["lo"]), int(e["hi"]))
            if key in svers:
                e["sver"] = svers[key]
        # incremental chains stay flat: carry the base's deltas only for
        # tables that carried at least one base file (fresh files are
        # stamped with THIS step, so older deltas can never apply to them)
        if base is not None:
            for d in base["deltas"]:
                if d["table"] in carried_tables:
                    deltas.append(dict(d))
        extras = dict(extras or {})
        extras.setdefault(
            "localizers",
            {t: localizer_meta(loc) for t, loc in self.localizers.items()},
        )
        checkpoint.finalize_snapshot(
            root, step, self.routing.to_payload(), entries, deltas,
            base_step=base_step, clocks=clocks, extras=extras,
        )
        return {
            "step": int(step),
            "segments": len(entries),
            "carried": n_carried,
            "delta_rows": delta_rows,
            "freeze_s": freezes,
        }

    def load_snapshot(
        self, root: str, step: int, *, timeout: Optional[float] = 600.0
    ) -> None:
        """Broadcast restore-from-partitioned-snapshot to the current fleet.

        The fleet shape may differ from the writing fleet's: each server
        reads only the manifest file ranges covering its CURRENT segments.
        """
        self._control_round(
            [
                Message(
                    task=Task(
                        TaskKind.CONTROL, self.name,
                        payload={"op": "restore_snap", "root": root,
                                 "step": int(step)},
                    ),
                    recver=server_id(s),
                )
                for s in self.routing.servers()
            ],
            "restore_snap", timeout,
        )
