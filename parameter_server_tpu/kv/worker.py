"""KVWorker: the classic Push/Pull facade with timestamps.

API parity with the reference worker (north-star requirement): ``push`` /
``pull`` return an integer timestamp; ``wait(ts)`` blocks; pulls deliver
values aligned with the request's key positions.  (Reference:
``src/parameter/parameter.h`` :: ``Parameter::Push/Pull/Wait`` [U].)

Pipeline per call (SURVEY.md §3.2 hot path, TPU mapping):

1. host: ``localize_to_slots`` — dedup keys, map to unique row slots
   (deterministic ``HashLocalizer`` for multi-worker consistency).
2. device: ``segment_combine`` duplicate positions (push only) — the
   worker-side pre-reduction; under a mesh this is where the DP ``psum``
   lands (parallel/, later milestone).
3. host: ``RangePartition.slice_ids`` — split the sorted slot segment per
   server (the reference's ``Parameter::Slice``).
4. Van: one request per server; responses complete the timestamp.
"""

from __future__ import annotations

import contextlib
import functools
import itertools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from parameter_server_tpu.config import TableConfig
from parameter_server_tpu.core.messages import Message, Task, TaskKind, server_id
from parameter_server_tpu.core.postoffice import Customer, Postoffice
from parameter_server_tpu.kv.partition import RangePartition
from parameter_server_tpu.ops import scatter
from parameter_server_tpu.utils.keys import HashLocalizer, localize_to_slots
from parameter_server_tpu.utils.trace import NULL_TRACER, Tracer


@functools.partial(jax.jit, static_argnames=("num_rows",))
def _segment_combine(inverse, values, num_rows: int):
    return scatter.segment_combine(values, inverse, num_rows)


class KVWorker(Customer):
    def __init__(
        self,
        post: Postoffice,
        table_cfgs: Dict[str, TableConfig],
        num_servers: int,
        *,
        name: str = "kv",
        localizers: Optional[Dict[str, HashLocalizer]] = None,
        min_bucket: int = 256,
        tracer: Tracer = NULL_TRACER,
        retry_on_timeout: bool = True,
    ) -> None:
        """``retry_on_timeout``: when a pull's deadline expires (dead or
        mid-promotion server), cancel the stuck task and re-issue it ONCE
        against the same server identity — by then
        :class:`~parameter_server_tpu.kv.replica.ReplicaSet` has typically
        rebound ``S{i}`` to the promoted standby, so the retry lands on live
        state and training continues without surfacing the death."""
        super().__init__(name, post)
        #: host-side span recorder (Push/Pull latency histograms, SURVEY §5)
        self.tracer = tracer
        self.table_cfgs = table_cfgs
        self.num_servers = num_servers
        self.min_bucket = min_bucket
        self.retry_on_timeout = retry_on_timeout
        self.partitions = {
            t: RangePartition(cfg.rows, num_servers) for t, cfg in table_cfgs.items()
        }
        self.localizers = localizers or {
            t: HashLocalizer(cfg.rows) for t, cfg in table_cfgs.items()
        }
        #: per-timestamp reassembly info for pulls
        self._pull_plans: Dict[int, dict] = {}
        #: deadline-retry counters (surfaced next to transport counters)
        self.pull_retries = 0
        self.push_retries = 0
        #: cross-node trace ids (see :meth:`_trace_ctx`)
        self._trace_seq = itertools.count()

    def _trace_ctx(self) -> dict:
        """Fresh trace context for one logical request.

        Stamped into ``Task.payload["__trace__"]`` of every wire leg and
        recorded as a ``trace`` attr on this worker's span; KVServer echoes
        it onto its handler spans, so ``tools/merge_traces.py`` can line up
        a worker's ``kv.push`` with the serving nodes' ``kv.server.push``
        on one merged timeline.  The id is unique per (node, customer,
        request) — no coordination needed across nodes.
        """
        return {
            "tid": f"{self.post.node_id}/{self.name}/{next(self._trace_seq)}",
            "origin": self.post.node_id,
            "customer": self.name,
        }

    # -- push ---------------------------------------------------------------
    def push(self, table: str, keys: np.ndarray, values: np.ndarray) -> int:
        """Push per-position gradient rows for ``keys``.  Returns timestamp.

        ``values`` has shape ``[len(keys), dim]`` (or ``[len(keys)]`` for
        dim=1 tables).
        """
        tctx = self._trace_ctx()
        with self.tracer.span(
            "kv.push", table=table, n=int(keys.size), trace=tctx["tid"]
        ):
            cfg = self.table_cfgs[table]
            vals = np.asarray(values, dtype=cfg.dtype).reshape(keys.size, cfg.dim)
            slots, inverse, _n = localize_to_slots(
                keys, self.localizers[table], min_bucket=self.min_bucket
            )
            # device-side duplicate pre-combine (worker-side pre-reduction)
            combined = np.asarray(
                _segment_combine(
                    jnp.asarray(inverse), jnp.asarray(vals), slots.shape[0]
                )
            )
            msgs = []
            for s, seg, local in self.partitions[table].slice_ids(slots):
                msgs.append(
                    Message(
                        task=Task(
                            TaskKind.PUSH,
                            self.name,
                            payload={"table": table, "__trace__": tctx},
                        ),
                        recver=server_id(s),
                        keys=local,
                        values=[combined[seg]],
                    )
                )
            # window: under a CoalescingVan the burst flushes at submit
            # exit (no flush-timer latency); nested inside push_many's
            # window it coalesces across tables instead
            with self.coalesce_window():
                return self.submit(msgs)

    def push_device(self, table: str, keys: np.ndarray, values) -> int:
        """Device-resident push: gradient rows never leave the device.

        Only the (small, int) keys are handled on the host; the value rows
        are a ``jax.Array`` that is duplicate-combined on device and sliced
        per server as device views.  Over the LoopbackVan those views flow
        to the server tables with no host round-trip — the SArray-zero-copy
        role of SURVEY §2 #19 in its TPU form.  (A cross-host Van serializes
        at its own boundary, which is where the reference copies too.)
        """
        import jax.numpy as jnp  # local alias keeps the hot path explicit

        tctx = self._trace_ctx()
        with self.tracer.span(
            "kv.push", table=table, n=int(keys.size), trace=tctx["tid"]
        ):
            cfg = self.table_cfgs[table]
            vals = values.reshape(keys.size, cfg.dim)
            slots, inverse, _n = localize_to_slots(
                keys, self.localizers[table], min_bucket=self.min_bucket
            )
            combined = _segment_combine(
                jnp.asarray(inverse), vals, slots.shape[0]
            )
            msgs = []
            for s, seg, local in self.partitions[table].slice_ids(slots):
                msgs.append(
                    Message(
                        task=Task(
                            TaskKind.PUSH,
                            self.name,
                            payload={"table": table, "__trace__": tctx},
                        ),
                        recver=server_id(s),
                        keys=local,
                        values=[combined[seg]],
                    )
                )
            with self.coalesce_window():
                return self.submit(msgs)

    def coalesce_window(self):
        """Context manager batching this worker's sends per destination.

        When the Postoffice's Van stack includes a
        :class:`~parameter_server_tpu.core.coalesce.CoalescingVan`, every
        message sent inside the window is bundled per server — a multi-table
        push pays the per-server frame overhead (pickle header, seq/ACK,
        filter pass) once.  A no-op (null context) on plain stacks, so
        callers never need to know what the Van is.
        """
        win = getattr(self.post.van, "window", None)
        return win() if callable(win) else contextlib.nullcontext()

    def push_many(
        self, updates: Dict[str, Tuple[np.ndarray, np.ndarray]]
    ) -> Dict[str, int]:
        """Push several tables' gradients in one coalescing window.

        ``updates``: ``{table: (keys, values)}``.  Returns ``{table: ts}``
        — one timestamp per table (responses from the same server must not
        share a ts), all of whose wire messages coalesce into one frame per
        server.  ``wait()`` each ts as usual.
        """
        with self.coalesce_window():
            return {
                t: self.push(t, keys, values)
                for t, (keys, values) in updates.items()
            }

    # -- pull ---------------------------------------------------------------
    def pull(self, table: str, keys: np.ndarray) -> int:
        """Request weights for ``keys``; fetch with :meth:`pull_result`."""
        slots, inverse, _n = localize_to_slots(
            keys, self.localizers[table], min_bucket=self.min_bucket
        )
        return self._submit_pull(table, slots, inverse, keys.shape)

    def _submit_pull(self, table, slots, inverse, shape) -> int:
        tctx = self._trace_ctx()
        msgs = []
        order = {}
        for s, seg, local in self.partitions[table].slice_ids(slots):
            order[server_id(s)] = seg
            msgs.append(
                Message(
                    task=Task(
                        TaskKind.PULL,
                        self.name,
                        payload={"table": table, "__trace__": tctx},
                    ),
                    recver=server_id(s),
                    keys=local,
                )
            )
        with self.coalesce_window():
            ts = self.submit(msgs, keep_responses=True)
        self._pull_plans[ts] = {
            "order": order,
            "inverse": inverse,
            "n_slots": slots.shape[0],
            "shape": shape,
            "table": table,
            # retained so a deadline retry can re-issue the identical pull
            "slots": slots,
            "trace": tctx["tid"],
        }
        return ts

    def _await_pull(self, ts: int, timeout: Optional[float]) -> tuple:
        """Wait for pull ``ts``; on deadline, cancel the stuck task and
        retry ONCE against the (possibly promoted) server identity.

        Returns ``(ts, plan, responses)`` with all kept state drained.
        """
        tid = self._pull_plans[ts].get("trace")
        with self.tracer.span("kv.pull.wait", ts=ts, trace=tid):
            completed = self.wait(ts, timeout)
        if not completed and self.retry_on_timeout:
            plan = self._pull_plans.pop(ts)
            # remote=True fences the dead pull at servers whose request leg
            # is still in flight — they drop it instead of computing a reply
            # nobody will read
            self.cancel(ts, "pull deadline", remote=True)
            self.take_responses(ts)  # responses of the dead task: drained
            self.pull_retries += 1
            ts = self._submit_pull(
                plan["table"], plan["slots"], plan["inverse"], plan["shape"]
            )
            tid = self._pull_plans[ts].get("trace")
            with self.tracer.span("kv.pull.wait", ts=ts, retry=1, trace=tid):
                completed = self.wait(ts, timeout)
        plan = self._pull_plans.pop(ts)  # always reclaim, even on error paths
        errs = self.errors(ts)
        responses = self.take_responses(ts)  # always drain kept state
        if not completed:
            raise TimeoutError(f"pull ts={ts} timed out")
        if errs:  # a dropped leg must not read as zero weights
            raise RuntimeError(f"pull ts={ts} failed on: " + "; ".join(errs))
        if len(responses) < len(plan["order"]):
            raise RuntimeError(
                f"pull ts={ts} incomplete: {len(responses)}/"
                f"{len(plan['order'])} servers answered (dead server?)"
            )
        return ts, plan, responses

    def pull_result(self, ts: int, timeout: Optional[float] = None) -> np.ndarray:
        """Block for pull ``ts`` and reassemble per-position weight rows.

        Output shape: ``keys.shape + (dim,)`` for dim>1 tables, ``keys.shape``
        for dim=1.
        """
        ts, plan, responses = self._await_pull(ts, timeout)
        cfg = self.table_cfgs[plan["table"]]
        uniq_rows = np.zeros((plan["n_slots"], cfg.dim), dtype=cfg.dtype)
        for resp in responses:
            seg = plan["order"][resp.sender]
            uniq_rows[seg] = resp.values[0]
        out = uniq_rows[plan["inverse"]]
        if cfg.dim == 1:
            return out.reshape(plan["shape"])
        return out.reshape(plan["shape"] + (cfg.dim,))

    def pull_result_device(self, ts: int, timeout: Optional[float] = None):
        """Like :meth:`pull_result` but assembles rows ON DEVICE.

        Servers replying with device arrays (``KVServer(device_replies=
        True)``) never touch host memory; numpy replies are uploaded once.
        Returns a ``jax.Array`` of shape ``keys.shape + (dim,)`` (or
        ``keys.shape`` for dim=1).
        """
        import jax
        import jax.numpy as jnp

        ts, plan, responses = self._await_pull(ts, timeout)
        cfg = self.table_cfgs[plan["table"]]
        uniq = jnp.zeros((plan["n_slots"], cfg.dim), jnp.dtype(cfg.dtype))
        for resp in responses:
            seg = plan["order"][resp.sender]
            rows = jnp.asarray(resp.values[0]).reshape(-1, cfg.dim)
            uniq = jax.lax.dynamic_update_slice(uniq, rows, (seg.start, 0))
        out = jnp.take(uniq, jnp.asarray(plan["inverse"]), axis=0)
        if cfg.dim == 1:
            return out.reshape(plan["shape"])
        return out.reshape(plan["shape"] + (cfg.dim,))

    def pull_sync(
        self, table: str, keys: np.ndarray, timeout: Optional[float] = None
    ) -> np.ndarray:
        return self.pull_result(self.pull(table, keys), timeout)

    def push_sync(
        self,
        table: str,
        keys: np.ndarray,
        values: np.ndarray,
        timeout: Optional[float] = None,
    ) -> int:
        """Push and block for all server acks, retrying once on deadline.

        The deadline path mirrors :meth:`pull_result`: the stuck task is
        cancelled (no leaked ``_pending`` state) and the push re-issued
        against the same ``S{i}`` identities — live again after a
        :class:`~parameter_server_tpu.kv.replica.ReplicaSet` promotion.
        Retried pushes are deduplicated by the transport only when the SAME
        message is retransmitted (``ReliableVan``); an app-layer retry is a
        new message, so — like the reference's retry — it can double-apply
        iff the original was applied but its ack was lost AND the transport
        below is unreliable.  Run over ``ReliableVan`` (acks retransmitted)
        that window closes: a surviving server acks, only a dead one
        triggers the retry.  Returns the completing timestamp.
        """
        ts = self.push(table, keys, values)
        if self.wait(ts, timeout):
            return ts
        if not self.retry_on_timeout:
            raise TimeoutError(f"push ts={ts} timed out")
        # remote=True: servers that have not applied the original yet DROP
        # it, closing the original+retry double-apply window that the
        # docstring's transport argument alone cannot (a delayed request
        # leg is not a retransmit, so ReliableVan dedup never sees it)
        self.cancel(ts, "push deadline", remote=True)
        self.push_retries += 1
        ts = self.push(table, keys, values)
        if not self.wait(ts, timeout):
            self.cancel(ts, "push deadline (retry)")
            raise TimeoutError(f"push ts={ts} timed out after retry")
        return ts

    # -- checkpoint (reference SaveModel/LoadModel broadcast tasks) ----------
    def save_model(
        self,
        root: str,
        step: int,
        *,
        clocks: Optional[list] = None,
        extras: Optional[dict] = None,
        timeout: Optional[float] = 600.0,
    ) -> None:
        """Broadcast SaveModel to all servers, then commit the manifest.

        Blocking: returns once every shard is on disk and MANIFEST.json is
        written (the commit marker — see ``checkpoint.finalize``).  Raises if
        any server's save failed (disk full etc.) instead of committing a
        partial checkpoint.
        """
        from parameter_server_tpu import checkpoint
        from parameter_server_tpu.utils.keys import localizer_meta

        ts = self._broadcast_control("save_model", {"root": root, "step": step})
        if not self.wait(ts, timeout):
            raise TimeoutError("save_model timed out")
        self.check(ts)
        self.take_responses(ts)
        # Record each table's key->row mapping so offline eval reconstructs
        # the exact localizer (hash_bits/seed) instead of guessing a default.
        extras = dict(extras or {})
        extras.setdefault(
            "localizers",
            {t: localizer_meta(loc) for t, loc in self.localizers.items()},
        )
        checkpoint.finalize(
            root,
            step,
            self.num_servers,
            {t: cfg.rows for t, cfg in self.table_cfgs.items()},
            clocks=clocks,
            extras=extras,
        )

    def load_model(
        self, root: str, step: int, *, timeout: Optional[float] = 600.0
    ) -> None:
        """Broadcast LoadModel: every server restores its row-range."""
        ts = self._broadcast_control("load_model", {"root": root, "step": step})
        if not self.wait(ts, timeout):
            raise TimeoutError("load_model timed out")
        self.check(ts)
        self.take_responses(ts)

    def _broadcast_control(self, op: str, payload: dict) -> int:
        msgs = [
            Message(
                task=Task(
                    TaskKind.CONTROL, self.name, payload={"op": op, **payload}
                ),
                recver=server_id(s),
            )
            for s in range(self.num_servers)
        ]
        return self.submit(msgs, keep_responses=True)

