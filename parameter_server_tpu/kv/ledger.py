"""ApplyLedger: device-plane observability for the sync-free apply engine.

PR 11's fused apply engine acks a PUSH as soon as the donated-buffer jit
call is DISPATCHED (``kv/server.py::_ack_push`` is AST-banned from touching
device state), which made the ack fast and made the device invisible: true
apply latency, device queue depth, and the host-assembly/H2D/compute split
no longer appear in ANY latency the telemetry plane measures.  The paper's
asynchronous-PS design makes server backlog the canonical overload signal —
this module is that gauge.

Lifecycle of one in-flight apply::

    tok = ledger.begin(table, members, rows)   # recv thread, t_submit
    ...host plane assembly...                  #   (one pinned host buffer)
    tok.mark_host()                            # host-assembly split point
    ...jnp.asarray / device stack...           #   (H2D handoff dispatch)
    tok.mark_h2d()
    ...donated-buffer jit dispatch...
    ledger.submit(tok, ref, fallback)          # still the recv thread

``ref`` is the apply's RESULT array (the table's new ``value``); the
**reaper** — a lazy-started daemon thread — retires entries once
``ref.is_ready()`` and never runs on the ack path, so the sync-free
contract holds by construction (and by AST:
:data:`~tools.check_wrappers.LEDGER_SYNC_FREE_FUNCS` bans device syncs in
``begin``/``mark_host``/``mark_h2d``/``submit``).  Between completions the
reaper BLOCKS on the oldest in-flight result (a GIL-releasing C++ wait) —
one wakeup per apply, not a poll cadence, so a busy server never pays
timer-interrupt preemption on its recv threads.  ``reap_interval_s`` is
only the degraded-mode cadence (donated-head races, :meth:`drain`).

Donation caveat: the next apply on the same table DONATES ``ref``'s buffer,
after which ``is_ready()`` raises.  Entries retire in FIFO order per table
and the device executes dispatches in order, so a deleted ``ref`` is
replaced by ``fallback()`` — the table's CURRENT value, whose readiness
bounds every older apply's completion.  Latency for such censored entries
is an upper bound (documented in the README); with the reaper waking per
completion the censoring window is one bundle.

What the ledger feeds:

- flight recorder: ``apply.submit`` / ``apply.done`` per apply and an
  edge-triggered ``apply.backlog`` when a configured bound is crossed
  (both directions, ``state=enter|clear``);
- telemetry: :meth:`counters` gauges (``inflight_bundles``,
  ``inflight_rows``, ``backlog_age_s``) and :meth:`latency_digests`
  cumulative per-table histograms (``apply.<t>`` total plus
  ``apply_host.<t>`` / ``apply_h2d.<t>`` / ``apply_dev.<t>`` attribution)
  — delta-framed by ``TelemetryPublisher`` like any other source;
- backpressure: :meth:`overloaded` is the level-triggered signal
  ``KVServer._ack_push`` turns into the ``__busy__`` ack hint.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional

from parameter_server_tpu.config import LedgerConfig
from parameter_server_tpu.core import flightrec
from parameter_server_tpu.utils.trace import LatencyHistogram


class _Inflight:
    """One registered apply.  Slotted: the submit path builds exactly one
    of these per bundle, nothing else."""

    __slots__ = (
        "bundle", "table", "members", "rows",
        "t_submit", "t_host", "t_h2d", "ref", "fallback", "tid",
    )

    def __init__(
        self,
        bundle: int,
        table: str,
        members: int,
        rows: int,
        tid: Optional[str] = None,
    ):
        self.bundle = bundle
        self.table = table
        self.members = members
        self.rows = rows
        self.t_submit = time.monotonic()
        self.t_host: Optional[float] = None
        self.t_h2d: Optional[float] = None
        self.ref = None
        self.fallback: Optional[Callable[[], object]] = None
        #: sampled trace id (ISSUE 18): set when a sampled request rode
        #: this apply — retirement then records a ``trace.apply`` child
        #: span carrying the host/H2D/device split
        self.tid = tid

    def mark_host(self) -> None:
        """Host plane assembly finished (the pinned-buffer pack)."""
        self.t_host = time.monotonic()

    def mark_h2d(self) -> None:
        """Device handoff dispatched (the ``jnp.asarray`` / device stack)."""
        self.t_h2d = time.monotonic()


class ApplyLedger:
    """Per-server registry of in-flight device applies + reaper thread.

    Submit-side methods (:meth:`begin`, ``mark_host``/``mark_h2d`` on the
    token, :meth:`submit`) run on the server's recv thread and are
    host-bookkeeping only — one lock acquire and a deque append.  Retiring
    happens exclusively on the reaper, which blocks inside the runtime on
    the oldest in-flight result between completions, self-stops after
    ``idle_stop_s`` with nothing in flight, and restarts lazily on the
    next submit — idle servers pay nothing, busy servers pay one wakeup
    per apply.
    """

    def __init__(
        self,
        node_id: str,
        cfg: Optional[LedgerConfig] = None,
        *,
        recorder: Optional[flightrec.FlightRecorder] = None,
    ) -> None:
        self.node_id = node_id
        self.cfg = cfg or LedgerConfig()
        if self.cfg.reap_interval_s <= 0:
            raise ValueError("reap_interval_s must be > 0")
        self._recorder = recorder
        self._lock = threading.Lock()
        #: submit -> reaper doorbell; shares the ledger lock.
        self._cond = threading.Condition(self._lock)
        #: per-table FIFO of in-flight entries (device executes dispatches
        #: in order, so per-table head-readiness implies everything older).
        self._inflight: Dict[str, collections.deque] = {}
        self._bundle_seq = 0
        self._inflight_rows = 0
        self._inflight_bundles = 0
        self.applies_submitted = 0
        self.applies_retired = 0
        #: retired via the donation fallback (latency is an upper bound).
        self.applies_censored = 0
        #: cumulative seconds-axis histograms, per table.
        self._hists: Dict[str, LatencyHistogram] = {}
        self._overloaded = False
        self._reaper: Optional[threading.Thread] = None
        self._closed = False

    # -- submit side (recv thread; sync-free by AST contract) ---------------
    def begin(
        self,
        table: str,
        members: int,
        rows: int,
        tid: Optional[str] = None,
    ) -> _Inflight:
        """Open an in-flight entry at dispatch start; returns the token the
        apply path marks its split points on.  ``tid``: sampled trace id
        riding this apply, if any (ISSUE 18)."""
        with self._lock:
            self._bundle_seq += 1
            seq = self._bundle_seq
        return _Inflight(seq, table, members, rows, tid)

    def submit(
        self, tok: _Inflight, ref, fallback: Callable[[], object]
    ) -> None:
        """Register the dispatched apply for reaping.

        ``ref``: the apply's result array (polled with ``is_ready()``);
        ``fallback``: zero-arg callable returning the table's CURRENT value
        array, used when a later apply donates ``ref``'s buffer away.
        """
        tok.ref = ref
        tok.fallback = fallback
        with self._lock:
            if self._closed:
                return
            dq = self._inflight.get(tok.table)
            if dq is None:
                dq = self._inflight[tok.table] = collections.deque()
            dq.append(tok)
            self._inflight_bundles += 1
            self._inflight_rows += tok.rows
            self.applies_submitted += 1
            crossed = self._backlog_edge_locked()
            start = self._reaper is None or not self._reaper.is_alive()
            if start:
                self._reaper = threading.Thread(
                    target=self._reap_loop,
                    name=f"apply-ledger-{self.node_id}",
                    daemon=True,
                )
                self._reaper.start()
            else:
                self._cond.notify()
        self._record(
            "apply.submit", node=self.node_id, bundle=tok.bundle,
            table=tok.table, members=tok.members, rows=tok.rows,
        )
        if crossed is not None:
            self._record_backlog(crossed)

    # -- backpressure --------------------------------------------------------
    def overloaded(self) -> bool:
        """Level-triggered backlog signal — the ``__busy__`` ack hint."""
        return self._overloaded

    def _backlog_age_locked(self, now: float) -> float:
        oldest = None
        for dq in self._inflight.values():
            if dq:
                t = dq[0].t_submit
                if oldest is None or t < oldest:
                    oldest = t
        return (now - oldest) if oldest is not None else 0.0

    def _backlog_edge_locked(self) -> Optional[bool]:
        """Recompute the overload state; returns the new state on a
        transition, None when unchanged.  Caller holds the lock."""
        c = self.cfg
        over = bool(
            (c.backlog_bundles and self._inflight_bundles > c.backlog_bundles)
            or (c.backlog_rows and self._inflight_rows > c.backlog_rows)
            or (
                c.backlog_age_s
                and self._backlog_age_locked(time.monotonic())
                > c.backlog_age_s
            )
        )
        if over == self._overloaded:
            return None
        self._overloaded = over
        return over

    def _record(self, kind: str, **fields) -> None:
        # aliased-callable form (as utils/slo.py): every call SITE passes a
        # literal kind from the EVENTS registry; the dispatch here stays
        # out of check_wrappers' definitive flightrec.record(...) scan
        rec = (
            flightrec.record if self._recorder is None
            else self._recorder.record
        )
        rec(kind, **fields)

    def _record_backlog(self, entered: bool) -> None:
        with self._lock:
            bundles = self._inflight_bundles
            rows = self._inflight_rows
            age = self._backlog_age_locked(time.monotonic())
        self._record(
            "apply.backlog",
            node=self.node_id,
            state="enter" if entered else "clear",
            inflight_bundles=bundles,
            inflight_rows=rows,
            age_s=round(age, 6),
        )

    # -- reaper --------------------------------------------------------------
    def _reap_loop(self) -> None:
        while True:
            with self._cond:
                while not self._closed and self._inflight_bundles == 0:
                    if not self._cond.wait(timeout=self.cfg.idle_stop_s):
                        # idle too long with nothing in flight: self-stop.
                        # The decision happens UNDER the lock, so a racing
                        # submit either lands before (wait returns True) or
                        # sees the dead thread and re-spawns.
                        if self._inflight_bundles == 0:
                            self._reaper = None
                            return
                if self._closed:
                    return
            self._reap_once()
            head = self._oldest_head()
            if head is None:
                continue
            try:
                # sleep INSIDE the runtime until the oldest dispatched
                # apply completes: the wait releases the GIL and wakes once
                # per completion — no poll cadence, no recv-thread
                # preemption.  Single device queue => oldest completes
                # first, so this is never a priority inversion.
                head.ref.block_until_ready()
            except Exception:
                # donated away mid-wait (or table replaced): degrade to one
                # interval of polling; _reap_once swaps in the fallback
                time.sleep(self.cfg.reap_interval_s)

    def _oldest_head(self) -> Optional[_Inflight]:
        with self._lock:
            heads = [dq[0] for dq in self._inflight.values() if dq]
        return min(heads, key=lambda e: e.t_submit, default=None)

    def _reap_once(self) -> List[_Inflight]:
        """Retire every per-table FIFO head whose result is ready."""
        done: List[_Inflight] = []
        censored: List[_Inflight] = []
        with self._lock:
            tables = list(self._inflight)
        for t in tables:
            while True:
                with self._lock:
                    dq = self._inflight.get(t)
                    head = dq[0] if dq else None
                if head is None:
                    break
                try:
                    ready = head.ref.is_ready()
                except Exception:
                    # a later apply donated this buffer away: poll the
                    # table's CURRENT value instead — its readiness bounds
                    # this (older) apply's completion
                    try:
                        head.ref = head.fallback()
                    except Exception:
                        ready = True  # table gone (resize/close): retire
                    else:
                        censored.append(head)
                        continue
                if not ready:
                    break
                with self._lock:
                    dq = self._inflight.get(t)
                    if not dq or dq[0] is not head:
                        break  # closed/cleared underneath us
                    dq.popleft()
                    self._inflight_bundles -= 1
                    self._inflight_rows -= head.rows
                    self.applies_retired += 1
                    if head in censored:
                        self.applies_censored += 1
                    crossed = self._backlog_edge_locked()
                self._retire(head)
                if crossed is not None:
                    self._record_backlog(crossed)
                done.append(head)
        return done

    def _retire(self, e: _Inflight) -> None:
        t_done = time.monotonic()
        t_host = e.t_host if e.t_host is not None else e.t_submit
        t_h2d = e.t_h2d if e.t_h2d is not None else t_host
        total = t_done - e.t_submit
        host = t_host - e.t_submit
        h2d = t_h2d - t_host
        dev = t_done - t_h2d
        with self._lock:
            hists = self._hists
            for name, v in (
                (f"apply.{e.table}", total),
                (f"apply_host.{e.table}", host),
                (f"apply_h2d.{e.table}", h2d),
                (f"apply_dev.{e.table}", dev),
            ):
                h = hists.get(name)
                if h is None:
                    h = hists[name] = LatencyHistogram()
                h.record(max(v, 0.0))
        self._record(
            "apply.done", node=self.node_id, bundle=e.bundle, table=e.table,
            members=e.members, rows=e.rows, ms=round(1e3 * total, 3),
            host_ms=round(1e3 * host, 3), h2d_ms=round(1e3 * h2d, 3),
            device_ms=round(1e3 * dev, 3),
        )
        if e.tid is not None:
            # sampled request tracing (ISSUE 18): the device-plane child
            # span — host pack / H2D / device execution attribution for
            # the apply the sampled request rode
            self._record(
                "trace.apply",
                tid=e.tid,
                node=self.node_id,
                table=e.table,
                ms=round(1e3 * total, 3),
                host_ms=round(1e3 * host, 3),
                h2d_ms=round(1e3 * h2d, 3),
                device_ms=round(1e3 * dev, 3),
            )

    # -- telemetry-facing reads ----------------------------------------------
    def counters(self) -> dict:
        """Live gauges + cumulative totals, publisher/Dashboard-mergeable.

        Gauges (``inflight_*``, ``backlog_age_s``) move both ways; the
        telemetry delta framing reconstructs them exactly (the cumulative
        sum of deltas IS the current value)."""
        with self._lock:
            return {
                "inflight_bundles": self._inflight_bundles,
                "inflight_rows": self._inflight_rows,
                "backlog_age_s": round(
                    self._backlog_age_locked(time.monotonic()), 6
                ),
                "applies_submitted": self.applies_submitted,
                "applies_retired": self.applies_retired,
                "applies_censored": self.applies_censored,
            }

    def latency_digests(self) -> Dict[str, dict]:
        """Cumulative per-table attribution digests, named for the
        telemetry plane (``TelemetryPublisher`` delta-encodes them; a
        ``SloSpec("apply-p99", "apply.w", 50.0, source="p99")`` reads the
        total in milliseconds via the default ``p99_scale``)."""
        with self._lock:
            return {name: h.to_dict() for name, h in self._hists.items()}

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until everything in flight retired (tests, shutdown)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight_bundles == 0:
                    return True
            time.sleep(self.cfg.reap_interval_s)
        return False

    def close(self) -> None:
        """Stop the reaper and drop in-flight entries (not retired)."""
        with self._lock:
            self._closed = True
            reaper = self._reaper
            self._inflight.clear()
            self._inflight_bundles = 0
            self._inflight_rows = 0
            self._cond.notify_all()
        if reaper is not None and reaper.is_alive():
            reaper.join(timeout=2.0)
