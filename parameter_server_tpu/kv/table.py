"""KVTable: a parameter table resident in device memory.

The TPU inversion of the reference server's storage (SURVEY.md §7): where the
reference keeps a sorted key array + value array per channel and merges pushes
with ``ParallelOrderedMatch`` (``src/parameter/kv_vector.h`` [U]), here the
table is a fixed ``[rows + 1, dim]`` ``jax.Array`` in HBM (last row = trash
row for padding), the host supplies dense unique row ids, and push/pull are
jit-compiled steps:

- ``push``: segment-combine duplicate positions -> gather value+state rows ->
  optimizer ``apply`` -> scatter rows back.  Buffers are donated, so the
  update is in-place in HBM.
- ``pull``: gather rows -> ``pull_weights`` (lazy FTRL weights etc.).

Shapes are bucket-padded by the host (``utils.keys``), so each table compiles
one kernel per (bucket, batch) shape pair.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from parameter_server_tpu.config import TableConfig
from parameter_server_tpu.kv.optim import ServerOptimizer, make_optimizer
from parameter_server_tpu.ops import scatter


class KVTable:
    """One table (or one row-range shard of a table) on the local device."""

    def __init__(self, cfg: TableConfig, *, rows: Optional[int] = None, seed: int = 0):
        self.cfg = cfg
        #: actual row count of this shard (cfg.rows is the global table size);
        #: one extra trash row is appended for padded ids.
        self.rows = cfg.rows if rows is None else rows
        self.dim = cfg.dim
        dtype = jnp.dtype(cfg.dtype)
        if cfg.init_scale > 0.0:
            key = jax.random.PRNGKey(seed)
            value = (
                jax.random.normal(key, (self.rows + 1, self.dim), dtype) * cfg.init_scale
            )
            value = value.at[self.rows].set(0.0)
        else:
            value = jnp.zeros((self.rows + 1, self.dim), dtype)
        self.value: jax.Array = value
        self.optimizer: ServerOptimizer = make_optimizer(cfg.optimizer)
        self.state: Dict[str, jax.Array] = {
            name: jnp.full((self.rows + 1, self.dim), fill, dtype)
            for name, fill in self.optimizer.state_shapes().items()
        }
        #: hot-path kernel selection (VERDICT r2 #4): "pallas" routes the
        #: gather + write-back through ops/scatter's DMA kernels — compiled
        #: on TPU, interpreter-run elsewhere so the FULL server path stays
        #: testable on the CPU mesh; "xla"/"auto" as documented on the flag.
        if cfg.scatter_impl not in ("auto", "xla", "pallas"):
            raise ValueError(
                f"scatter_impl must be auto|xla|pallas, got {cfg.scatter_impl!r}"
            )
        self.scatter_impl = cfg.scatter_impl
        self.fused_apply = cfg.fused_apply
        self._interpret = (
            cfg.scatter_impl == "pallas" and jax.default_backend() != "tpu"
        )
        self._push_fn = jax.jit(self._push_impl, donate_argnums=(0, 1))
        self._pull_fn = jax.jit(self._pull_impl)
        self._push_batch_fn = jax.jit(
            self._push_batch_impl, donate_argnums=(0, 1)
        )
        self._push_combined_fn = jax.jit(
            self._push_combined_impl, donate_argnums=(0, 1)
        )

    def _kern(self, fn, *args):
        return fn(*args, impl=self.scatter_impl, interpret=self._interpret)

    # -- jitted bodies ------------------------------------------------------
    def _apply_core(self, value, state, ids, grads):
        """Apply ``grads`` at unique ``ids``: fused or three-pass, then the
        trash-row reset (shared by every push entry point)."""
        if self.fused_apply:
            value, state = scatter.apply_rows(
                value, state, ids, grads, self.optimizer.apply,
                impl=self.scatter_impl, interpret=self._interpret,
            )
        else:
            v_rows = self._kern(scatter.gather_rows, value, ids)
            s_rows = {
                k: self._kern(scatter.gather_rows, v, ids)
                for k, v in state.items()
            }
            new_v, new_s = self.optimizer.apply(v_rows, s_rows, grads)
            value = self._kern(scatter.scatter_update_rows, value, ids, new_v)
            state = {
                k: self._kern(
                    scatter.scatter_update_rows, state[k], ids, new_s[k]
                )
                for k in state
            }
        # Re-zero the trash row: PAD_KEY positions in real (variable-nnz)
        # batches legitimately route gradients here; resetting keeps pulls of
        # padded positions exactly zero and makes duplicate-trash-id scatters
        # deterministic.
        value = value.at[-1].set(0.0)
        fills = self.optimizer.state_shapes()
        state = {k: state[k].at[-1].set(fills[k]) for k in state}
        return value, state

    def _push_impl(self, value, state, ids, combined):
        return self._apply_core(value, state, ids, combined)

    def _push_batch_impl(self, value, state, ids, positions, vals):
        # vals: (k, bm, dim) member stack; positions index its flattening,
        # with pads pointing at the appended zero row — the device-side
        # bucket pad (no host value copies, exact zeros: bitwise-neutral).
        flat = vals.reshape(-1, vals.shape[-1])
        flat = jnp.concatenate([flat, jnp.zeros((1,) + flat.shape[1:], flat.dtype)])
        return self._apply_core(value, state, ids, flat[positions])

    def _push_combined_impl(self, value, state, ids, inverse, vals):
        # segment_combine pre-merges duplicate rows across bundle members on
        # device; slots past the unique count only ever receive pad/trash
        # positions, whose values are exact zeros.
        flat = vals.reshape(-1, vals.shape[-1])
        combined = scatter.segment_combine(flat, inverse, ids.shape[0])
        return self._apply_core(value, state, ids, combined)

    def _pull_impl(self, value, state, ids):
        v_rows = self._kern(scatter.gather_rows, value, ids)
        s_rows = {k: self._kern(scatter.gather_rows, v, ids) for k, v in state.items()}
        return self.optimizer.pull_weights(v_rows, s_rows)

    # -- public ops ---------------------------------------------------------
    def push(self, ids: jax.Array, combined_grads: jax.Array) -> jax.Array:
        """Apply pre-combined gradient rows at unique ``ids`` (in place).

        ``ids`` must be unique (host guarantees via ``localize_to_slots``);
        padded ids point at the trash row and must carry zero gradients.
        Returns the new ``value`` array so the caller can hand it to the
        ApplyLedger as the readiness ref for this dispatch (the NEXT push
        donates it away, so polling through ``self.value`` would observe a
        later apply, not this one).
        """
        self.value, self.state = self._push_fn(
            self.value, self.state, ids, combined_grads
        )
        return self.value

    def push_batch(
        self, ids: jax.Array, positions: jax.Array, vals: jax.Array
    ) -> jax.Array:
        """One bundled apply round: unique ``ids`` gather their gradient rows
        out of the stacked member values by ``positions`` (pad positions index
        the appended zero row).  Donated in-place update, one jit call.
        Returns the new ``value`` (ledger readiness ref, as in :meth:`push`).
        """
        self.value, self.state = self._push_batch_fn(
            self.value, self.state, ids, positions, vals
        )
        return self.value

    def push_combined(
        self, ids: jax.Array, inverse: jax.Array, vals: jax.Array
    ) -> jax.Array:
        """Bundled apply with device pre-combine: every stacked value row is
        segment-summed into its unique-id slot (``inverse``), then applied in
        one donated jit call — the ``dup_policy="combine"`` engine mode.
        Returns the new ``value`` (ledger readiness ref, as in :meth:`push`).
        """
        self.value, self.state = self._push_combined_fn(
            self.value, self.state, ids, inverse, vals
        )
        return self.value

    def combine(self, inverse: jax.Array, values: jax.Array, num_rows: int) -> jax.Array:
        """Worker-side duplicate pre-combine (device segment_sum)."""
        return _combine_jit(inverse, values, num_rows)

    def pull(self, ids: jax.Array) -> jax.Array:
        """Servable weight rows for unique ``ids``."""
        return self._pull_fn(self.value, self.state, ids)

    # -- direct row access (checkpoint, tests, model eval) ------------------
    def weights(self) -> jax.Array:
        """Full servable weight table (excluding the trash row)."""
        return self.optimizer.pull_weights(self.value, self.state)[: self.rows]

    def set_value(self, value: np.ndarray | jax.Array) -> None:
        if value.shape != (self.rows + 1, self.dim):
            raise ValueError(
                f"expected {(self.rows + 1, self.dim)}, got {value.shape}"
            )
        self.value = jnp.asarray(value, dtype=self.value.dtype)

    def install_rows(
        self, value: np.ndarray, state: Dict[str, np.ndarray]
    ) -> None:
        """Replace the shard with ``[rows, dim]`` host arrays (NO trash row).

        The restore-side counterpart of the checkpoint writers (which save
        rows excluding the trash row): appends a fresh trash row — zero
        value, optimizer init fills — and installs via :meth:`resize`, so
        the shard may change row count (restore onto a different fleet
        shape).
        """
        if set(state) != set(self.state):
            raise ValueError(
                f"optimizer state keys mismatch: {set(state)} != {set(self.state)}"
            )
        n = int(value.shape[0])
        dtype = np.asarray(self.value).dtype
        fills = self.optimizer.state_shapes()
        buf = np.zeros((n + 1, self.dim), dtype)
        buf[:n] = value
        sbuf = {}
        for k, fill in fills.items():
            sk = np.full((n + 1, self.dim), fill, dtype)
            sk[:n] = state[k]
            sbuf[k] = sk
        self.resize(buf, sbuf)

    def resize(self, value: np.ndarray, state: Dict[str, np.ndarray]) -> None:
        """Replace the shard wholesale with a DIFFERENT row count.

        Live migration grows/shrinks a server's shard (``kv/server.py``
        adopt/release); ``value``/``state`` arrive as ``[new_rows + 1, dim]``
        host arrays INCLUDING the trash row.  The jitted push/pull steps are
        shape-polymorphic (jax.jit retraces per shape), so no re-wiring is
        needed — the next push simply compiles for the new shard size.
        """
        if value.ndim != 2 or value.shape[1] != self.dim or value.shape[0] < 1:
            raise ValueError(f"bad resize value shape {value.shape}")
        if set(state) != set(self.state):
            raise ValueError(
                f"optimizer state keys mismatch: {set(state)} != {set(self.state)}"
            )
        dtype = self.value.dtype
        self.rows = int(value.shape[0]) - 1
        self.value = jnp.asarray(value, dtype)
        self.state = {k: jnp.asarray(v, dtype) for k, v in state.items()}


@functools.partial(jax.jit, static_argnames=("num_rows",))
def _combine_jit(inverse, values, num_rows: int):
    return scatter.segment_combine(values, inverse, num_rows)
