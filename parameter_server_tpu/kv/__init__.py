"""kv subpackage."""
