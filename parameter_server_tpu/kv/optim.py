"""Server-side optimizers: row-wise update rules applied on Push.

The reference customizes server behavior through ``Parameter::SetValue`` /
KVMap entry functors — e.g. the FTRL entry keeping ``{z, n}`` and computing
the weight lazily on Pull (``src/app/linear_method/ftrl*.h`` [U]).  Here an
optimizer is a pair of pure, jit-friendly functions over *rows* (shape
``[n, dim]``): ``apply`` consumes gradient rows and per-row optimizer-state
rows; ``pull_weights`` maps stored value rows to servable weights (identity
for everything except FTRL).

State lives beside the value table as extra ``[rows, dim]`` arrays, so the
whole table (value + state) checkpoints and shards uniformly.

These are deliberately *not* optax transforms: PS optimizers act on gathered
row subsets with per-row step counts, which optax's whole-tree update model
does not express.  (optax remains the right tool for the dense model path in
``models/``.)
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp

from parameter_server_tpu.config import OptimizerConfig

Rows = jax.Array  # [n, dim]
State = Dict[str, Rows]


class ServerOptimizer:
    """Interface: init per-row state, apply updates, derive pull weights.

    ``apply`` is the ROW-WISE contract the fused apply kernel inlines
    (``ops.scatter.apply_rows``): a pure elementwise function over
    ``[n, dim]`` blocks — no cross-row reductions, no data-dependent
    shapes — so the same trace runs as the update stage of a single-pass
    gather→apply→scatter Pallas kernel or as plain XLA ops, bit-for-bit.
    """

    name = "base"
    #: True iff apply(value, state, 0) == (value, state) when l1 == l2 == 0.
    #: Required by the dense-apply paths (full-table elementwise update);
    #: rules with decaying state (Adam) must set False.
    g0_stable = False

    def __init__(self, cfg: OptimizerConfig) -> None:
        self.cfg = cfg

    def state_shapes(self) -> Dict[str, float]:
        """State array names -> fill value at init."""
        return {}

    def state_names(self) -> tuple[str, ...]:
        """Deterministic state-plane order (kernel scratch/DMA layout)."""
        return tuple(sorted(self.state_shapes()))

    def apply(self, value: Rows, state: State, grad: Rows) -> tuple[Rows, State]:
        raise NotImplementedError

    def pull_weights(self, value: Rows, state: State) -> Rows:
        return value


class SGD(ServerOptimizer):
    g0_stable = True
    name = "sgd"

    def apply(self, value, state, grad):
        cfg = self.cfg
        g = grad + cfg.l2 * value
        return value - cfg.learning_rate * g, state


class AdaGrad(ServerOptimizer):
    """AdaGrad with optional L1 truncation — the reference's async-SGD server
    rule for sparse LR (``src/app/linear_method/async_sgd.h`` [U])."""

    g0_stable = True
    name = "adagrad"

    def state_shapes(self):
        return {"sum_sq": 0.0}

    def apply(self, value, state, grad):
        cfg = self.cfg
        g = grad + cfg.l2 * value
        sum_sq = state["sum_sq"] + g * g
        lr = cfg.learning_rate / (jnp.sqrt(sum_sq) + cfg.eps)
        new = value - lr * g
        if cfg.l1 > 0:
            # soft-threshold (proximal L1): shrink toward zero by lr*l1
            new = jnp.sign(new) * jnp.maximum(jnp.abs(new) - lr * cfg.l1, 0.0)
        return new, {"sum_sq": sum_sq}


class Adam(ServerOptimizer):
    """Adam with per-row step counts (rows update at different rates under
    async sparse traffic, so a global step would mis-correct bias)."""

    name = "adam"

    def state_shapes(self):
        return {"m": 0.0, "v": 0.0, "t": 0.0}

    def apply(self, value, state, grad):
        cfg = self.cfg
        g = grad + cfg.l2 * value
        t = state["t"] + 1.0
        m = cfg.beta1 * state["m"] + (1 - cfg.beta1) * g
        v = cfg.beta2 * state["v"] + (1 - cfg.beta2) * g * g
        m_hat = m / (1 - cfg.beta1**t)
        v_hat = v / (1 - cfg.beta2**t)
        new = value - cfg.learning_rate * m_hat / (jnp.sqrt(v_hat) + cfg.eps)
        return new, {"m": m, "v": v, "t": t}


class FTRL(ServerOptimizer):
    """FTRL-proximal: value array stores ``z``; state stores ``n``.

    Push:  sigma = (sqrt(n + g^2) - sqrt(n)) / alpha
           z += g - sigma * w      (w = current lazy weight)
           n += g^2
    Pull:  w = 0                                  if |z| <= l1
           w = -(z - sign(z) l1) / ((beta + sqrt(n))/alpha + l2)  otherwise

    Matches the reference FTRLEntry update functor semantics [U].
    """

    g0_stable = True
    name = "ftrl"

    def state_shapes(self):
        return {"n": 0.0}

    def pull_weights(self, value, state):
        cfg = self.cfg
        z, n = value, state["n"]
        w = -(z - jnp.sign(z) * cfg.l1) / (
            (cfg.ftrl_beta + jnp.sqrt(n)) / cfg.ftrl_alpha + cfg.l2
        )
        return jnp.where(jnp.abs(z) <= cfg.l1, 0.0, w)

    def apply(self, value, state, grad):
        cfg = self.cfg
        z, n = value, state["n"]
        w = self.pull_weights(z, state)
        sigma = (jnp.sqrt(n + grad * grad) - jnp.sqrt(n)) / cfg.ftrl_alpha
        z = z + grad - sigma * w
        n = n + grad * grad
        return z, {"n": n}


_REGISTRY: Dict[str, Callable[[OptimizerConfig], ServerOptimizer]] = {
    "sgd": SGD,
    "adagrad": AdaGrad,
    "adam": Adam,
    "ftrl": FTRL,
}


def make_optimizer(cfg: OptimizerConfig) -> ServerOptimizer:
    try:
        return _REGISTRY[cfg.kind](cfg)
    except KeyError:
        raise ValueError(
            f"unknown optimizer {cfg.kind!r}; have {sorted(_REGISTRY)}"
        ) from None


def require_dense_apply(cfg: OptimizerConfig) -> None:
    """Validate that ``cfg`` is safe for the dense-apply (full-table) paths.

    Dense apply touches every row each step, so the update must be exactly
    zero at g=0: no penalties, and a ``g0_stable`` rule.
    """
    opt = make_optimizer(cfg)
    if cfg.l1 != 0.0 or cfg.l2 != 0.0 or not opt.g0_stable:
        raise ValueError(
            "dense-apply requires l1=l2=0 and a g0-stable optimizer "
            f"(got kind={cfg.kind!r}, l1={cfg.l1}, l2={cfg.l2}); "
            "use the row-apply path instead"
        )
