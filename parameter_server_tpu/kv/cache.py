"""HotRowCache: worker-side row cache with version-clock invalidation.

The serving plane (ISSUE 13) turns the worker into a read-mostly model
store: most pulls hit a small popular key set (Zipfian traffic), and the
PR-10 staleness plane already ships exactly the invalidation signal a
cache needs for free — every PUSH ack and PULL reply carries ``__sver__``,
the owning shard's per-segment version clock.  This module closes that
loop:

- entries are keyed ``(table, global row id)`` and stamped with the
  ``__sver__`` the row was fetched at plus the server it came from;
- a per-``(table, server)`` **watermark** tracks the highest ``__sver__``
  this worker has observed from that server on ANY reply — push acks,
  pull replies, and (since ISSUE 13) fence rejects all refresh it, so
  invalidation is piggybacked on traffic the worker already receives,
  never a broadcast;
- a lookup is a hit iff the entry came from the row's CURRENT owner and
  its stamp is not older than that owner's watermark.  The check is
  conservative: a write to any segment of the shard advances the shard's
  max clock and invalidates every cached row from that server, which may
  over-invalidate (a different segment was written) but can never serve a
  row staler than the watermark — the bounded-staleness contract the
  chaos tests assert.

Storage is a **direct-mapped arena** per table — parallel numpy vectors
``tags`` (global row id, -1 empty), ``svers``, ``owners`` (interned
server code) and a ``rows`` matrix, indexed by ``row_id & (capacity-1)``.
That makes the serving hot path (:meth:`lookup_many`) a handful of
vectorized compares and one fancy-index gather instead of a per-key
Python loop — the difference between a cache hit being ~10x cheaper than
the RPC it replaces and merely ~2x.  Eviction is by hash collision
(a new row landing on an occupied line overwrites it), which bounds
memory at ``capacity_rows`` lines per table with zero bookkeeping on the
hit path; collisions cost hit rate, never correctness.

Migration safety: entries remember their source server, so a row whose
range moved simply misses (new owner != entry server) even before the
worker clears the cache on routing-epoch adoption
(:meth:`~parameter_server_tpu.kv.worker.KVWorker.adopt_routing`).

Thread safety: lookups/inserts run on serving threads while watermarks
advance on the Van recv thread (``KVWorker._on_response``); one lock
covers both.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from parameter_server_tpu.core import flightrec


class _Arena:
    """Per-table direct-mapped store: parallel vectors over cache lines."""

    __slots__ = ("tags", "svers", "owners", "rows")

    def __init__(self, cap: int, dim: int, dtype) -> None:
        self.tags = np.full(cap, -1, dtype=np.int64)
        self.svers = np.zeros(cap, dtype=np.int64)
        self.owners = np.zeros(cap, dtype=np.int32)
        self.rows = np.zeros((cap, dim), dtype=dtype)


class HotRowCache:
    """Bounded direct-mapped ``(table, key) -> (row, sver, server)`` cache."""

    def __init__(
        self,
        capacity_rows: int = 65536,
        *,
        node: Optional[str] = None,
        audit: bool = False,
    ) -> None:
        cap = int(capacity_rows)
        #: lines per table, rounded up to a power of two so the index is a
        #: mask (``key & (cap - 1)``) instead of a modulo
        self.capacity_rows = (
            1 << (cap - 1).bit_length() if cap > 0 else 0
        )
        self._mask = self.capacity_rows - 1
        self.node = node
        self._arenas: Dict[str, _Arena] = {}
        #: server id string -> small dense code (arena ``owners`` entries)
        self._codes: Dict[str, int] = {}
        #: table -> watermark vector indexed by server code: the highest
        #: ``__sver__`` observed from that server on any reply
        self._wm: Dict[str, np.ndarray] = {}
        self._lock = threading.Lock()
        #: dashboard counters (Dashboard/telemetry-mergeable)
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        #: bounded-staleness audit trail (tests): every HIT appends
        #: ``(table, key, entry_sver, watermark_at_serve)`` — the invariant
        #: is ``entry_sver >= watermark_at_serve`` for every record.
        self.audit: Optional[List[tuple]] = [] if audit else None

    # -- server interning -----------------------------------------------------
    def _intern(self, server: str) -> int:
        """Dense code for a server id string (lock held by caller)."""
        code = self._codes.get(server)
        if code is None:
            code = len(self._codes)
            self._codes[server] = code
        return code

    def server_code(self, server: str) -> int:
        """Public interning entry point — lets the serving path translate
        owner strings to codes once per DISTINCT owner, then compare codes
        vectorized across the whole slot batch."""
        with self._lock:
            return self._intern(server)

    def _wm_vec(self, table: str) -> np.ndarray:
        """The table's watermark-by-code vector, grown to cover every
        interned code (lock held by caller)."""
        vec = self._wm.get(table)
        n = len(self._codes)
        if vec is None:
            vec = np.zeros(max(n, 1), dtype=np.int64)
            self._wm[table] = vec
        elif vec.shape[0] < n:
            vec = np.concatenate(
                [vec, np.zeros(n - vec.shape[0], dtype=np.int64)]
            )
            self._wm[table] = vec
        return vec

    # -- watermark (the piggybacked invalidation signal) ---------------------
    def observe(self, table: str, server: str, sver: int) -> None:
        """Advance the ``(table, server)`` watermark to at least ``sver``.

        Called from the worker's reply tap for every stamped reply; a
        lower/equal stamp (reordered reply) is a no-op — the watermark is
        monotone, matching the server clock it shadows.
        """
        with self._lock:
            code = self._intern(server)
            vec = self._wm_vec(table)
            if sver > vec[code]:
                vec[code] = int(sver)

    def watermark(self, table: str, server: str) -> int:
        with self._lock:
            code = self._intern(server)
            return int(self._wm_vec(table)[code])

    # -- lookup / insert ------------------------------------------------------
    def lookup_many(
        self, table: str, slots: np.ndarray, owner_codes: np.ndarray
    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Batched freshness-checked probe — the serving hot path.

        ``slots`` are global row ids (int64), ``owner_codes`` the parallel
        :meth:`server_code` of each row's CURRENT owner.  Returns
        ``(hit_mask, hit_rows)``: a boolean mask over ``slots`` and the
        cached rows for the hits in mask order (None when nothing hit).
        Semantics match per-key :meth:`lookup` — lazy eviction of
        moved/watermark-stale lines, counters, audit — but the whole batch
        costs one lock acquisition and a few vector ops.
        """
        n = int(slots.shape[0])
        with self._lock:
            ar = self._arenas.get(table)
            if ar is None or n == 0:
                self.misses += n
                return np.zeros(n, dtype=bool), None
            idx = slots & self._mask
            tags = ar.tags[idx]
            present = tags == slots
            wm = self._wm_vec(table)
            hit = present & (ar.owners[idx] == owner_codes)
            hit &= ar.svers[idx] >= wm[owner_codes]
            dead = present & ~hit
            if dead.any():
                # present but moved or watermark-stale: evict on the spot
                ar.tags[idx[dead]] = -1
                self.invalidations += int(dead.sum())
            n_hit = int(hit.sum())
            self.hits += n_hit
            self.misses += n - n_hit
            hit_rows = ar.rows[idx[hit]] if n_hit else None
            if self.audit is not None and n_hit:
                hi = idx[hit]
                for sl, sv, oc in zip(
                    slots[hit].tolist(),
                    ar.svers[hi].tolist(),
                    ar.owners[hi].tolist(),
                ):
                    self.audit.append((table, sl, sv, int(wm[oc])))
        return hit, hit_rows

    def lookup(self, table: str, key: int, owner: str):
        """The cached row for ``(table, key)`` iff still fresh, else None.

        Fresh means: cached from the row's CURRENT owner AND stamped at or
        above that owner's watermark.  A stale line is evicted on the spot
        (lazy invalidation — the watermark advance itself never walks
        lines).  Scalar convenience over :meth:`lookup_many`.
        """
        k = int(key)
        with self._lock:
            ar = self._arenas.get(table)
            if ar is None:
                self.misses += 1
                return None
            i = k & self._mask
            if int(ar.tags[i]) != k:
                self.misses += 1
                return None
            code = self._intern(owner)
            wm = int(self._wm_vec(table)[code])
            if int(ar.owners[i]) != code or int(ar.svers[i]) < wm:
                # the range moved (or the shard clock passed it): dead line
                ar.tags[i] = -1
                self.invalidations += 1
                self.misses += 1
                return None
            self.hits += 1
            if self.audit is not None:
                self.audit.append((table, k, int(ar.svers[i]), wm))
            return ar.rows[i].copy()

    def lookup_stale(self, table: str, key: int):
        """The cached row regardless of watermark/owner — the "stale" shed
        policy's degraded serve.  Returns ``(row, sver)`` or None."""
        k = int(key)
        with self._lock:
            ar = self._arenas.get(table)
            if ar is None:
                return None
            i = k & self._mask
            if int(ar.tags[i]) != k:
                return None
            return ar.rows[i].copy(), int(ar.svers[i])

    def insert(
        self, table: str, keys: np.ndarray, rows: np.ndarray,
        sver: int, server: str,
    ) -> None:
        """Cache fetched rows at the ``__sver__`` their reply carried.

        ``rows[i]`` is the value for ``keys[i]``; rows are copied into the
        arena so entries never alias a (possibly wire-view) reply buffer.
        A line holding the SAME key at a strictly fresher stamp is kept (a
        reordered stale reply must not regress the cache); a different key
        on the line is simply overwritten — collision eviction.
        """
        if self.capacity_rows <= 0:
            return
        keys = np.asarray(keys, dtype=np.int64)
        rows = np.asarray(rows)
        sver = int(sver)
        with self._lock:
            code = self._intern(server)
            ar = self._arenas.get(table)
            if ar is None:
                ar = _Arena(
                    self.capacity_rows, int(rows.shape[-1]), rows.dtype
                )
                self._arenas[table] = ar
            idx = keys & self._mask
            fresher = (ar.tags[idx] == keys) & (ar.svers[idx] > sver)
            if fresher.any():
                keep = ~fresher
                keys, idx, rows = keys[keep], idx[keep], rows[keep]
            ar.tags[idx] = keys
            ar.svers[idx] = sver
            ar.owners[idx] = code
            ar.rows[idx] = rows

    def invalidate_all(self, reason: str = "explicit") -> int:
        """Drop every entry (e.g. on routing-epoch adoption); returns the
        number dropped.  Watermarks survive — they shadow server clocks,
        which do not reset on migration (``_install_routing`` carries each
        shard's max forward)."""
        with self._lock:
            n = 0
            for ar in self._arenas.values():
                n += int((ar.tags != -1).sum())
                ar.tags.fill(-1)
            self.invalidations += n
        if n:
            flightrec.record(
                "cache.invalidate", node=self.node, n=n, reason=reason
            )
        return n

    # -- introspection --------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return sum(
                int((ar.tags != -1).sum()) for ar in self._arenas.values()
            )

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def counters(self) -> dict:
        """Dashboard/telemetry-mergeable counters (+ the entries gauge)."""
        return {
            "cache_hits": self.hits,
            "cache_misses": self.misses,
            "cache_invalidations": self.invalidations,
            "cache_entries": len(self),
        }
