"""Dense-tensor KV store: whole-model chunks across servers (KVLayer/KVStore).

The reference chunks big dense tensors (NN layers) across servers so workers
push gradients / pull weights for entire layers (``src/parameter/kv_store.h``,
``kv_layer.h`` [U]).  TPU-native version: the model's parameter pytree is
flattened to one contiguous float32 vector; servers own contiguous segments
(the NodeAssigner range scheme on *element offsets* instead of keys) stored
on device with row-wise optimizer state; workers push/pull either the whole
vector or per-segment slices through the Van with the usual timestamp API.

Segment (per-layer chunk) traffic is the spine of BASELINE config #4 (BERT
async push/pull of dense layers; VERDICT r2 missing #2): a whole-vector
BERT-base push is ~440 MB per worker per step — infeasible over DCN — while
per-segment pushes bound each message, give the transport chances to
pipeline (>= 2 chunks in flight), and let the next step's pulls start as
individual push acks arrive instead of after the full vector lands.  The
server applies a segment push to just that element range of its shard
(``jax.lax.dynamic_update_slice`` on a donated buffer: one compiled step per
distinct segment length, offsets traced).

This module is the Van-mode counterpart of the pure-GSPMD DP trainer in
``learner/dense.py``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from parameter_server_tpu.config import OptimizerConfig
from parameter_server_tpu.core.messages import Message, Task, TaskKind, server_id
from parameter_server_tpu.core.postoffice import Customer, Postoffice
from parameter_server_tpu.kv.optim import ServerOptimizer, make_optimizer
from parameter_server_tpu.kv.partition import RangePartition


def segment_offsets(total: int, num_servers: int) -> np.ndarray:
    """num_servers+1 element offsets; server s owns [off[s], off[s+1]).

    Delegates to :class:`RangePartition` so every layer (manager key ranges,
    sparse tables, dense segments) splits by the identical rule.
    """
    return RangePartition(total, num_servers).offsets


def fixed_segments(total: int, chunk_elems: int) -> List[Tuple[int, int]]:
    """Equal-size element segments [(start, end), ...] covering ``total``.

    The KVStore-style chunking: every segment (except the tail) is exactly
    ``chunk_elems`` long, so the server compiles at most a handful of
    slice-apply kernels regardless of layer structure.
    """
    if chunk_elems <= 0:
        raise ValueError(f"chunk_elems must be positive, got {chunk_elems}")
    return [
        (a, min(a + chunk_elems, total)) for a in range(0, total, chunk_elems)
    ]


def layer_segments(example_tree, max_elems: int = 1 << 22) -> List[Tuple[int, int]]:
    """Per-layer segments over the flattened pytree (the KVLayer scheme).

    Leaves coalesce greedily into segments up to ``max_elems``; an oversize
    leaf (a big embedding/ffn matrix) splits into ``max_elems`` chunks.
    Boundaries follow the same leaf order ``ravel_pytree`` flattens with, so
    segment [a, b) is exactly vector[a:b].
    """
    sizes = [int(np.prod(np.shape(leaf))) for leaf in jax.tree.leaves(example_tree)]
    segs: List[Tuple[int, int]] = []
    start = 0
    acc = 0
    pos = 0
    for sz in sizes:
        if acc and acc + sz > max_elems:
            segs.append((start, pos))
            start, acc = pos, 0
        if sz > max_elems:  # split the giant leaf on its own
            if acc:
                segs.append((start, pos))
            for a in range(pos, pos + sz, max_elems):
                segs.append((a, min(a + max_elems, pos + sz)))
            start, acc = pos + sz, 0
        else:
            acc += sz
        pos += sz
    if acc:
        segs.append((start, pos))
    return segs


def _apply_slice(opt: ServerOptimizer, value, state, grad, off):
    """Optimizer step on rows [off, off+len(grad)) of the local shard.

    Offset is traced (no recompile per segment position); length is static
    via the grad shape.  Donated buffers keep the update in place in HBM.
    """
    n = grad.shape[0]
    v = jax.lax.dynamic_slice(value, (off, 0), (n, 1))
    s = {k: jax.lax.dynamic_slice(state[k], (off, 0), (n, 1)) for k in state}
    nv, ns = opt.apply(v, s, grad)
    value = jax.lax.dynamic_update_slice(value, nv, (off, 0))
    state = {
        k: jax.lax.dynamic_update_slice(state[k], ns[k], (off, 0)) for k in state
    }
    return value, state


def _pull_slice(opt: ServerOptimizer, value, state, off, length: int):
    v = jax.lax.dynamic_slice(value, (off, 0), (length, 1))
    s = {k: jax.lax.dynamic_slice(state[k], (off, 0), (length, 1)) for k in state}
    return opt.pull_weights(v, s)


class DenseKVServer(Customer):
    """Owns one contiguous segment of each registered dense parameter vector."""

    def __init__(
        self,
        post: Postoffice,
        specs: Dict[str, Tuple[int, OptimizerConfig]],
        server_index: int,
        num_servers: int,
        *,
        name: str = "dense",
        init_vectors: Optional[Dict[str, np.ndarray]] = None,
    ) -> None:
        """``specs``: table name -> (total_elements, optimizer config)."""
        super().__init__(name, post)
        self.server_index = server_index
        self.num_servers = num_servers
        self.offsets: Dict[str, np.ndarray] = {}
        self.segments: Dict[str, dict] = {}
        for t, (total, opt_cfg) in specs.items():
            off = segment_offsets(total, num_servers)
            self.offsets[t] = off
            lo, hi = int(off[server_index]), int(off[server_index + 1])
            opt = make_optimizer(opt_cfg)
            if init_vectors and t in init_vectors:
                value = jnp.asarray(init_vectors[t][lo:hi], jnp.float32)
            else:
                value = jnp.zeros(hi - lo, jnp.float32)
            self.segments[t] = {
                "opt": opt,
                "value": value.reshape(-1, 1),
                "state": {
                    k: jnp.full((hi - lo, 1), fill, jnp.float32)
                    for k, fill in opt.state_shapes().items()
                },
                "apply": jax.jit(
                    lambda v, s, g, _opt=opt: _opt.apply(v, s, g),
                    donate_argnums=(0, 1),
                ),
                "pull": jax.jit(lambda v, s, _opt=opt: _opt.pull_weights(v, s)),
                # per-segment (KVLayer) ops: offset traced, length static ->
                # one compile per distinct segment length, not per offset
                "apply_slice": jax.jit(
                    lambda v, s, g, off, _opt=opt: _apply_slice(_opt, v, s, g, off),
                    donate_argnums=(0, 1),
                ),
                "pull_slice": jax.jit(
                    lambda v, s, off, _opt=opt, *, length: _pull_slice(
                        _opt, v, s, off, length
                    ),
                    static_argnames=("length",),
                ),
            }

    def handle_request(self, msg: Message) -> Message:
        if msg.task.kind == TaskKind.CONTROL:
            return self._handle_control(msg)
        seg = self.segments[msg.task.payload["table"]]
        offset = msg.task.payload.get("offset")  # segment traffic when set
        if msg.task.kind == TaskKind.PUSH:
            grad = jnp.asarray(msg.values[0]).reshape(-1, 1)
            if offset is None:
                seg["value"], seg["state"] = seg["apply"](
                    seg["value"], seg["state"], grad
                )
            else:
                local = offset - int(
                    self.offsets[msg.task.payload["table"]][self.server_index]
                )
                seg["value"], seg["state"] = seg["apply_slice"](
                    seg["value"], seg["state"], grad, jnp.int32(local)
                )
            return msg.reply()
        elif msg.task.kind == TaskKind.PULL:
            if offset is None:
                w = seg["pull"](seg["value"], seg["state"])
            else:
                local = offset - int(
                    self.offsets[msg.task.payload["table"]][self.server_index]
                )
                w = seg["pull_slice"](
                    seg["value"],
                    seg["state"],
                    jnp.int32(local),
                    length=int(msg.task.payload["length"]),
                )
            return msg.reply(values=[np.asarray(w).ravel()])
        raise ValueError(f"unsupported task kind {msg.task.kind}")

    # -- checkpoint (dense analogue of KVServer's SaveModel path) ------------
    def _handle_control(self, msg: Message) -> Message:
        op = msg.task.payload.get("op")
        if op == "save_model":
            self.save_checkpoint(msg.task.payload["root"], msg.task.payload["step"])
            return msg.reply()
        if op == "load_model":
            self.restore_checkpoint(msg.task.payload["root"], msg.task.payload["step"])
            return msg.reply()
        raise ValueError(f"unsupported control op {op!r}")

    def save_checkpoint(self, root: str, step: int) -> None:
        """Write this server's element-range of every dense vector."""
        from parameter_server_tpu import checkpoint

        for t, seg in self.segments.items():
            checkpoint.save_arrays_shard(
                root,
                step,
                t,
                self.server_index,
                self.num_servers,
                int(self.offsets[t][self.server_index]),
                np.asarray(seg["value"]),
                {k: np.asarray(v) for k, v in seg["state"].items()},
            )

    def restore_checkpoint(self, root: str, step: int) -> None:
        """Load this server's element-range (saved server count may differ)."""
        from parameter_server_tpu import checkpoint

        for t, seg in self.segments.items():
            arrays = checkpoint.load_arrays_shard(
                root, step, t, self.server_index, self.num_servers
            )
            seg["value"] = jnp.asarray(arrays["value"], jnp.float32)
            seg["state"] = {
                k: jnp.asarray(arrays[f"state.{k}"], jnp.float32)
                for k in seg["state"]
            }


class DenseKVWorker(Customer):
    """Push/pull whole flattened parameter vectors with timestamps."""

    def __init__(
        self,
        post: Postoffice,
        specs: Dict[str, int],
        num_servers: int,
        *,
        name: str = "dense",
    ) -> None:
        """``specs``: table name -> total_elements."""
        super().__init__(name, post)
        self.offsets = {
            t: segment_offsets(total, num_servers) for t, total in specs.items()
        }
        self.num_servers = num_servers
        self._pull_meta: Dict[int, str] = {}
        self._seg_pull_meta: Dict[int, dict] = {}
        #: raw (pre-filter) byte counters for the dashboard's bytes/step
        #: accounting (the reference network_usage.h role; VERDICT r2 #1).
        self.bytes_pushed = 0
        self.bytes_pulled = 0

    def push(self, table: str, grad_vector: np.ndarray) -> int:
        off = self.offsets[table]
        msgs = [
            Message(
                task=Task(TaskKind.PUSH, self.name, payload={"table": table}),
                recver=server_id(s),
                values=[np.asarray(grad_vector[off[s] : off[s + 1]], np.float32)],
            )
            for s in range(self.num_servers)
        ]
        self.bytes_pushed += int(np.asarray(grad_vector).nbytes)
        return self.submit(msgs)

    # -- per-segment (KVLayer chunk) traffic ---------------------------------
    def push_segment(
        self,
        table: str,
        start: int,
        grad_slice: np.ndarray,
        callback=None,
    ) -> int:
        """Push the gradient for elements [start, start+len).  Returns ts.

        One timestamp per segment: the caller streams segments while earlier
        ones are still in flight (the bounded-delay chunk pipeline), and an
        optional ``callback`` fires on the ack — the hook the learner uses to
        start the NEXT step's pull of the same segment immediately.
        """
        grad_slice = np.asarray(grad_slice, np.float32)
        off = self.offsets[table]
        end = start + grad_slice.shape[0]
        msgs = []
        for s in range(self.num_servers):
            a, b = max(start, int(off[s])), min(end, int(off[s + 1]))
            if a >= b:
                continue
            msgs.append(
                Message(
                    task=Task(
                        TaskKind.PUSH,
                        self.name,
                        payload={"table": table, "offset": a},
                    ),
                    recver=server_id(s),
                    values=[grad_slice[a - start : b - start]],
                )
            )
        self.bytes_pushed += int(grad_slice.nbytes)
        return self.submit(msgs, callback)

    def pull_segment(self, table: str, start: int, length: int) -> int:
        """Request weights for elements [start, start+length)."""
        off = self.offsets[table]
        end = start + length
        msgs = []
        order = {}
        for s in range(self.num_servers):
            a, b = max(start, int(off[s])), min(end, int(off[s + 1]))
            if a >= b:
                continue
            order[server_id(s)] = (a - start, b - start)
            msgs.append(
                Message(
                    task=Task(
                        TaskKind.PULL,
                        self.name,
                        payload={"table": table, "offset": a, "length": b - a},
                    ),
                    recver=server_id(s),
                )
            )
        ts = self.submit(msgs, keep_responses=True)
        self._seg_pull_meta[ts] = {"order": order, "length": length}
        return ts

    def pull_segment_result(
        self, ts: int, timeout: Optional[float] = None
    ) -> np.ndarray:
        completed = self.wait(ts, timeout)
        plan = self._seg_pull_meta.pop(ts)  # always reclaim
        errs = self.errors(ts)
        responses = self.take_responses(ts)  # always drain kept state
        if not completed:
            raise TimeoutError(f"segment pull ts={ts} timed out")
        if errs:  # a dropped leg must not read as zero parameters
            raise RuntimeError(
                f"segment pull ts={ts} failed on: " + "; ".join(errs)
            )
        if len(responses) < len(plan["order"]):
            raise RuntimeError(
                f"segment pull ts={ts} incomplete: {len(responses)}/"
                f"{len(plan['order'])} servers answered (dead server?)"
            )
        out = np.zeros(plan["length"], np.float32)
        for resp in responses:
            a, b = plan["order"][resp.sender]
            out[a:b] = resp.values[0]
        self.bytes_pulled += int(out.nbytes)
        return out

    def pull(self, table: str) -> int:
        msgs = [
            Message(
                task=Task(TaskKind.PULL, self.name, payload={"table": table}),
                recver=server_id(s),
            )
            for s in range(self.num_servers)
        ]
        ts = self.submit(msgs, keep_responses=True)
        self._pull_meta[ts] = table
        return ts

    def pull_result(self, ts: int, timeout: Optional[float] = None) -> np.ndarray:
        completed = self.wait(ts, timeout)
        table = self._pull_meta.pop(ts)  # always reclaim
        errs = self.errors(ts)
        responses = self.take_responses(ts)  # always drain kept state
        if not completed:
            raise TimeoutError(f"dense pull ts={ts} timed out")
        if errs:  # a dropped leg must not read as zero parameters
            raise RuntimeError(f"dense pull ts={ts} failed on: " + "; ".join(errs))
        if len(responses) < self.num_servers:
            raise RuntimeError(
                f"dense pull ts={ts} incomplete: {len(responses)}/"
                f"{self.num_servers} servers answered (dead server?)"
            )
        off = self.offsets[table]
        out = np.zeros(off[-1], np.float32)
        for resp in responses:
            s = int(resp.sender[1:])
            out[off[s] : off[s + 1]] = resp.values[0]
        return out

    def pull_sync(self, table: str, timeout: Optional[float] = None) -> np.ndarray:
        return self.pull_result(self.pull(table), timeout)

    # -- checkpoint broadcast (mirrors KVWorker.save_model/load_model) -------
    def save_model(
        self,
        root: str,
        step: int,
        *,
        clocks: Optional[List[int]] = None,
        extras: Optional[dict] = None,
        timeout: Optional[float] = 600.0,
    ) -> None:
        """All servers write their element-ranges; then commit the manifest.

        Use a root distinct from any sparse-table checkpoint root (one
        manifest lists one worker's tables).
        """
        from parameter_server_tpu import checkpoint

        ts = self._broadcast_control("save_model", {"root": root, "step": step})
        if not self.wait(ts, timeout):
            raise TimeoutError("dense save_model timed out")
        self.check(ts)
        self.take_responses(ts)
        checkpoint.finalize(
            root,
            step,
            self.num_servers,
            {t: int(off[-1]) for t, off in self.offsets.items()},
            clocks=clocks,
            extras=extras,
        )

    def load_model(
        self, root: str, step: int, *, timeout: Optional[float] = 600.0
    ) -> None:
        ts = self._broadcast_control("load_model", {"root": root, "step": step})
        if not self.wait(ts, timeout):
            raise TimeoutError("dense load_model timed out")
        self.check(ts)
        self.take_responses(ts)

    def _broadcast_control(self, op: str, payload: dict) -> int:
        msgs = [
            Message(
                task=Task(
                    TaskKind.CONTROL, self.name, payload={"op": op, **payload}
                ),
                recver=server_id(s),
            )
            for s in range(self.num_servers)
        ]
        return self.submit(msgs, keep_responses=True)


class PytreeCodec:
    """Flatten/unflatten a parameter pytree to the store's flat vector."""

    def __init__(self, example_tree) -> None:
        flat, self.unravel = ravel_pytree(example_tree)
        self.total = int(flat.shape[0])

    def flatten(self, tree) -> np.ndarray:
        return np.asarray(ravel_pytree(tree)[0], np.float32)

    def unflatten(self, vector: np.ndarray):
        return self.unravel(jnp.asarray(vector, jnp.float32))
