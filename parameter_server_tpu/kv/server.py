"""KVServer: the server-role Customer owning table shards.

Reference analogue: the server process's ``Parameter`` subclass answering
Push with ``SetValue`` (merge + update) and Pull with ``GetValue`` (gather)
(``src/parameter/parameter.h`` [U]).  Each KVServer instance owns the local
row-range shard of every registered table; requests arrive through the Van
recv thread (one per node — the reference's single-Executor-thread model, so
table mutation is single-threaded by construction) and the actual math runs
as the KVTable's jit-compiled device steps.
"""

from __future__ import annotations

import collections
import zlib
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from parameter_server_tpu.config import TableConfig
from parameter_server_tpu.core.messages import Message, Task, TaskKind
from parameter_server_tpu.core.postoffice import Customer, Postoffice
from parameter_server_tpu.kv.partition import RangePartition
from parameter_server_tpu.kv.table import KVTable
from parameter_server_tpu.utils.keys import bucket_size
from parameter_server_tpu.utils.trace import NULL_TRACER, Tracer


def _bucket(n: int) -> int:
    """Server-side id bucket: next power of two, >= 8 (pallas block floor)."""
    return bucket_size(max(n, 1), min_bucket=8)


class KVServer(Customer):
    """Server-side customer: routes Push/Pull to local table shards."""

    def __init__(
        self,
        post: Postoffice,
        table_cfgs: Dict[str, TableConfig],
        server_index: int,
        num_servers: int,
        *,
        name: str = "kv",
        tracer: Tracer = NULL_TRACER,
        device_replies: bool = False,
        replica: Optional[str] = None,
        replica_sync: bool = False,
        max_replica_lag: int = 8,
        replica_ack_timeout: float = 60.0,
    ) -> None:
        """``replica``: node id of a hot-standby KVServer holding the same
        shard (chain replication of key ranges, the reference paper's §4.3
        recovery [U]; VERDICT r3 #6).  Every applied push is forwarded to
        it in apply order, so the standby's table+optimizer state tracks the
        primary's exactly.  ``replica_sync=True`` = chain semantics: the
        worker's ack only fires after the replica applied (ZERO update loss
        on primary death); ``False`` = async forwarding with at most
        ``max_replica_lag`` pushes in flight (bounded loss, no added push
        latency).  On death, :func:`parameter_server_tpu.kv.replica.promote`
        rebinds the standby under the primary's node id."""
        super().__init__(name, post)
        #: reply to pulls with device arrays instead of host numpy — the
        #: zero-copy mode for in-process (Loopback) planes where worker and
        #: server share the device; cross-host Vans keep numpy replies.
        self.device_replies = device_replies
        self.server_index = server_index
        self.partitions = {
            t: RangePartition(cfg.rows, num_servers) for t, cfg in table_cfgs.items()
        }
        self.tables: Dict[str, KVTable] = {
            t: KVTable(
                cfg,
                rows=self.partitions[t].server_rows(server_index),
                # stable across OS processes (builtin str hash is salted per
                # interpreter — servers spawned as separate processes would
                # init different rows than an in-process cluster, breaking
                # cross-deployment loss parity and restart determinism)
                seed=zlib.crc32(f"{t}:{server_index}".encode()) & 0x7FFFFFFF,
            )
            for t, cfg in table_cfgs.items()
        }
        #: dashboard counters
        self.pushes = 0
        self.pulls = 0
        self.tracer = tracer
        # -- hot-replica forwarding channel ---------------------------------
        self.replica = replica
        self.replica_sync = replica_sync
        self.max_replica_lag = max_replica_lag
        self.replica_ack_timeout = replica_ack_timeout
        self._fwd_inflight: collections.deque[int] = collections.deque()
        if replica is not None:
            # A DEDICATED endpoint for the primary's client role: waiting
            # for replica acks on the server's own recv thread would
            # deadlock (that thread must process the ack).  The forwarding
            # Customer shares this server's customer name so the replica
            # routes the forwarded pushes into its normal kv handler.
            self._fwd_post = Postoffice(f"{post.node_id}.fw", post.van)
            self._fwd = Customer(name, self._fwd_post)

    def _forward_push(self, tname: str, msg: Message) -> None:
        fwd = Message(
            task=Task(TaskKind.PUSH, self._fwd.name, payload={"table": tname}),
            recver=self.replica,
            keys=np.asarray(msg.keys),
            values=[np.asarray(msg.values[0])],
        )
        ts = self._fwd.submit([fwd])
        if self.replica_sync:
            if not self._fwd.wait(ts, timeout=self.replica_ack_timeout):
                # deadline: free the stuck task before failing the push —
                # the fwd customer must not leak _pending state per timeout
                self._fwd.cancel(ts, "replica ack deadline")
                raise RuntimeError(
                    f"replica {self.replica} did not ack push (sync chain)"
                )
            self._fwd.check(ts)
        else:
            self._fwd_inflight.append(ts)
            while len(self._fwd_inflight) > self.max_replica_lag:
                old = self._fwd_inflight.popleft()
                if not self._fwd.wait(old, timeout=self.replica_ack_timeout):
                    self._fwd.cancel(old, "replica ack deadline")
                    raise RuntimeError(
                        f"replica {self.replica} lag exceeded "
                        f"{self.max_replica_lag} and oldest ack timed out"
                    )

    def flush_replica(self, timeout: float = 60.0) -> None:
        """Block until every async-forwarded push is acked by the replica."""
        while self._fwd_inflight:
            old = self._fwd_inflight.popleft()
            if not self._fwd.wait(old, timeout):
                self._fwd.cancel(old, "replica flush deadline")
                raise RuntimeError(f"replica flush: ts={old} not acked")

    def handle_request(self, msg: Message) -> Message:
        if msg.task.kind == TaskKind.CONTROL:
            return self._handle_control(msg)
        tname = msg.task.payload["table"]
        table = self.tables[tname]
        # cross-node stitching: echo the worker's trace context onto this
        # handler's spans so merge_traces can pair both ends of the request
        tctx = msg.task.payload.get("__trace__") or {}
        span_attrs = {"table": tname}
        if tctx.get("tid"):
            span_attrs["trace"] = tctx["tid"]
            span_attrs["origin"] = tctx.get("origin")
        # Bucket-pad the slice to a power of two: the worker bucket-pads its
        # unique slots, but the per-server split (Parameter::Slice) produces
        # arbitrary lengths again — without this every distinct length
        # compiles a fresh device step, and the pallas kernels (block DMA)
        # reject unaligned id vectors outright.  Pads route to the trash row
        # with zero gradients (the established PAD contract).
        n = int(np.asarray(msg.keys).shape[0])
        b = _bucket(n)
        ids_np = np.full(b, table.rows, dtype=np.int32)
        ids_np[:n] = msg.keys
        ids = jnp.asarray(ids_np)
        if msg.task.kind == TaskKind.PUSH:
            vals = msg.values[0]
            if isinstance(vals, jax.Array):  # device push: pad on device
                if b != n:
                    zeros = jnp.zeros((b - n,) + vals.shape[1:], vals.dtype)
                    vals = jnp.concatenate([vals, zeros])
            else:
                vals = np.asarray(vals)
                if b != n:
                    padded = np.zeros((b,) + vals.shape[1:], dtype=vals.dtype)
                    padded[:n] = vals
                    vals = padded
            with self.tracer.span("kv.server.push", **span_attrs):
                table.push(ids, jnp.asarray(vals))
            self.pushes += 1
            if self.replica is not None:
                # forward AFTER the local apply, in apply order (this recv
                # thread is the only writer), so the standby replays the
                # identical update sequence
                self._forward_push(tname, msg)
            return msg.reply()
        elif msg.task.kind == TaskKind.PULL:
            with self.tracer.span("kv.server.pull", **span_attrs):
                rows = table.pull(ids)
            self.pulls += 1
            if self.device_replies:
                return msg.reply(values=[rows[:n]])
            return msg.reply(values=[np.asarray(rows)[:n]])
        raise ValueError(f"unsupported task kind {msg.task.kind}")

    # -- shard transfer (same-id restart: kv/replica.restart_same_id) --------
    def export_shard(self) -> Dict[str, dict]:
        """Host-side snapshot of every table shard: value + optimizer state.

        The live-donor half of same-id restart recovery: a hot standby
        exports, the restarted primary imports, and the pair is bit-identical
        — including optimizer accumulators, which the wire protocol never
        carries (only the chain forwarding replays them).
        """
        return {
            t: {
                "value": np.asarray(table.value),
                "state": {k: np.asarray(v) for k, v in table.state.items()},
            }
            for t, table in self.tables.items()
        }

    def import_shard(self, shard: Dict[str, dict]) -> None:
        """Adopt an :meth:`export_shard` snapshot wholesale.

        Row ranges must match (same ``server_index``/``num_servers``); the
        donated push buffers are simply replaced, so the next push jit-step
        runs on the imported arrays.
        """
        for t, blob in shard.items():
            table = self.tables[t]
            table.value = jnp.asarray(blob["value"])
            table.state = {
                k: jnp.asarray(v) for k, v in blob["state"].items()
            }

    # -- checkpoint (reference SaveModel task: servers write their key-range
    # to file; src/app/linear_method/model_evaluation.h [U]) -----------------
    def _handle_control(self, msg: Message) -> Message:
        op = msg.task.payload.get("op")
        if op == "save_model":
            self.save_checkpoint(msg.task.payload["root"], msg.task.payload["step"])
            return msg.reply()
        if op == "load_model":
            self.restore_checkpoint(msg.task.payload["root"], msg.task.payload["step"])
            return msg.reply()
        raise ValueError(f"unsupported control op {op!r}")

    def save_checkpoint(self, root: str, step: int) -> None:
        """Write this server's row-range of every table (value + opt state)."""
        from parameter_server_tpu import checkpoint

        for t, table in self.tables.items():
            part = self.partitions[t]
            checkpoint.save_shard(
                root,
                step,
                t,
                table,
                self.server_index,
                part.num_servers,
                int(part.offsets[self.server_index]),
            )

    def restore_checkpoint(self, root: str, step: int) -> None:
        """Load this server's row-range; the saved server count may differ."""
        from parameter_server_tpu import checkpoint

        for t, table in self.tables.items():
            checkpoint.restore_shard(
                root, step, t, table, self.server_index, self.partitions[t].num_servers
            )
