"""KVServer: the server-role Customer owning table shards.

Reference analogue: the server process's ``Parameter`` subclass answering
Push with ``SetValue`` (merge + update) and Pull with ``GetValue`` (gather)
(``src/parameter/parameter.h`` [U]).  Each KVServer instance owns the local
row-range shard of every registered table; requests arrive through the Van
recv thread (one per node — the reference's single-Executor-thread model, so
table mutation is single-threaded by construction) and the actual math runs
as the KVTable's jit-compiled device steps.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from parameter_server_tpu.config import TableConfig
from parameter_server_tpu.core.messages import Message, TaskKind
from parameter_server_tpu.core.postoffice import Customer, Postoffice
from parameter_server_tpu.kv.partition import RangePartition
from parameter_server_tpu.kv.table import KVTable


class KVServer(Customer):
    """Server-side customer: routes Push/Pull to local table shards."""

    def __init__(
        self,
        post: Postoffice,
        table_cfgs: Dict[str, TableConfig],
        server_index: int,
        num_servers: int,
        *,
        name: str = "kv",
    ) -> None:
        super().__init__(name, post)
        self.server_index = server_index
        self.partitions = {
            t: RangePartition(cfg.rows, num_servers) for t, cfg in table_cfgs.items()
        }
        self.tables: Dict[str, KVTable] = {
            t: KVTable(
                cfg,
                rows=self.partitions[t].server_rows(server_index),
                seed=hash((t, server_index)) & 0x7FFFFFFF,
            )
            for t, cfg in table_cfgs.items()
        }
        #: dashboard counters
        self.pushes = 0
        self.pulls = 0

    def handle_request(self, msg: Message) -> Message:
        table = self.tables[msg.task.payload["table"]]
        ids = jnp.asarray(msg.keys)
        if msg.task.kind == TaskKind.PUSH:
            table.push(ids, jnp.asarray(msg.values[0]))
            self.pushes += 1
            return msg.reply()
        elif msg.task.kind == TaskKind.PULL:
            rows = table.pull(ids)
            self.pulls += 1
            return msg.reply(values=[np.asarray(rows)])
        raise ValueError(f"unsupported task kind {msg.task.kind}")
