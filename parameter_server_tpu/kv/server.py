"""KVServer: the server-role Customer owning table shards.

Reference analogue: the server process's ``Parameter`` subclass answering
Push with ``SetValue`` (merge + update) and Pull with ``GetValue`` (gather)
(``src/parameter/parameter.h`` [U]).  Each KVServer instance owns the local
row-range shard of every registered table; requests arrive through the Van
recv thread (one per node — the reference's single-Executor-thread model, so
table mutation is single-threaded by construction) and the actual math runs
as the KVTable's jit-compiled device steps.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from parameter_server_tpu.config import TableConfig
from parameter_server_tpu.core.messages import Message, TaskKind
from parameter_server_tpu.core.postoffice import Customer, Postoffice
from parameter_server_tpu.kv.partition import RangePartition
from parameter_server_tpu.kv.table import KVTable
from parameter_server_tpu.utils.trace import NULL_TRACER, Tracer


class KVServer(Customer):
    """Server-side customer: routes Push/Pull to local table shards."""

    def __init__(
        self,
        post: Postoffice,
        table_cfgs: Dict[str, TableConfig],
        server_index: int,
        num_servers: int,
        *,
        name: str = "kv",
        tracer: Tracer = NULL_TRACER,
    ) -> None:
        super().__init__(name, post)
        self.server_index = server_index
        self.partitions = {
            t: RangePartition(cfg.rows, num_servers) for t, cfg in table_cfgs.items()
        }
        self.tables: Dict[str, KVTable] = {
            t: KVTable(
                cfg,
                rows=self.partitions[t].server_rows(server_index),
                seed=hash((t, server_index)) & 0x7FFFFFFF,
            )
            for t, cfg in table_cfgs.items()
        }
        #: dashboard counters
        self.pushes = 0
        self.pulls = 0
        self.tracer = tracer

    def handle_request(self, msg: Message) -> Message:
        if msg.task.kind == TaskKind.CONTROL:
            return self._handle_control(msg)
        tname = msg.task.payload["table"]
        table = self.tables[tname]
        ids = jnp.asarray(msg.keys)
        if msg.task.kind == TaskKind.PUSH:
            with self.tracer.span("kv.server.push", table=tname):
                table.push(ids, jnp.asarray(msg.values[0]))
            self.pushes += 1
            return msg.reply()
        elif msg.task.kind == TaskKind.PULL:
            with self.tracer.span("kv.server.pull", table=tname):
                rows = table.pull(ids)
            self.pulls += 1
            return msg.reply(values=[np.asarray(rows)])
        raise ValueError(f"unsupported task kind {msg.task.kind}")

    # -- checkpoint (reference SaveModel task: servers write their key-range
    # to file; src/app/linear_method/model_evaluation.h [U]) -----------------
    def _handle_control(self, msg: Message) -> Message:
        op = msg.task.payload.get("op")
        if op == "save_model":
            self.save_checkpoint(msg.task.payload["root"], msg.task.payload["step"])
            return msg.reply()
        if op == "load_model":
            self.restore_checkpoint(msg.task.payload["root"], msg.task.payload["step"])
            return msg.reply()
        raise ValueError(f"unsupported control op {op!r}")

    def save_checkpoint(self, root: str, step: int) -> None:
        """Write this server's row-range of every table (value + opt state)."""
        from parameter_server_tpu import checkpoint

        for t, table in self.tables.items():
            part = self.partitions[t]
            checkpoint.save_shard(
                root,
                step,
                t,
                table,
                self.server_index,
                part.num_servers,
                int(part.offsets[self.server_index]),
            )

    def restore_checkpoint(self, root: str, step: int) -> None:
        """Load this server's row-range; the saved server count may differ."""
        from parameter_server_tpu import checkpoint

        for t, table in self.tables.items():
            checkpoint.restore_shard(
                root, step, t, table, self.server_index, self.partitions[t].num_servers
            )
