"""KVServer: the server-role Customer owning table shards.

Reference analogue: the server process's ``Parameter`` subclass answering
Push with ``SetValue`` (merge + update) and Pull with ``GetValue`` (gather)
(``src/parameter/parameter.h`` [U]).  Each KVServer instance owns the local
row-range shard of every registered table; requests arrive through the Van
recv thread (one per node — the reference's single-Executor-thread model, so
table mutation is single-threaded by construction) and the actual math runs
as the KVTable's jit-compiled device steps.

PR-6 ownership model: the shard is no longer the fixed uniform
``RangePartition`` split — an epoch-versioned
:class:`~parameter_server_tpu.kv.routing.RoutingTable` says which server
owns which global row ranges, and **live migration** rewrites it at runtime:

- Workers ship GLOBAL row ids stamped with their routing epoch
  (``__repoch__``); a request whose epoch disagrees, or whose rows this
  server does not own, is answered with a typed ``__error__`` reply carrying
  ``__fenced__`` + this server's routing table — rejected, NOT lost (the
  worker refreshes and retries; ``fenced_rejects`` counts these).
- Migration control ops (``migrate_*``) stream a sub-range to a recipient
  over the replica-chain transport path while the donor keeps serving;
  the only freeze is the atomic commit handler (this recv thread), whose
  duration is bounded by the final dirty-row delta.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import time
import zlib
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from parameter_server_tpu.config import ApplyEngineConfig, LedgerConfig, TableConfig
from parameter_server_tpu.core import flightrec
from parameter_server_tpu.core.messages import Message, Task, TaskKind
from parameter_server_tpu.core.postoffice import Customer, Postoffice
from parameter_server_tpu.core.tracectx import TRACE_KEY
from parameter_server_tpu.kv.consistency import MODE_CODES, FleetClock
from parameter_server_tpu.kv.ledger import ApplyLedger
from parameter_server_tpu.kv.partition import RangePartition
from parameter_server_tpu.kv.routing import (
    BUSY_KEY,
    CONSIST_STEP_KEY,
    FENCED_KEY,
    GROUP_KEY,
    READ_ONLY_KEY,
    ROUTING_EPOCH_KEY,
    ROUTING_KEY,
    VERSION_KEY,
    WAIT_KEY,
    RoutingTable,
)
from parameter_server_tpu.kv.table import KVTable
from parameter_server_tpu.utils.keys import bucket_size
from parameter_server_tpu.utils.trace import NULL_TRACER, LatencyHistogram, Tracer


def _bucket(n: int) -> int:
    """Server-side id bucket: next power of two, >= 8 (pallas block floor)."""
    return bucket_size(max(n, 1), min_bucket=8)


class KVServer(Customer):
    """Server-side customer: routes Push/Pull to local table shards."""

    def __init__(
        self,
        post: Postoffice,
        table_cfgs: Dict[str, TableConfig],
        server_index: int,
        num_servers: int,
        *,
        name: str = "kv",
        tracer: Tracer = NULL_TRACER,
        device_replies: bool = False,
        replica: Optional[str] = None,
        replica_sync: bool = False,
        max_replica_lag: int = 8,
        replica_ack_timeout: float = 60.0,
        routing: Optional[RoutingTable] = None,
        migrate_timeout: float = 30.0,
        apply: Optional[ApplyEngineConfig] = None,
        devobs: Optional[LedgerConfig] = None,
    ) -> None:
        """``replica``: node id of a hot-standby KVServer holding the same
        shard (chain replication of key ranges, the reference paper's §4.3
        recovery [U]; VERDICT r3 #6).  Every applied push is forwarded to
        it in apply order, so the standby's table+optimizer state tracks the
        primary's exactly.  ``replica_sync=True`` = chain semantics: the
        worker's ack only fires after the replica applied (ZERO update loss
        on primary death); ``False`` = async forwarding with at most
        ``max_replica_lag`` pushes in flight (bounded loss, no added push
        latency).  On death, :func:`parameter_server_tpu.kv.replica.promote`
        rebinds the standby under the primary's node id.

        ``routing``: explicit ownership map; defaults to the uniform
        epoch-0 split (identical to the legacy ``RangePartition``).  Pass a
        post-migration table to spawn a server into an already-rebalanced
        cluster (``scale_up`` spawns with ZERO owned rows and migrates onto
        it)."""
        super().__init__(name, post)
        #: bundle-batched apply engine knobs (ISSUE 11): how many same-table
        #: PUSHes of one coalesced bundle collapse into a single device
        #: apply, and the cross-member duplicate-row policy.
        self.apply_cfg = apply or ApplyEngineConfig()
        if self.apply_cfg.dup_policy not in ("rounds", "combine"):
            raise ValueError(
                f"dup_policy must be rounds|combine, "
                f"got {self.apply_cfg.dup_policy!r}"
            )
        #: device-plane observability (ISSUE 12): the ApplyLedger registers
        #: every dispatched device apply and retires it from its own reaper
        #: thread — the ack path only READS the level-triggered
        #: ``overloaded()`` flag (the ``__busy__`` hint), never device state.
        devobs = devobs or LedgerConfig()
        self.ledger: Optional[ApplyLedger] = (
            ApplyLedger(post.node_id, devobs) if devobs.enabled else None
        )
        #: reply to pulls with device arrays instead of host numpy — the
        #: zero-copy mode for in-process (Loopback) planes where worker and
        #: server share the device; cross-host Vans keep numpy replies.
        self.device_replies = device_replies
        self.server_index = server_index
        #: legacy uniform split — still the CHECKPOINT layout contract (shard
        #: files are uniform-contiguous; see save_checkpoint's guard).
        self.partitions = {
            t: RangePartition(cfg.rows, num_servers) for t, cfg in table_cfgs.items()
        }
        self.table_cfgs = table_cfgs
        self.routing = routing or RoutingTable.uniform(table_cfgs, num_servers)
        self._shard_maps: Dict[str, tuple] = {
            t: self._make_map(self.routing, t) for t in table_cfgs
        }
        #: ISSUE-10 staleness plane: per-table, per-owned-segment version
        #: clock (parallel to ``_shard_maps[t][0]``), bumped on every
        #: push-apply touching the segment; the max over the segments a
        #: request touches is stamped into its reply (``__sver__``) so
        #: workers can measure update lag at use time.  Mutated only on the
        #: recv thread (the single-writer table discipline).
        self._seg_versions: Dict[str, np.ndarray] = {
            t: np.zeros(self._shard_maps[t][0].shape[0], dtype=np.int64)
            for t in table_cfgs
        }
        self.tables: Dict[str, KVTable] = {
            t: KVTable(
                cfg,
                rows=self.routing.tables[t].server_rows(server_index),
                # stable across OS processes (builtin str hash is salted per
                # interpreter — servers spawned as separate processes would
                # init different rows than an in-process cluster, breaking
                # cross-deployment loss parity and restart determinism)
                seed=zlib.crc32(f"{t}:{server_index}".encode()) & 0x7FFFFFFF,
            )
            for t, cfg in table_cfgs.items()
        }
        #: dashboard counters
        self.pushes = 0
        self.pulls = 0
        #: hierarchical push (ISSUE 15): group-stamped pushes applied, and
        #: the member contributions they carried (``__grp__``'s ``n``) —
        #: the fan-in ratio pstop's GRP column derives.  A group push is
        #: ONE apply here (one ledger entry, one dup-policy unit); these
        #: counters are what make the pre-reduction visible.
        self.group_pushes = 0
        self.group_members = 0
        #: serving plane (ISSUE 13): read-only fast-path pulls answered,
        #: and their per-table server-side latency (dispatch -> reply built,
        #: including the D2H readback — the histogram the ``ro-p99`` SLO
        #: watches).  Recv-thread-only, like every other counter here.
        self.ro_pulls = 0
        self.ro_hist: Dict[str, LatencyHistogram] = {
            t: LatencyHistogram() for t in table_cfgs
        }
        self.fenced_rejects = 0
        # -- consistency plane (ISSUE 20) ------------------------------------
        #: per-gated-table live state: mode/bound start from the table's
        #: ConsistencyConfig but are retunable at runtime (``consist_set``
        #: — the BoundTuner's lever and the scenario DSL's mode-flip knob);
        #: the FleetClock is the vector clock of per-worker committed steps
        #: fed by ``__cstep__`` stamps.  Mutated on the recv thread (plus
        #: the van's incarnation callback — FleetClock locks internally).
        self._consist: Dict[str, dict] = {}
        for t, cfg in table_cfgs.items():
            if cfg.consistency is not None:
                self._consist[t] = {
                    "cfg": cfg.consistency,
                    "mode": cfg.consistency.mode,
                    "bound": cfg.consistency.bound,
                    "clock": FleetClock(),
                }
        self.consist_defers = 0
        self.consist_releases = 0
        #: senders currently parked on a ``__wait__`` defer, per table —
        #: the gate/release event pairing the postmortem anchor keys on
        #: (``consist.gate`` fires on FIRST defer, ``consist.release`` when
        #: that sender is next admitted; retries in between stay silent).
        self._consist_waiting: Dict[str, set] = {t: set() for t in self._consist}
        if self._consist and hasattr(post.van, "on_incarnation_advance"):
            # same-id restart fencing (ISSUE 20 satellite): the dead
            # incarnation's clock entry must not wedge the fleet minimum
            post.van.on_incarnation_advance.append(self._consist_incarnation)
        # -- sampled request tracing (ISSUE 18) ------------------------------
        #: server-side plane attribution across sampled requests, exported
        #: via :meth:`latency_digests`: ``trace.wire`` = worker submit ->
        #: handler dispatch (same-host monotonic clocks; cross-host fleets
        #: read the clock-rebased ``tools/critpath.py`` view instead),
        #: ``trace.sq`` = van receive -> handler dispatch (server queue),
        #: ``trace.apply`` = dispatch -> reply built.  Recv-thread-only,
        #: same discipline as ``ro_hist``.
        self._trace_hists: Dict[str, LatencyHistogram] = {}
        #: tid -> dispatch monotonic time, bridging :meth:`_trace_dispatch`
        #: to the reply site; bounded (error paths may never reply)
        self._trace_disp: Dict[str, float] = {}
        self.rows_migrated_in = 0
        self.rows_migrated_out = 0
        self.migration_freeze_s = 0.0
        self.migration_freeze_last_s = 0.0
        self.tracer = tracer
        self.migrate_timeout = migrate_timeout
        #: in-flight donor migrations: mid -> {table, lo, hi, to, dirty}
        self._migrations: Dict[str, dict] = {}
        #: in-flight recipient staging: mid -> {table, lo, hi, chunks}
        self._staging: Dict[str, dict] = {}
        #: durability plane (ISSUE 16): open snapshot windows, sid ->
        #: {dirty: {table: set(global rows)}} — armed by ``snap_begin``,
        #: drained by ``snap_commit``'s bounded freeze.  Same recv-thread
        #: single-writer discipline as ``_migrations``.
        self._snapshots: Dict[str, dict] = {}
        self.ckpt_commits = 0
        self.ckpt_freeze_s = 0.0
        self.ckpt_freeze_last_s = 0.0
        self.ckpt_delta_rows = 0
        self.ckpt_delta_overflow = 0
        #: soft bound on the commit-freeze delta (CheckpointConfig
        #: ``max_delta_rows``; settable per snap_begin payload).
        self.ckpt_max_delta_rows = 65536
        #: basis of the ``ckpt_age_s`` gauge: stamped at construction so a
        #: fleet that NEVER snapshots ages (and breaches the ckpt-age SLO)
        #: from boot, then re-stamped on every snapshot commit / restore.
        self._ckpt_commit_t = time.monotonic()
        #: lazy side customer for donor->recipient streaming (own endpoint:
        #: waiting for stage/install acks on this recv thread would deadlock)
        self._mig: Optional[Customer] = None
        # -- hot-replica forwarding channel ---------------------------------
        self.replica = replica
        self.replica_sync = replica_sync
        self.max_replica_lag = max_replica_lag
        self.replica_ack_timeout = replica_ack_timeout
        self._fwd_inflight: collections.deque[int] = collections.deque()
        if replica is not None:
            # A DEDICATED endpoint for the primary's client role: waiting
            # for replica acks on the server's own recv thread would
            # deadlock (that thread must process the ack).  The forwarding
            # Customer shares this server's customer name so the replica
            # routes the forwarded pushes into its normal kv handler.
            self._fwd_post = Postoffice(f"{post.node_id}.fw", post.van)
            self._fwd = Customer(name, self._fwd_post)

    # -- routing / shard maps -------------------------------------------------
    def _make_map(self, routing: RoutingTable, table: str) -> tuple:
        """``(starts, ends, locals)`` of this server's owned segments.

        Global row ``g`` in segment ``i`` lives at local row
        ``g - starts[i] + locals[i]`` — segments pack contiguously into the
        KVTable in global order.
        """
        segs = routing.tables[table].owned_segments(self.server_index)
        starts = np.asarray([lo for lo, _ in segs], dtype=np.int64)
        ends = np.asarray([hi for _, hi in segs], dtype=np.int64)
        sizes = ends - starts
        locs = np.concatenate([[0], np.cumsum(sizes)])[:-1].astype(np.int64)
        return starts, ends, locs

    def _try_localize(
        self, table: str, gids: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Map global rows to local rows against the CURRENT shard map.

        Returns ``(local, owned)``: ``local[i]`` is valid iff ``owned[i]``.
        """
        starts, ends, locs = self._shard_maps[table]
        gids = np.asarray(gids, dtype=np.int64)
        if starts.size == 0:
            return np.zeros(gids.shape, np.int64), np.zeros(gids.shape, bool)
        idx = np.searchsorted(starts, gids, side="right") - 1
        idx_c = np.clip(idx, 0, None)
        owned = (idx >= 0) & (gids >= 0) & (gids < ends[idx_c])
        local = np.where(owned, gids - starts[idx_c] + locs[idx_c], 0)
        return local, owned

    def _localize_request(
        self, table: str, keys
    ) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Worker keys (sorted GLOBAL ids, pad == global rows) -> local ids.

        One vectorized pass over the keys produces everything both data
        paths need: ``(local_ids int32, keys int64, touched_segments)``.
        The segment indices fall out of the localization's own
        ``searchsorted`` ranking, so the staleness bump no longer re-ranks
        the keys (it used to run ``searchsorted`` a second time per
        request).  Pads map to this shard's trash row; returns None when
        any real id is not owned here (the fence trigger).
        """
        grows = self.routing.tables[table].rows
        kn = np.asarray(keys, dtype=np.int64)
        out = np.full(kn.shape, self.tables[table].rows, dtype=np.int32)
        real = kn < grows
        segs = np.empty(0, dtype=np.int64)
        if real.any():
            starts, ends, locs = self._shard_maps[table]
            if starts.size == 0:
                return None
            rk = kn[real]
            idx = np.searchsorted(starts, rk, side="right") - 1
            idx_c = np.clip(idx, 0, None)
            owned = (idx >= 0) & (rk >= 0) & (rk < ends[idx_c])
            if not owned.all():
                return None
            out[real] = (rk - starts[idx_c] + locs[idx_c]).astype(np.int32)
            segs = np.unique(idx_c)
        return out, kn, segs

    def _fence_reply(self, msg: Message, why: str) -> Message:
        """Typed reject: ``__error__`` + ``__fenced__`` + the CURRENT table.

        The worker's retry loop keys on ``__fenced__`` (a real handler error
        must still raise) and adopts the attached routing iff it is newer
        than what it holds — rejected, not lost.

        ISSUE 13: fences also carry the shard's ``__sver__`` (and the table
        name the fence payload would otherwise drop), so a reject still
        refreshes the worker's cache-invalidation watermark — a fenced
        worker learns about writes it raced with from the reject itself.
        """
        self.fenced_rejects += 1
        flightrec.record(
            "fence.routing", node=self.post.node_id, sender=msg.sender,
            epoch=self.routing.epoch, why=why[:120],
        )
        reply = msg.reply()
        payload = {
            "__error__": why,
            FENCED_KEY: True,
            ROUTING_KEY: self.routing.to_payload(),
        }
        tctx = msg.task.payload.get(TRACE_KEY)
        if isinstance(tctx, dict) and tctx.get("tid") is not None:
            # ISSUE 18: a fence is still a reply leg of the sampled span
            # tree — echo the context (the fresh fence payload would drop
            # it) so the worker closes the tree, and record the verdict
            payload[TRACE_KEY] = tctx
            self._trace_disp.pop(tctx["tid"], None)
            flightrec.record(
                "trace.reply",
                tid=tctx["tid"],
                node=self.post.node_id,
                verdict="fenced",
            )
        tname = msg.task.payload.get("table")
        if tname in self._seg_versions:
            payload["table"] = tname
            payload[VERSION_KEY] = self.version_max(tname)
        reply.task = dataclasses.replace(msg.task, payload=payload)
        return reply

    def _wait_reply(self, msg: Message, tname: str, step: int, fm: int) -> Message:
        """Typed consistency defer (ISSUE 20): the sender ran too far ahead.

        Deliberately FENCE-SHAPED (``__error__`` + ``__fenced__`` + the
        current routing table) so pre-ISSUE-20 workers treat it as a fence
        and retry blindly — deferred, never dropped (MIGRATION.md).  New
        workers key on ``__wait__`` first: routing is fine, so the retry
        rides the gate budget (``gate_deadline_s``), not the fence budget,
        honoring the ``retry_after`` backoff hint.  The fleet clock
        snapshot rides along so the worker can see WHO it is waiting for.
        """
        st = self._consist[tname]
        self.consist_defers += 1
        waiting = self._consist_waiting[tname]
        if msg.sender not in waiting:
            waiting.add(msg.sender)
            flightrec.record(
                "consist.gate", node=self.post.node_id, sender=msg.sender,
                table=tname, step=step, fleet_min=fm,
                bound=int(st["bound"]),
            )
        reply = msg.reply()
        gap = step - fm - int(st["bound"])
        payload = {
            "__error__": (
                f"consistency gate ({st['mode'].value}): step {step} > "
                f"fleet_min {fm} + bound {st['bound']} on {tname!r}"
            ),
            FENCED_KEY: True,
            ROUTING_KEY: self.routing.to_payload(),
            WAIT_KEY: True,
            "clock": st["clock"].snapshot(),
            "fleet_min": fm,
            "bound": int(st["bound"]),
            "retry_after": min(0.25, 0.002 * max(1, gap)),
        }
        tctx = msg.task.payload.get(TRACE_KEY)
        if isinstance(tctx, dict) and tctx.get("tid") is not None:
            # a defer is still a reply leg of the sampled span tree
            payload[TRACE_KEY] = tctx
            self._trace_disp.pop(tctx["tid"], None)
            flightrec.record(
                "trace.reply", tid=tctx["tid"], node=self.post.node_id,
                verdict="wait",
            )
        payload["table"] = tname
        payload[VERSION_KEY] = self.version_max(tname)
        reply.task = dataclasses.replace(msg.task, payload=payload)
        return reply

    def _consist_incarnation(self, node_id: str, incarnation: int) -> None:
        """Van callback: a peer restarted under the same id — prune the
        dead incarnation's clock entry so it cannot wedge the fleet
        minimum (the new incarnation re-registers via ``consist_hello``
        or its first stamped request)."""
        for st in self._consist.values():
            st["clock"].on_incarnation_advance(node_id, incarnation)

    # -- staleness version clock (ISSUE 10) -----------------------------------
    def version_max(self, table: str) -> int:
        """Highest segment version of this shard (0 when it owns nothing)."""
        ver = self._seg_versions[table]
        return int(ver.max()) if ver.size else 0

    def _stamp_version(self, msg: Message, reply: Message, sver: int) -> Message:
        """Stamp ``__sver__`` onto a data reply, copy-on-write.

        ``Message.reply`` shares the request's Task (and payload dict) — on
        a Loopback plane that dict IS the sender's object, so the stamp must
        replace the Task with a fresh payload, exactly as ``_fence_reply``
        does, never mutate in place.

        Sampled request tracing (ISSUE 18): the request's ``__trace__``
        context rides the copied payload back automatically, which is what
        lets the worker close the span tree off this ack/reply; this is
        also the one choke point every data reply passes, so the
        ``trace.reply`` event and the dispatch → reply-built attribution
        (``trace.apply``) are recorded here, gated on the sampled context.
        """
        tctx = msg.task.payload.get(TRACE_KEY)
        if isinstance(tctx, dict) and tctx.get("tid") is not None:
            t_disp = self._trace_disp.pop(tctx["tid"], None)
            if t_disp is not None:
                self._trace_hist("trace.apply").record(
                    max(time.monotonic() - t_disp, 0.0)
                )
            flightrec.record(
                "trace.reply",
                tid=tctx["tid"],
                node=self.post.node_id,
                verdict="ok",
            )
        reply.task = dataclasses.replace(
            msg.task, payload={**msg.task.payload, VERSION_KEY: sver}
        )
        return reply

    def _forward_push(self, tname: str, msg: Message) -> None:
        fwd = Message(
            task=Task(TaskKind.PUSH, self._fwd.name, payload={"table": tname}),
            recver=self.replica,
            keys=np.asarray(msg.keys),
            values=[np.asarray(msg.values[0])],
        )
        ts = self._fwd.submit([fwd])
        if self.replica_sync:
            if not self._fwd.wait(ts, timeout=self.replica_ack_timeout):
                # deadline: free the stuck task before failing the push —
                # the fwd customer must not leak _pending state per timeout
                self._fwd.cancel(ts, "replica ack deadline")
                raise RuntimeError(
                    f"replica {self.replica} did not ack push (sync chain)"
                )
            self._fwd.check(ts)
        else:
            self._fwd_inflight.append(ts)
            while len(self._fwd_inflight) > self.max_replica_lag:
                old = self._fwd_inflight.popleft()
                if not self._fwd.wait(old, timeout=self.replica_ack_timeout):
                    self._fwd.cancel(old, "replica ack deadline")
                    raise RuntimeError(
                        f"replica {self.replica} lag exceeded "
                        f"{self.max_replica_lag} and oldest ack timed out"
                    )

    def flush_replica(self, timeout: float = 60.0) -> None:
        """Block until every async-forwarded push is acked by the replica."""
        while self._fwd_inflight:
            old = self._fwd_inflight.popleft()
            if not self._fwd.wait(old, timeout):
                self._fwd.cancel(old, "replica flush deadline")
                raise RuntimeError(f"replica flush: ts={old} not acked")

    def _forward_control(self, payload: dict, keys=None, values=None) -> None:
        """Replica-chain a migration control op, synchronously.

        Rides the same per-link FIFO as forwarded pushes, so the standby
        applies the shard-map change AFTER every push that preceded it here.
        """
        msg = Message(
            task=Task(TaskKind.CONTROL, self._fwd.name, payload=payload),
            recver=self.replica,
            keys=keys,
            values=values if values is not None else [],
        )
        ts = self._fwd.submit([msg], keep_responses=True)
        if not self._fwd.wait(ts, timeout=self.replica_ack_timeout):
            self._fwd.cancel(ts, "replica control deadline", remote=True)
            self._fwd.take_responses(ts)
            raise RuntimeError(
                f"replica {self.replica} did not ack {payload.get('op')!r}"
            )
        errs = self._fwd.errors(ts)
        self._fwd.take_responses(ts)
        if errs:
            raise RuntimeError(
                f"replica {payload.get('op')!r} failed: " + "; ".join(errs)
            )

    def counters(self) -> dict:
        """Migration/fence counters, Dashboard-mergeable (utils.metrics)."""
        out = {
            "fenced_rejects": self.fenced_rejects,
            "ro_pulls": self.ro_pulls,
            # hierarchical push (ISSUE 15): fan-in totals the telemetry
            # plane derives grp_pct from (group-reduced applies / raw
            # member contributions they replaced)
            "group_pushes": self.group_pushes,
            "group_members": self.group_members,
            "rows_migrated_in": self.rows_migrated_in,
            "rows_migrated_out": self.rows_migrated_out,
            "migration_freeze_s": round(self.migration_freeze_s, 6),
            # staleness plane: the shard's highest segment version, summed
            # over tables — a cheap fleet-wide write-progress gauge
            "seg_version_max": sum(
                self.version_max(t) for t in self.tables
            ),
            # durability plane (ISSUE 16): seconds since this shard last
            # committed to (or restored from) a durable snapshot — the
            # gauge pstop's CKPT column and the ckpt-age SLO watch —
            # plus commit totals and the bounded-freeze accounting
            "ckpt_age_s": round(time.monotonic() - self._ckpt_commit_t, 3),
            "ckpt_commits": self.ckpt_commits,
            "ckpt_freeze_s": round(self.ckpt_freeze_s, 6),
            "ckpt_delta_rows": self.ckpt_delta_rows,
            "ckpt_delta_overflow": self.ckpt_delta_overflow,
        }
        if self._consist:
            # consistency plane (ISSUE 20): defer/release totals plus the
            # mode/bound gauges pstop's MODE/BOUND columns decode (first
            # gated table by name — fleets gate one training table; the
            # clock size/prune gauges make membership drift visible)
            first = self._consist[sorted(self._consist)[0]]
            out["consist_defers"] = self.consist_defers
            out["consist_releases"] = self.consist_releases
            out["consist_mode"] = MODE_CODES[first["mode"]]
            out["consist_bound"] = (
                -1 if first["bound"] is None else int(first["bound"])
            )
            out["consist_clock_size"] = sum(
                st["clock"].size() for st in self._consist.values()
            )
            out["consist_pruned"] = sum(
                st["clock"].pruned for st in self._consist.values()
            )
        if self.ledger is not None:
            # device-plane gauges + totals (inflight_bundles/rows,
            # backlog_age_s, applies_*): ride the same counter channel —
            # telemetry's delta framing reconstructs gauges exactly
            out.update(self.ledger.counters())
        return out

    def latency_digests(self) -> Dict[str, dict]:
        """Device-plane apply attribution digests for the telemetry
        publisher (``apply.<t>`` total + host/h2d/dev splits, cumulative),
        plus the serving plane's read-only pull latency (``ro_pull.<t>``,
        the ``ro-p99`` SLO's metric)."""
        out = (
            self.ledger.latency_digests() if self.ledger is not None else {}
        )
        for t, hist in self.ro_hist.items():
            if hist.count:
                out[f"ro_pull.{t}"] = hist.to_dict()
        # tracing plane (ISSUE 18): trace.wire / trace.sq / trace.apply —
        # the series pstop's WIREµs/SQµs/APLY% columns and the
        # ``trace-wire-p99`` SLO (utils/slo.py tracing_plane_specs) consume
        for name, hist in self._trace_hists.items():
            if hist.count:
                out[name] = hist.to_dict()
        return out

    # -- request handling -----------------------------------------------------
    @staticmethod
    def _trace_tid_of(group: List[tuple]) -> Optional[str]:
        """First sampled member's trace id of a batched push group — the
        one the grouped apply's device attribution is charged to (pure
        dict lookups: stays sync-free on the batched-apply path)."""
        for _i, m, *_rest in group:
            tctx = m.task.payload.get(TRACE_KEY)
            if isinstance(tctx, dict) and tctx.get("tid") is not None:
                return tctx["tid"]
        return None

    def _trace_hist(self, name: str) -> LatencyHistogram:
        hist = self._trace_hists.get(name)
        if hist is None:
            hist = self._trace_hists[name] = LatencyHistogram()
        return hist

    def _trace_dispatch(self, msg: Message) -> None:
        """Handler-entry attribution for a sampled request (ISSUE 18).

        Gated on the request actually carrying a trace context — unsampled
        requests (the vast majority) cost one dict lookup here, nothing
        more (``tools/check_wrappers.py`` enforces the gate by AST).
        Records the ``trace.dispatch`` event and feeds the live
        wire/server-queue histograms from the context's origin/receive
        stamps; the dispatch time is kept so the reply site can attribute
        dispatch → reply-built into ``trace.apply``.
        """
        payload = msg.task.payload
        tctx = payload.get(TRACE_KEY) if isinstance(payload, dict) else None
        if isinstance(tctx, dict) and tctx.get("tid") is not None:
            now = time.monotonic()
            tid = tctx["tid"]
            t0 = tctx.get("t")
            rx = tctx.get("rx")
            if rx is not None:
                # wire transit proxy: origin submit -> van receive (the
                # rx stamp exists only on wire paths — loopback degrades
                # to no sample rather than a lie)
                if t0 is not None:
                    self._trace_hist("trace.wire").record(max(rx - t0, 0.0))
                self._trace_hist("trace.sq").record(max(now - rx, 0.0))
            while len(self._trace_disp) >= 1024:
                self._trace_disp.pop(next(iter(self._trace_disp)))
            self._trace_disp[tid] = now
            flightrec.record(
                "trace.dispatch",
                tid=tid,
                node=self.post.node_id,
                op=msg.task.kind.name.lower(),
                sender=msg.sender,
            )

    def _span_attrs(self, msg: Message, tname: str) -> dict:
        # cross-node stitching: echo the worker's trace context onto this
        # handler's spans so merge_traces can pair both ends of the request
        tctx = msg.task.payload.get("__trace__") or {}
        span_attrs = {"table": tname}
        if tctx.get("tid"):
            span_attrs["trace"] = tctx["tid"]
            span_attrs["origin"] = tctx.get("origin")
        return span_attrs

    def _validate_data_request(self, msg: Message):
        """Routing fence + localization for a PUSH/PULL.

        Returns a fence-reject ``Message``, or the localized
        ``(tname, ids_np, kn, segs)`` tuple when the request may proceed.

        Routing fence (PR-6): a stamped epoch that disagrees means the
        sender routed with a different table generation — reject with the
        current table rather than guessing (an id could alias a row this
        server owns under EITHER generation; applying would double-count
        when the worker retries the reject).  Unstamped requests (replica
        forwards, which follow the primary's apply order by construction)
        skip the epoch check but still ownership-check.
        """
        tname = msg.task.payload["table"]
        repoch = msg.task.payload.get(ROUTING_EPOCH_KEY)
        if repoch is not None and repoch != self.routing.epoch:
            return self._fence_reply(
                msg,
                f"routing epoch mismatch: request {repoch} != "
                f"server {self.routing.epoch}",
            )
        loc = self._localize_request(tname, msg.keys)
        if loc is None:
            return self._fence_reply(
                msg,
                f"not owner: {self.post.node_id} does not own all of "
                f"{len(np.asarray(msg.keys))} requested rows of {tname!r} "
                f"at epoch {self.routing.epoch}",
            )
        # consistency gate (ISSUE 20): a stamped request on a gated table
        # must sit within ``bound`` of the fleet minimum or it is deferred
        # with a typed ``__wait__`` reply.  AFTER the routing checks (a
        # mis-routed request must fence, not wait) and only for stamped
        # traffic — old workers and read-only serving pulls bypass.
        cstep = msg.task.payload.get(CONSIST_STEP_KEY)
        if cstep is not None and tname in self._consist:
            st = self._consist[tname]
            allowed, fm = st["clock"].gate(
                msg.sender, int(cstep), st["bound"]
            )
            if not allowed:
                return self._wait_reply(msg, tname, int(cstep), fm)
            waiting = self._consist_waiting[tname]
            if msg.sender in waiting:
                waiting.discard(msg.sender)
                self.consist_releases += 1
                flightrec.record(
                    "consist.release", node=self.post.node_id,
                    sender=msg.sender, table=tname, step=int(cstep),
                    fleet_min=fm,
                )
        ids_np, kn, segs = loc
        return tname, ids_np, kn, segs

    def _pad_ids(self, table: KVTable, ids_np: np.ndarray, b: int) -> np.ndarray:
        # Bucket-pad the slice to a power of two: the worker bucket-pads its
        # unique slots, but the per-server split (Parameter::Slice) produces
        # arbitrary lengths again — without this every distinct length
        # compiles a fresh device step, and the pallas kernels (block DMA)
        # reject unaligned id vectors outright.  Pads route to the trash row
        # with zero gradients (the established PAD contract).
        n = int(ids_np.shape[0])
        if b == n:
            return ids_np
        padded_ids = np.full(b, table.rows, dtype=np.int32)
        padded_ids[:n] = ids_np
        return padded_ids

    def _upload_values(self, vals, b: int, n: int) -> jax.Array:
        if not isinstance(vals, jax.Array):
            # direct device handoff: the wire value plane (a zero-copy
            # frombuffer view of the received frame) feeds the device
            # transfer as-is — no intermediate padded host copy
            vals = jnp.asarray(np.asarray(vals))
        if b != n:  # pad on device (exact zeros: bitwise-neutral)
            zeros = jnp.zeros((b - n,) + vals.shape[1:], vals.dtype)
            vals = jnp.concatenate([vals, zeros])
        return vals

    def _stack_planes(
        self, table: KVTable, group: List[tuple], k: int, bm: int, tok=None
    ) -> jax.Array:
        """Assemble the bundle's ``(k, bm, dim)`` value stack.

        Wire planes (host numpy views of the received frame) pack into ONE
        pinned host buffer and ride a single H2D transfer — measurably
        cheaper than k separate uploads plus a device-side ``stack`` (which
        re-copies the whole bundle through the CPU client).  Device-resident
        planes (Loopback ``push_device`` traffic) skip the host and stack on
        device; zero-pads are exact zeros either way, so both routes are
        bitwise-identical.
        """
        if all(not isinstance(m.values[0], jax.Array) for _, m, *_ in group):
            dim = table.dim
            buf = np.empty((k, bm, dim), dtype=np.dtype(table.cfg.dtype))
            for i, (_, m, _, ids_np, _, _) in enumerate(group):
                n = int(ids_np.shape[0])
                buf[i, :n] = np.asarray(m.values[0]).reshape(n, dim)
                if n < bm:  # pads must stay exact zeros (bitwise-neutral)
                    buf[i, n:] = 0.0
            if tok is not None:
                tok.mark_host()  # pinned-buffer pack done; H2D is next
            stack = jnp.asarray(buf)
            if tok is not None:
                tok.mark_h2d()
            return stack
        planes = []
        for _, m, _, ids_np, _, _ in group:
            n = int(ids_np.shape[0])
            planes.append(self._upload_values(m.values[0], bm, n))
        if tok is not None:
            tok.mark_host()  # device-resident planes: no host pack phase
        stack = jnp.stack(planes)
        if tok is not None:
            tok.mark_h2d()
        return stack

    def _handle_push_single(
        self,
        msg: Message,
        tname: str,
        ids_np: np.ndarray,
        kn: np.ndarray,
        segs: np.ndarray,
    ) -> Message:
        table = self.tables[tname]
        n = int(ids_np.shape[0])
        b = _bucket(n)
        tctx = msg.task.payload.get(TRACE_KEY)
        tok = (
            self.ledger.begin(
                tname,
                1,
                n,
                tid=tctx.get("tid") if isinstance(tctx, dict) else None,
            )
            if self.ledger is not None
            else None
        )
        ids_host = self._pad_ids(table, ids_np, b)
        if tok is not None:
            tok.mark_host()
        ids = jnp.asarray(ids_host)
        vals = self._upload_values(msg.values[0], b, n)
        if tok is not None:
            tok.mark_h2d()
        with self.tracer.span("kv.server.push", **self._span_attrs(msg, tname)):
            ref = table.push(ids, vals)
        if tok is not None:
            self.ledger.submit(tok, ref, lambda t=table: t.value)
        return self._ack_push(msg, tname, kn, segs)

    def _ack_push(
        self, msg: Message, tname: str, kn: np.ndarray, segs: np.ndarray
    ) -> Message:
        """Post-dispatch bookkeeping + ack: the SYNC-FREE tail of every push.

        Runs after the device apply is dispatched but makes no attempt to
        observe its result — no ``np.asarray``/``device_get``/
        ``block_until_ready`` may appear here (``tools/check_wrappers.py``
        enforces this by AST), so the worker's ack latency is host-side
        bookkeeping only, never device-apply latency.  (``_forward_push``
        is host-side wire I/O on pre-upload planes; in ``replica_sync``
        mode it deliberately blocks on the CHAIN ack, not on device work.)
        """
        self.pushes += 1
        cstep = msg.task.payload.get(CONSIST_STEP_KEY)
        if cstep is not None and tname in self._consist:
            # consistency plane (ISSUE 20): the stamped push is APPLIED —
            # the sender committed its step, so its vector-clock entry
            # advances past it (pure dict/int ops: stays sync-free)
            self._consist[tname]["clock"].commit(msg.sender, int(cstep))
        grp = msg.task.payload.get(GROUP_KEY)
        if grp is not None:
            # hierarchical push (ISSUE 15): this ONE apply stands for the
            # whole group's step — count the fan-in so the wire reduction
            # is reportable (pure dict/int ops: stays sync-free)
            self.group_pushes += 1
            self.group_members += int(grp.get("n") or 1)
        # staleness clock: every apply bumps the touched segments; the
        # ack carries the post-bump max so the pusher's next pulls can
        # be measured against a version it knows it contributed to
        ver = self._seg_versions[tname]
        if segs.size:
            ver[segs] += 1
            sver = int(ver[segs].max())
        else:
            sver = self.version_max(tname)
        if self._migrations:
            # dirty tracking: rows in a migrating range changed after
            # their chunk may have shipped — the commit delta re-sends
            # them, bounding the freeze to exactly this set
            for m in self._migrations.values():
                if m["table"] == tname:
                    hit = kn[(kn >= m["lo"]) & (kn < m["hi"])]
                    m["dirty"].update(int(x) for x in hit)
        if self._snapshots:
            # durability plane: rows written during an open snapshot
            # window go stale against the already-written segment files —
            # snap_commit re-exports exactly this set as the delta log,
            # which is what bounds the commit freeze (pure host set ops:
            # stays sync-free, same as the migration tracking above)
            hit = kn[kn < self.routing.tables[tname].rows]
            for sn in self._snapshots.values():
                sn["dirty"].setdefault(tname, set()).update(
                    int(x) for x in hit
                )
        if self.replica is not None:
            # forward AFTER the local apply, in apply order (this recv
            # thread is the only writer), so the standby replays the
            # identical update sequence
            self._forward_push(tname, msg)
        reply = self._stamp_version(msg, msg.reply(), sver)
        if self.ledger is not None and self.ledger.overloaded():
            # soft backpressure: the update WAS applied; the hint tells the
            # worker's admission control to slow down.  overloaded() is a
            # host-side flag maintained by the reaper — reading it here
            # keeps the ack sync-free.  _stamp_version already replaced the
            # Task payload with a fresh dict, so this cannot leak into the
            # sender's payload object on a Loopback plane.
            reply.task.payload[BUSY_KEY] = True
        return reply

    def _pull_device(
        self, msg: Message, tname: str, ids_np: np.ndarray, segs: np.ndarray
    ) -> Tuple[jax.Array, int, int]:
        """Dispatch the device gather; D2H is the CALLER's choice (the
        bundle path defers it to one transfer per bundle)."""
        table = self.tables[tname]
        n = int(ids_np.shape[0])
        b = _bucket(n)
        ids = jnp.asarray(self._pad_ids(table, ids_np, b))
        with self.tracer.span("kv.server.pull", **self._span_attrs(msg, tname)):
            rows = table.pull(ids)
        self.pulls += 1
        # staleness clock: the reply carries the current version of the
        # touched segments (read, not bumped) — what the worker computes
        # on is exactly this version of those ranges
        ver = self._seg_versions[tname]
        sver = int(ver[segs].max()) if segs.size else self.version_max(tname)
        return rows, n, sver

    def _pull_ro_device(
        self, msg: Message, tname: str, ids_np: np.ndarray, segs: np.ndarray
    ) -> Tuple[jax.Array, int, int]:
        """Read-only fast-path gather (ISSUE 13): same device dispatch as
        ``_pull_device`` but on the serving books — its own counter and
        per-table latency histogram, and (in the bundle path) NO flush of
        the open push group.  Skips everything a write needs: optimizer,
        dup policy, ApplyLedger, replica forwarding."""
        table = self.tables[tname]
        n = int(ids_np.shape[0])
        b = _bucket(n)
        ids = jnp.asarray(self._pad_ids(table, ids_np, b))
        with self.tracer.span(
            "kv.server.pull_ro", **self._span_attrs(msg, tname)
        ):
            rows = table.pull(ids)
        self.ro_pulls += 1
        ver = self._seg_versions[tname]
        sver = int(ver[segs].max()) if segs.size else self.version_max(tname)
        return rows, n, sver

    def handle_request(self, msg: Message) -> Message:
        if msg.task.kind == TaskKind.CONTROL:
            return self._handle_control(msg)
        self._trace_dispatch(msg)
        v = self._validate_data_request(msg)
        if isinstance(v, Message):
            return v
        tname, ids_np, kn, segs = v
        if msg.task.kind == TaskKind.PUSH:
            return self._handle_push_single(msg, tname, ids_np, kn, segs)
        elif msg.task.kind == TaskKind.PULL:
            if msg.task.payload.get(READ_ONLY_KEY):
                t0 = time.perf_counter()
                rows, n, sver = self._pull_ro_device(msg, tname, ids_np, segs)
                if self.device_replies:
                    vals = [rows[:n]]
                else:
                    vals = [np.asarray(rows)[:n]]
                self.ro_hist[tname].record(time.perf_counter() - t0)
                return self._stamp_version(msg, msg.reply(values=vals), sver)
            rows, n, sver = self._pull_device(msg, tname, ids_np, segs)
            if self.device_replies:
                return self._stamp_version(msg, msg.reply(values=[rows[:n]]), sver)
            return self._stamp_version(
                msg, msg.reply(values=[np.asarray(rows)[:n]]), sver
            )
        raise ValueError(f"unsupported task kind {msg.task.kind}")

    # -- bundle-batched apply engine (ISSUE 11) -------------------------------
    def _error_reply(self, msg: Message, exc: Exception) -> Message:
        """Per-member failure reply, same shape the Postoffice emits for a
        raising single-request handler."""
        reply = msg.reply()
        payload = {"__error__": f"{type(exc).__name__}: {exc}"}
        tctx = msg.task.payload.get(TRACE_KEY)
        if isinstance(tctx, dict):
            # keep the sampled span tree closable even on a failed member
            payload[TRACE_KEY] = tctx
        reply.task = dataclasses.replace(msg.task, payload=payload)
        return reply

    def handle_request_batch(self, msgs: List[Message]) -> List[Message]:
        """Bundle-batched request handling (the fused apply engine).

        A coalesced frame's members arrive together; this path preserves
        their sequential semantics while collapsing the device traffic:

        - consecutive same-table PUSHes (up to ``apply.apply_batch``) become
          ONE donated-buffer device apply (``_apply_push_group``) instead of
          one jit call per member;
        - every PULL's D2H readback is deferred so the whole bundle costs a
          single ``jax.device_get`` (none at all under ``device_replies``).

        A PULL, CONTROL, fence, or table switch flushes the open PUSH run
        first, so each member still observes exactly the writes that
        preceded it in bundle order.  Failures are isolated per member (the
        failing member answers ``__error__``; the rest of the bundle
        proceeds), except that a grouped device apply fails its whole group
        — the group is one device call by design.

        Read-only pulls (``__ro__``, ISSUE 13) are the exception to the
        flush rule: they deliberately do NOT flush the open push group —
        the serving plane's relaxed-read contract is "the table as of
        dispatch", so a read-only member may observe the shard WITHOUT the
        writes riding the same bundle.  They defer to their own single
        ``jax.device_get`` and record into the ``ro_pull.<t>`` histogram.
        """
        replies: List[Optional[Message]] = [None] * len(msgs)
        pulls: List[tuple] = []  # (i, msg, rows, n, sver)
        ro: List[tuple] = []  # (i, msg, tname, rows, n, sver, t0)
        group: List[tuple] = []  # (i, msg, tname, ids_np, kn, segs)

        def flush_group() -> None:
            if not group:
                return
            try:
                if len(group) == 1:
                    i, m, tname, ids_np, kn, segs = group[0]
                    replies[i] = self._handle_push_single(
                        m, tname, ids_np, kn, segs
                    )
                else:
                    self._apply_push_group(group, replies)
            except Exception as e:  # noqa: BLE001
                logging.getLogger(__name__).exception(
                    "%s: batched push apply failed (%d members)",
                    self.post.node_id,
                    len(group),
                )
                for i, m, *_ in group:
                    replies[i] = self._error_reply(m, e)
            group.clear()

        batch_cap = max(1, self.apply_cfg.apply_batch)
        for i, msg in enumerate(msgs):
            try:
                if msg.task.kind == TaskKind.CONTROL:
                    flush_group()
                    replies[i] = self._handle_control(msg)
                    continue
                self._trace_dispatch(msg)
                v = self._validate_data_request(msg)
                if isinstance(v, Message):
                    flush_group()  # the fence observes prior writes too
                    replies[i] = v
                    continue
                tname, ids_np, kn, segs = v
                if msg.task.kind == TaskKind.PUSH:
                    if group and (
                        group[0][2] != tname or len(group) >= batch_cap
                    ):
                        flush_group()
                    group.append((i, msg, tname, ids_np, kn, segs))
                elif msg.task.kind == TaskKind.PULL:
                    if msg.task.payload.get(READ_ONLY_KEY):
                        # NO flush_group(): relaxed read, see docstring
                        t0 = time.perf_counter()
                        rows, n, sver = self._pull_ro_device(
                            msg, tname, ids_np, segs
                        )
                        ro.append((i, msg, tname, rows, n, sver, t0))
                        continue
                    flush_group()  # the pull must see prior member pushes
                    rows, n, sver = self._pull_device(msg, tname, ids_np, segs)
                    pulls.append((i, msg, rows, n, sver))
                else:
                    raise ValueError(
                        f"unsupported task kind {msg.task.kind}"
                    )
            except Exception as e:  # noqa: BLE001
                logging.getLogger(__name__).exception(
                    "%s: handler error for %s from %s",
                    self.post.node_id,
                    msg.task.kind,
                    msg.sender,
                )
                replies[i] = self._error_reply(msg, e)
        flush_group()
        self._finish_pulls(pulls, replies)
        self._finish_ro_pulls(ro, replies)
        return replies

    def _finish_pulls(self, pulls: List[tuple], replies: List) -> None:
        """Materialize deferred pull replies: ONE host readback per bundle
        (zero under ``device_replies`` — the rows stay on device)."""
        if not pulls:
            return
        if self.device_replies:
            for i, m, rows, n, sver in pulls:
                replies[i] = self._stamp_version(
                    m, m.reply(values=[rows[:n]]), sver
                )
            return
        host = jax.device_get([rows for _, _, rows, _, _ in pulls])
        for (i, m, _, n, sver), h in zip(pulls, host):
            replies[i] = self._stamp_version(m, m.reply(values=[h[:n]]), sver)

    def _finish_ro_pulls(self, ro: List[tuple], replies: List) -> None:
        """Materialize deferred READ-ONLY pull replies: the bundle's other
        single ``jax.device_get``, with per-member serving latency recorded
        from each member's dispatch time."""
        if not ro:
            return
        if self.device_replies:
            for i, m, tname, rows, n, sver, t0 in ro:
                replies[i] = self._stamp_version(
                    m, m.reply(values=[rows[:n]]), sver
                )
                self.ro_hist[tname].record(time.perf_counter() - t0)
            return
        host = jax.device_get([rows for _, _, _, rows, _, _, _ in ro])
        done = time.perf_counter()
        for (i, m, tname, _, n, sver, t0), h in zip(ro, host):
            replies[i] = self._stamp_version(m, m.reply(values=[h[:n]]), sver)
            self.ro_hist[tname].record(done - t0)

    def _apply_push_group(self, group: List[tuple], replies: List) -> None:
        """One device apply for a run of same-table PUSHes.

        Member value planes upload as-is and zero-pad ON DEVICE to the
        common bucket ``bm`` (stack shape ``(k, bm, dim)``), so the jitted
        apply's compile-cache keys stay bucketed: ``(k, bm)`` pairs, never
        raw wire lengths.  Duplicate rows ACROSS members follow
        ``apply.dup_policy`` — occurrence ``"rounds"`` (bitwise-sequential)
        or device ``segment_combine`` (``"combine"``, classic PS sum).
        Bookkeeping (staleness bumps, dirty tracking, replica forwarding,
        acks) then runs per member in member order, exactly as sequential
        handling would have.
        """
        tname = group[0][2]
        table = self.tables[tname]
        k = len(group)
        bm = _bucket(max(int(g[3].shape[0]) for g in group))
        tok = (
            self.ledger.begin(
                tname,
                k,
                sum(int(g[3].shape[0]) for g in group),
                tid=self._trace_tid_of(group),
            )
            if self.ledger is not None
            else None
        )
        with self.tracer.span(
            "kv.server.push_batch", table=tname, members=k
        ):
            stack = self._stack_planes(table, group, k, bm, tok)
            # flat positions of every REAL id occurrence, in member order
            ids_list = [g[3] for g in group]
            all_ids = np.concatenate(ids_list).astype(np.int64)
            flat_pos = np.concatenate(
                [
                    i * bm + np.arange(a.shape[0], dtype=np.int32)
                    for i, a in enumerate(ids_list)
                ]
            ).astype(np.int32)
            real = all_ids != table.rows
            rid = all_ids[real]
            rpos = flat_pos[real]
            if self.apply_cfg.dup_policy == "combine":
                ref = self._push_group_combined(table, k, bm, rid, rpos, stack)
            else:
                ref = self._push_group_rounds(table, k, bm, rid, rpos, stack)
        if tok is not None:
            self.ledger.submit(tok, ref, lambda t=table: t.value)
        for i, m, tname_, _, kn, segs in group:
            replies[i] = self._ack_push(m, tname_, kn, segs)

    def _push_group_rounds(
        self,
        table: KVTable,
        k: int,
        bm: int,
        rid: np.ndarray,
        rpos: np.ndarray,
        stack: jax.Array,
    ) -> jax.Array:
        """Occurrence-round partitioning: round ``t`` applies each row's
        ``t``-th contribution in member order.  Row updates are independent
        and the optimizer is row-wise, so the per-row grad sequence — and
        therefore the result — is bitwise-identical to sequential
        per-member applies, for EVERY optimizer.  With no cross-member
        duplicates (the common case) this is exactly one device call."""
        pad_pos = k * bm  # the appended zero row
        if rid.size == 0:
            rounds = [(rid, rpos)]
        else:
            order = np.argsort(rid, kind="stable")
            sid = rid[order]
            spos = rpos[order]
            newgrp = np.empty(sid.shape, dtype=bool)
            newgrp[0] = True
            newgrp[1:] = sid[1:] != sid[:-1]
            ar = np.arange(sid.size, dtype=np.int64)
            grp_start = np.maximum.accumulate(np.where(newgrp, ar, 0))
            occ = ar - grp_start
            rounds = [
                (sid[occ == t], spos[occ == t])
                for t in range(int(occ.max()) + 1)
            ]
        ref = None
        for uids_t, pos_t in rounds:
            nt = int(uids_t.size)
            bu = _bucket(nt)
            ids_np = np.full(bu, table.rows, dtype=np.int32)
            ids_np[:nt] = uids_t.astype(np.int32)
            pos_np = np.full(bu, pad_pos, dtype=np.int32)
            pos_np[:nt] = pos_t
            ref = table.push_batch(
                jnp.asarray(ids_np), jnp.asarray(pos_np), stack
            )
        return ref  # last round's value: its readiness bounds every round

    def _push_group_combined(
        self,
        table: KVTable,
        k: int,
        bm: int,
        rid: np.ndarray,
        rpos: np.ndarray,
        stack: jax.Array,
    ) -> jax.Array:
        """Device pre-merge: duplicate rows across members segment-sum into
        one gradient row (the reference's ParallelOrderedMatch merge), then
        ONE apply — classic PS sum semantics (sequential-identical only for
        disjoint member rows)."""
        uids, inv_real = np.unique(rid, return_inverse=True)
        nu = int(uids.size)
        bu = _bucket(nu)
        if bu == nu and nu < k * bm:
            # every slot holds a real row but pad positions still need a
            # trash slot to sum (exact zeros) into — grow one bucket
            bu = _bucket(nu + 1)
        ids_np = np.full(bu, table.rows, dtype=np.int32)
        ids_np[:nu] = uids.astype(np.int32)
        inverse = np.full(k * bm, min(nu, bu - 1), dtype=np.int32)
        inverse[rpos] = inv_real.astype(np.int32)
        return table.push_combined(
            jnp.asarray(ids_np), jnp.asarray(inverse), stack
        )

    # -- shard transfer (same-id restart: kv/replica.restart_same_id) --------
    def export_shard(self) -> Dict[str, dict]:
        """Host-side snapshot of every table shard: value + optimizer state.

        The live-donor half of same-id restart recovery: a hot standby
        exports, the restarted primary imports, and the pair is bit-identical
        — including optimizer accumulators, which the wire protocol never
        carries (only the chain forwarding replays them).
        """
        return {
            t: {
                "value": np.asarray(table.value),
                "state": {k: np.asarray(v) for k, v in table.state.items()},
            }
            for t, table in self.tables.items()
        }

    def import_shard(self, shard: Dict[str, dict]) -> None:
        """Adopt an :meth:`export_shard` snapshot wholesale.

        Row ranges must match (same ``server_index`` and the same routing
        generation — post-migration restarts pass ``routing=`` at
        construction); the donated push buffers are simply replaced, so the
        next push jit-step runs on the imported arrays.
        """
        for t, blob in shard.items():
            table = self.tables[t]
            table.value = jnp.asarray(blob["value"])
            table.state = {
                k: jnp.asarray(v) for k, v in blob["state"].items()
            }

    def _export_rows(
        self, table: str, gids: np.ndarray
    ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """Snapshot value + optimizer-state rows at GLOBAL ids (owned)."""
        tbl = self.tables[table]
        local, owned = self._try_localize(table, gids)
        if not owned.all():
            raise ValueError(
                f"export of un-owned rows of {table!r} on {self.post.node_id}"
            )
        value = np.asarray(tbl.value)[local]
        state = {k: np.asarray(v)[local] for k, v in tbl.state.items()}
        return value, state

    def export_range(
        self, table: str, lo: int, hi: int
    ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """:meth:`export_shard` generalized to an arbitrary global range."""
        return self._export_rows(table, np.arange(lo, hi, dtype=np.int64))

    # -- live migration (PR-6) ------------------------------------------------
    def _ensure_mig(self) -> Customer:
        """Donor-side streaming customer on its own endpoint (deadlock-free:
        stage/install acks are processed by the ``.mig`` recv thread while
        this server's recv thread blocks inside the migration handler)."""
        if self._mig is None:
            mig_post = Postoffice(f"{self.post.node_id}.mig", self.post.van)
            self._mig = Customer(self.name, mig_post)
        return self._mig

    def _mig_rpc(
        self, recver: str, payload: dict, keys=None, values=None
    ) -> Message:
        mig = self._ensure_mig()
        ts = mig.submit(
            [
                Message(
                    task=Task(TaskKind.CONTROL, mig.name, payload=payload),
                    recver=recver,
                    keys=keys,
                    values=values,
                )
            ],
            keep_responses=True,
        )
        if not mig.wait(ts, timeout=self.migrate_timeout):
            mig.cancel(ts, f"migration {payload.get('op')!r} deadline",
                       remote=True)
            mig.take_responses(ts)
            raise TimeoutError(
                f"{payload.get('op')!r} to {recver} timed out"
            )
        errs = mig.errors(ts)
        responses = mig.take_responses(ts)
        if errs:
            raise RuntimeError(
                f"{payload.get('op')!r} to {recver} failed: " + "; ".join(errs)
            )
        return responses[0]

    def _install_routing(
        self, new_routing: RoutingTable, extra: Optional[dict] = None
    ) -> None:
        """Adopt ``new_routing``, rebuilding any table whose segments change.

        ``extra``: ``{table: (gids, value, state)}`` — source rows for
        newly-adopted ranges (the migration payload).  Runs on the recv
        thread, so it is atomic wrt pushes.
        """
        # durability plane: a routing change invalidates every open
        # snapshot's segment bookkeeping (files already written describe
        # the OLD layout) — abort them; the driver's commit then fails
        # loudly and no manifest ever references the torn files
        if self._snapshots:
            for sid in list(self._snapshots):
                del self._snapshots[sid]
                flightrec.record(
                    "ckpt.abort", node=self.post.node_id, sid=sid,
                    why="routing changed mid-snapshot",
                )
        for t, tbl in self.tables.items():
            new_segs = new_routing.tables[t].owned_segments(self.server_index)
            old_segs = self.routing.tables[t].owned_segments(self.server_index)
            ex = (extra or {}).get(t)
            if new_segs == old_segs and ex is None:
                continue
            self._rebuild_table(t, new_segs, ex)
        self.routing = new_routing
        self._shard_maps = {
            t: self._make_map(new_routing, t) for t in self.tables
        }
        # staleness clock across migrations: new segment layouts restart
        # from the shard's previous MAX, so the per-table version never goes
        # backwards (a worker's recorded last-push version stays comparable)
        self._seg_versions = {
            t: np.full(
                self._shard_maps[t][0].shape[0],
                self.version_max(t) if t in self._seg_versions else 0,
                dtype=np.int64,
            )
            for t in self.tables
        }

    def _rebuild_table(
        self, t: str, new_segs: List[Tuple[int, int]], extra
    ) -> None:
        """Re-pack the shard for a new segment layout.

        Every new-layout row must come from either the OLD shard (kept or
        re-ordered rows) or ``extra`` (adopted rows) — anything uncovered is
        a protocol error, never silently zero-initialized.
        """
        tbl = self.tables[t]
        parts = [np.arange(lo, hi, dtype=np.int64) for lo, hi in new_segs]
        gids = (
            np.concatenate(parts) if parts else np.empty(0, dtype=np.int64)
        )
        n = int(gids.shape[0])
        old_v = np.asarray(tbl.value)
        old_s = {k: np.asarray(v) for k, v in tbl.state.items()}
        value = np.empty((n + 1, tbl.dim), dtype=old_v.dtype)
        state = {
            k: np.empty((n + 1, tbl.dim), dtype=old_v.dtype) for k in old_s
        }
        # carry the trash row (re-zeroed every push anyway, but optimizer
        # fills must survive)
        value[n] = old_v[tbl.rows]
        for k in state:
            state[k][n] = old_s[k][tbl.rows]
        local, covered = self._try_localize(t, gids)
        if covered.any():
            src = local[covered]
            value[:n][covered] = old_v[src]
            for k in state:
                state[k][:n][covered] = old_s[k][src]
        if extra is not None:
            ids_e, v_e, s_e = extra
            ids_e = np.asarray(ids_e, dtype=np.int64)
            if ids_e.size:
                pos = np.searchsorted(ids_e, gids)
                pos_c = np.minimum(pos, ids_e.size - 1)
                hit = ids_e[pos_c] == gids
                src = pos_c[hit]
                value[:n][hit] = v_e[src]
                for k in state:
                    state[k][:n][hit] = np.asarray(s_e[k])[src]
                covered = covered | hit
        if n and not covered.all():
            missing = gids[~covered]
            raise RuntimeError(
                f"shard rebuild of {t!r} on {self.post.node_id}: "
                f"{missing.size} rows uncovered (first: {missing[:4]})"
            )
        tbl.resize(value, state)

    def adopt_routing(self, routing) -> bool:
        """Adopt a broadcast routing table (non-participant servers).

        Accepts a :class:`RoutingTable` or its payload dict.  Only newer
        epochs apply, and this path must NOT change this server's owned
        segments — content moves exclusively through the migrate ops.
        """
        if isinstance(routing, dict):
            routing = RoutingTable.from_payload(routing)
        if routing.epoch <= self.routing.epoch:
            return False
        for t in self.tables:
            if (
                routing.tables[t].owned_segments(self.server_index)
                != self.routing.tables[t].owned_segments(self.server_index)
            ):
                raise ValueError(
                    f"adopt_routing would change owned segments of {t!r} on "
                    f"{self.post.node_id}; use the migration protocol"
                )
        self._install_routing(routing)
        return True

    def _handle_migrate(self, msg: Message) -> Message:
        op = msg.task.payload["op"]
        p = msg.task.payload
        if op == "migrate_begin":
            # donor: arm dirty tracking for [lo, hi).  Idempotent restart: a
            # fresh mid for the same range supersedes any stale attempt.
            mid, t, lo, hi = p["mid"], p["table"], int(p["lo"]), int(p["hi"])
            _, owned = self._try_localize(t, np.arange(lo, hi, dtype=np.int64))
            if not owned.all():
                raise ValueError(
                    f"migrate_begin: {self.post.node_id} does not own "
                    f"[{lo}, {hi}) of {t!r}"
                )
            stale = [
                k
                for k, m in self._migrations.items()
                if (m["table"], m["lo"], m["hi"]) == (t, lo, hi)
            ]
            for k in stale:
                del self._migrations[k]
            self._migrations[mid] = {
                "table": t, "lo": lo, "hi": hi, "dirty": set()
            }
            flightrec.record(
                "migrate.begin", node=self.post.node_id, mid=mid,
                table=t, lo=lo, hi=hi,
            )
            return msg.reply()
        if op == "migrate_send":
            # donor: stream one live chunk to the recipient, keep serving
            # between chunks (requests queued behind this handler bound the
            # per-chunk pause, not the whole transfer)
            m = self._migrations[p["mid"]]
            lo, hi = int(p["lo"]), int(p["hi"])
            flightrec.record(
                "migrate.send", node=self.post.node_id, mid=p["mid"],
                to=p["to"], lo=lo, hi=hi,
            )
            value, state = self.export_range(m["table"], lo, hi)
            skeys = sorted(state)
            self._mig_rpc(
                p["to"],
                {
                    "op": "migrate_stage",
                    "mid": p["mid"],
                    "table": m["table"],
                    "lo": lo,
                    "hi": hi,
                    "state_keys": skeys,
                },
                values=[value] + [state[k] for k in skeys],
            )
            return msg.reply()
        if op == "migrate_stage":
            # recipient: buffer a streamed chunk (host memory, not the table)
            st = self._staging.setdefault(
                p["mid"], {"table": p["table"], "chunks": []}
            )
            value = np.asarray(msg.values[0])
            state = {
                k: np.asarray(v)
                for k, v in zip(p["state_keys"], msg.values[1:])
            }
            st["chunks"].append((int(p["lo"]), int(p["hi"]), value, state))
            flightrec.record(
                "migrate.stage", node=self.post.node_id, mid=p["mid"],
                lo=int(p["lo"]), hi=int(p["hi"]),
            )
            return msg.reply()
        if op == "migrate_commit":
            return self._commit_migration(msg)
        if op == "migrate_install":
            return self._install_migration(msg)
        if op == "migrate_adopt":
            # recipient's standby: adopt the fully-assembled range (chain-
            # forwarded by the recipient inside its install, so it lands
            # after every forwarded push that preceded the handoff)
            routing = RoutingTable.from_payload(p["routing"])
            gids = np.asarray(msg.keys, dtype=np.int64)
            value = np.asarray(msg.values[0])
            state = {
                k: np.asarray(v)
                for k, v in zip(p["state_keys"], msg.values[1:])
            }
            self._install_routing(
                routing, extra={p["table"]: (gids, value, state)}
            )
            self.rows_migrated_in += int(gids.size)
            flightrec.record(
                "migrate.adopt", node=self.post.node_id,
                table=p["table"], rows=int(gids.size),
            )
            return msg.reply()
        if op == "migrate_release":
            # donor's standby: drop the moved range, mirroring the primary
            self._install_routing(RoutingTable.from_payload(p["routing"]))
            flightrec.record(
                "migrate.release", node=self.post.node_id, table=p["table"],
            )
            return msg.reply()
        if op == "migrate_abort":
            self._migrations.pop(p["mid"], None)
            self._staging.pop(p["mid"], None)
            flightrec.record(
                "migrate.abort", node=self.post.node_id, mid=p["mid"],
            )
            return msg.reply()
        raise ValueError(f"unsupported migration op {op!r}")

    def _commit_migration(self, msg: Message) -> Message:
        """Donor commit = the freeze-fence window, bounded to the delta.

        Runs entirely on the recv thread, so no push interleaves: export the
        dirty delta, hand it to the recipient (which installs atomically),
        then shrink the local shard and adopt the new epoch.  Requests queued
        meanwhile hit the NEW table and fence — rejected, not lost.  Donor
        crash before the install ack leaves the old routing everywhere:
        the PR-4 restart path brings the donor back and the migration simply
        re-runs (staged chunks are superseded by the new mid).
        """
        p = msg.task.payload
        m = self._migrations.pop(p["mid"])
        t0 = time.perf_counter()
        new_routing = RoutingTable.from_payload(p["routing"])
        t = m["table"]
        dirty = np.asarray(sorted(m["dirty"]), dtype=np.int64)
        d_value, d_state = self._export_rows(t, dirty)
        skeys = sorted(d_state)
        try:
            self._mig_rpc(
                p["to"],
                {
                    "op": "migrate_install",
                    "mid": p["mid"],
                    "table": t,
                    "lo": m["lo"],
                    "hi": m["hi"],
                    "state_keys": skeys,
                    "routing": new_routing.to_payload(),
                },
                keys=dirty,
                values=[d_value] + [d_state[k] for k in skeys],
            )
        except Exception:
            # install failed: the range is still owned (and served) here —
            # re-arm tracking so the driver can retry/abort cleanly
            self._migrations[p["mid"]] = m
            raise
        # recipient owns the range now: shrink + new epoch, atomically for
        # every request behind this handler
        self._install_routing(new_routing)
        self.rows_migrated_out += m["hi"] - m["lo"]
        if self.replica is not None:
            self._forward_control(
                {
                    "op": "migrate_release",
                    "table": t,
                    "routing": new_routing.to_payload(),
                }
            )
        freeze = time.perf_counter() - t0
        self.migration_freeze_last_s = freeze
        self.migration_freeze_s += freeze
        flightrec.record(
            "migrate.commit", node=self.post.node_id, mid=p["mid"],
            table=t, rows=m["hi"] - m["lo"], dirty=int(dirty.size),
            epoch=new_routing.epoch, freeze_ms=round(1e3 * freeze, 3),
        )
        return msg.reply(values=[np.asarray([freeze], np.float64)])

    def _install_migration(self, msg: Message) -> Message:
        """Recipient install: staged chunks + dirty delta -> grown shard."""
        p = msg.task.payload
        t, lo, hi = p["table"], int(p["lo"]), int(p["hi"])
        st = self._staging.pop(p["mid"], {"chunks": []})
        tbl = self.tables[t]
        n = hi - lo
        dtype = np.asarray(tbl.value).dtype
        value = np.zeros((n, tbl.dim), dtype=dtype)
        state_names = sorted(tbl.state)
        state = {k: np.zeros((n, tbl.dim), dtype=dtype) for k in state_names}
        covered = np.zeros(n, dtype=bool)
        for c_lo, c_hi, c_val, c_state in st["chunks"]:
            a, b = c_lo - lo, c_hi - lo
            value[a:b] = c_val
            for k in state_names:
                state[k][a:b] = c_state[k]
            covered[a:b] = True
        d_ids = np.asarray(msg.keys, dtype=np.int64)
        if d_ids.size:
            d_val = np.asarray(msg.values[0])
            d_state = dict(zip(p["state_keys"], msg.values[1:]))
            idx = d_ids - lo
            value[idx] = d_val
            for k in state_names:
                state[k][idx] = np.asarray(d_state[k])
            covered[idx] = True
        if not covered.all():
            raise RuntimeError(
                f"migrate_install of {t!r}[{lo}:{hi}) on {self.post.node_id}: "
                f"{int((~covered).sum())} rows never staged"
            )
        routing = RoutingTable.from_payload(p["routing"])
        gids = np.arange(lo, hi, dtype=np.int64)
        self._install_routing(routing, extra={t: (gids, value, state)})
        self.rows_migrated_in += n
        flightrec.record(
            "migrate.install", node=self.post.node_id, mid=p["mid"],
            table=t, lo=lo, hi=hi, epoch=routing.epoch,
        )
        if self.replica is not None:
            self._forward_control(
                {
                    "op": "migrate_adopt",
                    "table": t,
                    "lo": lo,
                    "hi": hi,
                    "state_keys": state_names,
                    "routing": routing.to_payload(),
                },
                keys=gids,
                values=[value] + [state[k] for k in state_names],
            )
        return msg.reply()

    # -- checkpoint (reference SaveModel task: servers write their key-range
    # to file; src/app/linear_method/model_evaluation.h [U]) -----------------
    def _handle_control(self, msg: Message) -> Message:
        op = msg.task.payload.get("op")
        if op == "save_model":
            self.save_checkpoint(msg.task.payload["root"], msg.task.payload["step"])
            return msg.reply()
        if op == "load_model":
            self.restore_checkpoint(msg.task.payload["root"], msg.task.payload["step"])
            return msg.reply()
        if op == "adopt_routing":
            self.adopt_routing(msg.task.payload["routing"])
            return msg.reply()
        if op and op.startswith("migrate_"):
            return self._handle_migrate(msg)
        if op and op.startswith("snap_"):
            return self._handle_snapshot(msg)
        if op == "restore_snap":
            self.restore_snapshot(
                msg.task.payload["root"], msg.task.payload["step"]
            )
            return msg.reply()
        if op == "consist_hello":
            return self._handle_consist_hello(msg)
        if op == "consist_set":
            return self._handle_consist_set(msg)
        raise ValueError(f"unsupported control op {op!r}")

    # -- consistency plane control (ISSUE 20) --------------------------------
    def _handle_consist_hello(self, msg: Message) -> Message:
        """Register a worker in the fleet clock(s) BEFORE it trains.

        Up-front registration is what stops a fast worker free-running
        ahead during bring-up: until every peer's first stamped request
        arrives, the clock would not know the fleet is bigger than the
        senders it has seen.  Also the re-registration path after a
        same-id restart (a newer incarnation replaces the dead entry at
        the restored ``step``).
        """
        p = msg.task.payload
        worker = str(p.get("worker") or msg.sender)
        inc = int(p.get("incarnation", 0))
        step = int(p.get("step", 0))
        tname = p.get("table")
        tables = [tname] if tname else list(self._consist)
        for t in tables:
            if t in self._consist:
                self._consist[t]["clock"].hello(worker, inc, step)
        return msg.reply()

    def _handle_consist_set(self, msg: Message) -> Message:
        """Live retune: change a gated table's mode and/or bound.

        The BoundTuner's lever (bound only) and the scenario DSL's
        ``consistency_mode`` phase knob (mode flip mid-run).  A mode flip
        recomputes the bound from the mode semantics unless the payload
        pins one explicitly.
        """
        from parameter_server_tpu.config import ConsistencyMode

        p = msg.task.payload
        tname = p.get("table")
        tables = [tname] if tname else list(self._consist)
        for t in tables:
            st = self._consist.get(t)
            if st is None:
                continue
            if p.get("mode") is not None:
                mode = ConsistencyMode(p["mode"])
                st["mode"] = mode
                if mode == ConsistencyMode.BSP:
                    st["bound"] = 0
                elif mode == ConsistencyMode.ASP:
                    st["bound"] = None
                else:
                    st["bound"] = int(
                        p.get("bound", st["cfg"].max_delay)
                    )
            if p.get("bound") is not None:
                st["bound"] = int(p["bound"])
        return msg.reply()

    def save_checkpoint(self, root: str, step: int) -> None:
        """Write this server's row-range of every table (value + opt state).

        The shard-file format is uniform-contiguous (one ``row_offset`` per
        shard); post-migration layouts (moved/split ranges) are refused with
        a clear error — drain back to the uniform split before checkpointing,
        or rely on replica-chain recovery (the README "Elastic rebalancing"
        section documents this boundary).
        """
        from parameter_server_tpu import checkpoint

        for t, table in self.tables.items():
            part = self.partitions[t]
            uniform = [
                (int(part.offsets[s]), int(part.offsets[s + 1]))
                for s in (self.server_index,)
            ]
            segs = self.routing.tables[t].owned_segments(self.server_index)
            if segs != [seg for seg in uniform if seg[1] > seg[0]]:
                raise checkpoint.CheckpointLayoutError(
                    f"save_checkpoint: {self.post.node_id} owns migrated "
                    f"segments {segs} of {t!r} (uniform shard is {uniform}); "
                    "the legacy shard-file format is uniform-contiguous — "
                    "use the partitioned durability plane "
                    "(KVWorker.save_snapshot) or drain the migration back"
                )
            checkpoint.save_shard(
                root,
                step,
                t,
                table,
                self.server_index,
                part.num_servers,
                int(part.offsets[self.server_index]),
            )

    def restore_checkpoint(self, root: str, step: int) -> None:
        """Load this server's row-range; the saved server count may differ."""
        from parameter_server_tpu import checkpoint

        for t, table in self.tables.items():
            checkpoint.restore_shard(
                root, step, t, table, self.server_index, self.partitions[t].num_servers
            )

    # -- durability plane (ISSUE 16): partitioned incremental snapshots ------
    def _handle_snapshot(self, msg: Message) -> Message:
        """Three-phase snapshot, same shape as live migration.

        - ``snap_begin``  arms per-table dirty-row tracking (the
          ``_ack_push`` hot path adds only host set updates: sync-free);
        - ``snap_write``  bulk-exports ONE owned segment to its own file.
          Runs serially on the recv thread, so pushes interleave *between*
          segments — the table is never frozen for the bulk copy.  If the
          segment's version clock has not advanced past the driver's
          ``base_sver``, nothing is written and the driver carries the
          base manifest entry forward (the incremental path);
        - ``snap_commit`` is the only freeze: export the rows dirtied
          since ``snap_begin`` as the delta log and stamp commit-time
          segment versions.  Bounded by the dirty set exactly like
          :meth:`_commit_migration`, measured and recorded;
        - ``snap_abort``  drops the bookkeeping (files left behind are
          garbage a manifest never references — retention sweeps them).
        """
        from parameter_server_tpu import checkpoint

        p = msg.task.payload
        op = p["op"]
        if op == "snap_begin":
            sid = str(p["sid"])
            self._snapshots[sid] = {"dirty": {}}
            flightrec.record("ckpt.begin", node=self.post.node_id, sid=sid)
            return msg.reply()
        if op == "snap_abort":
            sn = self._snapshots.pop(str(p["sid"]), None)
            if sn is not None:
                flightrec.record(
                    "ckpt.abort", node=self.post.node_id, sid=str(p["sid"]),
                    why=str(p.get("why", "driver abort")),
                )
            return msg.reply()
        sid = str(p["sid"])
        if sid not in self._snapshots:
            raise RuntimeError(
                f"snapshot {sid!r} is not open on {self.post.node_id} "
                "(aborted by a routing change?)"
            )
        if op == "snap_write":
            t, lo, hi = p["table"], int(p["lo"]), int(p["hi"])
            starts, ends, _ = self._shard_maps[t]
            hit = np.nonzero((starts == lo) & (ends == hi))[0]
            if hit.size != 1:
                raise RuntimeError(
                    f"snap_write: {self.post.node_id} does not own segment "
                    f"{t}[{lo}:{hi}) as a whole"
                )
            cur = int(self._seg_versions[t][int(hit[0])])
            base = p.get("base_sver")
            reply = msg.reply()
            if base is not None and int(base) == cur:
                # version clock unchanged since the base snapshot: the
                # driver re-uses the base file + CRC (ship only deltas)
                reply.task = dataclasses.replace(
                    msg.task,
                    payload={"carried": True, "sver": cur, "table": t,
                             "lo": lo, "hi": hi},
                )
                return reply
            value, state = self.export_range(t, lo, hi)
            entry = checkpoint.write_segment_file(
                str(p["root"]), int(p["step"]), t, lo, hi, value, state
            )
            flightrec.record(
                "ckpt.segment", node=self.post.node_id, sid=sid, table=t,
                lo=lo, hi=hi, bytes=entry["bytes"],
            )
            reply.task = dataclasses.replace(
                msg.task,
                payload={"carried": False, "sver": cur, "table": t,
                         "lo": lo, "hi": hi, "entry": entry},
            )
            return reply
        if op == "snap_commit":
            sn = self._snapshots.pop(sid)
            t0 = time.perf_counter()
            root, step = str(p["root"]), int(p["step"])
            deltas: List[dict] = []
            n_dirty = 0
            for t in sorted(sn["dirty"]):
                gids = np.asarray(sorted(sn["dirty"][t]), dtype=np.int64)
                if not gids.size:
                    continue
                value, state = self._export_rows(t, gids)
                entry = checkpoint.write_delta_file(
                    root, step, t, self.server_index, gids, value, state
                )
                if entry is not None:
                    deltas.append(entry)
                    n_dirty += int(gids.size)
            svers = [
                [t, int(s), int(e), int(v)]
                for t in sorted(self.tables)
                for s, e, v in zip(
                    self._shard_maps[t][0], self._shard_maps[t][1],
                    self._seg_versions[t],
                )
            ]
            freeze = time.perf_counter() - t0
            self.ckpt_freeze_last_s = freeze
            self.ckpt_freeze_s += freeze
            self.ckpt_commits += 1
            self.ckpt_delta_rows += n_dirty
            over = n_dirty > self.ckpt_max_delta_rows
            if over:
                # soft bound: the snapshot still commits, but the breach
                # is visible (counter + event) so the interval can be
                # tightened before the freeze grows further
                self.ckpt_delta_overflow += 1
            self._ckpt_commit_t = time.monotonic()
            flightrec.record(
                "ckpt.commit", node=self.post.node_id, sid=sid, step=step,
                dirty=n_dirty, freeze_ms=round(1e3 * freeze, 3),
                over_bound=over,
            )
            reply = msg.reply()
            reply.task = dataclasses.replace(
                msg.task,
                payload={"deltas": deltas, "svers": svers,
                         "freeze_s": freeze},
            )
            return reply
        raise ValueError(f"unsupported snapshot op {op!r}")

    def restore_snapshot(
        self, root: str, step: int, *, adopt_routing: bool = False
    ) -> None:
        """Point-in-time restore from a partitioned snapshot.

        Reads only the manifest plus the file ranges covering the segments
        THIS server owns under its CURRENT routing table — the snapshot may
        have been written by a fleet of any shape (the reshard happens row-
        wise in :func:`checkpoint.snapshot_rows`).  Re-seeds the per-segment
        version clock from the manifest so the staleness plane stays
        monotonic across the restore.

        ``adopt_routing``: first adopt the manifest's routing table when it
        is NEWER than this server's — the same-id-restart path, where a
        freshly constructed server starts at the uniform epoch 0 but the
        snapshot was written by a fleet that had since migrated; without
        the adoption the restarted server would not own its migrated
        segments and every worker leg into them would fence forever.
        Fleet-shape restores (``load_snapshot``) keep it off: there the
        CURRENT fleet's routing is authoritative, not the writer's.
        """
        from parameter_server_tpu import checkpoint

        manifest = checkpoint.read_snapshot(root, step)
        if adopt_routing:
            snap_routing = RoutingTable.from_payload(manifest["routing"])
            if snap_routing.epoch > self.routing.epoch:
                # metadata-only adoption — no content hand-off like
                # ``_install_routing`` does for migrations, because every
                # owned row is about to be overwritten from the snapshot
                # (``install_rows`` below re-sizes the shard storage)
                self.routing = snap_routing
                self._shard_maps = {
                    t: self._make_map(snap_routing, t) for t in self.tables
                }
                self._seg_versions = {
                    t: np.zeros(
                        self._shard_maps[t][0].shape[0], dtype=np.int64
                    )
                    for t in self.tables
                }
        by_seg: Dict[Tuple[str, int, int], int] = {}
        for e in manifest["segments"]:
            key = (str(e["table"]), int(e["lo"]), int(e["hi"]))
            by_seg[key] = max(by_seg.get(key, 0), int(e.get("sver", 0)))
        for t, table in self.tables.items():
            segs = self.routing.tables[t].owned_segments(self.server_index)
            checkpoint.restore_segments(root, manifest, t, segs, table)
            ver = self._seg_versions[t]
            starts, ends, _ = self._shard_maps[t]
            for i in range(starts.shape[0]):
                lo, hi = int(starts[i]), int(ends[i])
                # exact match first; else the max over overlapping source
                # segments (restore onto a different fleet shape)
                v = by_seg.get((t, lo, hi))
                if v is None:
                    v = max(
                        (
                            sv for (tt, sl, sh), sv in by_seg.items()
                            if tt == t and sl < hi and sh > lo
                        ),
                        default=0,
                    )
                ver[i] = max(int(ver[i]), v)
        self._ckpt_commit_t = time.monotonic()
        flightrec.record(
            "ckpt.restore", node=self.post.node_id, step=int(step),
            tables=len(self.tables),
        )
