"""Hot-replica failover: chain replication of server key ranges.

The reference paper recovers a dead server's key range from a replica chain
(paper §4.3 [U]; the open tree only had snapshot restore — SURVEY.md §5
failure row).  Rounds 1–3 matched the open tree: server death rewound to the
last checkpoint, losing every update since (``learner/elastic.py``).  This
module closes the gap (VERDICT r3 #6):

- a **standby** is just another :class:`~parameter_server_tpu.kv.server.KVServer`
  holding the same shard (same ``server_index``/``num_servers`` — identical
  row range AND identical init seed), bound under a replica node id;
- the **primary** (``KVServer(replica="R0", ...)``) forwards every applied
  push to it in apply order over the Van, so table values and optimizer
  state replay identically — synchronously (zero loss: the worker's ack
  waits for the chain) or async with bounded lag;
- on primary death, :func:`promote` rebinds the standby's endpoint under the
  primary's node id: workers keep addressing ``S{i}`` and the trajectory
  continues WITHOUT the checkpoint rewind.

Scope: promotion rebinds a Van endpoint, which is in-process state — it
covers the LoopbackVan runtime (and any Van whose ``bind`` is cheap).  On
the cross-process TcpVan the same event is a manager route-table broadcast
(new address for ``S{i}``) — the forwarding protocol is Van-agnostic and
already crosses sockets unchanged; only the rebind differs.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, Optional

from parameter_server_tpu.config import TableConfig
from parameter_server_tpu.core import flightrec
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core.van import Van
from parameter_server_tpu.kv.routing import RoutingTable
from parameter_server_tpu.kv.server import KVServer


def replica_id(server_index: int) -> str:
    return f"R{server_index}"


def make_replicated_servers(
    van: Van,
    table_cfgs: Dict[str, TableConfig],
    num_servers: int,
    *,
    sync: bool = True,
    max_lag: int = 8,
    device_replies: bool = False,
    routing: Optional[RoutingTable] = None,
) -> tuple[list[KVServer], list[KVServer]]:
    """Build ``num_servers`` primaries, each chained to a hot standby.

    Returns ``(primaries, standbys)``; standby ``i`` mirrors shard ``i``.
    ``routing`` seeds a non-uniform ownership map on BOTH sides of every
    chain (a standby must hold its primary's exact shard layout — migration
    control ops are chain-forwarded, so the pair stays in lockstep).
    """
    standbys = [
        KVServer(
            Postoffice(replica_id(s), van),
            table_cfgs,
            s,
            num_servers,
            device_replies=device_replies,
            routing=routing,
        )
        for s in range(num_servers)
    ]
    primaries = [
        KVServer(
            Postoffice(f"S{s}", van),
            table_cfgs,
            s,
            num_servers,
            device_replies=device_replies,
            replica=replica_id(s),
            replica_sync=sync,
            max_replica_lag=max_lag,
            routing=routing,
        )
        for s in range(num_servers)
    ]
    return primaries, standbys


def promote(van: Van, standby: KVServer, primary_id: str) -> KVServer:
    """Take over a dead primary's identity with its hot standby.

    Rebinds the standby's Van endpoint under ``primary_id`` so worker
    traffic addressed to the dead server now lands on the replica, whose
    state is the primary's last applied (sync) or lag-bounded (async)
    update.  Replies carry ``primary_id`` as sender, so in-flight pull
    bookkeeping on workers keeps working.  Returns the standby.

    The standby stops answering under its old replica id (endpoint
    unbound); it has no replica of its own — re-chain by constructing a new
    standby and setting ``standby.replica`` if continued protection is
    needed.
    """
    post = standby.post
    old_id = post.node_id
    try:
        van.unbind(primary_id)  # drop the dead primary's endpoint, if any
    except Exception:  # noqa: BLE001 — already gone is fine
        pass
    # identity BEFORE endpoint (ADVICE r4): a request landing in the bind ->
    # node_id window would be answered under the old R{i} sender id, which
    # breaks workers' in-flight pull/push bookkeeping (replies must carry
    # primary_id, as promised above).  The old endpoint is unbound right
    # after, so misdirected old-endpoint replies are not a concern.
    post.node_id = primary_id
    van.bind(primary_id, post._on_recv)
    van.unbind(old_id)
    # fault-injection vans blackhole traffic by node id (the dead process's
    # socket); the promoted standby re-opens the identity
    reconnect = getattr(van, "reconnect", None)
    if reconnect is not None:
        reconnect(primary_id)
    flightrec.record(
        "node.promote", node=primary_id, standby=old_id,
    )
    return standby


def restart_same_id(
    van: Van,
    table_cfgs: Dict[str, TableConfig],
    server_index: int,
    num_servers: int,
    *,
    standby: Optional[KVServer] = None,
    ckpt_root: Optional[str] = None,
    register: Optional[Callable[[Postoffice], None]] = None,
    device_replies: bool = False,
    replica_sync: bool = True,
    max_lag: int = 8,
    routing: Optional[RoutingTable] = None,
) -> tuple[KVServer, str]:
    """Bring ``S{server_index}`` back under its OWN node id after a crash.

    The same-id restart lifecycle (ISSUE: incarnation-fenced restart, the
    production alternative to :func:`promote`'s id takeover):

    1. the dead process's endpoints (``S{i}`` and its ``S{i}.fw`` forwarding
       client) are unbound defensively and the identity stays DISCONNECTED
       while state restores — a worker retransmit landing on a cold table
       that an import then overwrites would be an acked-but-lost update;
    2. a fresh :class:`KVServer` is built (same index ⇒ same row range and
       deterministic init seed), then its shard restores from the live
       ``standby`` (:meth:`KVServer.export_shard`, preferred: bit-identical
       including optimizer state, ZERO loss under a sync chain) or from the
       latest committed checkpoint in ``ckpt_root`` (fallback: bounded
       rewind ≤ the checkpoint interval).  With neither the shard re-inits
       cold (the deterministic seed at least keeps restarts reproducible);
    3. dedup windows INTO ``S{i}`` are kept on the replica path — a sync
       chain's applied-set equals the windows' content, so the preserved
       windows ARE the recovered exactly-once state (a pre-crash push whose
       ACK was lost is deduped, and its effect arrives via the import).  On
       the checkpoint/cold paths the windows LIE (they claim delivery of
       effects the rewind lost), so ``drop_inbound_state`` clears them and
       still-retransmitting frames re-apply inside the accepted rewind;
    4. the identity reconnects and ``register`` (when given) re-registers
       with the scheduler, which bumps the node's incarnation and broadcasts
       the new binding — peers reset seq windows for frames FROM ``S{i}``
       and fence any zombie frames of the dead process.

    Returns ``(server, source)`` with source in {"replica", "partitioned",
    "checkpoint", "cold"} — replica chain first, then the partitioned
    durability-plane snapshot, then the legacy uniform checkpoint.  The new
    server re-chains to the standby's id when a standby is passed, so
    protection continues after the restart.
    """
    primary_id = f"S{server_index}"
    # .fw = replica-forwarding client, .mig = migration-streaming client —
    # both are the dead process's endpoints and must not answer for it
    for nid in (primary_id, f"{primary_id}.fw", f"{primary_id}.mig"):
        try:
            van.unbind(nid)
        except Exception:  # noqa: BLE001 — already unbound is the normal case
            pass
    # keep the identity dark while restoring (see docstring step 1); vans
    # without disconnect() are in-process test stacks where the caller
    # controls traffic, so the guard degrades safely
    disconnect = getattr(van, "disconnect", None)
    if disconnect is not None:
        disconnect(primary_id)
    if routing is None and standby is not None:
        # a post-migration shard layout lives in the standby's routing; the
        # restarted server must be built with the SAME map or the imported
        # arrays would not fit its tables
        routing = standby.routing
    server = KVServer(
        Postoffice(primary_id, van),
        table_cfgs,
        server_index,
        num_servers,
        device_replies=device_replies,
        replica=None if standby is None else standby.post.node_id,
        replica_sync=replica_sync,
        max_replica_lag=max_lag,
        routing=routing,
    )
    if standby is not None:
        server.import_shard(standby.export_shard())
        source = "replica"
    else:
        from parameter_server_tpu import checkpoint

        # restore-source ordering: replica chain (freshest, handled above)
        # > partitioned snapshot (any layout, incremental-aware) > legacy
        # uniform checkpoint > cold.  A corrupt/torn snapshot falls through
        # to the next source instead of wedging the restart.
        source = "cold"
        if ckpt_root is not None:
            snap = checkpoint.latest_snapshot(ckpt_root)
            if snap is not None:
                try:
                    # adopt the manifest's routing: the restarted server
                    # must rejoin at the fleet's (snapshot-time) epoch or
                    # it would not own its migrated segments
                    server.restore_snapshot(
                        ckpt_root, snap, adopt_routing=True
                    )
                    source = "partitioned"
                except (OSError, checkpoint.CheckpointCorruptError):
                    source = "cold"
            if source == "cold":
                step = checkpoint.latest_step(ckpt_root)
                if step is not None:
                    server.restore_checkpoint(ckpt_root, step)
                    source = "checkpoint"
        if hasattr(van, "drop_inbound_state"):
            van.drop_inbound_state(primary_id)
    logging.getLogger(__name__).info(
        "restart_same_id: %s restored from %s", primary_id, source
    )
    flightrec.record(
        "node.restart", node=primary_id, source=source,
    )
    for nid in (primary_id, f"{primary_id}.fw", f"{primary_id}.mig"):
        reconnect = getattr(van, "reconnect", None)
        if reconnect is not None:
            reconnect(nid)
    if register is not None:
        register(server.post)
    return server, source


class ReplicaSet:
    """Wire hot-standby promotion into the Manager's failure detection.

    The composition the reference paper describes (heartbeats -> dead
    server -> chain replica takes over the key range [U §4.3]): register
    this on the SCHEDULER's manager and a missed-heartbeat death of
    ``S{i}`` promotes standby ``i`` automatically — workers' next
    pull/push to ``S{i}`` lands on the replica with the full post-
    checkpoint state, instead of the snapshot-restore rewind
    (``learner/elastic.py``'s fallback for un-replicated shards).
    """

    def __init__(self, van: Van, standbys: list, *, manager=None) -> None:
        self.van = van
        self.standbys = list(standbys)
        self.promoted: dict[int, KVServer] = {}
        if manager is not None:
            manager.on_node_dead.append(self.on_node_dead)

    def on_node_dead(self, node_id: str) -> None:
        if not (node_id.startswith("S") and node_id[1:].isdigit()):
            return  # worker deaths are the WorkloadPool's problem
        idx = int(node_id[1:])
        if idx in self.promoted or idx >= len(self.standbys):
            return
        self.promoted[idx] = promote(self.van, self.standbys[idx], node_id)
