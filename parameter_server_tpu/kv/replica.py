"""Hot-replica failover: chain replication of server key ranges.

The reference paper recovers a dead server's key range from a replica chain
(paper §4.3 [U]; the open tree only had snapshot restore — SURVEY.md §5
failure row).  Rounds 1–3 matched the open tree: server death rewound to the
last checkpoint, losing every update since (``learner/elastic.py``).  This
module closes the gap (VERDICT r3 #6):

- a **standby** is just another :class:`~parameter_server_tpu.kv.server.KVServer`
  holding the same shard (same ``server_index``/``num_servers`` — identical
  row range AND identical init seed), bound under a replica node id;
- the **primary** (``KVServer(replica="R0", ...)``) forwards every applied
  push to it in apply order over the Van, so table values and optimizer
  state replay identically — synchronously (zero loss: the worker's ack
  waits for the chain) or async with bounded lag;
- on primary death, :func:`promote` rebinds the standby's endpoint under the
  primary's node id: workers keep addressing ``S{i}`` and the trajectory
  continues WITHOUT the checkpoint rewind.

Scope: promotion rebinds a Van endpoint, which is in-process state — it
covers the LoopbackVan runtime (and any Van whose ``bind`` is cheap).  On
the cross-process TcpVan the same event is a manager route-table broadcast
(new address for ``S{i}``) — the forwarding protocol is Van-agnostic and
already crosses sockets unchanged; only the rebind differs.
"""

from __future__ import annotations

from typing import Dict

from parameter_server_tpu.config import TableConfig
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core.van import Van
from parameter_server_tpu.kv.server import KVServer


def replica_id(server_index: int) -> str:
    return f"R{server_index}"


def make_replicated_servers(
    van: Van,
    table_cfgs: Dict[str, TableConfig],
    num_servers: int,
    *,
    sync: bool = True,
    max_lag: int = 8,
    device_replies: bool = False,
) -> tuple[list[KVServer], list[KVServer]]:
    """Build ``num_servers`` primaries, each chained to a hot standby.

    Returns ``(primaries, standbys)``; standby ``i`` mirrors shard ``i``.
    """
    standbys = [
        KVServer(
            Postoffice(replica_id(s), van),
            table_cfgs,
            s,
            num_servers,
            device_replies=device_replies,
        )
        for s in range(num_servers)
    ]
    primaries = [
        KVServer(
            Postoffice(f"S{s}", van),
            table_cfgs,
            s,
            num_servers,
            device_replies=device_replies,
            replica=replica_id(s),
            replica_sync=sync,
            max_replica_lag=max_lag,
        )
        for s in range(num_servers)
    ]
    return primaries, standbys


def promote(van: Van, standby: KVServer, primary_id: str) -> KVServer:
    """Take over a dead primary's identity with its hot standby.

    Rebinds the standby's Van endpoint under ``primary_id`` so worker
    traffic addressed to the dead server now lands on the replica, whose
    state is the primary's last applied (sync) or lag-bounded (async)
    update.  Replies carry ``primary_id`` as sender, so in-flight pull
    bookkeeping on workers keeps working.  Returns the standby.

    The standby stops answering under its old replica id (endpoint
    unbound); it has no replica of its own — re-chain by constructing a new
    standby and setting ``standby.replica`` if continued protection is
    needed.
    """
    post = standby.post
    old_id = post.node_id
    try:
        van.unbind(primary_id)  # drop the dead primary's endpoint, if any
    except Exception:  # noqa: BLE001 — already gone is fine
        pass
    # identity BEFORE endpoint (ADVICE r4): a request landing in the bind ->
    # node_id window would be answered under the old R{i} sender id, which
    # breaks workers' in-flight pull/push bookkeeping (replies must carry
    # primary_id, as promised above).  The old endpoint is unbound right
    # after, so misdirected old-endpoint replies are not a concern.
    post.node_id = primary_id
    van.bind(primary_id, post._on_recv)
    van.unbind(old_id)
    # fault-injection vans blackhole traffic by node id (the dead process's
    # socket); the promoted standby re-opens the identity
    reconnect = getattr(van, "reconnect", None)
    if reconnect is not None:
        reconnect(primary_id)
    return standby


class ReplicaSet:
    """Wire hot-standby promotion into the Manager's failure detection.

    The composition the reference paper describes (heartbeats -> dead
    server -> chain replica takes over the key range [U §4.3]): register
    this on the SCHEDULER's manager and a missed-heartbeat death of
    ``S{i}`` promotes standby ``i`` automatically — workers' next
    pull/push to ``S{i}`` lands on the replica with the full post-
    checkpoint state, instead of the snapshot-restore rewind
    (``learner/elastic.py``'s fallback for un-replicated shards).
    """

    def __init__(self, van: Van, standbys: list, *, manager=None) -> None:
        self.van = van
        self.standbys = list(standbys)
        self.promoted: dict[int, KVServer] = {}
        if manager is not None:
            manager.on_node_dead.append(self.on_node_dead)

    def on_node_dead(self, node_id: str) -> None:
        if not (node_id.startswith("S") and node_id[1:].isdigit()):
            return  # worker deaths are the WorkloadPool's problem
        idx = int(node_id[1:])
        if idx in self.promoted or idx >= len(self.standbys):
            return
        self.promoted[idx] = promote(self.van, self.standbys[idx], node_id)
