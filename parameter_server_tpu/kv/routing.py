"""Epoch-versioned routing tables: which server owns which row range.

PR-6 replaces the implicit uniform :class:`~parameter_server_tpu.kv.partition.
RangePartition` (frozen at launch) with an explicit routing table that live
migration can rewrite.  The reference treats dynamic key-range reassignment
as a first-class primitive (Li et al. §4.3 — a recovering/retiring server
hands its range to peers); here the same idea runs over the incarnation /
fencing substrate of PRs 1–4:

- a :class:`RoutingTable` is an immutable value stamped with an **epoch**;
  every :meth:`RoutingTable.move` returns a NEW table at ``epoch + 1``;
- workers stamp the epoch onto every PUSH/PULL (``__repoch__``); a server
  whose table disagrees answers with a typed ``__error__`` reply carrying
  ``__fenced__`` and its own table (``__routing__``) — **rejected, not
  lost**: the worker adopts the highest-epoch table it has seen and retries
  exactly the rejected positions;
- the scheduler (``core/manager.py``) owns the authoritative copy and
  broadcasts it (ROUTING control verb), but fences are self-healing, so a
  worker that missed the broadcast converges lazily off the rejects.

Unlike ``RangePartition``, segments are arbitrary ``(offsets, owners)``
splits: one server may own several disjoint ranges and a table's owners
need not be ``0..n-1``.  Workers therefore ship **global** row ids on the
wire and servers localize against their own shard map — local ids would be
ambiguous the moment a range moves.
"""

from __future__ import annotations

import bisect
import dataclasses
import functools
import zlib
from typing import Dict, Iterator, List, Tuple

import numpy as np

#: Task.payload key: routing epoch stamped by workers on every PUSH/PULL.
ROUTING_EPOCH_KEY = "__repoch__"
#: reply payload key: serialized RoutingTable riding a fence reject.
ROUTING_KEY = "__routing__"
#: reply payload key: marks a typed fence reject (wrong epoch / not owner).
FENCED_KEY = "__fenced__"
#: reply payload key: server-side segment version clock stamped onto PUSH
#: acks and PULL replies (max over the segments the request touched) — the
#: staleness plane's wire carrier (ISSUE 10).  Lives here with the other
#: wire keys because it is part of the same request/reply payload contract.
VERSION_KEY = "__sver__"
#: reply payload key: soft-backpressure hint stamped onto PUSH acks when
#: the server's ApplyLedger backlog exceeds its configured bound (ISSUE
#: 12).  Advisory, not a reject: the update WAS accepted; the worker's
#: admission control should slow down or shed load.  Same wire-contract
#: home as the other reply keys.
BUSY_KEY = "__busy__"
#: request payload key: marks a PULL as read-only serving traffic (ISSUE
#: 13).  The server answers it on the fast path — gather + one D2H per
#: bundle, its own latency histogram — WITHOUT flushing the open push
#: group of the bundle-batched apply engine, so a read-only pull observes
#: the table as of dispatch, not as of the bundle's writes (the serving
#: plane's relaxed-read contract).  Routing fences still apply: a
#: read-only pull is never served from rows this server does not own.
READ_ONLY_KEY = "__ro__"
#: request payload key: hierarchical-push group stamp (ISSUE 15).  A dict
#: ``{"op", "id", "n", "step", "ef", ...}`` riding worker-to-worker
#: contribution/handoff/done CONTROL frames and the elected leader's wire
#: PUSH.  On a PUSH it marks the frame as ONE logical apply for the whole
#: group (``n`` = contributing members) — the server's dup policy and
#: ApplyLedger already treat it as a single apply, and group accounting
#: (``KVServer.counters()``) reads ``n`` for the fan-in ratio.  Pre-group
#: servers ignore unknown payload keys, so stamped frames are
#: rolling-upgrade safe (MIGRATION.md).  Mirrored as ``_GROUP_KEY`` in
#: ``core/filters.py`` (import would cycle); test_group asserts equality.
GROUP_KEY = "__grp__"
#: request payload key: the sender's committed step for the addressed
#: table (ISSUE 20).  Stamped (plain int — stays on the fast meta codec)
#: on PUSH/PULL only when ``TableConfig.consistency`` is set; servers fold
#: it into their per-table fleet vector clock and gate the request against
#: the configured BSP/SSP bound.  Unstamped requests (old workers, ungated
#: tables) bypass the gate entirely — zero wire change.
CONSIST_STEP_KEY = "__cstep__"
#: reply payload key: typed consistency defer (ISSUE 20).  Stamped onto a
#: reply that also carries ``FENCED_KEY`` + ``ROUTING_KEY`` — the reply is
#: deliberately FENCE-SHAPED so pre-ISSUE-20 workers fall into their
#: existing fence-retry loop (ignored-as-retry: MIGRATION.md) — plus the
#: current fleet clock snapshot, fleet minimum, bound and a ``retry_after``
#: backoff hint.  New workers check this key FIRST: a wait is not a fence
#: (routing is fine), so waited positions retry without consuming the
#: fence-retry budget, under the table's ``gate_deadline_s``.
WAIT_KEY = "__wait__"


@dataclasses.dataclass(frozen=True)
class WorkerGroup:
    """Membership + deterministic per-step leader election (ISSUE 15).

    A group is the static set of co-located workers that pre-reduce their
    PUSH value planes before the wire.  :meth:`leader` is a pure function
    of ``(table, step)`` — every member computes the same answer with no
    coordination — and under ``"rotate"`` the elected leg rotates so wire
    load spreads evenly; the crc32 table offset de-phases tables so a
    multi-table step does not elect the same member for every table.

    ``salt`` re-elects deterministically: the fence-retry loops pass the
    attempt number, so a leader whose wire push was fenced mid-migration
    hands the retry to the next member instead of hammering one leg.
    """

    members: Tuple[str, ...]
    #: "rotate" (per-(table, step) rotation) or "fixed" (always member 0 —
    #: the mode that keeps ISSUE-14 error-feedback residuals owned by one
    #: sender; see ``config.GroupConfig``).
    election: str = "rotate"

    def __post_init__(self) -> None:
        if not self.members:
            raise ValueError("a worker group needs at least one member")
        if len(set(self.members)) != len(self.members):
            raise ValueError(f"duplicate group members: {self.members}")
        if self.election not in ("rotate", "fixed"):
            raise ValueError(
                f"election must be rotate|fixed, got {self.election!r}"
            )

    @property
    def size(self) -> int:
        return len(self.members)

    @property
    def gid(self) -> str:
        """Stable group id (member-derived; stamped onto group frames)."""
        return "+".join(self.members)

    def leader(self, table: str, step: int, salt: int = 0) -> str:
        """The member elected to push ``table``'s reduced tensor at
        ``step``; ``salt`` > 0 deterministically re-elects (fence retry)."""
        if self.election == "fixed" and salt == 0:
            return self.members[0]
        idx = (zlib.crc32(table.encode()) + int(step) + int(salt)) % len(
            self.members
        )
        return self.members[idx]


@dataclasses.dataclass(frozen=True)
class TableRouting:
    """One table's ownership map: ``owners[i]`` owns ``[offsets[i],
    offsets[i+1])`` of the global row space ``[0, rows)``.

    The trash row (global id == ``rows``, the PAD contract) is owned by the
    LAST segment's owner — the same rule ``RangePartition`` used for the
    last server, so uniform tables route identically to the legacy split.
    """

    rows: int
    offsets: Tuple[int, ...]
    owners: Tuple[int, ...]

    def __post_init__(self) -> None:
        off, own = self.offsets, self.owners
        if len(off) != len(own) + 1:
            raise ValueError(f"offsets/owners length mismatch: {off} / {own}")
        if not own:
            raise ValueError("a table needs at least one segment")
        if off[0] != 0 or off[-1] != self.rows:
            raise ValueError(f"offsets must span [0, {self.rows}): {off}")
        if any(b <= a for a, b in zip(off, off[1:])):
            raise ValueError(f"offsets must be strictly increasing: {off}")
        if any(s < 0 for s in own):
            raise ValueError(f"owners must be non-negative: {own}")

    @functools.cached_property
    def _off(self) -> np.ndarray:
        return np.asarray(self.offsets, dtype=np.int64)

    @classmethod
    def uniform(cls, rows: int, num_servers: int) -> "TableRouting":
        """The legacy even-contiguous split (RangePartition-compatible)."""
        base, rem = divmod(rows, num_servers)
        sizes = [base + (1 if s < rem else 0) for s in range(num_servers)]
        # zero-row servers own no segment (tiny tables on big fleets)
        offsets, owners = [0], []
        for s, size in enumerate(sizes):
            if size > 0:
                owners.append(s)
                offsets.append(offsets[-1] + size)
        return cls(rows, tuple(offsets), tuple(owners))

    # -- queries -------------------------------------------------------------
    def distinct_owners(self) -> Tuple[int, ...]:
        return tuple(sorted(set(self.owners)))

    def segments(self) -> List[Tuple[int, int, int]]:
        """All segments as ``[(lo, hi, owner), ...]`` in row order.

        The durability plane's iteration unit (ISSUE 16): a partitioned
        snapshot writes exactly one file per entry here, owned by
        ``owner``, so any layout this table can express can snapshot.
        """
        return [
            (int(self.offsets[i]), int(self.offsets[i + 1]), int(o))
            for i, o in enumerate(self.owners)
        ]

    def owned_segments(self, server: int) -> List[Tuple[int, int]]:
        """``[(lo, hi), ...]`` global ranges owned by ``server``, in order."""
        return [
            (int(self.offsets[i]), int(self.offsets[i + 1]))
            for i, o in enumerate(self.owners)
            if o == server
        ]

    def server_rows(self, server: int) -> int:
        return sum(hi - lo for lo, hi in self.owned_segments(server))

    def owner_of(self, row: int) -> int:
        """Owner of global ``row``; the trash row (== rows) maps to the
        last segment's owner."""
        if row >= self.rows:
            return self.owners[-1]
        i = bisect.bisect_right(self.offsets, row) - 1
        return self.owners[i]

    # -- rewrites ------------------------------------------------------------
    def move(self, lo: int, hi: int, to: int) -> "TableRouting":
        """Reassign global rows ``[lo, hi)`` to server ``to``.

        Splits segments at the boundaries, then coalesces adjacent segments
        of the same owner, so the map stays canonical (two moves that land
        on the same ownership compare equal).
        """
        if not (0 <= lo < hi <= self.rows):
            raise ValueError(f"bad range [{lo}, {hi}) for rows={self.rows}")
        bounds = sorted(set(self.offsets) | {lo, hi})
        offsets, owners = [0], []
        for a, b in zip(bounds, bounds[1:]):
            o = to if lo <= a < hi else self.owner_of(a)
            if owners and o == owners[-1]:
                offsets[-1] = b  # coalesce with the previous segment
            else:
                owners.append(o)
                offsets.append(b)
        return TableRouting(self.rows, tuple(offsets), tuple(owners))


@dataclasses.dataclass(frozen=True)
class RoutingTable:
    """Epoch-stamped ownership maps for every registered table.

    Immutable: rewrites go through :meth:`move`, which bumps the epoch —
    the monotonic epoch is what lets every node adopt "highest epoch wins"
    without coordination (a fence reply carrying an OLDER table is simply
    ignored; see ``KVWorker.adopt_routing``).
    """

    epoch: int
    tables: Dict[str, TableRouting]

    @classmethod
    def uniform(cls, table_cfgs, num_servers: int, *, epoch: int = 0):
        """Epoch-0 table matching the legacy RangePartition split.

        ``table_cfgs``: ``{name: TableConfig}`` (anything with ``.rows``)
        or ``{name: rows}``.
        """
        tables = {
            t: TableRouting.uniform(int(getattr(cfg, "rows", cfg)), num_servers)
            for t, cfg in table_cfgs.items()
        }
        return cls(epoch, tables)

    def servers(self) -> Tuple[int, ...]:
        """Sorted distinct owners across all tables."""
        out: set = set()
        for tr in self.tables.values():
            out.update(tr.owners)
        return tuple(sorted(out))

    def move(self, table: str, lo: int, hi: int, to: int) -> "RoutingTable":
        tables = dict(self.tables)
        tables[table] = tables[table].move(lo, hi, to)
        return RoutingTable(self.epoch + 1, tables)

    # -- request slicing (the Parameter::Slice analogue) ---------------------
    def slice_ids(
        self, table: str, sorted_ids: np.ndarray
    ) -> Iterator[Tuple[int, np.ndarray, np.ndarray]]:
        """Split sorted global row ids by owning server.

        Yields ``(server, positions, ids)`` for EVERY distinct owner of
        ``table`` (empty included — BSP tasks expect a response per server):
        ``positions`` indexes into ``sorted_ids`` (a server owning several
        segments gets ONE merged message — ``Customer._on_response`` counts
        at most one response per sender per ts), ``ids`` are the global rows
        at those positions, still ascending.  Pad ids (== rows) ride with
        the last segment's owner, as in the legacy split.
        """
        tr = self.tables[table]
        n = sorted_ids.shape[0]
        cut = np.searchsorted(sorted_ids, tr._off[1:-1], side="left")
        bounds = np.concatenate([[0], cut, [n]])
        per_owner: Dict[int, list] = {o: [] for o in tr.owners}
        for i, o in enumerate(tr.owners):
            a, b = int(bounds[i]), int(bounds[i + 1])
            if b > a:
                per_owner[o].append(np.arange(a, b, dtype=np.int64))
        for o in sorted(per_owner):
            segs = per_owner[o]
            pos = (
                np.concatenate(segs) if segs else np.empty(0, dtype=np.int64)
            )
            yield o, pos, sorted_ids[pos]

    # -- wire form -----------------------------------------------------------
    def to_payload(self) -> dict:
        return {
            "epoch": int(self.epoch),
            "tables": {
                t: {
                    "rows": int(tr.rows),
                    "offsets": [int(x) for x in tr.offsets],
                    "owners": [int(x) for x in tr.owners],
                }
                for t, tr in self.tables.items()
            },
        }

    @classmethod
    def from_payload(cls, payload: dict) -> "RoutingTable":
        tables = {
            t: TableRouting(
                int(blob["rows"]),
                tuple(int(x) for x in blob["offsets"]),
                tuple(int(x) for x in blob["owners"]),
            )
            for t, blob in payload["tables"].items()
        }
        return cls(int(payload["epoch"]), tables)
