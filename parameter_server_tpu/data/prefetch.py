"""Overlapped ingest: background batch assembly + H2D into a bounded queue.

The r5 bench exposed an inversion: the "pipelined" dispatch loop trailed the
*unoverlapped* host-fed phase sum because batch assembly (~0.14 s/window of
uint64 validation, stacking, casting) and the host→device transfer sat on
the critical path between device dispatches.  :class:`PrefetchPipeline`
moves both onto a producer thread feeding a depth-``depth`` queue of
device-resident blocks, so the host assembles block ``i+1`` (and stages its
H2D copy) while the device executes block ``i`` — the MLPerf TPU-pod infeed
lesson (arxiv 1909.09756) applied to the scan-block trainer.

Determinism: one producer thread calling ``make_block(0), make_block(1),
...`` in order, one bounded FIFO — consumers see exactly the sequence a
serial loop would produce.  ``depth=2`` is classic double buffering: the
producer stays at most one block ahead, bounding host memory and keeping
backpressure.

Shutdown is leak-free: :meth:`close` (or the context manager) stops the
producer even when it is blocked on a full queue, joins the thread, and
drains the queue so donated device buffers are released.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterator, Optional

import jax

#: queue sentinel: producer finished (``limit`` reached).
_DONE = object()


class PrefetchPipeline:
    """Double-buffered producer of device-resident input blocks.

    Parameters
    ----------
    make_block:
        ``make_block(i) -> block`` builds the ``i``-th host block (any
        pytree of numpy arrays).  Runs on the producer thread — keep all
        per-block host work (assembly, validation, casting) here so none of
        it lands on the consumer's critical path.
    depth:
        queue capacity (2 = double buffering: one block in flight on the
        device, one staged).
    limit:
        number of blocks to produce (None = unbounded; the consumer stops
        by closing the pipeline).
    device_put:
        override the H2D transfer (default ``jax.device_put``); tests pass
        an identity to run device-free.
    """

    def __init__(
        self,
        make_block: Callable[[int], Any],
        *,
        depth: int = 2,
        limit: Optional[int] = None,
        device_put: Optional[Callable[[Any], Any]] = None,
    ) -> None:
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._make_block = make_block
        self._limit = limit
        self._device_put = device_put if device_put is not None else jax.device_put
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        # counters (dashboard ``prefetch`` attachment)
        self._lock = threading.Lock()
        self._produced = 0
        self._consumed = 0
        self._stalls = 0
        self._stall_s = 0.0
        self._thread = threading.Thread(
            target=self._produce, name="prefetch-producer", daemon=True
        )
        self._thread.start()

    # -- producer -----------------------------------------------------------
    def _produce(self) -> None:
        i = 0
        try:
            while not self._stop.is_set():
                if self._limit is not None and i >= self._limit:
                    self._put(_DONE)
                    return
                block = self._device_put(self._make_block(i))
                if not self._put(block):
                    return  # stopped while waiting on a full queue
                with self._lock:
                    self._produced += 1
                i += 1
        except BaseException as e:  # noqa: BLE001 — surface on the consumer
            self._error = e
            self._put(_DONE)

    def _put(self, item: Any) -> bool:
        """put() that stays responsive to close() on a full queue."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    # -- consumer -----------------------------------------------------------
    def get(self) -> Any:
        """Next device block; raises StopIteration when ``limit`` blocks
        were consumed.  Time spent waiting on an empty queue is counted as a
        prefetch stall (the producer was the bottleneck)."""
        try:
            item = self._q.get_nowait()
        except queue.Empty:
            t0 = time.perf_counter()
            item = self._q.get()
            with self._lock:
                self._stalls += 1
                self._stall_s += time.perf_counter() - t0
        if item is _DONE:
            self._q.put(_DONE)  # keep later get()s terminating too
            if self._error is not None:
                raise self._error
            raise StopIteration
        with self._lock:
            self._consumed += 1
        return item

    def __iter__(self) -> Iterator[Any]:
        while True:
            try:
                yield self.get()
            except StopIteration:
                return

    # -- lifecycle / metrics ------------------------------------------------
    def counters(self) -> dict:
        with self._lock:
            return {
                "prefetch_produced": self._produced,
                "prefetch_consumed": self._consumed,
                "prefetch_stalls": self._stalls,
                "prefetch_stall_s": round(self._stall_s, 4),
            }

    def close(self) -> None:
        """Stop the producer, join it, drain the queue (leak-free)."""
        self._stop.set()
        # unblock a producer stuck in put() by making room
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=10)
        while True:  # drain anything the producer squeezed in while dying
            try:
                self._q.get_nowait()
            except queue.Empty:
                break

    def __enter__(self) -> "PrefetchPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
