"""Synthetic sparse CTR data: Criteo-shaped batches with known ground truth.

The reference tests convergence on a bundled rcv1 sample (SURVEY.md §4); we
generate a synthetic equivalent: each example has ``nnz`` categorical features
drawn zipf-skewed from a large key space, and the label is Bernoulli of the
logistic of a hidden sparse weight vector.  Known ground truth lets tests
assert logloss trajectories deterministically.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Tuple

import numpy as np

from parameter_server_tpu.utils.keys import mix64


@dataclasses.dataclass
class SyntheticCTR:
    """Deterministic stream of (keys [B, nnz], labels [B]) batches."""

    key_space: int = 1 << 22
    nnz: int = 39  # criteo: 39 categorical slots
    batch_size: int = 1024
    seed: int = 0
    #: fraction of informative features; the rest are noise keys
    informative: float = 0.05
    zipf_a: float = 1.3

    def __post_init__(self) -> None:
        rng = np.random.default_rng(self.seed)
        n_inf = max(1, int(self.key_space * self.informative))
        # hidden truth: informative keys get +-1 weights, hashed choice
        self._true_w_scale = 1.0
        self._n_inf = n_inf
        self._bias = -1.0
        self._rng = rng

    def _true_weight(self, keys: np.ndarray) -> np.ndarray:
        """Deterministic hidden weight for each key (no giant table needed)."""
        h = mix64(keys, seed=0xABCDEF)
        informative = (h % np.uint64(self.key_space)) < np.uint64(self._n_inf)
        sign = np.where((h >> np.uint64(1)) & np.uint64(1), 1.0, -1.0)
        return np.where(informative, sign * self._true_w_scale, 0.0)

    def batches(self, num_batches: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        for _ in range(num_batches):
            yield self.next_batch()

    def next_batch(self) -> Tuple[np.ndarray, np.ndarray]:
        rng = self._rng
        # zipf-skewed keys remixed over the key space (hot-key distribution)
        raw = rng.zipf(self.zipf_a, size=(self.batch_size, self.nnz)).astype(np.uint64)
        keys = mix64(raw, seed=7) % np.uint64(self.key_space)
        logits = self._true_weight(keys).sum(axis=1) + self._bias
        p = 1.0 / (1.0 + np.exp(-logits))
        labels = (rng.random(self.batch_size) < p).astype(np.float32)
        return keys, labels
