"""Synthetic sparse CTR data: Criteo-shaped batches with known ground truth.

The reference tests convergence on a bundled rcv1 sample (SURVEY.md §4); we
generate a synthetic equivalent: each example has ``nnz`` categorical features
drawn zipf-skewed from a large key space, and the label is Bernoulli of the
logistic of a hidden sparse weight vector.  Known ground truth lets tests
assert logloss trajectories deterministically.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Tuple

import numpy as np

from parameter_server_tpu.utils.keys import mix64


@dataclasses.dataclass
class SyntheticCTR:
    """Deterministic stream of (keys [B, nnz], labels [B]) batches."""

    key_space: int = 1 << 22
    nnz: int = 39  # criteo: 39 categorical slots
    batch_size: int = 1024
    seed: int = 0
    #: fraction of informative features; the rest are noise keys
    informative: float = 0.05
    zipf_a: float = 1.3

    def __post_init__(self) -> None:
        rng = np.random.default_rng(self.seed)
        n_inf = max(1, int(self.key_space * self.informative))
        # hidden truth: informative keys get +-1 weights, hashed choice
        self._true_w_scale = 1.0
        self._n_inf = n_inf
        self._bias = -1.0
        self._rng = rng

    def _true_weight(self, keys: np.ndarray) -> np.ndarray:
        """Deterministic hidden weight for each key (no giant table needed)."""
        h = mix64(keys, seed=0xABCDEF)
        informative = (h % np.uint64(self.key_space)) < np.uint64(self._n_inf)
        sign = np.where((h >> np.uint64(1)) & np.uint64(1), 1.0, -1.0)
        return np.where(informative, sign * self._true_w_scale, 0.0)

    def batches(self, num_batches: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        for _ in range(num_batches):
            yield self.next_batch()

    def next_batch(self) -> Tuple[np.ndarray, np.ndarray]:
        rng = self._rng
        # zipf-skewed keys remixed over the key space (hot-key distribution)
        raw = rng.zipf(self.zipf_a, size=(self.batch_size, self.nnz)).astype(np.uint64)
        keys = mix64(raw, seed=7) % np.uint64(self.key_space)
        logits = self._true_weight(keys).sum(axis=1) + self._bias
        p = 1.0 / (1.0 + np.exp(-logits))
        labels = (rng.random(self.batch_size) < p).astype(np.float32)
        return keys, labels


@dataclasses.dataclass
class SyntheticDLRM:
    """Criteo-DLRM-shaped batches: dense floats + categorical keys + label.

    The label depends on both the dense features and per-key hidden weights,
    so learning requires the MLPs *and* the embedding table to train.
    """

    key_space: int = 1 << 20
    n_dense: int = 13
    n_sparse: int = 26
    batch_size: int = 512
    seed: int = 0

    def __post_init__(self) -> None:
        self._rng = np.random.default_rng(self.seed)
        self._w_dense = np.random.default_rng(self.seed + 1).normal(
            size=self.n_dense
        ) / np.sqrt(self.n_dense)

    def _key_effect(self, keys: np.ndarray) -> np.ndarray:
        h = mix64(keys, seed=0x5EED)
        sign = np.where((h >> np.uint64(2)) & np.uint64(1), 1.0, -1.0)
        active = (h % np.uint64(4)) == 0  # quarter of keys matter
        return np.where(active, sign * 0.5, 0.0)

    def next_batch(self):
        rng = self._rng
        dense = rng.normal(size=(self.batch_size, self.n_dense)).astype(np.float32)
        raw = rng.zipf(1.2, size=(self.batch_size, self.n_sparse)).astype(np.uint64)
        keys = mix64(raw, seed=11) % np.uint64(self.key_space)
        logits = dense @ self._w_dense + self._key_effect(keys).sum(axis=1) - 0.5
        labels = (rng.random(self.batch_size) < 1 / (1 + np.exp(-logits))).astype(
            np.float32
        )
        return keys, dense, labels


@dataclasses.dataclass
class SyntheticImages:
    """Learnable image-classification stream (ResNet-class benchmarks).

    Each class owns a fixed random template; a sample is its class template
    plus gaussian noise — a tiny convnet separates the classes quickly, so
    time-to-accuracy is measurable without real data (the ResNet half of
    the north-star quality clock, VERDICT r4 #2 wording).
    """

    num_classes: int = 10
    hw: int = 16
    batch_size: int = 64
    seed: int = 0
    noise: float = 1.0

    def __post_init__(self) -> None:
        self._rng = np.random.default_rng(self.seed)
        tmpl_rng = np.random.default_rng(0xC1A55)
        self._templates = tmpl_rng.normal(
            size=(self.num_classes, self.hw, self.hw, 3)
        ).astype(np.float32)

    def next_batch(self):
        rng = self._rng
        labels = rng.integers(
            0, self.num_classes, size=self.batch_size
        ).astype(np.int32)
        images = (
            self._templates[labels]
            + self.noise
            * rng.normal(size=(self.batch_size, self.hw, self.hw, 3))
        ).astype(np.float32)
        return images, labels
