"""Text example parsers: libsvm and Criteo TSV -> numpy batches.

Reference analogue: ``src/data/text_parser.h/.cc`` parsing libsvm / criteo /
adfea / vw lines into ``Example`` protos [U] (SURVEY.md #18).  Here parsing
produces flat numpy arrays directly (no proto hop): CSR for variable-nnz
libsvm, fixed-width arrays for Criteo's 13 dense + 26 categorical slots.

The hot path is the native C++ parser (``native/src/textparse.cc``, loaded
via ctypes); every function degrades to a numpy/pure-Python fallback that is
bit-identical (tests assert parity, including the per-slot salted mix64
categorical hashing).
"""

from __future__ import annotations

import ctypes
import dataclasses
from typing import Optional, Tuple

import numpy as np

from parameter_server_tpu import native
from parameter_server_tpu.utils.keys import PAD_KEY, mix64

N_DENSE = 13  # criteo integer feature count
N_CAT = 26  # criteo categorical slot count
_MISSING_CAT = np.uint64(0xFFFFFFFFFFFFFFFE)

_f32p = ctypes.POINTER(ctypes.c_float)
_i64p = ctypes.POINTER(ctypes.c_int64)
_u64p = ctypes.POINTER(ctypes.c_uint64)


def _lib() -> Optional[ctypes.CDLL]:
    lib = native.load("textparse")
    if lib is not None and not getattr(lib, "_ps_sigs", False):
        lib.ps_libsvm_count.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, _i64p, _i64p,
            _i64p, _i64p,
        ]
        lib.ps_libsvm_fill.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, _i64p, _i64p,
            _f32p, _i64p, _u64p, _f32p,
        ]
        lib.ps_criteo_count.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, _i64p, _i64p,
        ]
        lib.ps_criteo_fill.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, _i64p,
            ctypes.c_int, ctypes.c_int, _f32p, _f32p, _u64p,
        ]
        lib.ps_mix64.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
        lib.ps_mix64.restype = ctypes.c_uint64
        lib._ps_sigs = True
    return lib


@dataclasses.dataclass
class CSRBatch:
    """Variable-nnz sparse examples in CSR form."""

    labels: np.ndarray  # [rows] f32
    indptr: np.ndarray  # [rows + 1] i64
    indices: np.ndarray  # [nnz] u64 feature keys
    values: np.ndarray  # [nnz] f32

    @property
    def rows(self) -> int:
        return int(self.labels.shape[0])

    def slice(self, lo: int, hi: int) -> "CSRBatch":
        a, b = int(self.indptr[lo]), int(self.indptr[hi])
        return CSRBatch(
            self.labels[lo:hi],
            (self.indptr[lo : hi + 1] - a).astype(np.int64),
            self.indices[a:b],
            self.values[a:b],
        )

    def to_fixed_nnz(
        self, max_nnz: int, *, pad_key: np.uint64 = PAD_KEY
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Pad/truncate to ``(keys [rows, max_nnz], vals, labels)``.

        Fixed-shape batches are what the jit-compiled learners consume
        (SURVEY.md §7 hard part #1: no dynamic shapes under jit).  PAD_KEY
        positions route to the table's trash row, contributing zero to
        logits and gradients (models/linear.py re-zeroes that row).
        """
        rows = self.rows
        keys = np.full((rows, max_nnz), pad_key, dtype=np.uint64)
        vals = np.zeros((rows, max_nnz), dtype=np.float32)
        counts = np.minimum(np.diff(self.indptr), max_nnz).astype(np.int64)
        # ragged -> rectangular via flat scatter (fully vectorized)
        row_idx = np.repeat(np.arange(rows), counts)
        starts = np.repeat(np.cumsum(counts) - counts, counts)
        within = np.arange(int(counts.sum()), dtype=np.int64) - starts
        src = within + np.repeat(self.indptr[:-1], counts)
        keys[row_idx, within] = self.indices[src]
        vals[row_idx, within] = self.values[src]
        return keys, vals, self.labels


def parse_libsvm(data: bytes, *, nthreads: int = 0) -> CSRBatch:
    """Parse a libsvm text buffer into a :class:`CSRBatch`.

    ``nthreads=0`` = auto.  Native path when available, else numpy fallback.
    """
    lib = _lib()
    if lib is not None:
        return _parse_libsvm_native(lib, data, nthreads or _auto_threads())
    return _parse_libsvm_py(data)


def _auto_threads() -> int:
    return min(8, __import__("os").cpu_count() or 1)


def _parse_libsvm_native(lib: ctypes.CDLL, data: bytes, nthreads: int) -> CSRBatch:
    rows = ctypes.c_int64()
    nnz = ctypes.c_int64()
    chunk_rows = np.zeros(max(nthreads, 1), dtype=np.int64)
    chunk_nnz = np.zeros(max(nthreads, 1), dtype=np.int64)
    lib.ps_libsvm_count(
        data, len(data), nthreads, ctypes.byref(rows), ctypes.byref(nnz),
        chunk_rows.ctypes.data_as(_i64p), chunk_nnz.ctypes.data_as(_i64p),
    )
    labels = np.empty(rows.value, dtype=np.float32)
    indptr = np.zeros(rows.value + 1, dtype=np.int64)
    indices = np.empty(nnz.value, dtype=np.uint64)
    values = np.empty(nnz.value, dtype=np.float32)
    lib.ps_libsvm_fill(
        data, len(data), nthreads,
        chunk_rows.ctypes.data_as(_i64p), chunk_nnz.ctypes.data_as(_i64p),
        labels.ctypes.data_as(_f32p), indptr.ctypes.data_as(_i64p),
        indices.ctypes.data_as(_u64p), values.ctypes.data_as(_f32p),
    )
    return CSRBatch(labels, indptr, indices, values)


def _float_prefix(tok: bytes) -> tuple[float, int]:
    """Mirror of the C parser's numeric subset: ``[-+]?d*[.d*][eE[-+]?d*]``.

    Returns ``(value, chars_consumed)``; consumed == 0 when the mantissa has
    no digits (malformed).  Used by both fallback parsers so accept/skip
    decisions match the native path token for token (no nan/inf, no
    locale, junk tolerated only after the numeric prefix).
    """
    i, n = 0, len(tok)
    neg = False
    if i < n and tok[i : i + 1] in (b"+", b"-"):
        neg = tok[i : i + 1] == b"-"
        i += 1
    v = 0.0
    digits = 0
    while i < n and 48 <= tok[i] <= 57:
        v = v * 10.0 + (tok[i] - 48)
        i += 1
        digits += 1
    if i < n and tok[i : i + 1] == b".":
        i += 1
        scale = 0.1
        while i < n and 48 <= tok[i] <= 57:
            v += (tok[i] - 48) * scale
            scale *= 0.1
            i += 1
            digits += 1
    if digits == 0:
        return 0.0, 0
    if i < n and tok[i : i + 1] in (b"e", b"E"):
        i += 1
        eneg = False
        if i < n and tok[i : i + 1] in (b"+", b"-"):
            eneg = tok[i : i + 1] == b"-"
            i += 1
        ex = 0
        while i < n and 48 <= tok[i] <= 57:
            if ex < 10000:  # saturate like the native parser
                ex = ex * 10 + (tok[i] - 48)
            i += 1
        try:
            v *= 10.0 ** (-ex if eneg else ex)
        except OverflowError:  # C pow() returns inf here; match it
            v = float("inf") if v else 0.0
    return (-v if neg else v), i


def _parse_libsvm_py(data: bytes) -> CSRBatch:
    labels, indptr, indices, values = [], [0], [], []
    for line in data.split(b"\n"):
        line = line.strip()
        # '#' is a comment ONLY at token start (native rule): a full-line
        # comment skips the row; '#' glued inside a token makes that token
        # malformed (skipped whole below), NOT a line truncation.
        if not line or line.startswith(b"#"):
            continue
        parts = line.split()
        label, _ = _float_prefix(parts[0])  # junk label -> 0.0, row kept
        labels.append(label)
        for tok in parts[1:]:
            if tok.startswith(b"#"):
                break  # trailing comment: rest of line ignored
            # accept/skip rules identical to the native parse_feature():
            # key must be all digits; value (if present) must be a fully-
            # consumed numeric; malformed tokens are skipped whole.
            k, _, v = tok.partition(b":")
            if not k.isdigit():
                continue
            if v or tok.endswith(b":"):
                val, used = _float_prefix(v)
                if used == 0 or used != len(v):
                    continue
            else:
                val = 1.0
            indices.append(int(k))
            values.append(val)
        indptr.append(len(indices))
    return CSRBatch(
        np.asarray(labels, np.float32),
        np.asarray(indptr, np.int64),
        np.asarray(indices, np.uint64),
        np.asarray(values, np.float32),
    )


def hash_cat(raw: np.ndarray, slot: np.ndarray | int) -> np.ndarray:
    """Per-slot salted key hash for categorical values (numpy reference).

    Must match the C++ ``mix64(raw, slot + 1)`` exactly.
    """
    seed = np.asarray(slot, dtype=np.uint64) + np.uint64(1)
    # mix64 takes a scalar seed; vectorize by folding the seed xor in here
    x = np.asarray(raw, dtype=np.uint64) ^ seed
    return mix64(x, 0)


def parse_criteo(
    data: bytes, *, nthreads: int = 0
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Parse Criteo TSV -> ``(labels [B], dense [B,13] f32, keys [B,26] u64)``.

    Missing dense fields parse as 0; missing categoricals hash a per-slot
    sentinel so every slot always yields a key (fixed-shape batches).
    """
    lib = _lib()
    if lib is not None:
        rows = ctypes.c_int64()
        nt = nthreads or _auto_threads()
        chunk_rows = np.zeros(max(nt, 1), dtype=np.int64)
        lib.ps_criteo_count(
            data, len(data), nt, ctypes.byref(rows),
            chunk_rows.ctypes.data_as(_i64p),
        )
        labels = np.empty(rows.value, dtype=np.float32)
        dense = np.empty((rows.value, N_DENSE), dtype=np.float32)
        keys = np.empty((rows.value, N_CAT), dtype=np.uint64)
        lib.ps_criteo_fill(
            data, len(data), nt, chunk_rows.ctypes.data_as(_i64p),
            N_DENSE, N_CAT,
            labels.ctypes.data_as(_f32p), dense.ctypes.data_as(_f32p),
            keys.ctypes.data_as(_u64p),
        )
        return labels, dense, keys
    return _parse_criteo_py(data)


_HEX = b"0123456789abcdefABCDEF"


def _hex_prefix(tok: bytes) -> np.uint64:
    """Native-parity hex parse: leading hex digits, wrapping mod 2**64;
    no hex digits (or empty) -> the missing sentinel.  Matches the C++
    parser's tolerance of junk suffixes and >16-digit fields exactly."""
    v = 0
    n = 0
    for c in tok:
        d = _HEX.find(c % 256 if isinstance(c, int) else c)
        if d < 0:
            break
        v = ((v << 4) | (d if d < 16 else d - 6)) & 0xFFFFFFFFFFFFFFFF
        n += 1
    return np.uint64(v) if n else _MISSING_CAT


def _parse_criteo_py(data: bytes) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    labels, dense, keys = [], [], []
    slots = np.arange(N_CAT, dtype=np.uint64)
    for line in data.split(b"\n"):
        if not line.strip():
            continue
        f = line.rstrip(b"\r").split(b"\t")
        labels.append(_float_prefix(f[0])[0])
        d = np.zeros(N_DENSE, dtype=np.float32)
        for i in range(N_DENSE):
            tok = f[1 + i] if 1 + i < len(f) else b""
            if tok:
                d[i] = _float_prefix(tok)[0]  # junk-suffix tolerant
        dense.append(d)
        raw = np.empty(N_CAT, dtype=np.uint64)
        for i in range(N_CAT):
            tok = f[1 + N_DENSE + i] if 1 + N_DENSE + i < len(f) else b""
            raw[i] = _hex_prefix(tok)
        keys.append(hash_cat(raw, slots))
    return (
        np.asarray(labels, np.float32),
        np.stack(dense) if dense else np.zeros((0, N_DENSE), np.float32),
        np.stack(keys) if keys else np.zeros((0, N_CAT), np.uint64),
    )


def write_libsvm(path: str, batch: CSRBatch) -> None:
    """Inverse of :func:`parse_libsvm`, for tests and cache round-trips."""
    with open(path, "w") as f:
        for r in range(batch.rows):
            a, b = int(batch.indptr[r]), int(batch.indptr[r + 1])
            feats = " ".join(
                f"{int(batch.indices[i])}:{batch.values[i]:g}" for i in range(a, b)
            )
            f.write(f"{batch.labels[r]:g} {feats}\n")
