"""Host data input pipeline: parsers, readers, synthetic generators.

Reference: ``src/data/`` (text parsers, SlotReader, StreamReader) [U],
SURVEY.md #18.  Text parsing runs in native C++ (``native/src/textparse.cc``)
with bit-identical numpy fallbacks.
"""

from parameter_server_tpu.data.prefetch import PrefetchPipeline
from parameter_server_tpu.data.reader import (
    SlotReader,
    StreamReader,
    criteo_log_transform,
)
from parameter_server_tpu.data.synthetic import SyntheticCTR, SyntheticDLRM
from parameter_server_tpu.data.text import (
    CSRBatch,
    parse_criteo,
    parse_libsvm,
    write_libsvm,
)

__all__ = [
    "CSRBatch",
    "PrefetchPipeline",
    "SlotReader",
    "StreamReader",
    "SyntheticCTR",
    "SyntheticDLRM",
    "criteo_log_transform",
    "parse_criteo",
    "parse_libsvm",
    "write_libsvm",
]
