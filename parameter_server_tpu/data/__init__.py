"""data subpackage."""
