"""Count-min tail filtering of sparse key streams.

Reference role: DARLIN's preprocessing drops tail features seen fewer than
``k`` times before training (countmin filter over the key stream —
``src/util/countmin.h`` + the linear-method preprocess stage [U]); the OSDI
paper credits this (with the KKT filter) for a large chunk of the traffic
reduction on 65 B-feature CTR data.  Billion-row DLRM tables have the same
shape of problem: most keys occur once or twice and their rows are pure
noise plus wasted pulls.

:class:`TailFilteredStream` applies the same idea online: a count-min
sketch counts arrivals; keys whose estimated frequency is below the
threshold are replaced with ``PAD_KEY`` — positions that localize to the
trash row, contribute zero to logits, and receive no updates.  The filter
is conservative (count-min never undercounts, so a frequent key is never
dropped) and warms up: early occurrences of eventually-frequent keys pass
once their count crosses the threshold.
"""

from __future__ import annotations

from typing import Callable, Iterator, Tuple

import numpy as np

from parameter_server_tpu.utils.countmin import CountMin
from parameter_server_tpu.utils.keys import PAD_KEY

Batch = Tuple[np.ndarray, ...]  # (keys [B, nnz], ...rest passthrough)


class TailFilteredStream:
    """Wrap a batch source; mask tail keys (est. count < threshold) to PAD.

    ``batch_fn`` returns ``(keys, *rest)``; only ``keys`` is rewritten.
    Statistics: ``seen``/``masked`` position counters -> ``masked_fraction``.
    """

    def __init__(
        self,
        batch_fn: Callable[[], Batch],
        threshold: int,
        *,
        width: int = 1 << 20,
        depth: int = 4,
        seed: int = 0,
    ) -> None:
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.batch_fn = batch_fn
        self.threshold = threshold
        self.sketch = CountMin(width=width, depth=depth, seed=seed)
        self.seen = 0
        self.masked = 0

    def __call__(self) -> Batch:
        keys, *rest = self.batch_fn()
        keys = np.asarray(keys, dtype=np.uint64)
        real = keys != PAD_KEY
        flat = keys[real]
        # count first, then filter: a key's own arrivals in this batch count
        # toward its threshold (so threshold=1 passes everything)
        self.sketch.add(flat)
        keep = self.sketch.filter(flat, self.threshold)
        out = keys.copy()
        vals = out[real]
        vals[~keep] = PAD_KEY
        out[real] = vals
        self.seen += int(flat.size)
        self.masked += int((~keep).sum())
        return (out, *rest)

    @property
    def masked_fraction(self) -> float:
        return self.masked / max(self.seen, 1)

    def __iter__(self) -> Iterator[Batch]:
        while True:
            yield self()
