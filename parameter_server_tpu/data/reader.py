"""Chunked file readers: the host input pipeline.

Reference analogues (SURVEY.md #18): ``src/data/slot_reader.h`` (parse once,
cache column groups locally, re-read cheaply per block) and
``src/data/stream_reader.h`` (minibatch streaming for online learners) [U].

- :class:`SlotReader` — parse text files once into CSR chunks, cache each
  parsed chunk as an ``.npz`` next to a content fingerprint; later passes
  (BCD iterates over feature blocks many times) load the cache instead of
  re-parsing.
- :class:`StreamReader` — endless minibatch iterator over a file list with
  fixed batch size (carry remainder across chunk boundaries), for the
  async-SGD/FTRL streaming path.
"""

from __future__ import annotations

import hashlib
import os
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from parameter_server_tpu.data import fs
from parameter_server_tpu.data import text as text_lib

CHUNK_BYTES = 8 << 20


def _read_chunks(path: str, chunk_bytes: int) -> Iterator[bytes]:
    """Yield line-aligned byte chunks of a text file.

    ``path`` may be any :mod:`parameter_server_tpu.data.fs` url — local,
    ``.gz``, or a remote ``psfs://`` shard — so every reader feeds from the
    cluster file service with no call-site changes (reference ``file.h``
    HDFS role).
    """
    with fs.open_stream(path) as f:
        carry = b""
        while True:
            block = f.read(chunk_bytes)
            if not block:
                if carry.strip():
                    yield carry
                return
            block = carry + block
            cut = block.rfind(b"\n")
            if cut < 0:
                carry = block
                continue
            yield block[: cut + 1]
            carry = block[cut + 1 :]


class SlotReader:
    """Parse-once, cache-locally reader for batch training (BCD path).

    ``format`` is ``"libsvm"`` (CSR) — criteo batch use goes through
    :class:`StreamReader`.  Cached chunks are keyed by (file size, mtime,
    chunk index) so edits invalidate the cache.
    """

    def __init__(
        self,
        files: Sequence[str],
        *,
        cache_dir: Optional[str] = None,
        chunk_bytes: int = CHUNK_BYTES,
    ) -> None:
        self.files = list(files)
        self.cache_dir = cache_dir
        self.chunk_bytes = chunk_bytes
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)

    def _file_tag(self, path: str) -> str:
        st = fs.stat(path)  # works for local AND psfs:// shard urls
        ident = path if "://" in path else os.path.abspath(path)
        return hashlib.sha1(
            f"{ident}:{st.size}:{st.mtime_ns}:"
            f"{self.chunk_bytes}".encode()
        ).hexdigest()[:16]

    def _cache_path(self, tag: str, idx: int) -> Optional[str]:
        if not self.cache_dir:
            return None
        return os.path.join(self.cache_dir, f"slot_{tag}_{idx}.npz")

    def _manifest_path(self, tag: str) -> str:
        return os.path.join(self.cache_dir, f"slot_{tag}.manifest")  # type: ignore[arg-type]

    def _load_chunk(self, cpath: str) -> text_lib.CSRBatch:
        z = np.load(cpath)
        return text_lib.CSRBatch(
            z["labels"], z["indptr"], z["indices"], z["values"]
        )

    def chunks(self) -> Iterator[text_lib.CSRBatch]:
        for path in self.files:
            tag = self._file_tag(path) if self.cache_dir else ""
            # warm-cache fast path: the manifest records the chunk count, so
            # later passes (BCD iterates many times) never re-read the raw
            # text at all
            if self.cache_dir:
                mpath = self._manifest_path(tag)
                if os.path.exists(mpath):
                    with open(mpath) as mf:
                        n_chunks = int(mf.read().strip())
                    paths = [self._cache_path(tag, i) for i in range(n_chunks)]
                    if all(os.path.exists(p) for p in paths):  # type: ignore[arg-type]
                        for p in paths:
                            yield self._load_chunk(p)  # type: ignore[arg-type]
                        continue
            n_chunks = 0
            for idx, raw in enumerate(_read_chunks(path, self.chunk_bytes)):
                n_chunks = idx + 1
                cpath = self._cache_path(tag, idx)
                if cpath and os.path.exists(cpath):
                    yield self._load_chunk(cpath)
                    continue
                batch = text_lib.parse_libsvm(raw)
                if cpath:
                    # name must end in .npz or np.savez appends it
                    tmp = cpath + f".{os.getpid()}.tmp.npz"
                    np.savez(
                        tmp,
                        labels=batch.labels,
                        indptr=batch.indptr,
                        indices=batch.indices,
                        values=batch.values,
                    )
                    os.replace(tmp, cpath)
                yield batch
            if self.cache_dir:
                tmp = self._manifest_path(tag) + f".{os.getpid()}.tmp"
                with open(tmp, "w") as mf:
                    mf.write(str(n_chunks))
                os.replace(tmp, self._manifest_path(tag))

    def read_all(self) -> text_lib.CSRBatch:
        """Concatenate every chunk (small datasets / tests)."""
        parts = list(self.chunks())
        if not parts:
            return text_lib.CSRBatch(
                np.zeros(0, np.float32), np.zeros(1, np.int64),
                np.zeros(0, np.uint64), np.zeros(0, np.float32),
            )
        labels = np.concatenate([p.labels for p in parts])
        indices = np.concatenate([p.indices for p in parts])
        values = np.concatenate([p.values for p in parts])
        indptr = [np.zeros(1, np.int64)]
        base = 0
        for p in parts:
            indptr.append(p.indptr[1:] + base)
            base += int(p.indptr[-1])
        return text_lib.CSRBatch(labels, np.concatenate(indptr), indices, values)


class StreamReader:
    """Fixed-size minibatch stream over text files (async SGD / FTRL path).

    Yields ``(keys [B, max_nnz], values, labels)`` for libsvm or
    ``(keys [B, 26], dense [B, 13], labels)`` for criteo.  Remainder rows at
    a chunk boundary carry into the next chunk; a final short batch is
    dropped (epoch semantics of streaming learners).
    """

    def __init__(
        self,
        files: Sequence[str],
        batch_size: int,
        *,
        format: str = "libsvm",
        max_nnz: int = 64,
        epochs: Optional[int] = None,
        chunk_bytes: int = CHUNK_BYTES,
        shuffle_seed: Optional[int] = None,
    ) -> None:
        if format not in ("libsvm", "criteo"):
            raise ValueError(f"unknown format {format!r}")
        self.files = list(files)
        self.batch_size = batch_size
        self.format = format
        self.max_nnz = max_nnz
        self.epochs = epochs
        self.chunk_bytes = chunk_bytes
        self.shuffle_seed = shuffle_seed

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        epoch = 0
        rng = (
            np.random.default_rng(self.shuffle_seed)
            if self.shuffle_seed is not None
            else None
        )
        pend: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        pend_rows = 0
        while self.epochs is None or epoch < self.epochs:
            for path in self.files:
                for raw in _read_chunks(path, self.chunk_bytes):
                    triple = self._parse(raw)
                    if rng is not None:
                        perm = rng.permutation(triple[2].shape[0])
                        triple = tuple(t[perm] for t in triple)  # type: ignore
                    pend.append(triple)
                    pend_rows += triple[2].shape[0]
                    while pend_rows >= self.batch_size:
                        batch, pend, pend_rows = _take(pend, self.batch_size)
                        yield batch
            epoch += 1

    def _parse(self, raw: bytes) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        if self.format == "criteo":
            labels, dense, keys = text_lib.parse_criteo(raw)
            return keys, dense, labels
        batch = text_lib.parse_libsvm(raw)
        keys, vals, labels = batch.to_fixed_nnz(self.max_nnz)
        return keys, vals, labels


def _take(
    pend: List[Tuple[np.ndarray, np.ndarray, np.ndarray]], n: int
) -> Tuple[Tuple[np.ndarray, np.ndarray, np.ndarray], list, int]:
    """Pop exactly n rows off the pending chunk list."""
    got, rows = [], 0
    while rows < n:
        t = pend.pop(0)
        take = min(n - rows, t[2].shape[0])
        got.append(tuple(x[:take] for x in t))
        if take < t[2].shape[0]:
            pend.insert(0, tuple(x[take:] for x in t))
        rows += take
    batch = tuple(np.concatenate([g[i] for g in got]) for i in range(3))
    left = sum(t[2].shape[0] for t in pend)
    return batch, pend, left  # type: ignore


def criteo_log_transform(dense: np.ndarray) -> np.ndarray:
    """Standard Criteo dense preprocess: ``log1p(max(x, 0))``."""
    return np.log1p(np.maximum(dense, 0.0)).astype(np.float32)
