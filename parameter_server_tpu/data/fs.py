"""Pluggable file access: local paths, gzip, and an in-cluster file server.

Reference analogue: ``src/util/file.h/.cc`` — the reference's readers open
local and ``hdfs://`` paths through one File API, which is how Criteo-1TB
shards reach worker machines [U].  The TPU-native counterpart keeps the
single-API shape with scheme dispatch:

- plain paths / ``file://`` — local files;
- ``*.gz`` — transparent gzip decompression (Criteo ships gzipped);
- ``psfs://host:port/relative/path`` — the :class:`FileServer` below, a
  read-only TCP file service any pod host can run next to its shard store
  (the HDFS-role replacement: workers stream ranges over DCN, no shared
  filesystem required).

Every reader in :mod:`parameter_server_tpu.data.reader` opens its inputs
through :func:`open_stream`, so remote shards feed SlotReader/StreamReader
(and therefore every learner) with no code changes at the call sites.

Protocol (length-prefixed, binary, read-only):
    request  = op:u8 | path_len:u32 | path_utf8 | offset:u64 | length:u64
    response = status:u8 | body_len:u64 | body
ops: 1=STAT (body = "size:mtime_ns"), 2=READ (body = file bytes),
3=LIST (body = newline-joined relative paths).  status: 0=ok, 1=error
(body = message).  The server only serves paths under its root (resolved,
symlink-safe) — it is a cluster-internal data plane, not a public service.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import gzip
import io
import os
import socket
import socketserver
import struct
import threading
from typing import BinaryIO, List, Optional, Tuple
from urllib.parse import urlparse

_OP_STAT, _OP_READ, _OP_LIST = 1, 2, 3
_MAX_READ = 64 << 20  # per-request range cap; readers chunk anyway


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("psfs: peer closed mid-frame")
        buf += part
    return buf


def _request_on(sock: socket.socket, addr: Tuple[str, int], op: int,
                path: str, offset: int = 0, length: int = 0) -> bytes:
    p = path.encode()
    frame = struct.pack("!BI", op, len(p)) + p + struct.pack("!QQ", offset, length)
    sock.sendall(frame)
    status, body_len = struct.unpack("!BQ", _recv_exact(sock, 9))
    body = _recv_exact(sock, body_len) if body_len else b""
    if status != 0:
        raise OSError(f"psfs://{addr[0]}:{addr[1]}/{path}: {body.decode()}")
    return body


def _request(addr: Tuple[str, int], op: int, path: str, offset: int = 0,
             length: int = 0) -> bytes:
    """One-shot request (STAT/LIST); streams use a persistent connection."""
    with socket.create_connection(addr, timeout=30) as sock:
        return _request_on(sock, addr, op, path, offset, length)


@dataclasses.dataclass(frozen=True)
class StatResult:
    size: int
    mtime_ns: int


class _RemoteFile(io.RawIOBase):
    """Read-only file-like over ranged psfs READ requests.

    Holds ONE persistent connection for its lifetime (the server handler
    loops over framed requests), so streaming a shard pays the TCP
    handshake and slow-start once — not per buffered read.  A dropped
    connection reconnects transparently once per request.
    """

    def __init__(self, addr: Tuple[str, int], path: str, size: int) -> None:
        super().__init__()
        self._addr = addr
        self._path = path
        self._size = size
        self._pos = 0
        self._sock: Optional[socket.socket] = None

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self._addr, timeout=30)
        return self._sock

    def _req(self, offset: int, length: int) -> bytes:
        try:
            return _request_on(
                self._conn(), self._addr, _OP_READ, self._path, offset, length
            )
        except (ConnectionError, TimeoutError):
            # transport died (NOT a server error reply, which raises plain
            # OSError): one transparent retry on a fresh connection
            self.close_connection()
            return _request_on(
                self._conn(), self._addr, _OP_READ, self._path, offset, length
            )

    def close_connection(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        self.close_connection()
        super().close()

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, pos: int, whence: int = os.SEEK_SET) -> int:
        base = {os.SEEK_SET: 0, os.SEEK_CUR: self._pos, os.SEEK_END: self._size}
        self._pos = max(0, base[whence] + pos)
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            n = self._size - self._pos
        n = min(n, self._size - self._pos)
        if n <= 0:
            return b""
        out = []
        while n > 0:
            take = min(n, _MAX_READ)
            body = self._req(self._pos, take)
            if not body:
                break
            out.append(body)
            self._pos += len(body)
            n -= len(body)
        return b"".join(out)

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)


def _parse_psfs(url: str) -> Tuple[Tuple[str, int], str]:
    u = urlparse(url)
    if u.scheme != "psfs" or u.port is None:
        raise ValueError(f"not a psfs://host:port/path url: {url!r}")
    return (u.hostname or "127.0.0.1", u.port), u.path.lstrip("/")


def stat(url: str) -> StatResult:
    """Size + mtime for any supported url (the reference File::Size role)."""
    if url.startswith("psfs://"):
        addr, path = _parse_psfs(url)
        size_s, mtime_s = _request(addr, _OP_STAT, path).decode().split(":")
        return StatResult(int(size_s), int(mtime_s))
    path = url[len("file://") :] if url.startswith("file://") else url
    st = os.stat(path)
    return StatResult(st.st_size, st.st_mtime_ns)


def open_stream(url: str) -> BinaryIO:
    """Open any supported url for binary reading (gzip-transparent)."""
    if url.startswith("psfs://"):
        addr, path = _parse_psfs(url)
        size = stat(url).size
        raw: BinaryIO = io.BufferedReader(
            _RemoteFile(addr, path, size), buffer_size=4 << 20
        )
    else:
        path = url[len("file://") :] if url.startswith("file://") else url
        raw = open(path, "rb")
    if url.endswith(".gz"):
        return gzip.open(raw, "rb")  # type: ignore[return-value]
    return raw


def list_files(pattern: str) -> List[str]:
    """Expand a glob into urls: local globs, or psfs LIST + fnmatch."""
    if pattern.startswith("psfs://"):
        addr, pat = _parse_psfs(pattern)
        names = _request(addr, _OP_LIST, "").decode().splitlines()
        return [
            f"psfs://{addr[0]}:{addr[1]}/{n}"
            for n in sorted(names)
            # glob semantics: '*' must not cross directory separators
            if n.count("/") == pat.count("/") and fnmatch.fnmatch(n, pat)
        ]
    import glob as glob_lib

    path = pattern[len("file://") :] if pattern.startswith("file://") else pattern
    return sorted(glob_lib.glob(path))


class FileServer:
    """Read-only TCP file service for a shard directory (HDFS-role host).

    Run one next to wherever the training shards live::

        srv = FileServer("/data/criteo", port=0)
        srv.start()            # srv.url -> "psfs://host:port"

    Workers then read ``f"{srv.url}/day_0.gz"`` through the ordinary
    readers.  Serving is threaded (one connection per request) and strictly
    confined to the resolved root.
    """

    def __init__(self, root: str, *, host: str = "0.0.0.0", port: int = 0,
                 advertise_host: str = "127.0.0.1") -> None:
        self.root = os.path.realpath(root)
        if not os.path.isdir(self.root):
            raise NotADirectoryError(self.root)
        self.advertise_host = advertise_host
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                # persistent connection: loop framed requests until the
                # client closes (streaming readers reuse one socket per
                # shard instead of a handshake per 4 MB buffer fill)
                while True:
                    try:
                        first = self.request.recv(1)
                        if not first:
                            return  # clean EOF
                        rest = _recv_exact(self.request, 4)
                        op, path_len = struct.unpack("!BI", first + rest)
                        path = _recv_exact(self.request, path_len).decode()
                        offset, length = struct.unpack(
                            "!QQ", _recv_exact(self.request, 16)
                        )
                    except (ConnectionError, OSError):
                        return
                    try:
                        body = outer._serve(op, path, offset, length)
                        status = 0
                    except Exception as e:  # noqa: BLE001 — reply, don't die
                        body = f"{type(e).__name__}: {e}".encode()[:4096]
                        status = 1
                    try:
                        self.request.sendall(
                            struct.pack("!BQ", status, len(body)) + body
                        )
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None
        #: per-op request counters (observability + cache-behavior tests)
        self.op_counts: dict = {}
        self._count_lock = threading.Lock()

    # -- request handlers ----------------------------------------------------
    def _resolve(self, rel: str) -> str:
        full = os.path.realpath(os.path.join(self.root, rel))
        if full != self.root and not full.startswith(self.root + os.sep):
            raise PermissionError(f"path escapes root: {rel!r}")
        return full

    def _serve(self, op: int, path: str, offset: int, length: int) -> bytes:
        with self._count_lock:
            self.op_counts[op] = self.op_counts.get(op, 0) + 1
        if op == _OP_STAT:
            st = os.stat(self._resolve(path))
            return f"{st.st_size}:{st.st_mtime_ns}".encode()
        if op == _OP_READ:
            if length > _MAX_READ:
                raise ValueError(f"range too large: {length}")
            with open(self._resolve(path), "rb") as f:
                f.seek(offset)
                return f.read(length)
        if op == _OP_LIST:
            names = []
            for dirpath, _dirs, files in os.walk(self.root):
                for name in files:
                    full = os.path.join(dirpath, name)
                    names.append(os.path.relpath(full, self.root))
            return "\n".join(names).encode()
        raise ValueError(f"unknown op {op}")

    # -- lifecycle -----------------------------------------------------------
    @property
    def url(self) -> str:
        return f"psfs://{self.advertise_host}:{self.port}"

    def start(self) -> "FileServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="psfs-server", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
