"""psx — the command-line launcher.

Reference analogue: ``script/local.sh`` + the gflags/`main.cc` entry point
(SURVEY.md §2 #23 [U]): one binary, behavior selected by config.  Here::

    psx run config.yaml [--steps N]     # run a registered app from a config
    psx eval CKPT_ROOT --table w ...    # offline AUC from a checkpoint
    psx apps                            # list registered apps

Installed as a console script (``pyproject.toml``) and runnable as
``python -m parameter_server_tpu.cli``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

import numpy as np

from parameter_server_tpu.core.filters import DEFAULT_SPEC


def _cmd_run(args: argparse.Namespace) -> int:
    from parameter_server_tpu import app as app_lib

    cfg = app_lib.load_config(args.config)
    if args.steps is not None:
        cfg = dataclasses.replace(cfg, steps=args.steps)
    if getattr(args, "tail_filter", None) is not None:
        cfg = dataclasses.replace(
            cfg,
            data=dataclasses.replace(cfg.data, tail_threshold=args.tail_filter),
        )
    run = app_lib.create(cfg)
    result = run()
    losses = result.pop("losses", [])
    if losses:
        result["first_loss"] = round(float(np.mean(losses[:10])), 6)
        result["final_loss"] = round(float(np.mean(losses[-10:])), 6)
    print(json.dumps({"app": cfg.app, **result}))
    return 0


def _cmd_eval(args: argparse.Namespace) -> int:
    from parameter_server_tpu import evaluation
    from parameter_server_tpu.utils.keys import HashLocalizer

    from parameter_server_tpu.data.synthetic import SyntheticCTR

    stream = SyntheticCTR(
        key_space=args.key_space,
        nnz=args.nnz,
        batch_size=args.batch_size,
        seed=args.seed,
    )
    batches = [stream.next_batch() for _ in range(args.batches)]
    report = evaluation.evaluate_checkpoint(
        args.ckpt_root,
        args.table,
        batches,
        step=args.step,
        model=args.model,
        localizer=(
            HashLocalizer(args.rows, hash_bits=args.hash_bits or 64)
            if args.rows
            else None
        ),
        hash_bits=args.hash_bits or None,
    )
    print(json.dumps(report))
    return 0


def _cmd_serve(args: argparse.Namespace) -> int:
    """Run the psfs shard file server (reference file.h/HDFS host role)."""
    import threading

    from parameter_server_tpu.data.fs import FileServer

    srv = FileServer(
        args.root, host=args.host, port=args.port,
        advertise_host=args.advertise_host,
    ).start()
    print(json.dumps({"url": srv.url, "root": srv.root}), flush=True)
    try:
        threading.Event().wait()  # serve until interrupted
    except KeyboardInterrupt:
        srv.stop()
    return 0


def _cmd_apps(_args: argparse.Namespace) -> int:
    from parameter_server_tpu import app as app_lib

    for name in app_lib.registered_apps():
        print(name)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="psx", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="run an app from a yaml/json config")
    run.add_argument("config")
    run.add_argument("--steps", type=int, default=None, help="override steps")
    run.add_argument(
        "--tail-filter", type=int, default=None, metavar="K",
        help="override data.tail_threshold: mask keys seen < K times "
        "(count-min tail filter on the input stream; 0 disables)",
    )
    run.set_defaults(fn=_cmd_run)

    ev = sub.add_parser("eval", help="offline eval of a saved checkpoint")
    ev.add_argument("ckpt_root")
    ev.add_argument("--table", default="w")
    ev.add_argument("--model", default="lr", choices=["lr", "fm"])
    ev.add_argument("--step", type=int, default=None)
    ev.add_argument("--rows", type=int, default=0, help="localizer capacity")
    ev.add_argument(
        "--hash-bits", type=int, default=0, choices=[0, 32, 64],
        help="hash width of the training localizer (0 = manifest/default); "
        "device-hash tables need 32",
    )
    ev.add_argument("--batches", type=int, default=8)
    ev.add_argument("--batch-size", type=int, default=1024)
    ev.add_argument("--key-space", type=int, default=1 << 22)
    ev.add_argument("--nnz", type=int, default=39)
    ev.add_argument("--seed", type=int, default=0)
    ev.set_defaults(fn=_cmd_eval)

    apps = sub.add_parser("apps", help="list registered apps")
    apps.set_defaults(fn=_cmd_apps)

    se = sub.add_parser(
        "serve",
        help="serve a shard directory over psfs:// (readers stream from it)",
    )
    se.add_argument("root")
    se.add_argument("--host", default="0.0.0.0")
    se.add_argument("--port", type=int, default=0)
    se.add_argument("--advertise-host", default="127.0.0.1")
    se.set_defaults(fn=_cmd_serve)

    la = sub.add_parser(
        "launch",
        help="spawn scheduler+servers+workers as OS processes over TcpVan",
    )
    la.add_argument("--workers", type=int, default=2)
    la.add_argument("--servers", type=int, default=2)
    la.add_argument("--steps", type=int, default=20)
    la.add_argument("--rows", type=int, default=1 << 14)
    la.add_argument("--batch-size", type=int, default=256)
    la.add_argument("--ckpt-root", default=None)
    la.add_argument(
        "--filters", default=DEFAULT_SPEC,
        help="wire filter stack on the TcpVan: 'none' to opt out, "
        "'lossless' (=key_caching+zlib, default — bit-exact wire), 'full' "
        "(adds the LOSSY int8 quantizer; explicit opt-in), or a "
        "'+'-joined subset of {key_caching, int8, zlib, noise}",
    )
    la.set_defaults(fn=_cmd_launch)

    sp = sub.add_parser(
        "launch-spmd",
        help="multi-host GSPMD job: N processes joined by jax.distributed "
        "(pod runtime; CPU-sim with --cpu-devices)",
    )
    sp.add_argument("--num-procs", type=int, default=2)
    sp.add_argument("--cpu-devices", type=int, default=4,
                    help="virtual CPU devices per process (0 = real chips)")
    sp.add_argument("--steps", type=int, default=8)
    sp.add_argument("--rows", type=int, default=1 << 12)
    sp.add_argument("--global-batch", type=int, default=256)
    sp.add_argument("--mesh-data", type=int, default=2)
    sp.set_defaults(fn=_cmd_launch_spmd)

    hy = sub.add_parser(
        "launch-hybrid",
        help="dual-plane config #5: TcpVan embedding servers in their own "
        "processes + a jax.distributed GSPMD body (CPU-sim by default)",
    )
    hy.add_argument("--num-body", type=int, default=2)
    hy.add_argument("--cpu-devices", type=int, default=4)
    hy.add_argument("--num-servers", type=int, default=2)
    hy.add_argument("--steps", type=int, default=4)
    hy.add_argument("--vocab", type=int, default=256)
    hy.add_argument("--layers", type=int, default=2)
    hy.add_argument("--heads", type=int, default=4)
    hy.add_argument("--d-model", type=int, default=32)
    hy.add_argument("--d-ff", type=int, default=64)
    hy.add_argument("--seq", type=int, default=16)
    hy.add_argument("--global-batch", type=int, default=8)
    hy.add_argument("--emb-optimizer", default="adagrad")
    hy.add_argument("--bsp", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="barrier the embedding plane every step (parity "
                    "mode, the default — matches launch_hybrid()); "
                    "--no-bsp enables the SSP overlap shape")
    hy.add_argument("--max-delay", type=int, default=2)
    hy.add_argument("--filters", default=DEFAULT_SPEC)
    hy.set_defaults(fn=_cmd_launch_hybrid)
    return p


def _cmd_launch_hybrid(args: argparse.Namespace) -> int:
    from parameter_server_tpu.launch_hybrid import launch_hybrid

    result = launch_hybrid(
        num_body=args.num_body,
        cpu_devices=args.cpu_devices,
        num_servers=args.num_servers,
        steps=args.steps,
        vocab=args.vocab, layers=args.layers, heads=args.heads,
        d_model=args.d_model, d_ff=args.d_ff, seq=args.seq,
        global_batch=args.global_batch,
        emb_optimizer=args.emb_optimizer,
        bsp=args.bsp, max_delay=args.max_delay,
        filters=args.filters,
    )
    losses = result["losses"].get(0, [])
    print(json.dumps({
        "returncodes": result["returncodes"],
        "losses": losses,
        "wire": result["wire"],
    }))
    return 0 if all(rc == 0 for rc in result["returncodes"]) else 1


def _cmd_launch_spmd(args: argparse.Namespace) -> int:
    from parameter_server_tpu.launch_spmd import launch_spmd

    result = launch_spmd(
        num_procs=args.num_procs,
        cpu_devices=args.cpu_devices,
        steps=args.steps,
        rows=args.rows,
        global_batch=args.global_batch,
        mesh_data=args.mesh_data,
    )
    losses = result["losses"].get(0, [])
    print(json.dumps({
        "returncodes": result["returncodes"],
        "first_loss": losses[0] if losses else None,
        "final_loss": losses[-1] if losses else None,
    }))
    return 0 if all(rc == 0 for rc in result["returncodes"]) else 1


def _cmd_launch(args: argparse.Namespace) -> int:
    from parameter_server_tpu.launch import launch

    result = launch(
        num_workers=args.workers,
        num_servers=args.servers,
        steps=args.steps,
        rows=args.rows,
        batch_size=args.batch_size,
        ckpt_root=args.ckpt_root,
        filters=args.filters,
    )
    print(json.dumps(result))
    return 0 if all(rc == 0 for rc in result["returncodes"]) else 1


def main(argv=None) -> int:
    # The dev image's sitecustomize registers the axon TPU plugin at
    # interpreter boot, BEFORE the environment's JAX_PLATFORMS=cpu is
    # consulted — re-assert the caller's intent or a CPU-only run hangs on
    # TPU backend init (same trick as tests/conftest.py / __graft_entry__).
    import os

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        from parameter_server_tpu.utils.platform import force_cpu

        force_cpu()
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
