"""Sharded checkpoint / resume for KV tables, optimizer state, and clocks.

Reference analogue: on a ``SaveModel`` task every server writes its key-range
of the model to file/HDFS and ``model_evaluation`` reads the parts back
(``src/app/linear_method/model_evaluation.h`` [U]).  The reference saves only
weights; this module closes the gap called out in SURVEY.md §5 by also saving
optimizer-state rows and the consistency vector clocks, so training can resume
mid-stream (SSP window intact) rather than restart.

Layout (one directory per step)::

    <root>/step_000042/
        MANIFEST.json                     # written LAST -> commit marker
        w.shard0-of-2.npz                 # value + optimizer state rows
        w.shard1-of-2.npz

Each shard file holds the server's contiguous row-range (NodeAssigner
scheme, ``kv/partition.py``) *excluding* the trash row, plus its global row
offset.  Restore is elastic: the new server count may differ from the saved
one — each restoring server reads exactly the old shard files overlapping its
new row-range and slices them (the re-shard path of SURVEY.md §5 elastic
recovery).
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import Any, Dict, List, Optional

import numpy as np

from parameter_server_tpu.kv.partition import RangePartition
from parameter_server_tpu.kv.table import KVTable

_STEP_PREFIX = "step_"
_MANIFEST = "MANIFEST.json"


def _step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"{_STEP_PREFIX}{step:06d}")


def _shard_path(step_dir: str, table: str, s: int, n: int) -> str:
    return os.path.join(step_dir, f"{table}.shard{s}-of-{n}.npz")


@dataclasses.dataclass(frozen=True)
class CheckpointInfo:
    step: int
    num_servers: int
    tables: Dict[str, int]  # table name -> global rows
    clocks: List[int]
    extras: Dict[str, Any]


def save_arrays_shard(
    root: str,
    step: int,
    table_name: str,
    server_index: int,
    num_servers: int,
    row_offset: int,
    value: np.ndarray,
    state: Dict[str, np.ndarray],
) -> str:
    """Write one server's row-range as raw arrays (the low-level writer).

    Safe to call concurrently from all servers: each writes a distinct file
    via an adjacent temp name + atomic rename.
    """
    step_dir = _step_dir(root, step)
    os.makedirs(step_dir, exist_ok=True)
    path = _shard_path(step_dir, table_name, server_index, num_servers)
    arrays = {
        "value": np.asarray(value),
        "row_offset": np.asarray(row_offset, dtype=np.int64),
    }
    for k, v in state.items():
        arrays[f"state.{k}"] = np.asarray(v)
    fd, tmp = tempfile.mkstemp(dir=step_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return path


def save_shard(
    root: str,
    step: int,
    table_name: str,
    table: KVTable,
    server_index: int,
    num_servers: int,
    row_offset: int,
) -> str:
    """Write one KVTable shard's row-range (value + optimizer state).

    The trash row (last) is excluded — it is reconstructed on restore.
    """
    return save_arrays_shard(
        root,
        step,
        table_name,
        server_index,
        num_servers,
        row_offset,
        np.asarray(table.value)[: table.rows],
        {k: np.asarray(v)[: table.rows] for k, v in table.state.items()},
    )


def finalize(
    root: str,
    step: int,
    num_servers: int,
    tables: Dict[str, int],
    clocks: Optional[List[int]] = None,
    extras: Optional[Dict[str, Any]] = None,
) -> None:
    """Coordinator commit: verify every shard exists, then write MANIFEST.

    A step directory without MANIFEST.json is an aborted save and is ignored
    by ``latest_step``/``restore`` — the commit-marker pattern.
    """
    step_dir = _step_dir(root, step)
    for t, _rows in tables.items():
        for s in range(num_servers):
            p = _shard_path(step_dir, t, s, num_servers)
            if not os.path.exists(p):
                raise FileNotFoundError(f"missing shard before commit: {p}")
    manifest = {
        "step": step,
        "num_servers": num_servers,
        "tables": dict(tables),
        "clocks": list(clocks or []),
        "extras": dict(extras or {}),
    }
    tmp = os.path.join(step_dir, _MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, os.path.join(step_dir, _MANIFEST))


def list_steps(root: str) -> List[int]:
    """Committed checkpoint steps, ascending."""
    if not os.path.isdir(root):
        return []
    steps = []
    for name in os.listdir(root):
        if not name.startswith(_STEP_PREFIX):
            continue
        if not os.path.exists(os.path.join(root, name, _MANIFEST)):
            continue  # aborted save
        try:
            steps.append(int(name[len(_STEP_PREFIX) :]))
        except ValueError:
            continue
    return sorted(steps)


def latest_step(root: str) -> Optional[int]:
    steps = list_steps(root)
    return steps[-1] if steps else None


def read_info(root: str, step: int) -> CheckpointInfo:
    with open(os.path.join(_step_dir(root, step), _MANIFEST)) as f:
        m = json.load(f)
    return CheckpointInfo(
        step=m["step"],
        num_servers=m["num_servers"],
        tables={k: int(v) for k, v in m["tables"].items()},
        clocks=[int(c) for c in m["clocks"]],
        extras=m["extras"],
    )


def _load_range(
    step_dir: str,
    table_name: str,
    saved_partition: RangePartition,
    lo: int,
    hi: int,
) -> Dict[str, np.ndarray]:
    """Assemble global rows [lo, hi) of a table from the saved shard files.

    Reads only the shards overlapping the range — the elastic-restore core.
    """
    off = saved_partition.offsets
    n = saved_partition.num_servers
    pieces: Dict[str, List[np.ndarray]] = {}
    for s in range(n):
        s_lo, s_hi = int(off[s]), int(off[s + 1])
        a, b = max(lo, s_lo), min(hi, s_hi)
        if a >= b:
            continue
        with np.load(_shard_path(step_dir, table_name, s, n)) as z:
            if int(z["row_offset"]) != s_lo:
                raise ValueError(
                    f"shard {s} of {table_name}: offset {int(z['row_offset'])}"
                    f" != expected {s_lo}"
                )
            for k in z.files:
                if k == "row_offset":
                    continue
                pieces.setdefault(k, []).append(z[k][a - s_lo : b - s_lo])
    return {k: np.concatenate(v, axis=0) for k, v in pieces.items()}


def load_arrays_shard(
    root: str,
    step: int,
    table_name: str,
    server_index: int,
    num_servers: int,
) -> Dict[str, np.ndarray]:
    """Read this server's (possibly re-sharded) row-range as raw arrays.

    ``num_servers`` is the NEW server count; the saved count comes from the
    manifest.  Returns ``{"value": ..., "state.<k>": ...}``.
    """
    info = read_info(root, step)
    rows = info.tables[table_name]
    saved = RangePartition(rows, info.num_servers)
    off = RangePartition(rows, num_servers).offsets
    lo, hi = int(off[server_index]), int(off[server_index + 1])
    return _load_range(_step_dir(root, step), table_name, saved, lo, hi)


def restore_shard(
    root: str,
    step: int,
    table_name: str,
    table: KVTable,
    server_index: int,
    num_servers: int,
) -> None:
    """Load this server's (possibly re-sharded) row-range into ``table``.

    ``num_servers`` is the NEW server count; the saved count comes from the
    manifest.  The table's trash row is reset to init fills.
    """
    arrays = load_arrays_shard(root, step, table_name, server_index, num_servers)
    if arrays["value"].shape[0] != table.rows:
        raise ValueError(
            f"table shard rows {table.rows} != saved range "
            f"{arrays['value'].shape[0]}"
        )
    import jax.numpy as jnp

    fills = table.optimizer.state_shapes()
    value = np.zeros((table.rows + 1, table.dim), np.asarray(table.value).dtype)
    value[: table.rows] = arrays["value"]
    table.value = jnp.asarray(value)
    for k in table.state:
        buf = np.full(
            (table.rows + 1, table.dim),
            fills[k],
            np.asarray(table.state[k]).dtype,
        )
        buf[: table.rows] = arrays[f"state.{k}"]
        table.state[k] = jnp.asarray(buf)


def load_global_weights(root: str, step: int, table_name: str) -> np.ndarray:
    """Full servable weight table for offline eval (model_evaluation path).

    Note: returns the raw *value* rows; for lazy-weight optimizers (FTRL) use
    ``load_global_arrays`` and compute weights via the optimizer.
    """
    return load_global_arrays(root, step, table_name)["value"]


def load_global_arrays(root: str, step: int, table_name: str) -> Dict[str, np.ndarray]:
    info = read_info(root, step)
    rows = info.tables[table_name]
    saved = RangePartition(rows, info.num_servers)
    return _load_range(_step_dir(root, step), table_name, saved, 0, rows)


def retain(root: str, keep: int) -> None:
    """Delete all but the newest ``keep`` committed checkpoints.

    ``keep=0`` deletes every committed checkpoint; negative is an error.
    """
    import shutil

    if keep < 0:
        raise ValueError(f"retain: keep must be >= 0, got {keep}")
    steps = list_steps(root)
    for step in steps if keep == 0 else steps[:-keep]:
        shutil.rmtree(_step_dir(root, step), ignore_errors=True)
