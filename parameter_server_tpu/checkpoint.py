"""Sharded checkpoint / resume for KV tables, optimizer state, and clocks.

Reference analogue: on a ``SaveModel`` task every server writes its key-range
of the model to file/HDFS and ``model_evaluation`` reads the parts back
(``src/app/linear_method/model_evaluation.h`` [U]).  The reference saves only
weights; this module closes the gap called out in SURVEY.md §5 by also saving
optimizer-state rows and the consistency vector clocks, so training can resume
mid-stream (SSP window intact) rather than restart.

Layout (one directory per step)::

    <root>/step_000042/
        MANIFEST.json                     # written LAST -> commit marker
        w.shard0-of-2.npz                 # value + optimizer state rows
        w.shard1-of-2.npz

Each shard file holds the server's contiguous row-range (NodeAssigner
scheme, ``kv/partition.py``) *excluding* the trash row, plus its global row
offset.  Restore is elastic: the new server count may differ from the saved
one — each restoring server reads exactly the old shard files overlapping its
new row-range and slices them (the re-shard path of SURVEY.md §5 elastic
recovery).

Durability plane (ISSUE 16) — the partitioned snapshot format (format 2)::

    <root>/snap_000042/
        MANIFEST.json                     # written LAST, CRC-armored
        w.seg00000000-00000250.npz        # one file per routing SEGMENT
        w.delta.s1.npz                    # dirty-row delta log (per server)

Differences from the legacy uniform layout:

- **partitioned**: one file per ``RoutingTable`` segment, written by the
  segment's OWNER, so any post-migration layout can snapshot (the legacy
  format refuses non-uniform fleets with :class:`CheckpointLayoutError`);
- **incremental**: every segment entry records its ``__sver__`` version
  clock (the per-segment LSN) at commit time; a later snapshot whose
  segment version has not advanced carries the OLD file forward instead of
  rewriting it, and rows written during the snapshot window ride a dirty
  delta log.  Per-entry ``step`` stamps order the replay: a delta applies
  to a row only when it is at least as new as the row's covering segment
  file, so a chain of incrementals restores bit-identical to a full save;
- **CRC-armored**: the manifest records a crc32 per referenced file and
  one over its own body; :func:`finalize_snapshot` verifies every file
  (existence, CRC, exact tiling of the row space) BEFORE the manifest is
  written, so a manifest can never reference a torn file, and
  :func:`read_snapshot` / :func:`snapshot_rows` re-verify on restore
  (:class:`CheckpointCorruptError`);
- **any fleet shape**: :func:`snapshot_rows` assembles an arbitrary global
  row range from whatever segment files overlap it (the redistribution
  schedule of PAPERS.md arXiv:2112.01075 — each new owner reads only the
  file ranges it owns), so restore reshards onto any new routing table.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from parameter_server_tpu.kv.partition import RangePartition
from parameter_server_tpu.kv.table import KVTable

_STEP_PREFIX = "step_"
_SNAP_PREFIX = "snap_"
_MANIFEST = "MANIFEST.json"

#: partitioned-snapshot manifest format (bumped on incompatible layout
#: changes; see MIGRATION.md "Snapshot format versioning").
SNAP_FORMAT = 2


class CheckpointLayoutError(RuntimeError):
    """The table layout cannot be saved in the requested checkpoint format.

    Raised (typed, not an opaque assert) by ``KVServer.save_checkpoint``
    when a post-migration fleet hits the legacy uniform-contiguous shard
    format — the caller should use the partitioned snapshot path
    (``KVWorker.save_snapshot``) instead.
    """


class CheckpointCorruptError(RuntimeError):
    """A snapshot file or manifest failed its CRC/consistency check.

    Torn files (a server killed mid-write), bit rot, and truncated
    manifests all land here — restore-source selection treats the snapshot
    as absent and falls back to the next source rather than loading
    corrupt rows.
    """


def _step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"{_STEP_PREFIX}{step:06d}")


def _shard_path(step_dir: str, table: str, s: int, n: int) -> str:
    return os.path.join(step_dir, f"{table}.shard{s}-of-{n}.npz")


@dataclasses.dataclass(frozen=True)
class CheckpointInfo:
    step: int
    num_servers: int
    tables: Dict[str, int]  # table name -> global rows
    clocks: List[int]
    extras: Dict[str, Any]


def save_arrays_shard(
    root: str,
    step: int,
    table_name: str,
    server_index: int,
    num_servers: int,
    row_offset: int,
    value: np.ndarray,
    state: Dict[str, np.ndarray],
) -> str:
    """Write one server's row-range as raw arrays (the low-level writer).

    Safe to call concurrently from all servers: each writes a distinct file
    via an adjacent temp name + atomic rename.
    """
    step_dir = _step_dir(root, step)
    os.makedirs(step_dir, exist_ok=True)
    path = _shard_path(step_dir, table_name, server_index, num_servers)
    arrays = {
        "value": np.asarray(value),
        "row_offset": np.asarray(row_offset, dtype=np.int64),
    }
    for k, v in state.items():
        arrays[f"state.{k}"] = np.asarray(v)
    fd, tmp = tempfile.mkstemp(dir=step_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return path


def save_shard(
    root: str,
    step: int,
    table_name: str,
    table: KVTable,
    server_index: int,
    num_servers: int,
    row_offset: int,
) -> str:
    """Write one KVTable shard's row-range (value + optimizer state).

    The trash row (last) is excluded — it is reconstructed on restore.
    """
    return save_arrays_shard(
        root,
        step,
        table_name,
        server_index,
        num_servers,
        row_offset,
        np.asarray(table.value)[: table.rows],
        {k: np.asarray(v)[: table.rows] for k, v in table.state.items()},
    )


def finalize(
    root: str,
    step: int,
    num_servers: int,
    tables: Dict[str, int],
    clocks: Optional[List[int]] = None,
    extras: Optional[Dict[str, Any]] = None,
) -> None:
    """Coordinator commit: verify every shard exists, then write MANIFEST.

    A step directory without MANIFEST.json is an aborted save and is ignored
    by ``latest_step``/``restore`` — the commit-marker pattern.
    """
    step_dir = _step_dir(root, step)
    for t, _rows in tables.items():
        for s in range(num_servers):
            p = _shard_path(step_dir, t, s, num_servers)
            if not os.path.exists(p):
                raise FileNotFoundError(f"missing shard before commit: {p}")
    manifest = {
        "step": step,
        "num_servers": num_servers,
        "tables": dict(tables),
        "clocks": list(clocks or []),
        "extras": dict(extras or {}),
    }
    tmp = os.path.join(step_dir, _MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, os.path.join(step_dir, _MANIFEST))


def list_steps(root: str) -> List[int]:
    """Committed checkpoint steps, ascending."""
    if not os.path.isdir(root):
        return []
    steps = []
    for name in os.listdir(root):
        if not name.startswith(_STEP_PREFIX):
            continue
        if not os.path.exists(os.path.join(root, name, _MANIFEST)):
            continue  # aborted save
        try:
            steps.append(int(name[len(_STEP_PREFIX) :]))
        except ValueError:
            continue
    return sorted(steps)


def latest_step(root: str) -> Optional[int]:
    steps = list_steps(root)
    return steps[-1] if steps else None


def read_info(root: str, step: int) -> CheckpointInfo:
    with open(os.path.join(_step_dir(root, step), _MANIFEST)) as f:
        m = json.load(f)
    return CheckpointInfo(
        step=m["step"],
        num_servers=m["num_servers"],
        tables={k: int(v) for k, v in m["tables"].items()},
        clocks=[int(c) for c in m["clocks"]],
        extras=m["extras"],
    )


def _load_range(
    step_dir: str,
    table_name: str,
    saved_partition: RangePartition,
    lo: int,
    hi: int,
) -> Dict[str, np.ndarray]:
    """Assemble global rows [lo, hi) of a table from the saved shard files.

    Reads only the shards overlapping the range — the elastic-restore core.
    """
    off = saved_partition.offsets
    n = saved_partition.num_servers
    pieces: Dict[str, List[np.ndarray]] = {}
    for s in range(n):
        s_lo, s_hi = int(off[s]), int(off[s + 1])
        a, b = max(lo, s_lo), min(hi, s_hi)
        if a >= b:
            continue
        with np.load(_shard_path(step_dir, table_name, s, n)) as z:
            if int(z["row_offset"]) != s_lo:
                raise ValueError(
                    f"shard {s} of {table_name}: offset {int(z['row_offset'])}"
                    f" != expected {s_lo}"
                )
            for k in z.files:
                if k == "row_offset":
                    continue
                pieces.setdefault(k, []).append(z[k][a - s_lo : b - s_lo])
    return {k: np.concatenate(v, axis=0) for k, v in pieces.items()}


def load_arrays_shard(
    root: str,
    step: int,
    table_name: str,
    server_index: int,
    num_servers: int,
) -> Dict[str, np.ndarray]:
    """Read this server's (possibly re-sharded) row-range as raw arrays.

    ``num_servers`` is the NEW server count; the saved count comes from the
    manifest.  Returns ``{"value": ..., "state.<k>": ...}``.
    """
    info = read_info(root, step)
    rows = info.tables[table_name]
    saved = RangePartition(rows, info.num_servers)
    off = RangePartition(rows, num_servers).offsets
    lo, hi = int(off[server_index]), int(off[server_index + 1])
    return _load_range(_step_dir(root, step), table_name, saved, lo, hi)


def restore_shard(
    root: str,
    step: int,
    table_name: str,
    table: KVTable,
    server_index: int,
    num_servers: int,
) -> None:
    """Load this server's (possibly re-sharded) row-range into ``table``.

    ``num_servers`` is the NEW server count; the saved count comes from the
    manifest.  The table's trash row is reset to init fills.
    """
    arrays = load_arrays_shard(root, step, table_name, server_index, num_servers)
    if arrays["value"].shape[0] != table.rows:
        raise ValueError(
            f"table shard rows {table.rows} != saved range "
            f"{arrays['value'].shape[0]}"
        )
    import jax.numpy as jnp

    fills = table.optimizer.state_shapes()
    value = np.zeros((table.rows + 1, table.dim), np.asarray(table.value).dtype)
    value[: table.rows] = arrays["value"]
    table.value = jnp.asarray(value)
    for k in table.state:
        buf = np.full(
            (table.rows + 1, table.dim),
            fills[k],
            np.asarray(table.state[k]).dtype,
        )
        buf[: table.rows] = arrays[f"state.{k}"]
        table.state[k] = jnp.asarray(buf)


def load_global_weights(root: str, step: int, table_name: str) -> np.ndarray:
    """Full servable weight table for offline eval (model_evaluation path).

    Note: returns the raw *value* rows; for lazy-weight optimizers (FTRL) use
    ``load_global_arrays`` and compute weights via the optimizer.
    """
    return load_global_arrays(root, step, table_name)["value"]


def load_global_arrays(root: str, step: int, table_name: str) -> Dict[str, np.ndarray]:
    info = read_info(root, step)
    rows = info.tables[table_name]
    saved = RangePartition(rows, info.num_servers)
    return _load_range(_step_dir(root, step), table_name, saved, 0, rows)


def retain(root: str, keep: int) -> None:
    """Delete all but the newest ``keep`` committed checkpoints.

    ``keep=0`` deletes every committed checkpoint; negative is an error.
    """
    import shutil

    if keep < 0:
        raise ValueError(f"retain: keep must be >= 0, got {keep}")
    steps = list_steps(root)
    for step in steps if keep == 0 else steps[:-keep]:
        shutil.rmtree(_step_dir(root, step), ignore_errors=True)


# -- durability plane: partitioned / incremental snapshots (ISSUE 16) --------
def _snap_dir(root: str, step: int) -> str:
    return os.path.join(root, f"{_SNAP_PREFIX}{step:06d}")


def _file_crc(path: str) -> int:
    """Streaming crc32 of a file's bytes (the torn-file armor)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                return crc
            crc = zlib.crc32(block, crc)


def _atomic_npz(snap_dir: str, path: str, arrays: Dict[str, np.ndarray]) -> None:
    fd, tmp = tempfile.mkstemp(dir=snap_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def write_segment_file(
    root: str,
    step: int,
    table_name: str,
    lo: int,
    hi: int,
    value: np.ndarray,
    state: Dict[str, np.ndarray],
) -> dict:
    """Write one routing segment's rows ``[lo, hi)`` (value + opt state).

    Written by the segment's OWNING server; safe concurrently because every
    segment has exactly one owner and writes go through an adjacent temp
    name + atomic rename.  Returns the manifest segment entry (without the
    commit-time ``sver`` stamp, which the driver fills in at finalize).
    """
    if value.shape[0] != hi - lo:
        raise ValueError(
            f"segment [{lo}, {hi}) of {table_name!r}: value has "
            f"{value.shape[0]} rows"
        )
    snap_dir = _snap_dir(root, step)
    os.makedirs(snap_dir, exist_ok=True)
    fname = f"{table_name}.seg{lo:08d}-{hi:08d}.npz"
    path = os.path.join(snap_dir, fname)
    arrays = {
        "value": np.asarray(value),
        "row_offset": np.asarray(lo, dtype=np.int64),
    }
    for k, v in state.items():
        arrays[f"state.{k}"] = np.asarray(v)
    _atomic_npz(snap_dir, path, arrays)
    return {
        "table": table_name,
        "lo": int(lo),
        "hi": int(hi),
        "step": int(step),
        "file": f"{_SNAP_PREFIX}{step:06d}/{fname}",
        "crc": _file_crc(path),
        "bytes": os.path.getsize(path),
        "sver": 0,
    }


def write_delta_file(
    root: str,
    step: int,
    table_name: str,
    writer: int,
    rows: np.ndarray,
    value: np.ndarray,
    state: Dict[str, np.ndarray],
) -> Optional[dict]:
    """Write a dirty-row delta log: rows written DURING the snapshot window.

    ``writer`` disambiguates concurrent writers (one delta file per server
    per table per step).  Returns the manifest delta entry, or None when
    there is nothing to log.
    """
    rows = np.asarray(rows, dtype=np.int64)
    if rows.size == 0:
        return None
    snap_dir = _snap_dir(root, step)
    os.makedirs(snap_dir, exist_ok=True)
    fname = f"{table_name}.delta.s{writer}.npz"
    path = os.path.join(snap_dir, fname)
    arrays = {"rows": rows, "value": np.asarray(value)}
    for k, v in state.items():
        arrays[f"state.{k}"] = np.asarray(v)
    _atomic_npz(snap_dir, path, arrays)
    return {
        "table": table_name,
        "step": int(step),
        "file": f"{_SNAP_PREFIX}{step:06d}/{fname}",
        "crc": _file_crc(path),
        "bytes": os.path.getsize(path),
        "rows": int(rows.size),
    }


def _manifest_crc(body: dict) -> int:
    return zlib.crc32(
        json.dumps(body, sort_keys=True, separators=(",", ":")).encode()
    )


def _verify_entry(root: str, entry: dict) -> str:
    """Existence + CRC check of one referenced file; returns its path."""
    path = os.path.join(root, entry["file"])
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"snapshot references missing file: {entry['file']}"
        )
    crc = _file_crc(path)
    if crc != int(entry["crc"]):
        raise CheckpointCorruptError(
            f"torn/corrupt snapshot file {entry['file']}: "
            f"crc {crc} != manifest {entry['crc']}"
        )
    return path


def finalize_snapshot(
    root: str,
    step: int,
    routing_payload: dict,
    segments: List[dict],
    deltas: List[dict],
    *,
    base_step: Optional[int] = None,
    clocks: Optional[List[int]] = None,
    extras: Optional[Dict[str, Any]] = None,
) -> None:
    """Driver commit: verify every referenced file, then write the manifest.

    The torn-file contract: a server killed mid-snapshot leaves either a
    missing segment (FileNotFoundError here) or a temp file no entry names
    — either way the manifest is never written, ``latest_snapshot`` never
    sees the step, and the previous snapshot stays the restore point.
    Verification also re-checks CARRIED entries (files living in older snap
    dirs), so an incremental chain cannot commit over a rotted base.
    """
    by_table: Dict[str, List[dict]] = {}
    for e in segments:
        by_table.setdefault(e["table"], []).append(e)
    for t, blob in routing_payload["tables"].items():
        rows = int(blob["rows"])
        entries = sorted(by_table.get(t, []), key=lambda e: e["lo"])
        cursor = 0
        for e in entries:
            if int(e["lo"]) != cursor:
                raise CheckpointCorruptError(
                    f"snapshot of {t!r} has a segment gap/overlap at row "
                    f"{cursor} (next entry starts at {e['lo']})"
                )
            cursor = int(e["hi"])
        if cursor != rows:
            raise CheckpointCorruptError(
                f"snapshot of {t!r} covers [0, {cursor}) of {rows} rows"
            )
    for entry in list(segments) + list(deltas):
        _verify_entry(root, entry)
    body = {
        "format": SNAP_FORMAT,
        "step": int(step),
        "base_step": None if base_step is None else int(base_step),
        "routing": routing_payload,
        "segments": sorted(
            segments, key=lambda e: (e["table"], e["lo"])
        ),
        "deltas": sorted(deltas, key=lambda e: (e["step"], e["table"])),
        "clocks": list(clocks or []),
        "extras": dict(extras or {}),
    }
    snap_dir = _snap_dir(root, step)
    os.makedirs(snap_dir, exist_ok=True)
    manifest = dict(body, crc=_manifest_crc(body))
    tmp = os.path.join(snap_dir, _MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, os.path.join(snap_dir, _MANIFEST))


def list_snapshots(root: str) -> List[int]:
    """Committed partitioned-snapshot steps, ascending (no CRC check)."""
    if not os.path.isdir(root):
        return []
    steps = []
    for name in os.listdir(root):
        if not name.startswith(_SNAP_PREFIX):
            continue
        if not os.path.exists(os.path.join(root, name, _MANIFEST)):
            continue  # aborted save
        try:
            steps.append(int(name[len(_SNAP_PREFIX):]))
        except ValueError:
            continue
    return sorted(steps)


def read_snapshot(root: str, step: int) -> dict:
    """Load + CRC-verify a snapshot manifest (raises on corruption)."""
    try:
        with open(os.path.join(_snap_dir(root, step), _MANIFEST)) as f:
            m = json.load(f)
    except json.JSONDecodeError as e:
        raise CheckpointCorruptError(
            f"snapshot {step} manifest is not valid JSON: {e}"
        ) from e
    if m.get("format") != SNAP_FORMAT:
        raise CheckpointCorruptError(
            f"snapshot {step} has format {m.get('format')!r}; this build "
            f"reads format {SNAP_FORMAT} (see MIGRATION.md)"
        )
    crc = m.pop("crc", None)
    if crc != _manifest_crc(m):
        raise CheckpointCorruptError(
            f"snapshot {step} manifest failed its CRC check "
            f"(recorded {crc})"
        )
    return m


def latest_snapshot(root: str) -> Optional[int]:
    """Newest snapshot whose manifest verifies; skips corrupt ones."""
    for step in reversed(list_snapshots(root)):
        try:
            read_snapshot(root, step)
            return step
        except (OSError, ValueError, CheckpointCorruptError):
            continue
    return None


def snapshot_rows(
    root: str, manifest: dict, table_name: str, lo: int, hi: int
) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
    """Assemble global rows ``[lo, hi)`` of ``table_name`` from a snapshot.

    The reshard-restore core: reads only the segment files OVERLAPPING the
    requested range (each is CRC-verified first), then replays the delta
    logs in step order — a delta row applies only when its stamp is at
    least as new as the row's covering segment file, which is what makes an
    incremental chain restore bit-identical to a full snapshot.
    """
    n = hi - lo
    if n <= 0:
        raise ValueError(f"bad range [{lo}, {hi})")
    value: Optional[np.ndarray] = None
    state: Dict[str, np.ndarray] = {}
    seg_step = np.zeros(n, dtype=np.int64)
    covered = np.zeros(n, dtype=bool)
    for e in manifest["segments"]:
        if e["table"] != table_name:
            continue
        a, b = max(lo, int(e["lo"])), min(hi, int(e["hi"]))
        if a >= b:
            continue
        path = _verify_entry(root, e)
        with np.load(path) as z:
            if int(z["row_offset"]) != int(e["lo"]):
                raise CheckpointCorruptError(
                    f"{e['file']}: row_offset {int(z['row_offset'])} != "
                    f"manifest lo {e['lo']}"
                )
            sl = slice(a - int(e["lo"]), b - int(e["lo"]))
            if value is None:
                v = z["value"]
                value = np.zeros((n, v.shape[1]), dtype=v.dtype)
                state = {
                    k[len("state."):]: np.zeros((n, v.shape[1]), dtype=v.dtype)
                    for k in z.files
                    if k.startswith("state.")
                }
            value[a - lo : b - lo] = z["value"][sl]
            for k in state:
                state[k][a - lo : b - lo] = z[f"state.{k}"][sl]
        seg_step[a - lo : b - lo] = int(e["step"])
        covered[a - lo : b - lo] = True
    if value is None or not covered.all():
        missing = int(n if value is None else (~covered).sum())
        raise CheckpointCorruptError(
            f"snapshot of {table_name!r}: {missing} rows of [{lo}, {hi}) "
            "not covered by any segment file"
        )
    for d in sorted(manifest["deltas"], key=lambda e: int(e["step"])):
        if d["table"] != table_name:
            continue
        path = _verify_entry(root, d)
        with np.load(path) as z:
            rows = np.asarray(z["rows"], dtype=np.int64)
            m = (rows >= lo) & (rows < hi)
            if not m.any():
                continue
            r = rows[m] - lo
            newer = int(d["step"]) >= seg_step[r]
            r = r[newer]
            if r.size == 0:
                continue
            value[r] = z["value"][m][newer]
            for k in state:
                state[k][r] = z[f"state.{k}"][m][newer]
    return value, state


def restore_segments(
    root: str,
    manifest: dict,
    table_name: str,
    segments: List[Tuple[int, int]],
    table: KVTable,
) -> None:
    """Load a server's owned ``[(lo, hi), ...]`` ranges into ``table``.

    The restore-to-any-fleet-shape path: ``segments`` comes from the NEW
    routing table and need not match the saved layout — each range is
    assembled from whatever files overlap it.  The trash row is rebuilt
    from optimizer init fills, exactly as the legacy restore does.
    """
    pieces = [
        snapshot_rows(root, manifest, table_name, lo, hi)
        for lo, hi in segments
        if hi > lo
    ]
    dtype = np.asarray(table.value).dtype
    if pieces:
        value = np.concatenate([v for v, _ in pieces], axis=0)
        state = {
            k: np.concatenate([s[k] for _, s in pieces], axis=0)
            for k in pieces[0][1]
        }
    else:
        value = np.zeros((0, table.dim), dtype)
        state = {k: np.zeros((0, table.dim), dtype) for k in table.state}
    table.install_rows(value.astype(dtype, copy=False), state)


def retain_snapshots(root: str, keep: int) -> None:
    """Delete old snapshot dirs, preserving incremental-chain references.

    Keeps the newest ``keep`` committed snapshots PLUS any older snap dir
    their manifests still reference (carried segment files / delta logs) —
    an incremental chain must never lose its base out from under it.
    ``keep=0`` deletes everything; negative is an error.

    Aborted snapshots (a snap dir with segment files but no manifest — a
    server died mid-write, or the driver aborted) are swept too, but only
    at steps BELOW the newest committed one: an in-flight snapshot always
    targets a step above everything committed, so its pre-commit files are
    never yanked by a concurrent retention pass.
    """
    import shutil

    if keep < 0:
        raise ValueError(f"retain_snapshots: keep must be >= 0, got {keep}")
    steps = list_snapshots(root)
    kept = set() if keep == 0 else set(steps[-keep:])
    referenced = set()
    for step in kept:
        try:
            m = read_snapshot(root, step)
        except (OSError, ValueError, CheckpointCorruptError):
            continue
        for e in list(m["segments"]) + list(m["deltas"]):
            referenced.add(str(e["file"]).split("/", 1)[0])
    for step in steps:
        if step in kept or f"{_SNAP_PREFIX}{step:06d}" in referenced:
            continue
        shutil.rmtree(_snap_dir(root, step), ignore_errors=True)
    if steps:
        newest = steps[-1]
        for name in os.listdir(root):
            if not name.startswith(_SNAP_PREFIX) or name in referenced:
                continue
            if os.path.exists(os.path.join(root, name, _MANIFEST)):
                continue
            try:
                aborted = int(name[len(_SNAP_PREFIX):])
            except ValueError:
                continue
            if aborted < newest:
                shutil.rmtree(os.path.join(root, name), ignore_errors=True)
