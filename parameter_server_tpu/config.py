"""Configuration dataclasses.

The reference uses a two-level config split: process-topology gflags (role,
scheduler address, worker/server counts) and a text-format protobuf app config
(data, loss, penalty, learning rate, consistency window).  (Reference:
``src/app/main.cc`` gflags + ``config/*.conf`` text protos [U].)  We keep the
same split and much of the field vocabulary, as plain dataclasses.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Tuple, Union


class ConsistencyMode(str, enum.Enum):
    """Consistency spectrum of the reference's Executor task DAG.

    BSP = depend on all prior iterations; ASP = no dependencies; SSP =
    bounded staleness of ``max_delay`` iterations.  (Reference:
    ``src/system/executor.h`` ``Task.time``/``wait_time`` semantics [U].)
    """

    BSP = "bsp"
    SSP = "ssp"
    ASP = "asp"


@dataclasses.dataclass(frozen=True)
class ConsistencyConfig:
    mode: ConsistencyMode = ConsistencyMode.BSP
    #: SSP staleness bound (the reference's ``max_delay`` flag); ignored for
    #: BSP (effectively 0) and ASP (effectively unbounded).
    max_delay: int = 0
    #: graceful-degradation deadline (ISSUE 20): when a wire-enforced gate
    #: (a ``__wait__`` defer loop) has held a request longer than this,
    #: pulls shed to the stale serving path (bounded by the advertised
    #: ``__sver__`` watermark) and pushes force through — never dropped.
    #: <= 0 disables shedding (wait forever; tests assert invariants with
    #: this).
    gate_deadline_s: float = 5.0
    #: base sleep between gate retries when the server's ``__wait__`` reply
    #: does not advertise its own ``retry_after`` hint.
    gate_retry_s: float = 0.005

    @property
    def bound(self) -> Optional[int]:
        """Staleness bound as an int, or None for unbounded (ASP)."""
        if self.mode == ConsistencyMode.BSP:
            return 0
        if self.mode == ConsistencyMode.SSP:
            return self.max_delay
        return None

    def __post_init__(self) -> None:
        if self.max_delay < 0:
            raise ValueError(
                f"max_delay must be >= 0, got {self.max_delay!r}"
            )
        if self.gate_retry_s <= 0:
            raise ValueError(
                f"gate_retry_s must be > 0, got {self.gate_retry_s!r}"
            )


@dataclasses.dataclass(frozen=True)
class TopologyConfig:
    """Process/device topology — the reference's gflags layer.

    On TPU the "servers" are shards of a device mesh axis rather than separate
    processes; ``num_servers`` becomes the number of table shards and
    ``num_workers`` the number of data-parallel worker slots.
    """

    num_workers: int = 1
    num_servers: int = 1
    #: mesh axis sizes (data, model); data axis carries DP gradient psum
    #: (the NCCL-pre-reduction replacement), model axis carries table shards.
    #: None = unset: apps pick their own default layout (e.g. sptp_lm puts
    #: all devices on sp).  An explicit shape — including (1, 1) — is
    #: validated against the available devices like any other.
    mesh_shape: Optional[Tuple[int, ...]] = None
    mesh_axis_names: Tuple[str, ...] = ("data", "model")


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Server-side update rule for a table.

    ``kind`` in {"sgd", "adagrad", "adam", "ftrl"}; FTRL mirrors the
    reference's KVMap FTRLEntry{z,n} lazy-weight scheme
    (``src/app/linear_method/ftrl*.h`` [U]).
    """

    kind: str = "adagrad"
    learning_rate: float = 0.1
    #: L1/L2 regularization (the reference's penalty protos).
    l1: float = 0.0
    l2: float = 0.0
    #: adagrad/adam epsilon; ftrl beta.
    eps: float = 1e-8
    beta1: float = 0.9
    beta2: float = 0.999
    #: ftrl alpha/beta per the FTRL-proximal paper parameterization.
    ftrl_alpha: float = 0.05
    ftrl_beta: float = 1.0


@dataclasses.dataclass(frozen=True)
class ApplyEngineConfig:
    """Server-side apply engine knobs (the bundle-batched push path).

    The engine turns a coalesced bundle of same-table PUSHes into (ideally)
    one donated-buffer device call instead of one per request.  How
    duplicate row ids ACROSS bundle members are handled is the semantic
    knob:

    - ``"rounds"`` (default): members are partitioned into occurrence
      rounds — round *k* applies the *k*-th contribution each row received,
      one device call per round.  Because the optimizer is row-wise, this
      is **bitwise-identical to sequential per-request apply for every
      optimizer**, duplicates included; with no cross-member duplicates it
      degenerates to exactly one call.
    - ``"combine"``: duplicate rows are pre-merged on device with
      ``segment_combine`` (the reference server's ParallelOrderedMatch
      merge) and applied once — always one device call.  This sums
      gradients before the update, the classic PS merge: identical to
      sequential when members touch disjoint rows, and the standard
      sum-semantics (not bitwise-sequential) when they overlap.
    """

    #: max same-table PUSHes concatenated into one batched device apply;
    #: <= 1 disables bundling (every request applies individually).
    apply_batch: int = 16
    #: cross-member duplicate-id policy: "rounds" | "combine" (see above).
    dup_policy: str = "rounds"


@dataclasses.dataclass(frozen=True)
class LedgerConfig:
    """Device-plane observability knobs (the server's ApplyLedger).

    PR 11 made PUSH acks sync-free, so the ack no longer observes the
    device apply at all — true apply latency, device queue depth, and the
    host-assembly/H2D/compute split became invisible.  The ledger
    (``kv/ledger.py``) registers every in-flight apply at dispatch and
    retires it from a background reaper thread once ``is_ready()`` — never
    from the ack path, so the sync-free contract holds.  Between
    completions the reaper blocks inside the runtime on the oldest
    in-flight result; ``reap_interval_s`` is only the degraded-mode poll
    cadence (donated-buffer races, ``drain``).

    Backlog bounds drive the soft-backpressure hint: when any configured
    bound is exceeded, the server stamps ``__busy__`` into push acks (the
    admission-control signal the serving plane consumes) and the
    ``apply.backlog`` flight-recorder event fires edge-triggered.  A bound
    of 0 disables that bound; all bounds 0 (the default) means the ledger
    observes but never hints.
    """

    enabled: bool = True
    #: reaper poll period; also bounds device-latency measurement error.
    reap_interval_s: float = 0.001
    #: reaper self-stops after this long with nothing in flight (restarted
    #: lazily on the next submit) — idle servers pay zero poll cost.
    idle_stop_s: float = 2.0
    #: backpressure bounds (0 = unbounded): in-flight device applies ...
    backlog_bundles: int = 0
    #: ... in-flight rows across those applies ...
    backlog_rows: int = 0
    #: ... and age of the oldest un-retired apply, in seconds.
    backlog_age_s: float = 0.0


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Read-heavy serving plane knobs (ISSUE 13).

    The serving plane layers three mechanisms over the training substrate:
    a worker-side hot-row cache invalidated by the piggybacked ``__sver__``
    segment version clock (``kv/cache.py``), a server-side read-only PULL
    fast path (``__ro__`` request flag), and SLO-driven admission control
    (``serve/admission.py``) consuming ``SloEngine.healthy()`` and the
    ledger's ``__busy__`` hints.
    """

    #: hot-row cache capacity, in rows per table (direct-mapped, rounded up
    #: to a power of two; collision-evicted); <= 0 disables caching.
    cache_rows: int = 65536
    #: what to do with read traffic while the plane is unhealthy (SLO breach
    #: or a live ``__busy__`` hint): "reject" answers immediately with a
    #: retry-after shed; "stale" serves watermark-invalid cache entries
    #: (bounded only by what the cache holds) and sheds uncached keys;
    #: "queue" waits up to ``queue_deadline_s`` for health, then sheds.
    policy: str = "reject"
    #: advisory client back-off carried by a reject shed, seconds.
    retry_after_s: float = 0.05
    #: max time a "queue" policy read waits for the plane to recover.
    queue_deadline_s: float = 0.5
    #: poll period while a "queue" policy read is parked.
    queue_poll_s: float = 0.005
    #: how recent a ``__busy__`` hint must be to count as live overload.
    busy_within_s: float = 1.0

    def __post_init__(self) -> None:
        if self.policy not in ("reject", "stale", "queue"):
            raise ValueError(
                f"serve policy must be reject|stale|queue, got {self.policy!r}"
            )


@dataclasses.dataclass(frozen=True)
class WireCompressionConfig:
    """Lossy wire codec for the DCN value plane (ISSUE 14).

    Selected per table (``TableConfig.compression``) and composed under
    ``CoalescingVan`` via :class:`~parameter_server_tpu.core.filters.
    QuantizingFilter` — one pass over the bundled value plane, PUSH
    requests only (PULL replies stay bit-exact so the serving plane's
    bitwise guarantees hold).

    ``error_feedback`` keeps a per-(sender, table, key) residual
    accumulator on the sender: the quantization error of each push is
    re-injected into the NEXT push for the same keys instead of lost —
    the EQuARX result (PAPERS.md) that makes lossy compression converge
    like the uncompressed run.  Residuals are dropped on ``adopt_routing``
    (new routing epoch), on a peer incarnation advance, and on a same-id
    restart, so a rebalanced or recovered fleet never replays stale error.

    ``per_row`` replaces ``FixingFloatFilter``'s old dim-based guess:
    ``True``/``False`` force per-row/per-tensor scales; ``"auto"`` keeps
    the measured heuristic (per-row only when the last dim is >= 16, since
    each row scale costs 4 header-borne bytes and would rival the int8
    payload of a dim-1 LR table).
    """

    #: wire codec: "none" (bit-exact), "int8", or "fp8".
    codec: str = "none"
    #: fp8 bit layout: "e4m3" (more mantissa) or "e5m2" (more range).
    fp8_format: str = "e4m3"
    #: "nearest" or "stochastic" (seeded from ``seed`` — deterministic).
    rounding: str = "nearest"
    #: carry quantization error forward per (sender, table, key).
    error_feedback: bool = True
    #: per-row scales: True | False | "auto" (the old dim heuristic).
    per_row: Union[bool, str] = "auto"
    #: stochastic-rounding rng seed (repo-wide seeded-replay contract).
    seed: int = 0

    def __post_init__(self) -> None:
        if self.codec not in ("none", "int8", "fp8"):
            raise ValueError(
                f"codec must be none|int8|fp8, got {self.codec!r}"
            )
        if self.fp8_format not in ("e4m3", "e5m2"):
            raise ValueError(
                f"fp8_format must be e4m3|e5m2, got {self.fp8_format!r}"
            )
        if self.rounding not in ("nearest", "stochastic"):
            raise ValueError(
                f"rounding must be nearest|stochastic, got {self.rounding!r}"
            )
        if not (self.per_row in (True, False) or self.per_row == "auto"):
            raise ValueError(
                f'per_row must be True, False, or "auto", got {self.per_row!r}'
            )


@dataclasses.dataclass(frozen=True)
class GroupConfig:
    """Hierarchical push: a worker group pre-reduces before the wire (ISSUE 15).

    Co-located workers (one host / one pod slice) sum their PUSH value
    planes locally — the MLPerf TPU-pod pattern (PAPERS.md,
    arXiv:1909.09756) of reducing over ICI before anything crosses DCN —
    and only one elected member pushes the reduced tensor, stamped
    (``kv/routing.py::GROUP_KEY``) so the server accounts it as ONE
    logical apply for the whole group.  Server inbound PUSH bytes and
    request count drop ~linearly in ``size``.

    ``election`` picks the pushing leg per ``(table, step)``:
    ``"rotate"`` (default) spreads wire load across members
    deterministically; ``"fixed"`` pins member 0 — required when the
    lossy wire codec's error-feedback residuals (ISSUE 14, keyed per
    ``(sender, table)``) should keep compressing group pushes: under
    rotation the residual owner would change every step, so group frames
    are stamped to BYPASS the codec instead (see
    ``core/filters.py::QuantizingFilter``).

    ``fallback`` is the degradation contract when the elected leader is
    dead or partitioned mid-step: ``"direct"`` (default) re-pushes the
    member's own gradient straight to the servers within the same step —
    no loss, at direct-push cost for that step; ``"none"`` raises instead
    (lockstep test topologies that must not mask a dead leader).

    ``reduce`` selects the pre-reduction path: ``"auto"`` uses an XLA
    ``psum`` when the members' contributions share one key set and enough
    local devices exist to map them (the shared-mesh case), else a
    deterministic host-side sorted-union merge (the loopback/multi-process
    topology); ``"merge"`` forces the host path; ``"psum"`` prefers the
    device path but still merges when key sets differ.
    """

    #: members per group (1 = grouping disabled).
    size: int = 1
    #: leader election per (table, step): "rotate" or "fixed".
    election: str = "rotate"
    #: leader-death degradation: "direct" (per-worker push) or "none".
    fallback: str = "direct"
    #: pre-reduction path: "auto", "psum", or "merge".
    reduce: str = "auto"
    #: seconds a member waits on the leader (contribution ack / done
    #: notify) before falling back; also the leader-side age at which an
    #: incomplete rendezvous set is flushed as a partial reduction.
    fallback_timeout: float = 0.25

    def __post_init__(self) -> None:
        if self.size < 1:
            raise ValueError(f"size must be >= 1, got {self.size!r}")
        if self.election not in ("rotate", "fixed"):
            raise ValueError(
                f"election must be rotate|fixed, got {self.election!r}"
            )
        if self.fallback not in ("direct", "none"):
            raise ValueError(
                f"fallback must be direct|none, got {self.fallback!r}"
            )
        if self.reduce not in ("auto", "psum", "merge"):
            raise ValueError(
                f"reduce must be auto|psum|merge, got {self.reduce!r}"
            )
        if self.fallback_timeout <= 0:
            raise ValueError(
                f"fallback_timeout must be > 0, got {self.fallback_timeout!r}"
            )


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    """Durability plane knobs (ISSUE 16): partitioned snapshot cadence.

    The partitioned snapshot path (``KVWorker.save_snapshot`` +
    ``checkpoint.finalize_snapshot``) snapshots ANY routing layout — one
    file per segment, an incremental carry when a segment's ``__sver__``
    clock has not advanced, and a dirty-row delta log that bounds the
    commit freeze.  This config feeds the ElasticTrainer's checkpoint loop
    and the ``durability_plane_specs`` SLO (``ckpt_age_s`` breaches when
    the last durable manifest is older than ``interval_s``).
    """

    #: target wall-clock seconds between durable manifests; the
    #: ``ckpt-age`` SLO breach threshold derives from it.
    interval_s: float = 60.0
    #: soft bound on the dirty-row delta a snapshot commit may export in
    #: its freeze window; a commit over the bound still lands (durability
    #: beats latency) but flags ``over_bound`` on its ``ckpt.commit``
    #: event and bumps the ``ckpt_delta_overflow`` counter.
    max_delta_rows: int = 65536
    #: snapshots kept by ``checkpoint.retain_snapshots`` (chain bases that
    #: kept manifests still reference are preserved regardless).
    retention: int = 3
    #: "auto" = legacy uniform shards while the layout allows them, the
    #: partitioned path once the fleet has rebalanced (or a snapshot chain
    #: exists to extend); "partitioned"/"legacy" force one path.
    mode: str = "auto"

    def __post_init__(self) -> None:
        if self.interval_s <= 0:
            raise ValueError(
                f"interval_s must be > 0, got {self.interval_s!r}"
            )
        if self.max_delta_rows < 1:
            raise ValueError(
                f"max_delta_rows must be >= 1, got {self.max_delta_rows!r}"
            )
        if self.retention < 0:
            raise ValueError(
                f"retention must be >= 0, got {self.retention!r}"
            )
        if self.mode not in ("auto", "legacy", "partitioned"):
            raise ValueError(
                f"mode must be auto|legacy|partitioned, got {self.mode!r}"
            )


@dataclasses.dataclass(frozen=True)
class TransportConfig:
    """Transport v2 knobs (ISSUE 17): wire backend + colocated shm rings.

    ``TcpVan`` consumes this; both knobs also answer to env overrides
    (``PS_WIRE=epoll|threaded``, ``PS_NO_SHM=1``) so tests and rollouts can
    flip backends without plumbing a config through every constructor.
    """

    #: native wire backend: "epoll" (one event-loop thread multiplexing all
    #: connections, vectored writev sends, bounded write queues —
    #: ``native/src/epollvan.cc``) or "threaded" (the PR 6 thread-per-
    #: connection core, ``native/src/tcpvan.cc``).  "epoll" quietly falls
    #: back to "threaded" when the epoll backend fails to build.
    wire: str = "epoll"
    #: negotiate shared-memory rings for colocated links (same boot id):
    #: frames bypass TCP via ``core/shm_ring.py``; any doubt (ring full,
    #: peer dead, old peer that never acks) degrades per-frame to TCP.
    shm: bool = True
    #: per-direction ring capacity in bytes.
    ring_capacity: int = 4 << 20
    #: how long a sender waits for ring space before falling back to TCP
    #: for that frame (counted in ``ring_full``).
    ring_wait_s: float = 0.0005

    def __post_init__(self) -> None:
        if self.wire not in ("epoll", "threaded"):
            raise ValueError(f"wire must be epoll|threaded, got {self.wire!r}")
        if self.ring_capacity < 4096:
            raise ValueError(
                f"ring_capacity must be >= 4096, got {self.ring_capacity!r}"
            )
        if self.ring_wait_s < 0:
            raise ValueError(
                f"ring_wait_s must be >= 0, got {self.ring_wait_s!r}"
            )


@dataclasses.dataclass(frozen=True)
class TableConfig:
    """A KV table: the unit the reference range-partitions across servers.

    (Reference: ``src/system/assigner.h`` NodeAssigner key-range split +
    ``src/parameter/kv_vector.h`` per-channel value arrays [U].)
    """

    name: str
    #: number of rows (vocabulary / feature capacity). Sparse tables index
    #: rows by localized key; dense tensors flatten to rows of ``dim``.
    rows: int
    #: value columns per key (the reference's ``k``-column KVVector).
    dim: int = 1
    dtype: str = "float32"
    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    #: stddev of normal init for value rows; 0.0 = zeros (LR weights).
    init_scale: float = 0.0
    #: if True the table is sharded over the mesh "model" axis (row-wise,
    #: contiguous ranges — the NodeAssigner scheme); if False it is replicated.
    sharded: bool = True
    #: row gather/scatter kernel on the Push/Pull hot path: "auto"/"xla"
    #: (take / at[].set — measured at the HBM roofline on v5e, the default
    #: verdict of bench.py --micro), or "pallas" (DMA kernels,
    #: ops/scatter.py — interpreter-run off TPU so tests exercise the same
    #: code path; dim == 128 or dim % 1024 == 0).
    scatter_impl: str = "auto"
    #: fused push apply: gather → optimizer step → scatter as ONE pass
    #: (``ops.scatter.apply_rows``).  Under ``scatter_impl="pallas"`` this
    #: is a single DMA kernel (one HBM row round-trip instead of three
    #: kernel groups); under XLA it traces the op-for-op identical graph as
    #: the legacy three-pass body, so flipping it is bitwise-neutral there.
    fused_apply: bool = True
    #: lossy wire codec for this table's PUSH plane; None = bit-exact wire.
    compression: Optional[WireCompressionConfig] = None
    #: wire-enforced consistency plane (ISSUE 20): when set, workers stamp
    #: their committed step (``__cstep__``) on this table's PUSH/PULL
    #: requests and servers gate them against the fleet's per-worker vector
    #: clock — block-the-laggard (SSP), rendezvous-barrier (BSP) or
    #: free-run (ASP).  None = ungated (the pre-ISSUE-20 wire, zero extra
    #: payload bytes).
    consistency: Optional[ConsistencyConfig] = None


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """Sampled end-to-end request tracing (ISSUE 18).

    ``KVWorker`` consumes this to decide whether a PUSH/PULL submit stamps
    a trace context (``core/tracectx.py``) into its payload.  Sampling is
    a deterministic hash of ``(trace_id, seed)`` so seeded replays trace
    the same requests and unsampled requests carry zero trace bytes on
    the wire.
    """

    #: master switch; False stamps no contexts at all (the predicate the
    #: hot path is gated behind — see tools/check_wrappers.py).
    enabled: bool = True
    #: trace 1-in-N requests.  1 = every request (tests), 0 = never;
    #: 1024 is the default the bench gate holds to ≤3% overhead.
    sample_every: int = 1024
    #: seed folded into the sampling hash; replays with the same seed
    #: sample the same trace ids.
    seed: int = 0

    def __post_init__(self) -> None:
        if self.sample_every < 0:
            raise ValueError(
                f"sample_every must be >= 0, got {self.sample_every!r}"
            )


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Scheduler-side telemetry aggregator sizing (ISSUE 19).

    The aggregator keeps a bounded ring of derived rows per publishing
    node.  A fixed per-node window tuned for ~4 nodes does not survive a
    200-publisher war game: 256 rows x 200 nodes is ~50k retained rows on
    the control plane.  Instead the per-node ring capacity is derived from
    a FLEET-WIDE row budget — ``min(window, ring_budget_rows // fleet)``,
    floored at ``min_window`` — and re-derived (rings re-capped in place)
    as new publishers appear, so total retained rows stay near the budget
    at any fleet size while small fleets keep the full ``window``.
    """

    #: per-node ring rows for small fleets (the pre-ISSUE-19 constant).
    window: int = 256
    #: fleet-wide retained-row budget; per-node capacity shrinks as the
    #: publisher count grows so the scheduler's memory stays flat.
    ring_budget_rows: int = 8192
    #: per-node capacity floor — even a 1000-node fleet keeps enough rows
    #: per node for rate windows and pstop history.
    min_window: int = 8

    def __post_init__(self) -> None:
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window!r}")
        if self.min_window < 1:
            raise ValueError(
                f"min_window must be >= 1, got {self.min_window!r}"
            )
        if self.min_window > self.window:
            raise ValueError(
                f"min_window ({self.min_window!r}) must be <= window "
                f"({self.window!r})"
            )
        if self.ring_budget_rows < self.window:
            raise ValueError(
                f"ring_budget_rows ({self.ring_budget_rows!r}) must be >= "
                f"window ({self.window!r})"
            )

    def node_window(self, fleet_size: int) -> int:
        """Per-node ring capacity for ``fleet_size`` publishers."""
        n = max(1, int(fleet_size))
        return max(self.min_window, min(self.window, self.ring_budget_rows // n))
