"""Declarative SLOs over the observability plane's existing series.

ROADMAP's read-heavy serving plane needs breach detection before admission
control / load shedding can land; this module is that substrate.  An
:class:`SloSpec` names a metric, a ceiling, and how to read the samples
(instantaneous gauge, windowed rate of a cumulative counter, or windowed
p99 of a cumulative :class:`~parameter_server_tpu.utils.trace.LatencyHistogram`
digest); an :class:`SloEngine` holds per-(node, metric) rolling windows fed
from the series the plane already produces — FleetMonitor snapshot rows,
``transport_counters`` dicts, MeteredVan per-link digests — and turns them
into per-node health verdicts.

Breaches are edge-triggered into the flight recorder: ``slo.breach`` when a
spec first exceeds its ceiling on a node, ``slo.clear`` when it recovers —
so the postmortem timeline shows WHEN health flipped, not a line per sweep.
The verdict objects themselves are level-triggered (current truth), which
is what an admission controller will poll.

Examples::

    specs = [
        SloSpec("inbound-p99", "push_p99_ms", 50.0),            # gauge
        SloSpec("retransmit-rate", "retransmits", 10.0,
                source="rate", window_s=5.0),                    # per-second
        SloSpec("bytes-per-step", "wire_bytes_per_step", 2e6),   # gauge
    ]
    eng = SloEngine(specs)
    eng.ingest_fleet(fleet)               # each monitor sweep
    eng.ingest_counters("S1", transport_counters(van))
    verdicts = eng.evaluate()             # {node: SloVerdict}
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Deque, Dict, List, Optional, Tuple

from parameter_server_tpu.core import flightrec
from parameter_server_tpu.utils.trace import LatencyHistogram

_SOURCES = ("gauge", "rate", "p99")


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One service-level objective: ``metric`` must stay <= ``max_value``.

    ``source`` picks the sample semantics:

    - ``"gauge"``: latest observed value inside the window (snapshot rows
      like ``push_p99_ms`` are already derived — compare directly);
    - ``"rate"``: (last - first) / elapsed over the window, for CUMULATIVE
      counters (``retransmits``, ``wire_bytes``) — ``max_value`` is per
      second;
    - ``"p99"``: windowed p99 of a cumulative LatencyHistogram digest
      series, scaled by ``p99_scale`` — the window's delta histogram is
      reconstructed by differencing bucket counts, so the p99 covers only
      samples recorded inside the window, not the whole run.

    ``p99_scale`` converts the histogram's native seconds axis into the
    units ``max_value`` is written in: the default ``1e3`` reads latency
    digests in milliseconds; unitless series that reuse the axis as a raw
    count — the staleness version-lag digests of ISSUE 10 — pass ``1.0``
    ("p99 staleness <= 8 versions" is ``SloSpec("stale", "staleness.w",
    8.0, source="p99", p99_scale=1.0)``).
    """

    name: str
    metric: str
    max_value: float
    source: str = "gauge"
    window_s: float = 10.0
    min_samples: int = 1
    p99_scale: float = 1e3

    def __post_init__(self) -> None:
        if self.source not in _SOURCES:
            raise ValueError(
                f"SloSpec {self.name!r}: source must be one of {_SOURCES}, "
                f"got {self.source!r}"
            )
        if self.window_s <= 0:
            raise ValueError(f"SloSpec {self.name!r}: window_s must be > 0")
        if self.p99_scale <= 0:
            raise ValueError(f"SloSpec {self.name!r}: p99_scale must be > 0")


@dataclasses.dataclass
class SloVerdict:
    """Per-node health verdict from one :meth:`SloEngine.evaluate` sweep."""

    node: str
    healthy: bool
    #: spec name -> (observed value, ceiling) for every breached spec.
    breaches: Dict[str, Tuple[float, float]]
    #: spec name -> observed value for every spec that had enough samples.
    observed: Dict[str, float]


class SloEngine:
    """Rolling-window evaluator for a set of :class:`SloSpec` objects.

    Feed it with any mix of :meth:`observe` (raw samples),
    :meth:`ingest_fleet` (FleetMonitor snapshot rows + per-link deliver
    digests), and :meth:`ingest_counters` (cumulative counter dicts);
    :meth:`evaluate` computes windowed values per node and edge-triggers
    ``slo.breach`` / ``slo.clear`` flight-recorder events on transitions.
    """

    def __init__(
        self,
        specs: List[SloSpec],
        *,
        recorder: Optional[flightrec.FlightRecorder] = None,
    ) -> None:
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SloSpec names: {sorted(names)}")
        self.specs = list(specs)
        self._recorder = recorder
        #: metrics any spec reads — bulk ingest skips everything else.
        self._spec_metrics = frozenset(s.metric for s in self.specs)
        #: widest window any spec holds over a metric: samples older than
        #: this are dead to EVERY spec, so ``_windowed`` may expire them
        #: from the series for good (amortized O(1) per sample) instead of
        #: re-scanning past them on every evaluation.
        self._max_window: Dict[str, float] = {}
        for s in self.specs:
            self._max_window[s.metric] = max(
                self._max_window.get(s.metric, 0.0), s.window_s
            )
        #: (node, metric) -> deque of (t, value-or-digest-dict) samples.
        self._series: Dict[Tuple[str, str], Deque[Tuple[float, object]]] = {}
        #: series keys that ever saw an out-of-order sample; only these pay
        #: a sort in ``_windowed`` — the common in-order path appends are
        #: already time-sorted.
        self._unsorted: set = set()
        self._last_obs_t: Dict[Tuple[str, str], float] = {}
        #: (spec name, node) -> currently breached?  (edge-trigger state)
        self._breached: Dict[Tuple[str, str], bool] = {}
        self._nodes: set = set()
        #: high-water mark of evaluate's ``now`` — late re-evaluations are
        #: clamped forward so an out-of-order caller cannot shrink the
        #: window backwards and retro-flip an edge-triggered breach.
        self._last_now: Optional[float] = None
        #: (spec name, node) -> engine time the CURRENT breach opened at.
        self._breach_open: Dict[Tuple[str, str], float] = {}
        #: (spec name, node) -> accumulated seconds over CLOSED breaches.
        self._breach_acc_s: Dict[Tuple[str, str], float] = {}
        #: closed breach intervals, in close order:
        #: {"slo", "node", "t0", "t1"} — the scorecard's breach timeline.
        self._breach_log: List[dict] = []

    # -- ingest --------------------------------------------------------------
    def observe(
        self, node: str, metric: str, value, now: Optional[float] = None
    ) -> None:
        """Record one sample.  ``value`` is a number for gauge/rate metrics
        or a LatencyHistogram digest dict (``to_dict`` form) for p99 ones."""
        now = time.monotonic() if now is None else now
        self._nodes.add(node)
        key = (node, metric)
        dq = self._series.get(key)
        if dq is None:
            dq = self._series[key] = collections.deque(maxlen=1024)
        last = self._last_obs_t.get(key)
        if last is not None and now < last:
            self._unsorted.add(key)
        else:
            self._last_obs_t[key] = now
        dq.append((now, value))

    def ingest_fleet(self, fleet, now: Optional[float] = None) -> None:
        """Sample every numeric field of each FleetMonitor snapshot row,
        plus each node's cumulative inbound deliver digest (for ``p99``
        specs over ``inbound_deliver``)."""
        now = time.monotonic() if now is None else now
        for node, row in fleet.snapshot(now).items():
            self._nodes.add(node)  # verdict coverage even with no spec metric
            for metric, value in row.items():
                if metric in self._spec_metrics and isinstance(value, (int, float)):
                    self.observe(node, metric, float(value), now)
        wants_inbound = any(
            s.source == "p99" and s.metric == "inbound_deliver"
            for s in self.specs
        )
        if wants_inbound:
            with fleet._lock:
                links = dict(fleet._links)
            for node in fleet.nodes():
                h = fleet._inbound_hist(links, node)
                if h.count:
                    self.observe(node, "inbound_deliver", h.to_dict(), now)

    def ingest_counters(
        self, node: str, counters: dict, now: Optional[float] = None
    ) -> None:
        """Sample a cumulative counter dict (``transport_counters`` output,
        a server's ``counters()``) for ``rate`` and ``gauge`` specs.  Only
        metrics some spec actually reads are retained — the telemetry plane
        calls this once per frame with dozens of transport counters."""
        now = time.monotonic() if now is None else now
        self._nodes.add(node)  # verdict coverage even with no spec metric
        for metric, value in counters.items():
            if metric in self._spec_metrics and isinstance(value, (int, float)):
                self.observe(node, metric, float(value), now)

    # -- evaluation ----------------------------------------------------------
    def _windowed(
        self, spec: SloSpec, node: str, now: float
    ) -> Optional[float]:
        """Current value of ``spec`` on ``node``, or None without enough
        in-window samples."""
        dq = self._series.get((node, spec.metric))
        if not dq:
            return None
        cutoff = now - spec.window_s
        # order by sample time, not append order: the live telemetry plane
        # delivers frames out of order (ISSUE 10), and a LATE old sample
        # must not masquerade as the window's latest gauge / rate endpoint.
        # A series that only ever appended in order is already time-sorted;
        # only series flagged by ``observe`` pay a filter + sort.
        if (node, spec.metric) in self._unsorted:
            window: object = sorted(
                (s for s in dq if s[0] >= cutoff), key=lambda s: s[0]
            )
        else:
            # time-sorted series: expire samples no spec can ever read
            # again (evaluate's ``now`` only moves forward, so neither can
            # any cutoff) — each sample is popped at most once across the
            # engine's whole lifetime instead of re-scanned every sweep
            expire = now - self._max_window[spec.metric]
            while dq and dq[0][0] < expire:
                dq.popleft()
            if not dq:
                return None
            if dq[0][0] >= cutoff:
                window = dq  # everything in window — evaluate in place
            else:
                window = [s for s in dq if s[0] >= cutoff]
        if len(window) < spec.min_samples:
            return None
        if spec.source == "gauge":
            return float(window[-1][1])
        if spec.source == "rate":
            if len(window) < 2:
                return None
            (t0, v0), (t1, v1) = window[0], window[-1]
            if t1 <= t0:
                return None
            return (float(v1) - float(v0)) / (t1 - t0)
        # p99 over the window's delta histogram
        if len(window) < 2:
            return None
        first, last = window[0][1], window[-1][1]
        delta = _delta_hist(first, last)
        if delta.count < spec.min_samples:
            return None
        return spec.p99_scale * delta.percentile(0.99)

    def evaluate(
        self,
        now: Optional[float] = None,
        nodes: Optional[List[str]] = None,
    ) -> Dict[str, SloVerdict]:
        """Per-node verdicts; edge-triggers breach/clear recorder events.

        ``now`` only moves forward: an evaluation stamped EARLIER than a
        previous one (a late telemetry frame re-triggering the sweep) is
        evaluated at the high-water clock, so an already-fired breach edge
        cannot retro-flip on stale time.

        ``nodes`` restricts the sweep to a subset (the 200-publisher
        war-game aggregator evaluates only the frame's sender per ingest
        — O(specs) instead of O(fleet x specs) — and runs one full-fleet
        sweep per runner tick).  Edge/interval state for unlisted nodes
        is untouched.
        """
        now = time.monotonic() if now is None else now
        if self._last_now is not None and now < self._last_now:
            now = self._last_now
        self._last_now = now
        sweep = (
            sorted(self._nodes) if nodes is None
            else sorted(self._nodes.intersection(nodes))
        )
        # explicit None test: an EMPTY FlightRecorder is falsy (__len__ == 0),
        # and the first breach is exactly when the injected recorder is empty
        rec = (
            flightrec.record if self._recorder is None
            else self._recorder.record
        )
        out: Dict[str, SloVerdict] = {}
        for node in sweep:
            breaches: Dict[str, Tuple[float, float]] = {}
            observed: Dict[str, float] = {}
            for spec in self.specs:
                value = self._windowed(spec, node, now)
                if value is None:
                    continue
                observed[spec.name] = value
                key = (spec.name, node)
                was = self._breached.get(key, False)
                is_breach = value > spec.max_value
                if is_breach:
                    breaches[spec.name] = (value, spec.max_value)
                if is_breach and not was:
                    # interval accounting opens on the same clamped clock
                    # the edge fired at, so out-of-order re-evaluations can
                    # neither reopen a closed interval nor shrink this one.
                    self._breach_open[key] = now
                    rec(
                        "slo.breach",
                        node=node,
                        slo=spec.name,
                        metric=spec.metric,
                        value=round(value, 4),
                        limit=spec.max_value,
                    )
                elif was and not is_breach:
                    t0 = self._breach_open.pop(key, now)
                    dur = max(now - t0, 0.0)
                    self._breach_acc_s[key] = (
                        self._breach_acc_s.get(key, 0.0) + dur
                    )
                    self._breach_log.append(
                        {"slo": spec.name, "node": node, "t0": t0, "t1": now}
                    )
                    rec(
                        "slo.clear",
                        node=node,
                        slo=spec.name,
                        metric=spec.metric,
                        value=round(value, 4),
                        limit=spec.max_value,
                    )
                self._breached[key] = is_breach
            out[node] = SloVerdict(
                node=node,
                healthy=not breaches,
                breaches=breaches,
                observed=observed,
            )
        return out

    def healthy(self, node: str) -> bool:
        """Level-triggered health of one node per the LAST evaluate sweep —
        the poll the future serving plane's admission control consumes."""
        return not any(
            breached and name_node[1] == node
            for name_node, breached in self._breached.items()
        )

    # -- breach-interval accounting ------------------------------------------
    def breach_seconds(
        self,
        *,
        node: Optional[str] = None,
        spec: Optional[str] = None,
        now: Optional[float] = None,
    ) -> float:
        """Total breached seconds, integrated from the edge-trigger stream.

        Sums every CLOSED breach interval plus the open tail of any breach
        still in flight, measured to ``now`` (clamped to the evaluate
        high-water clock — the same forward-only time the edges fired on,
        so a stale caller clock cannot shrink an open interval).  Filter by
        ``node`` and/or ``spec`` name; divide by 60 for the scorecard's
        SLO-breach-minutes.
        """
        if now is None:
            now = self._last_now if self._last_now is not None else 0.0
        elif self._last_now is not None and now < self._last_now:
            now = self._last_now
        total = 0.0
        for (sname, n), acc in self._breach_acc_s.items():
            if (node is None or n == node) and (spec is None or sname == spec):
                total += acc
        for (sname, n), t0 in self._breach_open.items():
            if (node is None or n == node) and (spec is None or sname == spec):
                total += max(now - t0, 0.0)
        return total

    def breach_timeline(self, now: Optional[float] = None) -> List[dict]:
        """Every breach interval — closed ones verbatim, open ones extended
        to ``now`` (high-water clamped) with ``"open": True`` — sorted by
        start time.  This is the per-node × per-SLO timeline the war-game
        scorecard integrates."""
        if now is None:
            now = self._last_now if self._last_now is not None else 0.0
        elif self._last_now is not None and now < self._last_now:
            now = self._last_now
        out = [dict(iv) for iv in self._breach_log]
        for (sname, n), t0 in self._breach_open.items():
            out.append(
                {"slo": sname, "node": n, "t0": t0,
                 "t1": max(now, t0), "open": True}
            )
        out.sort(key=lambda iv: (iv["t0"], iv["node"], iv["slo"]))
        return out


def device_plane_specs(
    table: str = "w",
    *,
    apply_p99_ms: float = 250.0,
    backlog_bundles: int = 8,
    window_s: float = 10.0,
) -> List[SloSpec]:
    """The ISSUE-12 device-plane SLO pair, wired to the ApplyLedger series.

    - ``apply-p99``: windowed p99 of the ``apply.<table>`` total-latency
      digest (submit -> retire, milliseconds) the ledger publishes through
      the telemetry ``digests`` channel;
    - ``apply-backlog``: the ``inflight_bundles`` gauge riding the server's
      ``counters()`` — the canonical async-PS overload signal.  Breaching
      it flips ``SloEngine.healthy(node)``, the same signal the server's
      soft-backpressure ``__busy__`` hint mirrors locally.
    """
    return [
        SloSpec(
            "apply-p99",
            f"apply.{table}",
            apply_p99_ms,
            source="p99",
            window_s=window_s,
        ),
        SloSpec(
            "apply-backlog",
            "inflight_bundles",
            float(backlog_bundles),
            source="gauge",
            window_s=window_s,
        ),
    ]


def serving_plane_specs(
    table: str = "w",
    *,
    ro_p99_ms: float = 50.0,
    backlog_bundles: int = 8,
    window_s: float = 10.0,
) -> List[SloSpec]:
    """The ISSUE-13 serving-plane SLO pair, for admission control.

    - ``ro-p99``: windowed p99 of the ``ro_pull.<table>`` digest — the
      server-side latency of the read-only fast path, published through
      the same telemetry ``digests`` channel as the apply digests;
    - ``apply-backlog``: the ``inflight_bundles`` gauge again.  Serving
      shares the device with training, so write backlog IS a serving
      overload signal: breaching either flips ``SloEngine.healthy(node)``
      false and the :class:`~parameter_server_tpu.serve.admission.
      AdmissionController` starts shedding within one telemetry beat.
    """
    return [
        SloSpec(
            "ro-p99",
            f"ro_pull.{table}",
            ro_p99_ms,
            source="p99",
            window_s=window_s,
        ),
        SloSpec(
            "apply-backlog",
            "inflight_bundles",
            float(backlog_bundles),
            source="gauge",
            window_s=window_s,
        ),
    ]


def compression_plane_specs(
    *,
    max_ratio_pct: float = 50.0,
    max_residual_norm: float = 1e4,
    window_s: float = 10.0,
) -> List[SloSpec]:
    """The ISSUE-14 quantized-wire-plane SLO pair.

    Both metrics ride the telemetry counter channel (the QuantizingFilter's
    ``counters()`` merged through ``CoalescingVan`` / ``transport_counters``),
    so ``SloEngine.ingest_counters`` picks them up with no new plumbing:

    - ``compress-ratio``: ``compress_ratio_pct`` gauge (compressed bytes as
      a percentage of raw) — breaching the ceiling means the codec stopped
      earning its keep (e.g. per-row scales inflating a narrow table);
    - ``compress-residual``: ``compress_residual_norm`` gauge, the L2 norm
      of outstanding error-feedback debt.  A norm that grows without bound
      means carried error is diverging (keys pushed once and never again),
      which quietly degrades convergence long before loss curves show it.
    """
    return [
        SloSpec(
            "compress-ratio",
            "compress_ratio_pct",
            max_ratio_pct,
            source="gauge",
            window_s=window_s,
        ),
        SloSpec(
            "compress-residual",
            "compress_residual_norm",
            max_residual_norm,
            source="gauge",
            window_s=window_s,
        ),
    ]


def durability_plane_specs(
    *,
    max_age_s: float = 120.0,
    window_s: float = 10.0,
) -> List[SloSpec]:
    """The ISSUE-16 durability-plane SLO.

    ``ckpt-age`` watches the server's ``ckpt_age_s`` gauge — seconds since
    the shard last committed to (or restored from) a durable snapshot.  The
    gauge's basis is stamped at server construction, so a fleet that NEVER
    snapshots breaches once ``max_age_s`` elapses: silence is a failure
    mode here, not a healthy default.  Breaching bounds the restore rewind
    (work since the last snapshot) — tighten the checkpoint interval or
    investigate why commits stopped (driver wedged, disk full, snapshots
    aborted by a routing churn loop).
    """
    return [
        SloSpec(
            "ckpt-age",
            "ckpt_age_s",
            max_age_s,
            source="gauge",
            window_s=window_s,
        ),
    ]


def tracing_plane_specs(
    *,
    wire_p99_ms: float = 5.0,
    window_s: float = 10.0,
) -> List[SloSpec]:
    """The ISSUE-18 tracing-plane SLO.

    ``trace-wire-p99`` watches the windowed p99 of the ``trace.wire``
    digest — worker submit stamp -> van receive for sampled requests,
    the only direct cross-node wire-transit measurement the fleet has
    (everything else folds queueing and apply in).  The server publishes
    it through ``latency_digests()`` like every other latency series, so
    the engine needs no new plumbing.  The digest only populates on real
    wire transports (TCP/epoll or the shm ring; loopback never stamps a
    receive), so in-process clusters simply report insufficient samples
    rather than a vacuous pass/fail.  Breaching means the network plane
    itself — not server queueing, not the device — is eating the request
    budget: look at retransmits, backpressure instants (``net.*``), or
    ``tools/critpath.py`` for the full per-plane split.
    """
    return [
        SloSpec(
            "trace-wire-p99",
            "trace.wire",
            wire_p99_ms,
            source="p99",
            window_s=window_s,
        ),
    ]


def consistency_plane_specs(
    *,
    gate_wait_p99_ms: float = 250.0,
    shed_per_s: float = 1.0,
    window_s: float = 10.0,
) -> List[SloSpec]:
    """The ISSUE-20 consistency-plane SLO pair.

    - ``gate-wait-p99``: windowed p99 of the worker's ``consist.gate_wait``
      digest — wall time a gated pull/push spent parked on ``__wait__``
      replies before the server admitted it.  Breaching means the wire
      (the staleness bound), not the device, is the fleet's bottleneck —
      the exact signal :class:`~parameter_server_tpu.kv.consistency.
      BoundTuner` consumes as its ``wire_bottleneck`` verdict to WIDEN
      the SSP bound.
    - ``shed-rate``: per-second rate of the worker's cumulative
      ``consist_degraded`` counter (stale-cache sheds + forced ungated
      requests).  Degradation is deliberate — bounded by the advertised
      ``__sver__`` watermark and flight-recorded — but a sustained rate
      means the gate deadline is doing the consistency plane's job, i.e.
      the configured mode is not actually being enforced.
    """
    return [
        SloSpec(
            "gate-wait-p99",
            "consist.gate_wait",
            gate_wait_p99_ms,
            source="p99",
            window_s=window_s,
        ),
        SloSpec(
            "shed-rate",
            "consist_degraded",
            shed_per_s,
            source="rate",
            window_s=window_s,
        ),
    ]


def _delta_hist(first: dict, last: dict) -> LatencyHistogram:
    """Histogram of the samples recorded BETWEEN two cumulative digests.

    Differences sparse bucket counts; count/sum difference likewise.  A
    negative difference (recorder reset between samples) falls back to the
    later digest alone rather than inventing negative mass.
    """
    h_last = LatencyHistogram.from_dict(last)
    h_first = LatencyHistogram.from_dict(first)
    if h_last.count < h_first.count:
        return h_last
    delta = LatencyHistogram()
    for i in range(delta.NBUCKETS):
        delta.counts[i] = h_last.counts[i] - h_first.counts[i]
        if delta.counts[i] < 0:
            return h_last
    delta.count = h_last.count - h_first.count
    delta.sum_s = max(h_last.sum_s - h_first.sum_s, 0.0)
    delta.max_s = h_last.max_s  # upper bound: exact window max not tracked
    return delta
