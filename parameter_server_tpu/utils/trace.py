"""Host-side tracing: spans, latency histograms, chrome-trace export.

SURVEY.md §5 tracing plan: the reference has only ad-hoc timing macros and
``/proc`` polling (``util/resource_usage.h``, ``system/network_usage.h``
[U]); the rebuild gets a real tracer — Push/Pull latency histograms on the
host path, exportable timelines, and a ``jax.profiler`` hook for the device
side (TensorBoard traces with ICI utilization).

Design: recording a span is two ``perf_counter`` calls and one deque append
under a lock (~1 microsecond) so the tracer can stay on in production; the
module-level :data:`NULL_TRACER` short-circuits to nothing for hot loops
that want zero overhead.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

#: one recorded span: (name, start_s, duration_s, thread_id, attrs)
Span = Tuple[str, float, float, int, Optional[dict]]


class Tracer:
    """Thread-safe span recorder with bounded memory."""

    def __init__(self, *, capacity: int = 100_000, enabled: bool = True) -> None:
        self.enabled = enabled
        self._spans: collections.deque[Span] = collections.deque(maxlen=capacity)
        #: O(1)-maintained per-name duration sums — unlike the bounded span
        #: deque these never drop history, so dashboards can poll cheap
        #: cumulative attribution without scanning (Dashboard.attribution).
        self._totals: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        start = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - start
            with self._lock:
                self._spans.append(
                    (name, start - self._t0, dur, threading.get_ident(),
                     attrs or None)
                )
                self._totals[name] = self._totals.get(name, 0.0) + dur

    def record(self, name: str, duration_s: float, **attrs) -> None:
        """Record an externally timed span (e.g. from a callback)."""
        if not self.enabled:
            return
        with self._lock:
            self._spans.append(
                (name, time.perf_counter() - self._t0 - duration_s,
                 duration_s, threading.get_ident(), attrs or None)
            )
            self._totals[name] = self._totals.get(name, 0.0) + duration_s

    def totals(self) -> Dict[str, float]:
        """Cumulative seconds per span name (O(names), never drops spans)."""
        with self._lock:
            return dict(self._totals)

    def spans(self, name: Optional[str] = None) -> List[Span]:
        with self._lock:
            out = list(self._spans)
        return out if name is None else [s for s in out if s[0] == name]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._totals.clear()

    # -- aggregation ---------------------------------------------------------
    def histogram(self, name: str) -> dict:
        """Latency stats for one span name (the Push/Pull histogram)."""
        durs = sorted(s[2] for s in self.spans(name))
        if not durs:
            return {"name": name, "count": 0}
        n = len(durs)

        def pct(p: float) -> float:
            return durs[min(n - 1, int(p * n))]

        return {
            "name": name,
            "count": n,
            "total_s": sum(durs),
            "mean_us": 1e6 * sum(durs) / n,
            "p50_us": 1e6 * pct(0.50),
            "p90_us": 1e6 * pct(0.90),
            "p99_us": 1e6 * pct(0.99),
            "max_us": 1e6 * durs[-1],
        }

    def summary(self) -> Dict[str, dict]:
        """Histogram per distinct span name."""
        return {name: self.histogram(name) for name in
                sorted({s[0] for s in self.spans()})}

    # -- export --------------------------------------------------------------
    def dump_chrome_trace(self, path: str) -> None:
        """Write the spans as a chrome://tracing / Perfetto JSON timeline."""
        events = [
            {
                "name": name,
                "ph": "X",
                "ts": start * 1e6,
                "dur": dur * 1e6,
                "pid": os.getpid(),
                "tid": tid,
                **({"args": attrs} if attrs else {}),
            }
            for name, start, dur, tid, attrs in self.spans()
        ]
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)

    def dump_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            for name, start, dur, tid, attrs in self.spans():
                f.write(
                    json.dumps(
                        {"name": name, "start_s": start, "dur_s": dur,
                         "tid": tid, "attrs": attrs}
                    )
                    + "\n"
                )


#: shared do-nothing tracer for hot paths with tracing off
NULL_TRACER = Tracer(enabled=False)


@contextlib.contextmanager
def jax_profile(logdir: str) -> Iterator[None]:
    """Device-side profile: wraps ``jax.profiler.trace`` (TensorBoard).

    The host Tracer covers Van/host latency; this captures the XLA timeline
    (HBM traffic, ICI collectives) for the same window.
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def resource_usage() -> dict:
    """Process CPU/memory snapshot (reference ``util/resource_usage.h`` [U]).

    Reads ``/proc`` directly (Linux); suitable as heartbeat ``stats`` payload.
    """
    out: dict = {"time": time.time()}
    try:
        with open("/proc/self/stat") as f:
            stat = f.read()
        # field 2 is "(comm)" and may itself contain spaces/parens — split
        # only AFTER the last ')', then index relative to field 3 ("state")
        parts = stat[stat.rindex(")") + 2 :].split()
        tick = os.sysconf("SC_CLK_TCK")
        out["cpu_user_s"] = int(parts[11]) / tick  # utime (field 14)
        out["cpu_sys_s"] = int(parts[12]) / tick  # stime (field 15)
        out["threads"] = int(parts[17])  # num_threads (field 20)
        out["rss_mb"] = int(parts[21]) * os.sysconf("SC_PAGE_SIZE") / 2**20
    except (OSError, IndexError, ValueError):
        pass  # non-Linux: time-only heartbeat stats
    return out
