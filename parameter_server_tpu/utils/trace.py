"""Host-side tracing: spans, latency histograms, chrome-trace export.

SURVEY.md §5 tracing plan: the reference has only ad-hoc timing macros and
``/proc`` polling (``util/resource_usage.h``, ``system/network_usage.h``
[U]); the rebuild gets a real tracer — Push/Pull latency histograms on the
host path, exportable timelines, and a ``jax.profiler`` hook for the device
side (TensorBoard traces with ICI utilization).

Design: recording a span is two ``perf_counter`` calls and one deque append
under a lock (~1 microsecond) so the tracer can stay on in production; the
module-level :data:`NULL_TRACER` short-circuits to nothing for hot loops
that want zero overhead.
"""

from __future__ import annotations

import collections
import contextlib
import json
import math
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

#: one recorded span: (name, start_s, duration_s, thread_id, attrs)
Span = Tuple[str, float, float, int, Optional[dict]]


class LatencyHistogram:
    """O(1) mergeable log-bucketed streaming duration histogram.

    Buckets are geometric: bucket ``i`` has upper edge ``BASE * GROWTH**i``
    (bucket 0 holds everything <= 1 us); 96 buckets reach ~27 minutes at
    <= 25% relative error — the right resolution for wire and handler
    latencies.  Unlike the Tracer's bounded span deque this NEVER drops
    history: count/sum/max are exact, percentiles are bucket-resolution
    upper bounds (clamped to the observed max, so ``p99 <= max`` always).
    Two histograms merge by adding bucket counts, which is what lets
    per-link digests ride heartbeats and be re-aggregated fleet-side
    (the reference monitor merged per-node ``network_usage`` the same way).

    No internal lock: recorders (Tracer, MeteredVan) already serialize
    under their own locks, and every mutation is a single GIL-atomic
    scalar op, so a concurrent read can only skew a snapshot, never
    corrupt state.
    """

    BASE = 1e-6
    GROWTH = 1.25
    NBUCKETS = 96
    _LOG_G = math.log(GROWTH)
    #: interned bucket-key strings — ``to_dict`` runs per telemetry frame
    #: on hot paths; 96 ``str(i)`` calls per digest add up.
    _BKEYS = tuple(str(i) for i in range(NBUCKETS))

    __slots__ = ("counts", "count", "sum_s", "max_s")

    def __init__(self) -> None:
        self.counts = [0] * self.NBUCKETS
        self.count = 0
        self.sum_s = 0.0
        self.max_s = 0.0

    def _bucket(self, seconds: float) -> int:
        if seconds <= self.BASE:
            return 0
        return min(
            self.NBUCKETS - 1,
            1 + int(math.log(seconds / self.BASE) / self._LOG_G),
        )

    def record(self, seconds: float) -> None:
        seconds = max(float(seconds), 0.0)
        self.counts[self._bucket(seconds)] += 1
        self.count += 1
        self.sum_s += seconds
        if seconds > self.max_s:
            self.max_s = seconds

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Add ``other``'s mass into this histogram (returns self)."""
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum_s += other.sum_s
        self.max_s = max(self.max_s, other.max_s)
        return self

    def merge_dict(self, d: dict) -> "LatencyHistogram":
        """Fold a ``to_dict`` digest in without materializing it — touches
        only the sparse occupied buckets, so merging a per-frame DELTA
        digest (usually one or two buckets) costs O(buckets present), not
        O(NBUCKETS).  The telemetry aggregator's per-frame cumulative fold
        is exactly that shape."""
        for i, c in (d.get("b") or {}).items():
            self.counts[int(i)] += int(c)
        self.count += int(d.get("count", 0))
        self.sum_s += float(d.get("sum_s", 0.0))
        self.max_s = max(self.max_s, float(d.get("max_s", 0.0)))
        return self

    def percentile(self, p: float) -> float:
        """Upper bound (seconds) of the bucket holding the p-quantile."""
        if self.count == 0:
            return 0.0
        target = max(1, math.ceil(p * self.count))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= target:
                return min(self.BASE * self.GROWTH**i, self.max_s)
        return self.max_s  # pragma: no cover — cum == count by construction

    def stats(self) -> dict:
        """The Tracer.histogram row shape (count / mean / p50 / p99 / max)."""
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "total_s": self.sum_s,
            "mean_us": 1e6 * self.sum_s / self.count,
            "p50_us": 1e6 * self.percentile(0.50),
            "p90_us": 1e6 * self.percentile(0.90),
            "p99_us": 1e6 * self.percentile(0.99),
            "max_us": 1e6 * self.max_s,
        }

    # -- wire form (heartbeat digests are JSON) ------------------------------
    def to_dict(self) -> dict:
        """JSON-safe digest; sparse buckets keep heartbeats small."""
        return {
            "count": self.count,
            "sum_s": self.sum_s,
            "max_s": self.max_s,
            "b": {self._BKEYS[i]: c for i, c in enumerate(self.counts) if c},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LatencyHistogram":
        h = cls()
        h.count = int(d.get("count", 0))
        h.sum_s = float(d.get("sum_s", 0.0))
        h.max_s = float(d.get("max_s", 0.0))
        for i, c in (d.get("b") or {}).items():
            h.counts[int(i)] = int(c)
        return h


class Tracer:
    """Thread-safe span recorder: bounded timeline + unbounded histograms.

    Two stores per span name, updated together under one lock:

    - a bounded deque of full spans (timelines / chrome-trace export) —
      oldest spans drop past ``capacity``;
    - a :class:`LatencyHistogram` that never drops, so
      :meth:`histogram` percentiles cover the whole run, not a silent
      recent window (they used to be computed over the deque: after 100k
      spans wrapped, "p99" quietly became "p99 of the last 100k").
    """

    def __init__(self, *, capacity: int = 100_000, enabled: bool = True) -> None:
        self.enabled = enabled
        self._spans: collections.deque[Span] = collections.deque(maxlen=capacity)
        #: never-dropping per-name latency histograms (histogram/summary/
        #: totals read these, so aggregates survive deque wraparound).
        self._hists: Dict[str, LatencyHistogram] = {}
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        start = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - start
            with self._lock:
                self._spans.append(
                    (name, start - self._t0, dur, threading.get_ident(),
                     attrs or None)
                )
                h = self._hists.get(name)
                if h is None:
                    h = self._hists[name] = LatencyHistogram()
                h.record(dur)

    def record(self, name: str, duration_s: float,
               start_s: Optional[float] = None, **attrs) -> None:
        """Record an externally timed span (e.g. from a callback).

        ``start_s``: the span's start as a ``time.perf_counter()`` value —
        without it the span is placed ending "now", which misorders
        retrospectively recorded phases on a timeline.
        """
        if not self.enabled:
            return
        if start_s is None:
            start_s = time.perf_counter() - duration_s
        with self._lock:
            self._spans.append(
                (name, start_s - self._t0, duration_s,
                 threading.get_ident(), attrs or None)
            )
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = LatencyHistogram()
            h.record(duration_s)

    def totals(self) -> Dict[str, float]:
        """Cumulative seconds per span name (O(names), never drops spans)."""
        with self._lock:
            return {name: h.sum_s for name, h in self._hists.items()}

    def spans(self, name: Optional[str] = None) -> List[Span]:
        with self._lock:
            out = list(self._spans)
        return out if name is None else [s for s in out if s[0] == name]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._hists.clear()

    # -- aggregation ---------------------------------------------------------
    def histogram(self, name: str) -> dict:
        """Latency stats for one span name (the Push/Pull histogram).

        Backed by the never-dropping :class:`LatencyHistogram`, so the
        percentiles cover every span ever recorded under ``name`` — not
        just the ones still in the bounded deque.
        """
        with self._lock:
            h = self._hists.get(name)
            stats = h.stats() if h is not None else {"count": 0}
        return {"name": name, **stats}

    def summary(self) -> Dict[str, dict]:
        """Histogram per distinct span name."""
        with self._lock:
            names = sorted(self._hists)
        return {name: self.histogram(name) for name in names}

    def digests(self) -> Dict[str, dict]:
        """JSON-safe per-name histogram digests (heartbeat payload form)."""
        with self._lock:
            return {name: h.to_dict() for name, h in self._hists.items()}

    # -- export --------------------------------------------------------------
    def dump_chrome_trace(self, path: str,
                          process_name: Optional[str] = None) -> None:
        """Write the spans as a chrome://tracing / Perfetto JSON timeline.

        ``process_name`` (e.g. the node id): embeds a top-level
        ``metadata`` block — the node name plus this tracer's perf_counter
        epoch — that ``tools/merge_traces.py`` uses to label the process
        and align per-node clocks on one merged timeline.
        """
        events = [
            {
                "name": name,
                "ph": "X",
                "ts": start * 1e6,
                "dur": dur * 1e6,
                "pid": os.getpid(),
                "tid": tid,
                **({"args": attrs} if attrs else {}),
            }
            for name, start, dur, tid, attrs in self.spans()
        ]
        doc: dict = {"traceEvents": events}
        if process_name is not None:
            doc["metadata"] = {"node": process_name, "clock_t0_s": self._t0}
        with open(path, "w") as f:
            json.dump(doc, f)

    def dump_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            for name, start, dur, tid, attrs in self.spans():
                f.write(
                    json.dumps(
                        {"name": name, "start_s": start, "dur_s": dur,
                         "tid": tid, "attrs": attrs}
                    )
                    + "\n"
                )


#: shared do-nothing tracer for hot paths with tracing off
NULL_TRACER = Tracer(enabled=False)


@contextlib.contextmanager
def jax_profile(logdir: str) -> Iterator[None]:
    """Device-side profile: wraps ``jax.profiler.trace`` (TensorBoard).

    The host Tracer covers Van/host latency; this captures the XLA timeline
    (HBM traffic, ICI collectives) for the same window.
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def resource_usage() -> dict:
    """Process CPU/memory snapshot (reference ``util/resource_usage.h`` [U]).

    Reads ``/proc`` directly (Linux); suitable as heartbeat ``stats`` payload.
    """
    out: dict = {"time": time.time()}
    try:
        with open("/proc/self/stat") as f:
            stat = f.read()
        # field 2 is "(comm)" and may itself contain spaces/parens — split
        # only AFTER the last ')', then index relative to field 3 ("state")
        parts = stat[stat.rindex(")") + 2 :].split()
        tick = os.sysconf("SC_CLK_TCK")
        out["cpu_user_s"] = int(parts[11]) / tick  # utime (field 14)
        out["cpu_sys_s"] = int(parts[12]) / tick  # stime (field 15)
        out["threads"] = int(parts[17])  # num_threads (field 20)
        out["rss_mb"] = int(parts[21]) * os.sysconf("SC_PAGE_SIZE") / 2**20
    except (OSError, IndexError, ValueError):
        pass  # non-Linux: time-only heartbeat stats
    return out
