"""Count-min sketch for tail-feature filtering.

The reference pre-filters rare features before inserting them into server
tables: workers push key lists, servers count occurrences in a count-min
sketch and only admit keys seen >= threshold times (reference
``src/util/countmin.h`` [U]; used by the linear-method preprocessing stage).
Filtering the long tail shrinks billion-row CTR vocabularies by large factors.

Vectorized numpy implementation; the sketch lives on the host beside the
Localizer.  Hashing is a splitmix64-style mix per row — cheap, deterministic,
and good avalanche behavior for integer feature keys.
"""

from __future__ import annotations

import numpy as np

from parameter_server_tpu.utils.keys import mix64 as _mix64


class CountMin:
    """Count-min sketch: conservative frequency estimates, never undercounts."""

    def __init__(self, width: int = 1 << 20, depth: int = 4, seed: int = 0):
        self.width = int(width)
        self.depth = int(depth)
        self._table = np.zeros((depth, self.width), dtype=np.uint32)
        rng = np.random.default_rng(seed)
        self._seeds = rng.integers(1, 2**63, size=depth, dtype=np.uint64)

    def _slots(self, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, dtype=np.uint64).ravel()
        return np.stack(
            [_mix64(keys, s) % np.uint64(self.width) for s in self._seeds]
        )  # [depth, n]

    def add(self, keys: np.ndarray, counts: np.ndarray | int = 1) -> None:
        slots = self._slots(keys)
        counts = np.broadcast_to(
            np.asarray(counts, dtype=np.uint32), slots.shape[1:]
        )
        for d in range(self.depth):
            np.add.at(self._table[d], slots[d], counts)

    def query(self, keys: np.ndarray) -> np.ndarray:
        """Estimated counts (>= true counts) for each key."""
        slots = self._slots(keys)
        est = self._table[0][slots[0]]
        for d in range(1, self.depth):
            est = np.minimum(est, self._table[d][slots[d]])
        return est

    def filter(self, keys: np.ndarray, threshold: int) -> np.ndarray:
        """Boolean mask of keys whose estimated count >= threshold."""
        return self.query(keys) >= np.uint32(threshold)
