"""Host-side utilities: key localization, sketches, metrics, checkpointing."""
