"""JAX platform forcing for subprocess roles and CPU-only tools.

The dev image's sitecustomize registers an experimental single-TPU PJRT
plugin in every interpreter; jax initializes all registered plugins at
backend discovery, which can block (the plugin dials a device-relay
service) even when ``JAX_PLATFORMS=cpu``.  Launched cluster roles are
host-side programs that must never touch the chip, so they unregister
non-standard plugin factories BEFORE the first backend access — the same
approach as ``tests/conftest.py``.
"""

from __future__ import annotations

import os


def force_cpu(n_devices: int = 0) -> None:
    """Pin this process to the CPU backend (optionally n virtual devices).

    Must run before any jax operation initializes a backend; afterwards it
    is a no-op (jax refuses to switch initialized platforms).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices:
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--xla_force_host_platform_device_count={n_devices}"
        if "xla_force_host_platform_device_count" in flags:
            # an inherited count (e.g. the test env's 8) must not override
            # the caller's explicit topology — replace it
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", want, flags
            )
        else:
            flags = (flags + " " + want).strip()
        os.environ["XLA_FLAGS"] = flags
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        from jax._src import xla_bridge as _xb

        for name in list(getattr(_xb, "_backend_factories", {})):
            if name not in ("cpu", "tpu", "gpu", "cuda", "rocm"):
                _xb._backend_factories.pop(name, None)
    except Exception:
        pass  # already initialized or internals moved: best effort
