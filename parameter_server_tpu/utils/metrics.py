"""Metrics and dashboard: AUC, logloss tracking, per-iteration progress rows.

Reference analogues: ``src/util/evaluation.h`` (AUC), scheduler
``dashboard.h`` per-iteration table, heartbeat-fed monitor [U].  Output is
both human-readable rows and structured JSONL (the north-star metrics
``examples/sec/chip`` and time-to-accuracy must be first-class outputs,
SURVEY.md §5).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import IO, Optional

import numpy as np


def auc(labels: np.ndarray, scores: np.ndarray) -> float:
    """Area under the ROC curve via rank statistic (ties averaged)."""
    labels = np.asarray(labels).ravel()
    scores = np.asarray(scores).ravel()
    pos = labels > 0.5
    n_pos = int(pos.sum())
    n_neg = labels.size - n_pos
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty(labels.size, dtype=np.float64)
    ranks[order] = np.arange(1, labels.size + 1)
    # average ranks over tied scores
    sorted_scores = scores[order]
    i = 0
    while i < labels.size:
        j = i
        while j + 1 < labels.size and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            ranks[order[i : j + 1]] = (i + 1 + j + 1) / 2.0
        i = j + 1
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg))


def transport_counters(van) -> dict:
    """Merge dashboard counters from a (possibly wrapped) Van stack.

    Walks the ``.inner`` chain of Van decorators (``ReliableVan``,
    ``ChaosVan``, ``MeteredVan``) down to the base transport, merging each
    layer's ``counters()`` dict — so retransmit / dup-suppressed / gave-up
    / injected-fault / wire-byte counts ride next to sent/dropped in one
    flat dict.  Same-named keys across layers are summed.
    """
    out: dict = {}
    seen = set()
    v = van
    while v is not None and id(v) not in seen:
        seen.add(id(v))
        get = getattr(v, "counters", None)
        if callable(get):
            try:
                for k, val in get().items():
                    out[k] = out.get(k, 0) + val
            except Exception:  # pragma: no cover — metrics must never crash
                pass
        v = getattr(v, "inner", None)
    return out


class CounterGroup:
    """Merge several ``counters()`` sources into one dict (summed keys).

    The migration plane's counters live on MANY objects — each
    :class:`~parameter_server_tpu.kv.server.KVServer` (``fenced_rejects``,
    ``rows_migrated_in/out``, freeze seconds), each
    :class:`~parameter_server_tpu.kv.worker.KVWorker` (``refresh_retries``,
    deadline retries) and the
    :class:`~parameter_server_tpu.kv.migrate.ShardMigrator` (moves/aborts).
    Group them (``CounterGroup(*servers, *workers, migrator)``) and attach
    as ``Dashboard(migration=...)`` so a rebalance shows up in the SAME rows
    as retransmits and cancels.  Postoffices also expose ``counters()``
    (``cancelled_drops``) — include them in the group and the Dashboard's
    transport ``rejects`` sub-dict lights up cancellation fences too.
    """

    def __init__(self, *sources) -> None:
        self.sources = list(sources)

    def add(self, *sources) -> "CounterGroup":
        self.sources.extend(sources)
        return self

    def counters(self) -> dict:
        out: dict = {}
        for src in self.sources:
            get = getattr(src, "counters", None)
            if not callable(get):
                continue
            try:
                for k, v in get().items():
                    out[k] = out.get(k, 0) + v
            except Exception:  # pragma: no cover — metrics must never crash
                pass
        return out


def _auto_peak_flops() -> float:
    """Peak dense FLOP/s of the active backend for the MFU denominator.

    TPU v5e ≈ 197 TFLOP/s bf16 (the honest MXU ceiling); CPU gets a nominal
    100 GF so CPU-sim MFU numbers stay visibly "not a TPU measurement".
    """
    try:
        import jax

        return {"tpu": 197e12, "gpu": 60e12}.get(jax.default_backend(), 1e11)
    except Exception:  # pragma: no cover — metrics must never crash training
        return 1e11


def lowered_flops(jitfn, *args) -> float:
    """XLA-reported FLOPs for ONE call of a jitted function.

    Uses the pre-compile HLO cost analysis (``Lowered.cost_analysis``): no
    compilation, no execution — cheap enough to run at trainer init.  This
    is the generic MFU numerator for models without a clean closed form
    (ResNet convs, DLRM interactions); transformers use the 6ND rule so the
    number matches the convention papers report.  Returns 0.0 when the
    backend can't produce an analysis (MFU column then stays off).
    """
    try:
        ca = jitfn.lower(*args).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("flops", 0.0))
    except Exception:  # pragma: no cover — metrics must never crash training
        return 0.0


def mesh_peak_flops(n_devices: int) -> float:
    """Aggregate peak FLOP/s of an ``n_devices`` mesh (MFU denominator).

    The numerator counts FLOPs executed across the WHOLE mesh, so the
    denominator must be the mesh's aggregate peak — one chip's peak would
    report an 8-chip run at up to 800% MFU.
    """
    return _auto_peak_flops() * n_devices


def lm_matmul_params(params, drop: frozenset) -> int:
    """6ND numerator: total size of matmul-participating param leaves.

    ``drop``: top-level keys that are gathers, not matmuls (the input
    embedding table when untied, positional embeddings).  Shared by every
    transformer trainer so the MFU accounting cannot drift between them.
    """
    import jax

    return sum(
        int(np.prod(leaf.shape))
        for k, sub in params.items()
        if k not in drop
        for leaf in jax.tree.leaves(sub)
    )


def trainer_dashboard(dashboard, n_devices: int) -> "Dashboard":
    """The trainer-ctor idiom in one place: default Dashboard + mesh peak.

    Every trainer calls this instead of repeating the
    default-then-set-peak_flops dance (a caller-provided non-zero
    ``peak_flops`` wins).
    """
    d = dashboard or Dashboard(print_every=0)
    if d.peak_flops <= 0.0:
        d.peak_flops = mesh_peak_flops(n_devices)
    return d


@dataclasses.dataclass
class Dashboard:
    """Per-iteration progress table + JSONL sink.

    Prints rows like the reference scheduler dashboard (iter, time, objective,
    relative delta, examples/sec) and appends machine-readable JSONL.

    MFU (VERDICT r2 weak #7): set ``flops_per_example`` (the model's FLOPs
    per trained example) and every row carries ``mfu_pct`` — per-interval
    model FLOP utilisation against ``peak_flops`` (auto-detected from the
    backend when 0).  Attach a :class:`~parameter_server_tpu.utils.trace.Tracer`
    and printed/JSONL rows also carry the host/H2D/device second-attribution
    of everything the trainer recorded spans for (:meth:`attribution`).
    """

    jsonl: Optional[IO[str]] = None
    print_every: int = 10
    #: model FLOPs per example; 0 disables the MFU column.
    flops_per_example: float = 0.0
    #: peak FLOP/s for the MFU denominator; 0 = auto by backend at first use.
    peak_flops: float = 0.0
    #: optional span recorder feeding host/H2D/device attribution.
    tracer: Optional[object] = None
    #: optional Van (stacked wrappers fine): rows gain a ``net`` dict of
    #: cumulative transport counters — retransmits, dup_suppressed, gave_up,
    #: injected chaos faults, sent/dropped (see :func:`transport_counters`)
    #: plus derived wire-efficiency fields when a ``CoalescingVan`` is in
    #: the stack: ``bundle_occupancy`` (sub-messages per bundle frame) and
    #: ``frames_per_step`` (per-interval wire frames / iterations — the
    #: number coalescing exists to shrink).  With a ``MeteredVan`` in the
    #: stack, rows also carry ``bytes_per_example`` (cumulative wire bytes
    #: / examples trained — the wire cost of progress) and
    #: ``wire_bytes_per_sec`` (per-interval link throughput).
    transport: Optional[object] = None
    #: optional ``data.prefetch.PrefetchPipeline`` (anything with
    #: ``counters()``): rows gain a ``prefetch`` dict — produced/consumed
    #: block counts and cumulative stall count/seconds (consumer time spent
    #: waiting on the producer; nonzero means ingest is the bottleneck).
    prefetch: Optional[object] = None
    #: optional migration-plane counter source (anything with ``counters()``
    #: — typically a :class:`CounterGroup` over servers/workers/migrator):
    #: rows gain a ``migration`` dict — rows migrated in/out, fenced
    #: (wrong-epoch) rejects, refresh retries, cumulative handoff freeze
    #: seconds — so a live rebalance is visible in the same place as
    #: retransmits and cancels.
    migration: Optional[object] = None
    _start: float = dataclasses.field(default_factory=time.time)
    _last_obj: Optional[float] = None
    _last_t: Optional[float] = None
    _examples: int = 0
    _header_printed: bool = False
    _attr_last: dict = dataclasses.field(default_factory=dict)
    _net_sent_last: int = 0
    _net_iter_last: int = -1
    _net_bytes_last: int = 0
    _net_t_last: Optional[float] = None

    def record(self, iteration: int, objective: float, extra: Optional[dict] = None,
               examples: int = 0, now: Optional[float] = None) -> None:
        """``now``: the tick's shared wall-clock stamp (defaults to a fresh
        ``time.time()``).  Callers that also write a fleet JSONL row this
        tick should capture one stamp and pass it to BOTH this and
        ``FleetMonitor.write_jsonl(wall=...)`` — otherwise every interval
        rate here uses a denominator skewed by however long the other sink's
        dump took."""
        self._examples += examples
        now = time.time() if now is None else now
        rel = (
            (objective - self._last_obj) / abs(self._last_obj)
            if self._last_obj not in (None, 0.0)
            else 0.0
        )
        self._last_obj = objective
        interval = now - (self._last_t if self._last_t is not None else self._start)
        self._last_t = now
        row = {
            "iter": iteration,
            "sec": round(now - self._start, 3),
            "objective": round(float(objective), 6),
            "rel_delta": round(float(rel), 6),
            "examples": self._examples,
            "examples_per_sec": round(self._examples / max(now - self._start, 1e-9), 1),
        }
        mfu = None
        if self.flops_per_example > 0.0 and examples:
            if self.peak_flops <= 0.0:
                self.peak_flops = _auto_peak_flops()
            mfu = (
                self.flops_per_example * examples
                / max(interval, 1e-9)
                / self.peak_flops
            )
            row["mfu_pct"] = round(mfu * 100.0, 4)
        if extra:
            row.update(extra)
        if self.transport is not None:
            net = transport_counters(self.transport)
            if net:
                frames = net.get("coalesce_frames", 0)
                if frames:
                    net["bundle_occupancy"] = round(
                        net.get("coalesce_msgs", 0) / frames, 2
                    )
                sent = net.get("sent")
                if sent is not None:
                    d_iter = iteration - self._net_iter_last
                    if self._net_iter_last >= 0 and d_iter > 0:
                        net["frames_per_step"] = round(
                            (sent - self._net_sent_last) / d_iter, 2
                        )
                    self._net_sent_last = sent
                    self._net_iter_last = iteration
                wire_bytes = net.get("wire_bytes")
                if wire_bytes is not None:
                    # wire efficiency next to examples_per_sec: cumulative
                    # bytes per trained example + per-interval throughput
                    if self._examples:
                        net["bytes_per_example"] = round(
                            wire_bytes / self._examples, 2
                        )
                    if self._net_t_last is not None:
                        net["wire_bytes_per_sec"] = round(
                            (wire_bytes - self._net_bytes_last)
                            / max(now - self._net_t_last, 1e-9),
                            1,
                        )
                    self._net_bytes_last = wire_bytes
                    self._net_t_last = now
                row["net"] = net
        if self.prefetch is not None:
            pf_counters = getattr(self.prefetch, "counters", None)
            if callable(pf_counters):
                try:
                    row["prefetch"] = pf_counters()
                except Exception:  # pragma: no cover — metrics must never
                    pass  # crash training
        if self.migration is not None:
            mig_counters = getattr(self.migration, "counters", None)
            if callable(mig_counters):
                try:
                    row["migration"] = mig_counters()
                except Exception:  # pragma: no cover — metrics must never
                    pass  # crash training
        net_row = row.get("net")
        if net_row is not None:
            # every reject class in one 0-filled sub-dict, so a garbled-wire
            # or fencing storm is visible in the transport section without
            # grepping per-layer counters.  frame/CRC/incarnation rejects
            # come from the van walk; routing fences and cancellation drops
            # live on KVServers / Postoffices — attach them via the
            # ``migration`` CounterGroup to light those two up.
            mig_row = row.get("migration") or {}
            net_row["rejects"] = {
                "frame_rejects": int(net_row.get("frame_rejects", 0)),
                "rejected_corrupt": int(net_row.get("rejected_corrupt", 0)),
                "rejected_stale": int(net_row.get("rejected_stale", 0)),
                "fenced_rejects": int(mig_row.get("fenced_rejects", 0)),
                "cancelled_drops": int(mig_row.get("cancelled_drops", 0)),
            }
        printing = self.print_every and iteration % self.print_every == 0
        if self.tracer is not None and (printing or self.jsonl is not None):
            # interval DELTAS (this row's share), from the tracer's O(1)
            # running totals — not a scan of the span deque, and not a
            # misleading cumulative sum per row
            attr = self.attribution()
            row["spans_s"] = {
                k: round(v - self._attr_last.get(k, 0.0), 4)
                for k, v in attr.items()
                if v - self._attr_last.get(k, 0.0) > 0
            }
            self._attr_last = attr
        if self.jsonl is not None:
            self.jsonl.write(json.dumps(row) + "\n")
            self.jsonl.flush()
        if printing:
            if not self._header_printed:
                print(
                    f"{'iter':>6} {'sec':>8} {'objective':>10} {'rel':>9} "
                    f"{'ex/s':>10} {'mfu%':>8}"
                )
                self._header_printed = True
            mfu_s = f"{mfu * 100:>8.3f}" if mfu is not None else f"{'-':>8}"
            print(
                f"{iteration:>6} {row['sec']:>8.2f} {row['objective']:>10.5f} "
                f"{row['rel_delta']:>9.5f} {row['examples_per_sec']:>10.1f} "
                f"{mfu_s}"
            )

    def attribution(self) -> dict:
        """Cumulative seconds per span name from the attached tracer.

        Trainers record spans named by plane (e.g. ``host.assemble``,
        ``h2d``, ``device.step``, ``kv.push``); this sums their durations so
        a step-time budget — where did the wall clock actually go — rides
        next to the throughput numbers (SURVEY §5 observability).  Uses the
        tracer's O(1) running totals when available (hot-path safe).
        """
        if self.tracer is None:
            return {}
        totals = getattr(self.tracer, "totals", None)
        if callable(totals):
            return totals()
        out: dict = {}
        for name, _start, dur, _tid, _attrs in self.tracer.spans():
            out[name] = out.get(name, 0.0) + dur
        return out

    @property
    def examples_per_sec(self) -> float:
        return self._examples / max(time.time() - self._start, 1e-9)
