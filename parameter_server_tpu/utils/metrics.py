"""Metrics and dashboard: AUC, logloss tracking, per-iteration progress rows.

Reference analogues: ``src/util/evaluation.h`` (AUC), scheduler
``dashboard.h`` per-iteration table, heartbeat-fed monitor [U].  Output is
both human-readable rows and structured JSONL (the north-star metrics
``examples/sec/chip`` and time-to-accuracy must be first-class outputs,
SURVEY.md §5).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import IO, Optional

import numpy as np


def auc(labels: np.ndarray, scores: np.ndarray) -> float:
    """Area under the ROC curve via rank statistic (ties averaged)."""
    labels = np.asarray(labels).ravel()
    scores = np.asarray(scores).ravel()
    pos = labels > 0.5
    n_pos = int(pos.sum())
    n_neg = labels.size - n_pos
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty(labels.size, dtype=np.float64)
    ranks[order] = np.arange(1, labels.size + 1)
    # average ranks over tied scores
    sorted_scores = scores[order]
    i = 0
    while i < labels.size:
        j = i
        while j + 1 < labels.size and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            ranks[order[i : j + 1]] = (i + 1 + j + 1) / 2.0
        i = j + 1
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg))


@dataclasses.dataclass
class Dashboard:
    """Per-iteration progress table + JSONL sink.

    Prints rows like the reference scheduler dashboard (iter, time, objective,
    relative delta, examples/sec) and appends machine-readable JSONL.
    """

    jsonl: Optional[IO[str]] = None
    print_every: int = 10
    _start: float = dataclasses.field(default_factory=time.time)
    _last_obj: Optional[float] = None
    _examples: int = 0
    _header_printed: bool = False

    def record(self, iteration: int, objective: float, extra: Optional[dict] = None,
               examples: int = 0) -> None:
        self._examples += examples
        now = time.time()
        rel = (
            (objective - self._last_obj) / abs(self._last_obj)
            if self._last_obj not in (None, 0.0)
            else 0.0
        )
        self._last_obj = objective
        row = {
            "iter": iteration,
            "sec": round(now - self._start, 3),
            "objective": round(float(objective), 6),
            "rel_delta": round(float(rel), 6),
            "examples": self._examples,
            "examples_per_sec": round(self._examples / max(now - self._start, 1e-9), 1),
        }
        if extra:
            row.update(extra)
        if self.jsonl is not None:
            self.jsonl.write(json.dumps(row) + "\n")
            self.jsonl.flush()
        if self.print_every and iteration % self.print_every == 0:
            if not self._header_printed:
                print(f"{'iter':>6} {'sec':>8} {'objective':>10} {'rel':>9} {'ex/s':>10}")
                self._header_printed = True
            print(
                f"{iteration:>6} {row['sec']:>8.2f} {row['objective']:>10.5f} "
                f"{row['rel_delta']:>9.5f} {row['examples_per_sec']:>10.1f}"
            )

    @property
    def examples_per_sec(self) -> float:
        return self._examples / max(time.time() - self._start, 1e-9)
