"""Key localization: global 64-bit feature keys -> dense local row ids.

This is the host-side half of the reference's core sparse trick
(``src/util/localizer.h`` :: ``Localizer`` [U]): global keys from parsed
examples are deduplicated and remapped to a compact dense id space so the
device only ever sees fixed-shape integer-indexed batches.  The device-side
half (gather / scatter-add over the row table) lives in
``parameter_server_tpu.ops.scatter`` (built in the same round as this module;
if that import fails you are looking at an intermediate tree).

Two flavors:

- :func:`localize_batch` — stateless per-batch dedup (np.unique), what the
  reference does per feature block.
- :class:`Localizer` — a persistent growing vocabulary mapping global keys to
  stable row slots, used by streaming learners (FTRL) where a key must keep
  its optimizer state across batches.

Shapes fed to jit-compiled code must be static; :func:`bucket_size` pads
unique-key counts to a small set of bucket sizes so recompilation happens at
most ``O(log(max_keys))`` times (SURVEY.md §7 hard part #1).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

#: Sentinel padding key: never a valid feature key. Padded rows scatter into a
#: dedicated trash row on device (see ops.scatter), so no masking is needed on
#: the hot path.
PAD_KEY = np.uint64(0xFFFFFFFFFFFFFFFF)

_MIX_MUL = np.uint64(0xFF51AFD7ED558CCD)
_MIX_MUL2 = np.uint64(0xC4CEB9FE1A85EC53)


def mix64(x: np.ndarray, seed: int | np.uint64 = 0) -> np.ndarray:
    """splitmix64-style avalanche mix, vectorized over uint64 arrays."""
    x = np.asarray(x, dtype=np.uint64)
    with np.errstate(over="ignore"):
        x = (x ^ np.uint64(seed)) * _MIX_MUL
        x ^= x >> np.uint64(33)
        x *= _MIX_MUL2
        x ^= x >> np.uint64(33)
    return x


#: murmur3 fmix32 constants — the 32-bit avalanche used when hashing happens
#: ON DEVICE (TPU has no native uint64).  ``models/linear.py`` ``mix32_jax``
#: imports these so the host/device twins stay bit-identical by construction.
MIX32_A = 0x85EB_CA6B
MIX32_B = 0xC2B2_AE35

#: uint32 image of PAD_KEY under truncation; reserved on the device-hash
#: path (keys must be < 2**32 - 1 there).
PAD_KEY32 = np.uint32(0xFFFF_FFFF)


def mix32(x: np.ndarray, seed: int | np.uint32 = 0) -> np.ndarray:
    """murmur3 fmix32 avalanche, vectorized over uint32 arrays.

    Host twin of the device-side ``mix32_jax``: both produce identical slot
    assignments, so host preprocessing and device hashing interoperate.
    """
    x = np.asarray(x, dtype=np.uint32)
    with np.errstate(over="ignore"):
        x = x ^ np.uint32(seed)
        x ^= x >> np.uint32(16)
        x *= np.uint32(MIX32_A)
        x ^= x >> np.uint32(13)
        x *= np.uint32(MIX32_B)
        x ^= x >> np.uint32(16)
    return x


def ensure_uint32_keys(keys: np.ndarray) -> np.ndarray:
    """Validate raw-width keys for the device-hash path; return them uint32.

    The device-hash trainer truncates keys to uint32, so keys ``>= 2**32-1``
    would silently wrap (or alias :data:`PAD_KEY32` and route to the trash
    row), corrupting training with no error.  This enforces the documented
    "< 2**32 - 1 unless PAD" contract: callers pass keys at their RAW width
    (a caller-side ``astype(np.uint32)`` would wrap bad keys before the
    check can see them — ADVICE r2), and this returns the validated uint32
    array.  Already-uint32 input passes through untouched (the width itself
    is the proof).  Shared by ``LocalLRTrainer.step_block`` and the prefetch
    producer so pipelined ingest keeps the same guard.
    """
    keys = np.asarray(keys)
    if keys.dtype == np.uint32:
        return keys
    kb = keys.astype(np.uint64)  # signed -1 coerces to PAD_KEY
    # cheap scalar early-out: only blocks containing a suspicious key
    # (>= uint32 max; PAD_KEY itself is uint64 max) pay for the mask
    if int(kb.max(initial=0)) >= 0xFFFF_FFFF:
        bad = (kb != PAD_KEY) & (kb >= np.uint64(0xFFFF_FFFF))
        if bad.any():
            raise ValueError(
                "device-hash keys must be < 2**32 - 1 "
                f"(or PAD_KEY); got {int(kb[bad][0])}"
            )
    return kb.astype(np.uint32)


def bucket_size(n: int, *, min_bucket: int = 256) -> int:
    """Round ``n`` up to the next power-of-two bucket (>= min_bucket).

    Bucketing the number of unique keys per batch keeps jit cache size
    logarithmic in batch size instead of recompiling per distinct count.
    """
    if n <= min_bucket:
        return min_bucket
    return 1 << int(np.ceil(np.log2(n)))


def localize_batch(
    keys: np.ndarray, *, pad_to_bucket: bool = True, min_bucket: int = 256
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Deduplicate a batch of global keys.

    Args:
      keys: int/uint array of global feature keys, any shape; flattened.
      pad_to_bucket: pad the unique-key array with :data:`PAD_KEY` up to a
        power-of-two bucket so downstream jit sees few distinct shapes.

    Returns:
      ``(unique_keys, inverse, n_unique)`` where ``unique_keys`` is sorted
      (padded with PAD_KEY at the tail if requested), ``inverse`` maps each
      input position to its row in ``unique_keys``, and ``n_unique`` is the
      true (unpadded) unique count.

    The sortedness of ``unique_keys`` is what lets the server side slice by
    key range with binary search (reference ``Parameter::Slice`` [U]).
    """
    # Keys are uint64 by contract; coerce signed parser output so PAD_KEY
    # padding cannot wrap to -1 and break the sortedness invariant.
    flat = np.ascontiguousarray(keys).ravel().astype(np.uint64, copy=False)
    uniq, inverse = np.unique(flat, return_inverse=True)
    n_unique = int(uniq.shape[0])
    if pad_to_bucket:
        cap = bucket_size(n_unique, min_bucket=min_bucket)
        if cap > n_unique:
            pad = np.full(cap - n_unique, PAD_KEY, dtype=uniq.dtype)
            uniq = np.concatenate([uniq, pad])
    return uniq, inverse.astype(np.int32), n_unique


def slice_by_ranges(
    sorted_keys: np.ndarray, range_bounds: np.ndarray
) -> np.ndarray:
    """Partition sorted keys into server key ranges.

    ``range_bounds`` is the ``num_servers + 1`` ascending boundary array from
    the NodeAssigner-style even split of the key space (reference
    ``src/system/assigner.h`` [U]).  Returns the ``num_servers + 1`` split
    indices into ``sorted_keys`` (use ``searchsorted`` semantics: server ``s``
    owns ``sorted_keys[idx[s]:idx[s+1]]``).
    """
    return np.searchsorted(sorted_keys, range_bounds, side="left")


def even_key_ranges(num_servers: int, key_space: int = 2**64) -> np.ndarray:
    """Evenly split ``[0, key_space)`` into ``num_servers`` contiguous ranges.

    Defaults to the full uint64 space (which :func:`localize_batch` produces —
    signed parser keys wrap into the top half).  The returned array has
    ``num_servers + 1`` bounds; since ``2**64`` itself is not representable,
    the final bound saturates to ``2**64 - 1`` (== :data:`PAD_KEY`) — PAD keys
    are excluded from server slicing anyway (callers slice ``uniq[:n]``).
    """
    if not (0 < key_space <= 2**64):
        raise ValueError("key_space must be in (0, 2**64]")
    step = key_space // num_servers
    bounds_py = [min(i * step, 2**64 - 1) for i in range(num_servers)]
    bounds_py.append(min(key_space, 2**64 - 1))
    return np.array(bounds_py, dtype=np.uint64)


def localize_to_slots(
    keys: np.ndarray, localizer: "Localizer", *, min_bucket: int = 256
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Full host-side key pipeline: raw keys -> unique row slots + inverse.

    Composes :func:`localize_batch` with :meth:`Localizer.assign` and then
    re-uniquifies the *slots* (after vocabulary overflow two distinct keys may
    hash-share a slot; the device requires unique ids for the scatter fast
    path).  Returns ``(slots, inverse, n)``: sorted unique slot ids padded to
    a power-of-two bucket (pads point at the trash row ``capacity``),
    position->slot-row inverse, and the true unique-slot count.
    """
    uniq, key_inv, _ = localize_batch(
        keys, pad_to_bucket=False, min_bucket=min_bucket
    )
    raw_slots = localizer.assign(uniq)
    uniq_slots, slot_inv = np.unique(raw_slots, return_inverse=True)
    n = int(uniq_slots.shape[0])
    cap = bucket_size(n, min_bucket=min_bucket)
    if cap > n:
        uniq_slots = np.concatenate(
            [uniq_slots, np.full(cap - n, localizer.capacity, dtype=uniq_slots.dtype)]
        )
    inverse = slot_inv[key_inv].astype(np.int32)
    return uniq_slots.astype(np.int32, copy=False), inverse, n


class HashLocalizer:
    """Stateless deterministic key -> slot mapping (the hashing trick).

    Multi-worker training requires every worker to map a global key to the
    *same* table row without coordination; a deterministic hash provides that
    (at the cost of collisions, which :func:`localize_to_slots` tolerates by
    re-uniquifying slots).  This is the standard large-vocabulary CTR/DLRM
    scheme and the multi-worker counterpart of :class:`Localizer`.
    """

    def __init__(self, capacity: int, seed: int = 0, hash_bits: int = 64):
        if not (0 < capacity < 2**31 - 1):
            raise ValueError(
                "capacity must fit int32 row ids (shard billion-row tables "
                "across servers / mesh axes instead)"
            )
        if hash_bits not in (32, 64):
            raise ValueError("hash_bits must be 32 or 64")
        self.capacity = capacity
        self.seed = seed
        #: 32 = murmur fmix32 on truncated keys, matching the device-side
        #: ``models.linear.mix32_jax`` (TPU has no uint64); keys must fit
        #: uint32 for collision behavior to stay key-space-uniform.
        self.hash_bits = hash_bits
        self.overflowed = True  # collisions always possible

    def assign(self, unique_keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(unique_keys, dtype=np.uint64)
        if self.hash_bits == 32:
            slots = (
                mix32(keys.astype(np.uint32), np.uint32(self.seed))
                % np.uint32(self.capacity)
            ).astype(np.int32)
        else:
            slots = (
                mix64(keys, self.seed) % np.uint64(self.capacity)
            ).astype(np.int32)
        return np.where(keys == PAD_KEY, np.int32(self.capacity), slots)


class IdentityLocalizer:
    """Exact key == row-slot mapping for dense-vocabulary tables.

    Embedding tables (token id -> row) need every id to hit ITS OWN row —
    hashing would collide distinct tokens.  Keys must already be dense ids
    in ``[0, capacity)``; PAD_KEY maps to the trash row ``capacity``.
    """

    def __init__(self, capacity: int):
        if not (0 < capacity < 2**31 - 1):
            raise ValueError("capacity must fit int32 row ids")
        self.capacity = capacity
        self.overflowed = False

    def assign(self, unique_keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(unique_keys, dtype=np.uint64)
        is_pad = keys == PAD_KEY
        # only PAD may reach the trash row (== capacity); a real key equal to
        # capacity must error, not silently alias pad updates
        bad = ~is_pad & (keys >= np.uint64(self.capacity))
        if bad.any():
            raise ValueError(
                f"IdentityLocalizer: key {int(keys[bad][0])} outside [0, "
                f"{self.capacity}) (dense-vocab tables take raw ids)"
            )
        return np.where(
            is_pad, np.int64(self.capacity), keys.astype(np.int64)
        ).astype(np.int32)


class _NativeKeyMap:
    """ctypes wrapper around the C++ keymap (``native/src/keymap.cc``)."""

    def __init__(self, lib, capacity: int) -> None:
        self._lib = lib
        self._h = lib.ps_keymap_new(capacity)
        if not self._h:
            raise MemoryError("ps_keymap_new failed")

    def assign(self, flat_keys: np.ndarray) -> np.ndarray:
        import ctypes

        flat_keys = np.ascontiguousarray(flat_keys, dtype=np.uint64)
        out = np.empty(flat_keys.shape[0], dtype=np.int32)
        self._lib.ps_keymap_assign(
            self._h,
            flat_keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            flat_keys.shape[0],
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return out

    def len(self) -> int:
        return int(self._lib.ps_keymap_len(self._h))

    def overflowed(self) -> bool:
        return bool(self._lib.ps_keymap_overflowed(self._h))

    def __del__(self) -> None:  # pragma: no cover — interpreter teardown
        h, self._h = getattr(self, "_h", None), None
        if h:
            try:
                self._lib.ps_keymap_free(h)
            except Exception:
                pass


def _native_keymap(capacity: int):
    """Load the native keymap engine, or None (numpy fallback)."""
    import ctypes

    from parameter_server_tpu import native

    lib = native.load("keymap")
    if lib is None:
        return None
    if not getattr(lib, "_ps_keymap_sigs", False):
        lib.ps_keymap_new.argtypes = [ctypes.c_int64]
        lib.ps_keymap_new.restype = ctypes.c_void_p
        lib.ps_keymap_free.argtypes = [ctypes.c_void_p]
        lib.ps_keymap_len.argtypes = [ctypes.c_void_p]
        lib.ps_keymap_len.restype = ctypes.c_int64
        lib.ps_keymap_overflowed.argtypes = [ctypes.c_void_p]
        lib.ps_keymap_assign.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib._ps_keymap_sigs = True
    return _NativeKeyMap(lib, capacity)


class Localizer:
    """Persistent global-key -> stable dense row-slot mapping.

    Streaming learners (async SGD / FTRL over an unbounded key stream) need a
    key to map to the *same* table row every time so its optimizer state
    accumulates.  The reference keeps this in the server's hash map
    (``src/parameter/kv_map.h`` :: ``KVMap`` [U]); on TPU the table is a fixed
    ``[capacity, dim]`` HBM array, so the hash lives on the host and hands the
    device dense row ids.

    When the vocabulary overflows ``capacity``, new keys hash-share rows
    (feature hashing) rather than erroring — matching large-scale CTR practice
    and the reference's countmin-based tail filtering spirit.

    The mapping is a flat open-addressing hash table (linear probing, load
    factor <= 1/2) with two interchangeable engines: the native C++ one
    (``native/src/keymap.cc``, the reference's KVMap/Localizer analogue —
    ~10-20x the old per-key dict loop) and a vectorized numpy fallback
    (windowed batch probing) for toolchain-less hosts.  A per-key Python
    dict loop was the measured host bottleneck at Criteo batch rates
    (VERDICT r1 weak #3).
    """

    #: empty bucket sentinel in the probe table (PAD_KEY never enters it —
    #: assign() short-circuits pads to the trash row first).
    _EMPTY = PAD_KEY
    #: probe window: each vectorized round inspects W consecutive buckets
    #: per key, so a linear-probe cluster walk of length L costs ceil(L/W)
    #: rounds instead of L (rounds are the Python-level cost driver).
    _W = 8

    def __init__(self, capacity: int):
        if not (0 < capacity < 2**31 - 1):
            raise ValueError("capacity must be positive and fit int32 row ids")
        self.capacity = capacity
        self._native = _native_keymap(capacity)
        if self._native is None:
            self._size = 1 << 16
            self._tkeys = np.full(self._size, self._EMPTY, dtype=np.uint64)
            self._tvals = np.zeros(self._size, dtype=np.int32)
        self._n = 0
        self._overflowed = False

    def __len__(self) -> int:
        if self._native is not None:
            return self._native.len()
        return self._n

    @property
    def overflowed(self) -> bool:
        if self._native is not None:
            return self._native.overflowed()
        return self._overflowed

    def _lookup(self, keys: np.ndarray) -> np.ndarray:
        """Vectorized windowed probe: slot for each key, -1 where absent."""
        mask = np.int64(self._size - 1)
        offs = np.arange(self._W, dtype=np.int64)
        pos = (mix64(keys) & np.uint64(mask)).astype(np.int64)
        vals = np.full(keys.shape[0], -1, dtype=np.int32)
        active = np.arange(keys.shape[0])
        while active.size:
            win = (pos[active][:, None] + offs) & mask  # [n, W]
            cur = self._tkeys[win]
            hit = cur == keys[active][:, None]
            stop = hit | (cur == self._EMPTY)  # absent iff EMPTY before hit
            stopped = stop.any(axis=1)
            first = stop.argmax(axis=1)
            rows = np.nonzero(stopped)[0]
            is_hit = hit[rows, first[rows]]
            hrows = rows[is_hit]
            vals[active[hrows]] = self._tvals[win[hrows, first[hrows]]]
            cont = active[~stopped]
            pos[cont] = (pos[cont] + self._W) & mask
            active = cont
        return vals

    def _insert(self, keys: np.ndarray, vals: np.ndarray) -> None:
        """Vectorized insert of NEW unique keys (callers grow first)."""
        mask = np.int64(self._size - 1)
        offs = np.arange(self._W, dtype=np.int64)
        pos = (mix64(keys) & np.uint64(mask)).astype(np.int64)
        remaining = np.arange(keys.shape[0])
        while remaining.size:
            win = (pos[remaining][:, None] + offs) & mask
            empty = self._tkeys[win] == self._EMPTY
            has_empty = empty.any(axis=1)
            # fully occupied window: jump that key ahead by W
            full = remaining[~has_empty]
            pos[full] = (pos[full] + self._W) & mask
            rows = np.nonzero(has_empty)[0]
            if rows.size:
                # claim each key's first empty bucket; duplicate targets
                # resolve by numpy scatter last-writer-wins, verified by
                # re-gather (keys are unique, so the winner re-reads itself).
                # Losers re-probe the SAME window next round: the bucket they
                # lost is occupied now, so they fall to a later empty slot.
                target = win[rows, empty[rows].argmax(axis=1)]
                cand = remaining[rows]
                self._tkeys[target] = keys[cand]
                self._tvals[target] = vals[cand]
                won = self._tkeys[target] == keys[cand]
                keep = np.zeros(keys.shape[0], dtype=bool)
                keep[remaining] = True
                keep[cand[won]] = False
                remaining = remaining[keep[remaining]]
            else:
                remaining = full

    def _grow_for(self, n_new: int) -> None:
        grew = False
        while (self._n + n_new) * 2 > self._size:
            self._size *= 2
            grew = True
        if grew:
            live = self._tkeys != self._EMPTY
            old_keys = self._tkeys[live]
            old_vals = self._tvals[live]
            self._tkeys = np.full(self._size, self._EMPTY, dtype=np.uint64)
            self._tvals = np.zeros(self._size, dtype=np.int32)
            if old_keys.size:
                self._insert(old_keys, old_vals)

    def assign(self, unique_keys: np.ndarray) -> np.ndarray:
        """Map unique global keys to row slots, growing the vocab as needed.

        PAD_KEY maps to slot ``capacity`` (the trash row — tables allocate
        ``capacity + 1`` rows; see ops.scatter).  Slot order matches the
        sequential first-appearance order of the old dict implementation:
        new keys get ids ``len(self)..`` in batch order.
        """
        keys = np.asarray(unique_keys, dtype=np.uint64)
        flat = keys.ravel()
        if self._native is not None:
            return self._native.assign(flat).reshape(keys.shape)
        out = np.empty(flat.shape[0], dtype=np.int32)
        is_pad = flat == PAD_KEY
        out[is_pad] = self.capacity
        real = np.nonzero(~is_pad)[0]
        rk = flat[real]
        vals = self._lookup(rk)
        missing = vals < 0
        if missing.any():
            new_keys = rk[missing]
            # dedup first (the contract says unique keys, but duplicates must
            # still share ONE slot, like the native engine / old dict — else
            # a dupe would burn an unreachable vocab row); slots are handed
            # out in first-appearance order
            uniq_new, first_idx, inv = np.unique(
                new_keys, return_index=True, return_inverse=True
            )
            arrival = np.argsort(first_idx, kind="stable")
            rank = np.empty(arrival.size, dtype=np.int64)
            rank[arrival] = np.arange(arrival.size)
            n_take = min(max(self.capacity - self._n, 0), arrival.size)
            taken = rank < n_take
            slots_u = np.empty(arrival.size, dtype=np.int32)
            slots_u[taken] = (self._n + rank[taken]).astype(np.int32)
            if n_take < arrival.size:
                # Feature-hashing fallback on overflow. Deterministic pure
                # function of the key — deliberately NOT cached, so host
                # memory stays bounded by ``capacity`` on unbounded
                # streaming key sets.
                self._overflowed = True
                slots_u[~taken] = (
                    uniq_new[~taken] % np.uint64(self.capacity)
                ).astype(np.int32)
            if n_take:
                self._grow_for(n_take)
                self._insert(uniq_new[taken], slots_u[taken])
                self._n += n_take
            vals[missing] = slots_u[inv]
        out[real] = vals
        return out.reshape(keys.shape)


def localizer_meta(loc) -> dict:
    """Reconstruction metadata for a localizer (checkpoint manifest extras).

    A checkpointed table is only servable with the SAME key->row mapping it
    was trained with (the reference writes raw key ranges so the mapping is
    the identity; here the mapping is a host-side function and must be
    recorded alongside the shards — VERDICT r2 weak #5).
    """
    meta = {"kind": type(loc).__name__, "capacity": int(loc.capacity)}
    if isinstance(loc, HashLocalizer):
        meta["seed"] = int(loc.seed)
        meta["hash_bits"] = int(loc.hash_bits)
    return meta


def localizer_from_meta(meta: dict):
    """Rebuild the key->row mapping recorded by :func:`localizer_meta`.

    Only deterministic localizers reconstruct (``HashLocalizer``,
    ``IdentityLocalizer``); the stateful :class:`Localizer` depends on key
    arrival order, which the checkpoint does not capture — pass the live
    instance (or re-stream the training keys) instead.
    """
    kind = meta.get("kind")
    if kind == "HashLocalizer":
        return HashLocalizer(
            int(meta["capacity"]),
            seed=int(meta.get("seed", 0)),
            hash_bits=int(meta.get("hash_bits", 64)),
        )
    if kind == "IdentityLocalizer":
        return IdentityLocalizer(int(meta["capacity"]))
    raise ValueError(
        f"cannot reconstruct localizer from meta {meta!r} (stateful "
        "Localizer mappings are arrival-order-dependent; pass the instance)"
    )
