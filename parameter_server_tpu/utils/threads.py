"""Thread fan-out with error propagation.

Every learner runs N worker loops in threads and must surface the first
failure to the caller instead of letting it die with the thread (Python's
default excepthook just prints).  One helper, used by the SGD, dense, and
BCD learners alike.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence


class ErrorGroup:
    """Collects exceptions from spawned threads; re-raises the first."""

    def __init__(self) -> None:
        self._errors: List[BaseException] = []
        self._lock = threading.Lock()

    def spawn(self, fn: Callable, *args, name: Optional[str] = None) -> threading.Thread:
        def guarded() -> None:
            try:
                fn(*args)
            except BaseException as e:
                with self._lock:
                    self._errors.append(e)

        t = threading.Thread(target=guarded, name=name)
        t.start()
        return t

    def check(self) -> None:
        """Raise the first recorded error, if any."""
        with self._lock:
            if self._errors:
                raise self._errors[0]


def run_threads(
    targets: Sequence[Callable[[], None]],
    *,
    name: str = "worker",
) -> None:
    """Run callables in parallel threads; join all; raise the first error."""
    group = ErrorGroup()
    threads = [
        group.spawn(fn, name=f"{name}-{i}") for i, fn in enumerate(targets)
    ]
    for t in threads:
        t.join()
    group.check()
