"""Thread fan-out with error propagation.

Every learner runs N worker loops in threads and must surface the first
failure to the caller instead of letting it die with the thread (Python's
default excepthook just prints).  One helper, used by the SGD, dense, and
BCD learners alike.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, List, Optional, Sequence


class CallbackExecutor:
    """Small shared pool of daemon threads for completion callbacks.

    ``Customer._finish_locked`` used to spawn one thread per callback;
    under high async push rates that is unbounded thread creation — a
    robustness hazard in its own right.  This executor caps the fan-out at
    ``workers`` lazily-started daemon threads feeding off one queue.

    Callbacks must not block indefinitely on OTHER callbacks (task
    completion itself is driven by Van recv threads, not this pool, so
    waiting on a task inside a callback is safe — waiting on another
    *callback* is not).
    """

    def __init__(self, workers: int = 4, name: str = "ps-callback") -> None:
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        #: pool cap (public: tests assert the fan-out stays bounded by it).
        self.workers = workers
        self._name = name
        self._lock = threading.Lock()
        self._started = 0

    def submit(self, fn: Callable, *args) -> None:
        self._q.put((fn, args))
        with self._lock:
            if self._started < self.workers:
                i = self._started
                self._started += 1
                threading.Thread(
                    target=self._run, name=f"{self._name}-{i}", daemon=True
                ).start()

    def _run(self) -> None:
        while True:
            fn, args = self._q.get()
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 — a bad callback must not kill
                # a shared pool thread
                logging.getLogger(__name__).exception(
                    "callback executor: callback raised"
                )


#: process-wide executor shared by every Customer (the "single shared
#: daemon executor" replacing thread-per-callback spawns).
CALLBACKS = CallbackExecutor()


class ErrorGroup:
    """Collects exceptions from spawned threads; re-raises the first."""

    def __init__(self) -> None:
        self._errors: List[BaseException] = []
        self._lock = threading.Lock()

    def spawn(self, fn: Callable, *args, name: Optional[str] = None) -> threading.Thread:
        def guarded() -> None:
            try:
                fn(*args)
            except BaseException as e:
                with self._lock:
                    self._errors.append(e)

        t = threading.Thread(target=guarded, name=name)
        t.start()
        return t

    def check(self) -> None:
        """Raise the first recorded error, if any."""
        with self._lock:
            if self._errors:
                raise self._errors[0]


def run_threads(
    targets: Sequence[Callable[[], None]],
    *,
    name: str = "worker",
) -> None:
    """Run callables in parallel threads; join all; raise the first error."""
    group = ErrorGroup()
    threads = [
        group.spawn(fn, name=f"{name}-{i}") for i, fn in enumerate(targets)
    ]
    for t in threads:
        t.join()
    group.check()
