"""DLRM over the mesh — BASELINE config #3."""

import numpy as np

from parameter_server_tpu.config import OptimizerConfig, TableConfig
from parameter_server_tpu.data.synthetic import SyntheticDLRM
from parameter_server_tpu.models.dlrm import SpmdDLRMTrainer
from parameter_server_tpu.parallel import mesh as mesh_lib


def _cfg(rows=1 << 14, dim=16):
    return TableConfig(
        name="emb",
        rows=rows,
        dim=dim,
        optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.05),
        init_scale=0.01,
    )


def test_dlrm_trains_on_mesh():
    mesh = mesh_lib.make_mesh((4, 2))
    data = SyntheticDLRM(key_space=1 << 14, batch_size=256, seed=0)
    trainer = SpmdDLRMTrainer(
        _cfg(),
        mesh,
        n_dense=data.n_dense,
        n_sparse=data.n_sparse,
        learning_rate=0.005,
        min_bucket=1024,
    )
    losses = [trainer.step(*data.next_batch()) for _ in range(30)]
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.02, losses[::10]


def test_dlrm_embedding_table_sharded():
    mesh = mesh_lib.make_mesh((2, 4))
    trainer = SpmdDLRMTrainer(_cfg(rows=1 << 12), mesh)
    assert len(trainer.emb_value.addressable_shards) == 8
    assert trainer.emb_value.addressable_shards[0].data.shape[0] == (
        trainer.total_rows // 4
    )
