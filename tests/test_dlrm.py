"""DLRM over the mesh — BASELINE config #3."""

import numpy as np

from parameter_server_tpu.config import OptimizerConfig, TableConfig
from parameter_server_tpu.data.synthetic import SyntheticDLRM
from parameter_server_tpu.models.dlrm import SpmdDLRMTrainer
from parameter_server_tpu.parallel import mesh as mesh_lib


def _cfg(rows=1 << 14, dim=16):
    return TableConfig(
        name="emb",
        rows=rows,
        dim=dim,
        optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.05),
        init_scale=0.01,
    )


def test_dlrm_trains_on_mesh():
    mesh = mesh_lib.make_mesh((4, 2))
    data = SyntheticDLRM(key_space=1 << 14, batch_size=256, seed=0)
    trainer = SpmdDLRMTrainer(
        _cfg(),
        mesh,
        n_dense=data.n_dense,
        n_sparse=data.n_sparse,
        learning_rate=0.005,
        min_bucket=1024,
    )
    losses = [trainer.step(*data.next_batch()) for _ in range(30)]
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.02, losses[::10]


def test_dlrm_embedding_table_sharded():
    mesh = mesh_lib.make_mesh((2, 4))
    trainer = SpmdDLRMTrainer(_cfg(rows=1 << 12), mesh)
    assert len(trainer.emb_value.addressable_shards) == 8
    assert trainer.emb_value.addressable_shards[0].data.shape[0] == (
        trainer.total_rows // 4
    )


def test_dlrm_16m_rows_rows_mode_memory_and_step():
    """2^24-row table trains rows-mode: per-step temp memory O(batch),
    never O(table) (the billion-row scaling argument, VERDICT r2 #5).

    Asserted from XLA's compiled memory analysis: the train step's temp
    allocation must be far below the table size — dense-fused would move
    the whole 128 MB value (+state) per step.
    """
    import jax
    import jax.numpy as jnp

    from parameter_server_tpu.utils.keys import localize_to_slots

    rows = 1 << 24
    mesh = mesh_lib.make_mesh((2, 4))
    cfg = TableConfig(
        name="emb",
        rows=rows,
        dim=2,
        optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.05),
        init_scale=0.0,  # zeros init: no O(table) random temp at setup
    )
    data = SyntheticDLRM(key_space=1 << 30, batch_size=128, seed=1)
    trainer = SpmdDLRMTrainer(
        cfg, mesh, n_dense=data.n_dense, n_sparse=data.n_sparse,
        min_bucket=1024,
    )
    keys, dense, labels = data.next_batch()
    # repeat one batch: after a few steps the loss must be below the start
    # (single-step comparisons are adam-warmup noise)
    rep = [trainer.step(keys, dense, labels) for _ in range(5)]
    assert np.isfinite(rep).all()
    assert rep[-1] < rep[0], rep

    # compiled-step temp memory: O(batch-rows), a small fraction of table
    slots, inverse, _ = localize_to_slots(
        keys, trainer.localizer, min_bucket=1024
    )
    args = (
        trainer.emb_value, trainer.emb_state, trainer.mlp_params,
        trainer.opt_state, jnp.asarray(slots), jnp.asarray(inverse),
        jnp.asarray(dense), jnp.asarray(labels),
    )
    ma = trainer._step.lower(*args).compile().memory_analysis()
    table_bytes = trainer.emb_value.nbytes * (1 + len(trainer.emb_state))
    assert ma.temp_size_in_bytes < table_bytes / 8, (
        ma.temp_size_in_bytes, table_bytes,
    )


def test_tail_filter_masks_rare_keys_and_trainer_still_learns():
    """Count-min tail filter on the input stream (DARLIN preprocess role):
    rare keys mask to PAD, frequent keys survive, DLRM still trains."""
    from parameter_server_tpu.data.tailfilter import TailFilteredStream
    from parameter_server_tpu.utils.keys import PAD_KEY

    data = SyntheticDLRM(key_space=1 << 20, batch_size=256, seed=2)
    # zipf-ify: square the stream keys onto a narrow head + long tail
    rng = np.random.default_rng(3)

    def batch_fn():
        keys, dense, labels = data.next_batch()
        head = rng.integers(0, 64, size=keys.shape, dtype=np.uint64)
        tail = rng.integers(0, 1 << 40, size=keys.shape, dtype=np.uint64)
        use_head = rng.random(keys.shape) < 0.7
        return np.where(use_head, head, tail), dense, labels

    stream = TailFilteredStream(batch_fn, threshold=3)
    mesh = mesh_lib.make_mesh((4, 2))
    trainer = SpmdDLRMTrainer(
        _cfg(rows=1 << 14), mesh, n_dense=data.n_dense,
        n_sparse=data.n_sparse, learning_rate=0.005, min_bucket=1024,
    )
    losses = []
    for _ in range(20):
        keys, dense, labels = stream()
        losses.append(trainer.step(keys, dense, labels))
    # the one-shot tail got masked; the head survived
    assert 0.05 < stream.masked_fraction < 0.6, stream.masked_fraction
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


def test_tail_filter_never_drops_frequent_keys():
    from parameter_server_tpu.data.tailfilter import TailFilteredStream
    from parameter_server_tpu.utils.keys import PAD_KEY

    frequent = np.arange(1, 9, dtype=np.uint64)

    def batch_fn():
        return (np.tile(frequent, (4, 1)),)

    stream = TailFilteredStream(batch_fn, threshold=2)
    stream()  # first sight: counts reach 4 per key (>= threshold)
    (keys2,) = stream()
    np.testing.assert_array_equal(keys2, np.tile(frequent, (4, 1)))
    # PAD positions pass through untouched and are not counted
    def batch_fn_pad():
        k = np.tile(frequent, (4, 1))
        k[:, -1] = PAD_KEY
        return (k,)

    stream2 = TailFilteredStream(batch_fn_pad, threshold=1)
    (out,) = stream2()
    assert (out[:, -1] == PAD_KEY).all()
    assert stream2.seen == 4 * 7


def test_dlrm_feasibility_aot_never_materializes():
    """The billion-row AOT path (VERDICT r4 #3) at test scale: compile the
    REAL step from ShapeDtypeStructs on the 8-dev mesh and read XLA's
    per-device memory — table bytes must dominate and fit the budget."""
    from parameter_server_tpu.parallel.feasibility import dlrm_feasibility

    out = dlrm_feasibility(
        rows_log2=18, dim=16, mesh_shape=(1, 8), batch=256, slots_log2=10
    )
    assert out["fits_v5e"] is True
    # value + adagrad state, row-sharded 8 ways
    assert out["table_bytes_per_device"] == 2 * ((1 << 18) + 8) * 16 * 4 // 8
    assert out["peak_bytes"] >= out["table_bytes_per_device"]
    # temps are O(batch), not O(table): far below one table shard
    assert out["temp_bytes"] < out["table_bytes_per_device"]


def test_init_sharded_table_zeros_matches_layout():
    """kind="zeros" must produce the same sharded layout/state fills as the
    gaussian init (only the value distribution differs)."""
    import jax

    from parameter_server_tpu.kv.optim import make_optimizer
    from parameter_server_tpu.models.dlrm import init_sharded_table

    cfg = TableConfig(
        name="emb", rows=1 << 10, dim=8, init_scale=0.01,
        optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.05),
    )
    mesh = mesh_lib.make_mesh((1, 8))
    opt = make_optimizer(cfg.optimizer)
    total = ((cfg.rows + 1 + 7) // 8) * 8
    vz, sz = init_sharded_table(cfg, mesh, opt, total, kind="zeros")
    vn, sn = init_sharded_table(cfg, mesh, opt, total, kind="normal")
    assert vz.sharding == vn.sharding and vz.shape == vn.shape
    assert float(jax.numpy.abs(vz).max()) == 0.0
    # the gaussian twin really drew values (nonzero init_scale): the kind
    # dispatch is observable, only the distribution differs
    assert float(jax.numpy.abs(vn[: cfg.rows]).max()) > 0.0
    for k in sz:
        np.testing.assert_array_equal(np.asarray(sz[k]), np.asarray(sn[k]))
