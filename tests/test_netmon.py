"""MeteredVan wire accounting (core/netmon.py).

Acceptance anchor: over a 2-worker/2-server cluster on the full
``MeteredVan(ReliableVan(ChaosVan(LoopbackVan())))`` stack, the meter's
per-link byte counters must EXACTLY equal the sum of each message's
keys/values nbytes — ground-truthed by an independent probe wrapper ABOVE
the meter, so retransmits/ACKs/dups in the layers below cannot contaminate
the logical counts.
"""

import time

import numpy as np

from parameter_server_tpu.config import OptimizerConfig, TableConfig
from parameter_server_tpu.core.chaos import ChaosVan
from parameter_server_tpu.core.messages import Message, Task, TaskKind
from parameter_server_tpu.core.netmon import (
    STAMP_KEY,
    MeteredVan,
    find_metered,
    payload_nbytes,
)
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core.resender import ReliableVan
from parameter_server_tpu.core.van import LoopbackVan, VanWrapper
from parameter_server_tpu.kv.server import KVServer
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.utils.metrics import transport_counters

NUM_SERVERS = 2
ROWS = 1 << 10


def _settle(predicate, deadline_s=5.0):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


class ProbeVan(VanWrapper):
    """Independent byte ground truth, stacked ABOVE the meter: counts each
    logical message's keys+values nbytes per directed link."""

    def __init__(self, inner):
        super().__init__(inner)
        self.bytes = {}
        self.msgs = {}

    def send(self, msg):
        link = f"{msg.sender}->{msg.recver}"
        nb = 0
        if msg.keys is not None:
            nb += int(np.asarray(msg.keys).nbytes)
        for v in msg.values:
            nb += int(np.asarray(v).nbytes)
        self.bytes[link] = self.bytes.get(link, 0) + nb
        self.msgs[link] = self.msgs.get(link, 0) + 1
        return self.inner.send(msg)


def _table_cfgs():
    return {
        "w": TableConfig(
            name="w", rows=ROWS, dim=2,
            optimizer=OptimizerConfig(kind="sgd", learning_rate=0.1),
        )
    }


def test_single_message_bytes_exact():
    van = MeteredVan(LoopbackVan())
    try:
        got = []
        van.bind("B", got.append)
        keys = np.arange(10, dtype=np.int64)
        vals = np.ones((10, 4), np.float32)
        msg = Message(
            task=Task(TaskKind.PUSH, "kv", payload={"table": "w"}),
            sender="A", recver="B", keys=keys, values=[vals],
        )
        assert payload_nbytes(msg) == keys.nbytes + vals.nbytes
        assert van.send(msg)
        assert _settle(lambda: len(got) == 1)
        links = van.links()
        assert links["A->B"]["msgs"] == 1
        assert links["A->B"]["bytes"] == keys.nbytes + vals.nbytes
        c = van.counters()
        assert c["wire_msgs"] == 1
        assert c["wire_bytes"] == keys.nbytes + vals.nbytes
        assert c["wire_links"] == 1
        assert c["wire_undeliverable"] == 0
        # the monotonic stamp is stripped before the handler sees the message
        assert STAMP_KEY not in got[0].task.payload
        assert got[0].task.payload["table"] == "w"
    finally:
        van.close()


def test_deliver_latency_recorded_and_nonnegative():
    van = MeteredVan(LoopbackVan())
    try:
        van.bind("B", lambda m: None)
        for _ in range(5):
            van.send(
                Message(task=Task(TaskKind.CONTROL, "x"),
                        sender="A", recver="B")
            )
        assert _settle(
            lambda: van.links()["A->B"]["deliver"]["count"] == 5
        )
        d = van.links()["A->B"]
        assert d["send"]["count"] == 5
        assert d["deliver"]["max_s"] >= 0.0
    finally:
        van.close()


def test_stamp_false_disables_deliver_histogram():
    van = MeteredVan(LoopbackVan(), stamp=False)
    try:
        got = []
        van.bind("B", got.append)
        van.send(
            Message(task=Task(TaskKind.CONTROL, "x"), sender="A", recver="B")
        )
        assert _settle(lambda: len(got) == 1)
        d = van.links()["A->B"]
        assert d["msgs"] == 1
        assert d["deliver"]["count"] == 0  # no stamp, no latency
    finally:
        van.close()


def test_undeliverable_counted():
    van = MeteredVan(LoopbackVan())
    try:
        msg = Message(
            task=Task(TaskKind.CONTROL, "x"), sender="A", recver="NOWHERE"
        )
        assert not van.send(msg)  # inner send fails: no such endpoint
        assert van.counters()["wire_undeliverable"] == 1
        assert van.counters()["wire_msgs"] == 1  # still counted as traffic
    finally:
        van.close()


def test_find_metered_walks_wrapper_stack():
    metered = MeteredVan(ChaosVan(LoopbackVan()))
    stack = ProbeVan(metered)
    try:
        assert find_metered(stack) is metered
        assert find_metered(LoopbackVan()) is None
    finally:
        stack.close()


def test_node_digests_report_only_originated_links():
    van = MeteredVan(LoopbackVan())
    try:
        van.bind("A", lambda m: None)
        van.bind("B", lambda m: None)
        van.send(Message(task=Task(TaskKind.CONTROL, "x"),
                         sender="A", recver="B"))
        van.send(Message(task=Task(TaskKind.CONTROL, "x"),
                         sender="B", recver="A"))
        assert _settle(lambda: van.counters()["wire_links"] == 2)
        assert set(van.node_digests("A")) == {"A->B"}
        assert set(van.node_digests("B")) == {"B->A"}
    finally:
        van.close()


def test_cluster_bytes_exact_over_metered_reliable_chaos_stack():
    """Acceptance (a): 2 workers x 2 servers over the full observability
    stack — per-link byte counters exactly equal the sum of message
    keys/values nbytes.  Chaos runs drop+dup BELOW the meter (latency 0,
    per the chaos determinism ground rules), so the wire repairs itself
    while the logical per-link accounting stays byte-exact."""
    chaos = ChaosVan(LoopbackVan(), seed=2, drop=0.1, duplicate=0.1)
    reliable = ReliableVan(
        chaos, timeout=0.05, backoff=1.0, max_retries=60, seed=2
    )
    metered = MeteredVan(reliable)
    van = ProbeVan(metered)  # ground truth ABOVE the meter
    try:
        cfgs = _table_cfgs()
        servers = [
            KVServer(Postoffice(f"S{s}", van), cfgs, s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        ]
        workers = [
            KVWorker(Postoffice(f"W{w}", van), cfgs, NUM_SERVERS,
                     min_bucket=16)
            for w in range(2)
        ]
        rng = np.random.default_rng(0)
        for _ in range(4):
            for w in workers:
                keys = rng.integers(0, ROWS, size=48).astype(np.uint64)
                grads = rng.standard_normal((48, 2)).astype(np.float32)
                assert w.wait(w.push("w", keys, grads), timeout=30)
                w.pull_sync("w", keys, timeout=30)
        assert van.flush(10)  # every send acked; wire quiescent
        links = metered.links()
        assert set(links) == set(van.bytes)
        for link, truth in van.bytes.items():
            assert links[link]["bytes"] == truth, link
            assert links[link]["msgs"] == van.msgs[link], link
        assert chaos.injected_drops + chaos.injected_dups > 0
        # worker->server links carry the key+grad tensors; byte-positive
        assert links["W0->S0"]["bytes"] > 0
        merged = transport_counters(van)
        assert merged["wire_bytes"] == sum(van.bytes.values())
        assert merged["wire_msgs"] == sum(van.msgs.values())
        assert merged["retransmits"] >= 0  # resender layer merged in
        assert merged["chaos_drops"] == chaos.injected_drops
        del servers
    finally:
        van.close()


def test_reply_leg_has_no_stale_stamp_latency():
    """msg.reply() shares the Task: the meter must strip its stamp on
    receive, or the response leg would record send->reply time-travel.
    Deliver latencies on the reply link must therefore be small and
    non-negative (not the full request round trip)."""
    van = MeteredVan(LoopbackVan())
    try:
        cfgs = _table_cfgs()
        KVServer(Postoffice("S0", van), cfgs, 0, 1)
        worker = KVWorker(Postoffice("W0", van), cfgs, 1, min_bucket=16)
        keys = np.arange(20, dtype=np.uint64)
        for _ in range(3):
            assert worker.wait(
                worker.push("w", keys, np.ones((20, 2), np.float32)),
                timeout=30,
            )
        assert _settle(
            lambda: van.links().get("S0->W0", {"deliver": {"count": 0}})[
                "deliver"]["count"] >= 3
        )
        reply = van.links()["S0->W0"]["deliver"]
        assert reply["count"] >= 3
        assert reply["max_s"] >= 0.0
    finally:
        van.close()


def test_clock_offset_corrects_deliver_latency():
    """Cross-host deliver latency embeds sender clock skew; a registered
    per-sender offset (sender monotonic minus local) is added back so the
    histogram reads true one-way latency.  Simulated here by registering a
    fake +250 ms skew on a zero-latency loopback link: the corrected
    deliver readings must all land near +250 ms."""
    van = MeteredVan(LoopbackVan())
    try:
        van.bind("B", lambda m: None)
        van.set_clock_offset("A", 0.25)
        for _ in range(5):
            van.send(
                Message(task=Task(TaskKind.CONTROL, "x"),
                        sender="A", recver="B")
            )
        assert _settle(
            lambda: van.links()["A->B"]["deliver"]["count"] == 5
        )
        d = van.links()["A->B"]["deliver"]
        assert d["max_s"] >= 0.2  # raw ~0 + 0.25 correction
        # clearing the offset stops the correction for later frames
        van.set_clock_offset("A", 0.0)
        van.send(
            Message(task=Task(TaskKind.CONTROL, "x"), sender="A", recver="B")
        )
        assert _settle(
            lambda: van.links()["A->B"]["deliver"]["count"] == 6
        )
    finally:
        van.close()
