"""ApplyLedger (ISSUE 12): retire-exactly-once accounting, donation
censoring, backlog edges + ``__busy__`` backpressure, reaper lifecycle,
and the deterministic backlog-breach e2e (ledger -> telemetry -> SLO ->
pstop) the device-plane observability layer promises.

The ledger's contract is bookkeeping-only on the ack path (the AST half
lives in ``tools/check_wrappers.py::LEDGER_SYNC_FREE_FUNCS``); these tests
pin the BEHAVIORAL half: acks land while the device apply is provably
still running, and every submitted apply retires exactly once even under
seeded retransmission/duplication chaos.
"""

import json
import pathlib
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from parameter_server_tpu.config import (
    LedgerConfig,
    OptimizerConfig,
    TableConfig,
)
from parameter_server_tpu.core import flightrec
from parameter_server_tpu.core.chaos import ChaosVan
from parameter_server_tpu.core.coalesce import CoalescingVan
from parameter_server_tpu.core.messages import Message, Task, TaskKind
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core.resender import ReliableVan
from parameter_server_tpu.core.telemetry import (
    TelemetryAggregator,
    TelemetryPublisher,
)
from parameter_server_tpu.core.van import LoopbackVan
from parameter_server_tpu.kv.ledger import ApplyLedger
from parameter_server_tpu.kv.server import KVServer
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.utils.slo import SloEngine, device_plane_specs

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1] / "tools"))
import pstop  # noqa: E402

DIM = 4
ROWS = 64

#: fast reaper degraded-mode cadence for fake (non-jax) refs, which lack
#: ``block_until_ready`` and so push the reaper onto its polling fallback.
_FAST = dict(reap_interval_s=0.002, idle_stop_s=0.2)


class _Ref:
    """Controllable stand-in for a dispatched jax result array."""

    def __init__(self, ready=False, dead=False):
        self.ready = ready
        self.dead = dead

    def is_ready(self):
        if self.dead:
            raise RuntimeError("buffer donated away")
        return self.ready


def _drained(ledger, timeout=5.0):
    assert ledger.drain(timeout), ledger.counters()


# ------------------------------------------------------------ unit: ledger


def test_submit_retires_exactly_once_with_attribution_digests():
    rec = flightrec.FlightRecorder(capacity=64)
    led = ApplyLedger("S0", LedgerConfig(**_FAST), recorder=rec)
    try:
        tok = led.begin("w", members=2, rows=12)
        tok.mark_host()
        tok.mark_h2d()
        ref = _Ref(ready=False)
        led.submit(tok, ref, fallback=lambda: ref)
        c = led.counters()
        assert c["inflight_bundles"] == 1 and c["inflight_rows"] == 12
        assert c["applies_submitted"] == 1 and c["applies_retired"] == 0
        ref.ready = True
        _drained(led)
        c = led.counters()
        assert c["inflight_bundles"] == 0 and c["inflight_rows"] == 0
        assert c["applies_retired"] == 1 and c["applies_censored"] == 0
        digs = led.latency_digests()
        assert set(digs) == {
            "apply.w", "apply_host.w", "apply_h2d.w", "apply_dev.w"
        }
        assert all(d["count"] == 1 for d in digs.values())
        kinds = [e["kind"] for e in rec.events()]
        assert kinds == ["apply.submit", "apply.done"]
        done = rec.events()[-1]
        assert done["rows"] == 12 and done["members"] == 2
        assert done["ms"] >= done["host_ms"] >= 0
    finally:
        led.close()


def test_donated_ref_retires_via_fallback_and_is_censored():
    led = ApplyLedger("S0", LedgerConfig(**_FAST))
    try:
        tok = led.begin("w", 1, 4)
        led.submit(tok, _Ref(dead=True), fallback=lambda: _Ref(ready=True))
        _drained(led)
        c = led.counters()
        assert c["applies_retired"] == 1 and c["applies_censored"] == 1
    finally:
        led.close()


def test_backlog_edge_events_and_overloaded_level():
    rec = flightrec.FlightRecorder(capacity=64)
    led = ApplyLedger(
        "S0", LedgerConfig(backlog_bundles=2, **_FAST), recorder=rec
    )
    try:
        refs = [_Ref() for _ in range(3)]
        for r in refs:
            led.submit(led.begin("w", 1, 1), r, fallback=lambda r=r: r)
        assert led.overloaded()  # 3 > 2: level-triggered hint is up
        edges = [e for e in rec.events() if e["kind"] == "apply.backlog"]
        assert [e["state"] for e in edges] == ["enter"]  # edge, not level
        assert edges[0]["inflight_bundles"] == 3
        for r in refs:
            r.ready = True
        _drained(led)
        assert not led.overloaded()
        edges = [e for e in rec.events() if e["kind"] == "apply.backlog"]
        assert [e["state"] for e in edges] == ["enter", "clear"]
    finally:
        led.close()


def test_reaper_self_stops_when_idle_and_restarts_on_submit():
    led = ApplyLedger("S0", LedgerConfig(**_FAST))
    try:
        led.submit(led.begin("w", 1, 1), _Ref(ready=True), lambda: None)
        _drained(led)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            reaper = led._reaper
            if reaper is None or not reaper.is_alive():
                break
            time.sleep(0.01)
        else:
            pytest.fail("reaper did not self-stop after idle_stop_s")
        led.submit(led.begin("w", 1, 1), _Ref(ready=True), lambda: None)
        _drained(led)
        assert led.counters()["applies_retired"] == 2
    finally:
        led.close()


# ------------------------------------------- behavioral: sync-free + ledger


def _entangle_fn():
    """Jitted identity whose output depends on ~300 ms of device work the
    compiler cannot elide (0.0 * finite is exact-zero but data-dependent),
    so 'did anything wait for the device?' is directly observable."""

    @jax.jit
    def entangle(v):
        z = jnp.full((1300, 1300), jnp.float32(1e-3)) + v[0, 0]
        for _ in range(6):
            z = jnp.tanh(z @ z)
        return v + 0.0 * z[: v.shape[0], : v.shape[1]]

    return entangle


def _slow_table(tbl):
    """Entangle every apply on ``tbl`` into ~300 ms of device work, keeping
    the push return value (the ledger's readiness ref) on the slow chain."""
    entangle = _entangle_fn()
    orig_push, orig_batch = tbl.push, tbl.push_batch

    def slow_push(ids, vals):
        orig_push(ids, vals)
        tbl.value = entangle(tbl.value)
        return tbl.value

    def slow_push_batch(ids, positions, vals):
        orig_batch(ids, positions, vals)
        tbl.value = entangle(tbl.value)
        return tbl.value

    tbl.push, tbl.push_batch = slow_push, slow_push_batch


def _push_msg(rng, n=5):
    ids = np.sort(rng.choice(np.arange(ROWS), size=n, replace=False))
    vals = rng.normal(size=(n, DIM)).astype(np.float32)
    return Message(
        task=Task(TaskKind.PUSH, "kv", payload={"table": "w"}),
        sender="W0",
        recver="S0",
        keys=np.asarray(ids, dtype=np.int32),
        values=[vals.reshape(-1, DIM)],
    )


def test_ack_lands_while_ledger_entry_still_in_flight():
    """The sync-free contract WITH the ledger attached: the push ack
    returns while ``is_ready()`` is still False AND the ledger still
    carries the apply in flight — registration happened on the ack path
    without observing the device, retirement strictly after."""
    van = LoopbackVan()
    try:
        cfg = TableConfig(
            name="w", rows=ROWS, dim=DIM,
            optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.1),
        )
        srv = KVServer(Postoffice("S0", van), {"w": cfg}, 0, 1)
        assert srv.ledger is not None  # on by default
        tbl = srv.tables["w"]
        _slow_table(tbl)
        rng = np.random.default_rng(8)

        srv.handle_request(_push_msg(rng))  # warm-up: compile apply+entangle
        jax.block_until_ready(tbl.value)
        _drained(srv.ledger)
        c0 = srv.ledger.counters()

        t0 = time.perf_counter()
        reply = srv.handle_request(_push_msg(rng))
        ack_s = time.perf_counter() - t0
        assert "__error__" not in reply.task.payload
        assert not tbl.value.is_ready(), "ack waited for the device apply"
        c1 = srv.ledger.counters()
        assert c1["applies_submitted"] == c0["applies_submitted"] + 1
        assert c1["applies_retired"] == c0["applies_retired"]  # not yet
        assert c1["inflight_bundles"] == 1

        jax.block_until_ready(tbl.value)
        device_s = time.perf_counter() - t0
        assert ack_s < device_s, (ack_s, device_s)
        _drained(srv.ledger)
        c2 = srv.ledger.counters()
        assert c2["applies_retired"] == c2["applies_submitted"]
        assert c2["inflight_bundles"] == 0
    finally:
        van.close()


# --------------------------------------------------- e2e: chaos accounting


def test_every_apply_retires_exactly_once_under_seeded_chaos():
    """Full production stack — coalesced bundles, retransmission over
    seeded drop/duplication chaos, grouped device applies — and the
    ledgers still balance: every submitted apply retires exactly once, no
    entry leaks, no entry double-retires (inflight would go negative and
    retired would overshoot submitted)."""
    chaos = ChaosVan(LoopbackVan(), seed=2, drop=0.05, duplicate=0.05)
    rel = ReliableVan(chaos, timeout=0.05, backoff=1.0, max_retries=60, seed=2)
    van = CoalescingVan(rel)
    try:
        cfgs = {
            "w": TableConfig(
                name="w", rows=1 << 10, dim=DIM,
                optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.1),
            )
        }
        servers = [
            KVServer(Postoffice(f"S{s}", van), cfgs, s, 2) for s in range(2)
        ]
        worker = KVWorker(Postoffice("W0", van), cfgs, 2)
        rng = np.random.default_rng(11)
        for _ in range(3):
            pool = rng.choice(1 << 10, size=96, replace=False).astype(np.uint32)
            k1, k2 = np.sort(pool[:64]), np.sort(pool[32:])
            g1 = rng.normal(size=(64, DIM)).astype(np.float32)
            g2 = rng.normal(size=(64, DIM)).astype(np.float32)
            with worker.coalesce_window():
                t1 = worker.push("w", k1, g1)
                t2 = worker.push("w", k2, g2)
            assert worker.wait(t1, timeout=60) and worker.wait(t2, timeout=60)
        assert van.flush(30)
        assert chaos.injected_drops + chaos.injected_dups > 0
        for srv in servers:
            _drained(srv.ledger, timeout=15.0)
            c = srv.ledger.counters()
            assert c["applies_submitted"] > 0
            assert c["applies_retired"] == c["applies_submitted"], c
            assert c["inflight_bundles"] == 0 and c["inflight_rows"] == 0, c
    finally:
        van.close()


# ------------------------------------- e2e: backlog breach, busy, pstop


def test_backlog_breach_fires_live_slo_busy_hints_and_pstop(
    tmp_path, capsys
):
    """The ISSUE-12 acceptance walk: a slow-apply server drives its
    backlog over the device-plane SLO bound; the live stream fires
    ``slo.breach``, the server stamps ``__busy__`` into acks (worker sees
    the hint), and the breach shows up in both ``pstop.snapshot()`` and
    the ``--json`` CLI output over the aggregator's JSONL spill — then
    everything clears once the device catches up.

    Slowness is injected at the ledger's own seam: the monkeypatched push
    returns a gate ref whose readiness the test controls, so the backlog
    depth is exact (real device chains throttle in the CPU dispatch queue
    and cap the pile-up nondeterministically).  The ack path underneath
    stays the real one — real applies, real replies, real busy stamps."""
    flightrec.configure(clear=True)
    rec = flightrec.FlightRecorder(capacity=256)
    van = LoopbackVan()
    try:
        cfgs = {
            "w": TableConfig(
                name="w", rows=ROWS, dim=DIM,
                optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.1),
            )
        }
        srv = KVServer(
            Postoffice("S0", van), cfgs, 0, 1,
            devobs=LedgerConfig(enabled=True, backlog_bundles=2, **_FAST),
        )
        worker = KVWorker(Postoffice("W0", van), cfgs, 1)
        tbl = srv.tables["w"]
        orig_push, gates = tbl.push, []

        def gated_push(ids, vals):
            orig_push(ids, vals)
            gates.append(_Ref(ready=False))
            return gates[-1]

        tbl.push = gated_push

        path = str(tmp_path / "telemetry.jsonl")
        eng = SloEngine(
            device_plane_specs("w", apply_p99_ms=1e9, backlog_bundles=2),
            recorder=rec,
        )
        agg = TelemetryAggregator(slo=eng, jsonl_path=path)
        pub = TelemetryPublisher("S0", van, sources=[srv])
        rng = np.random.default_rng(3)
        keys = np.sort(
            rng.choice(ROWS, size=8, replace=False)
        ).astype(np.uint32)

        def push():
            g = rng.standard_normal((8, DIM)).astype(np.float32)
            assert worker.wait(worker.push("w", keys, g), timeout=60)

        push()  # healthy phase: one apply, retired immediately
        gates[-1].ready = True
        _drained(srv.ledger)
        agg.ingest("S0", pub.frame())
        assert eng.healthy("S0")
        assert worker.busy_hints == 0

        # acks keep landing while nothing retires — the backlog climbs
        # deterministically past the bound of 2
        for _ in range(4):
            push()
        assert srv.ledger.counters()["inflight_bundles"] == 4
        assert srv.ledger.overloaded()
        assert worker.busy_hints > 0, "ack never carried the __busy__ hint"
        assert worker.server_busy("S0")

        agg.ingest("S0", pub.frame())  # the live stream carries the gauge
        assert not eng.healthy("S0")
        breaches = [e for e in rec.events() if e["kind"] == "slo.breach"]
        assert breaches and breaches[0]["slo"] == "apply-backlog"
        assert breaches[0]["node"] == "S0"

        latest = pstop.load_rows(path)
        snap = pstop.snapshot(latest)
        assert snap["breached"] == ["S0"]
        assert snap["nodes"]["S0"]["counters"]["inflight_bundles"] == 4
        assert "BREACH:apply-backlog" in "\n".join(pstop.render(latest))
        assert pstop.main(["--json", "--once", path]) == 0
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert doc["breached"] == ["S0"]

        # open the gates: everything retires, SLO clears on the next
        # frame, acks stop carrying the hint
        for g in gates:
            g.ready = True
        _drained(srv.ledger)
        assert not srv.ledger.overloaded()
        agg.ingest("S0", pub.frame())
        assert eng.healthy("S0")
        assert [e["kind"] for e in rec.events()].count("slo.clear") == 1
        hints_before = worker.busy_hints
        push()
        gates[-1].ready = True
        _drained(srv.ledger)
        assert worker.busy_hints == hints_before
    finally:
        van.close()
        flightrec.configure(clear=True)
