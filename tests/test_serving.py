"""Read-heavy serving plane (ISSUE 13): hot-row cache, read-only PULL
fast path, and SLO-driven admission control.

Four layers under test:

1. :class:`HotRowCache` unit semantics — version-clock freshness, owner
   binding, collision eviction, the batched probe, the audit trail;
2. ``KVWorker.pull_serve`` end-to-end against ``pull_sync`` ground truth,
   including the server's ``__ro__`` fast path bitwise contract;
3. the bounded-staleness CHAOS acceptance: under drop/duplicate/delay and
   a live shard migration, no cached read is ever staler than the
   worker's observed ``__sver__`` watermark;
4. admission control: a deterministic overload flips
   ``SloEngine.healthy()`` false and reads shed within one telemetry
   beat, visible as ``serve.shed`` + ``slo.breach`` flight-recorder
   events — plus the three shed policies and the serving telemetry
   columns (pstop RO/S, HIT%, SHED/S) and the bench_gate regression gate.
"""

import pathlib
import subprocess
import sys
import time

import numpy as np
import pytest

from parameter_server_tpu.config import (
    OptimizerConfig,
    ServeConfig,
    TableConfig,
)
from parameter_server_tpu.core import flightrec
from parameter_server_tpu.core.chaos import ChaosVan
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core.resender import ReliableVan
from parameter_server_tpu.core.telemetry import TelemetryAggregator
from parameter_server_tpu.core.van import LoopbackVan
from parameter_server_tpu.kv.cache import HotRowCache
from parameter_server_tpu.kv.migrate import ShardMigrator
from parameter_server_tpu.kv.server import KVServer
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.serve.admission import AdmissionController, ShedError
from parameter_server_tpu.serve.loadgen import LoadGenerator
from parameter_server_tpu.utils.slo import SloEngine, serving_plane_specs

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1] / "tools"))
import bench_gate  # noqa: E402
import pstop  # noqa: E402

ROWS = 1 << 10
DIM = 4
NUM_SERVERS = 2


def _table_cfgs():
    return {
        "w": TableConfig(
            name="w", rows=ROWS, dim=DIM,
            optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.1),
        )
    }


def _cluster(van, *, cache=None):
    servers = [
        KVServer(Postoffice(f"S{s}", van), _table_cfgs(), s, NUM_SERVERS)
        for s in range(NUM_SERVERS)
    ]
    worker = KVWorker(
        Postoffice("W0", van), _table_cfgs(), NUM_SERVERS, cache=cache
    )
    return servers, worker


# ------------------------------------------------------------ 1. cache unit


def test_cache_hit_then_watermark_invalidation():
    c = HotRowCache(64, audit=True)
    row = np.arange(DIM, dtype=np.float32)
    c.insert("w", np.array([7]), row[None, :], sver=3, server="S0")
    c.observe("w", "S0", 3)
    got = c.lookup("w", 7, "S0")
    np.testing.assert_array_equal(got, row)
    assert c.hits == 1 and c.misses == 0
    # a fresher write anywhere on the shard advances the watermark past
    # the entry's stamp: the entry dies lazily at the next probe
    c.observe("w", "S0", 5)
    assert c.lookup("w", 7, "S0") is None
    assert c.invalidations == 1 and c.misses == 1
    # audit invariant holds for the one hit that was served
    assert c.audit == [("w", 7, 3, 3)]


def test_cache_watermark_is_monotone_and_insert_never_regresses():
    c = HotRowCache(64)
    c.observe("w", "S0", 9)
    c.observe("w", "S0", 4)  # reordered reply: no-op
    assert c.watermark("w", "S0") == 9
    fresh = np.full((1, DIM), 2.0, np.float32)
    stale = np.full((1, DIM), 1.0, np.float32)
    c.insert("w", np.array([3]), fresh, sver=10, server="S0")
    c.insert("w", np.array([3]), stale, sver=9, server="S0")  # late reply
    got = c.lookup("w", 3, "S0")
    np.testing.assert_array_equal(got, fresh[0])


def test_cache_owner_mismatch_misses_before_any_epoch_adoption():
    """Migration safety: entries remember their source server, so a row
    whose range moved misses immediately — even before the worker clears
    the cache on routing adoption."""
    c = HotRowCache(64)
    c.insert("w", np.array([5]), np.ones((1, DIM), np.float32), 1, "S1")
    assert c.lookup("w", 5, "S0") is None  # S0 owns it now -> dead entry
    assert c.invalidations == 1


def test_cache_collision_eviction_bounds_memory():
    c = HotRowCache(4)  # 4 lines: keys 1 and 5 share line 1
    c.insert("w", np.array([1]), np.full((1, DIM), 1.0, np.float32), 1, "S0")
    c.insert("w", np.array([5]), np.full((1, DIM), 5.0, np.float32), 1, "S0")
    assert c.lookup("w", 1, "S0") is None  # evicted by the collision
    np.testing.assert_array_equal(
        c.lookup("w", 5, "S0"), np.full(DIM, 5.0, np.float32)
    )
    assert len(c) == 1


def test_lookup_many_matches_scalar_semantics():
    c = HotRowCache(64, audit=True)
    keys = np.array([1, 2, 3])
    rows = np.arange(3 * DIM, dtype=np.float32).reshape(3, DIM)
    c.insert("w", keys, rows, sver=2, server="S0")
    c.insert("w", np.array([3]), rows[2:], sver=2, server="S1")  # moved row
    code0 = c.server_code("S0")
    slots = np.array([1, 2, 3, 9], dtype=np.int64)
    hit, hit_rows = c.lookup_many(
        "w", slots, np.full(4, code0, dtype=np.int32)
    )
    assert hit.tolist() == [True, True, False, False]
    np.testing.assert_array_equal(hit_rows, rows[:2])
    # key 3 was owned by S1 in-cache but probed for S0: lazily evicted
    assert c.invalidations == 1
    assert c.hits == 2 and c.misses == 2
    assert [a[:2] for a in c.audit] == [("w", 1), ("w", 2)]
    assert all(sv >= wm for _, _, sv, wm in c.audit)


def test_lookup_stale_ignores_freshness_and_invalidate_all_keeps_wm():
    c = HotRowCache(64)
    c.insert("w", np.array([2]), np.ones((1, DIM), np.float32), 1, "S0")
    c.observe("w", "S0", 99)
    got = c.lookup_stale("w", 2)
    assert got is not None
    row, sver = got
    np.testing.assert_array_equal(row, np.ones(DIM, np.float32))
    assert sver == 1
    dropped = c.invalidate_all(reason="test")
    assert dropped == 1 and len(c) == 0
    assert c.watermark("w", "S0") == 99  # watermarks shadow server clocks


# --------------------------------------------- 2. pull_serve / __ro__ e2e


def test_pull_serve_matches_pull_sync_cold_warm_and_after_write():
    van = LoopbackVan()
    try:
        cache = HotRowCache(1 << 11, node="W0")
        _servers, worker = _cluster(van, cache=cache)
        rng = np.random.default_rng(0)
        keys = rng.choice(ROWS, size=256, replace=False).astype(np.int64)
        worker.push_sync(
            "w", np.sort(keys),
            rng.normal(size=(keys.size, DIM)).astype(np.float32), timeout=60,
        )
        # duplicates + unsorted order + a second dimensionality
        probe = np.concatenate([keys[:64][::-1], keys[:9]])
        ref = worker.pull_sync("w", probe, timeout=60)
        cold = worker.pull_serve("w", probe, timeout=60)  # all misses
        np.testing.assert_array_equal(cold, ref)
        warm = worker.pull_serve("w", probe, timeout=60)  # all hits
        np.testing.assert_array_equal(warm, ref)
        assert cache.hits > 0
        # a write invalidates through the PIGGYBACKED watermark: the very
        # next serve re-fetches instead of serving the dead entries
        worker.push_sync(
            "w", np.sort(keys[:64]),
            np.ones((64, DIM), np.float32), timeout=60,
        )
        after = worker.pull_serve("w", probe, timeout=60)
        np.testing.assert_array_equal(
            after, worker.pull_sync("w", probe, timeout=60)
        )
        batch2d = keys[:32].reshape(4, 8)
        np.testing.assert_array_equal(
            worker.pull_serve("w", batch2d, timeout=60),
            worker.pull_sync("w", batch2d, timeout=60),
        )
    finally:
        van.close()


def test_read_only_fast_path_is_bitwise_equal_and_instrumented():
    van = LoopbackVan()
    try:
        servers, worker = _cluster(van)
        rng = np.random.default_rng(1)
        keys = np.sort(rng.choice(ROWS, size=512, replace=False)).astype(
            np.int64
        )
        worker.push_sync(
            "w", keys, rng.normal(size=(keys.size, DIM)).astype(np.float32),
            timeout=60,
        )
        normal = worker.pull_sync("w", keys, timeout=60)
        ro = worker.pull_result(
            worker.pull("w", keys, read_only=True), timeout=60
        )
        np.testing.assert_array_equal(normal, ro)
        assert sum(s.ro_pulls for s in servers) > 0
        assert any("ro_pull.w" in s.latency_digests() for s in servers)
    finally:
        van.close()


# ---------------------------- 3. bounded staleness under chaos + migration


@pytest.mark.chaos
def test_bounded_staleness_under_chaos_with_live_migration():
    """The serving-plane acceptance invariant: across drop/duplicate/delay
    chaos, interleaved writes, and a LIVE shard migration, every cache hit
    served a row stamped at or above the worker's observed ``__sver__``
    watermark for the owning server — and the final serve agrees with the
    ground-truth RPC pull."""
    chaos = ChaosVan(LoopbackVan(), seed=3, drop=0.2, duplicate=0.2,
                     delay=0.01)
    van = ReliableVan(
        chaos, timeout=0.05, backoff=1.0, max_retries=120, seed=3
    )
    try:
        cache = HotRowCache(1 << 11, node="W0", audit=True)
        _servers, worker = _cluster(van, cache=cache)
        mig = ShardMigrator(Postoffice("M0", van), chunk_rows=128)
        rng = np.random.default_rng(7)
        hot = np.sort(rng.choice(ROWS, size=96, replace=False)).astype(
            np.int64
        )
        worker.push_sync(
            "w", hot, rng.normal(size=(hot.size, DIM)).astype(np.float32),
            timeout=60,
        )
        for step in range(10):
            # serve twice back-to-back: the second is the hit-path serve
            # (the write below advances the shard clock and — by design —
            # conservatively invalidates everything cached from it)
            worker.pull_serve("w", hot, timeout=60)
            worker.pull_serve("w", hot, timeout=60)
            # dirty a rotating subset: versions advance, watermarks follow
            sub = hot[step % 3 :: 3]
            worker.push_sync(
                "w", sub,
                rng.normal(size=(sub.size, DIM)).astype(np.float32),
                timeout=60,
            )
            if step == 5:
                # live migration: move the tail half of S1's range to S0
                new_routing = mig.migrate(
                    worker.routing, "w", ROWS - ROWS // 4, ROWS, 0
                )
                assert worker.adopt_routing(new_routing)
        final = worker.pull_serve("w", hot, timeout=60)
        np.testing.assert_array_equal(
            final, worker.pull_sync("w", hot, timeout=60)
        )
        assert chaos.injected_drops > 0  # the chaos actually did something
        assert cache.hits > 0 and cache.audit
        staler = [
            (t, k, sv, wm) for t, k, sv, wm in cache.audit if sv < wm
        ]
        assert not staler, f"cached reads staler than watermark: {staler[:5]}"
    finally:
        van.close()


# ---------------------------------------------------- 4. admission control


def test_slo_breach_sheds_within_one_beat_and_recovers():
    flightrec.configure(enabled=True, clear=True)
    van = LoopbackVan()
    try:
        cache = HotRowCache(1 << 11, node="W0")
        _servers, worker = _cluster(van, cache=cache)
        keys = np.arange(32, dtype=np.int64)
        worker.push_sync(
            "w", keys, np.ones((keys.size, DIM), np.float32), timeout=60
        )
        eng = SloEngine(serving_plane_specs("w", backlog_bundles=2))
        adm = AdmissionController(
            worker, healthy=lambda: eng.healthy("S0"), node="W0"
        )
        t0 = 100.0
        eng.observe("S0", "inflight_bundles", 0.0, now=t0)
        eng.evaluate(now=t0)
        assert adm.pull("w", keys, timeout=60).shape == (keys.size, DIM)
        # deterministic overload: backlog gauge above the armed limit —
        # ONE evaluate beat later the gate is shut
        eng.observe("S0", "inflight_bundles", 16.0, now=t0 + 1.0)
        eng.evaluate(now=t0 + 1.0)
        with pytest.raises(ShedError) as ei:
            adm.pull("w", keys, timeout=60)
        assert ei.value.retry_after_s == adm.cfg.retry_after_s
        assert adm.serve_shed == 1
        kinds = [e["kind"] for e in flightrec.get().events()]
        assert "slo.breach" in kinds and "serve.shed" in kinds
        # recovery: the breaching sample ages out of the window, the next
        # beat clears the breach, reads flow again
        eng.observe("S0", "inflight_bundles", 0.0, now=t0 + 30.0)
        eng.evaluate(now=t0 + 30.0)
        assert adm.pull("w", keys, timeout=60).shape == (keys.size, DIM)
        assert "slo.clear" in [e["kind"] for e in flightrec.get().events()]
    finally:
        van.close()
        flightrec.configure(enabled=True, clear=True)


def test_busy_hint_alone_trips_admission():
    van = LoopbackVan()
    try:
        _servers, worker = _cluster(van, cache=HotRowCache(64))
        adm = AdmissionController(worker, node="W0")
        assert not adm.overloaded("w")
        # a live __busy__ hint from an owner of "w" is a local overload
        # signal needing no SLO feed (stamp what the reply tap would)
        with worker._staleness_lock:
            worker._busy_last["S1"] = time.monotonic()
        assert adm.overloaded("w")
        with pytest.raises(ShedError):
            adm.pull("w", np.arange(4, dtype=np.int64))
    finally:
        van.close()


def test_stale_policy_serves_cached_rows_and_sheds_uncached():
    van = LoopbackVan()
    try:
        cache = HotRowCache(1 << 11, node="W0")
        _servers, worker = _cluster(van, cache=cache)
        keys = np.arange(16, dtype=np.int64)
        worker.push_sync(
            "w", keys, np.ones((keys.size, DIM), np.float32), timeout=60
        )
        ref = worker.pull_sync("w", keys, timeout=60)
        worker.pull_serve("w", keys, timeout=60)  # warm the cache
        adm = AdmissionController(
            worker, healthy=lambda: False, node="W0",
            cfg=ServeConfig(policy="stale"),
        )
        got = adm.pull("w", keys)  # degraded but answered
        np.testing.assert_array_equal(got, ref)
        assert adm.serve_stale == 1
        with pytest.raises(ShedError):
            adm.pull("w", np.arange(900, 910, dtype=np.int64))  # not cached
        assert adm.serve_shed == 1
    finally:
        van.close()


def test_queue_policy_waits_for_health_then_serves_or_sheds():
    van = LoopbackVan()
    try:
        cache = HotRowCache(1 << 11, node="W0")
        _servers, worker = _cluster(van, cache=cache)
        keys = np.arange(8, dtype=np.int64)
        worker.push_sync(
            "w", keys, np.ones((keys.size, DIM), np.float32), timeout=60
        )
        calls = {"n": 0}

        def healthy_after_three():
            calls["n"] += 1
            return calls["n"] > 3

        adm = AdmissionController(
            worker, healthy=healthy_after_three, node="W0",
            cfg=ServeConfig(policy="queue", queue_deadline_s=2.0,
                            queue_poll_s=0.001),
        )
        got = adm.pull("w", keys, timeout=60)
        assert got.shape == (keys.size, DIM)
        assert adm.serve_queue_waits == 1 and adm.serve_shed == 0
        adm_down = AdmissionController(
            worker, healthy=lambda: False, node="W0",
            cfg=ServeConfig(policy="queue", queue_deadline_s=0.02,
                            queue_poll_s=0.001),
        )
        with pytest.raises(ShedError):
            adm_down.pull("w", keys)
    finally:
        van.close()


# ------------------------------------------------------------- 5. loadgen


def test_loadgen_is_open_loop_seeded_and_counts_sheds():
    seen: list = []

    def record_pull(table, keys):
        seen.append(np.asarray(keys).copy())
        if len(seen) % 2 == 0:
            raise ShedError("drill", 0.01)

    gen = LoadGenerator(
        record_pull, table="w", num_keys=ROWS, keys_per_pull=4,
        clients=1000, per_client_qps=0.05, zipf_s=1.1, seed=11,
    )
    assert gen.qps == pytest.approx(50.0)
    rep = gen.run(0.3)
    assert rep.pulls == rep.served + rep.shed and rep.pulls == len(seen)
    assert rep.shed == rep.pulls // 2
    assert rep.shed_rate == round(rep.shed / rep.pulls, 4)
    # same seed -> the identical offered request sequence (open loop is
    # scheduled up front, independent of service-time feedback)
    seen2: list = []
    LoadGenerator(
        lambda t, k: seen2.append(np.asarray(k).copy()), table="w",
        num_keys=ROWS, keys_per_pull=4, clients=1000, per_client_qps=0.05,
        zipf_s=1.1, seed=11,
    ).run(0.3)
    assert len(seen2) == len(seen)
    for a, b in zip(seen, seen2):
        np.testing.assert_array_equal(a, b)


# --------------------------------------- 6. telemetry columns + pstop/gate


def test_aggregator_derives_serving_rates_and_pstop_renders_them():
    agg = TelemetryAggregator()
    agg.ingest("W0", {
        "seq": 1, "t_mono_s": 100.0,
        "counters": {"ro_pulls": 0, "serve_shed": 0,
                     "cache_hits": 0, "cache_misses": 0},
    }, now=100.0)
    agg.ingest("W0", {
        "seq": 2, "t_mono_s": 102.0,
        "counters": {"ro_pulls": 120, "serve_shed": 6,
                     "cache_hits": 90, "cache_misses": 30},
    }, now=102.0)
    row = agg.latest()["W0"]
    assert row["ro_per_s"] == pytest.approx(60.0)
    assert row["shed_per_s"] == pytest.approx(3.0)
    assert row["cache_hit_pct"] == pytest.approx(75.0)
    lines = pstop.render(agg.latest())
    assert "RO/S" in lines[0] and "HIT%" in lines[0] and "SHED/S" in lines[0]
    assert "60.0" in lines[1] and "75.0" in lines[1] and "3.0" in lines[1]
    snap = pstop.snapshot(agg.latest())
    assert snap["nodes"]["W0"]["ro_per_s"] == pytest.approx(60.0)
    # a node with no serving traffic renders placeholders, not zeros
    agg2 = TelemetryAggregator()
    agg2.ingest("S0", {"seq": 1, "t_mono_s": 1.0}, now=1.0)
    assert "ro_per_s" not in agg2.latest()["S0"]
    assert pstop.render(agg2.latest())[1].count(" -") >= 3


def _baseline_block(ms: float) -> str:
    return (
        "# baseline\n\n"
        "<!-- BENCH-SERVE:BEGIN -->\n"
        "| path | p50 |\n|---|---|\n"
        f"| hot hit ms | {ms} |\n"
        "<!-- BENCH-SERVE:END -->\n"
    )


def _git(repo, *args):
    subprocess.run(
        ["git", *args], cwd=repo, check=True, capture_output=True
    )


def test_bench_gate_fails_regressions_with_escape_hatch(
    tmp_path, monkeypatch
):
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "config", "user.email", "t@example.com")
    _git(tmp_path, "config", "user.name", "t")
    md = tmp_path / "BASELINE.md"
    md.write_text(_baseline_block(20.0))
    _git(tmp_path, "add", "BASELINE.md")
    _git(tmp_path, "commit", "-qm", "baseline")
    monkeypatch.setattr(bench_gate, "_REPO", tmp_path)
    assert bench_gate.main([]) == 0  # identical tree: clean
    md.write_text(_baseline_block(30.0))  # ms metric: +50% is a regression
    assert bench_gate.main(["--fail-over", "10"]) == 1
    monkeypatch.setenv("PS_BENCH_REBASE", "1")  # the sanctioned escape hatch
    assert bench_gate.main(["--fail-over", "10"]) == 0
    monkeypatch.delenv("PS_BENCH_REBASE")
    md.write_text(_baseline_block(15.0))  # improvement: clean
    assert bench_gate.main(["--fail-over", "10"]) == 0
    assert bench_gate.main(["--baseline", "no-such-rev"]) == 2
