"""Pipeline parallelism: GPipe microbatch pipeline over the pp mesh axis.

Completes the parallelism inventory (SURVEY §2 deferred PP).  The pipeline
must be EXACT: the scanned ppermute schedule computes the same function as
applying the stages sequentially, losses match to float tolerance, and
training through reverse-AD of the pipeline converges.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from parameter_server_tpu.models import transformer as tfm
from parameter_server_tpu.parallel.pp import PipelinedLMTrainer


def _pp_mesh(n=4):
    devices = jax.devices()[:n]
    return Mesh(np.asarray(devices), ("pp",))


def _cfg():
    return tfm.tiny_config(causal=True)  # 2 layers


def _tokens(cfg, rng, batch=8, seq=16):
    base = rng.integers(0, cfg.vocab_size, size=(batch, 1))
    offs = np.arange(seq)[None, :]
    return ((base + offs) % cfg.vocab_size).astype(np.int32)


def _sequential_loss(trainer, tokens):
    """Oracle: same params, stages applied in order, no pipeline."""
    cfg = trainer.cfg
    micro = tokens.reshape(
        trainer.n_micro, tokens.shape[0] // trainer.n_micro, tokens.shape[1]
    )
    stages_host = jax.device_get(trainer.stage_params)
    embed = jax.device_get(trainer.embed)
    head = jax.device_get(trainer.head)
    norm = jax.device_get(trainer.norm)
    losses = []
    for mb in micro:
        x = jnp.asarray(embed)[jnp.asarray(mb)]
        for s in range(trainer.n_stages):
            params_s = jax.tree.map(lambda a: jnp.asarray(a[s]), stages_host)
            x = trainer.stage_module.apply({"params": params_s}, x)
        x = trainer.norm_module.apply({"params": jax.tree.map(jnp.asarray, norm)}, x)
        logits = jnp.einsum("bsd,dv->bsv", x, jnp.asarray(head))
        losses.append(tfm.causal_lm_loss(logits, jnp.asarray(mb)))
    return float(jnp.mean(jnp.asarray(losses)))


@pytest.mark.parametrize("n_stages,n_layers", [(2, 2), (4, 4)])
def test_pipeline_matches_sequential(n_stages, n_layers):
    cfg = tfm.tiny_config(causal=True, n_layers=n_layers)
    mesh = _pp_mesh(n_stages)
    trainer = PipelinedLMTrainer(cfg, mesh, n_micro=4, seed=1)
    rng = np.random.default_rng(0)
    tokens = _tokens(cfg, rng)
    got = trainer.loss(tokens)
    want = _sequential_loss(trainer, tokens)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_pipeline_trains():
    cfg = tfm.tiny_config(causal=True, n_layers=4)
    mesh = _pp_mesh(4)
    trainer = PipelinedLMTrainer(cfg, mesh, n_micro=4, learning_rate=3e-3)
    rng = np.random.default_rng(2)
    losses = [trainer.step(_tokens(cfg, rng)) for _ in range(12)]
    assert np.isfinite(losses).all()
    assert np.mean(losses[-3:]) < np.mean(losses[:3]) - 0.1, losses


def test_pipeline_stage_weights_are_sharded():
    cfg = tfm.tiny_config(causal=True, n_layers=4)
    mesh = _pp_mesh(4)
    trainer = PipelinedLMTrainer(cfg, mesh, n_micro=4)
    leaf = jax.tree.leaves(trainer.stage_params)[0]
    assert leaf.shape[0] == 4  # stage axis
    # one stage per device, not replicated
    assert len(leaf.addressable_shards) == 4
    assert leaf.addressable_shards[0].data.shape[0] == 1


def test_pipeline_rejects_bad_shapes():
    cfg = tfm.tiny_config(causal=True, n_layers=2)  # 2 layers, 4 stages
    with pytest.raises(ValueError, match="n_layers"):
        PipelinedLMTrainer(cfg, _pp_mesh(4), n_micro=2)
    # learned positional embeddings are stage-0-only state: unsupported
    bert_like = tfm.tiny_config(causal=False, n_layers=2)
    with pytest.raises(ValueError, match="rotary"):
        PipelinedLMTrainer(bert_like, _pp_mesh(2), n_micro=2)
    mesh = _pp_mesh(2)
    # microbatch stack shards over pp: n_micro must split across stages
    with pytest.raises(ValueError, match="n_micro"):
        PipelinedLMTrainer(cfg, mesh, n_micro=3)
    trainer = PipelinedLMTrainer(cfg, mesh, n_micro=4)
    with pytest.raises(ValueError, match="n_micro"):
        trainer.step(np.zeros((9, 16), np.int32))  # 9 % 2 != 0


def test_pipeline_gradients_match_sequential():
    """Backward exactness: reverse-AD through the scanned ppermute pipeline
    must produce the SAME gradients as the sequential stage application —
    forward parity alone would not catch a corrupted cotangent route."""
    cfg = tfm.tiny_config(causal=True, n_layers=2)
    mesh = _pp_mesh(2)
    trainer = PipelinedLMTrainer(cfg, mesh, n_micro=2, seed=3)
    rng = np.random.default_rng(4)
    tokens = _tokens(cfg, rng, batch=4, seq=8)
    micro = jnp.asarray(trainer._micro(tokens))
    params = trainer._params()

    pipe_grads = jax.grad(trainer._loss)(params, micro)

    def seq_loss(p):
        losses = []
        for mb in micro:
            x = p["embed"][mb]
            for s in range(trainer.n_stages):
                ps = jax.tree.map(lambda a: a[s], p["stages"])
                x = trainer.stage_module.apply({"params": ps}, x)
            x = trainer.norm_module.apply({"params": p["norm"]}, x)
            logits = jnp.einsum("bsd,dv->bsv", x, p["head"])
            losses.append(tfm.causal_lm_loss(logits, mb))
        return jnp.mean(jnp.asarray(losses))

    host = jax.device_get(params)
    seq_grads = jax.grad(seq_loss)(jax.tree.map(jnp.asarray, host))
    for pg, sg in zip(jax.tree.leaves(pipe_grads), jax.tree.leaves(seq_grads)):
        np.testing.assert_allclose(
            np.asarray(pg), np.asarray(sg), rtol=1e-4, atol=1e-5
        )


def test_pipeline_opt_state_stays_pp_sharded():
    """Adam moments for the stage stack must be pp-sharded from init —
    replicating them would cost 2x the full stack per device."""
    cfg = tfm.tiny_config(causal=True, n_layers=4)
    trainer = PipelinedLMTrainer(cfg, _pp_mesh(4), n_micro=4)
    mu = jax.tree.leaves(trainer.opt_state[0].mu["stages"])[0]
    assert mu.addressable_shards[0].data.shape[0] == 1  # 1 of 4 stages


def test_pipeline_per_device_memory_is_bounded_by_m_over_s_model():
    """VERDICT r3 #8: the injection/output buffers are pp-sharded (O(M/S)
    per device, was O(M) replicated) and the tick body is rematerialized.
    Assert XLA's compiled per-device temps against the analytic budget:
    2 x (M/S) microbatch buffers + (M+S-1) remat-saved tick inputs + a
    working-set allowance — a regression that re-replicates the stack or
    drops remat blows through the 3x headroom."""
    cfg = tfm.tiny_config(
        causal=True, n_layers=4, d_model=256, max_seq=256, vocab_size=512
    )
    S, M, mb, seq = 4, 16, 4, 256
    mesh = _pp_mesh(S)
    trainer = PipelinedLMTrainer(cfg, mesh, n_micro=M)
    micro = jnp.zeros((M, mb, seq), jnp.int32)
    ma = (
        trainer._loss.lower(trainer._params(), micro).compile()
        .memory_analysis()
    )
    act = mb * seq * cfg.d_model * 4  # one microbatch activation, f32
    logits_mb = mb * seq * cfg.vocab_size * 4
    budget = (
        2 * (M // S) * act  # x stack + out_buf shards
        + (M + S - 1) * 2 * act  # remat-saved tick inputs (fwd+bwd pair)
        + (M // S) * logits_mb * 2  # local head logits + softmax copy
        + 16 * act  # per-tick working set allowance
    )
    assert ma.temp_size_in_bytes <= 3 * budget, (
        ma.temp_size_in_bytes,
        budget,
    )


def test_pipeline_bubble_amortizes_with_microbatches():
    """GPipe bubble model: per-example step time ~ (M+S-1)/M at fixed
    microbatch size.  S=4: M=4 -> 1.75, M=16 -> 1.19 — raising M must cut
    per-example time measurably (the table VERDICT r3 #8 asked for prints
    to the log; the assert keeps only the robust monotonic claim)."""
    import time

    cfg = tfm.tiny_config(causal=True, n_layers=4, d_model=128, max_seq=64)
    S, mb, seq = 4, 2, 64
    mesh = _pp_mesh(S)
    rng = np.random.default_rng(11)
    rows = []
    for M in (4, 16):
        trainer = PipelinedLMTrainer(cfg, mesh, n_micro=M, seed=2)
        tokens = _tokens(cfg, rng, batch=M * mb, seq=seq)
        micro = jnp.asarray(trainer._micro(tokens))
        params = trainer._params()
        trainer._loss(params, micro)  # compile
        t0 = time.perf_counter()
        reps = 3
        for _ in range(reps):
            jax.block_until_ready(trainer._loss(params, micro))
        per_example = (time.perf_counter() - t0) / reps / (M * mb)
        rows.append((M, per_example, (M + S - 1) / M))
        print(
            f"pp bubble: M={M} per-example={per_example * 1e3:.3f} ms "
            f"(model {(M + S - 1) / M:.2f}x ideal)"
        )
    # M=16 has 1.19x bubble vs M=4's 1.75x: per-example time must drop
    assert rows[1][1] < rows[0][1], rows


def test_pipeline_composes_with_dp():
    """DP x PP on one (data, pp) mesh: same math as pure PP, batch rows
    sharded over data, loss/grads allreduced — the composability the module
    docstring promises, tested rather than asserted."""
    from jax.sharding import Mesh as _Mesh

    cfg = tfm.tiny_config(causal=True, n_layers=4)
    devices = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh_dp_pp = _Mesh(devices, ("data", "pp"))
    rng = np.random.default_rng(7)
    tokens = _tokens(cfg, rng, batch=8, seq=16)

    dp_pp = PipelinedLMTrainer(cfg, mesh_dp_pp, n_micro=4, seed=5)
    pure = PipelinedLMTrainer(cfg, _pp_mesh(4), n_micro=4, seed=5)
    np.testing.assert_allclose(
        dp_pp.loss(tokens), pure.loss(tokens), rtol=2e-5, atol=2e-5
    )
    # and it trains
    losses = [dp_pp.step(_tokens(cfg, rng)) for _ in range(8)]
    assert np.isfinite(losses).all()
    assert np.mean(losses[-2:]) < np.mean(losses[:2])


def test_1f1b_matches_gpipe_trajectory():
    """schedule="1f1b" (manual interleaved backward) must produce the SAME
    training trajectory as the AD-through-scan GPipe schedule — identical
    math, different tick order (VERDICT r4 #9)."""
    cfg = tfm.tiny_config(
        causal=True, tie_embeddings=False, n_layers=4, n_kv_heads=4
    )
    mesh = _pp_mesh(4)
    rng = np.random.default_rng(0)
    toks = [_tokens(cfg, rng) for _ in range(3)]
    tg = PipelinedLMTrainer(cfg, mesh, n_micro=8, seed=0)
    t1 = PipelinedLMTrainer(cfg, mesh, n_micro=8, seed=0, schedule="1f1b")
    lg = [tg.step(t) for t in toks]
    l1 = [t1.step(t) for t in toks]
    np.testing.assert_allclose(lg, l1, rtol=2e-5, atol=1e-6)


def test_1f1b_composes_with_dp():
    """DP x PP with the manual 1F1B backward: the embedding gradient must
    carry the data-pmean scaling (a sum-scatter of per-replica dx would be
    n_data x too large — caught in review), so the trajectory must equal
    GPipe's on the same (data, pp) mesh and stream."""
    cfg = tfm.tiny_config(
        causal=True, tie_embeddings=False, n_layers=4, n_kv_heads=4
    )
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("data", "pp"))
    rng = np.random.default_rng(0)
    toks = [_tokens(cfg, rng, batch=16) for _ in range(3)]
    tg = PipelinedLMTrainer(cfg, mesh, n_micro=8, seed=0)
    t1 = PipelinedLMTrainer(cfg, mesh, n_micro=8, seed=0, schedule="1f1b")
    lg = [tg.step(t) for t in toks]
    l1 = [t1.step(t) for t in toks]
    np.testing.assert_allclose(lg, l1, rtol=2e-5, atol=1e-6)


def test_1f1b_memory_is_microbatch_independent():
    """1F1B's point: compiled temp memory stays ~flat as M grows (O(S)
    stash) while GPipe's saved residuals grow O(M).  Measured via XLA's
    own memory analysis of the compiled steps."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from parameter_server_tpu.parallel import pp as pp_lib

    cfg = tfm.tiny_config(
        causal=True, tie_embeddings=False, n_layers=4, n_kv_heads=4,
        d_model=128, d_ff=256, max_seq=128,
    )
    mesh = _pp_mesh(4)

    def temps(schedule, n_micro):
        step, _l, stage_module, norm_module, tx = pp_lib.make_pp_step(
            cfg, mesh, schedule=schedule
        )
        x0 = jnp.zeros((1, 8, cfg.d_model), jnp.float32)
        st_shapes = jax.eval_shape(
            lambda k: jax.vmap(
                lambda kk: stage_module.init(kk, x0)["params"]
            )(k),
            jax.ShapeDtypeStruct((4, 2), jnp.uint32),
        )
        st_shard = pp_lib.stage_sharding(mesh, st_shapes)
        repl = NamedSharding(mesh, P())
        params = {
            "stages": jax.tree.map(
                lambda s, sh: jax.ShapeDtypeStruct(
                    s.shape, s.dtype, sharding=sh
                ),
                st_shapes, st_shard,
            ),
            "embed": jax.ShapeDtypeStruct(
                (cfg.vocab_size, cfg.d_model), jnp.float32, sharding=repl
            ),
            "head": jax.ShapeDtypeStruct(
                (cfg.d_model, cfg.vocab_size), jnp.float32, sharding=repl
            ),
            "norm": jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(
                    s.shape, s.dtype, sharding=repl
                ),
                jax.eval_shape(
                    lambda: norm_module.init(
                        jax.random.PRNGKey(0), x0
                    )["params"]
                ),
            ),
        }
        import optax

        param_shardings = {
            "stages": st_shard,
            "embed": repl,
            "head": repl,
            "norm": jax.tree.map(lambda _: repl, params["norm"]),
        }
        opt = optax.tree_map_params(
            tx,
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            jax.eval_shape(tx.init, params),
            param_shardings,
        )
        tok = jax.ShapeDtypeStruct(
            (n_micro, 2, 128), jnp.int32,
            sharding=NamedSharding(mesh, P("pp")),
        )
        with mesh:
            c = step.lower(params, opt, tok).compile()
        return int(c.memory_analysis().temp_size_in_bytes)

    g_ratio = temps("gpipe", 32) / temps("gpipe", 8)
    f_ratio = temps("1f1b", 32) / temps("1f1b", 8)
    # measured: ~2.4x vs ~1.2x; margins generous against XLA version drift
    assert g_ratio > 1.7, g_ratio
    assert f_ratio < 1.45, f_ratio
    assert f_ratio < g_ratio - 0.4, (f_ratio, g_ratio)


def test_pp_composes_with_tp():
    """PP x TP (r5): stage weights shard over BOTH the stage and model
    axes via the partial-manual shard_map (only pp manual, model stays
    GSPMD) — same trajectory as the pp-only pipeline."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from parameter_server_tpu.parallel import pp as pp_lib

    cfg = tfm.tiny_config(
        causal=True, tie_embeddings=False, n_layers=4, n_kv_heads=2
    )

    def build(mesh, tp, schedule="gpipe"):
        step, _l, stage_module, norm_module, tx = pp_lib.make_pp_step(
            cfg, mesh, tp=tp, schedule=schedule
        )
        x0 = jnp.zeros((1, 8, cfg.d_model), jnp.float32)
        keys = jax.random.split(jax.random.PRNGKey(0), 2)
        init = lambda k: jax.vmap(  # noqa: E731
            lambda kk: stage_module.init(kk, x0)["params"]
        )(k)
        sh = pp_lib.stage_sharding(mesh, jax.eval_shape(init, keys), tp=tp)
        with mesh:
            stages = jax.jit(init, out_shardings=sh)(keys)
        repl = NamedSharding(mesh, P())
        rngs = jax.random.split(jax.random.PRNGKey(9), 3)
        params = {
            "stages": stages,
            "embed": jax.device_put(
                (jax.random.normal(rngs[0], (cfg.vocab_size, cfg.d_model))
                 * 0.02).astype(jnp.float32), repl),
            "head": jax.device_put(
                (jax.random.normal(rngs[1], (cfg.d_model, cfg.vocab_size))
                 * 0.02).astype(jnp.float32), repl),
            "norm": jax.device_put(
                norm_module.init(rngs[2], x0)["params"], repl),
        }
        return step, params, tx.init(params), mesh

    mesh_tp = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                   ("pp", "model"))
    mesh_pp = Mesh(np.asarray(jax.devices()[:2]), ("pp",))
    step_tp, p_tp, o_tp, _ = build(mesh_tp, True)
    step_1, p_1, o_1, _ = build(mesh_pp, False)
    toks = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(4, 1, 16)
    ).astype(np.int32)
    with mesh_tp:
        p_tp, o_tp, l_tp = step_tp(
            p_tp, o_tp,
            jax.device_put(jnp.asarray(toks),
                           NamedSharding(mesh_tp, P("pp"))),
        )
    with mesh_pp:
        p_1, o_1, l_1 = step_1(
            p_1, o_1,
            jax.device_put(jnp.asarray(toks),
                           NamedSharding(mesh_pp, P("pp"))),
        )
    np.testing.assert_allclose(float(l_tp), float(l_1), rtol=2e-5)
    # the TP sharding is real: a q kernel carries BOTH axes
    q_spec = str(
        p_tp["stages"]["Block_0"]["attn"]["q"]["kernel"].sharding.spec
    )
    assert "pp" in q_spec and "model" in q_spec, q_spec
    # ... and the manual-backward schedule composes with TP identically
    step_f, p_f, o_f, _ = build(mesh_tp, True, schedule="1f1b")
    with mesh_tp:
        _, _, l_f = step_f(
            p_f, o_f,
            jax.device_put(jnp.asarray(toks),
                           NamedSharding(mesh_tp, P("pp"))),
        )
    np.testing.assert_allclose(float(l_f), float(l_1), rtol=2e-5)
