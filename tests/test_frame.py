"""Flat wire frames (core/frame.py — ISSUE 7 tentpole).

Three layers of coverage:

1. **Codec unit matrix** — dtype round-trips (f32/f16/bf16/int8/bool/
   int64), empty values, keys=None, 0-row planes, oversized meta, and the
   typed-rejection contract: truncated buffers, garbled headers, and
   corrupted planes all raise :class:`FrameError`, never a bare struct/
   unicode error escaping on a recv thread.
2. **Header semantics** — transport stamps (``__rseq__``/``__rinc__``/
   ``__repoch__``/``__rcrc__``) lift into fixed header fields readable via
   :func:`frame.peek` alone (header-only dedup/fencing) and reinstate
   bitwise on decode; ``frame_nbytes`` sizes frames exactly without
   building them.
3. **Acceptance e2e** — LR training rides the REAL frame bytes
   (``FrameCodecVan`` under the full Coalesce+Metered+Reliable+Chaos
   stack) with seeded drop/duplication/corruption and a live mid-run
   migration: loss trajectory bitwise-equal to a clean run, exactly-once
   push accounting, corrupt frames caught by the resender's end-to-end
   CRC now carried in the header.
"""

import time

import numpy as np
import pytest

import jax.numpy as jnp
import ml_dtypes

from parameter_server_tpu.config import OptimizerConfig, TableConfig
from parameter_server_tpu.core import frame
from parameter_server_tpu.core.chaos import ChaosVan
from parameter_server_tpu.core.coalesce import CoalescingVan
from parameter_server_tpu.core.frame import FrameCodecVan, FrameError
from parameter_server_tpu.core.messages import (
    INCARNATION_KEY,
    Message,
    NodeRole,
    Task,
    TaskKind,
)
from parameter_server_tpu.core.netmon import MeteredVan
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core import resender as resender_mod
from parameter_server_tpu.core.resender import ReliableVan, payload_crc32
from parameter_server_tpu.core.van import LoopbackVan
from parameter_server_tpu.data.synthetic import SyntheticCTR
from parameter_server_tpu.kv import routing as routing_mod
from parameter_server_tpu.kv.migrate import ShardMigrator
from parameter_server_tpu.kv.server import KVServer
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.models import linear

ROWS = 1 << 10
NUM_SERVERS = 2
STEPS = 12


def _msg(**kw):
    defaults = dict(
        task=Task(TaskKind.PUSH, "t", payload={"table": "w"}),
        sender="W0",
        recver="S0",
        keys=np.arange(10, dtype=np.uint64),
        values=[np.arange(40, dtype=np.float32).reshape(10, 4)],
        is_request=True,
    )
    defaults.update(kw)
    return Message(**defaults)


def _roundtrip(msg):
    return frame.decode(frame.encode(msg))


def _assert_messages_equal(a: Message, b: Message):
    assert a.task.kind == b.task.kind
    assert a.task.customer == b.task.customer
    assert a.task.time == b.task.time
    assert a.task.wait_time == b.task.wait_time
    assert a.task.payload == b.task.payload
    assert a.sender == b.sender and a.recver == b.recver
    assert a.is_request == b.is_request
    if a.keys is None:
        assert b.keys is None
    else:
        assert a.keys.dtype == b.keys.dtype
        np.testing.assert_array_equal(a.keys, b.keys)
    assert len(a.values) == len(b.values)
    for va, vb in zip(a.values, b.values):
        assert va.dtype == vb.dtype and va.shape == vb.shape
        np.testing.assert_array_equal(
            np.asarray(va).view(np.uint8), np.asarray(vb).view(np.uint8)
        )


# ------------------------------------------------------- codec unit matrix


@pytest.mark.parametrize(
    "dtype",
    [
        np.float32,
        np.float16,
        ml_dtypes.bfloat16,
        np.int8,
        np.bool_,
        np.int64,
    ],
    ids=["f32", "f16", "bf16", "int8", "bool", "int64"],
)
def test_value_dtype_roundtrip(dtype):
    rng = np.random.default_rng(0)
    raw = rng.standard_normal((6, 3))
    vals = (raw > 0) if dtype is np.bool_ else raw.astype(dtype)
    msg = _msg(values=[np.ascontiguousarray(vals)])
    _assert_messages_equal(msg, _roundtrip(msg))


def test_empty_values_and_no_keys():
    msg = _msg(keys=None, values=[])
    got = _roundtrip(msg)
    _assert_messages_equal(msg, got)
    info = frame.peek(frame.encode(msg))
    assert info.n_arrays == 0 and info.planes_len == 0
    assert not info.flags & frame.FLAG_HAS_KEYS


def test_zero_row_plane_roundtrip():
    msg = _msg(
        keys=np.empty(0, dtype=np.uint64),
        values=[np.empty((0, 4), dtype=np.float32)],
    )
    got = _roundtrip(msg)
    _assert_messages_equal(msg, got)
    assert got.values[0].shape == (0, 4)


def test_scalar_plane_promotes_like_seed_codec():
    """0-d arrays frame as shape (1,) — np.ascontiguousarray's promotion,
    identical to the pickle codec this replaced (parity, not regression)."""
    got = _roundtrip(_msg(keys=None, values=[np.float32(3.5)]))
    assert got.values[0].shape == (1,)
    assert got.values[0][0] == np.float32(3.5)


def test_oversized_meta_roundtrip():
    msg = _msg(
        task=Task(
            TaskKind.CONTROL,
            "t",
            payload={"blob": "x" * 300_000, "ints": list(range(5000))},
        ),
        keys=None,
        values=[],
    )
    _assert_messages_equal(msg, _roundtrip(msg))


def test_decoded_planes_are_zero_copy_views():
    buf = frame.encode(_msg())
    got = frame.decode(buf)
    wire = np.frombuffer(buf, dtype=np.uint8)
    assert np.shares_memory(wire, got.keys)
    assert np.shares_memory(wire, got.values[0])
    assert not got.values[0].flags.writeable  # views of immutable bytes


def test_truncated_frame_is_typed_reject():
    buf = frame.encode(_msg())
    for cut in (0, 1, frame.HEADER_SIZE - 1, frame.HEADER_SIZE + 3,
                len(buf) - 1):
        with pytest.raises(FrameError):
            frame.decode(buf[:cut])


def test_garbled_header_is_typed_reject():
    buf = bytearray(frame.encode(_msg()))
    buf[5] ^= 0xFF  # inside the CRC-covered header region
    with pytest.raises(FrameError, match="header CRC"):
        frame.peek(bytes(buf))


def test_bad_magic_and_version_are_typed_rejects():
    good = frame.encode(_msg())
    with pytest.raises(FrameError):
        frame.decode(b"ZZ" + good[2:])  # magic AND header crc both wrong
    # random garbage entirely
    with pytest.raises(FrameError):
        frame.decode(b"\x00" * 64)


def test_corrupt_plane_is_typed_reject_and_verify_false_tolerates():
    buf = bytearray(frame.encode(_msg()))
    info = frame.peek(bytes(buf))
    buf[frame.HEADER_SIZE + info.meta_len + 7] ^= 0x10
    data = bytes(buf)
    assert not frame.verify_planes(data)
    with pytest.raises(FrameError, match="plane CRC"):
        frame.decode(data)
    got = frame.decode(data, verify=False)  # ChaosVan's injection path
    assert got.keys.shape == (10,)


def _fuzz_msg():
    """A frame whose meta exercises every decode path corruption can hit:
    strings, nested containers, a payload ndarray, and plane manifests."""
    return _msg(
        task=Task(
            TaskKind.PUSH,
            "t",
            payload={
                "table": "w",
                "scales": np.linspace(0.1, 1.0, 5, dtype=np.float32),
                "nested": (1, [2, "x"], b"\x00\xff"),
                "big": 1 << 80,
            },
        )
    )


def test_every_meta_bit_flip_is_typed_reject():
    """Single-bit flips in the meta section — which used to escape as
    OverflowError/ValueError off np.dtype/frombuffer and kill the recv
    thread — must ALL be caught, by the meta CRC, as FrameError."""
    good = frame.encode(_fuzz_msg())
    info = frame.peek(good)
    for off in range(frame.HEADER_SIZE, frame.HEADER_SIZE + info.meta_len):
        for bit in (0, 3, 7):
            buf = bytearray(good)
            buf[off] ^= 1 << bit
            with pytest.raises(FrameError):
                frame.decode(bytes(buf))


def test_fuzzed_frames_never_escape_frameerror():
    """Multi-bit garbling + truncation anywhere in the frame: decode either
    succeeds or raises FrameError — never any other exception type (the
    recv-thread survival contract)."""
    import random

    good = frame.encode(_fuzz_msg())
    rng = random.Random(7)
    for _ in range(400):
        buf = bytearray(good)
        for _ in range(rng.randint(1, 4)):
            buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
        if rng.random() < 0.25:
            buf = buf[: rng.randrange(len(buf))]
        try:
            frame.decode(bytes(buf))
        except FrameError:
            pass


def _refix_crcs(buf: bytearray) -> bytes:
    """Recompute meta+header CRCs so decode reaches the corrupted section
    (tests the structural validation BEHIND the CRC line of defense)."""
    import struct
    import zlib

    fields = list(frame.HEADER.unpack_from(buf, 0))
    meta_len = fields[11]
    meta = bytes(buf[frame.HEADER_SIZE : frame.HEADER_SIZE + meta_len])
    fields[10] = zlib.crc32(meta)  # meta_crc32
    frame.HEADER.pack_into(buf, 0, *fields[:-1], 0)
    struct.pack_into(
        "<I", buf, frame.HEADER_SIZE - 4,
        zlib.crc32(bytes(buf[: frame.HEADER_SIZE - 4])),
    )
    return bytes(buf)


def test_negative_manifest_dim_is_typed_reject():
    """A manifest claiming a negative shape dim must be a typed reject,
    not a silent mis-parse (frombuffer with negative count reads the whole
    remaining buffer; reshape treats a lone -N as -1)."""
    import struct

    buf = bytearray(frame.encode(_msg()))
    info = frame.peek(bytes(buf))
    # the last 8 meta bytes are the final dim of the last plane's shape
    # ((10, 4) float32 -> the 4)
    end = frame.HEADER_SIZE + info.meta_len
    assert struct.unpack_from("<q", buf, end - 8)[0] == 4
    struct.pack_into("<q", buf, end - 8, -4)
    with pytest.raises(FrameError, match="negative plane dim"):
        frame.decode(_refix_crcs(buf))


def test_negative_meta_ndarray_dim_is_typed_reject():
    """Same validation inside the tag codec's _T_NDARRAY branch (payload
    ndarrays: routing tables, q8 scales)."""
    import struct

    out = bytearray()
    frame._enc_obj(np.arange(6, dtype=np.float32).reshape(2, 3), out)
    # layout: tag(1) dlen(1) "float32"(7) ndim(1) dim0(8) dim1(8) data
    struct.pack_into("<q", out, 1 + 1 + 7 + 1, -2)
    with pytest.raises(FrameError, match="negative ndarray dim"):
        frame._dec_obj(bytes(out), 0)


def test_encode_overflowing_plane_count_is_typed_reject():
    """> 65535 planes cannot fit the u16 n_arrays field: typed FrameError
    at encode time, not a raw struct.error at send time."""
    msg = _msg(keys=None, values=[np.zeros(1, dtype=np.float32)] * 65600)
    with pytest.raises(FrameError, match="n_arrays"):
        frame.encode(msg)


# --------------------------------------------------- meta codec specifics


def test_meta_preserves_tuple_vs_list_and_bytes_and_bigint():
    payload = {
        "t": (1, 2, (3, "x")),
        "l": [1, 2, [3, "x"]],
        "b": b"\x00\xffraw",
        "big": 1 << 80,
        "neg": -(1 << 90),
        "f": 0.1,
        "none": None,
        "flag": True,
    }
    got = _roundtrip(_msg(task=Task(TaskKind.CONTROL, "t", payload=payload),
                          keys=None, values=[]))
    gp = got.task.payload
    assert gp == payload
    assert type(gp["t"]) is tuple and type(gp["l"]) is list
    assert type(gp["t"][2]) is tuple and type(gp["l"][2]) is list
    assert type(gp["b"]) is bytes


def test_meta_ndarray_payload_roundtrip():
    """q8 scale arrays and routing tables ride the payload as ndarrays."""
    scales = np.linspace(0.1, 2.0, 7, dtype=np.float32)
    got = _roundtrip(
        _msg(task=Task(TaskKind.PUSH, "t", payload={"q8_scales": scales}))
    )
    out = got.task.payload["q8_scales"]
    assert out.dtype == np.float32
    np.testing.assert_array_equal(out, scales)


def test_meta_np_scalars_decay_to_python_values():
    got = _roundtrip(
        _msg(task=Task(TaskKind.PUSH, "t",
                       payload={"n": np.int64(7), "x": np.float32(1.5),
                                "b": np.bool_(True)}),
             keys=None, values=[])
    )
    gp = got.task.payload
    assert gp["n"] == 7 and type(gp["n"]) is int
    assert gp["x"] == 1.5 and type(gp["x"]) is float
    assert gp["b"] is True


def test_meta_enums_decay_to_their_value_not_str():
    # the scheduler's node-table broadcast carries NodeRole entries;
    # receivers re-wrap with NodeRole(row["role"]) (core/manager.py), so
    # the wire value must be "scheduler", never str(obj)'s qualified name
    got = _roundtrip(
        _msg(task=Task(TaskKind.CONTROL, "mgr",
                       payload={"role": NodeRole.SCHEDULER,
                                "kind": TaskKind.PUSH}),
             keys=None, values=[])
    )
    gp = got.task.payload
    assert gp["role"] == "scheduler"
    assert NodeRole(gp["role"]) is NodeRole.SCHEDULER
    assert TaskKind(gp["kind"]) is TaskKind.PUSH


def test_meta_unknown_type_is_typed_reject():
    with pytest.raises(FrameError, match="cannot encode"):
        frame.encode(
            _msg(task=Task(TaskKind.PUSH, "t", payload={"fn": object()}))
        )


# ----------------------------------------------- header stamps + peek/dedup


def test_stamp_key_literals_match_their_owners():
    """frame.py repeats the stamp-key literals instead of importing their
    owner modules (keeps resender off the codec's import path); this pins
    the duplication."""
    assert frame.SEQ_KEY == resender_mod.SEQ_KEY
    assert frame.CRC_KEY == resender_mod.CRC_KEY
    assert frame.ROUTING_EPOCH_KEY == routing_mod.ROUTING_EPOCH_KEY


def test_stamps_lift_into_header_and_reinstate():
    payload = {
        "table": "w",
        resender_mod.SEQ_KEY: 7,
        INCARNATION_KEY: 2,
        routing_mod.ROUTING_EPOCH_KEY: 5,
        resender_mod.CRC_KEY: 123456,
    }
    msg = _msg(task=Task(TaskKind.PUSH, "t", payload=dict(payload)))
    buf = frame.encode(msg)

    # header-only visibility: dedup/fencing fields without any meta decode
    info = frame.peek(buf)
    assert info.seq == 7
    assert info.incarnation == 2
    assert info.epoch == 5
    assert info.e2e_crc == 123456
    assert info.is_request

    # the stamps rode the fixed header, not the meta section: the meta is
    # exactly as long as the same message without any stamps
    bare = _msg(task=Task(TaskKind.PUSH, "t", payload={"table": "w"}))
    assert info.meta_len == frame.peek(frame.encode(bare)).meta_len

    # ...and decode reinstates them bitwise
    got = frame.decode(buf)
    assert got.task.payload == payload


def test_encode_does_not_mutate_sender_payload():
    payload = {resender_mod.SEQ_KEY: 3, "table": "w"}
    msg = _msg(task=Task(TaskKind.PUSH, "t", payload=payload))
    frame.encode(msg)
    assert payload == {resender_mod.SEQ_KEY: 3, "table": "w"}


def test_non_int_stamp_values_ride_meta_not_header():
    msg = _msg(
        task=Task(TaskKind.PUSH, "t",
                  payload={resender_mod.SEQ_KEY: "not-an-int"})
    )
    buf = frame.encode(msg)
    info = frame.peek(buf)
    assert not info.flags & frame.FLAG_SEQ and info.seq is None
    assert frame.decode(buf).task.payload == {
        resender_mod.SEQ_KEY: "not-an-int"
    }


# ------------------------------------------- control-frame fast path


def _slow_encode(msg):
    """Force the general encoder (the fast path's ground truth)."""
    orig = frame._fast_encode
    frame._fast_encode = lambda m: None
    try:
        return frame.encode(msg)
    finally:
        frame._fast_encode = orig


def _ctl(payload, *, kind=TaskKind.CONTROL, is_request=False, time=0):
    return _msg(
        task=Task(kind, "t", time=time, payload=payload),
        keys=None,
        values=[],
        is_request=is_request,
    )


_FAST_ELIGIBLE = [
    _ctl({}),  # bare ack
    _ctl({resender_mod.SEQ_KEY: 7}),  # the resender ACK shape
    _ctl(
        {
            resender_mod.SEQ_KEY: 7,
            INCARNATION_KEY: 2,
            routing_mod.ROUTING_EPOCH_KEY: 5,
            resender_mod.CRC_KEY: 123456,
        }
    ),
    _ctl({"rows": 42, "step": -3}, kind=TaskKind.PUSH, is_request=True),
    _ctl({"n": (1 << 63) - 1, "m": -(1 << 63)}, time=-12345),  # i64 edges
]


def test_fast_path_is_byte_identical_to_general_encoder():
    """Every eligible no-plane control frame must encode to EXACTLY the
    general path's bytes — receivers (CRC checks, dedup peeks, goldens)
    can never tell which encoder ran."""
    for msg in _FAST_ELIGIBLE:
        fast = frame.encode(msg)
        assert frame._fast_encode(msg) is not None  # it really ran fast
        assert fast == _slow_encode(msg)
        _assert_messages_equal(frame.decode(fast), msg)


def test_fast_path_header_stamps_stay_peekable():
    buf = frame.encode(_FAST_ELIGIBLE[2])
    info = frame.peek(buf)
    assert info.seq == 7 and info.incarnation == 2
    assert info.epoch == 5 and info.e2e_crc == 123456


def test_fast_path_ineligible_payloads_fall_through():
    """Anything outside the meta-stable shape returns None from the fast
    encoder and rides the general path (which must still roundtrip)."""
    cases = [
        _ctl({"s": "text"}),  # non-int value
        _ctl({"b": True}),  # bool is not int (type-exact check)
        _ctl({"big": 1 << 70}),  # beyond the i64 slot
        _ctl({resender_mod.SEQ_KEY: 1 << 70}),  # out-of-range stamp
        _ctl({"nested": {"x": 1}}),
    ]
    for msg in cases:
        assert frame._fast_encode(msg) is None
        _assert_messages_equal(frame.decode(frame.encode(msg)), msg)


def test_fast_path_never_mutates_payload():
    payload = {resender_mod.SEQ_KEY: 3, "count": 9}
    msg = _ctl(dict(payload))
    frame.encode(msg)
    assert msg.task.payload == payload


def test_fast_cache_hit_reencodes_value_changes(monkeypatch):
    """Same signature, different slot values: the cached template must be
    re-patched per call, never replayed stale."""
    monkeypatch.setattr(frame, "_FAST_ENC_CACHE", {})
    a = _ctl({resender_mod.SEQ_KEY: 1, "n": 10}, time=5)
    b = _ctl({resender_mod.SEQ_KEY: 2, "n": -20}, time=6)
    ea, eb = frame.encode(a), frame.encode(b)
    assert len(frame._FAST_ENC_CACHE) == 1  # one signature, one template
    assert ea != eb
    assert ea == _slow_encode(a) and eb == _slow_encode(b)


def test_fast_cache_cap_bounds_memory_not_correctness(monkeypatch):
    monkeypatch.setattr(frame, "_FAST_ENC_CACHE", {})
    monkeypatch.setattr(frame, "_FAST_CACHE_CAP", 2)
    msgs = [_ctl({f"k{i}": i}) for i in range(4)]
    for m in msgs:
        assert frame.encode(m) == _slow_encode(m)  # overflow still correct
    assert len(frame._FAST_ENC_CACHE) == 2


def test_frame_nbytes_is_exact():
    cases = [
        _msg(),
        _msg(keys=None, values=[]),
        _msg(task=Task(TaskKind.PUSH, "t",
                       payload={"table": "w", resender_mod.SEQ_KEY: 9,
                                INCARNATION_KEY: 1,
                                resender_mod.CRC_KEY: 42}),
             values=[np.arange(40, dtype=np.float32).reshape(10, 4),
                     np.arange(3, dtype=np.int32)]),
        _msg(values=[np.zeros((5, 2), dtype=ml_dtypes.bfloat16)]),
        # out-of-range stamp values do NOT lift into the header — they ride
        # the meta section, and the estimate must include them (the filter
        # mirrors encode's _lift_int range checks, not just the key names)
        _msg(task=Task(TaskKind.PUSH, "t",
                       payload={"table": "w",
                                resender_mod.SEQ_KEY: 1 << 70,
                                resender_mod.CRC_KEY: 1 << 40,
                                INCARNATION_KEY: -(1 << 40),
                                routing_mod.ROUTING_EPOCH_KEY: 1 << 35})),
    ]
    for msg in cases:
        buf = frame.encode(msg)
        total, overhead = frame.frame_nbytes(msg)
        assert total == len(buf)
        assert overhead == frame.peek(buf).overhead


def test_payload_crc32_matches_header_plane_crc_for_plain_arrays():
    """Same bytes, two vantage points: the resender's zero-copy end-to-end
    CRC over (keys, values) equals the header's plane CRC when no filter
    rewrites the planes in between."""
    msg = _msg()
    assert payload_crc32(msg) == frame.peek(frame.encode(msg)).plane_crc


def test_frame_codec_van_counters():
    base = LoopbackVan()
    van = FrameCodecVan(base)
    try:
        got = []
        van.bind("S0", got.append)
        msg = _msg()
        assert van.send(msg)
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)  # loopback delivery rides a recv thread
        assert len(got) == 1
        _assert_messages_equal(msg, got[0])
        assert got[0] is not msg  # rode the wire bytes, not the reference
        c = van.counters()
        assert c["frames"] == 1 and c["frame_passthrough"] == 0
        assert c["frame_bytes"] == len(frame.encode(msg))
        assert c["frame_overhead_bytes"] == frame.peek(frame.encode(msg)).overhead
    finally:
        van.close()


# ----------------------------------------------------------- acceptance e2e


def _table_cfgs():
    return {
        "w": TableConfig(
            name="w", rows=ROWS, dim=1,
            optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.1),
        )
    }


def _batches():
    data = SyntheticCTR(key_space=4 * ROWS, nnz=8, batch_size=128, seed=3)
    return [data.next_batch() for _ in range(STEPS)]


def _train(worker, batches, on_step=None):
    losses = []
    for i, (keys, labels) in enumerate(batches):
        w_pos = worker.pull_sync("w", keys, timeout=60)
        g, _gb, loss = linear.grad_rows(jnp.asarray(w_pos), jnp.asarray(labels))
        worker.push_sync("w", keys, np.asarray(g) / labels.shape[0], timeout=60)
        losses.append(float(loss))
        if on_step is not None:
            on_step(i)
    return losses


def _clean_reference():
    van = LoopbackVan()
    try:
        servers = [
            KVServer(Postoffice(f"S{s}", van), _table_cfgs(), s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        ]
        worker = KVWorker(Postoffice("W0", van), _table_cfgs(), NUM_SERVERS)
        losses = _train(worker, _batches())
        return losses, sum(s.pushes for s in servers)
    finally:
        van.close()


def _framed_stack(*, seed=0, timeout=0.1, max_retries=60, **chaos_kw):
    """The full production wire plane over real frame bytes:

    Coalesce(Metered(Reliable(Chaos(FrameCodec(Loopback))))) — every
    message (bundles included) is encoded to a flat frame and decoded into
    frombuffer views before delivery, exactly as TcpVan would do it.
    """
    codec = FrameCodecVan(LoopbackVan())
    chaos = ChaosVan(codec, seed=seed, **chaos_kw)
    rel = ReliableVan(
        chaos, timeout=timeout, backoff=1.0, max_retries=max_retries,
        seed=seed,
    )
    metered = MeteredVan(rel, stamp=False)
    return CoalescingVan(metered), rel, chaos, codec, metered


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1])
def test_training_on_flat_frames_under_chaos_matches_clean_run(seed):
    """ISSUE 7 acceptance: bitwise training parity + exactly-once delivery
    with every message riding real frame bytes, under seeded drop,
    duplication AND corruption.  Corrupt planes re-framed by the chaos
    layer carry a self-consistent transport CRC, so they reach the
    resender — whose end-to-end ``__rcrc__`` (now a fixed header field)
    catches every flip: ``rejected_corrupt > 0`` and nothing is lost or
    double-applied."""
    ref_losses, ref_applied = _clean_reference()

    van, rel, chaos, codec, metered = _framed_stack(
        seed=seed, drop=0.05, duplicate=0.05, corrupt=0.05
    )
    try:
        servers = [
            KVServer(Postoffice(f"S{s}", van), _table_cfgs(), s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        ]
        worker = KVWorker(Postoffice("W0", van), _table_cfgs(), NUM_SERVERS)
        losses = _train(worker, _batches())

        np.testing.assert_allclose(losses, ref_losses, rtol=1e-7, atol=0)
        assert sum(s.pushes for s in servers) == ref_applied  # exactly once
        assert van.flush(10)
        assert rel.gave_up == 0
        assert chaos.injected_drops + chaos.injected_dups > 0
        assert chaos.injected_corrupt > 0  # flips actually happened
        assert rel.rejected_corrupt > 0  # ...and the e2e CRC caught them

        c = codec.counters()
        assert c["frames"] > 0
        assert c["frame_passthrough"] == 0  # EVERY message framed
        assert c["frame_bytes"] > c["frame_overhead_bytes"] > 0

        # metering agrees with the codec about per-frame overhead existing
        mc = metered.counters()
        assert mc["wire_frame_bytes"] > mc["wire_bytes"]
        assert mc["wire_overhead_bytes"] > 0
    finally:
        van.close()


@pytest.mark.migration
def test_live_migration_rides_flat_frames():
    """Mid-run shard migration with the worker left stale: fence rejects
    (epoch riding the fixed header), refresh, convergence — on flat frames
    end to end, with the trajectory bitwise-equal to the clean run."""
    ref_losses, ref_applied = _clean_reference()

    van, rel, chaos, codec, _metered = _framed_stack(seed=3, drop=0.02)
    try:
        servers = [
            KVServer(Postoffice(f"S{s}", van), _table_cfgs(), s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        ]
        worker = KVWorker(Postoffice("W0", van), _table_cfgs(), NUM_SERVERS)
        mig = ShardMigrator(Postoffice("M0", van), chunk_rows=256)
        moved = {}

        def on_step(i):
            if i != STEPS // 2:
                return
            # migrate WITHOUT informing the worker — it must discover the
            # new table from fence rejects alone, all on framed bytes
            moved["routing"] = mig.migrate(worker.routing, "w", 768, ROWS, 0)

        losses = _train(worker, _batches(), on_step=on_step)
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-7, atol=0)
        assert sum(s.pushes for s in servers) == ref_applied  # exactly once
        assert sum(s.fenced_rejects for s in servers) > 0
        assert worker.refresh_retries > 0
        assert worker.routing.epoch == moved["routing"].epoch  # converged
        assert codec.counters()["frame_passthrough"] == 0
        assert rel.gave_up == 0
        assert van.flush(10)
    finally:
        van.close()
