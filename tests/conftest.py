"""Test environment: force an 8-device virtual CPU mesh.

Multi-device behavior (sharding, collectives, psum-before-push) is tested on
one host by faking 8 CPU devices, mirroring how the reference tests multi-node
via N processes over loopback ZMQ (SURVEY.md §4).  Must run before jax import.

The dev image injects an experimental TPU PJRT plugin ("axon") into every
interpreter via sitecustomize; its init contacts a device-relay service and
can block CPU-only test runs (e.g. when a crashed process holds the single
TPU claim).  Tests never need the real chip, so the plugin registration is
removed outright before the first jax operation.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

try:  # unregister the axon PJRT plugin factory if sitecustomize added it
    # sitecustomize has already imported jax at interpreter boot, so the env
    # vars above were read too late for the config defaults — force them.
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as _xb

    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name not in ("cpu", "tpu", "gpu", "cuda", "rocm"):
            _xb._backend_factories.pop(_name, None)
except Exception:
    pass

import pytest


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """On any failing ``chaos``/``migration``-marked test, print the seeds.

    Seeded chaos runs are deterministic given (seed, send order), so a CI
    failure should be a one-liner to reproduce locally — but only if the
    seed makes it into the failure output.  Parametrized seeds come from
    ``item.callspec``; tests with hardcoded seeds can instead stash one via
    ``item.user_properties.append(("chaos_seed", seed))``.  Migration /
    rebalance tests (PR 6) get the same one-line repro contract — their
    kill-mid-stream and skew scenarios are seed-driven the same way, as do
    the durability-plane ``checkpoint`` drills (PR 16: kill-mid-snapshot,
    torn-file, reshard-restore) and the ``consistency``-plane gate drills
    (PR 20: SSP bound under seeded chaos, restart, migration).
    """
    outcome = yield
    report = outcome.get_result()
    if report.when != "call" or not report.failed:
        return
    if (
        "chaos" not in item.keywords
        and "migration" not in item.keywords
        and "checkpoint" not in item.keywords
        and "consistency" not in item.keywords
    ):
        return
    seeds = {}
    params = getattr(item, "callspec", None)
    if params is not None:
        for name, value in params.params.items():
            if "seed" in name.lower():
                seeds[name] = value
    for name, value in item.user_properties:
        if "seed" in name.lower():
            seeds[name] = value
    repro = f"pytest '{item.nodeid}'"
    detail = (
        f"chaos seeds: {seeds}" if seeds
        else "chaos seeds: (none recorded — check the test's literals)"
    )
    report.sections.append(
        ("chaos repro", f"{detail}\nrepro: {repro}")
    )
    # Black-box postmortem: the process-wide flight recorder still holds the
    # last N transport/KV events of the failed scenario — capture them before
    # the next test overwrites the ring.  Best-effort: a broken recorder must
    # not turn one failure into two.
    try:
        import pathlib
        import re

        from parameter_server_tpu.core import flightrec

        slug = re.sub(r"[^A-Za-z0-9_.-]+", "_", item.nodeid)[-80:]
        out_dir = pathlib.Path("/tmp/ps_postmortem") / slug
        paths = flightrec.dump(str(out_dir), reason=f"test-failure:{item.nodeid}")
        if paths:
            report.sections.append(
                (
                    "postmortem bundle",
                    "\n".join(paths)
                    + f"\nmerge: python tools/postmortem.py {out_dir}/*.json",
                )
            )
    except Exception:
        pass
