"""Test environment: force an 8-device virtual CPU mesh.

Multi-device behavior (sharding, collectives, psum-before-push) is tested on
one host by faking 8 CPU devices, mirroring how the reference tests multi-node
via N processes over loopback ZMQ (SURVEY.md §4).  Must run before jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
