"""Test environment: force an 8-device virtual CPU mesh.

Multi-device behavior (sharding, collectives, psum-before-push) is tested on
one host by faking 8 CPU devices, mirroring how the reference tests multi-node
via N processes over loopback ZMQ (SURVEY.md §4).  Must run before jax import.

The dev image injects an experimental TPU PJRT plugin ("axon") into every
interpreter via sitecustomize; its init contacts a device-relay service and
can block CPU-only test runs (e.g. when a crashed process holds the single
TPU claim).  Tests never need the real chip, so the plugin registration is
removed outright before the first jax operation.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

try:  # unregister the axon PJRT plugin factory if sitecustomize added it
    # sitecustomize has already imported jax at interpreter boot, so the env
    # vars above were read too late for the config defaults — force them.
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as _xb

    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name not in ("cpu", "tpu", "gpu", "cuda", "rocm"):
            _xb._backend_factories.pop(_name, None)
except Exception:
    pass
