import numpy as np
import pytest

import jax
import jax.numpy as jnp

from parameter_server_tpu.ops import scatter
from parameter_server_tpu.utils.keys import localize_batch


def _table(rows=64, dim=128, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(rows, dim)).astype(np.float32))


def test_gather_xla_matches_numpy():
    t = _table()
    ids = jnp.array([3, 0, 3, 63], dtype=jnp.int32)
    out = scatter.gather_rows(t, ids, impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(t)[[3, 0, 3, 63]])


def test_scatter_add_xla_duplicates():
    t = _table(rows=8, dim=128)
    ids = jnp.array([1, 1, 2], dtype=jnp.int32)
    rows = jnp.ones((3, 128), dtype=jnp.float32)
    out = scatter.scatter_add_rows(t, ids, rows, impl="xla")
    expect = np.asarray(t).copy()
    expect[1] += 2.0
    expect[2] += 1.0
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)


def test_segment_combine_pads_zero():
    vals = jnp.ones((5, 4), dtype=jnp.float32)
    inverse = jnp.array([0, 0, 1, 2, 1], dtype=jnp.int32)
    out = scatter.segment_combine(vals, inverse, num_rows=8)
    np.testing.assert_allclose(np.asarray(out)[0], 2.0 * np.ones(4))
    np.testing.assert_allclose(np.asarray(out)[1], 2.0 * np.ones(4))
    np.testing.assert_allclose(np.asarray(out)[2], 1.0 * np.ones(4))
    np.testing.assert_allclose(np.asarray(out)[3:], 0.0)


def test_combine_and_scatter_add_end_to_end():
    """Full push apply: raw batch keys -> localize -> combine -> scatter."""
    capacity, dim = 32, 128
    table = jnp.zeros((capacity + 1, dim), dtype=jnp.float32)
    keys = np.array([100, 7, 100, 9, 7, 100], dtype=np.uint64)
    uniq, inverse, n = localize_batch(keys, min_bucket=8)
    # dense local ids: pretend localizer assigned slots 0..n-1, pads -> trash
    slots = np.full(uniq.shape[0], capacity, dtype=np.int32)
    slots[:n] = np.arange(n)
    grads = jnp.ones((keys.shape[0], dim), dtype=jnp.float32)
    out = scatter.combine_and_scatter_add(
        table, jnp.asarray(slots), jnp.asarray(inverse), grads, uniq.shape[0]
    )
    out = np.asarray(out)
    # uniq sorted: [7, 9, 100]; counts [2, 1, 3]
    np.testing.assert_allclose(out[0], 2.0)
    np.testing.assert_allclose(out[1], 1.0)
    np.testing.assert_allclose(out[2], 3.0)
    np.testing.assert_allclose(out[3:capacity], 0.0)
    np.testing.assert_allclose(out[capacity], 0.0)  # trash row got only zeros


def test_gather_grad_is_scatter():
    """XLA gather must be differentiable (backward = scatter-add)."""
    t = _table(rows=8, dim=128)
    ids = jnp.array([1, 1, 3], dtype=jnp.int32)

    def loss(tbl):
        return jnp.sum(scatter.gather_rows(tbl, ids, impl="xla") ** 2)

    g = jax.grad(loss)(t)
    expect = np.zeros_like(np.asarray(t))
    tn = np.asarray(t)
    expect[1] = 2 * 2 * tn[1]  # row 1 gathered twice
    expect[3] = 2 * tn[3]
    np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-6)


def test_pallas_rejects_unaligned_ids():
    t = _table(rows=16, dim=128)
    ids = jnp.array([1, 2, 3], dtype=jnp.int32)  # not a multiple of 8
    with pytest.raises(ValueError, match="bucket-pad"):
        scatter._pallas_gather(t, ids, interpret=True)
    with pytest.raises(ValueError, match="bucket-pad"):
        scatter._pallas_scatter_add(t, ids, jnp.ones((3, 128)), interpret=True)


def test_pallas_rejects_unaligned_dim():
    t = jnp.zeros((16, 100), dtype=jnp.float32)
    ids = jnp.arange(8, dtype=jnp.int32)
    with pytest.raises(ValueError, match="dim == 128 or dim % 1024"):
        scatter._pallas_gather(t, ids, interpret=True)


def test_combine_and_scatter_add_duplicate_slots():
    """Overflowed-localizer case: two unique keys sharing a slot must both land."""
    table = jnp.zeros((4, 128), dtype=jnp.float32)
    # unique keys 0,1 both hashed to slot 2
    ids = jnp.array([2, 2], dtype=jnp.int32)
    inverse = jnp.array([0, 1], dtype=jnp.int32)
    vals = jnp.ones((2, 128), dtype=jnp.float32)
    out = scatter.combine_and_scatter_add(table, ids, inverse, vals, num_rows=2)
    np.testing.assert_allclose(np.asarray(out)[2], 2.0)


@pytest.mark.parametrize("op", ["gather", "scatter_add"])
def test_pallas_interpret_matches_xla(op):
    """Pallas kernels in interpret mode on CPU must match the XLA path."""
    t = _table(rows=64, dim=128)
    ids = jnp.asarray(np.random.default_rng(1).permutation(64)[:16].astype(np.int32))
    if op == "gather":
        got = scatter._pallas_gather(t, ids, interpret=True)
        want = scatter.gather_rows_xla(t, ids)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))
    else:
        rows = jnp.asarray(
            np.random.default_rng(2).normal(size=(16, 128)).astype(np.float32)
        )
        got = scatter._pallas_scatter_add(t, ids, rows, interpret=True)
        want = scatter.scatter_add_rows_xla(t, ids, rows)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_pallas_scatter_set_matches_xla():
    t = _table(rows=64, dim=128)
    ids = jnp.asarray(
        np.random.default_rng(3).choice(63, size=16, replace=False), jnp.int32
    )
    rows = jnp.asarray(
        np.random.default_rng(4).normal(size=(16, 128)), jnp.float32
    )
    want = scatter.scatter_update_rows_xla(t, ids, rows)
    got = scatter._pallas_scatter_set(t, ids, rows, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)
    # dispatcher form
    got2 = scatter.scatter_update_rows(
        t, ids, rows, impl="pallas", interpret=True
    )
    np.testing.assert_allclose(np.asarray(got2), np.asarray(want), atol=1e-6)


@pytest.mark.parametrize("n,block", [(64, None), (64, 8), (64, 32), (24, None), (96, 16)])
def test_pallas_double_buffered_scatter_add_blocks(n, block):
    """The double-buffered RMW kernel is exact for every block geometry.

    n=24 exercises the auto-pick fallback to 8; explicit blocks exercise the
    slot-reuse wait logic at different pipeline depths.
    """
    t = _table(rows=128, dim=128, seed=5)
    rng = np.random.default_rng(6)
    ids = jnp.asarray(rng.choice(127, size=n, replace=False), jnp.int32)
    rows = jnp.asarray(rng.normal(size=(n, 128)), jnp.float32)
    want = scatter.scatter_add_rows_xla(t, ids, rows)
    got = scatter._pallas_scatter_add(
        t, ids, rows, interpret=True, block_rows=block
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5
    )


def test_pallas_block_rows_validation():
    t = _table(rows=16, dim=128)
    ids = jnp.arange(12, dtype=jnp.int32)  # not divisible by 8
    with pytest.raises(ValueError, match="divisible by 8"):
        scatter._pallas_gather(t, ids, interpret=True)
    with pytest.raises(ValueError, match="block_rows"):
        scatter._pallas_gather(t, jnp.arange(16, dtype=jnp.int32),
                               interpret=True, block_rows=32)


def test_kvserver_full_path_pallas_parity():
    """FULL production push/pull path under scatter_impl='pallas' (VERDICT
    r2 #4): two identical KVServer clusters, one per kernel impl, must stay
    bitwise-close through repeated pushes with duplicates + pads."""
    from parameter_server_tpu.config import OptimizerConfig, TableConfig
    from parameter_server_tpu.core.postoffice import Postoffice
    from parameter_server_tpu.core.van import LoopbackVan
    from parameter_server_tpu.kv.server import KVServer
    from parameter_server_tpu.kv.worker import KVWorker
    from parameter_server_tpu.utils.keys import HashLocalizer

    rows, dim = 512, 128
    keys = (np.arange(96, dtype=np.uint64) * 7919) % 3000
    keys = np.concatenate([keys, keys[:32]])  # duplicates pre-combine
    rng = np.random.RandomState(0)
    grads = rng.randn(keys.size, dim).astype(np.float32)

    pulled = {}
    for impl in ("xla", "pallas"):
        cfgs = {
            "e": TableConfig(
                name="e", rows=rows, dim=dim,
                optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.1),
                scatter_impl=impl,
            )
        }
        van = LoopbackVan()
        try:
            servers = [
                KVServer(Postoffice(f"S{i}", van), cfgs, i, 2)
                for i in range(2)
            ]
            worker = KVWorker(
                Postoffice("W0", van), cfgs, 2, min_bucket=16,
                localizers={"e": HashLocalizer(rows)},
            )
            for _ in range(3):
                worker.wait(worker.push("e", keys, grads), timeout=30)
            pulled[impl] = worker.pull_sync("e", keys, timeout=30)
        finally:
            van.close()
    np.testing.assert_allclose(
        pulled["pallas"], pulled["xla"], atol=1e-5, rtol=1e-5
    )


@pytest.mark.parametrize("dim", [1024, 2048])
def test_pallas_chunked_wide_rows(dim):
    """Wide rows (transformer d_model) DMA as (dim//128, 128) chunks of the
    (rows*c, 128) view — the layout Mosaic accepts for dim % 1024 == 0."""
    rng = np.random.default_rng(8)
    t = jnp.asarray(rng.normal(size=(64, dim)), jnp.float32)
    ids = jnp.asarray(rng.choice(63, size=16, replace=False), jnp.int32)
    rows = jnp.asarray(rng.normal(size=(16, dim)), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(scatter._pallas_gather(t, ids, interpret=True)),
        np.asarray(jnp.take(t, ids, axis=0)), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(scatter._pallas_scatter_add(t, ids, rows, interpret=True)),
        np.asarray(scatter.scatter_add_rows_xla(t, ids, rows)),
        atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(scatter._pallas_scatter_set(t, ids, rows, interpret=True)),
        np.asarray(scatter.scatter_update_rows_xla(t, ids, rows)), atol=1e-6)


def test_pallas_rejects_unsupported_dim():
    t = jnp.zeros((16, 256), jnp.float32)  # 256: single-row slice unaligned
    with pytest.raises(ValueError, match="dim == 128 or dim % 1024"):
        scatter._pallas_gather(t, jnp.arange(8, dtype=jnp.int32), interpret=True)
    # and auto mode silently falls back to XLA
    out = scatter.gather_rows(t, jnp.arange(8, dtype=jnp.int32), impl="auto")
    assert out.shape == (8, 256)
