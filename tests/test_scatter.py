import numpy as np
import pytest

import jax
import jax.numpy as jnp

from parameter_server_tpu.ops import scatter
from parameter_server_tpu.utils.keys import localize_batch


def _table(rows=64, dim=128, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(rows, dim)).astype(np.float32))


def test_gather_xla_matches_numpy():
    t = _table()
    ids = jnp.array([3, 0, 3, 63], dtype=jnp.int32)
    out = scatter.gather_rows(t, ids, impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(t)[[3, 0, 3, 63]])


def test_scatter_add_xla_duplicates():
    t = _table(rows=8, dim=128)
    ids = jnp.array([1, 1, 2], dtype=jnp.int32)
    rows = jnp.ones((3, 128), dtype=jnp.float32)
    out = scatter.scatter_add_rows(t, ids, rows, impl="xla")
    expect = np.asarray(t).copy()
    expect[1] += 2.0
    expect[2] += 1.0
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)


def test_segment_combine_pads_zero():
    vals = jnp.ones((5, 4), dtype=jnp.float32)
    inverse = jnp.array([0, 0, 1, 2, 1], dtype=jnp.int32)
    out = scatter.segment_combine(vals, inverse, num_rows=8)
    np.testing.assert_allclose(np.asarray(out)[0], 2.0 * np.ones(4))
    np.testing.assert_allclose(np.asarray(out)[1], 2.0 * np.ones(4))
    np.testing.assert_allclose(np.asarray(out)[2], 1.0 * np.ones(4))
    np.testing.assert_allclose(np.asarray(out)[3:], 0.0)


def test_combine_and_scatter_add_end_to_end():
    """Full push apply: raw batch keys -> localize -> combine -> scatter."""
    capacity, dim = 32, 128
    table = jnp.zeros((capacity + 1, dim), dtype=jnp.float32)
    keys = np.array([100, 7, 100, 9, 7, 100], dtype=np.uint64)
    uniq, inverse, n = localize_batch(keys, min_bucket=8)
    # dense local ids: pretend localizer assigned slots 0..n-1, pads -> trash
    slots = np.full(uniq.shape[0], capacity, dtype=np.int32)
    slots[:n] = np.arange(n)
    grads = jnp.ones((keys.shape[0], dim), dtype=jnp.float32)
    out = scatter.combine_and_scatter_add(
        table, jnp.asarray(slots), jnp.asarray(inverse), grads, uniq.shape[0]
    )
    out = np.asarray(out)
    # uniq sorted: [7, 9, 100]; counts [2, 1, 3]
    np.testing.assert_allclose(out[0], 2.0)
    np.testing.assert_allclose(out[1], 1.0)
    np.testing.assert_allclose(out[2], 3.0)
    np.testing.assert_allclose(out[3:capacity], 0.0)
    np.testing.assert_allclose(out[capacity], 0.0)  # trash row got only zeros


def test_gather_grad_is_scatter():
    """XLA gather must be differentiable (backward = scatter-add)."""
    t = _table(rows=8, dim=128)
    ids = jnp.array([1, 1, 3], dtype=jnp.int32)

    def loss(tbl):
        return jnp.sum(scatter.gather_rows(tbl, ids, impl="xla") ** 2)

    g = jax.grad(loss)(t)
    expect = np.zeros_like(np.asarray(t))
    tn = np.asarray(t)
    expect[1] = 2 * 2 * tn[1]  # row 1 gathered twice
    expect[3] = 2 * tn[3]
    np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-6)


def test_pallas_rejects_unaligned_ids():
    t = _table(rows=16, dim=128)
    ids = jnp.array([1, 2, 3], dtype=jnp.int32)  # not a multiple of 8
    with pytest.raises(ValueError, match="bucket-pad"):
        scatter._pallas_gather(t, ids, interpret=True)
    with pytest.raises(ValueError, match="bucket-pad"):
        scatter._pallas_scatter_add(t, ids, jnp.ones((3, 128)), interpret=True)


def test_pallas_rejects_unaligned_dim():
    t = jnp.zeros((16, 100), dtype=jnp.float32)
    ids = jnp.arange(8, dtype=jnp.int32)
    with pytest.raises(ValueError, match="dim % 128"):
        scatter._pallas_gather(t, ids, interpret=True)


def test_combine_and_scatter_add_duplicate_slots():
    """Overflowed-localizer case: two unique keys sharing a slot must both land."""
    table = jnp.zeros((4, 128), dtype=jnp.float32)
    # unique keys 0,1 both hashed to slot 2
    ids = jnp.array([2, 2], dtype=jnp.int32)
    inverse = jnp.array([0, 1], dtype=jnp.int32)
    vals = jnp.ones((2, 128), dtype=jnp.float32)
    out = scatter.combine_and_scatter_add(table, ids, inverse, vals, num_rows=2)
    np.testing.assert_allclose(np.asarray(out)[2], 2.0)


@pytest.mark.parametrize("op", ["gather", "scatter_add"])
def test_pallas_interpret_matches_xla(op):
    """Pallas kernels in interpret mode on CPU must match the XLA path."""
    t = _table(rows=64, dim=128)
    ids = jnp.asarray(np.random.default_rng(1).permutation(64)[:16].astype(np.int32))
    if op == "gather":
        got = scatter._pallas_gather(t, ids, interpret=True)
        want = scatter.gather_rows_xla(t, ids)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))
    else:
        rows = jnp.asarray(
            np.random.default_rng(2).normal(size=(16, 128)).astype(np.float32)
        )
        got = scatter._pallas_scatter_add(t, ids, rows, interpret=True)
        want = scatter.scatter_add_rows_xla(t, ids, rows)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
