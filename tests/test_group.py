"""Hierarchical push (ISSUE 15): worker-group pre-reduction before the wire.

Covers the group plane end to end:

1. config surface: ``GroupConfig`` / ``WorkerGroup`` validation, the
   deterministic per-``(table, step)`` leader election (rotate + fixed),
   and the ``GROUP_KEY`` mirror in ``core/filters.py``;
2. ``GroupReducer``: same-keys reduction, sorted-union merge, partial
   take / stale flush, duplicate-deposit idempotence;
3. cluster parity: a size-2 group applies EXACTLY the sum the direct
   pushes apply, as ONE wire PUSH per server booked as one logical apply
   (``group_pushes`` / ``group_members``), with fewer inbound requests;
4. staleness (ISSUE 10 interaction): barrier-disciplined group arms at
   sizes 2 and 4 must not regress staleness p99 vs direct — the done
   notify advances EVERY member's ``_last_push_version``;
5. chaos: leader killed mid-step degrades to direct per-worker push
   within the same step, bitwise-equal to the clean fallback path;
6. EF interaction (PR 14): rotate-elected groups stamp ``ef="bypass"``
   (codec skips the frame — residuals are per ``(sender, table)`` and a
   rotating sender would shred them); fixed-elected groups quantize
   under the pinned leader's residual;
7. telemetry satellites: per-verb ``inbound_totals``, the aggregator's
   ``grp_pct`` derivation, and pstop's GRP% column.
"""

import threading

import numpy as np
import pytest

from parameter_server_tpu.config import (
    GroupConfig,
    OptimizerConfig,
    TableConfig,
)
from parameter_server_tpu.core import filters, flightrec
from parameter_server_tpu.core.coalesce import CoalescingVan, GroupReducer
from parameter_server_tpu.core.fleet import FleetMonitor
from parameter_server_tpu.core.netmon import MeteredVan
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core.telemetry import TelemetryAggregator
from parameter_server_tpu.core.van import LoopbackVan
from parameter_server_tpu.kv.routing import GROUP_KEY, WorkerGroup
from parameter_server_tpu.kv.server import KVServer
from parameter_server_tpu.kv.worker import KVWorker

ROWS = 1 << 12


def _cfgs(lr=1.0, dim=2):
    return {
        "w": TableConfig(
            name="w", rows=ROWS, dim=dim,
            optimizer=OptimizerConfig(kind="sgd", learning_rate=lr),
        )
    }


def _cluster(cfgs, worker_names, *, num_servers=2, group=None, group_cfg=None):
    metered = MeteredVan(LoopbackVan())
    van = CoalescingVan(metered)
    servers = [
        KVServer(Postoffice(f"S{s}", van), cfgs, s, num_servers)
        for s in range(num_servers)
    ]
    workers = [
        KVWorker(
            Postoffice(n, van), cfgs, num_servers,
            group=group, group_cfg=group_cfg,
        )
        for n in worker_names
    ]
    return van, metered, servers, workers


def _concurrent_push(workers, table, keys, grads, timeout=30):
    """Every group member must be inside push_sync together (the
    rendezvous contract) — drive them with one thread per member."""
    errs = []

    def go(w, g):
        try:
            w.push_sync(table, keys, g, timeout=timeout)
        except Exception as e:  # noqa: BLE001 — surfaced to the test
            errs.append(e)

    ts = [
        threading.Thread(target=go, args=(w, g), daemon=True)
        for w, g in zip(workers, grads)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs


# ------------------------------------------------------------- config plane


def test_group_config_validation():
    cfg = GroupConfig(size=4, election="rotate", fallback="direct")
    assert cfg.fallback_timeout > 0
    with pytest.raises(ValueError, match="election"):
        GroupConfig(size=2, election="raft")
    with pytest.raises(ValueError, match="fallback"):
        GroupConfig(size=2, fallback="retry")
    with pytest.raises(ValueError, match="reduce"):
        GroupConfig(size=2, reduce="allgather")
    with pytest.raises(ValueError):
        GroupConfig(size=0)


def test_worker_group_validation_and_props():
    g = WorkerGroup(members=("W0", "W1", "W2"))
    assert g.size == 3
    assert g.gid == "W0+W1+W2"
    with pytest.raises(ValueError):
        WorkerGroup(members=())
    with pytest.raises(ValueError):
        WorkerGroup(members=("W0", "W0"))
    with pytest.raises(ValueError, match="election"):
        WorkerGroup(members=("W0", "W1"), election="paxos")


def test_leader_election_deterministic_and_rotating():
    g = WorkerGroup(members=("W0", "W1", "W2", "W3"))
    # deterministic: same (table, step) always elects the same member
    assert g.leader("w", 7) == g.leader("w", 7)
    # rotation: consecutive steps walk the ring, so over size steps every
    # member leads exactly once per table — the load-rotation contract
    leaders = [g.leader("w", s) for s in range(4)]
    assert sorted(leaders) == sorted(g.members)
    # different tables shift the ring phase (crc32 keying), same coverage
    leaders_v = [g.leader("v", s) for s in range(4)]
    assert sorted(leaders_v) == sorted(g.members)
    # salt rotates deterministically off the base election (fence retries)
    assert g.leader("w", 3, salt=1) == g.leader("w", 4)


def test_fixed_election_pins_until_salted():
    g = WorkerGroup(members=("W0", "W1"), election="fixed")
    assert all(g.leader("w", s) == "W0" for s in range(5))
    assert all(g.leader("v", s) == "W0" for s in range(5))
    # a fence retry (salt > 0) still rotates away from a fenced leader
    assert g.leader("w", 0, salt=1) in g.members


def test_group_key_mirrors_filters_module():
    # kv/routing.py owns the wire constant; core/filters.py mirrors it to
    # avoid a core -> kv import cycle.  They MUST stay identical.
    assert GROUP_KEY == filters._GROUP_KEY


# ------------------------------------------------------------ GroupReducer


def test_reducer_same_keys_sums_and_consumes():
    red = GroupReducer(2, node="T", mode="auto")
    keys = np.array([3, 5, 9], dtype=np.int64)
    a = np.array([[1.0], [2.0], [3.0]], np.float32)
    b = np.array([[10.0], [20.0], [30.0]], np.float32)
    assert red.deposit("w", 0, "W0", keys, a) is None
    out = red.deposit("w", 0, "W1", keys, b)
    assert out is not None
    rkeys, rvals, fanin = out
    assert fanin == 2
    np.testing.assert_array_equal(rkeys, keys)
    np.testing.assert_allclose(rvals, a + b)
    assert not red.pending()  # consumed
    # duplicate deposit after consumption starts a fresh set, not a crash
    assert red.deposit("w", 1, "W0", keys, a) is None


def test_reducer_union_merge_disjoint_keys():
    red = GroupReducer(2, node="T", mode="merge")
    k0 = np.array([1, 3], dtype=np.int64)
    k1 = np.array([1, 2], dtype=np.int64)
    v0 = np.array([[1.0], [5.0]], np.float32)
    v1 = np.array([[1.0], [7.0]], np.float32)
    assert red.deposit("w", 0, "W0", k0, v0) is None
    rkeys, rvals, fanin = red.deposit("w", 0, "W1", k1, v1)
    assert fanin == 2
    np.testing.assert_array_equal(rkeys, np.array([1, 2, 3]))
    np.testing.assert_allclose(rvals, np.array([[2.0], [7.0], [5.0]]))


def test_reducer_duplicate_member_deposit_ignored():
    red = GroupReducer(2, node="T")
    keys = np.array([1], dtype=np.int64)
    v = np.ones((1, 1), np.float32)
    assert red.deposit("w", 0, "W0", keys, v) is None
    assert red.deposit("w", 0, "W0", keys, 5 * v) is None  # dup: ignored
    rkeys, rvals, fanin = red.deposit("w", 0, "W1", keys, v)
    np.testing.assert_allclose(rvals, 2 * np.ones((1, 1)))
    assert fanin == 2


def test_reducer_take_partial_and_stale_flush():
    red = GroupReducer(3, node="T")
    keys = np.array([2, 4], dtype=np.int64)
    v = np.ones((2, 1), np.float32)
    assert red.deposit("w", 5, "W0", keys, v) is None
    part = red.take("w", 5)
    assert part is not None and part[2] == 1
    np.testing.assert_allclose(part[1], v)
    assert red.take("w", 5) is None  # consumed
    # stale flush: a set older than the deadline is drained with its step
    assert red.deposit("w", 6, "W0", keys, v) is None
    stale = red.take_stale(0.0)
    assert [(t, s) for t, s, _ in stale] == [("w", 6)]
    assert not red.pending()


# ------------------------------------------------- cluster: parity + wire


def _inbound_push(metered):
    tot = {"msgs": 0, "bytes": 0}
    for link, d in metered.links().items():
        if link.partition("->")[2].startswith("S"):
            vb = (d.get("verbs") or {}).get("PUSH")
            if vb:
                tot["msgs"] += vb["msgs"]
                tot["bytes"] += vb["bytes"]
    return tot


def test_group_push_applies_sum_once_with_fewer_requests():
    cfgs = _cfgs()
    keys = np.array([1, 5, 9, ROWS + 7], dtype=np.int64)
    # integer-valued grads: float addition is exact, so the group arm's
    # summed apply must match the direct arm's sequential applies BITWISE
    grads = [
        np.full((keys.size, 2), 1.0, np.float32),
        np.full((keys.size, 2), 2.0, np.float32),
    ]

    def run(grouped):
        names = ("W0", "W1")
        group = WorkerGroup(members=names) if grouped else None
        gcfg = GroupConfig(size=2, fallback_timeout=10.0) if grouped else None
        van, metered, servers, workers = _cluster(
            cfgs, names, group=group, group_cfg=gcfg
        )
        try:
            before = workers[0].pull_sync("w", keys, timeout=30).copy()
            _concurrent_push(workers, "w", keys, grads)
            after = workers[0].pull_sync("w", keys, timeout=30)
            return {
                "delta": after - before,
                "push": _inbound_push(metered),
                "group_pushes": sum(s.group_pushes for s in servers),
                "group_members": sum(s.group_members for s in servers),
                "pushes": sum(s.pushes for s in servers),
                "worker_counters": [w.counters() for w in workers],
            }
        finally:
            van.close()

    direct = run(False)
    grouped = run(True)
    # parity: sgd lr=1 applied the exact gradient sum either way
    np.testing.assert_array_equal(direct["delta"], grouped["delta"])
    np.testing.assert_array_equal(grouped["delta"], -3.0 * np.ones((4, 2)))
    # one logical apply for the whole group, booked with its fan-in
    assert grouped["pushes"] == grouped["group_pushes"]
    assert grouped["group_members"] == 2 * grouped["group_pushes"]
    assert direct["group_pushes"] == 0
    # the wire saw HALF the PUSH requests (and bytes, same keys)
    assert grouped["push"]["msgs"] * 2 == direct["push"]["msgs"]
    assert grouped["push"]["bytes"] * 2 == direct["push"]["bytes"]
    # clean path: nobody degraded
    assert all(
        c.get("group_fallbacks", 0) == 0
        for c in grouped["worker_counters"]
    )


@pytest.mark.parametrize("size", [2, 4])
def test_staleness_p99_no_regression_vs_direct(size):
    """Barrier-disciplined training at group sizes 2 and 4: the merged
    ``staleness.w`` p99 of the grouped arm must not exceed the direct
    arm's.  Deterministic: with all pushes fenced behind a barrier before
    any pull, each arm's staleness sample multiset is fixed (direct: the
    k-th of N applies lags N-k versions; grouped: one logical apply that
    the done notify credits to EVERY member, so the lag is 0)."""
    cfgs = _cfgs()
    steps = 4
    rng = np.random.default_rng(7)
    keys = np.sort(rng.choice(ROWS, 32, replace=False)).astype(np.int64)
    g = np.ones((keys.size, 2), np.float32)

    def run(grouped):
        names = tuple(f"W{i}" for i in range(size))
        group = WorkerGroup(members=names) if grouped else None
        gcfg = (
            GroupConfig(size=size, fallback_timeout=10.0) if grouped else None
        )
        # ONE server so version arithmetic is single-stream
        van, _m, servers, workers = _cluster(
            cfgs, names, num_servers=1, group=group, group_cfg=gcfg
        )
        barrier = threading.Barrier(size)
        errs = []

        def drive(w):
            try:
                for _ in range(steps):
                    barrier.wait()
                    w.push_sync("w", keys, g, timeout=30)
                    barrier.wait()  # every apply lands before any pull
                    w.pull_sync("w", keys, timeout=30)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        try:
            ts = [
                threading.Thread(target=drive, args=(w,), daemon=True)
                for w in workers
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errs, errs
            from parameter_server_tpu.utils.trace import LatencyHistogram

            p99s = []
            for w in workers:
                d = w.staleness_digests().get("staleness.w")
                assert d is not None and d["count"] >= steps
                p99s.append(LatencyHistogram.from_dict(d).percentile(0.99))
            # one logical apply per step grouped, one per member direct
            assert servers[0].pushes == (steps if grouped else steps * size)
            return max(p99s)
        finally:
            van.close()

    direct_p99 = run(False)
    grouped_p99 = run(True)
    assert grouped_p99 <= direct_p99
    # and the direct arm genuinely has staleness to beat at these sizes
    assert direct_p99 >= 1.0


# ------------------------------------------------------------------ chaos


@pytest.mark.chaos
def test_leader_death_falls_back_bitwise_equal_to_clean_path():
    """Kill the peer member mid-run: the survivor's remaining steps must
    degrade to direct per-worker push with NO loss, and the final table
    must be BITWISE equal to a clean run that pushes the same gradients
    directly — the seeded-chaos acceptance of ISSUE 15."""
    cfgs = _cfgs()
    keys = np.array([3, 11, 42, 1000], dtype=np.int64)
    steps, kill_at = 6, 3
    grads = [
        [
            np.full((keys.size, 2), float(1 + s), np.float32),
            np.full((keys.size, 2), float(10 + s), np.float32),
        ]
        for s in range(steps)
    ]

    def run(kill):
        names = ("W0", "W1")
        loop = LoopbackVan()
        van = CoalescingVan(MeteredVan(loop))
        flightrec.configure(enabled=True, clear=True)
        group = WorkerGroup(members=names)
        gcfg = GroupConfig(size=2, fallback_timeout=0.3)
        try:
            servers = [
                KVServer(Postoffice(f"S{s}", van), cfgs, s, 2)
                for s in range(2)
            ]
            workers = [
                KVWorker(Postoffice(n, van), cfgs, 2, group=group,
                         group_cfg=gcfg)
                for n in names
            ]
            # clean reference arm: an ungrouped worker pushes the
            # survivor's post-death gradients directly
            direct = KVWorker(Postoffice("W9", van), cfgs, 2)
            for s in range(kill_at):
                _concurrent_push(workers, "w", keys, grads[s])
            if kill:
                loop.disconnect("W1")
                for s in range(kill_at, steps):
                    # survivor keeps its group: leader steps flush a
                    # partial set (member_timeout), member steps detect
                    # the dead leader and push direct — same-step, no loss
                    workers[0].push_sync("w", keys, grads[s][0], timeout=30)
            else:
                for s in range(kill_at, steps):
                    direct.push_sync("w", keys, grads[s][0], timeout=30)
            final = direct.pull_sync("w", keys, timeout=30) if not kill \
                else workers[0].pull_sync("w", keys, timeout=30)
            fallbacks = sum(
                w.counters().get("group_fallbacks", 0) for w in workers
            )
            reasons = {
                e.get("reason")
                for e in flightrec.get().events()
                if e["kind"] == "group.fallback"
            }
            return np.asarray(final), fallbacks, reasons
        finally:
            van.close()
            flightrec.configure(enabled=True, clear=True)

    clean, clean_fallbacks, _ = run(kill=False)
    chaos, chaos_fallbacks, reasons = run(kill=True)
    # bitwise: every degraded step applied exactly the survivor's gradient
    np.testing.assert_array_equal(chaos, clean)
    # exact loss parity follows from bitwise weights
    assert float(np.sum(chaos ** 2)) == float(np.sum(clean ** 2))
    assert clean_fallbacks == 0
    assert chaos_fallbacks == steps - kill_at
    assert reasons <= {"member_timeout", "dead_leader", "stale_set"}
    assert reasons  # at least one degradation path exercised


# ------------------------------------------------------------ EF gating


def _group_push_msg(ef):
    from parameter_server_tpu.core.messages import Message, Task, TaskKind

    return Message(
        task=Task(
            TaskKind.PUSH,
            "kv",
            payload={
                "table": "w",
                GROUP_KEY: {"id": "W0+W1", "n": 2, "step": 0, "ef": ef},
            },
        ),
        sender="W0",
        recver="S0",
        keys=np.array([1, 2], dtype=np.int32),
        values=[np.array([[1.5], [2.5]], np.float32)],
    )


def test_ef_bypass_skips_codec_for_rotating_groups():
    from parameter_server_tpu.config import WireCompressionConfig
    from parameter_server_tpu.core.filters import QuantizingFilter

    codec = QuantizingFilter(
        default=WireCompressionConfig(codec="int8", error_feedback=True)
    )
    msg = _group_push_msg("bypass")
    out = codec.encode(msg)
    # frame untouched: float32 planes, no residual store created
    assert out.values[0].dtype == np.float32
    np.testing.assert_array_equal(out.values[0], msg.values[0])
    assert codec.counters().get("compress_wire_bytes", 0) == 0
    assert not codec._residuals


def test_ef_leader_mode_quantizes_under_pinned_residual():
    from parameter_server_tpu.config import WireCompressionConfig
    from parameter_server_tpu.core.filters import QuantizingFilter

    codec = QuantizingFilter(
        default=WireCompressionConfig(codec="int8", error_feedback=True)
    )
    out = codec.encode(_group_push_msg("leader"))
    assert out.values[0].dtype != np.float32  # quantized
    # the residual belongs to the PINNED leader's (sender, table) store —
    # fixed election means that store owns the whole group's residual
    assert set(codec._residuals) == {("W0", "w")}


def test_fixed_election_worker_stamps_leader_ef():
    names = ("W0", "W1")
    group = WorkerGroup(members=names, election="fixed")
    gcfg = GroupConfig(size=2, election="fixed", fallback_timeout=10.0)
    van, metered, servers, workers = _cluster(
        _cfgs(), names, group=group, group_cfg=gcfg
    )
    try:
        assert all(w._group_ef == "leader" for w in workers)
        keys = np.array([4, 8], dtype=np.int64)
        grads = [np.ones((2, 2), np.float32)] * 2
        _concurrent_push(workers, "w", keys, grads)
        # fixed election: W0 leads every step, so only W0 touches servers
        push_senders = {
            link.partition("->")[0]
            for link, d in metered.links().items()
            if link.partition("->")[2].startswith("S")
            and (d.get("verbs") or {}).get("PUSH")
        }
        assert push_senders == {"W0"}
    finally:
        van.close()


def test_rotate_election_worker_stamps_bypass_ef():
    names = ("W0", "W1")
    group = WorkerGroup(members=names)
    van, _m, _s, workers = _cluster(
        _cfgs(), names, group=group,
        group_cfg=GroupConfig(size=2, fallback_timeout=10.0),
    )
    try:
        assert all(w._group_ef == "bypass" for w in workers)
    finally:
        van.close()


# ------------------------------------------------------- telemetry plane


def test_inbound_totals_aggregates_per_verb():
    fleet = FleetMonitor()
    fleet.observe(
        "W0",
        {"links": {"W0->S0": {
            "msgs": 5, "bytes": 500,
            "verbs": {"PUSH": {"msgs": 3, "bytes": 300},
                      "PULL": {"msgs": 2, "bytes": 200}},
        }}},
        now=1.0,
    )
    fleet.observe(
        "W1",
        {"links": {"W1->S0": {
            "msgs": 1, "bytes": 50,
            "verbs": {"PUSH": {"msgs": 1, "bytes": 50}},
        }}},
        now=1.0,
    )
    tot = fleet.inbound_totals()["S0"]
    assert tot["bytes"] == 550 and tot["msgs"] == 6
    assert tot["verbs"]["PUSH"] == {"msgs": 4, "bytes": 350}
    assert tot["verbs"]["PULL"] == {"msgs": 2, "bytes": 200}


def test_inbound_totals_tolerates_verbless_digests():
    fleet = FleetMonitor()
    fleet.observe(
        "W0", {"links": {"W0->S0": {"msgs": 2, "bytes": 20}}}, now=1.0
    )
    tot = fleet.inbound_totals()["S0"]
    assert tot == {"bytes": 20, "msgs": 2, "verbs": {}}


def test_aggregator_derives_grp_pct():
    agg = TelemetryAggregator()
    assert agg.ingest(
        "S0",
        {"seq": 1, "t_mono_s": 1.0,
         "counters": {"group_pushes": 5, "group_members": 20}},
        now=1.0,
    )
    row = agg.latest()["S0"]
    assert row["grp_pct"] == 25.0
    # no group traffic -> no column (pstop renders '-')
    assert agg.ingest("W0", {"seq": 1, "t_mono_s": 1.0}, now=1.0)
    assert "grp_pct" not in agg.latest()["W0"]


def test_pstop_renders_grp_column():
    import pathlib
    import sys

    sys.path.insert(
        0, str(pathlib.Path(__file__).resolve().parent.parent / "tools")
    )
    import pstop

    latest = {
        "S0": {"seq": 3, "ingest_t": 1.0, "grp_pct": 25.0},
        "W0": {"seq": 2, "ingest_t": 1.0},
    }
    lines = pstop.render(latest)
    header = lines[0]
    assert "GRP%" in header
    assert header.index("CMPR%") < header.index("GRP%") < header.index(
        "SHED/S"
    )
    s_row = next(ln for ln in lines if ln.startswith("S0"))
    w_row = next(ln for ln in lines if ln.startswith("W0"))
    assert "25.0" in s_row
    # the non-server row renders '-' in the GRP% slot, not a crash
    assert "25.0" not in w_row
