"""Fault injection + elastic recovery: the SURVEY.md §5 failure paths.

Integration tests wiring Manager heartbeats, WorkloadPool reassignment, the
consistency clock, the KV layer, and snapshot recovery into one training run
— the coverage the reference never had (SURVEY.md §4 "fault paths effectively
untested" — an explicit opportunity).
"""

import threading
import time

import numpy as np
import pytest

from parameter_server_tpu.config import (
    ConsistencyConfig,
    ConsistencyMode,
    OptimizerConfig,
    TableConfig,
)
from parameter_server_tpu.core.manager import launch_local_cluster
from parameter_server_tpu.core.messages import server_id, worker_id
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core.van import LoopbackVan
from parameter_server_tpu.data.synthetic import SyntheticCTR
from parameter_server_tpu.kv.server import KVServer
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.learner.elastic import ElasticTrainer, recover_server
from parameter_server_tpu.utils.keys import HashLocalizer


def _shards(n_shards, batches_per_shard=2, batch=64, seed=0):
    data = SyntheticCTR(key_space=5000, nnz=8, batch_size=batch, seed=seed)
    return [
        [data.next_batch() for _ in range(batches_per_shard)]
        for _ in range(n_shards)
    ]


def _kv_cluster(van, posts, num_workers, num_servers, rows=2000):
    cfgs = {
        "w": TableConfig(
            name="w",
            rows=rows,
            dim=1,
            optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.1),
        )
    }
    loc = {"w": HashLocalizer(rows)}
    servers = {
        server_id(i): KVServer(posts[server_id(i)], cfgs, i, num_servers)
        for i in range(num_servers)
    }
    workers = {
        worker_id(i): KVWorker(
            posts[worker_id(i)], cfgs, num_servers, localizers=loc, min_bucket=16
        )
        for i in range(num_workers)
    }
    return cfgs, servers, workers, loc


def test_worker_death_reassigns_and_completes():
    """Kill one of three workers mid-run; survivors finish ALL workloads."""
    van = LoopbackVan()
    try:
        sched, managers, posts = launch_local_cluster(
            van, num_workers=3, num_servers=2, heartbeat_timeout=0.3
        )
        cfgs, servers, workers, _loc = _kv_cluster(van, posts, 3, 2)
        trainer = ElasticTrainer(
            workers,
            sched,
            _shards(12),
            ConsistencyConfig(mode=ConsistencyMode.ASP),
            managers=managers,
            heartbeat_interval=0.05,
            timeout=20.0,
        )
        done = threading.Event()
        result = {}

        def run():
            result["losses"] = trainer.run()
            done.set()

        t = threading.Thread(target=run)
        t.start()
        # let some work complete, then kill W2 (process stops + socket dies)
        deadline = time.monotonic() + 20
        while trainer.pool.num_done() < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        victim = worker_id(2)
        trainer.kill(victim)
        van.disconnect(victim)
        # scheduler sweep: the trainer's heartbeat thread keeps survivors
        # (and servers) alive; the victim goes silent and gets detected
        while not done.is_set() and time.monotonic() < deadline:
            sched.check_heartbeats()
            time.sleep(0.05)
        t.join(timeout=30)
        assert done.is_set(), (
            f"run incomplete: {trainer.pool.num_done()}/{len(trainer.pool)}"
        )
        assert trainer.pool.all_done()
        # death detection is asynchronous to completion: under suite load
        # the survivors can finish every workload before a sweep crosses
        # the victim's 0.3 s silence window — keep sweeping until the
        # detector fires rather than racing it (VERDICT r4 weak #6)
        detect_deadline = time.monotonic() + 10
        while sched.is_alive(victim) and time.monotonic() < detect_deadline:
            sched.check_heartbeats()
            time.sleep(0.05)
        assert not sched.is_alive(victim)
        # the victim's unfinished workloads were completed by survivors
        completed_by = {
            w.completed_by for w in trainer.pool._workloads.values()
        }
        assert completed_by <= {worker_id(0), worker_id(1), victim}
        assert len(result["losses"]) >= 24  # every batch trained at least once
    finally:
        van.close()


def test_server_death_recovery_from_snapshot(tmp_path):
    """Lose a server shard; rebuild it from the last committed checkpoint."""
    van = LoopbackVan()
    try:
        sched, managers, posts = launch_local_cluster(
            van, num_workers=2, num_servers=2, heartbeat_timeout=30
        )
        cfgs, servers, workers, loc = _kv_cluster(van, posts, 2, 2)
        trainer = ElasticTrainer(
            workers,
            sched,
            _shards(6),
            ConsistencyConfig(mode=ConsistencyMode.ASP),
            managers=managers,
            ckpt_root=str(tmp_path),
            ckpt_every=2,
            timeout=20.0,
        )
        trainer.run()
        assert trainer.last_ckpt_step is not None
        w0 = next(iter(workers.values()))
        probe = np.arange(100, dtype=np.uint64) * 31
        at_ckpt = None  # expected weights are whatever the checkpoint holds

        # SERVER DEATH: S1's HBM state is gone
        dead = server_id(1)
        van.disconnect(dead)
        with pytest.raises((RuntimeError, TimeoutError)):
            w0.pull_sync("w", probe, timeout=2)

        # RECOVERY: replacement server binds the same id, restores its shard
        van.unbind(dead)
        van.reconnect(dead)
        new_server = recover_server(
            lambda: KVServer(Postoffice(dead, van), cfgs, 1, 2),
            str(tmp_path),
        )
        servers[dead] = new_server
        after = w0.pull_sync("w", probe, timeout=10)
        # restored weights match the checkpoint exactly on S1's range and
        # training can continue (push works against the new server)
        from parameter_server_tpu import checkpoint

        step = checkpoint.latest_step(str(tmp_path))
        full = checkpoint.load_global_weights(str(tmp_path), step, "w")
        slots = loc["w"].assign(probe)
        part = new_server.partitions["w"]
        lo = int(part.offsets[1])
        on_s1 = slots >= lo
        np.testing.assert_allclose(
            after[on_s1], full[slots[on_s1], 0], rtol=1e-6
        )
        # training continues against the recovered server: the push must
        # observably change the weights (a dropped push would leave them)
        ts = w0.push("w", probe, np.ones((100, 1), np.float32))
        assert w0.wait(ts, timeout=10)
        after_push = w0.pull_sync("w", probe, timeout=10)
        assert np.abs(after_push - after).max() > 1e-4
    finally:
        van.close()


def test_dead_server_pull_raises_not_zeros():
    """A pull with a dead server leg must raise, never return silent zeros."""
    van = LoopbackVan()
    try:
        cfgs = {
            "w": TableConfig(
                name="w", rows=100, dim=1, optimizer=OptimizerConfig(kind="sgd")
            )
        }
        servers = [
            KVServer(Postoffice(server_id(i), van), cfgs, i, 2) for i in range(2)
        ]
        worker = KVWorker(Postoffice("W0", van), cfgs, 2, min_bucket=16)
        keys = np.arange(50, dtype=np.uint64)
        worker.pull_sync("w", keys, timeout=10)  # healthy pull works
        van.disconnect(server_id(0))
        with pytest.raises((RuntimeError, TimeoutError)):
            worker.pull_sync("w", keys, timeout=2)
    finally:
        van.close()


def test_dense_dead_server_pull_raises_not_zeros():
    """Dense pulls get the same dead-server semantics as sparse pulls."""
    from parameter_server_tpu.kv.dense import DenseKVServer, DenseKVWorker

    van = LoopbackVan()
    try:
        opt = OptimizerConfig(kind="sgd", learning_rate=1.0)
        servers = [
            DenseKVServer(
                Postoffice(server_id(i), van), {"m": (100, opt)}, i, 2
            )
            for i in range(2)
        ]
        worker = DenseKVWorker(Postoffice("W0", van), {"m": 100}, 2)
        assert worker.pull_sync("m", timeout=10).shape == (100,)
        van.disconnect(server_id(1))
        with pytest.raises((RuntimeError, TimeoutError)):
            worker.pull_sync("m", timeout=2)
    finally:
        van.close()
