"""Quantized wire plane with per-key error feedback (ISSUE 14 tentpole).

Acceptance anchors:

1. fp8 (e4m3/e5m2) numpy bit-trick codec: roundtrip error bounds, the
   seeded stochastic-rounding rng contract (unseeded refusal), and
   seed-replay determinism;
2. ``QuantizingFilter`` as the ``CoalescingVan`` codec: single-message
   and bundle roundtrips, PUSH-requests-only scope, ``FLAG_COMPRESSED``
   on the wire frame, MeteredVan raw-vs-wire byte accounting;
3. convergence parity — int8+EF training tracks the uncompressed run
   under seeded chaos across a LIVE migration, while plain int8 (no
   error feedback) measurably stalls on a dominant-magnitude gradient;
4. residual lifecycle — accumulators drop on ``adopt_routing`` (new
   routing epoch) and on a same-id restart (incarnation advance), never
   replaying stale error into a rebalanced/recovered fleet;
5. observability — ``cmpr_pct`` rides telemetry rows into pstop's CMPR%
   column, the compression SLO pair breaches on a bad ratio, the
   ``compress.*`` events are registered, and benchdiff parses the
   auto-recorded BENCH-COMPRESS block.
"""

import pathlib
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from parameter_server_tpu.config import (
    OptimizerConfig,
    TableConfig,
    WireCompressionConfig,
)
from parameter_server_tpu.core import coalesce, flightrec, frame
from parameter_server_tpu.core import filters as filters_mod
from parameter_server_tpu.core.chaos import ChaosVan
from parameter_server_tpu.core.coalesce import CoalescingVan
from parameter_server_tpu.core.filters import (
    QuantizingFilter,
    _resolve_per_row,
    find_quantizers,
    quantizer_from_tables,
)
from parameter_server_tpu.core.messages import Message, Task, TaskKind
from parameter_server_tpu.core.netmon import MeteredVan
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core.resender import ReliableVan
from parameter_server_tpu.core.telemetry import (
    TelemetryAggregator,
    TelemetryPublisher,
)
from parameter_server_tpu.core.van import LoopbackVan
from parameter_server_tpu.data.synthetic import SyntheticCTR
from parameter_server_tpu.kv.migrate import ShardMigrator
from parameter_server_tpu.kv.server import KVServer
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.models import linear
from parameter_server_tpu.ops.quantize import (
    FP8_FORMATS,
    dequantize_fp8,
    quantize_fp8,
)
from parameter_server_tpu.utils.metrics import transport_counters
from parameter_server_tpu.utils.slo import SloEngine, compression_plane_specs

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import benchdiff  # noqa: E402
import pstop  # noqa: E402

ROWS = 1 << 10
NUM_SERVERS = 2
STEPS = 12


def _int8_ef(**kw):
    return WireCompressionConfig(codec="int8", error_feedback=True, **kw)


def _table_cfgs(compression=None):
    return {
        "w": TableConfig(
            name="w", rows=ROWS, dim=1,
            optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.1),
            compression=compression,
        )
    }


def _push_msg(keys, values, table="w"):
    return Message(
        task=Task(TaskKind.PUSH, "kv", payload={"table": table}),
        sender="W0",
        recver="S0",
        keys=keys,
        values=list(values),
    )


# ------------------------------------------------------------ constants


def test_bundle_constants_match_coalesce():
    """filters.py mirrors the bundle literals to avoid an import cycle;
    this is the tripwire if coalesce.py ever renames them."""
    assert filters_mod._BUNDLE_CUSTOMER == coalesce.BUNDLE_CUSTOMER
    assert filters_mod._BUNDLE_KEY == coalesce.BUNDLE_KEY


# ------------------------------------------------------------------ fp8


@pytest.mark.parametrize("fmt,bound", [("e4m3", 0.0625), ("e5m2", 0.125)])
def test_fp8_roundtrip_relative_error_bound(fmt, bound):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 4)).astype(np.float32)
    q, s = quantize_fp8(x, fmt=fmt)
    got = dequantize_fp8(q, s, fmt=fmt)
    # normal-range values (>= ~1.6% of absmax for e4m3) carry a relative
    # error bounded by half an ulp: 2^-4 (3 mantissa bits) / 2^-3 (2 bits);
    # the near-zero tail falls into the scaled format's subnormal region,
    # where only the ABSOLUTE step (scale * min subnormal) is bounded
    amax = float(np.abs(x).max())
    normal = np.abs(x) >= amax / 32.0
    rel = np.abs(got - x) / np.maximum(np.abs(x), 1e-9)
    assert normal.sum() > 100
    assert float(rel[normal].max()) <= bound
    assert float(np.abs(got - x)[~normal].max()) <= amax / 32.0


@pytest.mark.parametrize("fmt", sorted(FP8_FORMATS))
def test_fp8_zeros_and_dynamic_range(fmt):
    q, s = quantize_fp8(np.zeros((8,), np.float32), fmt=fmt)
    np.testing.assert_array_equal(dequantize_fp8(q, s, fmt=fmt), 0.0)
    # four decades spanning the scaled format's finite range stay finite,
    # distinct, and ordered (no wraparound through the NaN/inf codes)
    x = np.array([0.01, 0.1, 1.0, 10.0, 100.0], np.float32)
    q, s = quantize_fp8(x, fmt=fmt)
    got = dequantize_fp8(q, s, fmt=fmt)
    assert np.all(np.isfinite(got)) and np.all(np.diff(got) > 0)


def test_fp8_stochastic_needs_seed_and_replays_deterministically():
    x = np.linspace(-2, 2, 97).astype(np.float32)
    with pytest.raises(ValueError, match="needs rng= or seed="):
        quantize_fp8(x, stochastic=True)
    a, _ = quantize_fp8(x, stochastic=True, seed=7)
    b, _ = quantize_fp8(x, stochastic=True, seed=7)
    c, _ = quantize_fp8(x, stochastic=True, seed=8)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_fp8_stochastic_rounding_is_unbiased():
    # a value midway between representables must average out to itself
    x = np.array([1.0, 0.30], np.float32)  # scale pinned by the 1.0
    rng = np.random.default_rng(3)
    draws = [
        dequantize_fp8(*quantize_fp8(x, stochastic=True, rng=rng))[1]
        for _ in range(2000)
    ]
    assert abs(float(np.mean(draws)) - 0.30) < 0.005


# ------------------------------------------------- per_row config plumbing


def test_per_row_resolution():
    wide = np.zeros((4, 32), np.float32)
    narrow = np.zeros((4, 1), np.float32)
    assert _resolve_per_row("auto", wide) is True
    assert _resolve_per_row("auto", narrow) is False
    assert _resolve_per_row(True, narrow) is True
    assert _resolve_per_row(False, wide) is False


def test_fixing_float_per_row_config_changes_precision():
    """Rows with wildly different magnitudes: per-row scales quantize the
    small row finely; a forced per-tensor scale flattens it to the shared
    grid.  The explicit config knob must be observable end to end."""
    from parameter_server_tpu.core.filters import FixingFloatFilter

    x = np.vstack([
        np.full((1, 32), 100.0, np.float32),
        np.full((1, 32), 0.1, np.float32),
    ])
    per_row = FixingFloatFilter(config=WireCompressionConfig(per_row=True))
    per_tensor = FixingFloatFilter(config=WireCompressionConfig(per_row=False))
    got_row = per_row.decode(per_row.encode(_push_msg(None, [x]))).values[0]
    got_tensor = (
        per_tensor.decode(per_tensor.encode(_push_msg(None, [x]))).values[0]
    )
    err_row = np.abs(got_row[1] - 0.1).max()
    err_tensor = np.abs(got_tensor[1] - 0.1).max()
    assert err_row < 0.001  # 0.1/127 grid
    assert err_tensor > 0.01  # 100/127 grid rounds 0.1 to 0


# ------------------------------------------------------- QuantizingFilter


def test_quantizing_filter_single_push_roundtrip_and_flag():
    codec = QuantizingFilter(default=_int8_ef())
    keys = np.arange(32, dtype=np.int64)
    vals = np.linspace(-1, 1, 32).astype(np.float32).reshape(32, 1)
    enc = codec.encode(_push_msg(keys, [vals]))
    assert enc.values[0].dtype == np.int8
    assert frame.COMPRESSED_KEY in enc.task.payload
    # the frame codec stamps the compressed flag from the payload marker
    info = frame.peek(frame.encode(enc))
    assert info.flags & frame.FLAG_COMPRESSED
    dec = codec.decode(enc)
    assert frame.COMPRESSED_KEY not in dec.task.payload
    assert dec.values[0].dtype == np.float32
    np.testing.assert_allclose(dec.values[0], vals, atol=1.0 / 127 + 1e-6)
    c = codec.counters()
    assert c["compress_raw_bytes"] > c["compress_wire_bytes"] > 0


def test_quantizing_filter_scopes_to_push_requests_only():
    codec = QuantizingFilter(default=_int8_ef())
    vals = [np.ones((8, 1), np.float32)]
    pull = Message(
        task=Task(TaskKind.PULL, "kv", payload={"table": "w"}),
        sender="W0", recver="S0", keys=np.arange(8), values=list(vals),
    )
    assert codec.encode(pull) is pull
    reply = _push_msg(np.arange(8), vals)
    reply.is_request = False
    assert codec.encode(reply) is reply
    # tables routed to codec "none" pass through untouched
    off = QuantizingFilter(
        default=WireCompressionConfig(),
        per_table={"w": WireCompressionConfig()},
    )
    msg = _push_msg(np.arange(8), vals)
    assert off.encode(msg) is msg


def test_error_feedback_recovers_sub_step_gradients():
    """The EF physics: a plane whose absmax is ~300x the interesting
    values rounds them to ZERO every push; error feedback accumulates the
    loss and emits it once it crosses a quant step."""
    keys = np.arange(2, dtype=np.int64)
    g = np.array([[100.0], [0.3]], np.float32)

    def total(codec):
        out = np.zeros((2, 1), np.float32)
        for _ in range(10):
            dec = codec.decode(codec.encode(_push_msg(keys, [g.copy()])))
            out += dec.values[0]
        return out

    ef = total(QuantizingFilter(default=_int8_ef()))
    plain = total(
        QuantizingFilter(
            default=WireCompressionConfig(codec="int8", error_feedback=False)
        )
    )
    assert abs(ef[1, 0] - 3.0) < 100.0 / 127  # within one quant step
    assert plain[1, 0] == 0.0  # every push rounded the 0.3 away
    assert abs(ef[0, 0] - 1000.0) < 1e-3


def test_quantizer_from_tables_accepts_dicts_and_gates_on_config():
    assert quantizer_from_tables(_table_cfgs(None)) is None
    codec = quantizer_from_tables(_table_cfgs(_int8_ef()))
    assert isinstance(codec, QuantizingFilter)
    assert codec.per_table["w"].codec == "int8"


# ------------------------------------------------ cluster: bytes + parity


def _codec_stack(compression, *, seed=0, drop=0.0):
    """CoalescingVan(ReliableVan(ChaosVan(LoopbackVan)), codec=...) —
    the codec runs once per bundle ABOVE the reliability layer, so
    retransmits resend the already-quantized frame (no double EF)."""
    chaos = ChaosVan(LoopbackVan(), seed=seed, drop=drop)
    rel = ReliableVan(
        chaos, timeout=0.1, backoff=1.0, max_retries=60, seed=seed
    )
    codec = quantizer_from_tables(
        _table_cfgs(compression)
    ) if compression is not None else None
    van = CoalescingVan(MeteredVan(rel), codec=codec)
    return van, rel, codec


def test_cluster_roundtrip_and_metered_raw_bytes():
    cfgs = _table_cfgs(_int8_ef())
    van, _rel, codec = _codec_stack(_int8_ef())
    try:
        servers = [
            KVServer(Postoffice(f"S{s}", van), cfgs, s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        ]
        worker = KVWorker(Postoffice("W0", van), cfgs, NUM_SERVERS)
        rng = np.random.default_rng(0)
        keys = np.sort(rng.choice(ROWS, 200, replace=False)).astype(np.int64)
        vals = rng.normal(size=(keys.size, 1)).astype(np.float32)
        worker.push_sync("w", keys, vals, timeout=60)
        got = worker.pull_sync("w", keys, timeout=60)
        # adagrad lr=0.1 applied the dequantized push: within one int8 step
        assert np.all(np.isfinite(got)) and float(np.abs(got).max()) > 0
        c = transport_counters(van)
        assert c["compress_raw_bytes"] > c["compress_wire_bytes"] > 0
        # satellite 2: MeteredVan books what the frame WOULD have weighed
        assert c["wire_raw_bytes"] > c["wire_bytes"] > 0
        saved = c["wire_raw_bytes"] - c["wire_bytes"]
        assert saved == c["compress_raw_bytes"] - c["compress_wire_bytes"]
        assert len(find_quantizers(van)) == 1
        assert servers  # keep the recv handlers alive until close
    finally:
        van.close()


@pytest.mark.chaos
def test_plain_int8_stalls_where_error_feedback_converges():
    """Dominant-magnitude gradient through a REAL cluster under seeded
    chaos: per-tensor int8 rounds the small coordinates to zero every
    step, so without EF they never move; with EF the carried residual
    crosses the quant step and the accumulated update converges.  One
    server so the dominant coordinate shares every wire message."""
    pushes = 12
    cfgs = {
        "w": TableConfig(
            name="w", rows=64, dim=1,
            optimizer=OptimizerConfig(kind="sgd", learning_rate=1.0),
        )
    }

    def run(compression):
        chaos = ChaosVan(LoopbackVan(), seed=1, drop=0.05)
        rel = ReliableVan(
            chaos, timeout=0.1, backoff=1.0, max_retries=60, seed=1
        )
        codec = QuantizingFilter(default=compression) if compression else None
        van = CoalescingVan(rel, codec=codec)
        try:
            cfg = {
                "w": TableConfig(
                    name="w", rows=64, dim=1,
                    optimizer=cfgs["w"].optimizer, compression=compression,
                )
            }
            server = KVServer(Postoffice("S0", van), cfg, 0, 1)
            worker = KVWorker(Postoffice("W0", van), cfg, 1)
            keys = np.arange(40, dtype=np.int64)
            g = np.full((keys.size, 1), -0.3, np.float32)
            g[0, 0] = -100.0  # pins the per-tensor scale at ~100/127
            for _ in range(pushes):
                worker.push_sync("w", keys, g.copy(), timeout=60)
            w = worker.pull_sync("w", keys, timeout=60)
            assert server.pushes >= pushes
            return np.asarray(w, np.float32).reshape(-1)
        finally:
            van.close()

    exact = run(None)
    ef = run(_int8_ef())
    plain = run(WireCompressionConfig(codec="int8", error_feedback=False))
    # HashLocalizer folds keys into 64 slots, so colliding keys SUM their
    # gradients: the exact arm is the per-slot ground truth.  Slots hit by
    # exactly one small key accumulated pushes * 0.3 = 3.6 — those are the
    # sub-quant-step coordinates plain int8 must keep rounding to zero
    # (0.3 / (100/127) = 0.38 -> rint 0), while multi-key collisions can
    # legitimately cross the step.
    single = np.isclose(exact, pushes * 0.3, atol=1e-3)
    assert single.sum() >= 5
    # EF arm: every coordinate within ONE quant step of the exact run
    assert float(np.abs(ef - exact).max()) <= 100.0 / 127 + 1e-5
    # plain int8: the single-key small coordinates never moved
    assert float(np.abs(plain[single]).max()) == 0.0


@pytest.mark.chaos
@pytest.mark.migration
def test_training_parity_int8_ef_under_chaos_across_live_migration():
    """Real sparse-LR training, uncompressed vs int8+EF, both under the
    SAME seeded chaos, with a live migration (move + adopt_routing, which
    resets residuals) in the middle of the compressed run.  Final losses
    must agree within a tight tolerance."""

    def run(compression, migrate):
        van, _rel, codec = _codec_stack(compression, seed=2, drop=0.05)
        cfgs = _table_cfgs(compression)
        try:
            servers = [
                KVServer(Postoffice(f"S{s}", van), cfgs, s, NUM_SERVERS)
                for s in range(NUM_SERVERS)
            ]
            worker = KVWorker(Postoffice("W0", van), cfgs, NUM_SERVERS)
            data = SyntheticCTR(
                key_space=4 * ROWS, nnz=8, batch_size=128, seed=3
            )
            batches = [data.next_batch() for _ in range(STEPS)]
            mig = ShardMigrator(Postoffice("M0", van), chunk_rows=256)
            losses = []
            for i, (keys, labels) in enumerate(batches):
                if migrate and i == STEPS // 2:
                    new_routing = mig.migrate(
                        worker.routing, "w", 768, ROWS, 0
                    )
                    assert worker.adopt_routing(new_routing)
                    if codec is not None:
                        assert codec.resets >= 1
                w_pos = worker.pull_sync("w", keys, timeout=60)
                g, _gb, loss = linear.grad_rows(
                    jnp.asarray(w_pos), jnp.asarray(labels)
                )
                worker.push_sync(
                    "w", keys, np.asarray(g) / labels.shape[0], timeout=60
                )
                losses.append(float(loss))
            assert servers
            return losses
        finally:
            van.close()

    ref = run(None, migrate=False)
    comp = run(_int8_ef(), migrate=True)
    assert ref[-1] < ref[0]  # the reference actually learned
    assert abs(comp[-1] - ref[-1]) < 0.03
    assert abs(float(np.mean(comp[-3:])) - float(np.mean(ref[-3:]))) < 0.03


# ------------------------------------------------------ residual lifecycle


@pytest.mark.migration
def test_residuals_reset_on_adopt_routing():
    flightrec.configure(enabled=True, clear=True)
    cfgs = _table_cfgs(_int8_ef())
    codec = quantizer_from_tables(cfgs)
    van = CoalescingVan(LoopbackVan(), codec=codec)
    try:
        servers = [
            KVServer(Postoffice(f"S{s}", van), cfgs, s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        ]
        worker = KVWorker(Postoffice("W0", van), cfgs, NUM_SERVERS)
        rng = np.random.default_rng(4)
        keys = np.sort(rng.choice(ROWS, 100, replace=False)).astype(np.int64)
        worker.push_sync(
            "w", keys, rng.normal(size=(100, 1)).astype(np.float32),
            timeout=60,
        )
        assert codec._residuals and codec.resets == 0
        mig = ShardMigrator(Postoffice("M0", van), chunk_rows=256)
        new_routing = mig.migrate(worker.routing, "w", 768, ROWS, 0)
        assert worker.adopt_routing(new_routing)
        assert codec.resets >= 1 and not codec._residuals
        events = [
            e for e in flightrec.get().events()
            if e["kind"] == "compress.residual_reset"
        ]
        assert events and events[-1]["reason"] == "adopt_routing"
        assert servers
    finally:
        van.close()
        flightrec.configure(enabled=True, clear=True)


def test_residuals_reset_on_same_id_restart():
    """``restart_node`` (PR-4 same-id restart) advances the incarnation;
    the CoalescingVan ctor subscribed the codec to ReliableVan's
    incarnation-advance hook, so carried error dies with the old process."""
    cfgs = _table_cfgs(_int8_ef())
    codec = quantizer_from_tables(cfgs)
    rel = ReliableVan(
        LoopbackVan(), timeout=0.1, backoff=1.0, max_retries=60, seed=0
    )
    van = CoalescingVan(rel, codec=codec)
    try:
        servers = [
            KVServer(Postoffice(f"S{s}", van), cfgs, s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        ]
        worker = KVWorker(Postoffice("W0", van), cfgs, NUM_SERVERS)
        rng = np.random.default_rng(5)
        keys = np.sort(rng.choice(ROWS, 64, replace=False)).astype(np.int64)
        worker.push_sync(
            "w", keys, rng.normal(size=(64, 1)).astype(np.float32),
            timeout=60,
        )
        assert codec._residuals
        rel.restart_node("S0")
        assert codec.resets >= 1 and not codec._residuals
        assert servers
    finally:
        van.close()


# --------------------------------------------------------- observability


def test_cmpr_pct_rides_telemetry_into_pstop():
    class _Src:
        def counters(self):
            return {"wire_bytes": 300, "wire_raw_bytes": 1200}

    flightrec.configure(clear=True)
    try:
        rec = flightrec.FlightRecorder(capacity=16)
        pub = TelemetryPublisher("W0", None, recorder=rec, sources=[_Src()])
        agg = TelemetryAggregator()
        assert agg.ingest("W0", pub.frame(now=1.0), now=1.0)
        row = agg.latest()["W0"]
        assert row["cmpr_pct"] == 25.0
        out = "\n".join(pstop.render(agg.latest()))
        assert "CMPR%" in out and "25.0" in out
    finally:
        flightrec.configure(clear=True)


def test_compression_slo_breaches_on_bad_ratio():
    specs = compression_plane_specs(max_ratio_pct=50.0)
    assert [s.metric for s in specs] == [
        "compress_ratio_pct", "compress_residual_norm",
    ]
    eng = SloEngine(specs)
    eng.ingest_counters("W0", {"compress_ratio_pct": 80.0}, now=1.0)
    verdicts = eng.evaluate(now=1.5)
    assert not verdicts["W0"].healthy
    assert "compress-ratio" in verdicts["W0"].breaches
    eng.ingest_counters("W0", {"compress_ratio_pct": 26.0}, now=20.0)
    assert eng.evaluate(now=20.5)["W0"].healthy


def test_compress_events_registered_everywhere():
    kinds = {"compress.encode", "compress.decode", "compress.residual_reset"}
    assert kinds <= flightrec.EVENTS
    import check_wrappers  # tools/, via the sys.path insert above

    assert kinds <= set(check_wrappers.REQUIRED_EVENTS)


def test_benchdiff_parses_bench_compress_block():
    """Satellite 6 smoke: the auto-recorded BENCH-COMPRESS block is
    benchdiff-visible, so bench_gate diffs it like every other arm."""
    metrics = benchdiff.load_baseline_md(REPO / "BASELINE.md")
    compress = {k: v for k, v in metrics.items() if k.startswith("compress/")}
    assert "compress/pushed-value-plane reduction" in compress
    assert compress["compress/pushed-value-plane reduction"]["value"] >= 3.0
    assert any("examples/s" in k for k in compress)
