"""Tracing subsystem: spans, histograms, exports, KV-layer wiring."""

import builtins
import json
import threading
import time

import numpy as np

from parameter_server_tpu.config import OptimizerConfig, TableConfig
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core.van import LoopbackVan
from parameter_server_tpu.kv.server import KVServer
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.utils.trace import (
    NULL_TRACER,
    LatencyHistogram,
    Tracer,
    resource_usage,
)


def test_span_recording_and_histogram():
    tr = Tracer()
    for i in range(20):
        with tr.span("op", i=i):
            time.sleep(0.001)
    h = tr.histogram("op")
    assert h["count"] == 20
    assert h["p50_us"] >= 1000  # slept >= 1ms
    assert h["p99_us"] >= h["p50_us"]
    assert h["max_us"] >= h["p99_us"]
    assert tr.histogram("missing")["count"] == 0
    assert "op" in tr.summary()


def test_span_thread_safety_and_capacity():
    tr = Tracer(capacity=100)

    def worker():
        for _ in range(100):
            with tr.span("w"):
                pass

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(tr.spans("w")) == 100  # bounded by capacity, no crash


def test_null_tracer_records_nothing():
    with NULL_TRACER.span("x"):
        pass
    NULL_TRACER.record("y", 0.5)
    assert NULL_TRACER.spans() == []


def test_exports(tmp_path):
    tr = Tracer()
    with tr.span("a", table="w"):
        pass
    tr.record("b", 0.002)
    chrome = tmp_path / "trace.json"
    tr.dump_chrome_trace(str(chrome))
    events = json.loads(chrome.read_text())["traceEvents"]
    assert {e["name"] for e in events} == {"a", "b"}
    assert all(e["ph"] == "X" and "dur" in e for e in events)
    assert any(e.get("args") == {"table": "w"} for e in events)

    jl = tmp_path / "trace.jsonl"
    tr.dump_jsonl(str(jl))
    rows = [json.loads(line) for line in jl.read_text().splitlines()]
    assert len(rows) == 2 and rows[1]["dur_s"] == 0.002


def test_resource_usage_fields():
    ru = resource_usage()
    assert ru["rss_mb"] > 1.0
    assert ru["cpu_user_s"] >= 0.0
    assert ru["threads"] >= 1


def test_resource_usage_non_linux_fallback(monkeypatch):
    """No /proc (macOS/Windows): a time-only dict, never an exception."""
    real_open = builtins.open

    def fake_open(path, *args, **kwargs):
        if str(path).startswith("/proc/"):
            raise OSError("no /proc on this platform")
        return real_open(path, *args, **kwargs)

    monkeypatch.setattr(builtins, "open", fake_open)
    ru = resource_usage()
    assert set(ru) == {"time"}
    assert ru["time"] > 0


# ------------------------------------------------------- LatencyHistogram


def test_latency_histogram_exact_moments_and_bounded_percentiles():
    h = LatencyHistogram()
    values = [0.0005, 0.001, 0.002, 0.004, 0.008, 0.5]
    for v in values:
        h.record(v)
    assert h.count == len(values)
    assert abs(h.sum_s - sum(values)) < 1e-12  # count/sum/max are EXACT
    assert h.max_s == 0.5
    # percentiles are bucket upper bounds: >= the true quantile, <= max,
    # within the 25% bucket growth factor
    p50 = h.percentile(0.50)
    assert 0.002 <= p50 <= 0.002 * LatencyHistogram.GROWTH
    assert h.percentile(0.99) <= h.max_s
    assert h.percentile(1.0) == h.max_s
    # negative durations clamp to bucket 0, never throw
    h.record(-1.0)
    assert h.count == len(values) + 1


def test_latency_histogram_empty_and_extremes():
    h = LatencyHistogram()
    assert h.percentile(0.99) == 0.0
    assert h.stats() == {"count": 0}
    h.record(1e-9)  # below BASE -> bucket 0
    h.record(1e9)  # beyond the last bucket -> max stays exact, but
    # percentiles saturate at the last bucket's upper edge (<= max)
    assert h.max_s == 1e9
    assert h.percentile(1.0) <= h.max_s
    last_edge = LatencyHistogram.BASE * (
        LatencyHistogram.GROWTH ** (LatencyHistogram.NBUCKETS - 1)
    )
    assert h.percentile(1.0) == last_edge  # ~27 min: the range ceiling


def test_latency_histogram_merge_equals_union():
    a, b, u = LatencyHistogram(), LatencyHistogram(), LatencyHistogram()
    for i in range(50):
        v = 1e-5 * (i + 1)
        (a if i % 2 else b).record(v)
        u.record(v)
    a.merge(b)
    assert a.counts == u.counts
    assert a.count == u.count
    assert abs(a.sum_s - u.sum_s) < 1e-12
    assert a.percentile(0.9) == u.percentile(0.9)


def test_latency_histogram_dict_round_trip():
    h = LatencyHistogram()
    for v in (1e-5, 3e-4, 0.02, 1.5):
        h.record(v)
    d = h.to_dict()
    json.dumps(d)  # heartbeat-safe
    back = LatencyHistogram.from_dict(d)
    assert back.counts == h.counts
    assert back.count == h.count
    assert back.max_s == h.max_s


def test_tracer_histogram_survives_deque_wraparound():
    """The old bounded-deque histogram silently became 'stats of the last
    capacity spans'; the LatencyHistogram backing must count everything."""
    tr = Tracer(capacity=10)
    for _ in range(100):
        tr.record("op", 0.001)
    assert len(tr.spans("op")) == 10  # timeline stays bounded...
    assert tr.histogram("op")["count"] == 100  # ...aggregates do not
    assert tr.totals()["op"] >= 0.1 - 1e-9
    digests = tr.digests()
    assert digests["op"]["count"] == 100


def test_kv_layer_traced_push_pull():
    van = LoopbackVan()
    try:
        cfgs = {
            "w": TableConfig(
                name="w", rows=500, dim=2,
                optimizer=OptimizerConfig(kind="sgd", learning_rate=1.0),
            )
        }
        server_tracer = Tracer()
        worker_tracer = Tracer()
        servers = [
            KVServer(
                Postoffice(f"S{i}", van), cfgs, i, 2, tracer=server_tracer
            )
            for i in range(2)
        ]
        worker = KVWorker(
            Postoffice("W0", van), cfgs, 2, min_bucket=16, tracer=worker_tracer
        )
        keys = np.arange(40, dtype=np.uint64)
        for _ in range(3):
            worker.wait(
                worker.push("w", keys, np.ones((40, 2), np.float32)), timeout=10
            )
            worker.pull_sync("w", keys, timeout=10)
        s = worker_tracer.summary()
        assert s["kv.push"]["count"] == 3
        assert s["kv.pull.wait"]["count"] == 3
        ss = server_tracer.summary()
        # both servers share the tracer: 3 pushes+pulls x 2 servers
        assert ss["kv.server.push"]["count"] == 6
        assert ss["kv.server.pull"]["count"] == 6
        assert ss["kv.server.push"]["mean_us"] > 0
    finally:
        van.close()
