"""Tracing subsystem: spans, histograms, exports, KV-layer wiring."""

import json
import threading
import time

import numpy as np

from parameter_server_tpu.config import OptimizerConfig, TableConfig
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core.van import LoopbackVan
from parameter_server_tpu.kv.server import KVServer
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.utils.trace import NULL_TRACER, Tracer, resource_usage


def test_span_recording_and_histogram():
    tr = Tracer()
    for i in range(20):
        with tr.span("op", i=i):
            time.sleep(0.001)
    h = tr.histogram("op")
    assert h["count"] == 20
    assert h["p50_us"] >= 1000  # slept >= 1ms
    assert h["p99_us"] >= h["p50_us"]
    assert h["max_us"] >= h["p99_us"]
    assert tr.histogram("missing")["count"] == 0
    assert "op" in tr.summary()


def test_span_thread_safety_and_capacity():
    tr = Tracer(capacity=100)

    def worker():
        for _ in range(100):
            with tr.span("w"):
                pass

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(tr.spans("w")) == 100  # bounded by capacity, no crash


def test_null_tracer_records_nothing():
    with NULL_TRACER.span("x"):
        pass
    NULL_TRACER.record("y", 0.5)
    assert NULL_TRACER.spans() == []


def test_exports(tmp_path):
    tr = Tracer()
    with tr.span("a", table="w"):
        pass
    tr.record("b", 0.002)
    chrome = tmp_path / "trace.json"
    tr.dump_chrome_trace(str(chrome))
    events = json.loads(chrome.read_text())["traceEvents"]
    assert {e["name"] for e in events} == {"a", "b"}
    assert all(e["ph"] == "X" and "dur" in e for e in events)
    assert any(e.get("args") == {"table": "w"} for e in events)

    jl = tmp_path / "trace.jsonl"
    tr.dump_jsonl(str(jl))
    rows = [json.loads(line) for line in jl.read_text().splitlines()]
    assert len(rows) == 2 and rows[1]["dur_s"] == 0.002


def test_resource_usage_fields():
    ru = resource_usage()
    assert ru["rss_mb"] > 1.0
    assert ru["cpu_user_s"] >= 0.0
    assert ru["threads"] >= 1


def test_kv_layer_traced_push_pull():
    van = LoopbackVan()
    try:
        cfgs = {
            "w": TableConfig(
                name="w", rows=500, dim=2,
                optimizer=OptimizerConfig(kind="sgd", learning_rate=1.0),
            )
        }
        server_tracer = Tracer()
        worker_tracer = Tracer()
        servers = [
            KVServer(
                Postoffice(f"S{i}", van), cfgs, i, 2, tracer=server_tracer
            )
            for i in range(2)
        ]
        worker = KVWorker(
            Postoffice("W0", van), cfgs, 2, min_bucket=16, tracer=worker_tracer
        )
        keys = np.arange(40, dtype=np.uint64)
        for _ in range(3):
            worker.wait(
                worker.push("w", keys, np.ones((40, 2), np.float32)), timeout=10
            )
            worker.pull_sync("w", keys, timeout=10)
        s = worker_tracer.summary()
        assert s["kv.push"]["count"] == 3
        assert s["kv.pull.wait"]["count"] == 3
        ss = server_tracer.summary()
        # both servers share the tracer: 3 pushes+pulls x 2 servers
        assert ss["kv.server.push"]["count"] == 6
        assert ss["kv.server.pull"]["count"] == 6
        assert ss["kv.server.push"]["mean_us"] > 0
    finally:
        van.close()
