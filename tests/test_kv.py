import numpy as np
import pytest

import jax.numpy as jnp

from parameter_server_tpu.config import OptimizerConfig, TableConfig
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core.van import LoopbackVan
from parameter_server_tpu.kv.optim import make_optimizer
from parameter_server_tpu.kv.partition import RangePartition
from parameter_server_tpu.kv.server import KVServer
from parameter_server_tpu.kv.table import KVTable
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.utils.keys import HashLocalizer


def test_unknown_optimizer():
    with pytest.raises(ValueError, match="unknown optimizer"):
        make_optimizer(OptimizerConfig(kind="lbfgs"))


def test_sgd_apply():
    opt = make_optimizer(OptimizerConfig(kind="sgd", learning_rate=0.5, l2=0.1))
    v = jnp.ones((2, 3))
    g = jnp.full((2, 3), 2.0)
    new, _ = opt.apply(v, {}, g)
    np.testing.assert_allclose(np.asarray(new), 1 - 0.5 * (2 + 0.1), rtol=1e-6)


def test_adagrad_apply_matches_numpy():
    opt = make_optimizer(OptimizerConfig(kind="adagrad", learning_rate=0.1, eps=1e-8))
    v = jnp.zeros((4, 1))
    state = {"sum_sq": jnp.zeros((4, 1))}
    g = jnp.array([[1.0], [2.0], [0.0], [-1.0]])
    new, ns = opt.apply(v, state, g)
    gn = np.asarray(g)
    expect = -0.1 * gn / (np.abs(gn) + 1e-8)
    expect[2] = 0.0
    np.testing.assert_allclose(np.asarray(new), expect, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ns["sum_sq"]), gn * gn)


def test_adam_per_row_step():
    opt = make_optimizer(OptimizerConfig(kind="adam", learning_rate=0.01))
    v = jnp.zeros((2, 1))
    state = {k: jnp.zeros((2, 1)) for k in ("m", "v", "t")}
    g = jnp.array([[1.0], [0.0]])
    new, ns = opt.apply(v, state, g)
    # row 0 took a step; first adam step size ~= lr
    assert abs(float(new[0, 0]) + 0.01) < 1e-3
    assert float(ns["t"][0, 0]) == 1.0 and float(ns["t"][1, 0]) == 1.0


def test_ftrl_lazy_weights_and_sparsity():
    cfg = OptimizerConfig(kind="ftrl", l1=1.0, ftrl_alpha=0.1)
    opt = make_optimizer(cfg)
    z = jnp.array([[0.5], [-5.0]])
    state = {"n": jnp.array([[1.0], [4.0]])}
    w = opt.pull_weights(z, state)
    assert float(w[0, 0]) == 0.0  # |z| <= l1 -> exactly zero (L1 sparsity)
    expect = -(-5.0 + 1.0) / ((1.0 + 2.0) / 0.1)
    np.testing.assert_allclose(float(w[1, 0]), expect, rtol=1e-5)


def test_ftrl_learns_sign():
    """Pushing constant positive gradients drives the weight negative."""
    cfg = OptimizerConfig(kind="ftrl", l1=0.01, ftrl_alpha=0.5)
    t = KVTable(TableConfig(name="w", rows=8, dim=1, optimizer=cfg))
    ids = jnp.arange(8, dtype=jnp.int32)
    for _ in range(20):
        t.push(ids, jnp.ones((8, 1)))
    w = np.asarray(t.pull(ids))
    assert np.all(w < 0)


def test_table_push_pull_shadow():
    cfg = TableConfig(
        name="emb",
        rows=64,
        dim=8,
        optimizer=OptimizerConfig(kind="sgd", learning_rate=1.0),
    )
    t = KVTable(cfg)
    rng = np.random.default_rng(0)
    shadow = np.zeros((65, 8), dtype=np.float64)
    for _ in range(5):
        ids = np.sort(rng.permutation(64)[:16]).astype(np.int32)
        grads = rng.normal(size=(16, 8)).astype(np.float32)
        t.push(jnp.asarray(ids), jnp.asarray(grads))
        shadow[ids] -= grads
    np.testing.assert_allclose(
        np.asarray(t.pull(jnp.arange(64, dtype=jnp.int32))),
        shadow[:64],
        rtol=1e-5,
        atol=1e-6,
    )


def test_table_init_scale():
    cfg = TableConfig(name="emb", rows=100, dim=16, init_scale=0.1)
    t = KVTable(cfg)
    vals = np.asarray(t.value)
    assert 0.01 < vals[:100].std() < 0.3
    np.testing.assert_allclose(vals[100], 0.0)  # trash row zeroed


def test_trash_row_stays_zero_under_pad_gradients():
    """PAD_KEY positions in variable-nnz batches must not poison the trash row."""
    from parameter_server_tpu.utils.keys import PAD_KEY, HashLocalizer, localize_to_slots

    cfg = TableConfig(
        name="w", rows=64, dim=4,
        optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.5),
    )
    t = KVTable(cfg)
    loc = HashLocalizer(64)
    keys = np.array([5, 9, PAD_KEY, PAD_KEY], dtype=np.uint64)
    slots, inverse, n = localize_to_slots(keys, loc, min_bucket=8)
    grads = np.ones((4, 4), dtype=np.float32)  # pads carry REAL grads
    combined = t.combine(jnp.asarray(inverse), jnp.asarray(grads), slots.shape[0])
    t.push(jnp.asarray(slots), combined)
    np.testing.assert_allclose(np.asarray(t.value)[64], 0.0)  # trash reset
    np.testing.assert_allclose(np.asarray(t.state["sum_sq"])[64], 0.0)
    # pulls of pad positions are exactly zero
    pulled = np.asarray(t.pull(jnp.asarray(slots)))
    trash_positions = slots == 64
    np.testing.assert_allclose(pulled[trash_positions], 0.0)


def test_hash_localizer_rejects_giant_capacity():
    from parameter_server_tpu.utils.keys import HashLocalizer

    with pytest.raises(ValueError, match="int32"):
        HashLocalizer(3_000_000_000)


def test_range_partition():
    p = RangePartition(rows=10, num_servers=3)
    np.testing.assert_array_equal(p.offsets, [0, 4, 7, 10])
    ids = np.array([0, 3, 4, 9, 10], dtype=np.int32)  # 10 == trash
    parts = list(p.slice_ids(ids))
    assert [seg for _, seg, _ in parts] == [slice(0, 2), slice(2, 3), slice(3, 5)]
    np.testing.assert_array_equal(parts[0][2], [0, 3])
    np.testing.assert_array_equal(parts[1][2], [0])
    np.testing.assert_array_equal(parts[2][2], [2, 3])  # local trash == 3


def test_range_partition_empty_segments():
    p = RangePartition(rows=100, num_servers=4)
    parts = list(p.slice_ids(np.array([0, 1], dtype=np.int32)))
    assert len(parts) == 4
    assert parts[1][2].size == 0 and parts[3][2].size == 0


@pytest.fixture
def cluster():
    van = LoopbackVan()
    cfgs = {
        "w": TableConfig(
            name="w",
            rows=1000,
            dim=4,
            optimizer=OptimizerConfig(kind="sgd", learning_rate=1.0),
        )
    }
    servers = [
        KVServer(Postoffice(f"S{i}", van), cfgs, i, 2) for i in range(2)
    ]
    worker = KVWorker(Postoffice("W0", van), cfgs, 2, min_bucket=16)
    yield van, servers, worker, cfgs
    van.close()


def test_worker_server_roundtrip(cluster):
    van, servers, worker, cfgs = cluster
    keys = np.array([17, 999999, 17, 42], dtype=np.uint64)
    # initial pull: zeros
    w0 = worker.pull_sync("w", keys, timeout=10)
    assert w0.shape == (4, 4)
    np.testing.assert_allclose(w0, 0.0)
    # push gradient 1.0 everywhere; key 17 appears twice -> combined grad 2
    ts = worker.push("w", keys, np.ones((4, 4), dtype=np.float32))
    assert worker.wait(ts, timeout=10)
    w1 = worker.pull_sync("w", keys, timeout=10)
    np.testing.assert_allclose(w1[0], -2.0, rtol=1e-6)  # sgd lr=1: w -= g
    np.testing.assert_allclose(w1[2], -2.0, rtol=1e-6)
    np.testing.assert_allclose(w1[1], -1.0, rtol=1e-6)
    np.testing.assert_allclose(w1[3], -1.0, rtol=1e-6)
    assert servers[0].pushes + servers[1].pushes == 2


def test_worker_multi_worker_consistency(cluster):
    """Two workers sharing HashLocalizers see each other's pushes."""
    van, servers, worker, cfgs = cluster
    worker2 = KVWorker(Postoffice("W1", van), cfgs, 2, min_bucket=16)
    keys = np.array([123456789], dtype=np.uint64)
    ts = worker.push("w", keys, np.full((1, 4), 3.0, dtype=np.float32))
    worker.wait(ts, timeout=10)
    w = worker2.pull_sync("w", keys, timeout=10)
    np.testing.assert_allclose(w[0], -3.0, rtol=1e-6)
