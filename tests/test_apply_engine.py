"""Bundle-batched fused apply engine (ISSUE 11): parity, sync-free acks,
compile-cache discipline, and e2e chaos bitwise equivalence.

``KVServer.handle_request_batch`` collapses a coalesced bundle's
consecutive same-table PUSHes into ONE donated-buffer device apply and
defers every PULL's readback to a single ``device_get`` per bundle.  The
contract under test:

- ``dup_policy="rounds"`` (default) is **bitwise-identical to sequential
  per-member applies for every optimizer**, including bundles whose
  members push overlapping row ids (occurrence-round partitioning applies
  each row's t-th contribution in member order).
- ``dup_policy="combine"`` pre-merges duplicate rows on device
  (``segment_combine``) — one apply always, classic PS sum semantics,
  sequential-identical when member rows are disjoint.
- The PUSH ack path never observes device results (``is_ready`` stays
  False through the ack — the behavioral twin of the
  ``tools/check_wrappers.py`` AST ban).
- Compile-cache keys stay bucketed: randomized request sizes compile at
  most one step per (members, bucket) signature, never per raw size.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from parameter_server_tpu.config import (
    ApplyEngineConfig,
    OptimizerConfig,
    TableConfig,
)
from parameter_server_tpu.core.chaos import ChaosVan
from parameter_server_tpu.core.coalesce import CoalescingVan
from parameter_server_tpu.core.messages import Message, Task, TaskKind
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core.resender import ReliableVan
from parameter_server_tpu.core.van import LoopbackVan
from parameter_server_tpu.kv.server import KVServer, _bucket
from parameter_server_tpu.kv.worker import KVWorker

DIM = 4
ROWS = 64


def _server(van, *, opt="adagrad", apply=None, rows=ROWS, node="S0"):
    cfg = TableConfig(
        name="w",
        rows=rows,
        dim=DIM,
        optimizer=OptimizerConfig(kind=opt, learning_rate=0.1),
    )
    return KVServer(Postoffice(node, van), {"w": cfg}, 0, 1, apply=apply)


def _push(ids, vals):
    return Message(
        task=Task(TaskKind.PUSH, "kv", payload={"table": "w"}),
        sender="W0",
        recver="S0",
        keys=np.asarray(ids, dtype=np.int32),
        values=[np.asarray(vals, dtype=np.float32).reshape(-1, DIM)],
    )


def _pull(ids):
    return Message(
        task=Task(TaskKind.PULL, "kv", payload={"table": "w"}),
        sender="W0",
        recver="S0",
        keys=np.asarray(ids, dtype=np.int32),
    )


def _rows(rng, n, lo=0, hi=ROWS):
    """n sorted unique row ids (the worker pre-combines within a push, so
    per-member ids are unique; duplicates live ACROSS members)."""
    return np.sort(rng.choice(np.arange(lo, hi), size=n, replace=False))


def _grads(rng, n):
    return rng.normal(size=(n, DIM)).astype(np.float32)


def _member_msgs(seed, k=4):
    """k push members with deliberately overlapping ids and mixed sizes
    (exercises cross-member duplicates AND device bucket padding)."""
    rng = np.random.default_rng(seed)
    sizes = [5, 3, 9, 1, 6, 2][:k]
    msgs = []
    for i, n in enumerate(sizes):
        # low id range forces heavy overlap between members
        ids = _rows(rng, n, 0, max(12, 2 * n))
        msgs.append(_push(ids, _grads(rng, n)))
    return msgs


def _table_bits(server):
    tbl = server.tables["w"]
    return np.asarray(tbl.value), {
        k: np.asarray(v) for k, v in sorted(tbl.state.items())
    }


def _assert_tables_equal(a, b):
    va, sa = a
    vb, sb = b
    np.testing.assert_array_equal(va, vb)  # bitwise, not allclose
    assert sorted(sa) == sorted(sb)
    for k in sa:
        np.testing.assert_array_equal(sa[k], sb[k])


def _no_errors(replies):
    for r in replies:
        assert r is not None
        assert "__error__" not in r.task.payload, r.task.payload


# ------------------------------------------------ batched vs sequential


@pytest.mark.parametrize("opt", ["sgd", "adagrad", "adam", "ftrl"])
def test_rounds_batched_is_bitwise_sequential(opt):
    """Default policy, overlapping member ids, EVERY optimizer: one
    batched apply == member-by-member applies, bit for bit (value AND
    optimizer state)."""
    van = LoopbackVan()
    try:
        seq = _server(van, opt=opt, node="Sseq")
        bat = _server(van, opt=opt, node="Sbat")
        for msg in _member_msgs(seed=1):
            seq.handle_request(msg)
        replies = bat.handle_request_batch(_member_msgs(seed=1))
        _no_errors(replies)
        _assert_tables_equal(_table_bits(seq), _table_bits(bat))
        assert bat.pushes == seq.pushes  # bookkeeping ran per member
    finally:
        van.close()


def test_combine_matches_sequential_on_disjoint_rows():
    van = LoopbackVan()
    try:
        rng = np.random.default_rng(3)
        msgs = []
        for i in range(4):  # disjoint id ranges: combine == sequential
            ids = _rows(rng, 6, 16 * i, 16 * (i + 1))
            msgs.append(_push(ids, _grads(rng, 6)))
        seq = _server(van, node="Sseq")
        bat = _server(
            van, node="Sbat", apply=ApplyEngineConfig(dup_policy="combine")
        )
        for m in msgs:
            seq.handle_request(m)
        _no_errors(bat.handle_request_batch(msgs))
        _assert_tables_equal(_table_bits(seq), _table_bits(bat))
    finally:
        van.close()


def test_combine_sums_cross_member_duplicates():
    """Classic PS semantics: duplicate rows across members pre-sum into
    one gradient before the step — identical to ONE push of the summed
    grads, not to sequential replay."""
    van = LoopbackVan()
    try:
        ids = np.array([2, 5, 9], dtype=np.int64)
        g1 = _grads(np.random.default_rng(4), 3)
        g2 = _grads(np.random.default_rng(5), 3)
        ref = _server(van, node="Sref")
        ref.handle_request(_push(ids, g1 + g2))
        bat = _server(
            van, node="Sbat", apply=ApplyEngineConfig(dup_policy="combine")
        )
        _no_errors(bat.handle_request_batch([_push(ids, g1), _push(ids, g2)]))
        _assert_tables_equal(_table_bits(ref), _table_bits(bat))
    finally:
        van.close()


def test_pull_inside_bundle_observes_exactly_prior_members():
    """[push A, pull, push B] in one bundle: the pull flushes A's group
    and must NOT see B — same observable order as sequential handling."""
    van = LoopbackVan()
    try:
        rng = np.random.default_rng(6)
        ids = np.arange(8, dtype=np.int64)
        a, b = _grads(rng, 8), _grads(rng, 8)
        seq = _server(van, node="Sseq")
        seq.handle_request(_push(ids, a))
        want = seq.handle_request(_pull(ids)).values[0]
        bat = _server(van, node="Sbat")
        replies = bat.handle_request_batch(
            [_push(ids, a), _pull(ids), _push(ids, b)]
        )
        _no_errors(replies)
        np.testing.assert_array_equal(np.asarray(replies[1].values[0]), want)
        # ...and the trailing push still applied
        seq.handle_request(_push(ids, b))
        _assert_tables_equal(_table_bits(seq), _table_bits(bat))
    finally:
        van.close()


def test_batch_isolates_member_failures():
    """A failing member answers __error__; the rest of the bundle lands."""
    van = LoopbackVan()
    try:
        rng = np.random.default_rng(7)
        ids = np.arange(4, dtype=np.int64)
        g = _grads(rng, 4)
        bad = _push(ids, g)
        bad.task = Task(TaskKind.PUSH, "kv", payload={"table": "nope"})
        srv = _server(van)
        replies = srv.handle_request_batch([_push(ids, g), bad])
        assert "__error__" not in replies[0].task.payload
        assert "__error__" in replies[1].task.payload
        assert srv.pushes == 1
    finally:
        van.close()


def test_dup_policy_is_validated():
    van = LoopbackVan()
    try:
        with pytest.raises(ValueError, match="dup_policy"):
            _server(van, apply=ApplyEngineConfig(dup_policy="merge"))
    finally:
        van.close()


# ------------------------------------------------------- sync-free acks


def _entangle_fn():
    """Jitted identity whose output depends on ~300 ms of device work the
    compiler cannot elide (0.0 * finite is exact-zero but data-dependent),
    making 'did the ack wait for the device?' directly observable."""

    @jax.jit
    def entangle(v):
        z = jnp.full((1300, 1300), jnp.float32(1e-3)) + v[0, 0]
        for _ in range(6):
            z = jnp.tanh(z @ z)
        return v + 0.0 * z[: v.shape[0], : v.shape[1]]

    return entangle


@pytest.mark.parametrize("batched", [False, True], ids=["single", "bundle"])
def test_push_ack_does_not_wait_for_device_apply(batched):
    """Behavioral twin of the check_wrappers AST ban: with the device
    apply artificially entangled into ~300 ms of compute, the ack still
    returns while the table value is NOT ready — the reply path performed
    no sync."""
    van = LoopbackVan()
    try:
        srv = _server(van)
        tbl = srv.tables["w"]
        entangle = _entangle_fn()
        orig_push, orig_batch = tbl.push, tbl.push_batch

        def slow_push(ids, vals):
            orig_push(ids, vals)
            tbl.value = entangle(tbl.value)

        def slow_push_batch(ids, positions, vals):
            orig_batch(ids, positions, vals)
            tbl.value = entangle(tbl.value)

        tbl.push, tbl.push_batch = slow_push, slow_push_batch
        rng = np.random.default_rng(8)

        def fire(seed):
            rng2 = np.random.default_rng(seed)
            if batched:
                msgs = [
                    _push(_rows(rng2, 5), _grads(rng2, 5)),
                    _push(_rows(rng2, 7), _grads(rng2, 7)),
                ]
                return srv.handle_request_batch(msgs)
            return [srv.handle_request(_push(_rows(rng2, 5), _grads(rng2, 5)))]

        fire(0)  # warm-up: compile the apply + entangle steps
        jax.block_until_ready(tbl.value)
        t0 = time.perf_counter()
        replies = fire(1)
        ack_s = time.perf_counter() - t0
        _no_errors(replies)
        assert not tbl.value.is_ready(), (
            "push ack blocked until the device apply completed"
        )
        jax.block_until_ready(tbl.value)
        device_s = time.perf_counter() - t0
        assert ack_s < device_s, (ack_s, device_s)
    finally:
        van.close()


# ------------------------------------------------ compile-cache hygiene


def test_batched_apply_compile_cache_stays_bucketed():
    """Randomized member counts and sizes must compile at most one device
    step per (members, bucket...) signature — NEVER one per raw size (the
    wire produces arbitrary lengths; compile storms are the failure mode
    the bucketing exists to prevent)."""
    van = LoopbackVan()
    try:
        srv = _server(van, apply=ApplyEngineConfig(apply_batch=8))
        tbl = srv.tables["w"]
        rng = np.random.default_rng(9)
        raw_sizes = set()
        k_seen, bm_seen, bu_seen = set(), set(), set()
        pushes = 0
        for _ in range(25):
            k = int(rng.integers(2, 5))
            sizes = [int(rng.integers(1, 33)) for _ in range(k)]
            msgs = [
                _push(_rows(rng, n), _grads(rng, n)) for n in sizes
            ]
            _no_errors(srv.handle_request_batch(msgs))
            pushes += k
            raw_sizes.update(sizes)
            k_seen.add(k)
            bm_seen.add(_bucket(max(sizes)))
            bu_seen.update(_bucket(n) for n in range(1, max(sizes) + 1))
        # the workload really was shape-diverse: far more raw sizes than
        # bucket keys, so per-size compilation would blow the bound below
        assert len(raw_sizes) > len(bm_seen) * len(k_seen)
        bound = len(k_seen) * len(bm_seen) * len(bu_seen)
        assert pushes > bound
        assert tbl._push_batch_fn._cache_size() <= bound, (
            f"{tbl._push_batch_fn._cache_size()} compiled batch steps for "
            f"{pushes} pushes (bucket bound {bound})"
        )
    finally:
        van.close()


# ------------------------------------------------------- e2e chaos stack


def _e2e_cfgs():
    return {
        "w": TableConfig(
            name="w",
            rows=1 << 10,
            dim=DIM,
            optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.1),
        )
    }


def _e2e_run(van, num_servers=2, steps=3):
    """Deterministic push schedule: each step issues TWO pushes of the
    same table in ONE coalescing window with overlapping key sets, so the
    per-server bundles carry cross-member duplicate rows."""
    cfgs = _e2e_cfgs()
    for s in range(num_servers):
        KVServer(Postoffice(f"S{s}", van), cfgs, s, num_servers)
    worker = KVWorker(Postoffice("W0", van), cfgs, num_servers)
    rng = np.random.default_rng(11)
    for _ in range(steps):
        pool = rng.choice(1 << 10, size=96, replace=False).astype(np.uint32)
        k1 = np.sort(pool[:64])
        k2 = np.sort(pool[32:])  # 32 keys overlap k1
        g1 = rng.normal(size=(64, DIM)).astype(np.float32)
        g2 = rng.normal(size=(64, DIM)).astype(np.float32)
        with worker.coalesce_window():
            t1 = worker.push("w", k1, g1)
            t2 = worker.push("w", k2, g2)
        assert worker.wait(t1, timeout=60) and worker.wait(t2, timeout=60)
    probe = np.arange(1 << 10, dtype=np.uint32)
    return worker.pull_sync("w", probe, timeout=60)


def test_e2e_bundled_batched_pushes_bitwise_match_sequential_under_chaos():
    """The acceptance gate: the full production stack — coalesced bundles,
    batch delivery, grouped device applies, retransmission under seeded
    drop/duplication chaos — lands the SAME bits as clean per-request
    handling over a plain LoopbackVan, with cross-bundle duplicate ids in
    every window."""
    clean = LoopbackVan()
    try:
        want = _e2e_run(clean)
    finally:
        clean.close()

    chaos = ChaosVan(LoopbackVan(), seed=2, drop=0.05, duplicate=0.05)
    rel = ReliableVan(chaos, timeout=0.05, backoff=1.0, max_retries=60, seed=2)
    van = CoalescingVan(rel)
    try:
        got = _e2e_run(van)
        assert van.flush(30)
        assert rel.gave_up == 0
        assert chaos.injected_drops + chaos.injected_dups > 0
        assert van.counters()["coalesce_msgs"] > van.counters()["coalesce_frames"]
    finally:
        van.close()
    np.testing.assert_array_equal(got, want)  # bitwise, not allclose
