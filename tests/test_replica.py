"""Hot-replica failover: the loss trajectory continues EXACTLY (VERDICT r3 #6).

Contrast with ``learner/elastic.py``'s snapshot recovery, which rewinds to
the last checkpoint and loses every update since: here a primary dies
mid-run, its standby is promoted, and training continues as if nothing
happened — asserted against an uninterrupted reference run, update for
update.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from parameter_server_tpu.config import OptimizerConfig, TableConfig
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core.van import LoopbackVan
from parameter_server_tpu.data.synthetic import SyntheticCTR
from parameter_server_tpu.kv import replica as replica_lib
from parameter_server_tpu.kv.server import KVServer
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.models import linear

ROWS = 1 << 10
NUM_SERVERS = 2
STEPS = 12
KILL_AFTER = 6


def _table_cfgs():
    return {
        "w": TableConfig(
            name="w",
            rows=ROWS,
            dim=1,
            optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.1),
        )
    }


def _batches():
    data = SyntheticCTR(key_space=4 * ROWS, nnz=8, batch_size=128, seed=3)
    return [data.next_batch() for _ in range(STEPS)]


def _train(worker: KVWorker, batches, on_step=None) -> list:
    losses = []
    for i, (keys, labels) in enumerate(batches):
        w_pos = worker.pull_sync("w", keys, timeout=30)
        g, _gb, loss = linear.grad_rows(jnp.asarray(w_pos), jnp.asarray(labels))
        ts = worker.push("w", keys, np.asarray(g) / labels.shape[0])
        assert worker.wait(ts, timeout=30)
        losses.append(float(loss))
        if on_step is not None:
            on_step(i)
    return losses


def _reference_losses() -> list:
    van = LoopbackVan()
    try:
        for s in range(NUM_SERVERS):
            KVServer(Postoffice(f"S{s}", van), _table_cfgs(), s, NUM_SERVERS)
        worker = KVWorker(Postoffice("W0", van), _table_cfgs(), NUM_SERVERS)
        return _train(worker, _batches())
    finally:
        van.close()


@pytest.mark.parametrize("sync", [True, False])
def test_promoted_standby_continues_trajectory_exactly(sync):
    """Kill primary S0 mid-run, promote its standby, keep training: every
    loss matches the uninterrupted run — zero updates lost (sync chain), or
    zero after an explicit flush (async with bounded lag)."""
    reference = _reference_losses()

    van = LoopbackVan()
    try:
        primaries, standbys = replica_lib.make_replicated_servers(
            van, _table_cfgs(), NUM_SERVERS, sync=sync, max_lag=4
        )
        worker = KVWorker(Postoffice("W0", van), _table_cfgs(), NUM_SERVERS)

        def on_step(i):
            if i != KILL_AFTER - 1:
                return
            if not sync:
                # async chain: bounded lag means forwards may still be in
                # flight; a real primary death here would lose <= max_lag
                # pushes.  Drain them to model the lag window being clear
                # at the failure instant (the sync=True case needs nothing).
                primaries[0].flush_replica()
            van.unbind("S0")  # the primary process dies
            replica_lib.promote(van, standbys[0], "S0")

        losses = _train(worker, _batches(), on_step=on_step)
    finally:
        van.close()

    # exact continuation: the standby replayed the identical update stream
    # through the identical jit apply, from the identical init seed
    np.testing.assert_allclose(losses, reference, rtol=1e-7, atol=0)


def test_sync_chain_acks_after_replica_applied():
    """replica_sync=True: when the worker's push ack fires, the standby has
    already applied the update (pull the standby directly and compare)."""
    van = LoopbackVan()
    try:
        primaries, standbys = replica_lib.make_replicated_servers(
            van, _table_cfgs(), NUM_SERVERS, sync=True
        )
        worker = KVWorker(Postoffice("W0", van), _table_cfgs(), NUM_SERVERS)
        keys, labels = _batches()[0]
        w_pos = worker.pull_sync("w", keys, timeout=30)
        g, _gb, _loss = linear.grad_rows(
            jnp.asarray(w_pos), jnp.asarray(labels)
        )
        ts = worker.push("w", keys, np.asarray(g) / labels.shape[0])
        assert worker.wait(ts, timeout=30)
        # primary and standby tables are bitwise identical right now
        for s in range(NUM_SERVERS):
            np.testing.assert_array_equal(
                np.asarray(primaries[s].tables["w"].value),
                np.asarray(standbys[s].tables["w"].value),
            )
    finally:
        van.close()


def test_manager_heartbeat_death_triggers_promotion():
    """End-to-end failure loop: the scheduler's heartbeat sweep detects the
    dead primary and the ReplicaSet promotes its standby — workers keep
    pulling from S0 without ever learning anything happened."""
    import time

    from parameter_server_tpu.core.manager import launch_local_cluster

    van = LoopbackVan()
    try:
        sched, managers, posts = launch_local_cluster(
            van, num_workers=1, num_servers=NUM_SERVERS,
            heartbeat_timeout=0.6,
        )
        # KVServers/standbys bind their own endpoints next to the manager
        # nodes (manager ids are the cluster identities; table traffic uses
        # the kv customer on separate S*/R* postoffices in this in-process
        # sim, so reuse the manager's S* postoffices for the primaries)
        table_cfgs = _table_cfgs()
        standbys = [
            KVServer(
                Postoffice(replica_lib.replica_id(s), van),
                table_cfgs, s, NUM_SERVERS,
            )
            for s in range(NUM_SERVERS)
        ]
        primaries = [
            KVServer(
                posts[f"S{s}"], table_cfgs, s, NUM_SERVERS,
                replica=replica_lib.replica_id(s), replica_sync=True,
            )
            for s in range(NUM_SERVERS)
        ]
        assert primaries
        rset = replica_lib.ReplicaSet(van, standbys, manager=sched)
        # the cluster already owns the W0 endpoint; attach the kv customer
        worker = KVWorker(posts["W0"], table_cfgs, NUM_SERVERS)
        batches = _batches()
        losses_pre = _train(worker, batches[:4])
        assert np.all(np.isfinite(losses_pre))

        # keep every OTHER node's heartbeat fresh while S0 goes silent
        van.disconnect("S0")  # the primary process dies
        deadline = time.time() + 5.0
        while time.time() < deadline and 0 not in rset.promoted:
            for nid, mgr in managers.items():
                if nid not in ("H", "S0"):
                    mgr.send_heartbeat()
            sched.check_heartbeats()
            time.sleep(0.1)
        assert 0 in rset.promoted, "heartbeat sweep never promoted standby 0"
        assert not sched.is_alive("S0")
        # pulls/pushes to S0 now land on the promoted standby: training
        # continues with the full pre-death state (no checkpoint rewind)
        losses_post = _train(worker, batches[4:8])
        assert np.all(np.isfinite(losses_post))
    finally:
        van.close()


def test_promotion_preserves_optimizer_state():
    """AdaGrad accumulators ride the chain too: post-promotion updates use
    the primary's accumulated state, not a fresh one (the silent-corruption
    a values-only replica would cause)."""
    van = LoopbackVan()
    try:
        primaries, standbys = replica_lib.make_replicated_servers(
            van, _table_cfgs(), NUM_SERVERS, sync=True
        )
        worker = KVWorker(Postoffice("W0", van), _table_cfgs(), NUM_SERVERS)
        batches = _batches()
        _train(worker, batches[:4])
        for s in range(NUM_SERVERS):
            for k, st in primaries[s].tables["w"].state.items():
                np.testing.assert_array_equal(
                    np.asarray(st),
                    np.asarray(standbys[s].tables["w"].state[k]),
                )
    finally:
        van.close()


def test_replica_forwarding_rides_real_sockets():
    """The chain protocol is Van-agnostic: a primary on the native TcpVan
    forwards applied pushes to a standby over REAL sockets (the DCN shape —
    promotion there is a route-table update, see kv/replica.py docstring)."""
    from parameter_server_tpu import native

    if native.load("tcpvan") is None:  # pragma: no cover
        pytest.skip("no native toolchain for tcpvan")
    from parameter_server_tpu.core.tcp_van import TcpVan

    van_w, van_p, van_r = TcpVan(), TcpVan(), TcpVan()
    try:
        cfgs = _table_cfgs()
        standby = KVServer(Postoffice("R0", van_r), cfgs, 0, 1)
        primary = KVServer(
            Postoffice("S0", van_p), cfgs, 0, 1,
            replica="R0", replica_sync=True,
        )
        van_p.add_route("R0", van_r.address)
        van_w.add_route("S0", van_p.address)
        worker = KVWorker(Postoffice("W0", van_w), cfgs, 1)
        keys, labels = _batches()[0]
        w_pos = worker.pull_sync("w", keys, timeout=30)
        g, _gb, _loss = linear.grad_rows(
            jnp.asarray(w_pos), jnp.asarray(labels)
        )
        ts = worker.push("w", keys, np.asarray(g) / labels.shape[0])
        assert worker.wait(ts, timeout=30)
        np.testing.assert_array_equal(
            np.asarray(primary.tables["w"].value),
            np.asarray(standby.tables["w"].value),
        )
        assert van_p.bytes_sent() > 0  # the forward crossed a socket
    finally:
        van_w.close()
        van_p.close()
        van_r.close()
