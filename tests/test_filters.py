"""Wire filter chain: key caching, compression, int8 quantization."""

import numpy as np
import pytest

from parameter_server_tpu.core.filters import (
    CompressingFilter,
    FilterChain,
    FixingFloatFilter,
    KeyCachingFilter,
)
from parameter_server_tpu.core.messages import Message, Task, TaskKind
from parameter_server_tpu.ops.quantize import dequantize_int8, quantize_int8


def _msg(keys=None, values=()):
    return Message(
        task=Task(TaskKind.PUSH, "kv", payload={"table": "w"}),
        sender="W0",
        recver="S0",
        keys=keys,
        values=list(values),
    )


def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 16)).astype(np.float32)
    q, s = quantize_int8(x, per_row=True)
    err = np.abs(dequantize_int8(q, s) - x)
    # max error <= half a quant step per row
    step = np.max(np.abs(x), axis=1, keepdims=True) / 127.0
    assert np.all(err <= step * 0.5 + 1e-7)


def test_quantize_zero_array():
    q, s = quantize_int8(np.zeros((4, 4), np.float32))
    np.testing.assert_array_equal(dequantize_int8(q, s), 0.0)


def test_compressing_filter_roundtrip_and_savings():
    f = CompressingFilter()
    vals = [np.zeros((1000,), np.float32), np.arange(12, dtype=np.int32)]
    enc = f.encode(_msg(values=vals))
    dec = f.decode(enc)
    np.testing.assert_array_equal(dec.values[0], vals[0])
    np.testing.assert_array_equal(dec.values[1], vals[1])
    assert f.bytes_out < f.bytes_in / 10  # zeros compress hard


def test_fixing_float_filter_roundtrip():
    f = FixingFloatFilter()
    rng = np.random.default_rng(1)
    vals = [rng.normal(size=(32, 8)).astype(np.float32),
            np.arange(5, dtype=np.int32)]  # ints pass through untouched
    dec = f.decode(f.encode(_msg(values=vals)))
    np.testing.assert_allclose(dec.values[0], vals[0], atol=0.05)
    np.testing.assert_array_equal(dec.values[1], vals[1])
    assert dec.values[1].dtype == np.int32


def test_key_caching_filter():
    f = KeyCachingFilter()
    keys = np.array([3, 5, 9], dtype=np.int32)
    m1 = f.decode(f.encode(_msg(keys=keys)))
    np.testing.assert_array_equal(m1.keys, keys)
    assert f.hits == 0
    # same keys again: wire message drops them, decode restores
    enc2 = f.encode(_msg(keys=keys))
    assert enc2.keys is None and f.hits == 1
    m2 = f.decode(enc2)
    np.testing.assert_array_equal(m2.keys, keys)
    # different keys: cache refresh, no hit
    keys3 = np.array([1], dtype=np.int32)
    m3 = f.decode(f.encode(_msg(keys=keys3)))
    np.testing.assert_array_equal(m3.keys, keys3)
    assert f.hits == 1


def test_filter_chain_end_to_end_through_van():
    """Full chain riding the LoopbackVan under a real push/pull workload."""
    from parameter_server_tpu.config import OptimizerConfig, TableConfig
    from parameter_server_tpu.core.postoffice import Postoffice
    from parameter_server_tpu.core.van import LoopbackVan
    from parameter_server_tpu.kv.server import KVServer
    from parameter_server_tpu.kv.worker import KVWorker

    chain = FilterChain(
        [KeyCachingFilter(), FixingFloatFilter(), CompressingFilter()]
    )
    van = LoopbackVan(filter_chain=chain)
    try:
        cfgs = {
            "w": TableConfig(
                name="w", rows=256, dim=4,
                optimizer=OptimizerConfig(kind="sgd", learning_rate=1.0),
            )
        }
        _server = KVServer(Postoffice("S0", van), cfgs, 0, 1)
        worker = KVWorker(Postoffice("W0", van), cfgs, 1, min_bucket=16)
        keys = np.array([7, 7, 21], dtype=np.uint64)
        ts = worker.push("w", keys, np.ones((3, 4), np.float32))
        worker.wait(ts, timeout=10)
        w = worker.pull_sync("w", keys, timeout=10)
        # lr=1 sgd: w = -combined_grad (quantization tolerance)
        np.testing.assert_allclose(w[0], -2.0, atol=0.1)
        np.testing.assert_allclose(w[2], -1.0, atol=0.1)
        # repeated same-key pull hits the key cache
        worker.pull_sync("w", keys, timeout=10)
        assert chain.filters[0].hits >= 1
    finally:
        van.close()


def test_add_noise_filter_perturbs_floats_only():
    """The debug add_noise codec (reference src/filter/add_noise.h analogue):
    float32 values get Gaussian noise at encode, ints pass untouched, decode
    is the identity (noise is injected, not round-tripped)."""
    from parameter_server_tpu.core.filters import AddNoiseFilter

    f = AddNoiseFilter(sigma=0.1, seed=3)
    vals = [np.zeros((256,), np.float32), np.arange(4, dtype=np.int64)]
    enc = f.encode(_msg(values=vals))
    assert not np.allclose(enc.values[0], 0.0)
    assert np.abs(enc.values[0]).mean() < 0.5  # sigma-scale, not garbage
    np.testing.assert_array_equal(enc.values[1], vals[1])
    dec = f.decode(enc)
    np.testing.assert_array_equal(dec.values[0], enc.values[0])


def test_make_chain_specs():
    from parameter_server_tpu.core.filters import (
        AddNoiseFilter,
        make_chain,
    )

    assert make_chain("none") is None
    full = make_chain("full")
    assert [type(f) for f in full.filters] == [
        KeyCachingFilter, FixingFloatFilter, CompressingFilter,
    ]
    # the launcher default: bit-exact on the wire, no int8 (ADVICE r4)
    lossless = make_chain("lossless")
    assert [type(f) for f in lossless.filters] == [
        KeyCachingFilter, CompressingFilter,
    ]
    custom = make_chain("noise+zlib")
    assert [type(f) for f in custom.filters] == [
        AddNoiseFilter, CompressingFilter,
    ]
    with pytest.raises(ValueError):
        make_chain("lz5")


def test_chain_records_codec_overhead():
    chain = FilterChain([FixingFloatFilter(), CompressingFilter()])
    vals = [np.ones((512,), np.float32)]
    for _ in range(3):
        chain.decode(chain.encode(_msg(values=vals)))
    oh = chain.overhead()
    assert oh["encode_calls"] == 3 and oh["decode_calls"] == 3
    assert oh["encode_us_per_msg"] > 0 and oh["decode_us_per_msg"] > 0


def test_compressing_counters_roll_back_on_send_failure():
    """bytes_in/bytes_out must not count frames that never hit the wire
    (ADVICE r3): a failed send un-commits exactly the failed message's
    contribution."""
    f = CompressingFilter()
    chain = FilterChain([f])
    keys = np.arange(64, dtype=np.int64)
    vals = [np.zeros((1024,), np.float32)]
    ok = chain.encode(_msg(keys=keys, values=vals))
    bi_ok, bo_ok = f.bytes_in, f.bytes_out
    assert bi_ok > 0 and bo_ok > 0
    failed = chain.encode(_msg(keys=keys, values=vals))
    assert f.bytes_in == 2 * bi_ok
    chain.on_send_failed(_msg(keys=keys, values=vals), failed)
    assert (f.bytes_in, f.bytes_out) == (bi_ok, bo_ok)


def test_key_cache_rolls_back_on_send_failure():
    """A failed wire write must invalidate the link's send cache: otherwise
    the next send hash-hits, ships keys=None, and the receiver (which never
    saw the keys) raises a cache miss — poisoning the link until the key
    set changes."""
    import numpy as np

    from parameter_server_tpu.core.filters import FilterChain, KeyCachingFilter
    from parameter_server_tpu.core.messages import Message, Task, TaskKind

    chain = FilterChain([KeyCachingFilter()])
    keys = np.arange(8, dtype=np.int32)

    def msg():
        return Message(
            task=Task(TaskKind.PULL, "kv", payload={}),
            sender="W0", recver="S0", keys=keys,
        )

    assert chain.encode(msg()).keys is not None  # first send ships keys
    chain.on_send_failed(msg())  # ...but the socket write failed
    again = chain.encode(msg())
    assert again.keys is not None  # MUST re-ship, not hash-hit
    # receiver sees it, so a later send may legitimately hit
    chain.decode(again)
    assert chain.encode(msg()).keys is None
