"""SP x TP x FSDP-state composition (parallel/sp_fsdp.py, VERDICT r4 #5).

The composed long-context trainer must compute the SAME function as the
dense single-device trainer while actually sharding: sequence over ``sp``
(ring attention via partial shard_map), weights over ``model`` (TP rules),
moments over ``sp`` (FSDP-state).
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from parameter_server_tpu.learner.lm import SpmdLMTrainer
from parameter_server_tpu.models import transformer as tfm
from parameter_server_tpu.parallel import mesh as mesh_lib
from parameter_server_tpu.parallel.sp_fsdp import SpTpLMTrainer


def _mesh(sp=4, tp=2):
    return Mesh(np.asarray(jax.devices()).reshape(sp, tp), ("sp", "model"))


def _cfg(**kw):
    defaults = dict(
        causal=True, tie_embeddings=False, n_heads=4, n_kv_heads=2,
        max_seq=256,
    )
    defaults.update(kw)
    return tfm.tiny_config(**defaults)


def test_sptp_matches_dense_trainer_trajectory():
    """Same seed, same stream: the (sp=4, model=2) composed trajectory
    equals the dense single-device trainer's — ring + TP + moments-FSDP +
    chunked loss change the distribution, not the math."""
    cfg = _cfg()
    tr = SpTpLMTrainer(cfg, _mesh(), fsdp="state", loss_chunk=16, seed=0)
    ref = SpmdLMTrainer(
        cfg, mesh_lib.make_mesh((1, 1), devices=jax.devices()[:1]), seed=0
    )
    rng = np.random.default_rng(0)
    toks = [
        rng.integers(0, cfg.vocab_size, size=(2, 64)).astype(np.int32)
        for _ in range(4)
    ]
    l_sp = [tr.step(t) for t in toks]
    l_ref = [ref.step_causal(t) for t in toks]
    np.testing.assert_allclose(l_sp, l_ref, rtol=2e-4, atol=2e-5)


def test_sptp_composes_with_scan_remat():
    """scan_blocks + remat + the composed shardings in one step."""
    cfg = _cfg(scan_blocks=True, remat=True)
    tr = SpTpLMTrainer(cfg, _mesh(), fsdp="state", loss_chunk=16, seed=1)
    rng = np.random.default_rng(1)
    losses = [
        tr.step(
            rng.integers(0, cfg.vocab_size, size=(2, 64)).astype(np.int32)
        )
        for _ in range(3)
    ]
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0] + 0.5  # trains, not diverges


def test_sptp_shardings_are_real():
    """Weights carry the model axis, moments additionally carry sp, and
    no param is fully replicated when the TP rule shards it."""
    cfg = _cfg()
    mesh = _mesh()
    tr = SpTpLMTrainer(cfg, mesh, fsdp="state", loss_chunk=16)

    def spec_names(arr):
        out = set()
        for axes in arr.sharding.spec:
            if axes is None:
                continue
            for a in (axes if isinstance(axes, tuple) else (axes,)):
                out.add(a)
        return out

    # attention q kernel: TP over heads
    q_kernel = tr.params["layer_0"]["attn"]["q"]["kernel"]
    assert "model" in spec_names(q_kernel)
    # its adamw moment: TP AND sp (FSDP-state)
    import optax

    mu = None
    for leaf_state in tr.opt_state:
        if isinstance(leaf_state, optax.ScaleByAdamState):
            mu = leaf_state.mu["layer_0"]["attn"]["q"]["kernel"]
            break
    assert mu is not None
    assert {"model", "sp"} <= spec_names(mu)


def test_sptp_rejects_bad_configs():
    with pytest.raises(ValueError, match="sp"):
        SpTpLMTrainer(_cfg(), mesh_lib.make_mesh((4, 2)))  # data/model mesh
    with pytest.raises(ValueError, match="causal"):
        SpTpLMTrainer(
            tfm.tiny_config(causal=False, tie_embeddings=False), _mesh()
        )
    tr = SpTpLMTrainer(_cfg(), _mesh())
    with pytest.raises(ValueError, match="sp shards"):
        tr.step(np.zeros((2, 30), np.int32))  # 30 % 4 != 0
