"""Same-id crash-restart recovery (incarnation fencing, ISSUE 5 tentpole).

The scenario promotion (``tests/test_chaos.py``) dodges: a server process
dies and comes back UNDER ITS OWN node id.  The transport half is the
incarnation fence in ``core/resender.py`` (zombie frames dropped, seq space
reset); the state half is ``kv/replica.restart_same_id`` (shard restored
from the live standby — zero loss — or the latest checkpoint — bounded
rewind); the membership half is the scheduler bumping the incarnation on
re-registration (``core/manager.py``).

Acceptance (ISSUE 5): kill and restart the SAME server node id twice
mid-run under seeded 5% drop; training completes with the exact fault-free
trajectory (replica path), push-apply count equal to the clean run's (zero
duplicate-apply), and zero stale-incarnation frames delivered.
"""

import time

import numpy as np
import pytest

import jax.numpy as jnp

from parameter_server_tpu.config import OptimizerConfig, TableConfig
from parameter_server_tpu.core.chaos import ChaosVan
from parameter_server_tpu.core.messages import (
    INCARNATION_KEY,
    Message,
    Task,
    TaskKind,
)
from parameter_server_tpu.core.postoffice import Customer, Postoffice
from parameter_server_tpu.core.resender import (
    CRC_KEY,
    SEQ_KEY,
    ReliableVan,
    payload_crc32,
)
from parameter_server_tpu.core.van import LoopbackVan
from parameter_server_tpu.data.synthetic import SyntheticCTR
from parameter_server_tpu.kv import replica as replica_lib
from parameter_server_tpu.kv.server import KVServer
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.models import linear

pytestmark = pytest.mark.chaos

ROWS = 1 << 10
NUM_SERVERS = 2
STEPS = 12


def _table_cfgs():
    return {
        "w": TableConfig(
            name="w", rows=ROWS, dim=1,
            optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.1),
        )
    }


def _batches():
    data = SyntheticCTR(key_space=4 * ROWS, nnz=8, batch_size=128, seed=3)
    return [data.next_batch() for _ in range(STEPS)]


def _train(worker, batches, on_step=None):
    losses = []
    for i, (keys, labels) in enumerate(batches):
        w_pos = worker.pull_sync("w", keys, timeout=60)
        g, _gb, loss = linear.grad_rows(jnp.asarray(w_pos), jnp.asarray(labels))
        worker.push_sync("w", keys, np.asarray(g) / labels.shape[0], timeout=60)
        losses.append(float(loss))
        if on_step is not None:
            on_step(i)
    return losses


def _clean_reference():
    van = LoopbackVan()
    try:
        servers = [
            KVServer(Postoffice(f"S{s}", van), _table_cfgs(), s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        ]
        worker = KVWorker(Postoffice("W0", van), _table_cfgs(), NUM_SERVERS)
        losses = _train(worker, _batches())
        return losses, sum(s.pushes for s in servers)
    finally:
        van.close()


def _reliable_stack(*, seed=0, timeout=0.05, max_retries=60, **chaos_kw):
    chaos = ChaosVan(LoopbackVan(), seed=seed, **chaos_kw)
    van = ReliableVan(
        chaos, timeout=timeout, backoff=1.0, max_retries=max_retries,
        seed=seed,
    )
    return van, chaos


def _settle(predicate, deadline_s=5.0):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


# ------------------------------------------------------- acceptance e2e


@pytest.mark.parametrize("seed", [0, 1])
def test_same_id_double_restart_under_drop_matches_clean_run(seed):
    """ISSUE 5 acceptance: S0 is killed and restarted IN PLACE twice
    mid-run under seeded 5% drop.  The shard restores from the sync
    replica chain, so the trajectory is exactly the fault-free run's, the
    total applied-push count equals the clean run's (exactly-once held
    across both restarts), and no stale-incarnation frame was delivered
    (fenced frames are counted, never handled)."""
    ref_losses, ref_applied = _clean_reference()

    van, chaos = _reliable_stack(seed=seed, timeout=0.1, drop=0.05)
    try:
        primaries, standbys = replica_lib.make_replicated_servers(
            van, _table_cfgs(), NUM_SERVERS, sync=True
        )
        worker = KVWorker(Postoffice("W0", van), _table_cfgs(), NUM_SERVERS)
        s0_instances = [primaries[0]]

        def restart():
            # crash: both of the process's endpoints vanish (server identity
            # + its replica-forwarding client endpoint)
            van.unbind("S0")
            van.unbind("S0.fw")
            # local incarnation authority (no Manager in this test): the
            # restarted process gets a fresh epoch before it goes live
            van.restart_node("S0")
            new_s0, source = replica_lib.restart_same_id(
                van, _table_cfgs(), 0, NUM_SERVERS, standby=standbys[0]
            )
            assert source == "replica"
            s0_instances.append(new_s0)

        def on_step(i):
            if i in (STEPS // 3, 2 * STEPS // 3):
                restart()

        losses = _train(worker, _batches(), on_step=on_step)
        assert len(s0_instances) == 3  # original + two restarts
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-7, atol=0)
        applied = sum(s.pushes for s in s0_instances) + primaries[1].pushes
        assert applied == ref_applied  # zero duplicate-apply, zero loss
        assert van.incarnations.get("S0") == 2
        assert van.flush(10)
        assert van.gave_up == 0
        assert chaos.injected_drops > 0  # the run was actually lossy
    finally:
        van.close()


def test_same_id_restart_checkpoint_fallback_bounded_rewind(tmp_path):
    """No standby: the restarted shard rewinds to the latest COMMITTED
    checkpoint — and no further (restored rows equal the snapshot taken at
    save time bit-for-bit).  Training still completes end to end, and the
    dedup windows into the node were dropped (pre-crash frames may
    re-apply inside the accepted rewind, so exact parity is NOT asserted
    — boundedness and completion are)."""
    root = str(tmp_path / "ckpt")
    van, _chaos = _reliable_stack(seed=3, timeout=0.1, drop=0.02)
    try:
        servers = [
            KVServer(Postoffice(f"S{s}", van), _table_cfgs(), s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        ]
        worker = KVWorker(Postoffice("W0", van), _table_cfgs(), NUM_SERVERS)
        batches = _batches()
        at_save = {}
        restarted = {}

        def on_step(i):
            if i == 3:
                worker.save_model(root, step=i, timeout=60)
                at_save["shard"] = servers[0].export_shard()
            if i == 7:
                van.unbind("S0")
                van.restart_node("S0")
                new_s0, source = replica_lib.restart_same_id(
                    van, _table_cfgs(), 0, NUM_SERVERS, ckpt_root=root
                )
                assert source == "checkpoint"
                restarted["server"] = new_s0
                # bounded rewind: the restored rows are EXACTLY the step-3
                # snapshot — nothing newer survived, nothing older leaked in
                got = new_s0.export_shard()
                np.testing.assert_array_equal(
                    got["w"]["value"], at_save["shard"]["w"]["value"]
                )
                for k, v in at_save["shard"]["w"]["state"].items():
                    np.testing.assert_array_equal(got["w"]["state"][k], v)

        losses = _train(worker, batches, on_step=on_step)
        assert len(losses) == STEPS  # the run completed through the rewind
        assert "server" in restarted
        assert van.flush(10)
    finally:
        van.close()


def test_restore_selection_replica_then_checkpoint_then_cold(tmp_path):
    """restart_same_id restore preference: live standby > latest committed
    checkpoint > cold deterministic re-init."""
    root = str(tmp_path / "ckpt")
    van, _chaos = _reliable_stack(seed=0, timeout=0.1)
    try:
        cfgs = _table_cfgs()
        server = KVServer(Postoffice("S0", van), cfgs, 0, 1)
        standby = KVServer(Postoffice("R0", van), cfgs, 0, 1)
        cold_state = server.export_shard()["w"]["value"].copy()

        # give server, standby, and checkpoint three DISTINCT states
        worker = KVWorker(Postoffice("W0", van), cfgs, 1)
        keys = np.arange(16, dtype=np.int64)
        worker.push_sync("w", keys, np.ones(16, np.float32), timeout=60)
        server.save_checkpoint(root, step=1)
        from parameter_server_tpu import checkpoint

        checkpoint.finalize(root, 1, 1, {"w": cfgs["w"].rows})
        ckpt_state = server.export_shard()["w"]["value"].copy()
        worker.push_sync("w", keys, np.ones(16, np.float32), timeout=60)
        standby.import_shard(server.export_shard())
        replica_state = standby.export_shard()["w"]["value"].copy()
        assert not np.array_equal(ckpt_state, replica_state)

        van.unbind("S0")
        s, source = replica_lib.restart_same_id(
            van, cfgs, 0, 1, standby=standby, ckpt_root=root
        )
        assert source == "replica"
        np.testing.assert_array_equal(
            s.export_shard()["w"]["value"], replica_state
        )

        van.unbind("S0")
        s, source = replica_lib.restart_same_id(van, cfgs, 0, 1, ckpt_root=root)
        assert source == "checkpoint"
        np.testing.assert_array_equal(
            s.export_shard()["w"]["value"], ckpt_state
        )

        van.unbind("S0")
        s, source = replica_lib.restart_same_id(van, cfgs, 0, 1)
        assert source == "cold"
        np.testing.assert_array_equal(
            s.export_shard()["w"]["value"], cold_state  # deterministic seed
        )
    finally:
        van.close()


# ------------------------------------------------ incarnation fence units


def test_zombie_stale_incarnation_frames_are_fenced():
    """A frame stamped with a superseded incarnation is dropped without an
    ACK or delivery: the zombie's resender would exhaust its budget into
    the void, and the successor's state is never touched."""
    van = ReliableVan(LoopbackVan(), timeout=30.0)
    try:
        seen = []

        class Recorder(Customer):
            def handle_request(self, msg):
                seen.append(float(msg.values[0][0]))
                return msg.reply()

        Recorder("rec", Postoffice("S0", van))
        client = Customer("rec", Postoffice("W0", van))
        ts = client.submit(
            [Message(task=Task(TaskKind.PUSH, "rec"), recver="S0",
                     values=[np.array([1.0])])]
        )
        assert client.wait(ts, timeout=10)
        assert seen == [1.0]

        assert van.restart_node("W0") == 1  # W0's process was replaced

        # hand-craft what the dead pre-restart process would emit: a frame
        # carrying the OLD incarnation (0 == omitted) and a fresh seq, with
        # a VALID CRC — only the incarnation fence can reject it
        zombie = Message(
            task=Task(TaskKind.PUSH, "rec"), sender="W0", recver="S0",
            values=[np.array([666.0])],
        )
        zombie.task.payload = {
            SEQ_KEY: 99, CRC_KEY: payload_crc32(zombie),
        }
        acks_before = van.acks_sent
        van.inner.send(zombie)  # inject below the resender's stamping
        assert _settle(lambda: van.rejected_stale == 1)
        time.sleep(0.05)  # grace: the frame must not trickle through late
        assert seen == [1.0]  # never delivered
        assert van.acks_sent == acks_before  # and never acked

        # the successor (new incarnation) still works, from seq 0
        ts = client.submit(
            [Message(task=Task(TaskKind.PUSH, "rec"), recver="S0",
                     values=[np.array([2.0])])]
        )
        assert client.wait(ts, timeout=10)
        assert seen == [1.0, 2.0]
    finally:
        van.close()


def test_incarnation_advance_resets_windows_and_seq():
    """After restart_node the node's links restart at seq 0 under the new
    incarnation and receivers accept them — without the reset, the fresh
    seq 0 would read as a duplicate of pre-restart traffic and be eaten."""
    van = ReliableVan(LoopbackVan(), timeout=30.0)
    try:
        seen = []

        class Recorder(Customer):
            def handle_request(self, msg):
                seen.append(msg.task.payload.get("n"))
                return msg.reply()

        Recorder("rec", Postoffice("S0", van))
        client = Customer("rec", Postoffice("W0", van))
        for n in range(3):  # burn seqs 0..2 (plus ack/reply traffic)
            ts = client.submit(
                [Message(task=Task(TaskKind.PUSH, "rec", payload={"n": n}),
                         recver="S0")]
            )
            assert client.wait(ts, timeout=10)
        assert seen == [0, 1, 2]
        assert van.dup_suppressed == 0

        van.restart_node("W0")
        for n in range(3, 6):  # new process: seqs 0..2 AGAIN, new inc
            ts = client.submit(
                [Message(task=Task(TaskKind.PUSH, "rec", payload={"n": n}),
                         recver="S0")]
            )
            assert client.wait(ts, timeout=10)
        assert seen == [0, 1, 2, 3, 4, 5]  # nothing eaten as a duplicate
        assert van.dup_suppressed == 0
        assert van.rejected_stale == 0
    finally:
        van.close()


def test_manager_reregistration_bumps_incarnation_and_broadcasts():
    """The scheduler is the incarnation authority: a REGISTER for an id it
    already knows bumps the row's incarnation, re-broadcasts the binding,
    and every endpoint's transport learns the new epoch."""
    from parameter_server_tpu.core.manager import Manager, launch_local_cluster

    van, _chaos = _reliable_stack(seed=0, timeout=0.1)
    try:
        sched, managers, _posts = launch_local_cluster(
            van, num_workers=1, num_servers=1, heartbeat_timeout=30
        )
        row = [n for n in sched.nodes() if n.node_id == "S0"][0]
        assert row.incarnation == 0

        # the S0 process dies and a replacement re-registers under the id
        van.unbind("S0")
        new_mgr = Manager(
            Postoffice("S0", van), num_workers=1, num_servers=1
        )
        assert new_mgr.register_with_scheduler(timeout=10)

        row = [n for n in sched.nodes() if n.node_id == "S0"][0]
        assert row.incarnation == 1
        assert row.alive
        # range assignment survived the restart
        assert (row.range_begin, row.range_end) == sched.server_range("S0")
        # the broadcast reached the transport fence on every endpoint
        # (shared van in-process): frames from S0 now stamp incarnation 1
        assert _settle(lambda: van.incarnations.get("S0") == 1)
        # and the restarted node learned the full table back
        assert _settle(
            lambda: len(new_mgr.nodes()) == len(sched.nodes())
        )
        # peers saw the rejoin row too
        w_mgr = managers["W0"]
        assert _settle(
            lambda: any(
                n.node_id == "S0" and n.incarnation == 1
                for n in w_mgr.nodes()
            )
        )
    finally:
        van.close()


def test_full_restart_lifecycle_with_scheduler(tmp_path):
    """learner.elastic.restart_server: crash S0, restore from the standby,
    re-register — the scheduler bumps the incarnation and the worker keeps
    training against the same identity with zero loss."""
    from parameter_server_tpu.core.manager import launch_local_cluster
    from parameter_server_tpu.learner.elastic import restart_server

    ref_losses, _ = _clean_reference()

    van, _chaos = _reliable_stack(seed=4, timeout=0.1, drop=0.02)
    try:
        sched, _managers, posts = launch_local_cluster(
            van, num_workers=1, num_servers=NUM_SERVERS, heartbeat_timeout=30
        )
        cfgs = _table_cfgs()
        # each node id has ONE Postoffice (the cluster's); KVServer and the
        # Manager are sibling customers on it — same layout as production
        standbys = [
            KVServer(Postoffice(f"R{s}", van), cfgs, s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        ]
        for s in range(NUM_SERVERS):
            KVServer(
                posts[f"S{s}"], cfgs, s, NUM_SERVERS,
                replica=f"R{s}", replica_sync=True,
            )
        worker = KVWorker(posts["W0"], cfgs, NUM_SERVERS)
        restarted = {}

        def on_step(i):
            if i != STEPS // 2:
                return
            van.unbind("S0")
            van.unbind("S0.fw")
            server, source, mgr = restart_server(
                van, cfgs, 0, NUM_SERVERS,
                num_workers=1, standby=standbys[0], heartbeat_timeout=30,
            )
            assert source == "replica"
            assert mgr is not None
            restarted["server"] = server

        losses = _train(worker, _batches(), on_step=on_step)
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-7, atol=0)
        assert "server" in restarted
        row = [n for n in sched.nodes() if n.node_id == "S0"][0]
        assert row.incarnation == 1
        assert van.incarnations.get("S0") == 1
        assert van.flush(10)
    finally:
        van.close()


# --------------------------------------------------------- remote cancel


def test_remote_cancel_drops_queued_work_at_receiver():
    """Customer.cancel(remote=True): the CANCEL control frame fences a
    delayed request at the receiving Postoffice — the dead request is
    dropped instead of executed (the reference ran abandoned work to
    completion)."""
    chaos = ChaosVan(LoopbackVan(), seed=0)
    try:
        ran = []

        class Recorder(Customer):
            def handle_request(self, msg):
                ran.append(self.post.node_id)
                return msg.reply()

        from parameter_server_tpu.core.chaos import ChaosConfig

        s0_post = Postoffice("S0", chaos)
        s1_post = Postoffice("S1", chaos)
        Recorder("rec", s0_post)
        Recorder("rec", s1_post)
        client = Customer("rec", Postoffice("W0", chaos))

        # S1's request leg is slow; S0 answers immediately
        chaos.set_link("W0", "S1", ChaosConfig(delay=0.4))
        ts = client.submit(
            [
                Message(task=Task(TaskKind.PUSH, "rec"), recver="S0"),
                Message(task=Task(TaskKind.PUSH, "rec"), recver="S1"),
            ]
        )
        assert _settle(lambda: ran == ["S0"])  # S0 executed
        # cancel overtakes the delayed leg (its frame rides the link with
        # the heal-time config — delivered synchronously)
        chaos.set_link("W0", "S1", ChaosConfig())
        assert client.cancel(ts, "test deadline", remote=True)
        assert _settle(lambda: s1_post.cancelled_drops == 1, 3.0)
        time.sleep(0.2)  # grace past the delayed delivery
        assert ran == ["S0"]  # S1 never executed the dead request
        assert s0_post.cancelled_drops == 0  # answered legs aren't fenced

        # the fence was consumed; fresh requests to S1 execute normally
        ts = client.submit(
            [Message(task=Task(TaskKind.PUSH, "rec"), recver="S1")]
        )
        assert client.wait(ts, timeout=10)
        assert ran == ["S0", "S1"]
    finally:
        chaos.close()
