"""tools/check_wrappers.py wired as a tier-1 test (ISSUE 6 satellite).

The Van wrapper flush/close-delegation and counters-no-recursion contracts
were convention until PR 6; this keeps them enforced on every run.
"""

from __future__ import annotations

import pathlib
import sys
import textwrap

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import check_wrappers  # noqa: E402


def test_repo_wrappers_clean():
    problems = []
    for f in sorted((REPO / "parameter_server_tpu").rglob("*.py")):
        if "VanWrapper" in f.read_text():
            problems.extend(check_wrappers.check_file(f))
    assert problems == [], "\n".join(problems)


def test_catches_non_delegating_flush(tmp_path):
    bad = tmp_path / "bad_van.py"
    bad.write_text(
        textwrap.dedent(
            """
            class SwallowingVan(VanWrapper):
                def flush(self, timeout=5.0):
                    return True  # drains nothing below

                def close(self):
                    self.inner.close()
            """
        )
    )
    problems = check_wrappers.check_file(bad)
    assert len(problems) == 1
    assert "SwallowingVan.flush" in problems[0]


def test_catches_counters_recursion(tmp_path):
    bad = tmp_path / "bad_counters.py"
    bad.write_text(
        textwrap.dedent(
            """
            class DoubleCountVan(VanWrapper):
                def counters(self):
                    return {**self.inner.counters(), "mine": 1}
            """
        )
    )
    problems = check_wrappers.check_file(bad)
    assert len(problems) == 1
    assert "DoubleCountVan.counters" in problems[0]


def test_frame_hot_path_is_pickle_free():
    """The flat-frame hot path (codec, transport, resender, coalescer) must
    never re-import pickle — the serialize tax ISSUE 7 removed."""
    problems = []
    for rel in check_wrappers.NO_PICKLE_MODULES:
        path = REPO / "parameter_server_tpu" / rel
        assert path.is_file(), f"hot-path module moved: {rel}"
        problems.extend(check_wrappers.check_no_pickle(path))
    assert problems == [], "\n".join(problems)


def test_catches_pickle_import_on_hot_path(tmp_path):
    bad = tmp_path / "bad_codec.py"
    bad.write_text(
        textwrap.dedent(
            """
            import pickle
            from pickle import dumps

            def encode(msg):
                return pickle.dumps(msg)
            """
        )
    )
    problems = check_wrappers.check_no_pickle(bad)
    assert len(problems) == 2
    assert "pickle" in problems[0]


def test_no_pickle_allows_clean_module(tmp_path):
    ok = tmp_path / "ok_codec.py"
    ok.write_text("import struct\nimport zlib\n")
    assert check_wrappers.check_no_pickle(ok) == []


def test_main_fails_loudly_if_hot_path_module_missing(tmp_path, monkeypatch):
    """NO_PICKLE_MODULES entries must exist when scanning the real package;
    a rename must fail the check, not silently skip the ban."""
    monkeypatch.setattr(
        check_wrappers, "NO_PICKLE_MODULES",
        check_wrappers.NO_PICKLE_MODULES + ("core/renamed_codec.py",),
    )
    assert check_wrappers.main(["check_wrappers"]) == 1


def test_event_registry_loads_and_repo_record_sites_clean():
    """Every flightrec.record / rec(...) call site in the package uses a
    literal kind from the EVENTS registry (ISSUE 8 satellite)."""
    events = check_wrappers.load_event_registry(
        REPO / "parameter_server_tpu" / check_wrappers.FLIGHTREC_MODULE
    )
    assert "frame.send" in events and "slo.breach" in events
    problems = []
    for f in sorted((REPO / "parameter_server_tpu").rglob("*.py")):
        problems.extend(check_wrappers.check_flightrec_calls(f, events))
    assert problems == [], "\n".join(problems)


def test_catches_unregistered_kind(tmp_path):
    bad = tmp_path / "bad_kind.py"
    bad.write_text(
        textwrap.dedent(
            """
            from parameter_server_tpu.core import flightrec

            def fence(node):
                flightrec.record("fence.incarnaton", node=node)  # typo
            """
        )
    )
    events = frozenset({"fence.incarnation"})
    problems = check_wrappers.check_flightrec_calls(bad, events)
    assert len(problems) == 1
    assert "fence.incarnaton" in problems[0]


def test_catches_unregistered_kind_via_alias_and_method(tmp_path):
    bad = tmp_path / "bad_alias.py"
    bad.write_text(
        textwrap.dedent(
            """
            def sweep(recorder, rec):
                rec("slo.braech", node="W0")              # aliased callable
                recorder.record("frame.rejct", node="S0")  # method form
            """
        )
    )
    events = frozenset({"slo.breach", "frame.reject"})
    problems = check_wrappers.check_flightrec_calls(bad, events)
    assert len(problems) == 2
    assert "slo.braech" in problems[0]
    assert "frame.rejct" in problems[1]


def test_catches_non_literal_kind_on_canonical_form(tmp_path):
    bad = tmp_path / "bad_dynamic.py"
    bad.write_text(
        textwrap.dedent(
            """
            from parameter_server_tpu.core import flightrec

            def log(kind):
                flightrec.record(kind, node="S0")  # dynamic — unverifiable
            """
        )
    )
    problems = check_wrappers.check_flightrec_calls(bad, frozenset({"x.y"}))
    assert len(problems) == 1
    assert "non-literal" in problems[0]


def test_record_shaped_non_recorder_calls_not_flagged(tmp_path):
    ok = tmp_path / "ok_hist.py"
    ok.write_text(
        textwrap.dedent(
            """
            def measure(hist, lat):
                hist.record(lat)           # histogram sample, not an event
                hist.record(0.003)
                db.record("row")           # undotted string: unrelated API
            """
        )
    )
    assert check_wrappers.check_flightrec_calls(ok, frozenset({"x.y"})) == []


def test_registry_load_fails_loudly(tmp_path):
    """A moved or computed EVENTS literal must raise, never yield an empty
    registry that passes every call site vacuously."""
    import pytest

    missing = tmp_path / "no_registry.py"
    missing.write_text("OTHER = frozenset({'a.b'})\n")
    with pytest.raises(ValueError, match="EVENTS"):
        check_wrappers.load_event_registry(missing)

    computed = tmp_path / "computed.py"
    computed.write_text("EVENTS = frozenset(sorted({'a.b'}))\n")
    with pytest.raises(ValueError, match="literal"):
        check_wrappers.load_event_registry(computed)

    empty = tmp_path / "empty.py"
    empty.write_text("EVENTS = frozenset(set())\n")
    with pytest.raises(ValueError):
        check_wrappers.load_event_registry(empty)


def test_verb_registry_loads_and_repo_cmd_sites_clean():
    """Every ``{"cmd": ...}`` payload literal in the package names a verb
    from the CONTROL_VERBS registry (ISSUE 10 satellite), and the new
    telemetry event kinds are registered."""
    verbs, names = check_wrappers.load_verb_registry(
        REPO / "parameter_server_tpu" / check_wrappers.MANAGER_MODULE
    )
    assert "telemetry" in verbs and "heartbeat" in verbs
    assert names.get("TELEMETRY") == "telemetry"
    events = check_wrappers.load_event_registry(
        REPO / "parameter_server_tpu" / check_wrappers.FLIGHTREC_MODULE
    )
    assert "telemetry.publish" in events and "telemetry.drop" in events
    problems = []
    for f in sorted((REPO / "parameter_server_tpu").rglob("*.py")):
        problems.extend(check_wrappers.check_control_verbs(f, verbs, names))
    assert problems == [], "\n".join(problems)


def test_catches_unknown_cmd_literal_and_computed_value(tmp_path):
    bad = tmp_path / "bad_cmd.py"
    bad.write_text(
        textwrap.dedent(
            """
            def send(mgr, verb):
                mgr.submit({"cmd": "telemtry"})       # typo literal
                mgr.submit({"cmd": verb})             # unknown name
                mgr.submit({"cmd": "heartbeat"})      # fine: registered
                mgr.submit({"cmd": HEARTBEAT})        # fine: verb constant
                mgr.submit({"cmd": manager.TELEMETRY})  # fine: dotted form
            """
        )
    )
    verbs = frozenset({"heartbeat", "telemetry"})
    names = {"HEARTBEAT": "heartbeat", "TELEMETRY": "telemetry"}
    problems = check_wrappers.check_control_verbs(bad, verbs, names)
    assert len(problems) == 2
    assert "telemtry" in problems[0]
    assert "not a" in problems[1]


def test_verb_registry_load_fails_loudly(tmp_path):
    """Same stance as the event registry: a moved/computed CONTROL_VERBS
    literal (or a registry with no matching verb constants) raises."""
    import pytest

    missing = tmp_path / "no_verbs.py"
    missing.write_text("OTHER = frozenset({'ping'})\n")
    with pytest.raises(ValueError, match="CONTROL_VERBS"):
        check_wrappers.load_verb_registry(missing)

    computed = tmp_path / "computed_verbs.py"
    computed.write_text("CONTROL_VERBS = frozenset(sorted({'ping'}))\n")
    with pytest.raises(ValueError, match="literal"):
        check_wrappers.load_verb_registry(computed)

    unnamed = tmp_path / "unnamed_verbs.py"
    unnamed.write_text("CONTROL_VERBS = frozenset({'ping'})\n")
    with pytest.raises(ValueError, match="constants"):
        check_wrappers.load_verb_registry(unnamed)


def test_push_ack_path_is_sync_free():
    """The registered push-ack functions in kv/server.py contain no
    blocking device syncs (ISSUE 11 satellite): acks return while the
    donated device apply is still in flight."""
    path = REPO / "parameter_server_tpu" / check_wrappers.SERVER_MODULE
    assert path.is_file(), "server module moved: update SERVER_MODULE"
    problems = check_wrappers.check_push_ack_sync_free(path)
    assert problems == [], "\n".join(problems)


def test_catches_sync_in_ack_path(tmp_path):
    bad = tmp_path / "bad_server.py"
    bad.write_text(
        textwrap.dedent(
            """
            class KVServer:
                def _ack_push(self, msg, tname, kn, segs):
                    rows = np.asarray(self._last)      # D2H sync
                    self._last.block_until_ready()     # explicit sync
                    return msg.reply()

                def _apply_push_group(self, group, replies):
                    snap = jax.device_get(self._v)     # D2H sync
                    return snap

                def _push_group_rounds(self, *a):
                    pass

                def _push_group_combined(self, *a):
                    pass
            """
        )
    )
    problems = check_wrappers.check_push_ack_sync_free(bad)
    assert len(problems) == 3
    assert "np.asarray" in problems[0]
    assert "block_until_ready" in problems[1]
    assert "jax.device_get" in problems[2]


def test_sync_free_registry_fails_loudly_on_rename(tmp_path):
    """A renamed registered function must FAIL the check — the contract
    never passes vacuously against code it no longer reads."""
    bad = tmp_path / "renamed_server.py"
    bad.write_text(
        textwrap.dedent(
            """
            class KVServer:
                def _ack_push_v2(self, msg):
                    return msg.reply()
            """
        )
    )
    problems = check_wrappers.check_push_ack_sync_free(bad)
    assert len(problems) == 1
    assert "missing" in problems[0]
    assert "SYNC_FREE_FUNCS" in problems[0]


def test_sync_free_allows_host_side_bookkeeping(tmp_path):
    ok = tmp_path / "ok_server.py"
    ok.write_text(
        textwrap.dedent(
            """
            class KVServer:
                def _ack_push(self, msg, tname, kn, segs):
                    ver = self._seg_versions[tname]
                    if segs.size:
                        ver[segs] += 1
                    hit = kn[(kn >= 0) & (kn < 10)]
                    return msg.reply()

                def _apply_push_group(self, group, replies):
                    ids = np.concatenate([g[3] for g in group])
                    stack = jnp.stack([g[1] for g in group])  # H2D is fine
                    return ids, stack

                def _push_group_rounds(self, *a):
                    order = np.argsort(a[0], kind="stable")
                    return order

                def _push_group_combined(self, *a):
                    u, inv = np.unique(a[0], return_inverse=True)
                    return u, inv
            """
        )
    )
    assert check_wrappers.check_push_ack_sync_free(ok) == []


def test_accepts_super_delegation(tmp_path):
    ok = tmp_path / "ok_van.py"
    ok.write_text(
        textwrap.dedent(
            """
            class PoliteVan(VanWrapper):
                def flush(self, timeout=5.0):
                    self._drain_mine(timeout)
                    return super().flush(timeout)

                def close(self):
                    self._thread.join()
                    self.inner.close()

                def counters(self):
                    return {"mine": 1}
            """
        )
    )
    assert check_wrappers.check_file(ok) == []


def test_ledger_submit_path_is_sync_free():
    """The ApplyLedger's ack-path methods (ISSUE 12) obey the same AST
    ban as the push-ack functions they run inside: registration is host
    bookkeeping only, never a device sync."""
    path = REPO / "parameter_server_tpu" / check_wrappers.LEDGER_MODULE
    assert path.is_file(), "ledger module moved: update LEDGER_MODULE"
    problems = check_wrappers.check_push_ack_sync_free(
        path,
        check_wrappers.LEDGER_SYNC_FREE_FUNCS,
        "LEDGER_SYNC_FREE_FUNCS",
    )
    assert problems == [], "\n".join(problems)


def test_catches_sync_in_ledger_submit_path(tmp_path):
    bad = tmp_path / "bad_ledger.py"
    bad.write_text(
        textwrap.dedent(
            """
            class ApplyLedger:
                def begin(self, table, members, rows):
                    return object()

                def mark_host(self):
                    pass

                def mark_h2d(self):
                    pass

                def submit(self, tok, ref, fallback):
                    ref.block_until_ready()        # device sync on submit
                    self._q.append(tok)

                def overloaded(self):
                    return bool(np.asarray(self._gauge))  # D2H sync
            """
        )
    )
    problems = check_wrappers.check_push_ack_sync_free(
        bad,
        check_wrappers.LEDGER_SYNC_FREE_FUNCS,
        "LEDGER_SYNC_FREE_FUNCS",
    )
    assert len(problems) == 2
    joined = "\n".join(problems)
    assert "block_until_ready" in joined
    assert "np.asarray" in joined


def test_ledger_registry_fails_loudly_on_rename(tmp_path):
    bad = tmp_path / "renamed_ledger.py"
    bad.write_text(
        textwrap.dedent(
            """
            class ApplyLedger:
                def begin(self, table, members, rows):
                    return object()
            """
        )
    )
    problems = check_wrappers.check_push_ack_sync_free(
        bad,
        check_wrappers.LEDGER_SYNC_FREE_FUNCS,
        "LEDGER_SYNC_FREE_FUNCS",
    )
    assert len(problems) == 1
    assert "missing" in problems[0]
    assert "LEDGER_SYNC_FREE_FUNCS" in problems[0]


def test_apply_event_taxonomy_stays_registered():
    """main() loud-fails if the ``apply.*`` kinds are dropped from the
    flightrec EVENTS registry; the positive half here pins that the live
    registry still carries every required kind."""
    from parameter_server_tpu.core import flightrec

    missing = check_wrappers.REQUIRED_EVENTS - flightrec.EVENTS
    assert not missing, f"EVENTS lost required apply kinds: {sorted(missing)}"
    assert check_wrappers.main([]) == 0  # the repo itself stays clean
