"""tools/check_wrappers.py wired as a tier-1 test (ISSUE 6 satellite).

The Van wrapper flush/close-delegation and counters-no-recursion contracts
were convention until PR 6; this keeps them enforced on every run.
"""

from __future__ import annotations

import pathlib
import sys
import textwrap

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import check_wrappers  # noqa: E402


def test_repo_wrappers_clean():
    problems = []
    for f in sorted((REPO / "parameter_server_tpu").rglob("*.py")):
        if "VanWrapper" in f.read_text():
            problems.extend(check_wrappers.check_file(f))
    assert problems == [], "\n".join(problems)


def test_catches_non_delegating_flush(tmp_path):
    bad = tmp_path / "bad_van.py"
    bad.write_text(
        textwrap.dedent(
            """
            class SwallowingVan(VanWrapper):
                def flush(self, timeout=5.0):
                    return True  # drains nothing below

                def close(self):
                    self.inner.close()
            """
        )
    )
    problems = check_wrappers.check_file(bad)
    assert len(problems) == 1
    assert "SwallowingVan.flush" in problems[0]


def test_catches_counters_recursion(tmp_path):
    bad = tmp_path / "bad_counters.py"
    bad.write_text(
        textwrap.dedent(
            """
            class DoubleCountVan(VanWrapper):
                def counters(self):
                    return {**self.inner.counters(), "mine": 1}
            """
        )
    )
    problems = check_wrappers.check_file(bad)
    assert len(problems) == 1
    assert "DoubleCountVan.counters" in problems[0]


def test_frame_hot_path_is_pickle_free():
    """The flat-frame hot path (codec, transport, resender, coalescer) must
    never re-import pickle — the serialize tax ISSUE 7 removed."""
    problems = []
    for rel in check_wrappers.NO_PICKLE_MODULES:
        path = REPO / "parameter_server_tpu" / rel
        assert path.is_file(), f"hot-path module moved: {rel}"
        problems.extend(check_wrappers.check_no_pickle(path))
    assert problems == [], "\n".join(problems)


def test_catches_pickle_import_on_hot_path(tmp_path):
    bad = tmp_path / "bad_codec.py"
    bad.write_text(
        textwrap.dedent(
            """
            import pickle
            from pickle import dumps

            def encode(msg):
                return pickle.dumps(msg)
            """
        )
    )
    problems = check_wrappers.check_no_pickle(bad)
    assert len(problems) == 2
    assert "pickle" in problems[0]


def test_no_pickle_allows_clean_module(tmp_path):
    ok = tmp_path / "ok_codec.py"
    ok.write_text("import struct\nimport zlib\n")
    assert check_wrappers.check_no_pickle(ok) == []


def test_main_fails_loudly_if_hot_path_module_missing(tmp_path, monkeypatch):
    """NO_PICKLE_MODULES entries must exist when scanning the real package;
    a rename must fail the check, not silently skip the ban."""
    monkeypatch.setattr(
        check_wrappers, "NO_PICKLE_MODULES",
        check_wrappers.NO_PICKLE_MODULES + ("core/renamed_codec.py",),
    )
    assert check_wrappers.main(["check_wrappers"]) == 1


def test_accepts_super_delegation(tmp_path):
    ok = tmp_path / "ok_van.py"
    ok.write_text(
        textwrap.dedent(
            """
            class PoliteVan(VanWrapper):
                def flush(self, timeout=5.0):
                    self._drain_mine(timeout)
                    return super().flush(timeout)

                def close(self):
                    self._thread.join()
                    self.inner.close()

                def counters(self):
                    return {"mine": 1}
            """
        )
    )
    assert check_wrappers.check_file(ok) == []
