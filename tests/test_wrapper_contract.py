"""tools/check_wrappers.py wired as a tier-1 test (ISSUE 6 satellite).

The Van wrapper flush/close-delegation and counters-no-recursion contracts
were convention until PR 6; this keeps them enforced on every run.
"""

from __future__ import annotations

import pathlib
import sys
import textwrap

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import check_wrappers  # noqa: E402


def test_repo_wrappers_clean():
    problems = []
    for f in sorted((REPO / "parameter_server_tpu").rglob("*.py")):
        if "VanWrapper" in f.read_text():
            problems.extend(check_wrappers.check_file(f))
    assert problems == [], "\n".join(problems)


def test_catches_non_delegating_flush(tmp_path):
    bad = tmp_path / "bad_van.py"
    bad.write_text(
        textwrap.dedent(
            """
            class SwallowingVan(VanWrapper):
                def flush(self, timeout=5.0):
                    return True  # drains nothing below

                def close(self):
                    self.inner.close()
            """
        )
    )
    problems = check_wrappers.check_file(bad)
    assert len(problems) == 1
    assert "SwallowingVan.flush" in problems[0]


def test_catches_counters_recursion(tmp_path):
    bad = tmp_path / "bad_counters.py"
    bad.write_text(
        textwrap.dedent(
            """
            class DoubleCountVan(VanWrapper):
                def counters(self):
                    return {**self.inner.counters(), "mine": 1}
            """
        )
    )
    problems = check_wrappers.check_file(bad)
    assert len(problems) == 1
    assert "DoubleCountVan.counters" in problems[0]


def test_accepts_super_delegation(tmp_path):
    ok = tmp_path / "ok_van.py"
    ok.write_text(
        textwrap.dedent(
            """
            class PoliteVan(VanWrapper):
                def flush(self, timeout=5.0):
                    self._drain_mine(timeout)
                    return super().flush(timeout)

                def close(self):
                    self._thread.join()
                    self.inner.close()

                def counters(self):
                    return {"mine": 1}
            """
        )
    )
    assert check_wrappers.check_file(ok) == []
