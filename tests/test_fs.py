"""File service (reference ``file.h``/HDFS role): local, gzip, psfs://.

The capability VERDICT r2 missing #6 asked for: readers must feed from
non-local shard stores.  These tests run a real FileServer over TCP
loopback and drive the FULL reader path (chunking, parsing, caching,
stream batching) through psfs:// urls.
"""

import gzip
import os

import numpy as np
import pytest

from parameter_server_tpu.data import fs
from parameter_server_tpu.data.reader import SlotReader, StreamReader


@pytest.fixture
def served_dir(tmp_path):
    root = tmp_path / "shards"
    root.mkdir()
    srv = fs.FileServer(str(root), host="127.0.0.1").start()
    try:
        yield root, srv
    finally:
        srv.stop()


def _libsvm_lines(rows, seed=0):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(rows):
        label = int(rng.integers(0, 2))
        keys = sorted(rng.choice(1000, size=5, replace=False))
        feats = " ".join(f"{k}:1" for k in keys)
        lines.append(f"{label} {feats}\n")
    return "".join(lines)


def test_stat_read_list_roundtrip(served_dir):
    root, srv = served_dir
    payload = b"hello shard bytes" * 1000
    (root / "a.bin").write_bytes(payload)
    (root / "sub").mkdir()
    (root / "sub" / "b.bin").write_bytes(b"nested")

    url = f"{srv.url}/a.bin"
    st = fs.stat(url)
    assert st.size == len(payload)
    with fs.open_stream(url) as f:
        assert f.read() == payload
    # ranged read through seek
    with fs.open_stream(url) as f:
        f.seek(6)
        assert f.read(5) == payload[6:11]
    names = fs.list_files(f"{srv.url}/*.bin")
    assert names == [f"{srv.url}/a.bin"]
    nested = fs.list_files(f"{srv.url}/sub/*.bin")
    assert nested == [f"{srv.url}/sub/b.bin"]


def test_path_escape_refused(served_dir):
    _root, srv = served_dir
    with pytest.raises(OSError, match="escapes root|No such file"):
        fs.open_stream(f"{srv.url}/../secrets").read()


def test_gzip_transparent_local_and_remote(served_dir):
    root, srv = served_dir
    text = _libsvm_lines(50)
    with gzip.open(root / "part.txt.gz", "wt") as f:
        f.write(text)
    with fs.open_stream(str(root / "part.txt.gz")) as f:
        local = f.read()
    with fs.open_stream(f"{srv.url}/part.txt.gz") as f:
        remote = f.read()
    assert local == remote == text.encode()


def test_stream_reader_over_psfs_matches_local(served_dir):
    root, srv = served_dir
    (root / "train.txt").write_text(_libsvm_lines(200, seed=1))
    local_batches = list(
        StreamReader([str(root / "train.txt")], batch_size=64, epochs=1)
    )
    remote_batches = list(
        StreamReader([f"{srv.url}/train.txt"], batch_size=64, epochs=1)
    )
    assert len(local_batches) == len(remote_batches) == 3
    for lb, rb in zip(local_batches, remote_batches):
        for a, b in zip(lb, rb):
            np.testing.assert_array_equal(a, b)


def test_slot_reader_caches_remote_shards(served_dir, tmp_path):
    root, srv = served_dir
    (root / "block.txt").write_text(_libsvm_lines(120, seed=2))
    cache = tmp_path / "cache"
    url = f"{srv.url}/block.txt"
    r1 = SlotReader([url], cache_dir=str(cache))
    first = r1.read_all()
    assert first.rows == 120
    reads_after_first = srv.op_counts.get(2, 0)  # _OP_READ
    assert reads_after_first > 0
    # second pass: freshness STAT only, the bytes come from the local cache
    r2 = SlotReader([url], cache_dir=str(cache))
    second = r2.read_all()
    np.testing.assert_array_equal(first.labels, second.labels)
    np.testing.assert_array_equal(first.indices, second.indices)
    assert srv.op_counts.get(2, 0) == reads_after_first  # zero new READs
