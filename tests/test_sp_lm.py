"""Sequence-parallel LM trainer: ring attention inside the model, trained.

The long-context claim at trainer level: the SP trainer must compute the
SAME function as the dense single-mesh trainer (same params, same stream),
train end to end, and keep the per-device O(seq/n) memory shape.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from parameter_server_tpu.learner.lm import SpmdLMTrainer
from parameter_server_tpu.models import transformer as tfm
from parameter_server_tpu.parallel import mesh as mesh_lib
from parameter_server_tpu.parallel.sp_lm import SpLMTrainer


def _sp_mesh(n=8):
    return Mesh(np.asarray(jax.devices()[:n]), ("sp",))


def _cfg(**kw):
    defaults = dict(
        causal=True, tie_embeddings=False, n_heads=4, n_kv_heads=4,
        max_seq=256,
    )
    defaults.update(kw)
    return tfm.tiny_config(**defaults)


def _tokens(cfg, rng, batch=4, seq=64):
    return rng.integers(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)


def test_sp_trainer_matches_dense_trainer_trajectory():
    """Same init seed, same stream: the 8-shard ring trajectory equals the
    dense single-mesh trajectory (the param trees are identical and the
    ring computes exact attention)."""
    cfg = _cfg()
    rng = np.random.default_rng(0)
    batches = [_tokens(cfg, rng) for _ in range(4)]

    dense = SpmdLMTrainer(
        cfg, mesh_lib.make_mesh((1, 1), devices=jax.devices()[:1]),
        learning_rate=1e-2, seed=3,
    )
    sp = SpLMTrainer(cfg, _sp_mesh(8), learning_rate=1e-2, seed=3)
    for b in batches:
        np.testing.assert_allclose(
            sp.step(b), dense.step_causal(b), rtol=2e-4, atol=1e-5
        )


def test_sp_trainer_trains_long_sequences():
    cfg = _cfg(max_seq=2048)
    sp = SpLMTrainer(cfg, _sp_mesh(8), learning_rate=3e-3, seed=1)
    rng = np.random.default_rng(2)
    # structured stream a tiny model can learn
    base = rng.integers(0, cfg.vocab_size, size=(2, 1))
    offs = np.arange(1024)[None, :]
    tokens = ((base + offs) % cfg.vocab_size).astype(np.int32)
    losses = [sp.step(tokens) for _ in range(8)]
    assert np.isfinite(losses).all()
    assert np.mean(losses[-2:]) < np.mean(losses[:2]), losses


def test_sp_trainer_memory_stays_blockwise():
    """The compiled SP step must not materialize the O(S^2) score matrix:
    per-device temps at seq 4096 stay far below the full matrix bytes."""
    cfg = _cfg(max_seq=4096, n_layers=2)
    sp = SpLMTrainer(cfg, _sp_mesh(8), seed=0)
    B, S = 1, 4096
    tok = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=sp._seq_sharding)
    msk = jax.ShapeDtypeStruct(
        (B, S), jnp.float32, sharding=sp._seq_sharding
    )
    ma = (
        sp._step.lower(sp.params, sp.opt_state, tok, tok, msk)
        .compile()
        .memory_analysis()
    )
    scores_bytes = B * cfg.n_heads * S * S * 4  # the full matrix, per layer
    assert ma.temp_size_in_bytes < scores_bytes, (
        ma.temp_size_in_bytes,
        scores_bytes,
    )


def test_sp_trainer_scan_blocks_composes():
    """SP x scan-over-layers x remat: the 8B-recipe structure under ring
    attention compiles and trains."""
    cfg = _cfg(scan_blocks=True, remat=True, n_layers=2)
    sp = SpLMTrainer(cfg, _sp_mesh(8), learning_rate=3e-3, seed=4)
    rng = np.random.default_rng(5)
    losses = [sp.step(_tokens(cfg, rng)) for _ in range(4)]
    assert np.isfinite(losses).all()


def test_sp_trainer_rejects_bad_configs():
    with pytest.raises(ValueError, match="sp"):
        SpLMTrainer(_cfg(), mesh_lib.make_mesh((2, 4)))
    with pytest.raises(ValueError, match="causal"):
        SpLMTrainer(
            tfm.tiny_config(causal=False, tie_embeddings=False), _sp_mesh(2)
        )
    sp = SpLMTrainer(_cfg(), _sp_mesh(8))
    with pytest.raises(ValueError, match="sp shards"):
        sp.step(np.zeros((2, 60), np.int32))  # 60 % 8 != 0
    # learned positionals + global seq > max_seq must fail LOUDLY at the
    # trainer (the positions-given path in _apply_body cannot raise and
    # jnp.take would silently clip — ADVICE r4)
    lp = SpLMTrainer(
        _cfg(positional="learned", norm="ln", max_seq=32), _sp_mesh(8)
    )
    with pytest.raises(ValueError, match="max_seq"):
        lp.step(np.zeros((2, 64), np.int32))  # 64 > max_seq 32


def test_sp_composes_with_dp():
    """DP x SP on one (data, sp) mesh: same math as pure SP and as the
    dense trainer — batch rows shard over data, sequence over sp, gradient
    psums over both axes."""
    cfg = _cfg()
    rng = np.random.default_rng(6)
    batches = [_tokens(cfg, rng, batch=4, seq=64) for _ in range(3)]

    dense = SpmdLMTrainer(
        cfg, mesh_lib.make_mesh((1, 1), devices=jax.devices()[:1]),
        learning_rate=1e-2, seed=9,
    )
    dp_sp = SpLMTrainer(
        cfg,
        mesh_lib.make_mesh((2, 4), ("data", "sp")),
        learning_rate=1e-2, seed=9,
    )
    for b in batches:
        np.testing.assert_allclose(
            dp_sp.step(b), dense.step_causal(b), rtol=2e-4, atol=1e-5
        )


def test_sp_trainer_ulysses_matches_dense():
    """attn="ulysses": all-to-all head redistribution gives the same
    trajectory as the dense trainer (n_heads % shards == 0)."""
    cfg = _cfg()  # 4 heads, 4 shards
    rng = np.random.default_rng(8)
    batches = [_tokens(cfg, rng) for _ in range(3)]
    dense = SpmdLMTrainer(
        cfg, mesh_lib.make_mesh((1, 1), devices=jax.devices()[:1]),
        learning_rate=1e-2, seed=11,
    )
    uly = SpLMTrainer(
        cfg, _sp_mesh(4), learning_rate=1e-2, seed=11, attn="ulysses"
    )
    for b in batches:
        np.testing.assert_allclose(
            uly.step(b), dense.step_causal(b), rtol=2e-4, atol=1e-5
        )
