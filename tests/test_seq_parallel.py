"""Ring attention + Ulysses vs full-attention oracle on the 8-device mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from parameter_server_tpu.ops.ring_attention import (
    make_ring_attention,
    reference_attention,
)
from parameter_server_tpu.ops.ulysses import make_ulysses_attention


def _mesh_sp(n=8):
    return Mesh(np.asarray(jax.devices()[:n]), ("sp",))


def _qkv(rng, b=2, s=64, h=8, d=16):
    return tuple(
        jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng)
    mesh = _mesh_sp()
    fn = make_ring_attention(mesh, sp_axis="sp", causal=causal)
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = fn(qs, ks, vs)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(causal):
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng)  # h=8 divisible by sp=8
    mesh = _mesh_sp()
    fn = make_ulysses_attention(mesh, sp_axis="sp", causal=causal)
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = fn(qs, ks, vs)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5)


def test_ulysses_multiple_heads_per_device():
    """hn > 1: head regrouping must preserve head identity (regression)."""
    rng = np.random.default_rng(3)
    q, k, v = _qkv(rng, b=1, s=32, h=16, d=8)  # hn = 16/8 = 2
    mesh = _mesh_sp()
    fn = make_ulysses_attention(mesh, sp_axis="sp", causal=True)
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    out = fn(*(jax.device_put(x, spec) for x in (q, k, v)))
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5)


def test_ring_attention_long_seq_smoke():
    """Longer-than-memory-per-shard shape sanity (4k tokens over 8 shards)."""
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, b=1, s=4096, h=2, d=8)
    mesh = _mesh_sp()
    fn = make_ring_attention(mesh, sp_axis="sp", causal=True)
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    out = fn(*(jax.device_put(x, spec) for x in (q, k, v)))
    assert out.shape == (1, 4096, 2, 8)
    assert np.isfinite(np.asarray(out)).all()
