"""Ring attention + Ulysses vs full-attention oracle on the 8-device mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from parameter_server_tpu.ops.ring_attention import (
    make_ring_attention,
    reference_attention,
)
from parameter_server_tpu.ops.ulysses import make_ulysses_attention


def _mesh_sp(n=8):
    return Mesh(np.asarray(jax.devices()[:n]), ("sp",))


def _qkv(rng, b=2, s=64, h=8, d=16):
    return tuple(
        jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng)
    mesh = _mesh_sp()
    fn = make_ring_attention(mesh, sp_axis="sp", causal=causal)
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = fn(qs, ks, vs)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(causal):
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng)  # h=8 divisible by sp=8
    mesh = _mesh_sp()
    fn = make_ulysses_attention(mesh, sp_axis="sp", causal=causal)
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = fn(qs, ks, vs)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5)


def test_ulysses_multiple_heads_per_device():
    """hn > 1: head regrouping must preserve head identity (regression)."""
    rng = np.random.default_rng(3)
    q, k, v = _qkv(rng, b=1, s=32, h=16, d=8)  # hn = 16/8 = 2
    mesh = _mesh_sp()
    fn = make_ulysses_attention(mesh, sp_axis="sp", causal=True)
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    out = fn(*(jax.device_put(x, spec) for x in (q, k, v)))
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5)


def test_ring_attention_long_seq_smoke():
    """Longer-than-memory-per-shard shape sanity (4k tokens over 8 shards)."""
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, b=1, s=4096, h=2, d=8)
    mesh = _mesh_sp()
    fn = make_ring_attention(mesh, sp_axis="sp", causal=True)
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    out = fn(*(jax.device_put(x, spec) for x in (q, k, v)))
    assert out.shape == (1, 4096, 2, 8)
    assert np.isfinite(np.asarray(out)).all()


def test_ring_attention_memory_bound_at_8k():
    """Per-device peak temp memory is O(seq/n) blockwise, NOT O(seq^2).

    The entire point of ring attention for the BERT/Llama configs (VERDICT
    r2 #8): at seq=8192 on the 8-shard mesh, the compiled per-device
    program's temp allocation must come in far below full attention's
    O(seq^2) score matrix — asserted from XLA's own memory analysis of the
    compiled executables, not a proxy model.
    """
    B, S, H, D = 1, 8192, 4, 64
    n = 8
    mesh = _mesh_sp(n)
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    shape = jax.ShapeDtypeStruct((B, S, H, D), jnp.float32, sharding=sh)
    ring = make_ring_attention(mesh, sp_axis="sp", causal=True)
    ring_ma = ring.lower(shape, shape, shape).compile().memory_analysis()

    full = jax.jit(lambda q, k, v: reference_attention(q, k, v, causal=True))
    shape_r = jax.ShapeDtypeStruct((B, S, H, D), jnp.float32)
    full_ma = full.lower(shape_r, shape_r, shape_r).compile().memory_analysis()

    scores_bytes = B * H * S * S * 4  # the f32 score matrix full attn holds
    assert full_ma.temp_size_in_bytes >= scores_bytes  # oracle sanity
    # per-device ring temps must beat the O(S^2) cost by at least the shard
    # factor n (measured: ~58x at these shapes; n is the safe lower bar)
    assert ring_ma.temp_size_in_bytes * n <= full_ma.temp_size_in_bytes, (
        ring_ma.temp_size_in_bytes,
        full_ma.temp_size_in_bytes,
    )
    # and per-device arguments hold only the 1/n sequence shard
    assert ring_ma.argument_size_in_bytes <= 3 * B * (S // n) * H * D * 4 + 4096


def test_ring_attention_exact_at_8k():
    """Exactness (not just smoke) at seq=8192: ring == full softmax."""
    rng = np.random.default_rng(4)
    q, k, v = _qkv(rng, b=1, s=8192, h=2, d=8)
    mesh = _mesh_sp()
    fn = make_ring_attention(mesh, sp_axis="sp", causal=True)
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    out = fn(*(jax.device_put(x, spec) for x in (q, k, v)))
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_gradients_match_reference(causal):
    """The flash-style ring backward (custom_vjp) must produce the exact
    dQ/dK/dV of full attention — value parity alone would not catch a
    mis-rotated accumulator."""
    rng = np.random.default_rng(7)
    q, k, v = _qkv(rng, b=1, s=32, h=2, d=8)
    mesh = _mesh_sp()
    ring = make_ring_attention(mesh, sp_axis="sp", causal=causal)
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    w = jnp.asarray(rng.normal(size=q.shape).astype(np.float32))

    def ring_loss(q_, k_, v_):
        return jnp.sum(ring(q_, k_, v_) * w)

    def ref_loss(q_, k_, v_):
        return jnp.sum(reference_attention(q_, k_, v_, causal=causal) * w)

    got = jax.grad(ring_loss, argnums=(0, 1, 2))(
        *(jax.device_put(x, spec) for x in (q, k, v))
    )
    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, r in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), rtol=1e-4, atol=1e-5
        )


def test_ring_attention_backward_memory_stays_blockwise():
    """Training through the ring must not save per-step score blocks
    (O(S^2/n)) nor per-step K/V copies (O(S) x n): with the custom_vjp the
    per-device residuals are the local O(S/n) blocks and backward temps
    are one (S/n)^2 working set."""
    B, S, H, D = 1, 8192, 4, 64
    n = 8
    mesh = _mesh_sp(n)
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    shape = jax.ShapeDtypeStruct((B, S, H, D), jnp.float32, sharding=sh)
    ring = make_ring_attention(mesh, sp_axis="sp", causal=True)

    def loss(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    ma = (
        jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        .lower(shape, shape, shape)
        .compile()
        .memory_analysis()
    )
    scores_bytes = B * H * S * S * 4
    # far below the O(S^2) matrix AND below n saved K/V copies
    assert ma.temp_size_in_bytes < scores_bytes // n, (
        ma.temp_size_in_bytes,
        scores_bytes // n,
    )
