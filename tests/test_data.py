"""Data IO layer: text parsers (native/Python parity), readers, e2e train.

Mirrors the reference's parser unit tests (SURVEY.md §4: text parser +
SlotReader gtests) plus parity assertions the reference never needed (two
parser implementations here: C++ and numpy fallback).
"""

import os

import numpy as np
import pytest

from parameter_server_tpu import native
from parameter_server_tpu.data import reader as reader_lib
from parameter_server_tpu.data import text as text_lib
from parameter_server_tpu.utils.keys import PAD_KEY, mix64

LIBSVM_SAMPLE = b"""# comment line
1 3:0.5 17:1.25 100000:2
0 5:1 6:-0.75
1 12345678901:3.5e-2  # trailing comment
0

-1 7:1e3
"""


def _py_parse(fn, *args, **kw):
    """Run a parse with the native path disabled."""
    native._cache.clear()
    os.environ["PS_NO_NATIVE"] = "1"
    try:
        return fn(*args, **kw)
    finally:
        del os.environ["PS_NO_NATIVE"]
        native._cache.clear()


def test_libsvm_fallback_basics():
    b = _py_parse(text_lib.parse_libsvm, LIBSVM_SAMPLE)
    assert b.rows == 5
    np.testing.assert_array_equal(b.labels, [1, 0, 1, 0, -1])
    np.testing.assert_array_equal(b.indptr, [0, 3, 5, 6, 6, 7])
    assert b.indices[0] == 3 and b.values[1] == pytest.approx(1.25)
    assert b.indices[5] == 12345678901
    assert b.values[5] == pytest.approx(3.5e-2)


def test_libsvm_native_matches_python():
    if native.load("textparse") is None:
        pytest.skip("no native toolchain")
    rng = np.random.default_rng(0)
    lines = []
    for _ in range(500):
        nnz = rng.integers(0, 40)
        feats = " ".join(
            f"{rng.integers(0, 1 << 48)}:{rng.normal():.6g}" for _ in range(nnz)
        )
        lines.append(f"{rng.integers(0, 2)} {feats}")
    data = ("\n".join(lines) + "\n").encode()
    a = text_lib.parse_libsvm(data)
    b = _py_parse(text_lib.parse_libsvm, data)
    np.testing.assert_array_equal(a.labels, b.labels)
    np.testing.assert_array_equal(a.indptr, b.indptr)
    np.testing.assert_array_equal(a.indices, b.indices)
    np.testing.assert_allclose(a.values, b.values, rtol=1e-6)


def test_criteo_native_matches_python_and_hashes():
    data = (
        b"1\t" + b"\t".join(b"%d" % i for i in range(13)) + b"\t"
        + b"\t".join(b"%02x" % i for i in range(26)) + b"\n"
        + b"0\t\t2\t\t4\t5\t6\t7\t8\t9\t10\t11\t12\t\tdeadbeef"
        + b"\t" * 25 + b"\n"
    )
    lp, dp, kp = _py_parse(text_lib.parse_criteo, data)
    assert lp.shape == (2,) and dp.shape == (2, 13) and kp.shape == (2, 26)
    assert dp[1, 0] == 0.0 and dp[1, 1] == 2.0  # missing dense -> 0
    # slot salting: same raw value in different slots -> different keys
    assert kp[1, 1] != kp[1, 2]
    # hash parity with utils.keys.mix64
    want = mix64(np.uint64(0xDEADBEEF) ^ np.uint64(1), 0)
    assert kp[1, 0] == want
    if native.load("textparse") is not None:
        ln, dn, kn = text_lib.parse_criteo(data)
        np.testing.assert_array_equal(ln, lp)
        np.testing.assert_array_equal(dn, dp)
        np.testing.assert_array_equal(kn, kp)


def test_malformed_tokens_skip_not_hang():
    """qid:/negative/junk-suffix tokens are skipped whole by BOTH parsers.

    Regression: the native tokenizer previously made no forward progress on
    tokens without a leading digit (infinite loop in count, overrun in fill).
    """
    svm = (
        b"1 qid:3 5:1\n"          # qid token skipped, 5:1 kept
        b"0 -3:0.5 7:2\n"         # negative key skipped
        b"1 3:0.5x 9:1\n"         # junk-suffix value: token skipped
        b"0 5: 11:1\n"            # empty value: token skipped
        b"1 3.5:1 13:4\n"         # non-integer key skipped
        b"abc 15:1e2\n"           # junk label -> 0.0, exponent value kept
    )
    a = _py_parse(text_lib.parse_libsvm, svm)
    np.testing.assert_array_equal(a.labels, [1, 0, 1, 0, 1, 0])
    np.testing.assert_array_equal(a.indices, [5, 7, 9, 11, 13, 15])
    np.testing.assert_allclose(a.values, [1, 2, 1, 1, 4, 100])
    if native.load("textparse") is not None:
        b = text_lib.parse_libsvm(svm)
        np.testing.assert_array_equal(a.labels, b.labels)
        np.testing.assert_array_equal(a.indptr, b.indptr)
        np.testing.assert_array_equal(a.indices, b.indices)
        np.testing.assert_allclose(a.values, b.values)


def test_criteo_dense_junk_no_desync():
    """Non-numeric dense fields zero that field only; columns stay aligned."""
    tsv = (
        b"1\tnan\t2\t1a\t4\t5\t6\t7\t8\t9\t10\t11\t12\t99"
        + b"\t" + b"\t".join(b"%02x" % i for i in range(26)) + b"\n"
    )
    lp, dp, kp = _py_parse(text_lib.parse_criteo, tsv)
    assert dp[0, 0] == 0.0  # 'nan' rejected (C numeric subset has no nan)
    assert dp[0, 1] == 2.0
    assert dp[0, 2] == 1.0  # '1a' -> numeric prefix 1, junk dropped
    assert dp[0, 12] == 99.0
    assert kp[0, 0] == text_lib.hash_cat(np.uint64(0), 0)  # col 14 == "00"
    if native.load("textparse") is not None:
        ln, dn, kn = text_lib.parse_criteo(tsv)
        np.testing.assert_array_equal(dn, dp)
        np.testing.assert_array_equal(kn, kp)


def test_parser_parity_edge_cases():
    """Comment lines, blank CRLF lines, junk/overflow hex — both paths agree."""
    svm = b"# header comment\n1 3:0.5\n   # indented comment\n0 5:1\n"
    a = _py_parse(text_lib.parse_libsvm, svm)
    assert a.rows == 2
    if native.load("textparse") is not None:
        b = text_lib.parse_libsvm(svm)
        np.testing.assert_array_equal(a.labels, b.labels)
        np.testing.assert_array_equal(a.indptr, b.indptr)
    tsv = (
        b"1\t" + b"\t".join(b"%d" % i for i in range(13)) + b"\t"
        + b"\t".join(b"%02x" % i for i in range(26)) + b"\n"
        + b"\r\n"  # blank CRLF line: not a row
        + b"0\t" + b"\t" * 13 + b"12345678901234567"  # 17 hex digits: wraps
        + b"\t12z9"  # junk suffix: hex prefix 0x12
        + b"\t" * 24 + b"\n"
    )
    lp, dp, kp = _py_parse(text_lib.parse_criteo, tsv)
    assert lp.shape == (2,)
    assert kp[1, 0] == text_lib.hash_cat(
        np.uint64(0x2345678901234567), 0
    )  # top digit wrapped off
    assert kp[1, 1] == text_lib.hash_cat(np.uint64(0x12), 1)
    if native.load("textparse") is not None:
        ln, dn, kn = text_lib.parse_criteo(tsv)
        np.testing.assert_array_equal(ln, lp)
        np.testing.assert_array_equal(kn, kp)


def test_mix64_abi_parity():
    lib = text_lib._lib()  # sets ps_mix64 argtypes/restype (order-independent)
    if lib is None:
        pytest.skip("no native toolchain")
    xs = np.random.default_rng(1).integers(0, 1 << 63, size=32, dtype=np.uint64)
    for x in xs:
        assert lib.ps_mix64(int(x), 7) == int(mix64(x, 7))


def test_to_fixed_nnz_pads_and_truncates():
    b = _py_parse(text_lib.parse_libsvm, LIBSVM_SAMPLE)
    keys, vals, labels = b.to_fixed_nnz(2)
    assert keys.shape == (5, 2)
    assert keys[0, 0] == 3 and keys[0, 1] == 17  # truncated row
    assert keys[3, 0] == PAD_KEY and vals[3, 0] == 0.0  # empty row padded
    np.testing.assert_array_equal(labels, b.labels)


def test_write_parse_roundtrip(tmp_path):
    b = _py_parse(text_lib.parse_libsvm, LIBSVM_SAMPLE)
    p = tmp_path / "out.libsvm"
    text_lib.write_libsvm(str(p), b)
    b2 = text_lib.parse_libsvm(p.read_bytes())
    np.testing.assert_array_equal(b.indices, b2.indices)
    np.testing.assert_allclose(b.values, b2.values, rtol=1e-5)


def _write_synthetic_libsvm(path, rows, seed=0, nnz=8, key_space=1 << 16):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(rows):
            keys = rng.integers(0, key_space, size=nnz)
            label = rng.integers(0, 2)
            f.write(f"{label} " + " ".join(f"{k}:1" for k in keys) + "\n")


def test_slot_reader_caches(tmp_path):
    data = tmp_path / "train.libsvm"
    _write_synthetic_libsvm(str(data), 300)
    cache = tmp_path / "cache"
    r = reader_lib.SlotReader(
        [str(data)], cache_dir=str(cache), chunk_bytes=4096
    )
    full = r.read_all()
    assert full.rows == 300
    cached_files = list(cache.glob("slot_*.npz"))
    assert cached_files, "cache not written"
    # second pass hits the cache and returns identical data
    full2 = r.read_all()
    np.testing.assert_array_equal(full.indices, full2.indices)
    np.testing.assert_array_equal(full.indptr, full2.indptr)
    # warm-cache fast path: overwrite the raw file with garbage while
    # preserving (size, mtime) — the manifest + chunk cache must serve the
    # ORIGINAL data without touching the raw bytes
    st = data.stat()
    data.write_bytes(b"#" * st.st_size)
    os.utime(data, ns=(st.st_atime_ns, st.st_mtime_ns))
    full3 = r.read_all()
    np.testing.assert_array_equal(full.indices, full3.indices)


def test_stream_reader_batches(tmp_path):
    data = tmp_path / "s.libsvm"
    _write_synthetic_libsvm(str(data), 250)
    sr = reader_lib.StreamReader(
        [str(data)], batch_size=64, max_nnz=8, epochs=2, chunk_bytes=2048
    )
    batches = list(sr)
    # 500 rows over 2 epochs -> 7 full batches of 64
    assert len(batches) == (250 * 2) // 64
    for keys, vals, labels in batches:
        assert keys.shape == (64, 8) and labels.shape == (64,)
        assert keys.dtype == np.uint64


def test_stream_reader_criteo(tmp_path):
    lines = []
    rng = np.random.default_rng(3)
    for i in range(40):
        dense = "\t".join(str(int(x)) for x in rng.integers(0, 100, 13))
        cats = "\t".join(f"{int(x):x}" for x in rng.integers(0, 1 << 32, 26))
        lines.append(f"{i % 2}\t{dense}\t{cats}")
    p = tmp_path / "day0.tsv"
    p.write_text("\n".join(lines) + "\n")
    sr = reader_lib.StreamReader(
        [str(p)], batch_size=16, format="criteo", epochs=1
    )
    batches = list(sr)
    assert len(batches) == 2
    keys, dense, labels = batches[0]
    assert keys.shape == (16, 26) and dense.shape == (16, 13)


def test_e2e_train_from_libsvm_file(tmp_path):
    """Full slice: text file -> StreamReader -> LocalLRTrainer, loss drops."""
    from parameter_server_tpu.config import OptimizerConfig, TableConfig
    from parameter_server_tpu.learner.sgd import LocalLRTrainer

    # learnable synthetic: label = (sum of key parities) threshold
    path = tmp_path / "train.libsvm"
    rng = np.random.default_rng(7)
    with open(path, "w") as f:
        for _ in range(2000):
            keys = rng.integers(0, 512, size=6)
            label = int(np.sum(keys % 7 == 0) > 0)
            f.write(f"{label} " + " ".join(f"{k}:1" for k in keys) + "\n")
    cfg = TableConfig(
        name="w", rows=4096, dim=1,
        optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.2),
    )
    tr = LocalLRTrainer(cfg, min_bucket=256)
    losses = []
    sr = reader_lib.StreamReader([str(path)], batch_size=256, max_nnz=6, epochs=4)
    for keys, _vals, labels in sr:
        losses.append(tr.step(keys, labels))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.05


def test_libsvm_hash_comment_parity():
    """'#' glued inside a token is a malformed token, not a line truncation.

    Regression: the Python fallback used to cut the line at the first '#'
    anywhere, diverging from the native rule (comment only at token start;
    mid-token '#' -> skip that token whole, keep parsing the line).
    """
    data = (
        b"1 3:1#x 5:2\n"      # 3:1#x malformed -> only 5:2 survives
        b"# full line comment\n"
        b"0 7:1 # trailing 9:9\n"  # comment token ends the line
        b"1 12#4:5 8:1\n"     # 12#4:5 malformed key -> only 8:1
    )
    b = _py_parse(text_lib.parse_libsvm, data)
    np.testing.assert_array_equal(b.labels, [1, 0, 1])
    np.testing.assert_array_equal(b.indices, [5, 7, 8])
    np.testing.assert_array_equal(b.indptr, [0, 1, 2, 3])
    if native.load("textparse") is not None:
        a = text_lib.parse_libsvm(data)
        np.testing.assert_array_equal(a.labels, b.labels)
        np.testing.assert_array_equal(a.indices, b.indices)
        np.testing.assert_array_equal(a.indptr, b.indptr)
        np.testing.assert_allclose(a.values, b.values)


def test_float_exponent_overflow_parity():
    """Huge exponents must saturate to inf/0, never raise or wrap (UB)."""
    data = b"1 3:1e400 4:1e-400 5:2e2147483648 6:1.5\n"
    b = _py_parse(text_lib.parse_libsvm, data)
    np.testing.assert_array_equal(b.indices, [3, 4, 5, 6])
    assert np.isinf(b.values[0]) and b.values[1] == 0.0
    assert np.isinf(b.values[2]) and b.values[3] == pytest.approx(1.5)
    if native.load("textparse") is not None:
        a = text_lib.parse_libsvm(data)
        np.testing.assert_array_equal(a.indices, b.indices)
        np.testing.assert_array_equal(a.values, b.values)
