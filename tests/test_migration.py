"""Live shard migration + elastic rebalancing (ISSUE 6 tentpole).

Five scenarios on the acceptance list:

1. end-to-end ``ShardMigrator.migrate`` moves value AND optimizer state
   bitwise, shrinking the donor and growing the recipient;
2. pushes landing mid-stream ride the dirty DELTA shipped inside the
   bounded ``migrate_commit`` freeze — nothing lost, nothing doubled;
3. a worker routed by a stale table is REJECTED (typed fence), adopts the
   attached table, and re-submits only the fenced positions — under seeded
   chaos the final model is bitwise-equal to the fault-free run;
4. the closed loop: a Zipfian-hot workload drives ``FleetMonitor`` inbound
   byte ranking -> ``RebalancePolicy`` splits the hot range mid-training
   with loss-trajectory and push-apply parity, and the hot server's
   inbound byte share measurably drops;
5. ``scale_up`` / ``drain_down`` grow and retire servers live with zero
   loss and a bounded freeze; a donor killed mid-stream falls back to the
   PR-4 same-id restart path and the migration re-runs idempotently.
"""

import time

import numpy as np
import pytest

import jax.numpy as jnp

from parameter_server_tpu.config import OptimizerConfig, TableConfig
from parameter_server_tpu.core.chaos import ChaosVan
from parameter_server_tpu.core.fleet import FleetMonitor
from parameter_server_tpu.core.netmon import MeteredVan
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core.resender import ReliableVan
from parameter_server_tpu.core.van import LoopbackVan
from parameter_server_tpu.data.synthetic import SyntheticCTR
from parameter_server_tpu.kv import replica as replica_lib
from parameter_server_tpu.kv.migrate import ShardMigrator
from parameter_server_tpu.kv.routing import RoutingTable
from parameter_server_tpu.kv.server import KVServer
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.learner.elastic import (
    RebalanceConfig,
    RebalancePolicy,
    drain_down,
    scale_up,
)
from parameter_server_tpu.models import linear
from parameter_server_tpu.utils.keys import HashLocalizer

pytestmark = pytest.mark.migration

ROWS = 1 << 10
NUM_SERVERS = 2
STEPS = 12


def _table_cfgs():
    return {
        "w": TableConfig(
            name="w", rows=ROWS, dim=1,
            optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.1),
        )
    }


def _batches():
    data = SyntheticCTR(key_space=4 * ROWS, nnz=8, batch_size=128, seed=3)
    return [data.next_batch() for _ in range(STEPS)]


def _train(worker, batches, on_step=None):
    losses = []
    for i, (keys, labels) in enumerate(batches):
        w_pos = worker.pull_sync("w", keys, timeout=60)
        g, _gb, loss = linear.grad_rows(jnp.asarray(w_pos), jnp.asarray(labels))
        worker.push_sync("w", keys, np.asarray(g) / labels.shape[0], timeout=60)
        losses.append(float(loss))
        if on_step is not None:
            on_step(i)
    return losses


def _clean_reference(batches):
    """Fault-free fixed-topology run: losses, applied pushes, full table."""
    van = LoopbackVan()
    try:
        servers = [
            KVServer(Postoffice(f"S{s}", van), _table_cfgs(), s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        ]
        worker = KVWorker(Postoffice("W0", van), _table_cfgs(), NUM_SERVERS)
        losses = _train(worker, batches)
        value, state = _assemble(worker.routing, dict(enumerate(servers)))
        return losses, sum(s.pushes for s in servers), value, state
    finally:
        van.close()


def _reliable_stack(*, seed=0, timeout=0.05, max_retries=60, **chaos_kw):
    chaos = ChaosVan(LoopbackVan(), seed=seed, **chaos_kw)
    van = ReliableVan(
        chaos, timeout=timeout, backoff=1.0, max_retries=max_retries,
        seed=seed,
    )
    return van, chaos


def _assemble(routing: RoutingTable, servers_by_index, table="w"):
    """Full ``[rows, dim]`` value + optimizer state, stitched per segment."""
    tr = routing.tables[table]
    value = None
    state = None
    for i, owner in enumerate(tr.owners):
        lo, hi = tr.offsets[i], tr.offsets[i + 1]
        v, st = servers_by_index[owner].export_range(table, lo, hi)
        if value is None:
            value = np.zeros((tr.rows,) + v.shape[1:], v.dtype)
            state = {
                k: np.zeros((tr.rows,) + a.shape[1:], a.dtype)
                for k, a in st.items()
            }
        value[lo:hi] = v
        for k, a in st.items():
            state[k][lo:hi] = a
    return value, state


def _keys_hashing_into(lo, hi, count, *, start=0):
    """Raw keys whose HashLocalizer slot lands in global rows [lo, hi)."""
    loc = HashLocalizer(ROWS)
    found = []
    k = start
    while len(found) < count:
        cand = np.arange(k, k + 4096, dtype=np.int64)
        slots = loc.assign(cand.astype(np.uint64))
        hit = cand[(slots >= lo) & (slots < hi)]
        found.extend(int(x) for x in hit)
        k += 4096
    return np.asarray(found[:count], dtype=np.int64)


# ------------------------------------------------------ 1. basic migration


def test_migrate_moves_value_and_optimizer_state_bitwise():
    batches = _batches()
    ref_losses, _ref_applied, ref_value, ref_state = _clean_reference(batches)

    van = LoopbackVan()
    try:
        servers = {
            s: KVServer(Postoffice(f"S{s}", van), _table_cfgs(), s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        }
        worker = KVWorker(Postoffice("W0", van), _table_cfgs(), NUM_SERVERS)
        mig = ShardMigrator(Postoffice("M0", van), chunk_rows=128)
        routing = worker.routing
        moved = {}

        def on_step(i):
            if i != STEPS // 2:
                return
            # move the tail half of S1's range to S0, live
            new_routing = mig.migrate(routing, "w", 768, ROWS, 0)
            assert new_routing.epoch == routing.epoch + 1
            assert worker.adopt_routing(new_routing)
            moved["routing"] = new_routing

        losses = _train(worker, batches, on_step=on_step)
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-7, atol=0)

        routing = moved["routing"]
        assert routing.tables["w"].owned_segments(0) == [(0, 512), (768, ROWS)]
        assert routing.tables["w"].owned_segments(1) == [(512, 768)]
        value, state = _assemble(routing, servers)
        np.testing.assert_array_equal(value, ref_value)
        for k in ref_state:
            np.testing.assert_array_equal(state[k], ref_state[k])

        assert servers[1].rows_migrated_out == 256
        assert servers[0].rows_migrated_in >= 256  # chunks + dirty delta
        assert mig.migrations == 1 and mig.rows_moved == 256
        assert servers[1].migration_freeze_last_s >= 0.0
        # the freeze is the delta export, NOT the 256-row stream: bounded
        assert servers[1].migration_freeze_last_s < 5.0
    finally:
        van.close()


# ------------------------------------ 2. dirty delta inside the commit fence


def test_push_between_chunks_rides_commit_delta():
    """Rows dirtied AFTER their chunk shipped are re-sent in the commit
    freeze — the recipient's final state includes the late push exactly
    once (compared bitwise against a migration-free twin cluster)."""
    cfgs = _table_cfgs()
    lo, hi = 768, ROWS
    hot = _keys_hashing_into(lo, hi, 32)

    van = LoopbackVan()
    ref_van = LoopbackVan()
    try:
        servers = {
            s: KVServer(Postoffice(f"S{s}", van), cfgs, s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        }
        worker = KVWorker(Postoffice("W0", van), cfgs, NUM_SERVERS)
        ref_servers = {
            s: KVServer(Postoffice(f"S{s}", ref_van), cfgs, s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        }
        ref_worker = KVWorker(Postoffice("W0", ref_van), cfgs, NUM_SERVERS)

        ones = np.ones(hot.size, np.float32)
        worker.push_sync("w", hot, ones, timeout=60)
        ref_worker.push_sync("w", hot, ones, timeout=60)

        mig = ShardMigrator(Postoffice("M0", van), chunk_rows=128)
        routing = worker.routing
        new_routing = routing.move("w", lo, hi, 0)
        mid = "test:delta:0"
        mig._rpc("S1", {"op": "migrate_begin", "mid": mid, "table": "w",
                        "lo": lo, "hi": hi})
        for a in range(lo, hi, 128):
            mig._rpc("S1", {"op": "migrate_send", "mid": mid, "to": "S0",
                            "lo": a, "hi": a + 128})
        # every chunk has shipped; NOW dirty some of the migrating rows
        worker.push_sync("w", hot, 2 * ones, timeout=60)
        ref_worker.push_sync("w", hot, 2 * ones, timeout=60)
        mig._rpc("S1", {"op": "migrate_commit", "mid": mid, "to": "S0",
                        "routing": new_routing.to_payload()})

        assert worker.adopt_routing(new_routing)
        value, state = _assemble(new_routing, servers)
        ref_value, ref_state = _assemble(ref_worker.routing, ref_servers)
        np.testing.assert_array_equal(value, ref_value)
        for k in ref_state:
            np.testing.assert_array_equal(state[k], ref_state[k])
        # the counter is DISTINCT rows handed over, not chunk+delta traffic
        assert servers[0].rows_migrated_in == hi - lo
        assert servers[1].migration_freeze_last_s > 0.0
    finally:
        van.close()
        ref_van.close()


# --------------------------------------- 3. fencing under seeded packet loss


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0])
def test_stale_worker_is_fenced_not_lost_under_chaos(seed):
    """The worker is NOT told about a mid-run migration: its next requests
    carry the old epoch and are rejected with the new table attached.  The
    fence loop converges, and under seeded 5% drop the final model is
    bitwise-equal to the fault-free fixed-topology run — rejected, never
    lost, never double-applied."""
    batches = _batches()
    ref_losses, _ref_applied, ref_value, ref_state = _clean_reference(batches)

    van, chaos = _reliable_stack(seed=seed, timeout=0.1, drop=0.05)
    try:
        servers = {
            s: KVServer(Postoffice(f"S{s}", van), _table_cfgs(), s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        }
        worker = KVWorker(Postoffice("W0", van), _table_cfgs(), NUM_SERVERS)
        mig = ShardMigrator(Postoffice("M0", van), chunk_rows=256)
        moved = {}

        def on_step(i):
            if i != STEPS // 2:
                return
            # migrate WITHOUT informing the worker — it must discover the
            # new table from fence rejects alone
            moved["routing"] = mig.migrate(worker.routing, "w", 768, ROWS, 0)

        losses = _train(worker, batches, on_step=on_step)
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-7, atol=0)
        assert sum(s.fenced_rejects for s in servers.values()) > 0
        assert worker.refresh_retries > 0
        assert worker.routing.epoch == moved["routing"].epoch  # converged

        value, state = _assemble(moved["routing"], servers)
        np.testing.assert_array_equal(value, ref_value)
        for k in ref_state:
            np.testing.assert_array_equal(state[k], ref_state[k])
        assert chaos.injected_drops > 0  # the run was actually lossy
        assert van.flush(10)
    finally:
        van.close()


# ------------------------------------- 4. monitor-driven elastic rebalancing


def test_zipfian_skew_triggers_rebalance_with_parity():
    """ISSUE 6 acceptance e2e: a Zipfian-hot workload concentrates inbound
    bytes on S1; the FleetMonitor->RebalancePolicy loop splits the hot
    range off mid-training.  Zero lost/double-applied pushes (loss
    trajectory AND push-apply counts exactly match the no-rebalance run),
    and the hot server's inbound byte share drops measurably."""
    cfgs = _table_cfgs()
    rs = np.random.RandomState(7)
    hot = _keys_hashing_into(896, ROWS, 96)  # inside S1's tail half
    cold = rs.randint(0, 4 * ROWS, size=4096).astype(np.int64)
    batches = []
    for _ in range(STEPS):
        pick = rs.rand(128, 8) < 0.85
        keys = np.where(
            pick,
            hot[rs.randint(0, hot.size, size=(128, 8))],
            cold[rs.randint(0, cold.size, size=(128, 8))],
        )
        labels = rs.randint(0, 2, size=128).astype(np.float32)
        batches.append((keys, labels))

    ref_losses, ref_applied, ref_value, ref_state = _clean_reference(batches)

    metered = MeteredVan(LoopbackVan())
    try:
        servers = {
            s: KVServer(Postoffice(f"S{s}", metered), cfgs, s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        }
        worker = KVWorker(Postoffice("W0", metered), cfgs, NUM_SERVERS)
        monitor = FleetMonitor()
        mig = ShardMigrator(Postoffice("M0", metered), chunk_rows=256)
        policy = RebalancePolicy(
            monitor, mig, config=RebalanceConfig(hot_share=0.6)
        )
        state = {"routing": worker.routing, "at_move": None}

        def on_step(i):
            if state["at_move"] is not None:
                return  # one move is the scenario; fresh-window reuse would
                # chase the stale pre-move skew
            monitor.observe("W0", {"links": metered.links()})
            routing, moved_now = policy.maybe_rebalance(state["routing"])
            if moved_now:
                state["routing"] = routing
                state["at_move"] = (i, monitor.inbound_totals())
                # scheduler ROUTING broadcast stand-in: adopt eagerly so
                # parity is exact (fences would still converge, but each
                # fence round adds empty-leg re-pushes to the counters)
                assert worker.adopt_routing(routing)

        losses = _train(worker, batches, on_step=on_step)
        assert state["at_move"] is not None, "skew never triggered a move"
        move_step, totals_mid = state["at_move"]
        assert move_step < STEPS - 2  # moved mid-run, with steps left after
        assert policy.moves and policy.moves[0]["frm"] == 1
        assert policy.moves[0]["share"] >= 0.6

        # parity: identical trajectory and applied-push counts
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-7, atol=0)
        applied = sum(s.pushes for s in servers.values())
        assert applied == ref_applied
        value, st = _assemble(state["routing"], servers)
        np.testing.assert_array_equal(value, ref_value)
        for k in ref_state:
            np.testing.assert_array_equal(st[k], ref_state[k])

        # the hot server's inbound byte share dropped measurably
        monitor.observe("W0", {"links": metered.links()})
        totals_end = monitor.inbound_totals()

        def share(totals_a, totals_b):
            delta = {
                s: totals_b.get(f"S{s}", {}).get("bytes", 0)
                - totals_a.get(f"S{s}", {}).get("bytes", 0)
                for s in range(NUM_SERVERS)
            }
            return delta[1] / max(sum(delta.values()), 1)

        before = share({}, totals_mid)  # cumulative up to the move
        after = share(totals_mid, totals_end)  # the post-move window
        assert before > 0.6
        assert after < before - 0.2
    finally:
        metered.close()


# ----------------------------------------------- 5a. scale up + drain down


def test_scale_up_then_drain_down_zero_loss():
    """Grow to a third server live, then retire S1 live: the trajectory
    never deviates from the fixed 2-server run, the final model is
    bitwise-identical, every freeze was bounded, and the retired identity
    serves nothing."""
    cfgs = _table_cfgs()
    batches = _batches()
    ref_losses, _ref_applied, ref_value, ref_state = _clean_reference(batches)

    van = LoopbackVan()
    try:
        servers = {
            s: KVServer(Postoffice(f"S{s}", van), cfgs, s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        }
        worker = KVWorker(Postoffice("W0", van), cfgs, NUM_SERVERS)
        mig = ShardMigrator(Postoffice("M0", van), chunk_rows=128)
        state = {"routing": worker.routing}

        def on_step(i):
            if i == STEPS // 3:
                new_server, routing = scale_up(
                    van, cfgs, state["routing"], 2,
                    migrator=mig, num_servers=3,
                )
                servers[2] = new_server
                state["routing"] = routing
                assert worker.adopt_routing(routing)
                assert routing.tables["w"].server_rows(2) > 0
            if i == 2 * STEPS // 3:
                routing = drain_down(
                    van, state["routing"], 1, migrator=mig
                )
                state["routing"] = routing
                assert worker.adopt_routing(routing)

        losses = _train(worker, batches, on_step=on_step)
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-7, atol=0)

        routing = state["routing"]
        assert 1 not in routing.servers()
        assert routing.tables["w"].server_rows(1) == 0
        value, st = _assemble(routing, servers)
        np.testing.assert_array_equal(value, ref_value)
        for k in ref_state:
            np.testing.assert_array_equal(st[k], ref_state[k])
        for s in servers.values():
            assert s.migration_freeze_last_s < 5.0  # bounded, never a pause
        # the retired identity's endpoints are gone
        assert "S1" not in van._endpoints
    finally:
        van.close()


# ------------------------------------ 5b. donor killed mid-stream (chaos)


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1])
def test_donor_killed_mid_stream_migration_restarts_idempotently(seed):
    """ISSUE 6 satellite: the donor dies BETWEEN migrate_send chunks under
    seeded 5% drop.  Recovery is the PR-4 same-id restart (shard from the
    sync standby), after which the migration re-runs from scratch with a
    fresh id — stale staged chunks are superseded, and the loss trajectory
    and push-apply counts exactly match the fault-free run."""
    batches = _batches()
    ref_losses, ref_applied, ref_value, ref_state = _clean_reference(batches)

    van, chaos = _reliable_stack(seed=seed, timeout=0.1, drop=0.05)
    try:
        cfgs = _table_cfgs()
        primaries, standbys = replica_lib.make_replicated_servers(
            van, cfgs, NUM_SERVERS, sync=True
        )
        worker = KVWorker(Postoffice("W0", van), cfgs, NUM_SERVERS)
        mig = ShardMigrator(Postoffice("M0", van), chunk_rows=64)
        s1_instances = [primaries[1]]
        state = {"routing": worker.routing}

        def on_step(i):
            if i != STEPS // 2:
                return
            routing = state["routing"]
            # stream PART of the range, then kill the donor mid-migration
            mid = f"test:kill:{seed}"
            mig._rpc("S1", {"op": "migrate_begin", "mid": mid, "table": "w",
                            "lo": 768, "hi": ROWS})
            mig._rpc("S1", {"op": "migrate_send", "mid": mid, "to": "S0",
                            "lo": 768, "hi": 832})
            for endpoint in ("S1", "S1.fw", "S1.mig"):
                van.unbind(endpoint)
            van.restart_node("S1")
            new_s1, source = replica_lib.restart_same_id(
                van, cfgs, 1, NUM_SERVERS, standby=standbys[1]
            )
            assert source == "replica"
            # ownership never changed: the restarted donor holds the FULL
            # pre-migration shard at the old epoch
            assert new_s1.routing.epoch == routing.epoch
            s1_instances.append(new_s1)
            # re-run the whole migration; the fresh id supersedes the
            # stale staged chunks on the recipient
            new_routing = mig.migrate(routing, "w", 768, ROWS, 0)
            state["routing"] = new_routing
            assert worker.adopt_routing(new_routing)

        losses = _train(worker, batches, on_step=on_step)
        assert len(s1_instances) == 2
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-7, atol=0)
        applied = primaries[0].pushes + sum(s.pushes for s in s1_instances)
        assert applied == ref_applied  # zero lost, zero double-applied

        servers = {0: primaries[0], 1: s1_instances[-1]}
        value, st = _assemble(state["routing"], servers)
        np.testing.assert_array_equal(value, ref_value)
        for k in ref_state:
            np.testing.assert_array_equal(st[k], ref_state[k])
        assert s1_instances[-1].rows_migrated_out == 256
        assert van.flush(10)
        assert van.gave_up == 0
        assert chaos.injected_drops > 0
    finally:
        van.close()


# --------------------------------------------------- scheduler ROUTING verb


def test_scheduler_routing_broadcast_reaches_managers_and_workers():
    """Manager.set_routing: the scheduler broadcasts the table; peers store
    it, fire on_routing, and a wired worker adopts eagerly (no fence
    round-trip needed to converge)."""
    from parameter_server_tpu.core.manager import launch_local_cluster

    van, _chaos = _reliable_stack(seed=0, timeout=0.1)
    try:
        sched, managers, posts = launch_local_cluster(
            van, num_workers=1, num_servers=NUM_SERVERS, heartbeat_timeout=30
        )
        cfgs = _table_cfgs()
        worker = KVWorker(posts["W0"], cfgs, NUM_SERVERS)
        managers["W0"].on_routing.append(worker.adopt_routing)

        rt = RoutingTable.uniform(cfgs, NUM_SERVERS).move("w", 768, ROWS, 0)
        sched.set_routing(rt)

        deadline = time.time() + 5
        while time.time() < deadline:
            if worker.routing.epoch == rt.epoch:
                break
            time.sleep(0.01)
        assert worker.routing.epoch == rt.epoch
        assert worker.routing.tables["w"] == rt.tables["w"]
        assert managers["W0"].routing.epoch == rt.epoch
        # stale (lower-epoch) broadcast is ignored everywhere
        sched.routing = None
        sched.set_routing(RoutingTable.uniform(cfgs, NUM_SERVERS))
        time.sleep(0.1)
        assert worker.routing.epoch == rt.epoch
    finally:
        van.close()


# ------------------------------------------------------- counters satellite


def test_migration_counters_merge_into_dashboard_group():
    from parameter_server_tpu.utils.metrics import CounterGroup

    van = LoopbackVan()
    try:
        cfgs = _table_cfgs()
        servers = {
            s: KVServer(Postoffice(f"S{s}", van), cfgs, s, NUM_SERVERS)
            for s in range(NUM_SERVERS)
        }
        worker = KVWorker(Postoffice("W0", van), cfgs, NUM_SERVERS)
        mig = ShardMigrator(Postoffice("M0", van), chunk_rows=128)
        group = CounterGroup(*servers.values(), worker, mig)

        new_routing = mig.migrate(worker.routing, "w", 768, ROWS, 0)
        # a stale push: fenced once, then adopted and re-applied
        keys = _keys_hashing_into(768, ROWS, 8)
        worker.push_sync("w", keys, np.ones(keys.size, np.float32), timeout=60)
        assert worker.routing.epoch == new_routing.epoch

        got = group.counters()
        assert got["rows_migrated_out"] == 256
        assert got["rows_migrated_in"] >= 256
        assert got["fenced_rejects"] > 0
        assert got["refresh_retries"] > 0
        assert got["rows_moved"] == 256
        assert got["migrations"] == 1
        assert got["migration_freeze_s"] > 0.0
    finally:
        van.close()
