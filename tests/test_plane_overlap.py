"""Smoke the overlapped embedding-plane harness (bench part c).

The full 8B-shape measurement is a bench; here a small shape must drive
the same machinery — TcpVan sockets, codec chain, device replies,
prefetched pull + bounded-delay push against a body window — and return
a well-formed record, so the --llama8b section cannot rot.
"""

import numpy as np
import pytest

import bench
from parameter_server_tpu import native


def test_emb_plane_overlapped_small_shape():
    if native.load("tcpvan") is None:  # pragma: no cover
        pytest.skip("no native toolchain for tcpvan")
    r = bench._emb_plane_overlapped(
        VOCAB=16384, D=256, B=8, S=256, steps=3, t_body_s=0.2,
        filters="key_caching+int8",
    )
    assert r["steps"] == 3
    assert len(r["exposure_ms"]) == 3
    assert np.all(np.isfinite(r["exposure_ms"]))
    # real bytes crossed the sockets, and int8 compressed them: the wire
    # must be well under the raw f32 rows (2 directions) yet nonzero
    assert 0 < r["wire_mb_per_step"] < 2 * r["raw_row_mb_per_step"]
    assert r["unique_rows_per_step"] > 0
    assert r["tokens_per_sec_overlapped"] > 0


def test_emb_plane_overlapped_zero_body_measures_serial_plane():
    """The sweep's t_body_s=0 run: no body window to hide behind, so the
    record reports the plane's serial cost directly and the "% of a
    zero-length body" ratio is None (not a division blowup or a fake 0)."""
    if native.load("tcpvan") is None:  # pragma: no cover
        pytest.skip("no native toolchain for tcpvan")
    r = bench._emb_plane_overlapped(
        VOCAB=16384, D=256, B=8, S=256, steps=3, t_body_s=0.0,
        filters="key_caching+int8",
    )
    assert r["exposure_pct_of_body"] is None
    assert r["t_body_ms"] == 0
    assert np.all(np.isfinite(r["exposure_ms"]))
    assert np.all(np.asarray(r["exposure_ms"]) >= 0)
    assert r["tokens_per_sec_overlapped"] > 0


def test_plane_codec_microbench_shape():
    c = bench._plane_codec_microbench(D=64, rows=500)
    assert c["payload_mb"] > 0
    assert c["quantize_ms"] >= 0 and c["dequantize_ms"] >= 0
    assert -100 <= c["zlib_saves_pct"] <= 100
