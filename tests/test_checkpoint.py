"""Checkpoint/resume: sharded save, elastic restore, commit marker.

Covers the SURVEY.md §5 checkpoint plan: table shards + optimizer-state rows
+ consistency clocks, restore under a *different* server count (elastic
re-shard), and the reference SaveModel broadcast path over the Van.
"""

import numpy as np
import pytest

from parameter_server_tpu import checkpoint
from parameter_server_tpu.config import OptimizerConfig, TableConfig
from parameter_server_tpu.core.postoffice import Postoffice
from parameter_server_tpu.core.van import LoopbackVan
from parameter_server_tpu.kv.server import KVServer
from parameter_server_tpu.kv.table import KVTable
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.utils.keys import HashLocalizer


def _cfgs(rows=1000, dim=4, kind="adagrad"):
    return {
        "w": TableConfig(
            name="w",
            rows=rows,
            dim=dim,
            optimizer=OptimizerConfig(kind=kind, learning_rate=0.5),
        )
    }


def _cluster(van, cfgs, num_servers, worker_name="W0", localizers=None):
    servers = [
        KVServer(Postoffice(f"S{i}", van), cfgs, i, num_servers)
        for i in range(num_servers)
    ]
    worker = KVWorker(
        Postoffice(worker_name, van),
        cfgs,
        num_servers,
        min_bucket=16,
        localizers=localizers,
    )
    return servers, worker


def test_save_restore_roundtrip(tmp_path):
    van = LoopbackVan()
    try:
        cfgs = _cfgs()
        servers, worker = _cluster(van, cfgs, 2)
        keys = np.arange(0, 64, dtype=np.uint64) * 7919
        grads = np.random.RandomState(0).randn(64, 4).astype(np.float32)
        worker.wait(worker.push("w", keys, grads), timeout=10)
        before = worker.pull_sync("w", keys, timeout=10)

        worker.save_model(str(tmp_path), step=3, clocks=[1, 1], extras={"epoch": 2})

        # clobber the tables, then restore over the Van
        for s in servers:
            t = s.tables["w"]
            t.set_value(np.full((t.rows + 1, t.dim), 9.0, np.float32))
        worker.load_model(str(tmp_path), step=3)
        after = worker.pull_sync("w", keys, timeout=10)
        np.testing.assert_allclose(after, before, rtol=1e-6)

        info = checkpoint.read_info(str(tmp_path), 3)
        assert info.clocks == [1, 1]
        assert info.extras["epoch"] == 2
        # the key->row mapping is auto-recorded for offline eval
        assert info.extras["localizers"]["w"]["kind"] == "HashLocalizer"
        assert info.extras["localizers"]["w"]["hash_bits"] == 64
        assert checkpoint.latest_step(str(tmp_path)) == 3
    finally:
        van.close()


def test_optimizer_state_survives_resume(tmp_path):
    """Resume must continue the adagrad trajectory, not restart it."""
    van = LoopbackVan()
    try:
        cfgs = _cfgs(kind="adagrad")
        loc = {"w": HashLocalizer(1000)}
        servers, worker = _cluster(van, cfgs, 2, localizers=loc)
        keys = np.array([11, 22, 33], dtype=np.uint64)
        g = np.ones((3, 4), dtype=np.float32)
        worker.wait(worker.push("w", keys, g), timeout=10)
        worker.save_model(str(tmp_path), step=1)
        # continue training in the original cluster -> ground truth
        worker.wait(worker.push("w", keys, g), timeout=10)
        truth = worker.pull_sync("w", keys, timeout=10)

        # fresh cluster restores and takes the same second step
        van2 = LoopbackVan()
        try:
            servers2, worker2 = _cluster(van2, cfgs, 2, localizers=loc)
            worker2.load_model(str(tmp_path), step=1)
            worker2.wait(worker2.push("w", keys, g), timeout=10)
            resumed = worker2.pull_sync("w", keys, timeout=10)
            np.testing.assert_allclose(resumed, truth, rtol=1e-6)
        finally:
            van2.close()
    finally:
        van.close()


@pytest.mark.parametrize("new_servers", [1, 3, 4])
def test_elastic_restore_different_server_count(tmp_path, new_servers):
    """Save with 2 servers, restore with N: the elastic re-shard path."""
    van = LoopbackVan()
    try:
        cfgs = _cfgs(rows=500, dim=2, kind="sgd")
        loc = {"w": HashLocalizer(500)}
        servers, worker = _cluster(van, cfgs, 2, localizers=loc)
        keys = (np.arange(80, dtype=np.uint64) * 104729) % 100000
        grads = np.random.RandomState(1).randn(80, 2).astype(np.float32)
        worker.wait(worker.push("w", keys, grads), timeout=10)
        before = worker.pull_sync("w", keys, timeout=10)
        worker.save_model(str(tmp_path), step=7)
    finally:
        van.close()

    van2 = LoopbackVan()
    try:
        servers2, worker2 = _cluster(
            van2, cfgs, new_servers, worker_name="W0", localizers=loc
        )
        worker2.load_model(str(tmp_path), step=7)
        after = worker2.pull_sync("w", keys, timeout=10)
        np.testing.assert_allclose(after, before, rtol=1e-6)
    finally:
        van2.close()


def test_uncommitted_checkpoint_ignored(tmp_path):
    cfg = _cfgs(rows=100, dim=1)["w"]
    table = KVTable(cfg, rows=100)
    checkpoint.save_shard(str(tmp_path), 5, "w", table, 0, 1, 0)
    # no finalize -> invisible
    assert checkpoint.latest_step(str(tmp_path)) is None
    checkpoint.finalize(str(tmp_path), 5, 1, {"w": 100})
    assert checkpoint.latest_step(str(tmp_path)) == 5


def test_finalize_refuses_missing_shards(tmp_path):
    cfg = _cfgs(rows=100, dim=1)["w"]
    table = KVTable(cfg, rows=50)
    checkpoint.save_shard(str(tmp_path), 2, "w", table, 0, 2, 0)
    with pytest.raises(FileNotFoundError):
        checkpoint.finalize(str(tmp_path), 2, 2, {"w": 100})


def test_load_global_weights_and_retain(tmp_path):
    cfg = _cfgs(rows=100, dim=3)["w"]
    import jax.numpy as jnp

    full = np.arange(300, dtype=np.float32).reshape(100, 3)
    for step in (1, 2, 3):
        for s, (lo, hi) in enumerate(((0, 50), (50, 100))):
            t = KVTable(cfg, rows=hi - lo)
            buf = np.zeros((t.rows + 1, 3), np.float32)
            buf[: t.rows] = full[lo:hi] * step
            t.value = jnp.asarray(buf)
            checkpoint.save_shard(str(tmp_path), step, "w", t, s, 2, lo)
        checkpoint.finalize(str(tmp_path), step, 2, {"w": 100})
    got = checkpoint.load_global_weights(str(tmp_path), 2, "w")
    np.testing.assert_allclose(got, full * 2)
    checkpoint.retain(str(tmp_path), keep=1)
    assert checkpoint.list_steps(str(tmp_path)) == [3]


def test_save_model_failure_raises_not_hangs(tmp_path):
    """A server-side save error must surface as an exception on the worker
    (error reply), not an eternal wait() on the missing response leg."""
    van = LoopbackVan()
    try:
        cfgs = _cfgs(rows=100, dim=1)
        servers, worker = _cluster(van, cfgs, 2)
        bad = tmp_path / "not_a_dir"
        bad.write_text("file in the way")
        with pytest.raises(RuntimeError, match="failed on"):
            worker.save_model(str(bad / "ckpt"), step=1, timeout=30)
    finally:
        van.close()


def test_dense_checkpoint_roundtrip_and_reshard(tmp_path):
    """Dense segments save/restore, including under a new server count."""
    from parameter_server_tpu.kv.dense import DenseKVServer, DenseKVWorker

    van = LoopbackVan()
    try:
        opt = OptimizerConfig(kind="adagrad", learning_rate=0.5)
        total = 1000
        servers = [
            DenseKVServer(
                Postoffice(f"S{i}", van), {"m": (total, opt)}, i, 2
            )
            for i in range(2)
        ]
        worker = DenseKVWorker(Postoffice("W0", van), {"m": total}, 2)
        rng = np.random.RandomState(0)
        for _ in range(3):
            worker.wait(
                worker.push("m", rng.randn(total).astype(np.float32)),
                timeout=10,
            )
        before = worker.pull_sync("m", timeout=10)
        worker.save_model(str(tmp_path), step=4, clocks=[3])
    finally:
        van.close()

    # restore into a 3-server cluster: elastic re-shard of dense segments
    van2 = LoopbackVan()
    try:
        servers2 = [
            DenseKVServer(
                Postoffice(f"S{i}", van2), {"m": (total, opt)}, i, 3
            )
            for i in range(3)
        ]
        worker2 = DenseKVWorker(Postoffice("W0", van2), {"m": total}, 3)
        worker2.load_model(str(tmp_path), step=4)
        after = worker2.pull_sync("m", timeout=10)
        np.testing.assert_allclose(after, before, rtol=1e-6)
        # optimizer state restored too: a further identical push moves the
        # weights the same way it would have in the original cluster
        worker2.wait(
            worker2.push("m", np.ones(total, np.float32)), timeout=10
        )
        moved = worker2.pull_sync("m", timeout=10)
        assert np.abs(moved - after).max() > 1e-4
        assert checkpoint.read_info(str(tmp_path), 4).clocks == [3]
    finally:
        van2.close()


def test_retain_keep_zero_deletes_all(tmp_path):
    """retain(keep=0) deletes everything; negative keep raises (ADVICE r1)."""
    van = LoopbackVan()
    try:
        cfgs = _cfgs()
        _servers, worker = _cluster(van, cfgs, 2)
        for step in (1, 2, 3):
            worker.save_model(str(tmp_path), step=step)
        checkpoint.retain(str(tmp_path), keep=2)
        assert checkpoint.list_steps(str(tmp_path)) == [2, 3]
        checkpoint.retain(str(tmp_path), keep=0)
        assert checkpoint.list_steps(str(tmp_path)) == []
        with pytest.raises(ValueError):
            checkpoint.retain(str(tmp_path), keep=-1)
    finally:
        van.close()


def test_eval_reconstructs_manifest_localizer(tmp_path):
    """Offline eval must score with the TRAINING hash width, not a default.

    A 32-bit-hash table evaluated through the 64-bit default localizer
    mis-assigns essentially every key (VERDICT r2 weak #5); with the
    manifest-recorded metadata the same call scores correctly.
    """
    from parameter_server_tpu import evaluation
    from parameter_server_tpu.utils.keys import (
        localizer_from_meta,
        localizer_meta,
    )

    rows = 512
    loc32 = HashLocalizer(rows, seed=7, hash_bits=32)
    # meta roundtrip preserves the full construction
    rebuilt = localizer_from_meta(localizer_meta(loc32))
    keys = np.arange(1, 400, dtype=np.uint64) * 2654435761
    np.testing.assert_array_equal(rebuilt.assign(keys), loc32.assign(keys))

    van = LoopbackVan()
    try:
        cfgs = _cfgs(rows=rows, dim=1)
        _servers, worker = _cluster(van, cfgs, 2, localizers={"w": loc32})
        rng = np.random.RandomState(0)
        # teach the table a planted signal: weight +3 on half the keys
        pos_keys = keys[: keys.size // 2]
        neg_keys = keys[keys.size // 2 :]
        for _ in range(30):
            worker.wait(
                worker.push("w", pos_keys, -np.ones((pos_keys.size, 1), np.float32)),
                timeout=10,
            )
            worker.wait(
                worker.push("w", neg_keys, np.ones((neg_keys.size, 1), np.float32)),
                timeout=10,
            )
        worker.save_model(str(tmp_path), step=1)

        def batches():
            lab = np.concatenate([
                np.ones(pos_keys.size), np.zeros(neg_keys.size)
            ])
            ks = np.concatenate([pos_keys, neg_keys]).reshape(-1, 1)
            return [(ks, lab)]

        good = evaluation.evaluate_checkpoint(str(tmp_path), "w", batches())
        assert good["auc"] > 0.9  # manifest localizer -> rows line up
        # forcing the (wrong) 64-bit default must visibly degrade scoring
        bad = evaluation.evaluate_checkpoint(
            str(tmp_path), "w", batches(), hash_bits=64
        )
        assert bad["auc"] < good["auc"]
    finally:
        van.close()
